"""shadow_tpu: a TPU-native conservative-window parallel discrete-event
network simulator with the capabilities of the Shadow simulator.

Where the reference (mckerrigan/shadow, see /root/repo/SURVEY.md) advances
per-host mutexed priority queues with pthread worker pools, shadow_tpu keeps
the entire simulation state — per-host event queues, TCP connection tables,
NIC token buckets, CoDel router queues, topology latency matrices — as
struct-of-arrays pytrees sharded over a `jax.sharding.Mesh`, advanced by
vmapped kernels under `jit`, with the conservative time window implemented
as a `lax.pmin` collective across the mesh.

Simulation time is int64 nanoseconds (reference:
src/main/core/support/definitions.h:18), which requires jax x64 mode; we
enable it at import so every downstream module sees consistent dtypes.
"""

import jax

jax.config.update("jax_enable_x64", True)

__version__ = "0.1.0"

from shadow_tpu.core import timebase  # noqa: E402,F401
from shadow_tpu.core.events import Events, EventQueue  # noqa: E402,F401
from shadow_tpu.core.engine import Engine, EngineConfig  # noqa: E402,F401

"""Device-side bridge model for the real-binary process tier.

The counterpart of the reference's host syscall backend (host.c:773-1651):
the native runtime's syscall *requests* become injected command events
executed by this model's handler (bind/listen/connect/send/close against
the device TCP), and the driver *observes* outcomes each window by
diffing the device socket/TCB tables (established connections, delivered
byte counts, consumed FINs) into completions for the green threads.

Only metadata runs on device; the payload bytes stay in the native
runtime's per-fd streams (SURVEY.md §7 hard part (e)).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from shadow_tpu.core.engine import Emit
from shadow_tpu.core.events import Events
from shadow_tpu.host.sockets import PROTO_TCP
from shadow_tpu.transport.stack import F_FIN, N_PKT_ARGS
from shadow_tpu.transport.tcp import LISTEN as TCP_LISTEN
from shadow_tpu.transport.tcp import emit_concat

_I32 = jnp.int32

# command words (args[0] of an injected KIND_CMD event)
CMD_LISTEN = 1   # args: [cmd, slot, port]
CMD_CONNECT = 2  # args: [cmd, slot, sport, peer_gid, peer_port]
CMD_SEND = 3     # args: [cmd, slot, nbytes]
CMD_CLOSE = 4    # args: [cmd, slot]


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class ProcApp:
    """Per-host observation state ([H] / [H, S])."""

    gid: jax.Array  # i32[H]
    fin_seen: jax.Array  # bool[H, S] — stream EOF consumed per socket
    fin_gen: jax.Array  # i32[H, S] — conn incarnation fin_seen belongs to
    # (device child-slot reuse bumps tcb.conn_gen without any driver
    # bind, so a sticky fin_seen from the previous connection must be
    # reset lazily when a new incarnation's first delivery arrives)


class ProcTierModel:
    """AppModel executing native-process commands on the device stack."""

    name = "shim"
    needs_tcp = True
    n_kinds = 1
    # the driver stops individual green threads at their stoptime
    # (process.c process_stop semantics); device-side host muting must
    # not also fire — it would freeze the whole host's TCP machinery
    owns_process_lifecycle = True

    def __init__(self):
        self._stack = None
        self.kind_cmd = None  # absolute kind index, set by make_handlers

    def app_rows(self) -> int:
        return 1

    def handler_rows(self) -> int:
        return 4  # connect(2) + send kick(1) + close kick(1)

    def build(self, b):
        n = b.n_hosts
        state = ProcApp(
            gid=jnp.arange(n, dtype=_I32),
            fin_seen=jnp.zeros((n, b.n_sockets), bool),
            fin_gen=jnp.zeros((n, b.n_sockets), _I32),
        )
        return state, self._make_handlers, self._on_recv

    def _make_handlers(self, stack, kind_base):
        self._stack = stack
        self.kind_cmd = kind_base
        return [self._on_cmd]

    def _on_cmd(self, hs, ev: Events, key):
        stack, tcp = self._stack, self._stack.tcp
        cmd = ev.args[0]
        slot = jnp.maximum(ev.args[1], 0)
        is_listen = cmd == CMD_LISTEN
        is_conn = cmd == CMD_CONNECT

        # bind the socket row in-lane (tgen's rebind idiom; host.c bind)
        sk = hs.net.sockets
        do_bind = is_listen | is_conn
        port = ev.args[2]  # listen port / connect source port
        w = lambda a, v: a.at[slot].set(jnp.where(do_bind, v, a[slot]))
        sk = dataclasses.replace(
            sk,
            proto=w(sk.proto, PROTO_TCP),
            local_port=w(sk.local_port, port),
            peer_host=w(sk.peer_host, jnp.where(is_conn, ev.args[3], -1)),
            peer_port=w(sk.peer_port, jnp.where(is_conn, ev.args[4], 0)),
        )
        tcb = hs.net.tcb
        st_new = tcb.state.at[slot].set(
            jnp.where(is_listen, TCP_LISTEN, tcb.state[slot])
        )
        tcb = dataclasses.replace(tcb, state=st_new)
        fin_clear = hs.app.fin_seen.at[slot].set(
            jnp.where(do_bind, False, hs.app.fin_seen[slot])
        )
        hs = dataclasses.replace(
            hs,
            app=dataclasses.replace(hs.app, fin_seen=fin_clear),
            net=dataclasses.replace(hs.net, sockets=sk, tcb=tcb),
        )

        hs, em_conn = tcp.connect(stack, hs, slot, ev.time, mask=is_conn)
        hs, em_send = tcp.send(
            hs, slot, ev.args[2], ev.time, mask=cmd == CMD_SEND
        )
        hs, em_close = tcp.close(hs, slot, ev.time, mask=cmd == CMD_CLOSE)
        return hs, emit_concat(em_conn, em_send, em_close)

    def _on_recv(self, hs, slot, pkt, now, key):
        got = slot >= 0
        eof = got & ((pkt.flags & F_FIN) != 0)
        s = jnp.maximum(slot, 0)
        app = hs.app
        # lazy per-incarnation reset: if this slot's TCB was reused since
        # fin_seen was last written, the sticky EOF belongs to a previous
        # connection and must clear before this delivery is applied
        cur_gen = hs.net.tcb.conn_gen[s]
        stale = got & (app.fin_gen[s] != cur_gen)
        fin0 = jnp.where(stale, False, app.fin_seen[s])
        fin = app.fin_seen.at[s].set(jnp.where(eof, True, fin0))
        fgen = app.fin_gen.at[s].set(
            jnp.where(got, cur_gen, app.fin_gen[s])
        )
        hs = dataclasses.replace(
            hs, app=dataclasses.replace(app, fin_seen=fin, fin_gen=fgen)
        )
        return hs, Emit.none(1, N_PKT_ARGS)

"""Device-side bridge model for the real-binary process tier.

The counterpart of the reference's host syscall backend (host.c:773-1651):
the native runtime's syscall *requests* become injected command events
executed by this model's handler (bind/listen/connect/send/close against
the device TCP), and the driver *observes* outcomes each window by
diffing the device socket/TCB tables (established connections, delivered
byte counts, consumed FINs) into completions for the green threads.

Only metadata runs on device; the payload bytes stay in the native
runtime's per-fd streams (SURVEY.md §7 hard part (e)).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from shadow_tpu.core.engine import Emit
from shadow_tpu.core.events import Events
from shadow_tpu.host.sockets import PROTO_NONE, PROTO_TCP, PROTO_UDP
from shadow_tpu.transport.stack import F_FIN, N_PKT_ARGS
from shadow_tpu.transport.tcp import LISTEN as TCP_LISTEN
from shadow_tpu.transport.tcp import _put, _sel, emit_concat

_I32 = jnp.int32

# command words (args[0] of an injected KIND_CMD event)
CMD_LISTEN = 1    # args: [cmd, slot, port]
CMD_CONNECT = 2   # args: [cmd, slot, sport, peer_gid, peer_port]
CMD_SEND = 3      # args: [cmd, slot, nbytes]
CMD_CLOSE = 4     # args: [cmd, slot]
CMD_UDP_BIND = 5  # args: [cmd, slot, port]
CMD_SENDTO = 6    # args: [cmd, slot, dst_gid, dst_port, nbytes, seq]
CMD_UDP_CLOSE = 7  # args: [cmd, slot]

# per-host UDP delivery ring depth: bounds datagrams deliverable to one
# host between two driver observes (one conservative window); overflow
# is detected and raised by the driver, never silent
UDP_RING = 64


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class ProcApp:
    """Per-host observation state ([H] / [H, S])."""

    gid: jax.Array  # i32[H]
    fin_seen: jax.Array  # bool[H, S] — stream EOF consumed per socket
    fin_gen: jax.Array  # i32[H, S] — conn incarnation fin_seen belongs to
    # (device child-slot reuse bumps tcb.conn_gen without any driver
    # bind, so a sticky fin_seen from the previous connection must be
    # reset lazily when a new incarnation's first delivery arrives)
    # UDP delivery ring (udp.c:26-60 immediate buffer-in, realized as
    # per-window records the driver drains): each delivered datagram
    # appends (src gid, src port, dst port, length, sender seq)
    udp_cnt: jax.Array  # i32[H] total datagrams ever delivered
    udp_src: jax.Array  # i32[H, R]
    udp_sport: jax.Array  # i32[H, R]
    udp_dport: jax.Array  # i32[H, R]
    udp_len: jax.Array  # i32[H, R]
    udp_seq: jax.Array  # i32[H, R]


class ProcTierModel:
    """AppModel executing native-process commands on the device stack."""

    name = "shim"
    needs_tcp = True
    n_kinds = 1
    # the driver stops individual green threads at their stoptime
    # (process.c process_stop semantics); device-side host muting must
    # not also fire — it would freeze the whole host's TCP machinery
    owns_process_lifecycle = True

    def __init__(self):
        self._stack = None
        self.kind_cmd = None  # absolute kind index, set by make_handlers

    def app_rows(self) -> int:
        return 1

    def handler_rows(self) -> int:
        return 5  # connect(2) + send kick(1) + close kick(1) + udp(1)

    def build(self, b):
        n = b.n_hosts
        zr = jnp.zeros((n, UDP_RING), _I32)
        state = ProcApp(
            gid=jnp.arange(n, dtype=_I32),
            fin_seen=jnp.zeros((n, b.n_sockets), bool),
            fin_gen=jnp.zeros((n, b.n_sockets), _I32),
            udp_cnt=jnp.zeros((n,), _I32),
            udp_src=zr, udp_sport=zr, udp_dport=zr, udp_len=zr, udp_seq=zr,
        )
        return state, self._make_handlers, self._on_recv

    def _make_handlers(self, stack, kind_base):
        self._stack = stack
        self.kind_cmd = kind_base
        return [self._on_cmd]

    def _on_cmd(self, hs, ev: Events, key):
        stack, tcp = self._stack, self._stack.tcp
        cmd = ev.args[0]
        slot = jnp.maximum(ev.args[1], 0)
        is_listen = cmd == CMD_LISTEN
        is_conn = cmd == CMD_CONNECT
        is_ubind = cmd == CMD_UDP_BIND
        is_uclose = cmd == CMD_UDP_CLOSE

        # bind the socket row in-lane (tgen's rebind idiom; host.c bind;
        # UDP association per udp.c:26-60 — bind installs the demux row,
        # close clears it)
        sk = hs.net.sockets
        do_bind = is_listen | is_conn | is_ubind | is_uclose
        port = jnp.where(is_uclose, 0, ev.args[2])
        proto = jnp.where(
            is_ubind, PROTO_UDP, jnp.where(is_uclose, PROTO_NONE, PROTO_TCP)
        )
        w = lambda a, v: _put(a, slot, v, do_bind)
        sk = dataclasses.replace(
            sk,
            proto=w(sk.proto, proto),
            local_port=w(sk.local_port, port),
            peer_host=w(sk.peer_host, jnp.where(is_conn, ev.args[3], -1)),
            peer_port=w(sk.peer_port, jnp.where(is_conn, ev.args[4], 0)),
        )
        tcb = hs.net.tcb
        st_new = _put(tcb.state, slot, TCP_LISTEN, is_listen)
        tcb = dataclasses.replace(tcb, state=st_new)
        fin_clear = _put(hs.app.fin_seen, slot, False, do_bind)
        hs = dataclasses.replace(
            hs,
            app=dataclasses.replace(hs.app, fin_seen=fin_clear),
            net=dataclasses.replace(hs.net, sockets=sk, tcb=tcb),
        )

        hs, em_conn = tcp.connect(stack, hs, slot, ev.time, mask=is_conn)
        hs, em_send = tcp.send(
            hs, slot, ev.args[2], ev.time, mask=cmd == CMD_SEND
        )
        hs, em_close = tcp.close(hs, slot, ev.time, mask=cmd == CMD_CLOSE)
        hs, em_udp = stack.send_udp(
            hs, ev.time, slot, ev.args[2], ev.args[3], ev.args[4],
            aux=ev.args[5], mask=cmd == CMD_SENDTO,
        )
        return hs, emit_concat(em_conn, em_send, em_close, em_udp)

    def _on_recv(self, hs, slot, pkt, now, key):
        got = slot >= 0
        app = hs.app

        # UDP datagram: append a delivery record to the ring the driver
        # drains each window (payload bytes move host-side by seq)
        is_udp = got & (pkt.proto == PROTO_UDP)
        idx = jnp.where(is_udp, app.udp_cnt % UDP_RING, 0)
        wr = lambda a, v: _put(a, idx, v, is_udp)
        app = dataclasses.replace(
            app,
            udp_cnt=app.udp_cnt + is_udp.astype(_I32),
            udp_src=wr(app.udp_src, pkt.src_host),
            udp_sport=wr(app.udp_sport, pkt.src_port),
            udp_dport=wr(app.udp_dport, pkt.dst_port),
            udp_len=wr(app.udp_len, pkt.length),
            udp_seq=wr(app.udp_seq, pkt.aux),
        )
        hs = dataclasses.replace(hs, app=app)

        eof = got & ~is_udp & ((pkt.flags & F_FIN) != 0)
        s = jnp.maximum(slot, 0)
        # lazy per-incarnation reset: if this slot's TCB was reused since
        # fin_seen was last written, the sticky EOF belongs to a previous
        # connection and must clear before this delivery is applied
        cur_gen = _sel(hs.net.tcb.conn_gen, s)
        stale = got & (_sel(app.fin_gen, s) != cur_gen)
        fin0 = jnp.where(stale, False, _sel(app.fin_seen, s))
        fin = _put(app.fin_seen, s, jnp.where(eof, True, fin0), got)
        fgen = _put(app.fin_gen, s, cur_gen, got
        )
        hs = dataclasses.replace(
            hs, app=dataclasses.replace(app, fin_seen=fin, fin_gen=fgen)
        )
        return hs, Emit.none(1, N_PKT_ARGS)

"""ProcessTier: window-batched syscall exchange between the native
green-thread runtime and the device simulation.

The reference interleaves plugin execution with simulation events at
nanosecond granularity (+1ns epoll notify tasks, epoll.c:500-583 →
process_continue). A TPU cannot afford a host↔device round trip per
syscall, so this driver batches the exchange at conservative-window
granularity (SURVEY.md §7 step 6b): once per window it

  1. feeds completions (established connects, accepted children, timer
     wakes) into `shim_pump`, which runs every runnable green thread
     until all block again and returns their syscall requests;
  2. translates requests into command events injected into the device
     queues (executed by ProcTierModel's handler at the window open);
  3. steps the simulation one window;
  4. diffs the device socket/TCB tables: newly-established connections
     become completions for the next pump, per-socket delivered-byte
     growth moves real bytes between the native runtime's endpoint
     streams (shim_wire_deliver), consumed FINs become stream EOFs.

Deviation from the reference, documented for the parity check: process
reactions land at window boundaries (one lookahead of added latency per
blocking syscall round trip). Byte-stream order is exact on lossy paths
too: config-built simulations run the device TCP in strict in-order
delivery mode (transport/tcp.py in_order), so the per-socket delivered
counters this driver diffs only ever advance in stream order.
"""

from __future__ import annotations

import heapq
import os
import shlex
from typing import Any

import dataclasses
import jax
import jax.numpy as jnp
import numpy as np

from shadow_tpu.config import ShadowConfig, expand_hosts, resolve_path
from shadow_tpu.core.events import Events, queue_push
from shadow_tpu.core.timebase import SECOND
from shadow_tpu.host.sockets import EPHEMERAL_BASE
from shadow_tpu.proc.model import (
    CMD_CLOSE,
    CMD_CONNECT,
    CMD_LISTEN,
    CMD_SEND,
    ProcTierModel,
)
from shadow_tpu.proc.native import (
    COMP_ACCEPT,
    COMP_CONNECT_FAIL,
    COMP_CONNECT_OK,
    COMP_WAKE,
    REQ_CLOSE,
    REQ_CONNECT,
    REQ_EXIT,
    REQ_LISTEN,
    COMP_TIMER,
    REQ_LOG,
    REQ_SEND,
    REQ_SLEEP,
    REQ_TIMER,
    ShimRuntime,
)
from shadow_tpu.sim import build_simulation
from shadow_tpu.transport.stack import N_PKT_ARGS
from shadow_tpu.transport.tcp import CLOSED, ESTABLISHED, SYN_SENT


class ProcessTier:
    """Drives native plugin processes against a config-built simulation.

    Every <process> in the config whose plugin path is a .so exporting
    `shim_main` runs as a green thread in the native runtime; argv is
    [basename, *arguments.split()].
    """

    def __init__(self, cfg: ShadowConfig, *, seed: int = 0,
                 n_sockets: int = 8, capacity: int | None = None,
                 strict_overflow: bool = True, tcp_cc: str = "reno",
                 rx_queue: str = "codel", qdisc: str = "fifo",
                 interface_buffer: int = 1_024_000):
        self.strict_overflow = strict_overflow
        self.model = ProcTierModel()
        self.sim = build_simulation(
            cfg, seed=seed, n_sockets=n_sockets, capacity=capacity,
            app_model=self.model, tcp_cc=tcp_cc, rx_queue=rx_queue,
            qdisc=qdisc, interface_buffer=interface_buffer,
        )
        if self.sim.mesh is not None:
            raise NotImplementedError("ProcessTier is single-shard for now")
        self.rt = ShimRuntime()
        self.n_sockets = n_sockets
        # the interposer's getaddrinfo resolves against the runtime's DNS
        # table; push the whole (static) registry up front (dns.c role)
        for addr in self.sim.dns.entries():
            self.rt.dns_add(addr.name, addr.ip)
        self.logs: list[tuple[int, int, str]] = []  # (sim_ns, pid, msg)
        self.exit_codes: dict[int, int] = {}

        # (pid, fd) <-> (gid, slot) endpoint maps
        self.slot_of: dict[tuple[int, int], tuple[int, int]] = {}
        self.ep_of: dict[tuple[int, int], tuple[int, int]] = {}
        self.listen_ep: dict[tuple[int, int], tuple[int, int]] = {}
        self.pending_conn: dict[tuple[int, int], tuple[int, int]] = {}
        self.wire: dict[tuple[int, int], tuple[int, int]] = {}  # slot<->slot
        self.undelivered: dict[tuple[int, int], int] = {}
        self.pid_host: dict[int, int] = {}
        self._next_slot: dict[int, int] = {}
        self._next_sport: dict[int, int] = {}
        self._next_fd: dict[int, int] = {}
        self._starts: list[tuple[int, int]] = []  # (t_ns, pid) heap
        self._wakes: list[tuple[int, int, int]] = []  # (t_ns, pid, gen)
        # timerfd arms: (deadline_ns, pid, fd, interval_ns, gen) heap;
        # _timer_gen holds each fd's current arm generation so re-armed
        # or closed timers' stale entries retire on pop
        self._timers: list[tuple[int, int, int, int, int]] = []
        self._timer_gen: dict[tuple[int, int], int] = {}
        self._pending_comps: list[tuple] = []
        self._push_jit = jax.jit(queue_push, static_argnames=())

        for h in expand_hosts(cfg):
            for p in h.spec.processes:
                spec = cfg.plugin_by_id(p.plugin)
                path = resolve_path(spec.path, cfg.base_dir) if spec else p.plugin
                if not (path.endswith(".so") and os.path.exists(path)):
                    raise ValueError(
                        "the process tier runs native plugins only: "
                        f"plugin {p.plugin!r} resolves to {path!r}, which "
                        "is not an existing .so — configs cannot mix "
                        "native plugins with modeled ones yet"
                    )
                argv = [os.path.basename(path)] + shlex.split(p.arguments)
                pid = self.rt.spawn(h.gid, path, argv)
                self.pid_host[pid] = h.gid
                heapq.heappush(self._starts, (int(p.starttime * SECOND), pid))

        h_n = len(self.sim.names)
        self._prev_rx = np.zeros((h_n, n_sockets), np.int64)
        self._prev_fin = np.zeros((h_n, n_sockets), bool)

    # ------------------------------------------------------------- helpers
    def _alloc_slot(self, gid: int) -> int:
        # driver-owned slots grow downward from the top; TCP child sockets
        # allocate first-free from 0 upward, so the ends never collide
        s = self._next_slot.get(gid, self.n_sockets - 1)
        self._next_slot[gid] = s - 1
        if s < 1:
            raise RuntimeError(f"host {gid}: out of socket slots")
        return s

    def _alloc_sport(self, gid: int) -> int:
        p = self._next_sport.get(gid, EPHEMERAL_BASE + 4096)
        self._next_sport[gid] = p + 1
        return p

    def _alloc_fd(self, pid: int) -> int:
        # driver-assigned child fds live in the 2'000'000+ band, disjoint
        # from the runtime's own 1'000'000+ allocations (shim_runtime.cpp
        # kFirstFd) — fd numbers stay globally unique
        f = self._next_fd.get(pid, 2_000_000)
        self._next_fd[pid] = f + 1
        return f

    # ---------------------------------------------------------- translate
    def _translate(self, reqs, now: int) -> list[tuple[int, list[int]]]:
        rows: list[tuple[int, list[int]]] = []
        for r in reqs:
            pid, fd = int(r.pid), int(r.fd)
            gid = self.pid_host[pid]
            if r.op == REQ_LISTEN:
                slot = self._alloc_slot(gid)
                self.slot_of[(pid, fd)] = (gid, slot)
                self.ep_of[(gid, slot)] = (pid, fd)
                self.listen_ep[(gid, int(r.port))] = (pid, fd)
                rows.append((gid, [CMD_LISTEN, slot, int(r.port)]))
            elif r.op == REQ_CONNECT:
                name = r.name.decode()
                if name:
                    addr = self.sim.dns.resolve_name(name)
                else:
                    # interposer form: a1 carries the virtual IPv4 from
                    # connect(sockaddr_in) (host order)
                    addr = self.sim.dns.resolve_ip(int(r.a1))
                if addr is None:
                    self._pending_comps.append(
                        (pid, COMP_CONNECT_FAIL, fd, 0)
                    )
                    continue
                slot = self._alloc_slot(gid)
                sport = self._alloc_sport(gid)
                self.slot_of[(pid, fd)] = (gid, slot)
                self.ep_of[(gid, slot)] = (pid, fd)
                self.pending_conn[(gid, slot)] = (pid, fd)
                rows.append(
                    (gid, [CMD_CONNECT, slot, sport, addr.host_id,
                           int(r.port)])
                )
            elif r.op == REQ_SEND:
                key = (pid, fd)
                if key in self.slot_of:
                    gid, slot = self.slot_of[key]
                    rows.append((gid, [CMD_SEND, slot, int(r.a0)]))
            elif r.op == REQ_CLOSE:
                key = (pid, fd)
                if key in self.slot_of:
                    gid, slot = self.slot_of[key]
                    rows.append((gid, [CMD_CLOSE, slot]))
            elif r.op == REQ_SLEEP:
                heapq.heappush(self._wakes, (int(r.a0), pid, int(r.port)))
            elif r.op == REQ_TIMER:
                gen = int(r.port)
                self._timer_gen[(pid, fd)] = gen
                if int(r.a0) >= 0:  # a0 = -1 is a disarm
                    heapq.heappush(
                        self._timers, (int(r.a0), pid, fd, int(r.a1), gen)
                    )
            elif r.op == REQ_LOG:
                self.logs.append((now, pid, r.name.decode()))
            elif r.op == REQ_EXIT:
                self.exit_codes[pid] = int(r.a0)
        return rows

    # ------------------------------------------------------------- inject
    def _inject(self, st, rows, now: int):
        if not rows:
            return st
        m = len(rows)
        cap = 1 << max(m - 1, 0).bit_length()  # pad: bounded recompiles
        times = np.full((cap,), np.iinfo(np.int64).max, np.int64)
        dst = np.zeros((cap,), np.int32)
        seq = np.zeros((cap,), np.int32)
        kind = np.zeros((cap,), np.int32)
        argw = np.zeros((cap, N_PKT_ARGS), np.int32)
        src_seq = np.array(jax.device_get(st.src_seq))
        for i, (gid, args) in enumerate(rows):
            times[i] = now
            dst[i] = gid
            seq[i] = src_seq[gid]
            src_seq[gid] += 1
            kind[i] = self.model.kind_cmd
            argw[i, : len(args)] = args
        ev = Events(
            time=jnp.asarray(times), dst=jnp.asarray(dst),
            src=jnp.asarray(dst), seq=jnp.asarray(seq),
            kind=jnp.asarray(kind), args=jnp.asarray(argw),
        )
        mask = jnp.asarray(np.arange(cap) < m)
        q2 = self._push_jit(st.queues, ev, mask, jnp.int32(0))
        return dataclasses.replace(
            st, queues=q2, src_seq=jnp.asarray(src_seq)
        )

    # ------------------------------------------------------------ observe
    def _observe(self, st) -> None:
        """Diff device tables into completions + byte/FIN wire ops."""
        net = st.hosts.net
        tstate = np.array(jax.device_get(net.tcb.state))
        rx = np.array(jax.device_get(net.sockets.rx_bytes))
        fin = np.array(jax.device_get(st.hosts.app.fin_seen))
        lport = np.array(jax.device_get(net.sockets.local_port))
        phost = np.array(jax.device_get(net.sockets.peer_host))
        pport = np.array(jax.device_get(net.sockets.peer_port))

        # pending active opens
        for key, (pid, fd) in list(self.pending_conn.items()):
            gid, slot = key
            s = tstate[gid, slot]
            if s >= ESTABLISHED:
                self._pending_comps.append((pid, COMP_CONNECT_OK, fd, 0))
                del self.pending_conn[key]
            elif s == CLOSED:
                self._pending_comps.append((pid, COMP_CONNECT_FAIL, fd, 0))
                del self.pending_conn[key]
                del self.ep_of[key]
                del self.slot_of[(pid, fd)]

        # new child sockets on listening hosts -> accepts
        for (gid, port), (lpid, lfd) in self.listen_ep.items():
            for slot in range(tstate.shape[1]):
                if (gid, slot) in self.ep_of:
                    continue
                if tstate[gid, slot] >= ESTABLISHED and \
                        tstate[gid, slot] != SYN_SENT and \
                        lport[gid, slot] == port:
                    nfd = self._alloc_fd(lpid)
                    self.ep_of[(gid, slot)] = (lpid, nfd)
                    self.slot_of[(lpid, nfd)] = (gid, slot)
                    self._pending_comps.append(
                        (lpid, COMP_ACCEPT, lfd, nfd)
                    )

        # wire pairing: match endpoints by the (host, port) 4-tuple
        for key in [k for k in self.ep_of if k not in self.wire]:
            gid, slot = key
            peer = (int(phost[gid, slot]), -1)
            if peer[0] < 0:
                continue
            pg = peer[0]
            for pslot in range(tstate.shape[1]):
                if (pg, pslot) not in self.ep_of:
                    continue
                if (
                    lport[pg, pslot] == pport[gid, slot]
                    and phost[pg, pslot] == gid
                    and pport[pg, pslot] == lport[gid, slot]
                ):
                    self.wire[key] = (pg, pslot)
                    self.wire[(pg, pslot)] = key
                    break

        # delivered bytes + FIN propagation
        for key, (pid, fd) in self.ep_of.items():
            gid, slot = key
            d = int(rx[gid, slot] - self._prev_rx[gid, slot])
            if d > 0:
                self.undelivered[key] = self.undelivered.get(key, 0) + d
            if self.undelivered.get(key) and key in self.wire:
                src = self.wire[key]
                if src in self.ep_of:
                    spid, sfd = self.ep_of[src]
                    moved = self.rt.wire_deliver(
                        spid, sfd, pid, fd, self.undelivered[key]
                    )
                    if moved > 0:
                        self.undelivered[key] -= moved
            if fin[gid, slot] and not self._prev_fin[gid, slot]:
                if not self.undelivered.get(key):
                    self.rt.wire_fin(pid, fd)
                else:
                    # bytes still owed; FIN re-checked next window
                    fin[gid, slot] = False

        self._prev_rx = rx
        self._prev_fin = fin

    # ---------------------------------------------------------------- run
    def run(self, stop_s: float | None = None):
        sim = self.sim
        stop_ns = int(stop_s * SECOND) if stop_s is not None else sim.stop_ns
        st = sim.state0
        now = 0
        while True:
            comps = self._pending_comps
            self._pending_comps = []
            while self._starts and self._starts[0][0] <= now:
                _, pid = heapq.heappop(self._starts)
                self.rt.start(pid)
            while self._wakes and self._wakes[0][0] <= now:
                _, pid, gen = heapq.heappop(self._wakes)
                comps.append((pid, COMP_WAKE, -1, gen))
            while self._timers and self._timers[0][0] <= now:
                t, pid, fd, interval, gen = heapq.heappop(self._timers)
                if self._timer_gen.get((pid, fd)) != gen:
                    continue  # re-armed or closed since: stale
                if interval > 0:
                    # credit every expiration the window skipped over and
                    # re-arm on the absolute grid (timer.c interval
                    # expirations with no drift)
                    n_exp = (now - t) // interval + 1
                    heapq.heappush(
                        self._timers, (t + n_exp * interval, pid, fd,
                                       interval, gen)
                    )
                comps.append((pid, COMP_TIMER, fd, int(n_exp if interval > 0 else 1), gen))

            reqs = self.rt.pump(now, comps)
            st = self._inject(st, self._translate(reqs, now), now)

            if now >= stop_ns:
                break
            # never step past the next host-side interest point
            bound = stop_ns
            if self._starts:
                bound = min(bound, max(self._starts[0][0], now + 1))
            if self._wakes:
                bound = min(bound, max(self._wakes[0][0], now + 1))
            # retire re-armed/disarmed timer entries so a dead arm stops
            # bounding window sizes
            while self._timers and self._timer_gen.get(
                (self._timers[0][1], self._timers[0][2])
            ) != self._timers[0][4]:
                heapq.heappop(self._timers)
            if self._timers:
                bound = min(bound, max(self._timers[0][0], now + 1))
            st = sim.step_window(st, bound)
            now = int(jax.device_get(st.now))
            self._observe(st)
        drops = int(jax.device_get(st.queues.drops.sum()))
        if drops and self.strict_overflow:
            raise RuntimeError(
                f"event queue overflow: {drops} events dropped (capacity "
                f"{self.sim.engine.cfg.capacity}); native processes may "
                "have observed a corrupted simulation — rerun with a "
                "larger capacity"
            )
        return st

    def close(self):
        self.rt.close()

"""ProcessTier: window-batched syscall exchange between the native
green-thread runtime and the device simulation.

The reference interleaves plugin execution with simulation events at
nanosecond granularity (+1ns epoll notify tasks, epoll.c:500-583 →
process_continue). A TPU cannot afford a host↔device round trip per
syscall, so this driver batches the exchange at conservative-window
granularity (SURVEY.md §7 step 6b): once per window it

  1. feeds completions (established connects, accepted children, timer
     wakes) into `shim_pump`, which runs every runnable green thread
     until all block again and returns their syscall requests;
  2. translates requests into command events injected into the device
     queues (executed by ProcTierModel's handler at the window open);
  3. steps the simulation one window;
  4. diffs the device socket/TCB tables: newly-established connections
     become completions for the next pump, per-socket delivered-byte
     growth moves real bytes between the native runtime's endpoint
     streams (shim_wire_deliver), consumed FINs become stream EOFs.

Deviation from the reference, documented for the parity check: process
reactions land at window boundaries (one lookahead of added latency per
blocking syscall round trip). Byte-stream order is exact on lossy paths
too: config-built simulations run the device TCP in strict in-order
delivery mode (transport/tcp.py in_order), so the per-socket delivered
counters this driver diffs only ever advance in stream order.
"""

from __future__ import annotations

import heapq
import os
import shlex
from typing import Any

import dataclasses
import jax
import jax.numpy as jnp
import numpy as np

from shadow_tpu.config import ShadowConfig, expand_hosts, resolve_path
from shadow_tpu.core.events import Events, queue_push
from shadow_tpu.core.timebase import SECOND
from shadow_tpu.host.sockets import EPHEMERAL_BASE
from shadow_tpu.proc.model import (
    CMD_CLOSE,
    CMD_CONNECT,
    CMD_LISTEN,
    CMD_SEND,
    CMD_SENDTO,
    CMD_UDP_BIND,
    CMD_UDP_CLOSE,
    UDP_RING,
    ProcTierModel,
)
from shadow_tpu.proc.native import (
    COMP_ACCEPT,
    COMP_CONNECT_FAIL,
    COMP_CONNECT_OK,
    COMP_WAKE,
    REQ_CLOSE,
    REQ_CONNECT,
    REQ_EXIT,
    REQ_LISTEN,
    COMP_TIMER,
    REQ_LOG,
    REQ_SEND,
    REQ_SENDTO,
    REQ_SLEEP,
    REQ_TIMER,
    REQ_UDP_BIND,
    ShimRuntime,
)
from shadow_tpu.sim import build_simulation
from shadow_tpu.transport.stack import N_PKT_ARGS
from shadow_tpu.transport.tcp import CLOSED, ESTABLISHED

# how long a closed UDP endpoint's source-attribution zombie may outlive
# its last unaccounted datagram: covers datagrams the network dropped
# outright (they never reach a ring, so the seq-set drain can't retire
# them) — far beyond any path latency the topology can express
_UDP_ZOMBIE_TTL_NS = 30 * SECOND


class ProcessTier:
    """Drives native plugin processes against a config-built simulation.

    Every <process> in the config whose plugin path is a .so exporting
    `shim_main` runs as a green thread in the native runtime; argv is
    [basename, *arguments.split()].
    """

    def __init__(self, cfg: ShadowConfig, *, seed: int = 0,
                 n_sockets: int = 8, capacity: int | None = None,
                 strict_overflow: bool = True, tcp_cc: str = "reno",
                 rx_queue: str = "codel", qdisc: str = "fifo",
                 interface_buffer: int = 1_024_000, mesh=None,
                 driver_slots: int | None = None, locality: bool = False,
                 trace: int = 0, profiler=None, overflow: str = "drop"):
        self.strict_overflow = strict_overflow
        self.overflow = overflow
        self.model = ProcTierModel()
        # hard slot-space split: device-created children live in
        # [0, child_limit), driver-owned sockets in [child_limit, S).
        # Without it, a recycled driver slot could be claimed by an
        # inbound SYN while the driver still holds it in its free list.
        if driver_slots is None:
            driver_slots = min(max(1, n_sockets // 2), n_sockets - 1)
        if not 0 < driver_slots < n_sockets:
            raise ValueError(
                f"driver_slots must be in (0, {n_sockets}), got {driver_slots}"
            )
        self._child_limit = n_sockets - driver_slots
        self.sim = build_simulation(
            cfg, seed=seed, n_sockets=n_sockets, capacity=capacity,
            app_model=self.model, tcp_cc=tcp_cc, rx_queue=rx_queue,
            qdisc=qdisc, interface_buffer=interface_buffer, mesh=mesh,
            tcp_child_slot_limit=self._child_limit, locality=locality,
            trace=trace, profiler=profiler, overflow=overflow,
        )
        self.rt = ShimRuntime()
        self.rt.set_seed(seed)  # roots plugin rand()/urandom determinism
        self.lost_stream_bytes = 0  # bytes unflushable at endpoint drop
        self.n_sockets = n_sockets
        # the interposer's getaddrinfo resolves against the runtime's DNS
        # table; push the whole (static) registry up front (dns.c role)
        for addr in self.sim.dns.entries():
            self.rt.dns_add(addr.name, addr.ip)
        self.logs: list[tuple[int, int, str]] = []  # (sim_ns, pid, msg)
        self.exit_codes: dict[int, int] = {}

        # (pid, fd) <-> (gid, slot) endpoint maps
        self.slot_of: dict[tuple[int, int], tuple[int, int]] = {}
        self.ep_of: dict[tuple[int, int], tuple[int, int]] = {}
        self.listen_ep: dict[tuple[int, int], tuple[int, int]] = {}
        self._listen_of_ep: dict[tuple[int, int], tuple[int, int]] = {}
        self.pending_conn: dict[tuple[int, int], tuple[int, int]] = {}
        self.wire: dict[tuple[int, int], tuple[int, int]] = {}  # slot<->slot
        # full-4-tuple wire index: (gid, lport, peer_gid, pport) -> (gid,
        # slot). The reference demuxes by the same 4-tuple key
        # (network_interface.c:375-455); matching on it makes parallel
        # same-port connects between one host pair unambiguous.
        self._four: dict[tuple[int, int, int, int], tuple[int, int]] = {}
        self._four_key: dict[tuple[int, int], tuple] = {}  # ep -> its key
        self._driver_owned: set[tuple[int, int]] = set()
        self._free_slots: dict[int, list[int]] = {}
        self.pid_host: dict[int, int] = {}
        self._next_slot: dict[int, int] = {}
        self._next_sport: dict[int, int] = {}
        self._next_fd: dict[int, int] = {}
        self._starts: list[tuple[int, int]] = []  # (t_ns, pid) heap
        self._wakes: list[tuple[int, int, int]] = []  # (t_ns, pid, gen)
        # timerfd arms: (deadline_ns, pid, fd, interval_ns, gen) heap;
        # _timer_gen holds each fd's current arm generation so re-armed
        # or closed timers' stale entries retire on pop
        self._timers: list[tuple[int, int, int, int, int]] = []
        self._timer_gen: dict[tuple[int, int], int] = {}
        self._pending_comps: list[tuple] = []
        self._push_jit = jax.jit(queue_push, static_argnames=())

        # per-process stoptime heap ((stop_ns, pid); the reference stops
        # each plugin individually, configuration.h:38-102 + process_stop)
        self._stops: list[tuple[int, int]] = []
        # per-host process specs, kept for fault restarts: a host coming
        # back up respawns these with fresh state (new pids, empty fds)
        self._proc_spec: dict[int, list[tuple]] = {}
        # locality may have renumbered gids; map hosts by NAME
        gid_of = {name: g for g, name in enumerate(self.sim.names)}
        for h in expand_hosts(cfg):
            gid = gid_of.get(h.name, h.gid)
            for p in h.spec.processes:
                spec = cfg.plugin_by_id(p.plugin)
                path = resolve_path(spec.path, cfg.base_dir) if spec else p.plugin
                if not (path.endswith(".so") and os.path.exists(path)):
                    raise ValueError(
                        "the process tier runs native plugins only: "
                        f"plugin {p.plugin!r} resolves to {path!r}, which "
                        "is not an existing .so — configs cannot mix "
                        "native plugins with modeled ones yet"
                    )
                argv = [os.path.basename(path)] + shlex.split(p.arguments)
                pid = self.rt.spawn(gid, path, argv)
                self.rt.set_host_name(pid, h.name)
                self.pid_host[pid] = gid
                heapq.heappush(self._starts, (int(p.starttime * SECOND), pid))
                if p.stoptime:
                    heapq.heappush(
                        self._stops, (int(p.stoptime * SECOND), pid)
                    )
                self._proc_spec.setdefault(gid, []).append((
                    path, argv, h.name, int(p.starttime * SECOND),
                    int(p.stoptime * SECOND) if p.stoptime else None,
                ))

        # UDP endpoint bookkeeping (udp.c:26-60 association realized as
        # driver maps): (pid, fd) -> (gid, slot, port) for runtime
        # endpoints, the (gid, port) demux index for routing delivery
        # records back to senders and receivers, and each host's
        # virtual IP for recvfrom addresses
        self.udp_eps: dict[tuple[int, int], tuple[int, int, int]] = {}
        self.udp_port: dict[tuple[int, int], tuple[int, int]] = {}
        self._udp_used = False
        self._gid_ip: dict[int, int] = {
            a.host_id: a.ip for a in self.sim.dns.entries()
        }

        # device arrays may be shape-bucketed wider than the real host
        # count; the observe mirrors must match the DEVICE row dimension
        # (padded rows stay inert/zero)
        h_n = (self.sim.engine.cfg.n_hosts
               * self.sim.engine.cfg.n_shards)
        self._prev_udp_cnt = np.zeros((h_n,), np.int32)
        # (gid, port) -> (pid, fd) for EXITED senders whose in-flight
        # datagrams still need payload attribution at the ring drain.
        # _udp_outstanding holds each source endpoint's sent-but-not-yet-
        # drained datagram seqs: a zombie is pruned the moment its set
        # empties (the drain cursor passed its last in-flight datagram),
        # with _udp_zombie_deadline as the TTL backstop for datagrams the
        # network dropped (those never reach any ring)
        self._udp_src_zombies: dict[tuple[int, int], tuple[int, int]] = {}
        self._udp_outstanding: dict[tuple[int, int], set[int]] = {}
        self._udp_zombie_deadline: dict[tuple[int, int], int] = {}
        self._prev_rx = np.zeros((h_n, n_sockets), np.int64)
        self._prev_fin = np.zeros((h_n, n_sockets), bool)
        # vectorized-observe state: endpoint membership, per-slot owed
        # bytes, and the device TCB's slot-incarnation counter (conn_gen)
        # for robust reuse detection
        self._known = np.zeros((h_n, n_sockets), bool)
        self._undeliv = np.zeros((h_n, n_sockets), np.int64)
        self._prev_gen = np.zeros((h_n, n_sockets), np.int32)

    # ------------------------------------------------------------- helpers
    def _alloc_slot(self, gid: int) -> int:
        # driver-owned slots grow downward from the top (TCP child
        # sockets allocate first-free from 0 upward, so the ends never
        # collide); slots freed by completed close handshakes recycle
        # first, so connection churn no longer exhausts the table
        free = self._free_slots.get(gid)
        if free:
            return free.pop()
        s = self._next_slot.get(gid, self.n_sockets - 1)
        self._next_slot[gid] = s - 1
        if s < self._child_limit:
            raise RuntimeError(
                f"host {gid}: out of driver socket slots (reserved "
                f"[{self._child_limit}, {self.n_sockets}); raise "
                "n_sockets or driver_slots)"
            )
        return s

    def _close_udp_ep(self, key, rows, now: int) -> None:
        """Tear down one UDP endpoint (exit/close/stoptime-kill/crash
        share this): free the driver slot, clear the DESTINATION demux
        row — arrivals addressed to it now drop, kernel semantics — but
        keep SOURCE attribution for datagrams already sent: the ring
        drain needs (pid, fd) to locate the payload stash (the runtime
        keeps fds entries until shim_free), and dropping it lost a
        server's final reply when it echoed then returned from main().
        A zombie is only created while datagrams are actually
        outstanding, and the ring drain prunes it the moment its last
        one is accounted for — churny UDP workloads no longer grow this
        map without bound."""
        gid, slot, port = self.udp_eps.pop(key)
        self.udp_port.pop((gid, port), None)
        src_key = (gid, port)
        if self._udp_outstanding.get(src_key):
            self._udp_src_zombies[src_key] = key
            self._udp_zombie_deadline[src_key] = now + _UDP_ZOMBIE_TTL_NS
        else:
            self._udp_outstanding.pop(src_key, None)
        self._free_slots.setdefault(gid, []).append(slot)
        rows.append((gid, [CMD_UDP_CLOSE, slot]))

    def _register_ep(self, gid: int, slot: int, pid: int, fd: int,
                     driver_owned: bool) -> None:
        self.ep_of[(gid, slot)] = (pid, fd)
        self.slot_of[(pid, fd)] = (gid, slot)
        self._known[gid, slot] = True
        self._undeliv[gid, slot] = 0
        self._prev_fin[gid, slot] = False  # fresh incarnation baseline
        if driver_owned:
            self._driver_owned.add((gid, slot))

    def _drop_ep(self, gid: int, slot: int, *, recycle: bool,
                 surface_eof: bool = False) -> None:
        """Forget one endpoint's mappings, flushing owed bytes in BOTH
        wire directions first (the endpoints' byte streams outlive the
        slot mapping in the native runtime, so a final flush here keeps
        a peer from being stranded mid-stream). Optionally recycles a
        driver-owned slot and surfaces EOF to the dropped side."""
        key = (gid, slot)
        ep = self.ep_of.pop(key, None)
        peer = self.wire.pop(key, None)
        if peer is not None:
            self.wire.pop(peer, None)
        if ep is not None:
            pid, fd = ep
            if peer is not None and peer in self.ep_of:
                ppid, pfd = self.ep_of[peer]
                # 1. bytes this reader is still owed from its peer
                owed = int(self._undeliv[key])
                if owed:
                    moved = self.rt.wire_deliver(ppid, pfd, pid, fd, owed)
                    self._undeliv[key] -= max(moved, 0)
                # 2. bytes the peer is still owed from this endpoint —
                # after this drop nothing would route them
                powed = int(self._undeliv[peer])
                if powed:
                    moved = self.rt.wire_deliver(pid, fd, ppid, pfd, powed)
                    self._undeliv[peer] -= max(moved, 0)
            if self._undeliv[key]:
                self.lost_stream_bytes += int(self._undeliv[key])
            if surface_eof:
                self.rt.wire_fin(pid, fd)
            self.slot_of.pop(ep, None)
        fk = self._four_key.pop(key, None)
        if fk is not None:
            self._four.pop(fk, None)
        self.pending_conn.pop(key, None)
        self._known[gid, slot] = False
        self._undeliv[gid, slot] = 0
        if key in self._driver_owned:
            self._driver_owned.discard(key)
            if recycle:
                self._free_slots.setdefault(gid, []).append(slot)

    # ------------------------------------------------------------- faults
    def _fault_down(self, gid: int, rows, now: int) -> None:
        """A scheduled crash took the host down: kill its native
        processes and drop the driver's endpoint bookkeeping outright.
        The device side wipes the host's queue and re-templates its rows
        at the fault epoch, so there is no close handshake to observe —
        surviving peers tear down through the real retransmit/RST paths
        instead, exactly as against a real dead box."""
        for pid, g in list(self.pid_host.items()):
            if g != gid or pid in self.exit_codes:
                continue
            self.rt.kill(pid, 0)
            self.exit_codes[pid] = 0
            for key in [k for k in self._timer_gen if k[0] == pid]:
                self._timer_gen[key] += 1
            self._wakes = [w for w in self._wakes if w[1] != pid]
            heapq.heapify(self._wakes)
            # a process the crash beat to its starttime never boots this
            # incarnation (it comes back with the host, if it restarts)
            self._starts = [s for s in self._starts if s[1] != pid]
            heapq.heapify(self._starts)
        for key in [k for k in list(self.ep_of) if k[0] == gid]:
            self._drop_ep(*key, recycle=True)
        for gp in [k for k in self.listen_ep if k[0] == gid]:
            ep = self.listen_ep.pop(gp)
            self._listen_of_ep.pop(ep, None)
        for key in [k for k, v in self.udp_eps.items() if v[0] == gid]:
            self._close_udp_ep(key, rows, now)

    def _fault_up(self, gid: int, now: int) -> None:
        """The host rebooted: respawn its configured processes with
        fresh state — new pids, empty fd tables, starttime re-applied
        relative to boot (the operator-restarts-the-daemon analog)."""
        for path, argv, name, start_ns, stop_ns in self._proc_spec.get(
                gid, ()):
            if stop_ns is not None and stop_ns <= now:
                continue  # its configured lifetime already ended
            pid = self.rt.spawn(gid, path, argv)
            self.rt.set_host_name(pid, name)
            self.pid_host[pid] = gid
            heapq.heappush(self._starts, (max(start_ns, now), pid))
            if stop_ns is not None:
                heapq.heappush(self._stops, (stop_ns, pid))

    def _wire_try_pair(self, gid: int, slot: int, lport: int,
                       peer_gid: int, pport: int) -> None:
        """Index an endpoint by its connection 4-tuple and pair it with
        the reverse tuple's endpoint when that side exists."""
        key = (gid, slot)
        fk = (gid, lport, peer_gid, pport)
        self._four[fk] = key
        self._four_key[key] = fk
        other = self._four.get((peer_gid, pport, gid, lport))
        if other is not None and other != key:
            self.wire[key] = other
            self.wire[other] = key

    def _alloc_sport(self, gid: int) -> int:
        p = self._next_sport.get(gid, EPHEMERAL_BASE + 4096)
        self._next_sport[gid] = p + 1
        return p

    def _alloc_fd(self, pid: int) -> int:
        # driver-assigned child fds live in the 2'000'000+ band, disjoint
        # from the runtime's own 1'000'000+ allocations (shim_runtime.cpp
        # kFirstFd) — fd numbers stay globally unique
        f = self._next_fd.get(pid, 2_000_000)
        self._next_fd[pid] = f + 1
        return f

    # ---------------------------------------------------------- translate
    def _translate(self, reqs, now: int) -> list[tuple[int, list[int]]]:
        rows: list[tuple[int, list[int]]] = []
        for r in reqs:
            pid, fd = int(r.pid), int(r.fd)
            gid = self.pid_host[pid]
            if r.op == REQ_LISTEN:
                slot = self._alloc_slot(gid)
                self._register_ep(gid, slot, pid, fd, driver_owned=True)
                self.listen_ep[(gid, int(r.port))] = (pid, fd)
                self._listen_of_ep[(pid, fd)] = (gid, int(r.port))
                rows.append((gid, [CMD_LISTEN, slot, int(r.port)]))
            elif r.op == REQ_CONNECT:
                name = r.name.decode()
                if name:
                    addr = self.sim.dns.resolve_name(name)
                elif int(r.a1) in (0, 0x7F000001):
                    # wildcard/loopback: this host (the reference's
                    # single-process tests connect to INADDR_LOOPBACK;
                    # the device routes it over the topology self-loop)
                    addr = self.sim.dns.resolve_name(
                        self.sim.names[gid]
                    )
                else:
                    # interposer form: a1 carries the virtual IPv4 from
                    # connect(sockaddr_in) (host order)
                    addr = self.sim.dns.resolve_ip(int(r.a1))
                if addr is None:
                    self._pending_comps.append(
                        (pid, COMP_CONNECT_FAIL, fd, 0)
                    )
                    continue
                slot = self._alloc_slot(gid)
                sport = self._alloc_sport(gid)
                self._register_ep(gid, slot, pid, fd, driver_owned=True)
                self.pending_conn[(gid, slot)] = (pid, fd)
                self._wire_try_pair(gid, slot, sport, addr.host_id,
                                    int(r.port))
                rows.append(
                    (gid, [CMD_CONNECT, slot, sport, addr.host_id,
                           int(r.port)])
                )
            elif r.op == REQ_SEND:
                key = (pid, fd)
                if key in self.slot_of:
                    gid, slot = self.slot_of[key]
                    rows.append((gid, [CMD_SEND, slot, int(r.a0)]))
            elif r.op == REQ_UDP_BIND:
                self._udp_used = True
                slot = self._alloc_slot(gid)
                self.udp_eps[(pid, fd)] = (gid, slot, int(r.port))
                self.udp_port[(gid, int(r.port))] = (pid, fd)
                # a re-bound port supersedes any exited sender's zombie:
                # without this, the drain could attribute the NEW
                # process's in-flight datagrams to the old one's stash
                self._udp_src_zombies.pop((gid, int(r.port)), None)
                self._udp_outstanding.pop((gid, int(r.port)), None)
                self._udp_zombie_deadline.pop((gid, int(r.port)), None)
                rows.append((gid, [CMD_UDP_BIND, slot, int(r.port)]))
            elif r.op == REQ_SENDTO:
                ep = self.udp_eps.get((pid, fd))
                if ep is None:
                    continue  # closed underneath the sender
                seq = int(r.a0) >> 32
                nbytes = int(r.a0) & 0xFFFFFFFF
                ip = int(r.a1)
                # wildcard/loopback route to the sending host itself
                if ip in (0, 0x7F000001):
                    dst_gid = gid
                else:
                    addr = self.sim.dns.resolve_ip(ip)
                    if addr is None:
                        continue  # unroutable: the datagram just drops
                    dst_gid = addr.host_id
                rows.append((gid, [CMD_SENDTO, ep[1], dst_gid,
                                   int(r.port), nbytes, seq]))
                self._udp_outstanding.setdefault(
                    (gid, ep[2]), set()).add(seq)
            elif r.op == REQ_CLOSE:
                key = (pid, fd)
                if key in self._listen_of_ep:
                    # a closed listener has no handshake to run down:
                    # recycle its slot NOW so a close-then-listen pair
                    # arriving in one pump (the reference's sequential
                    # test programs do this) never exhausts the band;
                    # the device resets the row at the window open
                    gp = self._listen_of_ep.pop(key)
                    self.listen_ep.pop(gp, None)
                    if key in self.slot_of:
                        gid, slot = self.slot_of[key]
                        rows.append((gid, [CMD_CLOSE, slot]))
                        self._drop_ep(gid, slot, recycle=True)
                        # pre-acknowledge the conn_gen bump the device's
                        # listener reset will apply at the window open:
                        # without this, a re-listen that reuses the slot
                        # in this same pump would read the bump as ITS
                        # OWN turnover and be torn down by observe
                        self._prev_gen[gid, slot] += 1
                elif key in self.udp_eps:
                    self._close_udp_ep(key, rows, now)
                elif key in self.slot_of:
                    gid, slot = self.slot_of[key]
                    rows.append((gid, [CMD_CLOSE, slot]))
            elif r.op == REQ_SLEEP:
                heapq.heappush(self._wakes, (int(r.a0), pid, int(r.port)))
            elif r.op == REQ_TIMER:
                gen = int(r.port)
                self._timer_gen[(pid, fd)] = gen
                if int(r.a0) >= 0:  # a0 = -1 is a disarm
                    heapq.heappush(
                        self._timers, (int(r.a0), pid, fd, int(r.a1), gen)
                    )
            elif r.op == REQ_LOG:
                self.logs.append((now, pid, r.name.decode()))
            elif r.op == REQ_EXIT:
                self.exit_codes[pid] = int(r.a0)
                # a process that returns from main() with sockets still
                # open gets the kernel-close semantics: FIN every driver
                # endpoint it holds (the same sweep the stoptime-kill
                # path runs) and free its datagram slots — without this
                # its peers never see EOF and slot_of pins the
                # all-exited early break open forever
                for (p_pid, p_fd), (gid, slot) in list(self.slot_of.items()):
                    if p_pid == pid:
                        rows.append((gid, [CMD_CLOSE, slot]))
                for key in [k for k in self.udp_eps if k[0] == pid]:
                    self._close_udp_ep(key, rows, now)
        return rows

    # ------------------------------------------------------------- inject
    def _inject(self, st, rows, now: int):
        if not rows:
            return st
        m = len(rows)
        cap = 1 << max(m - 1, 0).bit_length()  # pad: bounded recompiles
        times = np.full((cap,), np.iinfo(np.int64).max, np.int64)
        dst = np.zeros((cap,), np.int32)
        seq = np.zeros((cap,), np.int32)
        kind = np.zeros((cap,), np.int32)
        argw = np.zeros((cap, N_PKT_ARGS), np.int32)
        src_seq = np.array(jax.device_get(st.src_seq))  # shadowlint: no-deadline=proc-tier pump; covered by the stall watchdog's pets
        for i, (gid, args) in enumerate(rows):
            times[i] = now
            dst[i] = gid
            seq[i] = src_seq[gid]
            src_seq[gid] += 1
            kind[i] = self.model.kind_cmd
            argw[i, : len(args)] = args
        ev = Events(
            time=jnp.asarray(times), dst=jnp.asarray(dst),
            src=jnp.asarray(dst), seq=jnp.asarray(seq),
            kind=jnp.asarray(kind), args=jnp.asarray(argw),
        )
        mask = jnp.asarray(np.arange(cap) < m)
        q2 = self._push_jit(st.queues, ev, mask, jnp.int32(0))
        return dataclasses.replace(
            st, queues=q2, src_seq=jnp.asarray(src_seq)
        )

    # ------------------------------------------------------------ observe
    def _observe(self, st) -> None:
        """Diff device tables into completions + byte/FIN wire ops.

        One batched device_get per window; every scan below walks only
        numpy-selected CHANGED entries, never the full [H, S] table in
        Python (the round-2 version's per-slot loops were O(hosts x
        slots) per window — hopeless at 1k processes)."""
        net = st.hosts.net
        tstate, rx, fin_raw, fgen, lport, phost, pport, cgen = (
            np.asarray(x)
            for x in jax.device_get((  # shadowlint: no-deadline=proc-tier pump; covered by the stall watchdog's pets
                net.tcb.state, net.sockets.rx_bytes, st.hosts.app.fin_seen,
                st.hosts.app.fin_gen, net.sockets.local_port,
                net.sockets.peer_host, net.sockets.peer_port,
                net.tcb.conn_gen,
            ))
        )
        # a fin_seen flag only counts for the slot incarnation it was
        # recorded against; a sticky flag from a previous connection on a
        # reused slot must not read as this stream's EOF
        fin = fin_raw & (fgen == cgen)

        # UDP delivery ring: move each newly-recorded datagram's payload
        # from its sender's in-flight pool to the receiver's queue
        # (fetched only once a datagram socket exists — pure-TCP runs
        # pay nothing)
        if self._udp_used:
            app = st.hosts.app
            ucnt, usrc, usport, udport, _ulen, useq = (
                np.asarray(x) for x in jax.device_get((  # shadowlint: no-deadline=proc-tier pump; covered by the stall watchdog's pets
                    app.udp_cnt, app.udp_src, app.udp_sport,
                    app.udp_dport, app.udp_len, app.udp_seq,
                ))
            )
            for g in np.nonzero(ucnt != self._prev_udp_cnt)[0]:
                g = int(g)
                lo, hi = int(self._prev_udp_cnt[g]), int(ucnt[g])
                if hi - lo > UDP_RING:
                    raise RuntimeError(
                        f"host {g}: {hi - lo} UDP datagrams delivered in "
                        f"one window overran the {UDP_RING}-slot ring; "
                        "deliveries were lost"
                    )
                for i in range(lo, hi):
                    k = i % UDP_RING
                    dst_ep = self.udp_port.get((g, int(udport[g, k])))
                    src_key = (int(usrc[g, k]), int(usport[g, k]))
                    src_ep = (self.udp_port.get(src_key)
                              or self._udp_src_zombies.get(src_key))
                    out = self._udp_outstanding.get(src_key)
                    if out is not None:
                        out.discard(int(useq[g, k]))
                        if not out and src_key in self._udp_src_zombies:
                            # the drain cursor just passed the zombie's
                            # last in-flight datagram: nothing can
                            # attribute to it anymore
                            del self._udp_src_zombies[src_key]
                            del self._udp_outstanding[src_key]
                            self._udp_zombie_deadline.pop(src_key, None)
                    if dst_ep is None or src_ep is None:
                        continue  # endpoint closed while in flight
                    self.rt.udp_deliver(
                        src_ep[0], src_ep[1], int(useq[g, k]),
                        dst_ep[0], dst_ep[1],
                        self._gid_ip.get(int(usrc[g, k]), 0),
                        int(usport[g, k]),
                    )
            self._prev_udp_cnt = ucnt.copy()

        # accumulate this window's delivered-byte deltas FIRST (against
        # the pre-drop _known mask): bytes that land in the same window
        # an endpoint's slot turns over must reach the drop-time flush,
        # not vanish with the _known clear
        self._undeliv += np.where(self._known,
                                  np.maximum(rx - self._prev_rx, 0), 0)
        prev_rx = self._prev_rx  # pre-update snapshot for step 2 below
        self._prev_rx = rx

        # 0. slot incarnation changed under a live endpoint: the device
        # TCP closed and reset the slot (every path back to CLOSED goes
        # through _fresh_row_like's conn_gen bump — tcp.py RST/final-ACK
        # frees and TIME_WAIT expiry). The old incarnation's stream is
        # over: flush owed bytes, surface EOF, recycle driver slots.
        for gid, slot in zip(*np.nonzero((cgen != self._prev_gen)
                                         & self._known)):
            key = (int(gid), int(slot))
            if key in self.pending_conn:
                continue  # refused connect: handled below as CLOSED
            self._drop_ep(*key, recycle=True, surface_eof=True)

        # 1. pending active opens resolve
        for key, (pid, fd) in list(self.pending_conn.items()):
            s = tstate[key]
            if s >= ESTABLISHED:
                self._pending_comps.append((pid, COMP_CONNECT_OK, fd, 0))
                del self.pending_conn[key]
            elif s == CLOSED:
                self._pending_comps.append((pid, COMP_CONNECT_FAIL, fd, 0))
                self._drop_ep(*key, recycle=True)

        # 2. new established connections we don't know -> accepted
        # children (their local port is a listen port; driver-owned
        # connect slots are marked known at translate time)
        for gid, slot in zip(*np.nonzero((tstate >= ESTABLISHED)
                                         & ~self._known)):
            gid, slot = int(gid), int(slot)
            lp = self.listen_ep.get((gid, int(lport[gid, slot])))
            if lp is None:
                continue
            lpid, lfd = lp
            nfd = self._alloc_fd(lpid)
            self._register_ep(gid, slot, lpid, nfd, driver_owned=False)
            # under loss the handshake's final ACK can arrive in the same
            # window as the first data burst: the child is ESTABLISHED
            # with rx_bytes already advanced, but the delta pass above ran
            # before this endpoint was _known. Everything delivered since
            # the last window is owed. rx_bytes is a cumulative lifetime
            # counter (never reset on slot reuse), so the baseline is the
            # pre-update snapshot — the previous incarnation's final
            # count — not zero.
            self._undeliv[gid, slot] = max(
                int(rx[gid, slot]) - int(prev_rx[gid, slot]), 0
            )
            self._wire_try_pair(gid, slot, int(lport[gid, slot]),
                                int(phost[gid, slot]),
                                int(pport[gid, slot]))
            self._pending_comps.append((lpid, COMP_ACCEPT, lfd, nfd))

        # 3. delivered bytes + FIN propagation, changed endpoints only
        fresh_fin = fin & ~self._prev_fin
        for gid, slot in zip(*np.nonzero(
            self._known & ((self._undeliv > 0) | fresh_fin)
        )):
            key = (int(gid), int(slot))
            pid, fd = self.ep_of[key]
            owed = int(self._undeliv[key])
            if owed and key in self.wire:
                src = self.wire[key]
                if src in self.ep_of:
                    spid, sfd = self.ep_of[src]
                    moved = self.rt.wire_deliver(spid, sfd, pid, fd, owed)
                    if moved > 0:
                        self._undeliv[key] -= moved
            if fresh_fin[key]:
                if not self._undeliv[key]:
                    self.rt.wire_fin(pid, fd)
                else:
                    # bytes still owed; FIN re-checked next window
                    fin[key] = False

        self._prev_fin = fin
        self._prev_gen = cgen.copy()

    def live_pids(self) -> list[int]:
        """Virtual pids still running, by the native runtime's green-
        thread ground truth (driver bookkeeping can lag a window behind
        on kills; the runtime cannot) — recorded in the watchdog's
        stall diagnostic bundle."""
        alive = set(self.rt.live_pids())
        return sorted(p for p in self.pid_host if p in alive)

    # ---------------------------------------------------------------- run
    def run(self, stop_s: float | None = None, supervisor=None):
        """Drive the window loop to the stop time.

        `supervisor` (runtime.Supervisor, optional) is petted once per
        window with the frontier time — covering BOTH blocking sites,
        the jitted step and the native `shim_pump` (a plugin spinning
        without yielding hangs the pump forever; the watchdog converts
        that into a stall abort with the live pids in the bundle) — and
        its stop requests (SIGINT/SIGTERM) end the run at the next
        window boundary.
        """
        sim = self.sim
        stop_ns = int(stop_s * SECOND) if stop_s is not None else sim.stop_ns
        st = sim.state0
        now = 0
        # host-side mirror of the fault schedule's liveness flips: a
        # down-flip kills the host's native processes, an up-flip
        # reboots them (the device applies the matching queue wipe and
        # state re-template at the same epoch inside the jitted loop)
        flips = (sim.faults.transitions_in(-1, stop_ns)
                 if sim.faults is not None else [])
        fcur = 0
        while True:
            comps = self._pending_comps
            self._pending_comps = []
            stop_rows = []
            while fcur < len(flips) and flips[fcur][0] <= now:
                _, fgid, up = flips[fcur]
                fcur += 1
                if up:
                    self._fault_up(fgid, now)
                else:
                    self._fault_down(fgid, stop_rows, now)
            while self._starts and self._starts[0][0] <= now:
                _, pid = heapq.heappop(self._starts)
                self.rt.start(pid)
            while self._stops and self._stops[0][0] <= now:
                _, pid = heapq.heappop(self._stops)
                if pid in self.exit_codes:
                    continue  # already exited on its own
                self.rt.kill(pid, 0)
                self.exit_codes[pid] = 0
                # retire the dead process's timer arms and sleeps so
                # they stop bounding window sizes and pumping
                # completions at nobody (the stale-gen path drops the
                # heap entries lazily)
                for key in [k for k in self._timer_gen if k[0] == pid]:
                    self._timer_gen[key] += 1
                self._wakes = [w for w in self._wakes if w[1] != pid]
                heapq.heapify(self._wakes)
                # kernel-side teardown continues for the dead process's
                # sockets (the reference's process_stop leaves the TCP
                # close handshakes to the host model): FIN every driver
                # endpoint the process still holds
                for (pfd_pid, fd), (gid, slot) in list(self.slot_of.items()):
                    if pfd_pid == pid:
                        stop_rows.append((gid, [CMD_CLOSE, slot]))
                # and its datagram sockets (no handshake to run down:
                # free the slot and clear the demux row immediately)
                for key in [k for k in self.udp_eps if k[0] == pid]:
                    self._close_udp_ep(key, stop_rows, now)
            if stop_rows:
                st = self._inject(st, stop_rows, now)
            while self._wakes and self._wakes[0][0] <= now:
                _, pid, gen = heapq.heappop(self._wakes)
                comps.append((pid, COMP_WAKE, -1, gen))
            while self._timers and self._timers[0][0] <= now:
                t, pid, fd, interval, gen = heapq.heappop(self._timers)
                if self._timer_gen.get((pid, fd)) != gen:
                    continue  # re-armed or closed since: stale
                if interval > 0:
                    # credit every expiration the window skipped over and
                    # re-arm on the absolute grid (timer.c interval
                    # expirations with no drift)
                    n_exp = (now - t) // interval + 1
                    heapq.heappush(
                        self._timers, (t + n_exp * interval, pid, fd,
                                       interval, gen)
                    )
                comps.append((pid, COMP_TIMER, fd, int(n_exp if interval > 0 else 1), gen))

            if self.sim.profiler is not None:
                with self.sim.profiler.phase("pump"):
                    reqs = self.rt.pump(now, comps)
            else:
                reqs = self.rt.pump(now, comps)
            st = self._inject(st, self._translate(reqs, now), now)
            if supervisor is not None:
                supervisor.pet(
                    now_ns=now, n_live_processes=len(self.live_pids()),
                    n_exited=len(self.exit_codes),
                )
                if supervisor.stop_requested:
                    # graceful shutdown: the proc tier has no checkpoint
                    # (native endpoint streams live host-side), so "at
                    # the next window boundary" just means stop cleanly
                    # — logs and exit codes collected so far survive
                    break

            if now >= stop_ns:
                break
            # every process has exited and no driver endpoint still owes
            # a teardown handshake: the remaining horizon is dead time
            # (the reference likewise ends when its process count hits
            # zero before stoptime, master.c end-of-simulation path)
            if (
                self.exit_codes
                and len(self.exit_codes) >= len(self.pid_host)
                and not self._starts
                and not self.slot_of
                and not self.udp_eps
                and fcur >= len(flips)  # a restart could revive hosts
            ):
                break
            # never step past the next host-side interest point
            bound = stop_ns
            if self._starts:
                bound = min(bound, max(self._starts[0][0], now + 1))
            if self._stops:
                bound = min(bound, max(self._stops[0][0], now + 1))
            if self._wakes:
                bound = min(bound, max(self._wakes[0][0], now + 1))
            # retire re-armed/disarmed timer entries so a dead arm stops
            # bounding window sizes
            while self._timers and self._timer_gen.get(
                (self._timers[0][1], self._timers[0][2])
            ) != self._timers[0][4]:
                heapq.heappop(self._timers)
            if self._timers:
                bound = min(bound, max(self._timers[0][0], now + 1))
            # land the window edge on the next liveness flip so the
            # device's epoch switch and the driver's kill/respawn agree
            # on when the crash happened
            if fcur < len(flips):
                bound = min(bound, max(flips[fcur][0], now + 1))
            st = sim.step_window(st, bound)
            if sim.pressure is not None:
                # the tier already steps window-by-window (bounded by
                # host-side interest points), so the spill reservoir's
                # harvest/refill hook slots in at every boundary for
                # free — sharing the frontier probe's device_get so the
                # idle refill check costs no extra round-trip
                now_a, wr = jax.device_get((st.now, st.queues.spill.wr))  # shadowlint: no-deadline=proc-tier pump; covered by the stall watchdog's pets
                st = sim._note_owned(
                    sim.pressure.boundary(st, wr=np.asarray(wr))
                )
                now = int(now_a)
            else:
                now = int(jax.device_get(st.now))  # shadowlint: no-deadline=proc-tier pump; covered by the stall watchdog's pets
            self._observe(st)
            if self._udp_zombie_deadline:
                for zk in [k for k, d in self._udp_zombie_deadline.items()
                           if d <= now]:
                    del self._udp_zombie_deadline[zk]
                    self._udp_src_zombies.pop(zk, None)
                    self._udp_outstanding.pop(zk, None)
        drops = int(jax.device_get(st.queues.drops.sum()))  # shadowlint: no-deadline=proc-tier pump; covered by the stall watchdog's pets
        if drops and self.overflow == "strict":
            from shadow_tpu.runtime.pressure import QueuePressureError

            raise QueuePressureError(
                drops, self.sim.engine.cfg.capacity, self.sim.summary(st)
            )
        if drops and self.strict_overflow and self.overflow == "drop":
            raise RuntimeError(
                f"event queue overflow: {drops} events dropped (capacity "
                f"{self.sim.engine.cfg.capacity}); native processes may "
                "have observed a corrupted simulation — rerun with a "
                "larger capacity"
            )
        if self.lost_stream_bytes and self.strict_overflow:
            raise RuntimeError(
                f"{self.lost_stream_bytes} delivered bytes could not be "
                "flushed to their endpoint before its slot turned over — "
                "a native process observed a truncated stream"
            )
        return st

    def close(self):
        self.rt.close()

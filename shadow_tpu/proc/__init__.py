"""Real-binary execution tier: native green-thread runtime + device bridge.

See native/shim/shim_runtime.cpp (the runtime), proc/native.py (build +
ctypes bindings), proc/model.py (the device-side command/observation
model), proc/tier.py (the window-batched syscall exchange loop).
"""

from shadow_tpu.proc.native import ShimRuntime, build_runtime, compile_plugin
from shadow_tpu.proc.tier import ProcessTier  # noqa: E402

__all__ = ["ShimRuntime", "build_runtime", "compile_plugin", "ProcessTier"]

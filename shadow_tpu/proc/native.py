"""Build + ctypes bindings for the native shim runtime.

The runtime (native/shim/shim_runtime.cpp) is compiled on demand with the
system toolchain into native/build/ — the framework's equivalent of the
reference's cmake targets for rpth/elf-loader/preload (they build once
beside the simulator; here the first ProcessTier use triggers it).
"""

from __future__ import annotations

import ctypes
import os
import subprocess

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_SHIM_DIR = os.path.join(_REPO_ROOT, "native", "shim")
_BUILD_DIR = os.path.join(_REPO_ROOT, "native", "build")

REQ_LISTEN, REQ_CONNECT, REQ_SEND, REQ_CLOSE = 1, 2, 3, 4
REQ_SLEEP, REQ_EXIT, REQ_LOG, REQ_TIMER = 5, 6, 7, 8
REQ_UDP_BIND, REQ_SENDTO = 9, 10
COMP_CONNECT_OK, COMP_CONNECT_FAIL, COMP_ACCEPT, COMP_WAKE = 1, 2, 3, 4
COMP_TIMER = 5


class ShimReq(ctypes.Structure):
    _fields_ = [
        ("pid", ctypes.c_int32),
        ("op", ctypes.c_int32),
        ("fd", ctypes.c_int32),
        ("port", ctypes.c_int32),
        ("a0", ctypes.c_int64),
        ("a1", ctypes.c_int64),
        ("name", ctypes.c_char * 64),
    ]


class ShimComp(ctypes.Structure):
    _fields_ = [
        ("pid", ctypes.c_int32),
        ("op", ctypes.c_int32),
        ("fd", ctypes.c_int32),
        ("pad", ctypes.c_int32),
        ("r0", ctypes.c_int64),
    ]


# ASan + UBSan, leak checking on, hard-fail on any UB report. These can
# only be applied to EXECUTABLE targets here: this container's dynamic
# loader cannot host a sanitized DSO in a dlmopen namespace (the ASan
# runtime must be first in the *initial* library list, and a secondary
# namespace has no such slot — every preload/static-libasan variant
# fails link-time or load-time). sanitizer_smoke() therefore links the
# interposer INTO a sanitized driver binary instead of sanitizing the
# plugin .so path.
SANITIZE_FLAGS = [
    "-fsanitize=address,undefined",
    "-fno-sanitize-recover=all",
    "-fno-omit-frame-pointer",
    "-g", "-O1",
]


def _compile(sources: list[str], out: str, extra: list[str],
             cc: str | None = None, sanitize: bool = False) -> str:
    os.makedirs(_BUILD_DIR, exist_ok=True)
    if os.path.exists(out) and all(
        os.path.getmtime(out) >= os.path.getmtime(s) for s in sources
    ):
        return out
    if cc is None:
        cc = "gcc" if all(s.endswith(".c") for s in sources) else "g++"
    opt = SANITIZE_FLAGS if sanitize else ["-O2"]
    cmd = [cc, *opt, "-fPIC", "-shared", "-o", out, *sources,
           "-I", _SHIM_DIR, "-ldl", *extra]
    res = subprocess.run(cmd, capture_output=True, text=True)
    if res.returncode != 0:
        raise RuntimeError(f"native build failed:\n{' '.join(cmd)}\n{res.stderr}")
    return out


def build_runtime() -> str:
    """Compile (if stale) and return the runtime .so path."""
    return _compile(
        [os.path.join(_SHIM_DIR, "shim_runtime.cpp")],
        os.path.join(_BUILD_DIR, "libshim_runtime.so"),
        [],
    )


_INTERPOSE_DIR = os.path.join(_REPO_ROOT, "native", "interpose")


def build_interposer() -> str:
    """Compile (if stale) and return libshadow_interpose.so — the libc
    surface unmodified POSIX plugins link against (the reference's
    libshadow-interpose.so role, src/preload/interposer.c)."""
    return _compile(
        [os.path.join(_INTERPOSE_DIR, "interpose.c")],
        os.path.join(_BUILD_DIR, "libshadow_interpose.so"),
        [],
    )


def build_sanitizer_smoke() -> str:
    """Compile (if stale) interpose.c + asan_smoke.c into ONE sanitized
    executable. Statically linking the interposer into the driver makes
    its libc-shadowing definitions bind for the driver's direct calls —
    the same resolution order a dlmopen namespace gives plugins — while
    keeping the sanitizer runtime first in the initial library list,
    which the dlmopen path cannot (see SANITIZE_FLAGS note)."""
    sources = [
        os.path.join(_INTERPOSE_DIR, "interpose.c"),
        os.path.join(_INTERPOSE_DIR, "asan_smoke.c"),
    ]
    out = os.path.join(_BUILD_DIR, "asan_smoke")
    os.makedirs(_BUILD_DIR, exist_ok=True)
    if os.path.exists(out) and all(
        os.path.getmtime(out) >= os.path.getmtime(s) for s in sources
    ):
        return out
    cmd = ["gcc", *SANITIZE_FLAGS, "-D_GNU_SOURCE", "-o", out, *sources,
           "-I", _SHIM_DIR, "-ldl"]
    res = subprocess.run(cmd, capture_output=True, text=True)
    if res.returncode != 0:
        raise RuntimeError(
            f"sanitizer smoke build failed:\n{' '.join(cmd)}\n{res.stderr}")
    return out


def sanitizer_smoke(timeout: float = 120.0) -> dict:
    """Build and run the sanitized interposer harness.

    Returns {"ok", "returncode", "stdout", "stderr", "exe"}; ok requires
    exit 0 AND the ASAN_SMOKE_OK stamp (a sanitizer abort yields
    neither). Leak checking is forced on so the vfd/epoll/sigtable
    reset paths are verified to free what they allocate."""
    exe = build_sanitizer_smoke()
    env = dict(os.environ)
    env["ASAN_OPTIONS"] = "detect_leaks=1:abort_on_error=0:exitcode=23"
    env["UBSAN_OPTIONS"] = "halt_on_error=1:print_stacktrace=1"
    res = subprocess.run([exe], capture_output=True, text=True,
                         timeout=timeout, env=env)
    ok = res.returncode == 0 and "ASAN_SMOKE_OK" in res.stdout
    return {"ok": ok, "returncode": res.returncode,
            "stdout": res.stdout, "stderr": res.stderr, "exe": exe}


def compile_posix_plugin(
    source: str, name: str | None = None,
    include_dirs: list[str] | None = None,
    extra_sources: list[str] | None = None,
) -> str:
    """Compile an UNMODIFIED POSIX source (ordinary `main`, plain libc
    socket/poll/epoll/select calls) into a simulator plugin.

    The source is built as a shared object linked against
    libshadow_interpose ahead of libc, so inside its dlmopen namespace
    every libc call it makes resolves to the interposer and runs against
    the simulated stack — the reference's LD_PRELOAD contract
    (src/preload/preload_defs.h:10-375) realized per-namespace. The
    compat include dir supplies a minimal <glib.h> so reference test
    sources build as-is.
    """
    interposer = build_interposer()
    base = name or os.path.splitext(os.path.basename(source))[0]
    out = os.path.join(_BUILD_DIR, f"lib{base}.so")
    srcs = [source] + list(extra_sources or [])
    deps = srcs + [interposer]
    if os.path.exists(out) and all(
        os.path.getmtime(out) >= os.path.getmtime(s) for s in deps
    ):
        return out
    cc = "g++" if source.endswith(("cc", "cpp")) else "gcc"
    cmd = [
        cc, "-O1", "-fPIC", "-shared", "-D_GNU_SOURCE", "-o", out, *srcs,
        "-I", os.path.join(_INTERPOSE_DIR, "compat"),
        *sum([["-I", d] for d in (include_dirs or [])], []),
        "-L", _BUILD_DIR, "-lshadow_interpose",
        f"-Wl,-rpath,{_BUILD_DIR}", "-Wl,--no-as-needed",
    ]
    res = subprocess.run(cmd, capture_output=True, text=True)
    if res.returncode != 0:
        raise RuntimeError(
            f"posix plugin build failed:\n{' '.join(cmd)}\n{res.stderr}"
        )
    return out


def compile_plugin(source: str, name: str | None = None) -> str:
    """Compile a plugin .c/.cpp (exporting shim_main) into native/build."""
    base = name or os.path.splitext(os.path.basename(source))[0]
    cc = "g++" if source.endswith(("cc", "cpp")) else "gcc"
    out = os.path.join(_BUILD_DIR, f"lib{base}.so")
    os.makedirs(_BUILD_DIR, exist_ok=True)
    if os.path.exists(out) and os.path.getmtime(out) >= os.path.getmtime(source):
        return out
    cmd = [cc, "-O2", "-fPIC", "-shared", "-o", out, source, "-I", _SHIM_DIR]
    res = subprocess.run(cmd, capture_output=True, text=True)
    if res.returncode != 0:
        raise RuntimeError(f"plugin build failed:\n{' '.join(cmd)}\n{res.stderr}")
    return out


# count of runtime instances created in THIS interpreter — lets test
# harnesses detect they are not the first tier in the process (see the
# shutdown capstone's known-interaction containment)
N_RUNTIMES_CREATED = 0


class ShimRuntime:
    """ctypes wrapper over one runtime instance (a set of virtual
    processes sharing the driver's pump cadence)."""

    def __init__(self, max_reqs: int = 4096):
        global N_RUNTIMES_CREATED
        N_RUNTIMES_CREATED += 1
        lib = ctypes.CDLL(build_runtime())
        lib.shim_init.restype = ctypes.c_void_p
        lib.shim_free.argtypes = [ctypes.c_void_p]
        lib.shim_last_error.argtypes = [ctypes.c_void_p]
        lib.shim_last_error.restype = ctypes.c_char_p
        lib.shim_spawn.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_int,
        ]
        lib.shim_start.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.shim_pump.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.POINTER(ShimComp),
            ctypes.c_int, ctypes.POINTER(ShimReq), ctypes.c_int,
        ]
        lib.shim_wire_deliver.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_int64,
        ]
        lib.shim_wire_deliver.restype = ctypes.c_int64
        lib.shim_wire_fin.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_int,
        ]
        lib.shim_udp_deliver.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_int, ctypes.c_int64,
            ctypes.c_int, ctypes.c_int, ctypes.c_uint32, ctypes.c_int,
        ]
        lib.shim_udp_deliver.restype = ctypes.c_int64
        lib.shim_proc_exit_code.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.POINTER(ctypes.c_int),
        ]
        lib.shim_dns_add.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32,
        ]
        lib.shim_kill.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_int,
        ]
        lib.shim_set_host_name.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_char_p,
        ]
        lib.shim_set_seed.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        self._lib = lib
        self._rt = lib.shim_init()
        self._req_buf = (ShimReq * max_reqs)()
        self._max_reqs = max_reqs
        self._spawned: list[int] = []

    def close(self):
        if self._rt:
            self._lib.shim_free(self._rt)
            self._rt = None

    def spawn(self, host_gid: int, so_path: str, argv: list[str]) -> int:
        packed = b"\x00".join(a.encode() for a in argv) + b"\x00"
        pid = self._lib.shim_spawn(
            self._rt, host_gid, so_path.encode(), packed, len(argv)
        )
        if pid < 0:
            raise RuntimeError(
                self._lib.shim_last_error(self._rt).decode()
            )
        self._spawned.append(pid)
        return pid

    def live_pids(self) -> list[int]:
        """Pids the runtime still considers running — the green-thread
        ground truth the watchdog's stall bundle records (a spinning
        plugin is *running*, which is exactly the problem)."""
        if not self._rt:
            return []
        return [p for p in self._spawned if self.exit_code(p) is None]

    def start(self, pid: int) -> None:
        self._lib.shim_start(self._rt, pid)

    def set_host_name(self, pid: int, name: str) -> None:
        """Virtual hostname for gethostname/uname (dns.c name)."""
        self._lib.shim_set_host_name(self._rt, pid, name.encode())

    def set_seed(self, seed: int) -> None:
        """Simulation seed rooting every virtual process's deterministic
        rand()/urandom stream (random.c:15-50 hierarchy)."""
        self._lib.shim_set_seed(self._rt, seed)

    def pump(self, now_ns: int, comps: list[tuple]) -> list[ShimReq]:
        """comps: [(pid, op, fd, r0[, pad])] -> emitted requests."""
        carr = (ShimComp * max(len(comps), 1))()
        for i, c in enumerate(comps):
            pid, op, fd, r0 = c[:4]
            carr[i].pid, carr[i].op, carr[i].fd, carr[i].r0 = pid, op, fd, r0
            carr[i].pad = c[4] if len(c) > 4 else 0
        n = self._lib.shim_pump(
            self._rt, now_ns, carr, len(comps), self._req_buf, self._max_reqs
        )
        return [self._req_buf[i] for i in range(n)]

    def wire_deliver(self, src_pid, src_fd, dst_pid, dst_fd, n) -> int:
        return int(self._lib.shim_wire_deliver(
            self._rt, src_pid, src_fd, dst_pid, dst_fd, n
        ))

    def wire_fin(self, pid, fd) -> None:
        self._lib.shim_wire_fin(self._rt, pid, fd)

    def udp_deliver(self, src_pid, src_fd, seq, dst_pid, dst_fd,
                    src_ip, src_port) -> int:
        """Move one device-delivered datagram's payload from the sender's
        in-flight pool to the receiver's queue (source address stamped
        for recvfrom)."""
        return int(self._lib.shim_udp_deliver(
            self._rt, src_pid, src_fd, seq, dst_pid, dst_fd, src_ip,
            src_port,
        ))

    def dns_add(self, name: str, ip: int) -> None:
        """Push one name -> virtual-IPv4 (host order) mapping for the
        interposer's getaddrinfo (dns.c registry semantics)."""
        self._lib.shim_dns_add(self._rt, name.encode(), ip)

    def kill(self, pid: int, exit_code: int = 0) -> None:
        """Stop a virtual process (per-process stoptime semantics)."""
        self._lib.shim_kill(self._rt, pid, exit_code)

    def exit_code(self, pid: int) -> int | None:
        done = ctypes.c_int(0)
        code = self._lib.shim_proc_exit_code(
            self._rt, pid, ctypes.byref(done)
        )
        return int(code) if done.value else None

"""TCP as a vectorized per-connection state table.

The reference implements a full TCP state machine as ~2.5k lines of
per-socket pointer code: 11 connection states, listen/accept child
multiplexing, seq/ack windows, RTO timers with Karn/Jacobson RTT
estimation, fast retransmit/recovery, and pluggable congestion control
(reference: src/main/host/descriptor/tcp.c:42-53 states, :925-1065 RTO/RTT,
:1777 tcp_processPacket, :91-113 TCPServer/TCPChild; tcp_cong_reno.c:13-60
reno hook tables; interval bookkeeping in C++ tcp_retransmit_tally.cc).

TPU-native redesign:

- **Sequence space is MSS-sized segments**, not bytes: seq/ack/window
  arithmetic is small-integer, the receive reassembly buffer is one u64
  bitmap per connection, and the C++ interval tally collapses into bit
  tricks (trailing-ones of the bitmap = in-order advance). Stream byte
  positions are recovered from the connection's byte counter `snd_buf`:
  segment s spans bytes [s*MSS, min((s+1)*MSS, snd_buf)).
- All connections of all hosts form one [H, S] struct-of-arrays TCB table;
  every transition is an elementwise masked update inside the vmapped
  event handlers — no branches, no per-connection heap objects.
- **Timers are events** carrying (slot, generation, kind); a fired timer
  whose generation mismatches the TCB's is stale and ignored (the
  reference invalidates timers with expire IDs the same way,
  src/main/host/descriptor/timer.c:23-42). The RTO timer is lazily
  rescheduled: if it fires before the current deadline (the deadline was
  pushed forward by an ACK), it re-emits itself at the new deadline, so at
  most one timer event per connection is ever in flight.
- Transmission is ACK-clocked + self-kicked: handlers send up to a static
  burst of segments through the tx-NIC virtual clock and emit a local
  KIND_TCP_TX continuation when the window allows more, paced at the NIC
  free time (the reference's _tcp_flush + wantsSend loop, tcp.c:1121,
  network_interface.c:519-579).

Fidelity features (round 2):
- **Delayed ACK** (reference tcp.c delack; definitions.h:130-131
  CONFIG_TCP_DELACK_MIN = 40ms): an in-order data segment with no ACK
  already owed delays its ACK up to DELACK_DELAY or until a second
  segment / out-of-order arrival / FIN forces one; outbound data
  piggybacks the cumulative ack and clears the debt.
- **Receive-window autotuning** (tcp.c:407-598 buffer autotuning): the
  advertised window starts at RCV_WND segments and doubles toward the
  reassembly capacity whenever a round-trip's delivered segments fill
  half of it (dynamic right-sizing; RTT estimated from the packet
  timestamp's one-way delay). socketrecvbuffer caps it per host.
- **Pluggable congestion control** (tcp_cong.h:17-30 hook vtable): reno
  (tcp_cong_reno.c), cubic (RFC 8312; the reference CLI advertises it,
  options.c), and aimd, selected per run.
- **In-order app delivery** (optional): bytes surface to the app only as
  rcv_nxt advances — the byte-stream contract the real-binary tier needs;
  on-arrival counting remains the default for raw-engine users.

- **SACK** (tcp.c SACK + the C++ retransmit tally's sacked/lost range
  bookkeeping, tcp_retransmit_tally.cc): every ACK advertises the first
  64 bits of the receiver's reassembly bitmap (relative to the ack);
  the sender keeps a `sacked` scoreboard relative to snd_una and skips
  sacked segments when refilling the window after a timeout's
  go-back-N rewind — received data is never retransmitted. (The
  reference caps its SACK list similarly; ranges beyond the 64-segment
  horizon simply retransmit.)

Remaining deliberate deviations:
- A refilled partial segment is tracked for exactly one outstanding
  partial (the common request/response case); overlapping multiple
  partials under-deliver bytes to the app counter only.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

from shadow_tpu.core.engine import BURST_NSEG_SHIFT, Emit
from shadow_tpu.core.timebase import MILLISECOND, SECOND
from shadow_tpu.host.nic import HEADER_TCP, MTU
from shadow_tpu.host.sockets import PROTO_NONE, PROTO_TCP, PROTO_UDP
from shadow_tpu.transport.stack import (
    F_RETX,
    A_LEN,
    F_ACK,
    F_FIN,
    F_RST,
    F_SYN,
    KIND_PKT_ARRIVE,
    N_PKT_ARGS,
    N_STACK_KINDS,
    Pkt,
)

# App bytes per full segment (definitions.h:188 MTU minus TCP/IP/eth headers).
MSS = MTU - HEADER_TCP  # 1434

# Connection states (tcp.c:42-53).
CLOSED = 0
LISTEN = 1
SYN_SENT = 2
SYN_RCVD = 3
ESTABLISHED = 4
FIN_WAIT_1 = 5
FIN_WAIT_2 = 6
CLOSE_WAIT = 7
CLOSING = 8
LAST_ACK = 9
TIME_WAIT = 10

# Timing constants (definitions.h:123-125,198).
RTO_INIT = SECOND
RTO_MIN = SECOND // 5
RTO_MAX = 120 * SECOND
TIME_WAIT_DELAY = 60 * SECOND
DELACK_DELAY = 40 * MILLISECOND  # definitions.h:130 CONFIG_TCP_DELACK_MIN
INIT_CWND = 10.0
# slow start runs until the first loss (tcp_cong_reno.c:124
# ssthresh = INT32_MAX); the f32 value just has to dwarf CWND_MAX
INIT_SSTHRESH = float(1 << 30)
CWND_MAX = 1024.0
RCV_WND = 64  # segments: the initial advertised window
WND_WORDS = 4  # u64 words in the reassembly bitmap (64 segs each)

# Event kinds provided by this module (appended after the stack's).
KIND_TCP_TIMER = N_STACK_KINDS  # 2
KIND_TCP_TX = N_STACK_KINDS + 1  # 3
N_TCP_KINDS = N_STACK_KINDS + 2

# Timer/kick event arg words.
T_SLOT = 0
T_GEN = 1
T_KIND = 2
TK_RTO = 0
TK_TIMEWAIT = 1
TK_DELACK = 2

_I32 = jnp.int32
_I64 = jnp.int64


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class TCB:
    """Per-connection state, [H, S] at rest, scalar rows inside handlers.

    Replaces the reference's per-socket TCP struct (tcp.c:125-190 seq/ack
    block, :175-190 retransmit block, tcp_cong.h cwnd).
    """

    state: jax.Array  # i32
    snd_una: jax.Array  # i32 first unacked segment
    snd_nxt: jax.Array  # i32 next segment to send
    snd_buf: jax.Array  # i64 total bytes written by the app
    fin_pending: jax.Array  # bool app closed; FIN occupies seq n_segs
    rcv_nxt: jax.Array  # i32 next expected segment
    ooo: jax.Array  # u64[W] bitmap: bit i = segment rcv_nxt+i received
    rfin_seq: jax.Array  # i32 peer FIN's seq (-1 none)
    partial_seq: jax.Array  # i32 last partial segment delivered (-1 none)
    partial_len: jax.Array  # i32 bytes delivered for it
    cwnd: jax.Array  # f32 congestion window (segments)
    ssthresh: jax.Array  # f32
    dup_acks: jax.Array  # i32
    recover: jax.Array  # i32 NewReno recovery point (-1 = open)
    srtt: jax.Array  # i64 ns (0 = no sample yet)
    rttvar: jax.Array  # i64 ns
    rto: jax.Array  # i64 ns
    rto_deadline: jax.Array  # i64 current retransmit deadline
    timer_live: jax.Array  # bool a timer event is in flight
    timer_gen: jax.Array  # i32 generation for stale-timer rejection
    peer_wnd: jax.Array  # i32 advertised window (segments)
    n_retx: jax.Array  # i32 retransmitted segments (observability)
    rwnd: jax.Array  # i32 window we advertise (autotuned upward)
    rwnd_cap: jax.Array  # i32 autotune ceiling (socketrecvbuffer / MSS)
    delack_segs: jax.Array  # i32 in-order segments with a delayed ACK owed
    delack_live: jax.Array  # bool a delack timer event is in flight
    pend_echo: jax.Array  # i32 ts to echo in the next (possibly delayed) ACK
    rcv_ep_start: jax.Array  # i64 autotune epoch start (0 = unset)
    rcv_ep_segs: jax.Array  # i32 segments delivered this epoch
    cc_wmax: jax.Array  # f32 cubic W_max (cwnd at last loss)
    cc_epoch: jax.Array  # i64 cubic epoch start (0 = unset)
    conn_gen: jax.Array  # i32 slot incarnation (stale-delack rejection)
    sacked: jax.Array  # u64 SACK scoreboard: bit i = snd_una+i received
    # bounded send buffer (socketsendbuffer; tcp.c:407-598 autotune
    # family): snd_cap caps unacked bytes held in snd_buf (0 =
    # unlimited); app bytes beyond it wait in app_pending and drain as
    # ACKs free space — the jitted analog of a blocking send()
    snd_cap: jax.Array  # i64 bytes (0 = unlimited)
    app_pending: jax.Array  # i64 app bytes waiting for buffer space

    @staticmethod
    def create(n_hosts: int, n_sockets: int, rcv_wnd=None,
               wnd_words: int = WND_WORDS, snd_cap=None) -> "TCB":
        s = (n_hosts, n_sockets)
        zi = jnp.zeros(s, _I32)
        zl = jnp.zeros(s, _I64)
        zb = jnp.zeros(s, bool)
        if snd_cap is None:
            cap_snd = zl
        else:
            cap_snd = jnp.broadcast_to(
                jnp.asarray(snd_cap, _I64)[:, None], s
            )
        cap_max = 64 * wnd_words
        if rcv_wnd is None:
            cap = jnp.full(s, cap_max, _I32)
        else:
            cap = jnp.broadcast_to(
                jnp.clip(jnp.asarray(rcv_wnd, _I32), 1, cap_max)[:, None], s
            )
        return TCB(
            state=zi,
            snd_una=zi,
            snd_nxt=zi,
            snd_buf=zl,
            fin_pending=zb,
            rcv_nxt=zi,
            ooo=jnp.zeros(s + (wnd_words,), jnp.uint64),
            rfin_seq=jnp.full(s, -1, _I32),
            partial_seq=jnp.full(s, -1, _I32),
            partial_len=zi,
            cwnd=jnp.full(s, INIT_CWND, jnp.float32),
            ssthresh=jnp.full(s, INIT_SSTHRESH, jnp.float32),
            dup_acks=zi,
            recover=jnp.full(s, -1, _I32),
            srtt=zl,
            rttvar=zl,
            rto=jnp.full(s, RTO_INIT, _I64),
            rto_deadline=zl,
            timer_live=zb,
            timer_gen=zi,
            peer_wnd=jnp.full(s, RCV_WND, _I32),
            n_retx=zi,
            rwnd=jnp.minimum(jnp.int32(RCV_WND), cap),
            rwnd_cap=cap,
            delack_segs=zi,
            delack_live=zb,
            pend_echo=zi,
            rcv_ep_start=zl,
            rcv_ep_segs=zi,
            cc_wmax=jnp.zeros(s, jnp.float32),
            cc_epoch=zl,
            conn_gen=zi,
            sacked=jnp.zeros(s, jnp.uint64),
            snd_cap=cap_snd,
            app_pending=zl,
        )

    def listen(self, host: int, slot: int) -> "TCB":
        """Setup-time op on the [H, S] table: mark a listening socket
        (pair with SocketTable.bind(host, slot, PROTO_TCP, port))."""
        return dataclasses.replace(
            self, state=self.state.at[host, slot].set(LISTEN)
        )


def _row(tcb, c):
    """Read slot c's scalar row from a per-host [S, ...] TCB slice.

    One-hot select, not `a[c]`: a computed-index gather under vmap
    lowers to a serialized per-row gather on TPU — measured as the
    dominant per-step cost of the chained drain at 1k hosts (~45 TCB
    fields x several _row/_write_row calls per packet event). The
    one-hot form is [S]-lane elementwise VPU work. Index semantics match
    jax's clamp-to-range indexing via the clip."""

    def pick(a):
        s = a.shape[0]
        cc = jnp.clip(c, 0, s - 1)
        oh = jnp.arange(s, dtype=jnp.int32) == cc
        ohx = oh.reshape((s,) + (1,) * (a.ndim - 1))
        zero = jnp.zeros((), a.dtype)  # keeps bool/i64 fields their dtype
        return jnp.sum(jnp.where(ohx, a, zero), axis=0, dtype=a.dtype)

    return jax.tree.map(pick, tcb)


def _write_row(tcb, c, new, mask):
    """Masked write of a scalar row into slot c (one-hot, scatter-free;
    see _row)."""

    def put(a, n):
        s = a.shape[0]
        cc = jnp.clip(c, 0, s - 1)
        oh = (jnp.arange(s, dtype=jnp.int32) == cc) & mask
        ohx = oh.reshape((s,) + (1,) * (a.ndim - 1))
        return jnp.where(ohx, n, a)

    return jax.tree.map(put, tcb, new)


def _sel(a, c):
    """Scalar read a[c] from a per-host [S] array, gather-free (one-hot
    select; computed-index gathers serialize on TPU under vmap — see
    _row). Out-of-range c clamps, matching jax indexing."""
    s = a.shape[0]
    cc = jnp.clip(c, 0, s - 1)
    zero = jnp.zeros((), a.dtype)
    return jnp.sum(
        jnp.where(jnp.arange(s, dtype=_I32) == cc, a, zero), dtype=a.dtype
    )


def _put(a, c, v, mask=True):
    """Masked scalar write a[c] = v on a per-host [S] array (one-hot,
    scatter-free; see _sel)."""
    s = a.shape[0]
    cc = jnp.clip(c, 0, s - 1)
    oh = (jnp.arange(s, dtype=_I32) == cc) & mask
    return jnp.where(oh, v, a)


def _fresh_row_like(old: TCB) -> TCB:
    """Default-valued scalar row preserving timer_gen (so stale timer
    events from a previous connection on this slot never match) and the
    per-host receive-buffer cap."""
    z32 = jnp.int32(0)
    return TCB(
        state=z32,
        snd_una=z32,
        snd_nxt=z32,
        snd_buf=jnp.int64(0),
        fin_pending=jnp.asarray(False),
        rcv_nxt=z32,
        ooo=jnp.zeros_like(old.ooo),
        rfin_seq=jnp.int32(-1),
        partial_seq=jnp.int32(-1),
        partial_len=z32,
        cwnd=jnp.float32(INIT_CWND),
        ssthresh=jnp.float32(INIT_SSTHRESH),
        dup_acks=z32,
        recover=jnp.int32(-1),
        srtt=jnp.int64(0),
        rttvar=jnp.int64(0),
        rto=jnp.int64(RTO_INIT),
        rto_deadline=jnp.int64(0),
        timer_live=jnp.asarray(False),
        timer_gen=old.timer_gen,
        peer_wnd=jnp.int32(RCV_WND),
        n_retx=old.n_retx,
        rwnd=jnp.minimum(jnp.int32(RCV_WND), old.rwnd_cap),
        rwnd_cap=old.rwnd_cap,
        delack_segs=z32,
        delack_live=jnp.asarray(False),
        pend_echo=z32,
        rcv_ep_start=jnp.int64(0),
        rcv_ep_segs=z32,
        cc_wmax=jnp.float32(0.0),
        cc_epoch=jnp.int64(0),
        conn_gen=old.conn_gen + 1,
        sacked=jnp.uint64(0),
        snd_cap=old.snd_cap,
        app_pending=jnp.int64(0),
    )


def _n_segs(snd_buf):
    return ((snd_buf + MSS - 1) // MSS).astype(_I32)


def _send_room(row, unlimited_default) -> jax.Array:
    """Free send-buffer bytes under snd_cap (0 = unlimited -> the
    caller's default), counting only unacked bytes as occupancy."""
    acked = jnp.minimum(row.snd_una.astype(_I64) * MSS, row.snd_buf)
    return jnp.where(
        row.snd_cap > 0,
        jnp.maximum(row.snd_cap - (row.snd_buf - acked), 0),
        unlimited_default,
    )


def _admit_bytes(row, add):
    """Admit `add` app bytes into snd_buf with the partial-segment
    rewind: a transmitted partial tail segment retransmits with its
    grown payload (module docstring)."""
    boundary = (row.snd_buf // MSS).astype(_I32)
    rewind = (
        (add > 0) & ((row.snd_buf % MSS) != 0) & (row.snd_nxt > boundary)
    )
    nxt = jnp.where(rewind, boundary, row.snd_nxt)
    return dataclasses.replace(
        row,
        snd_buf=row.snd_buf + add,
        snd_nxt=nxt,
        snd_una=jnp.minimum(row.snd_una, nxt),
    )


def _fin_ready(row) -> jax.Array:
    """The FIN may only take its sequence slot once every app byte —
    including bytes still waiting behind the send-buffer cap — is in
    snd_buf; otherwise drained bytes would land past the FIN's seq."""
    return row.fin_pending & (row.app_pending == 0)


def _outstanding(row) -> jax.Array:
    """True while the connection still needs timer coverage: unacked
    flight, queued-but-unsent data or FIN, or a handshake in progress.
    (A timer that dies with work pending strands the connection if the
    last in-flight packet is lost.)"""
    lim = _n_segs(row.snd_buf) + _fin_ready(row).astype(_I32)
    return (
        (row.snd_nxt > row.snd_una)
        | ((row.snd_una < lim) & (row.state >= ESTABLISHED))
        | (row.app_pending > 0)
        | (row.state == SYN_SENT)
        | (row.state == SYN_RCVD)
    )


def _seg_len(snd_buf, s):
    return jnp.clip(snd_buf - s.astype(_I64) * MSS, 0, MSS).astype(_I32)


def _trailing_ones(x):
    """Count of consecutive set bits from bit 0 of a u64 (all-ones -> 64).

    This is the whole of the in-order-advance computation that the
    reference's C++ interval tally performs with std::vector range merges
    (tcp_retransmit_tally.cc)."""
    y = (x + jnp.uint64(1)).astype(jnp.uint64)
    return jax.lax.population_count((y & (~y + jnp.uint64(1))) - jnp.uint64(1)).astype(_I32)


def _trailing_ones_vec(ooo):
    """Trailing ones across a [W]-word bitmap (word 0 = lowest bits)."""
    t = jax.vmap(_trailing_ones)(ooo)  # i32[W]
    full = (t == 64).astype(_I32)
    # word i contributes only if all lower words are saturated
    pre = jnp.concatenate([jnp.ones((1,), _I32), jnp.cumprod(full[:-1])])
    return jnp.sum(t * pre).astype(_I32)


def _bit_vec(off, w: int):
    """One-hot [W]-word u64 vector for bit `off` (off in [0, 64*w))."""
    wi = off // 64
    bi = jnp.clip(off - wi * 64, 0, 63).astype(jnp.uint64)
    sel = jnp.arange(w, dtype=_I32) == wi
    return jnp.where(sel, jnp.uint64(1) << bi, jnp.uint64(0))


def _range_vec(lo, hi, w: int):
    """[W]-word u64 mask of bits [lo, hi), clamped to [0, 64w).

    The burst analog of _bit_vec: a fold of k contiguous segments marks
    its whole run in one pass (all elementwise, per-word shift math)."""
    lo = jnp.clip(lo, 0, 64 * w)
    hi = jnp.clip(hi, 0, 64 * w)
    idx = jnp.arange(w, dtype=_I32) * 64
    a = jnp.clip(lo - idx, 0, 64)
    b = jnp.clip(hi - idx, 0, 64)
    n = jnp.maximum(b - a, 0)
    ones = jnp.where(
        n >= 64,
        ~jnp.uint64(0),
        (jnp.uint64(1) << jnp.minimum(n, 63).astype(jnp.uint64))
        - jnp.uint64(1),
    )
    return jnp.where(
        n > 0, ones << jnp.minimum(a, 63).astype(jnp.uint64), jnp.uint64(0)
    )


def _bit_test(ooo, off):
    """Is bit `off` set in the [W]-word bitmap? (off must be >= 0).
    One-hot select, not ooo[wi]: computed-index gathers serialize on
    TPU under vmap (see _row)."""
    w = ooo.shape[0]
    wi = jnp.clip(off // 64, 0, w - 1)
    bi = jnp.clip(off - (off // 64) * 64, 0, 63).astype(jnp.uint64)
    word = jnp.sum(
        jnp.where(jnp.arange(w, dtype=_I32) == wi, ooo, jnp.uint64(0)),
        dtype=jnp.uint64,
    )
    return ((word >> bi) & jnp.uint64(1)) != 0


def _shift_right_vec(ooo, adv):
    """Shift a [W]-word bitmap right by `adv` bits (adv in [0, 64*W]).
    The word realignment is a one-hot [W, 2W+1] select instead of a
    computed-index take (gather-free; see _row)."""
    w = ooo.shape[0]
    ws = adv // 64
    bs = jnp.clip(adv - ws * 64, 0, 63).astype(jnp.uint64)
    padded = jnp.concatenate([ooo, jnp.zeros((w + 1,), jnp.uint64)])
    j = jnp.arange(2 * w + 1, dtype=_I32)[None, :]
    base = jnp.arange(w, dtype=_I32)[:, None] + ws

    def take1(off_mat):
        m = j == jnp.clip(off_mat, 0, 2 * w)
        return jnp.sum(
            jnp.where(m, padded[None, :], jnp.uint64(0)), axis=1,
            dtype=jnp.uint64,
        )

    lo = take1(base)
    hi = take1(base + 1)
    return (lo >> bs) | jnp.where(
        bs > 0, hi << (jnp.uint64(64) - bs), jnp.uint64(0)
    )


# ---------------------------------------------------------------------------
# Congestion-control hook tables (the reference's TCPCongHooks vtable,
# tcp_cong.h:17-30: {duplicate_ack, fast_recovery, new_ack, timeout,
# ssthresh} + cwnd). Each hook is elementwise over scalar TCB rows; the
# algorithm is chosen per run (options.c --tcp-congestion-control), so
# dispatch is plain Python — zero device cost.
#
# Hook contract:
#   on_ack(row, n_acked, now) -> (cwnd', cc_wmax', cc_epoch')
#       congestion-avoidance/slow-start growth on an advancing ACK
#       outside recovery.
#   on_loss(row, flight, now) -> (cwnd', ssthresh', cc_wmax', cc_epoch')
#       fast-retransmit entry (3 dup acks).
#   on_timeout(row, flight, now) -> (ssthresh', cc_wmax', cc_epoch')
#       RTO collapse (cwnd is always forced to 1 by the caller).


class RenoCC:
    """NewReno (tcp_cong_reno.c:13-60 slow-start/CA/fast-recovery)."""

    name = "reno"

    @staticmethod
    def on_ack(row, n_acked, now):
        n = n_acked.astype(jnp.float32)
        cwnd = jnp.where(
            row.cwnd < row.ssthresh,
            row.cwnd + n,
            row.cwnd + n / jnp.maximum(row.cwnd, 1.0),
        )
        return cwnd, row.cc_wmax, row.cc_epoch

    @staticmethod
    def on_loss(row, flight, now):
        ss = jnp.maximum(flight / 2, 2.0)
        return ss + 3, ss, row.cc_wmax, row.cc_epoch

    @staticmethod
    def on_timeout(row, flight, now):
        return jnp.maximum(flight / 2, 2.0), row.cc_wmax, row.cc_epoch


class AimdCC:
    """Classic AIMD: reno growth, multiplicative halving on loss with no
    fast-recovery inflation (the reference CLI's 'aimd', options.c)."""

    name = "aimd"

    on_ack = RenoCC.on_ack

    @staticmethod
    def on_loss(row, flight, now):
        ss = jnp.maximum(flight / 2, 2.0)
        return ss, ss, row.cc_wmax, row.cc_epoch

    on_timeout = RenoCC.on_timeout


class CubicCC:
    """CUBIC (RFC 8312, the Linux bictcp shape): concave/convex window
    growth W(t) = C*(t-K)^3 + origin with K = cbrt((origin - cwnd0)/C),
    where cwnd0 is the cwnd when the epoch starts — if cwnd0 >= W_max
    (no-loss epoch), K = 0 and origin = cwnd0, i.e. immediate convex
    growth (Linux bictcp_update's last_max <= cwnd case). A TCP-friendly
    floor tracks what reno would have reached since the epoch.

    cc_wmax doubles as the epoch origin; cc_epoch == 0 means "epoch not
    started" and the next CA ack starts it (storing K via the origin)."""

    name = "cubic"
    C = 0.4
    BETA = 0.7

    @classmethod
    def on_ack(cls, row, n_acked, now):
        n = n_acked.astype(jnp.float32)
        in_ss = row.cwnd < row.ssthresh
        fresh_epoch = row.cc_epoch == 0
        epoch = jnp.where(fresh_epoch, now, row.cc_epoch)
        # origin: W_max if we're below it (concave ascent back to it),
        # else the current cwnd (convex probe; K = 0)
        origin = jnp.where(
            fresh_epoch, jnp.maximum(row.cc_wmax, row.cwnd), row.cc_wmax
        )
        cwnd0 = jnp.minimum(row.cwnd, origin)  # epoch-start estimate
        k = jnp.cbrt(jnp.maximum(origin - cwnd0, 0.0) / cls.C)
        srtt_s = jnp.maximum(row.srtt, MILLISECOND).astype(jnp.float32) * 1e-9
        t = (now - epoch).astype(jnp.float32) * 1e-9 + srtt_s
        target = cls.C * (t - k) ** 3 + origin
        # reno-equivalent window since the epoch started (RFC 8312 W_est
        # rebased at the epoch-start cwnd, not beta*W_max, so a no-loss
        # epoch is never slower than reno)
        friendly = cwnd0 + (
            3.0 * (1.0 - cls.BETA) / (1.0 + cls.BETA)
        ) * (t / srtt_s)
        target = jnp.maximum(target, friendly)
        # per-ack growth toward the target, capped at slow-start pace
        inc = jnp.minimum(
            jnp.maximum(target - row.cwnd, 0.0)
            / jnp.maximum(row.cwnd, 1.0) * n,
            n,
        )
        cwnd = jnp.where(in_ss, row.cwnd + n, row.cwnd + inc)
        return (
            cwnd,
            jnp.where(in_ss, row.cc_wmax, origin),
            jnp.where(in_ss, row.cc_epoch, epoch),
        )

    @classmethod
    def on_loss(cls, row, flight, now):
        # fast convergence: if below the previous W_max, remember less
        wmax = jnp.where(
            row.cwnd < row.cc_wmax,
            row.cwnd * (2.0 - cls.BETA) / 2.0,
            row.cwnd,
        )
        ss = jnp.maximum(row.cwnd * cls.BETA, 2.0)
        return ss + 3, ss, wmax, jnp.zeros_like(row.cc_epoch)

    @classmethod
    def on_timeout(cls, row, flight, now):
        ss = jnp.maximum(row.cwnd * cls.BETA, 2.0)
        return ss, row.cwnd, jnp.zeros_like(row.cc_epoch)


CC_ALGOS = {c.name: c for c in (RenoCC, AimdCC, CubicCC)}


def _ts_us(now):
    """Nonzero i32 microsecond timestamp for the header ts/echo word
    (tcp.c header timestamps for RTT)."""
    return jnp.maximum((now // 1000) & 0x7FFFFFFF, 1).astype(_I32)


def _pkt_args(sport, dport, seq=0, ack=0, length=0, wnd=RCV_WND, aux=0,
              flags=0, sack=0):
    return Pkt.encode_args(
        PROTO_TCP, sport, dport, seq=seq, ack=ack, length=length, wnd=wnd,
        aux=aux, flags=flags, sack=sack,
    )


def _ctl_args(slot, gen_or_zero, tk=0):
    f = lambda x: jnp.asarray(x, _I32)
    z = jnp.int32(0)
    return jnp.stack(
        [f(slot), f(gen_or_zero), f(tk)] + [z] * (N_PKT_ARGS - 3)
    )


def _emit_from_rows(rows):
    stk = lambda key, dt: jnp.stack([jnp.asarray(r[key], dt) for r in rows])
    return Emit(
        dst=stk("dst", _I32),
        dt=stk("dt", _I64),
        kind=stk("kind", _I32),
        args=jnp.stack([r["args"] for r in rows]),
        mask=stk("mask", bool),
        local=stk("local", bool),
    )


def emit_concat(*ems: Emit) -> Emit:
    return jax.tree.map(lambda *xs: jnp.concatenate(xs), *ems)


class TCP:
    """The TCP protocol hook installed into `transport.stack.Stack`.

    tx_burst: segments sent per KIND_TCP_TX kick (static unroll).
    inline_budget: segments sent inline from the ACK-processing path.
    auto_close: a connection reaching CLOSE_WAIT closes itself (the typical
      sim-server behavior; apps may instead close explicitly).
    cc: congestion-control algorithm name ('reno'|'cubic'|'aimd'; the
      reference's --tcp-congestion-control, options.c) or a hook class.
    delack: delayed-ACK (reference tcp.c delack) — on by default, as in
      the reference.
    in_order: app deliveries surface bytes only as rcv_nxt advances
      (strict byte-stream order) instead of on arrival.
    rst_on_unmatched: a TCP segment that demuxes to no socket (or lands
      non-SYN on a bare listener) draws an RST, the kernel's answer to a
      segment for a connection it doesn't know. Off by default — the
      bundled drivers close via FIN and never strand segments — but
      sim.py enables it when the fault schedule can crash hosts, so
      survivors' retransmits toward a crash-restarted peer (whose
      connection state the reboot wiped) tear down through the real RST
      path instead of blackholing until RTO exhaustion.

    Engine `max_emit` must be >= `min_max_emit(app_rows)` where app_rows is
    the number of Emit rows the installed on_recv callback returns.
    """

    def __init__(self, tx_burst: int = 4, inline_budget: int = 2,
                 auto_close: bool = True, cc="reno", delack: bool = True,
                 in_order: bool = False, autotune: bool = True,
                 child_slot_limit: int | None = None,
                 rst_on_unmatched: bool = False):
        self.tx_burst = tx_burst
        self.inline_budget = inline_budget
        self.auto_close = auto_close
        self.cc = CC_ALGOS[cc] if isinstance(cc, str) else cc
        self.delack = delack
        self.in_order = in_order
        self.autotune = autotune
        self.rst_on_unmatched = rst_on_unmatched
        # passive-open children only allocate slots < limit, reserving
        # the top of the table for driver/app-owned sockets (the process
        # tier's split — without it a recycled driver slot could be
        # claimed by an inbound SYN while the driver still holds it)
        self.child_slot_limit = child_slot_limit

    def min_max_emit(self, app_rows: int = 1) -> int:
        """Smallest EngineConfig.max_emit that fits this TCP's handlers.

        process_segment emits [ctl, retx] + inline_budget data rows +
        [kick, rto-timer, delack-timer] plus the on_recv callback's rows
        (>= 1, since on_recv must return an Emit); _on_timer emits 4."""
        return max(self.tx_burst + 2, self.inline_budget + 5 + app_rows, 4)

    # ------------------------------------------------------------ helpers
    def _seg_row(self, nic_tx, row, now, dst_host, sport, dport, s, is_fin,
                 ok, unlimited, is_retx=False):
        """One data/FIN segment through the tx NIC; returns
        (nic_tx', emit_row). `is_retx` stamps F_RETX into the header so
        receivers/captures can classify the segment (the PDS_RETRANSMITTED
        stage of the reference's packet lifecycle, packet.h:20-40)."""
        length = jnp.where(is_fin, 0, _seg_len(row.snd_buf, s))
        wire = length + HEADER_TCP
        nic2, _start, fin_t = nic_tx.admit(now, wire, unlimited)
        nic_tx = jax.tree.map(lambda n, o: jnp.where(ok, n, o), nic2, nic_tx)
        flags = (F_ACK | jnp.where(is_fin, F_FIN, 0)
                 | jnp.where(jnp.asarray(is_retx), F_RETX, 0))
        args = _pkt_args(
            sport, dport, seq=s, ack=row.rcv_nxt, length=length,
            wnd=row.rwnd, aux=_ts_us(now), flags=flags, sack=row.ooo[0],
        )
        em = dict(
            dst=dst_host, dt=jnp.where(ok, fin_t - now, 0),
            kind=KIND_PKT_ARRIVE, args=args, mask=ok, local=False,
        )
        return nic_tx, em

    def _tx_segments(self, nic_tx, row, now, dst_host, sport, dport, budget,
                     enabled, unlimited):
        """Send up to `budget` new segments from snd_nxt (window-limited).

        Returns (nic_tx', row', rows, more). State moves to FIN_WAIT_1 /
        LAST_ACK when the FIN goes out (tcp.c _tcp_flush semantics)."""
        n_segs = _n_segs(row.snd_buf)
        fin_rdy = _fin_ready(row)
        lim = n_segs + fin_rdy.astype(_I32)
        # closing states stay sendable so a post-timeout go-back-N window
        # (snd_nxt rewound below old flight) can refill with a full cwnd
        # instead of one segment per RTO
        can = enabled & (
            (row.state == ESTABLISHED) | (row.state == CLOSE_WAIT)
            | (row.state == FIN_WAIT_1) | (row.state == CLOSING)
            | (row.state == LAST_ACK)
        )
        win = jnp.minimum(row.cwnd.astype(_I32), row.peer_wnd)
        nxt = row.snd_nxt
        sent_fin = jnp.asarray(False)
        rows = []
        for _ in range(budget):
            s = nxt
            is_data = s < n_segs
            is_fin = fin_rdy & ~is_data & (s == n_segs)
            inwin = (s < row.snd_una + win) & (s < lim)
            # SACK scoreboard: a segment the receiver already holds is
            # skipped (nxt advances without a wire packet) — the whole
            # point of the sacked/lost range bookkeeping the reference
            # keeps in tcp_retransmit_tally.cc
            s_rel = s - row.snd_una
            is_sacked = is_data & (s_rel >= 0) & (s_rel < 64) & (
                ((row.sacked >> jnp.clip(s_rel, 0, 63).astype(jnp.uint64))
                 & jnp.uint64(1)) != 0
            )
            ok = can & (is_data | is_fin) & inwin & ~is_sacked
            nic_tx, em = self._seg_row(
                nic_tx, row, now, dst_host, sport, dport, s, is_fin, ok,
                unlimited,
            )
            rows.append(em)
            nxt = nxt + (ok | (can & is_sacked & inwin)).astype(_I32)
            sent_fin = sent_fin | (ok & is_fin)
        state = jnp.where(
            sent_fin & (row.state == ESTABLISHED), FIN_WAIT_1,
            jnp.where(sent_fin & (row.state == CLOSE_WAIT), LAST_ACK, row.state),
        )
        row = dataclasses.replace(row, snd_nxt=nxt, state=state)
        more = can & (nxt < lim) & (nxt < row.snd_una + win)
        return nic_tx, row, rows, more

    def _kick_row(self, slot, now, free_at, mask):
        return dict(
            dst=0, dt=jnp.maximum(free_at - now, 1), kind=KIND_TCP_TX,
            args=_ctl_args(slot, 0), mask=mask, local=True,
        )

    def _arm_row(self, row, slot, now, enter_tw):
        """RTO arm (when outstanding data and no live timer) or TIME_WAIT
        timer (on entering TIME_WAIT); at most one fires per event."""
        arm = _outstanding(row) & ~row.timer_live & ~enter_tw
        fire = arm | enter_tw
        gen = row.timer_gen + fire.astype(_I32)
        tk = jnp.where(enter_tw, TK_TIMEWAIT, TK_RTO)
        dt = jnp.where(enter_tw, TIME_WAIT_DELAY, row.rto)
        row = dataclasses.replace(
            row,
            timer_live=row.timer_live | fire,
            timer_gen=gen,
            rto_deadline=jnp.where(arm, now + row.rto, row.rto_deadline),
        )
        em = dict(
            dst=0, dt=dt, kind=KIND_TCP_TIMER,
            args=_ctl_args(slot, gen, tk), mask=fire, local=True,
        )
        return row, em

    # --------------------------------------------------------- public API
    def connect(self, stack, hs, slot, now, mask=True):
        """Active open (tcp_connectToPeer). The socket at `slot` must be
        bound with proto=TCP, a local port, and the peer set. Returns
        (hs', Emit[2]) = SYN + RTO timer."""
        net = hs.net
        c = jnp.maximum(jnp.asarray(slot, _I32), 0)
        mask = jnp.asarray(mask, bool) & (jnp.asarray(slot, _I32) >= 0)
        old = _row(net.tcb, c)
        row = _fresh_row_like(old)
        row = dataclasses.replace(
            row,
            state=jnp.int32(SYN_SENT),
            timer_live=jnp.asarray(True),
            timer_gen=old.timer_gen + 1,
            rto_deadline=now + RTO_INIT,
        )
        unlimited = now < stack.bootstrap_end
        nic2, _s, fin_t = net.nic_tx.admit(now, HEADER_TCP, unlimited)
        nic_tx = jax.tree.map(
            lambda n, o: jnp.where(mask, n, o), nic2, net.nic_tx
        )
        syn = dict(
            dst=_sel(net.sockets.peer_host, c),
            dt=jnp.where(mask, fin_t - now, 0),
            kind=KIND_PKT_ARRIVE,
            args=_pkt_args(
                _sel(net.sockets.local_port, c), _sel(net.sockets.peer_port, c),
                wnd=row.rwnd, aux=_ts_us(now), flags=F_SYN,
            ),
            mask=mask, local=False,
        )
        timer = dict(
            dst=0, dt=jnp.int64(RTO_INIT), kind=KIND_TCP_TIMER,
            args=_ctl_args(c, row.timer_gen, TK_RTO), mask=mask, local=True,
        )
        tcb = _write_row(net.tcb, c, row, mask)
        hs = dataclasses.replace(
            hs, net=dataclasses.replace(net, tcb=tcb, nic_tx=nic_tx)
        )
        return hs, _emit_from_rows([syn, timer])

    def send(self, hs, slot, nbytes, now, mask=True):
        """Queue bytes on the connection (host_sendUserData ->
        tcp_sendUserData). Returns (hs', Emit[1]) = a tx kick.

        If the previously-final segment was partial and already
        transmitted, snd_nxt/snd_una rewind to retransmit it with the
        grown payload (see module docstring)."""
        net = hs.net
        c = jnp.maximum(jnp.asarray(slot, _I32), 0)
        mask = jnp.asarray(mask, bool) & (jnp.asarray(slot, _I32) >= 0)
        row = _row(net.tcb, c)
        # bounded send buffer: only `room` bytes enter snd_buf now; the
        # rest wait in app_pending and drain as ACKs free space (the
        # jitted analog of the reference's blocking send against its
        # autotuned buffer, tcp.c:407-598)
        nb = jnp.asarray(nbytes, _I64)
        accept = jnp.minimum(nb, _send_room(row, nb))
        row = _admit_bytes(row, accept)
        row = dataclasses.replace(
            row, app_pending=row.app_pending + (nb - accept)
        )
        tcb = _write_row(net.tcb, c, row, mask)
        sockets = net.sockets.add_tx(jnp.where(mask, c, -1), nbytes)
        hs = dataclasses.replace(
            hs, net=dataclasses.replace(net, tcb=tcb, sockets=sockets)
        )
        return hs, _emit_from_rows([self._kick_row(c, now, now, mask)])

    def close(self, hs, slot, now, mask=True):
        """Half-close after pending data (tcp.c CLOSED->FIN path): the FIN
        is sent once everything queued has gone out. Closing a LISTEN
        socket has no handshake to run down — the slot resets (and its
        conn_gen bumps so drivers observe the turnover) immediately."""
        net = hs.net
        c = jnp.maximum(jnp.asarray(slot, _I32), 0)
        mask = jnp.asarray(mask, bool) & (jnp.asarray(slot, _I32) >= 0)
        row = _row(net.tcb, c)
        lst = mask & (row.state == LISTEN)
        tcb = _write_row(net.tcb, c, _fresh_row_like(row), lst)
        fp = _put(tcb.fin_pending, c, True, mask & ~lst)
        tcb = dataclasses.replace(tcb, fin_pending=fp)
        # the listener's demux row clears too, so a later bind of the
        # same port cannot alias two socket rows
        sk = net.sockets
        w = lambda a, v: _put(a, c, v, lst)
        sk = dataclasses.replace(
            sk, proto=w(sk.proto, 0), local_port=w(sk.local_port, 0)
        )
        hs = dataclasses.replace(
            hs, net=dataclasses.replace(net, tcb=tcb, sockets=sk)
        )
        return hs, _emit_from_rows(
            [self._kick_row(c, now, now, mask & ~lst)]
        )

    # ------------------------------------------------- segment processing
    def process_segment(self, stack, hs, slot, pkt: Pkt, ev, key, on_recv):
        """The vectorized tcp_processPacket (tcp.c:1777): handshake,
        ACK/reno/RTT, data reassembly, FIN/close transitions, inline tx,
        ACK generation. Also routes UDP packets to `on_recv` (the stack
        funnels every demuxed packet here when TCP is installed)."""
        if hs.net.tcb is None:
            raise ValueError(
                "Stack(tcp=...) requires HostNet.create(..., with_tcp=True) "
                "so the host state carries a TCB table"
            )
        net = hs.net
        now = ev.time
        unlimited = now < stack.bootstrap_end
        slot = jnp.asarray(slot, _I32)
        have = slot >= 0
        c = jnp.maximum(slot, 0)
        is_udp = (pkt.proto == PROTO_UDP) & have
        is_tcp = (pkt.proto == PROTO_TCP) & have
        row = _row(net.tcb, c)
        sockets = net.sockets

        f = pkt.flags
        f_syn = (f & F_SYN) != 0
        f_ackf = (f & F_ACK) != 0
        f_fin = (f & F_FIN) != 0
        f_rst = (f & F_RST) != 0
        syn_only = is_tcp & f_syn & ~f_ackf
        synack = is_tcp & f_syn & f_ackf
        plain_ack = is_tcp & f_ackf & ~f_syn

        # -- passive open: SYN at LISTEN -> child slot (TCPServer/TCPChild,
        # tcp.c:91-113); SYN at SYN_RCVD = dup -> re-SYN-ACK
        at_listen = syn_only & (row.state == LISTEN)
        dup_syn = syn_only & (row.state == SYN_RCVD)
        child_free = sockets.proto == PROTO_NONE
        if self.child_slot_limit is not None:
            child_free = child_free & (
                jnp.arange(child_free.shape[0]) < self.child_slot_limit
            )
        free_slot = jnp.argmax(child_free).astype(_I32)
        do_open = at_listen & child_free[free_slot]
        child = jnp.where(do_open, free_slot, c)
        child_old = _row(net.tcb, child)
        child_row = _fresh_row_like(child_old)
        child_row = dataclasses.replace(
            child_row,
            state=jnp.int32(SYN_RCVD),
            peer_wnd=jnp.maximum(pkt.wnd, 1),
            timer_live=jnp.asarray(True),
            timer_gen=child_old.timer_gen + 1,
            rto_deadline=now + RTO_INIT,
        )
        wr = lambda a, v, m: _put(a, child, v, m)
        sockets = dataclasses.replace(
            sockets,
            proto=wr(sockets.proto, PROTO_TCP, do_open),
            local_port=wr(sockets.local_port, pkt.dst_port, do_open),
            peer_host=wr(sockets.peer_host, pkt.src_host, do_open),
            peer_port=wr(sockets.peer_port, pkt.src_port, do_open),
        )

        # -- handshake completions & RST
        est_active = synack & (row.state == SYN_SENT)
        est_passive = plain_ack & (row.state == SYN_RCVD)
        got_rst = (
            is_tcp & f_rst & (row.state != LISTEN) & (row.state != CLOSED)
        )
        state1 = jnp.where(
            est_active | est_passive, ESTABLISHED, row.state
        ).astype(_I32)
        row = dataclasses.replace(
            row,
            state=state1,
            peer_wnd=jnp.where(
                est_active | plain_ack, jnp.maximum(pkt.wnd, 1), row.peer_wnd
            ),
        )
        # handshake RTT seeds srtt on the client (SYN ts echoed in SYN-ACK)
        hs_rtt = jnp.maximum(
            ((_ts_us(now) - pkt.aux) & 0x7FFFFFFF).astype(_I64) * 1000, 1
        )
        sample_hs = est_active & (pkt.aux != 0)
        row = dataclasses.replace(
            row,
            srtt=jnp.where(sample_hs, hs_rtt, row.srtt),
            rttvar=jnp.where(sample_hs, hs_rtt // 2, row.rttvar),
            rto=jnp.where(
                sample_hs,
                jnp.clip(hs_rtt + 4 * (hs_rtt // 2), RTO_MIN, RTO_MAX),
                row.rto,
            ),
        )

        # -- ACK processing (reno + NewReno recovery + RTT, tcp.c:925-1065,
        # tcp_cong_reno.c)
        ack_ok = plain_ack & (row.state >= ESTABLISHED) & (row.state <= LAST_ACK)
        # the valid ack range is bounded by *ever-sent* data, not snd_nxt:
        # after a timeout's go-back-N rewind, acks for segments beyond the
        # rewound snd_nxt are still legitimate and must heal the window
        ack = jnp.clip(
            pkt.ack, 0, _n_segs(row.snd_buf) + _fin_ready(row).astype(_I32)
        )
        advanced = ack_ok & (ack > row.snd_una)
        n_acked = jnp.where(advanced, ack - row.snd_una, 0)
        sample = advanced & (pkt.aux != 0)
        rtt = jnp.maximum(
            ((_ts_us(now) - pkt.aux) & 0x7FFFFFFF).astype(_I64) * 1000, 1
        )
        first = row.srtt == 0
        srtt_prev = row.srtt
        srtt = jnp.where(
            sample, jnp.where(first, rtt, (7 * row.srtt + rtt) // 8), row.srtt
        )
        rttvar = jnp.where(
            sample,
            jnp.where(
                first, rtt // 2,
                (3 * row.rttvar + jnp.abs(srtt_prev - rtt)) // 4,
            ),
            row.rttvar,
        )
        rto = jnp.where(
            sample,
            jnp.clip(srtt + jnp.maximum(4 * rttvar, MILLISECOND), RTO_MIN, RTO_MAX),
            row.rto,
        )

        in_rec = row.recover >= 0
        pure = plain_ack & (pkt.length == 0) & ~f_fin
        is_dup = (
            ack_ok & pure & ~advanced
            & (row.snd_nxt > row.snd_una) & (ack == row.snd_una)
        )
        # a pure dup ACK answering a burst-folded delivery stands for
        # pkt.nseg per-segment dup ACKs (the reference receiver emits
        # one per arriving segment) — count them all, and trigger fast
        # retransmit on CROSSING the 3-dup threshold, since the counter
        # can now jump past it in one step
        dup_acks = jnp.where(
            advanced, 0,
            row.dup_acks + jnp.where(is_dup, pkt.nseg, 0),
        )
        fr = is_dup & (dup_acks >= 3) & (row.dup_acks < 3) & ~in_rec
        flight = (row.snd_nxt - row.snd_una).astype(jnp.float32)
        exit_rec = advanced & in_rec & (ack >= row.recover)
        partial_ack = advanced & in_rec & ~exit_rec
        cw_ack, wmax_ack, epoch_ack = self.cc.on_ack(row, n_acked, now)
        # congestion-window validation: a window/app-limited flow must not
        # inflate cwnd past what it actually uses (else a later loss cuts
        # from a fictitious height) — growth is capped at 2x the flight
        cw_ack = jnp.minimum(
            cw_ack,
            jnp.maximum(
                jnp.maximum(flight * 2, row.cwnd), jnp.float32(INIT_CWND)
            ),
        )
        cw_loss, ss_loss, wmax_loss, epoch_loss = self.cc.on_loss(
            row, flight, now
        )
        # a carrier crossing the 3-dup threshold spends its remaining
        # dups on recovery inflation, exactly as the unfolded per-dup
        # stream would (dups #4.. each inflate cwnd by one segment)
        fr_extra = jnp.maximum(dup_acks - 3, 0).astype(jnp.float32)
        cwnd = jnp.where(
            fr, cw_loss + fr_extra,
            jnp.where(
                is_dup & in_rec, row.cwnd + pkt.nseg,
                jnp.where(
                    exit_rec, row.ssthresh,
                    jnp.where(advanced & ~in_rec, cw_ack, row.cwnd),
                ),
            ),
        )
        cwnd = jnp.minimum(cwnd, CWND_MAX)
        grow_ack = advanced & ~in_rec
        cc_wmax = jnp.where(
            fr, wmax_loss, jnp.where(grow_ack, wmax_ack, row.cc_wmax)
        )
        cc_epoch = jnp.where(
            fr, epoch_loss, jnp.where(grow_ack, epoch_ack, row.cc_epoch)
        )
        retx = fr | partial_ack
        snd_una = jnp.where(advanced, ack, row.snd_una)
        # SACK scoreboard maintenance: realign to the new snd_una, then
        # absorb the ACK's advertised bitmap (relative to its ack field,
        # which equals the new snd_una whenever it is current)
        shift = jnp.clip(snd_una - row.snd_una, 0, 63).astype(jnp.uint64)
        sacked = jnp.where(
            (snd_una - row.snd_una) >= 64, jnp.uint64(0),
            row.sacked >> shift,
        )
        sacked = jnp.where(
            ack_ok & (ack == snd_una), sacked | pkt.sack, sacked
        )
        row = dataclasses.replace(row, sacked=sacked)
        n_segs = _n_segs(row.snd_buf)
        fin_acked = _fin_ready(row) & (snd_una >= n_segs + 1)
        state2 = jnp.where(
            (row.state == FIN_WAIT_1) & fin_acked, FIN_WAIT_2,
            jnp.where(
                (row.state == CLOSING) & fin_acked, TIME_WAIT,
                jnp.where(
                    (row.state == LAST_ACK) & fin_acked, CLOSED, row.state
                ),
            ),
        ).astype(_I32)
        enter_tw_ack = (row.state == CLOSING) & fin_acked
        freed_ack = (row.state == LAST_ACK) & fin_acked
        row = dataclasses.replace(
            row,
            state=state2,
            snd_una=snd_una,
            snd_nxt=jnp.maximum(row.snd_nxt, snd_una),
            cwnd=cwnd,
            ssthresh=jnp.where(fr, ss_loss, row.ssthresh),
            cc_wmax=cc_wmax,
            cc_epoch=cc_epoch,
            dup_acks=dup_acks,
            recover=jnp.where(
                fr, row.snd_nxt, jnp.where(exit_rec, -1, row.recover)
            ),
            srtt=srtt, rttvar=rttvar, rto=rto,
            rto_deadline=jnp.where(advanced, now + rto, row.rto_deadline),
            n_retx=row.n_retx + retx.astype(_I32),
        )
        # send-buffer drain: ACK progress freed space — admit waiting
        # app bytes (the unblocking edge of the reference's blocking
        # send), with the same partial-segment rewind tcp.send applies
        take = jnp.where(
            advanced & (row.app_pending > 0),
            jnp.minimum(row.app_pending, _send_room(row, row.app_pending)),
            jnp.int64(0),
        )
        row = _admit_bytes(row, take)
        row = dataclasses.replace(
            row, app_pending=row.app_pending - take
        )

        # -- data / FIN receive: bitmap reassembly + cumulative advance
        has_seg = (
            is_tcp & ~f_syn & ((pkt.length > 0) | f_fin)
            & (row.state >= ESTABLISHED)
        )
        wnd_words = row.ooo.shape[0]
        wnd_cap = 64 * wnd_words
        # burst delivery: this packet may stand for pkt.nseg contiguous
        # segments [seq, seq+nseg) totalling pkt.length bytes (the
        # engine's stage fold; nseg == 1 for untouched packets). The
        # whole run marks as a range mask; freshness is per bit, so a
        # burst overlapping retransmitted/duplicate segments delivers
        # exactly its new bits.
        off = pkt.seq - row.rcv_nxt
        end = off + pkt.nseg
        rng = _range_vec(off, end, wnd_words)
        new_bits = rng & ~row.ooo
        any_new = jnp.any(new_bits != 0)
        in_win = (end > 0) & (off < wnd_cap)
        fresh = has_seg & in_win & any_new
        # a burst's last segment is the only one the fold allows to be
        # partial; its sequence slot carries the sub-MSS tail. The
        # burst's FIRST segment may be a refill of the tracked partial
        # (a stream boundary: the sender refilled the tail segment with
        # the next stream's bytes and the fold chained full segments
        # behind it) — the refill delta must not vanish inside the run.
        last_seq = pkt.seq + pkt.nseg - 1
        last_len = pkt.length - (pkt.nseg - 1) * MSS
        # refill: the tracked partial slot may be ANY member of the run
        # (head: the classic single-segment refill; middle: a go-back-N
        # retransmit burst re-sending the refilled slot at full MSS;
        # tail: a refilled-but-still-partial slot). Its delta counts iff
        # that slot's bit is already held — a fresh bit delivers through
        # the normal per-bit path instead.
        p_seq = row.partial_seq
        p_in_run = (
            has_seg & (pkt.length > 0) & (p_seq >= 0)
            & (p_seq >= pkt.seq) & (p_seq <= last_seq)
        )
        p_off = p_seq - row.rcv_nxt
        p_already = (p_off < 0) | (
            (p_off < wnd_cap) & _bit_test(row.ooo, jnp.maximum(p_off, 0))
        )
        p_member_len = jnp.where(p_seq == last_seq, last_len, MSS)
        refill = p_in_run & p_already & (p_member_len > row.partial_len)
        refill_delta = jnp.where(refill, p_member_len - row.partial_len, 0)
        ooo1 = jnp.where(fresh, row.ooo | new_bits, row.ooo)
        adv = jnp.where(fresh, _trailing_ones_vec(ooo1), 0)
        rcv_nxt = row.rcv_nxt + adv
        ooo2 = _shift_right_vec(ooo1, adv)
        is_partial = (
            has_seg & (pkt.length > 0) & (last_len < MSS) & (fresh | refill)
        )
        if self.in_order:
            # bytes surface only as rcv_nxt advances: adv full segments,
            # corrected for partial segments inside the advanced range —
            # the arriving one and/or the tracked outstanding partial —
            # and for the FIN's sequence slot, which carries no data
            new_bytes = adv * MSS
            fin_seq = jnp.where(
                has_seg & f_fin & (row.rfin_seq < 0), pkt.seq, row.rfin_seq
            )
            new_bytes -= jnp.where(
                (fin_seq >= 0) & (fin_seq >= row.rcv_nxt)
                & (fin_seq < rcv_nxt),
                MSS, 0,
            )
            new_bytes -= jnp.where(
                fresh & is_partial & (last_seq < rcv_nxt),
                MSS - last_len, 0,
            )
            prev_partial_adv = (
                (row.partial_seq >= row.rcv_nxt)
                & (row.partial_seq < rcv_nxt) & (row.partial_seq != last_seq)
            )
            new_bytes -= jnp.where(
                prev_partial_adv, MSS - row.partial_len, 0
            )
            # a refill for an already-advanced partial delivers its delta
            # now; for a not-yet-advanced one the delta surfaces with the
            # advance (partial_len below is updated either way)
            new_bytes += jnp.where(
                refill & (row.partial_seq < row.rcv_nxt),
                refill_delta, 0,
            )
            new_bytes = new_bytes.astype(_I32)
        else:
            # per-bit freshness: a burst overlapping already-held
            # segments delivers only its new bits. The partial tail
            # counts its own length; every other fresh bit is full-MSS.
            n_fresh = jnp.sum(
                jax.lax.population_count(new_bits).astype(_I32)
            )
            last_bit_fresh = _bit_test(
                new_bits, jnp.clip(last_seq - row.rcv_nxt, 0, wnd_cap - 1)
            ) & (last_seq >= row.rcv_nxt)
            burst_bytes = n_fresh * MSS - jnp.where(
                (last_len < MSS) & last_bit_fresh, MSS - last_len, 0
            )
            new_bytes = (
                jnp.where(fresh, burst_bytes, 0) + refill_delta
            ).astype(_I32)
        clear_partial = p_in_run & (p_member_len >= MSS)
        rfin = jnp.where(has_seg & f_fin, pkt.seq, row.rfin_seq)
        consumed_before = (row.rfin_seq >= 0) & (row.rcv_nxt > row.rfin_seq)
        consumed_after = (rfin >= 0) & (rcv_nxt > rfin)
        fin_new = consumed_after & ~consumed_before
        state3 = jnp.where(
            fin_new & (row.state == ESTABLISHED), CLOSE_WAIT,
            jnp.where(
                fin_new & (row.state == FIN_WAIT_1), CLOSING,
                jnp.where(
                    fin_new & (row.state == FIN_WAIT_2), TIME_WAIT, row.state
                ),
            ),
        ).astype(_I32)
        enter_tw = enter_tw_ack | (fin_new & (row.state == FIN_WAIT_2))
        # -- receive-window autotuning (tcp.c:407-598): grow the advertised
        # window toward the bitmap capacity when a round-trip's deliveries
        # fill half of it. RTT is estimated from the packet timestamp's
        # one-way delay (sim clocks are globally synchronous).
        if self.autotune:
            owd = jnp.maximum(
                ((_ts_us(now) - pkt.aux) & 0x7FFFFFFF).astype(_I64) * 1000,
                MILLISECOND,
            )
            ep_start = jnp.where(
                row.rcv_ep_start > 0, row.rcv_ep_start, now
            )
            ep_segs = row.rcv_ep_segs + adv
            ep_done = has_seg & (now - ep_start >= 2 * owd)
            rwnd = jnp.where(
                ep_done,
                jnp.clip(2 * ep_segs, row.rwnd, row.rwnd_cap),
                row.rwnd,
            )
            row = dataclasses.replace(
                row,
                rwnd=rwnd,
                rcv_ep_segs=jnp.where(
                    has_seg, jnp.where(ep_done, 0, ep_segs), row.rcv_ep_segs
                ),
                rcv_ep_start=jnp.where(
                    has_seg, jnp.where(ep_done, now, ep_start),
                    row.rcv_ep_start,
                ),
            )
        row = dataclasses.replace(
            row,
            state=state3,
            rcv_nxt=rcv_nxt,
            ooo=ooo2,
            rfin_seq=rfin,
            partial_seq=jnp.where(
                is_partial, last_seq,
                jnp.where(clear_partial, -1, row.partial_seq),
            ),
            partial_len=jnp.where(
                is_partial, last_len,
                jnp.where(clear_partial, 0, row.partial_len),
            ),
        )
        # auto-close: server-side close when the peer closes
        do_autoclose = (
            jnp.asarray(self.auto_close) & (row.state == CLOSE_WAIT)
            & ~row.fin_pending
        )
        row = dataclasses.replace(
            row, fin_pending=row.fin_pending | do_autoclose
        )
        # -- delayed ACK (tcp.c delack): an in-order segment with no ACK
        # debt outstanding waits for a second segment or the delack timer;
        # anything out-of-order / duplicate / FIN-bearing ACKs immediately
        # (the dup-ACK stream drives the peer's fast retransmit)
        in_order_fresh = fresh & (off == 0)
        delay_ok = (
            jnp.asarray(self.delack) & has_seg & in_order_fresh & ~f_fin
            & ~fin_new & (row.delack_segs == 0) & (pkt.nseg == 1)
        )
        send_ack = (has_seg & ~delay_ok) | dup_syn
        arm_delack = delay_ok & ~row.delack_live
        row = dataclasses.replace(
            row,
            delack_segs=jnp.where(
                has_seg, jnp.where(delay_ok, 1, 0), row.delack_segs
            ),
            delack_live=row.delack_live | arm_delack,
            pend_echo=jnp.where(has_seg, pkt.aux, row.pend_echo),
        )

        # -- retransmit row (fast retransmit / NewReno partial ack)
        nic_tx = net.nic_tx
        peer_h = _sel(sockets.peer_host, c)
        peer_p = _sel(sockets.peer_port, c)
        sport = _sel(sockets.local_port, c)
        retx_fin = _fin_ready(row) & (row.snd_una == n_segs)
        nic_tx, retx_row = self._seg_row(
            nic_tx, row, now, peer_h, sport, peer_p, row.snd_una, retx_fin,
            retx & (row.snd_una < row.snd_nxt), unlimited, is_retx=True,
        )

        # -- inline new-data tx (ACK-clocked)
        nic_tx, row, data_rows, more = self._tx_segments(
            nic_tx, row, now, peer_h, sport, peer_p, self.inline_budget,
            is_tcp & ~do_open, unlimited,
        )
        kick = self._kick_row(c, now, nic_tx.free_at, more)
        # outbound data/retransmit segments carry ack=rcv_nxt: the
        # piggybacked ACK clears any delayed-ACK debt
        sent_data = retx_row["mask"]
        for r in data_rows:
            sent_data = sent_data | r["mask"]
        row = dataclasses.replace(
            row, delack_segs=jnp.where(sent_data, 0, row.delack_segs)
        )

        # -- control/ACK row: SYN-ACK (passive open / dup SYN), the
        # handshake-completing pure ACK, a data/dup ACK — or an RST for a
        # segment no socket claims. The RST shares the ctl emit lane: an
        # unmatched segment triggers none of the other ctl conditions
        # (is_tcp needs a slot; a stray at LISTEN has no has_seg/ack_ok).
        if self.rst_on_unmatched:
            need_rst = (
                (pkt.proto == PROTO_TCP)
                & ((pkt.flags & F_RST) == 0)
                & (
                    (slot < 0)
                    | (is_tcp & ~f_syn & (row.state == LISTEN))
                )
            )
        else:
            need_rst = jnp.asarray(False)
        need_synack = do_open | dup_syn
        need_ctl = need_synack | est_active | send_ack | need_rst
        ctl_flags = jnp.where(
            need_synack, F_SYN | F_ACK,
            jnp.where(need_rst, F_RST | F_ACK, F_ACK),
        )
        ctl_ack = jnp.where(need_synack | need_rst, 0, row.rcv_nxt)
        # echo the arriving segment's ts for the peer's RTT estimator; the
        # SYN-ACK echoes the SYN's ts the same way
        ctl_aux = pkt.aux
        nic2, _s2, fin_t2 = nic_tx.admit(now, HEADER_TCP, unlimited)
        nic_tx = jax.tree.map(
            lambda n, o: jnp.where(need_ctl, n, o), nic2, nic_tx
        )
        ctl = dict(
            dst=pkt.src_host,
            dt=jnp.where(need_ctl, fin_t2 - now, 0),
            kind=KIND_PKT_ARRIVE,
            args=_pkt_args(
                pkt.dst_port, pkt.src_port, seq=0, ack=ctl_ack,
                # a dup/data ACK answering an nseg-fold represents nseg
                # per-segment ACKs: the count rides the length word's
                # high bits (low 24 bits stay 0 = no payload) so the
                # sender's dup-ack ladder advances as if unfolded
                length=jnp.where(
                    need_synack | (pkt.nseg <= 1), 0,
                    pkt.nseg.astype(jnp.int32) << BURST_NSEG_SHIFT,
                ),
                wnd=row.rwnd, aux=ctl_aux, flags=ctl_flags,
                sack=row.ooo[0],
            ),
            mask=need_ctl, local=False,
        )

        # -- timer row (RTO arm or TIME_WAIT), then slot free / RST reset
        row, timer_row = self._arm_row(row, c, now, enter_tw)
        # a passive open must arm the CHILD's RTO timer (SYN-ACK
        # retransmit; a lost server reply would otherwise hang forever).
        # The listener's own arm is necessarily idle when a SYN arrives,
        # so the child shares the row.
        timer_row = dict(
            dst=0,
            dt=jnp.where(do_open, jnp.int64(RTO_INIT), timer_row["dt"]),
            kind=KIND_TCP_TIMER,
            args=jnp.where(
                do_open,
                _ctl_args(child, child_row.timer_gen, TK_RTO),
                timer_row["args"],
            ),
            mask=timer_row["mask"] | do_open,
            local=True,
        )
        freed = freed_ack | got_rst
        row = jax.tree.map(
            lambda fresh_v, cur: jnp.where(freed, fresh_v, cur),
            dataclasses.replace(
                _fresh_row_like(row), timer_gen=row.timer_gen + 1
            ),
            row,
        )
        sockets = dataclasses.replace(
            sockets, proto=_put(sockets.proto, c, PROTO_NONE, freed & is_tcp)
        )

        # -- write back: main row at c, child row at its slot
        tcb = _write_row(net.tcb, c, row, is_tcp & ~at_listen)
        tcb = _write_row(tcb, child, child_row, do_open)
        # byte accounting: UDP counts arrivals, TCP counts newly-delivered
        deliver_len = jnp.where(is_tcp, new_bytes, pkt.length)
        # app sees data deliveries AND stream EOF (the consumed FIN): the
        # F_FIN bit in the delivered flags is re-synthesized to mean "the
        # peer finished sending" — the app-visible recv()==0 the reference
        # surfaces through descriptor status (tcp.c FIN -> readable EOF)
        deliver = is_udp | (is_tcp & ((new_bytes > 0) | fin_new))
        sockets = sockets.add_rx(jnp.where(deliver, c, -1), deliver_len)
        hs = dataclasses.replace(
            hs,
            net=dataclasses.replace(
                net, tcb=tcb, sockets=sockets, nic_tx=nic_tx
            ),
        )

        # -- app delivery (once, after all state updates)
        eof_flags = jnp.where(
            is_tcp,
            (pkt.flags & ~F_FIN) | jnp.where(fin_new, F_FIN, 0),
            pkt.flags,
        )
        pkt2 = dataclasses.replace(pkt, length=deliver_len, flags=eof_flags)
        hs, app_em = on_recv(hs, jnp.where(deliver, slot, -1), pkt2, now, key)
        da_row = dict(
            dst=0, dt=jnp.int64(DELACK_DELAY), kind=KIND_TCP_TIMER,
            args=_ctl_args(c, row.conn_gen, TK_DELACK), mask=arm_delack,
            local=True,
        )
        ours = _emit_from_rows(
            [ctl, retx_row] + data_rows + [kick, timer_row, da_row]
        )
        return hs, emit_concat(ours, app_em)

    # ------------------------------------------------------ event handlers
    def _on_tx(self, stack, hs, ev, key):
        """KIND_TCP_TX: paced/window-limited transmission kick."""
        net = hs.net
        now = ev.time
        c = jnp.maximum(ev.args[T_SLOT], 0)
        row = _row(net.tcb, c)
        enabled = _sel(net.sockets.proto, c) == PROTO_TCP
        unlimited = now < stack.bootstrap_end
        nic_tx, row, rows, more = self._tx_segments(
            net.nic_tx, row, now,
            _sel(net.sockets.peer_host, c), _sel(net.sockets.local_port, c),
            _sel(net.sockets.peer_port, c), self.tx_burst, enabled, unlimited,
        )
        rows.append(self._kick_row(c, now, nic_tx.free_at, more))
        row, timer_row = self._arm_row(
            row, c, now, jnp.asarray(False)
        )
        rows.append(timer_row)
        tcb = _write_row(net.tcb, c, row, enabled)
        hs = dataclasses.replace(
            hs, net=dataclasses.replace(net, tcb=tcb, nic_tx=nic_tx)
        )
        return hs, _emit_from_rows(rows)

    def _on_timer(self, stack, hs, ev, key):
        """KIND_TCP_TIMER: RTO expiry (with lazy reschedule) or TIME_WAIT
        expiry (tcp.c retransmit timers; CONFIG_TCPCLOSETIMER_DELAY)."""
        net = hs.net
        now = ev.time
        c = jnp.maximum(ev.args[T_SLOT], 0)
        gen = ev.args[T_GEN]
        tk = ev.args[T_KIND]
        row = _row(net.tcb, c)
        slot_ok = _sel(net.sockets.proto, c) == PROTO_TCP
        live = (gen == row.timer_gen) & slot_ok
        unlimited = now < stack.bootstrap_end

        # delayed-ACK expiry: flush the owed ACK. The gen word carries the
        # slot's connection incarnation, so a timer armed by a previous
        # connection on a reused slot is inert for the new one
        is_da = slot_ok & (tk == TK_DELACK) & (gen == row.conn_gen)
        da_fire = is_da & (row.delack_segs > 0)
        row = dataclasses.replace(
            row,
            delack_live=jnp.where(is_da, False, row.delack_live),
            delack_segs=jnp.where(is_da, 0, row.delack_segs),
        )

        # TIME_WAIT expiry: free the slot
        tw_done = live & (tk == TK_TIMEWAIT) & (row.state == TIME_WAIT)

        rto_ev = live & (tk == TK_RTO)
        early = rto_ev & (now < row.rto_deadline)
        fire = rto_ev & ~early
        outstanding = _outstanding(row)
        timeout = fire & outstanding
        # timeout: collapse to loss state (cc timeout hook + go-back-N)
        flight = (row.snd_nxt - row.snd_una).astype(jnp.float32)
        ss_to, wmax_to, epoch_to = self.cc.on_timeout(row, flight, now)
        row = dataclasses.replace(
            row,
            ssthresh=jnp.where(timeout, ss_to, row.ssthresh),
            cc_wmax=jnp.where(timeout, wmax_to, row.cc_wmax),
            cc_epoch=jnp.where(timeout, epoch_to, row.cc_epoch),
            cwnd=jnp.where(timeout, 1.0, row.cwnd),
            dup_acks=jnp.where(timeout, 0, row.dup_acks),
            recover=jnp.where(timeout, -1, row.recover),
            rto=jnp.where(
                timeout, jnp.minimum(row.rto * 2, RTO_MAX), row.rto
            ),
            snd_nxt=jnp.where(
                timeout & (row.state >= ESTABLISHED), row.snd_una, row.snd_nxt
            ),
            timer_live=jnp.where(fire & ~outstanding, False, row.timer_live),
            rto_deadline=jnp.where(
                timeout,
                now + jnp.minimum(row.rto * 2, RTO_MAX),
                row.rto_deadline,
            ),
            n_retx=row.n_retx + timeout.astype(_I32),
        )

        # retransmission: SYN / SYN-ACK / data-or-FIN at snd_una
        peer_h = _sel(net.sockets.peer_host, c)
        peer_p = _sel(net.sockets.peer_port, c)
        sport = _sel(net.sockets.local_port, c)
        is_syn_rtx = timeout & (row.state == SYN_SENT)
        is_synack_rtx = timeout & (row.state == SYN_RCVD)
        is_data_rtx = timeout & (row.state >= ESTABLISHED)
        n_segs = _n_segs(row.snd_buf)
        retx_fin = _fin_ready(row) & (row.snd_una == n_segs)
        nic_tx, data_row = self._seg_row(
            net.nic_tx, row, now, peer_h, sport, peer_p, row.snd_una,
            retx_fin, is_data_rtx, unlimited, is_retx=True,
        )
        hs_flags = jnp.where(is_syn_rtx, F_SYN, F_SYN | F_ACK)
        nic2, _s, fin_t = nic_tx.admit(now, HEADER_TCP, unlimited)
        hs_mask = is_syn_rtx | is_synack_rtx
        nic_tx = jax.tree.map(
            lambda n, o: jnp.where(hs_mask, n, o), nic2, nic_tx
        )
        hs_row = dict(
            dst=peer_h, dt=jnp.where(hs_mask, fin_t - now, 0),
            kind=KIND_PKT_ARRIVE,
            args=_pkt_args(sport, peer_p, wnd=row.rwnd, aux=_ts_us(now),
                           flags=hs_flags),
            mask=hs_mask, local=False,
        )
        # re-arm: early -> at deadline (same gen); timeout -> +rto'
        rearm = early | timeout
        timer_row = dict(
            dst=0,
            dt=jnp.maximum(
                jnp.where(early, row.rto_deadline - now, row.rto), 1
            ),
            kind=KIND_TCP_TIMER,
            args=_ctl_args(c, row.timer_gen, TK_RTO),
            mask=rearm, local=True,
        )
        # the flushed delayed ACK (echoes the delayed segment's timestamp)
        nic3, _s3, fin_t3 = nic_tx.admit(now, HEADER_TCP, unlimited)
        nic_tx = jax.tree.map(
            lambda n, o: jnp.where(da_fire, n, o), nic3, nic_tx
        )
        da_ack_row = dict(
            dst=peer_h, dt=jnp.where(da_fire, fin_t3 - now, 0),
            kind=KIND_PKT_ARRIVE,
            args=_pkt_args(
                sport, peer_p, seq=0, ack=row.rcv_nxt, length=0,
                wnd=row.rwnd, aux=row.pend_echo, flags=F_ACK,
                sack=row.ooo[0],
            ),
            mask=da_fire, local=False,
        )

        # free on TIME_WAIT expiry
        row = jax.tree.map(
            lambda fresh_v, cur: jnp.where(tw_done, fresh_v, cur),
            dataclasses.replace(
                _fresh_row_like(row), timer_gen=row.timer_gen + 1
            ),
            row,
        )
        sockets = dataclasses.replace(
            net.sockets,
            proto=_put(net.sockets.proto, c, PROTO_NONE, tw_done),
        )
        tcb = _write_row(net.tcb, c, row, live | is_da)
        hs = dataclasses.replace(
            hs,
            net=dataclasses.replace(
                net, tcb=tcb, nic_tx=nic_tx, sockets=sockets
            ),
        )
        return hs, _emit_from_rows([data_row, hs_row, timer_row, da_ack_row])

    def make_handlers(self, stack):
        """[KIND_TCP_TIMER, KIND_TCP_TX] handlers (appended after the
        stack's arrive/rx pair by Stack.make_handlers)."""
        return [
            lambda hs, ev, key: self._on_timer(stack, hs, ev, key),
            lambda hs, ev, key: self._on_tx(stack, hs, ev, key),
        ]

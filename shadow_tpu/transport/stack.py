"""The host network stack: packet events, delivery pipeline, send path.

Reference pipeline per packet (SURVEY.md §3.2-3.3): socket flush ->
qdisc/token-bucket send (network_interface.c:519-579) -> worker_sendPacket
(latency + reliability + barrier clamp, worker.c:243-304) -> dst router
CoDel enqueue (router.c:96-133) -> NIC token-bucket receive
(network_interface.c:192-226) -> socket demux (:375-455) -> transport
processPacket -> app wakeup via epoll.

TPU-native pipeline, two event hops per packet:

  sender handler:  tx-NIC virtual clock -> Emit(dst, dt=serialize delay)
  [engine routes: + path latency, reliability roll, window clamp]
  KIND_PKT_ARRIVE @ dst: rx-NIC virtual clock gives (start, finish);
      sojourn = start - arrival feeds CoDel -> maybe drop;
      else local Emit at dt = finish-now, kind = KIND_PKT_RX
  KIND_PKT_RX @ dst: socket demux -> protocol dispatch (UDP: count bytes,
      app on_recv callback; TCP: segment processing via the tcp hook)

Packet metadata rides the event's i32 args; payload *bytes* never exist on
device — only lengths (the reference similarly keeps Payload refs out of
headers, packet.c:40-63; one app payload word can ride the aux field).
The local ARRIVE->RX re-emit would lose the sender's identity (ev.src of a
local event is the host itself), so the arrive handler stashes the true
source id in the A_SRC arg word.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import jax
import jax.numpy as jnp

from shadow_tpu.core import rng as srng
from shadow_tpu.core.engine import (
    BURST_LEN_MASK, BURST_NSEG_SHIFT, Emit,
)
from shadow_tpu.core.events import Events
from shadow_tpu.host.nic import HEADER_TCP, HEADER_UDP, MTU, NIC, CoDel
from shadow_tpu.host.sockets import PROTO_TCP, PROTO_UDP, SocketTable

# ---------------------------------------------------------------------------
# Packet arg layout: 11 i32 words.
N_PKT_ARGS = 11
A_META = 0  # proto | tcp flags (bit-packed, see below)
A_SPORT = 1
A_DPORT = 2
A_SEQ = 3  # TCP: segment sequence number (in segments)
A_ACK = 4  # TCP: cumulative ack (in segments)
A_LEN = 5  # payload bytes
A_WND = 6  # TCP: advertised receive window (segments)
A_AUX = 7  # timestamp echo (ms) / app payload word
A_SRC = 8  # original source host id (stashed across the local rx re-emit)
A_SACK0 = 9  # TCP: SACK bitmap rel. to ack, bits 0-31 (tcp.c SACK list)
A_SACK1 = 10  # TCP: SACK bitmap bits 32-63

F_SYN = 1 << 2
F_ACK = 1 << 3
F_FIN = 1 << 4
F_RST = 1 << 5
F_RETX = 1 << 6  # sender-stamped retransmission (PDS_RETRANSMITTED)

KIND_PKT_ARRIVE = 0
KIND_PKT_RX = 1
N_STACK_KINDS = 2


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class Pkt:
    """Decoded packet metadata (scalars inside a vmapped handler)."""

    proto: jax.Array
    flags: jax.Array
    src_host: jax.Array
    src_port: jax.Array
    dst_port: jax.Array
    seq: jax.Array
    ack: jax.Array
    length: jax.Array
    wnd: jax.Array
    aux: jax.Array
    sack: jax.Array  # u64 bitmap: bit i = segment ack+i held by receiver
    # burst delivery (engine._burst_fold): this packet stands for `nseg`
    # contiguous same-flow segments totalling `length` bytes; 1 for every
    # packet the fold never touched. Rides the A_LEN word's bits 24..30.
    nseg: jax.Array

    @staticmethod
    def decode(ev: Events) -> "Pkt":
        """Decode a KIND_PKT_RX event (src from the stashed arg word)."""
        a = ev.args
        return Pkt(
            proto=a[A_META] & 0x3,
            flags=a[A_META],
            src_host=a[A_SRC],
            src_port=a[A_SPORT],
            dst_port=a[A_DPORT],
            seq=a[A_SEQ],
            ack=a[A_ACK],
            length=a[A_LEN] & BURST_LEN_MASK,
            wnd=a[A_WND],
            aux=a[A_AUX],
            nseg=jnp.maximum(a[A_LEN] >> BURST_NSEG_SHIFT, 1),
            sack=(
                a[A_SACK0].astype(jnp.uint32).astype(jnp.uint64)
                | (a[A_SACK1].astype(jnp.uint32).astype(jnp.uint64) << 32)
            ),
        )

    @staticmethod
    def encode_args(proto, sport, dport, seq=0, ack=0, length=0, wnd=0,
                    aux=0, flags=0, sack=0):
        """i32[N_PKT_ARGS] args vector for an Emit (scalar fields)."""
        meta = jnp.asarray(proto, jnp.int32) | jnp.asarray(flags, jnp.int32)
        mk = lambda x: jnp.broadcast_to(jnp.asarray(x, jnp.int32), meta.shape)
        sack = jnp.asarray(sack, jnp.uint64)
        s0 = (sack & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32).astype(jnp.int32)
        s1 = (sack >> jnp.uint64(32)).astype(jnp.uint32).astype(jnp.int32)
        meta = jnp.broadcast_to(
            meta, jnp.broadcast_shapes(meta.shape, s0.shape)
        )
        return jnp.stack(
            [meta, mk(sport), mk(dport), mk(seq), mk(ack), mk(length),
             mk(wnd), mk(aux), mk(0), mk(s0), mk(s1)]
        )


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class HostNet:
    """Per-host network-stack state bundle ([H]-leading at rest).

    The composition mirrors Host's members: NIC both directions, upstream
    router AQM, socket table (host.c:76-91,199-206).
    """

    nic_tx: NIC
    nic_rx: NIC
    codel: CoDel
    sockets: SocketTable
    tcb: Any = None  # transport.tcp.TCB [H, S] when TCP is installed
    cap: Any = None  # utils.pcap.CaptureRing when logpcap is set

    @staticmethod
    def create(n_hosts: int, n_sockets: int, bw_up_kib, bw_down_kib,
               with_tcp: bool = False, rcv_wnd_bytes=None,
               wnd_words: int | None = None, rx_buf_bytes=0,
               snd_buf_bytes=None) -> "HostNet":
        up = jnp.broadcast_to(jnp.asarray(bw_up_kib), (n_hosts,))
        down = jnp.broadcast_to(jnp.asarray(bw_down_kib), (n_hosts,))
        tcb = None
        if with_tcp:
            from shadow_tpu.transport.tcp import MSS, TCB, WND_WORDS

            ww = wnd_words or WND_WORDS
            cap_max = 64 * ww
            # socketrecvbuffer caps the autotuned advertised window at the
            # buffer's segment count (host.c autotuned buffers,
            # tcp.c:407-598); the hard ceiling is the reassembly bitmap
            rcv_wnd = None
            if rcv_wnd_bytes is not None:
                rb = jnp.asarray(rcv_wnd_bytes, jnp.int64)
                rcv_wnd = jnp.where(
                    rb > 0, jnp.clip(rb // MSS, 1, cap_max), cap_max
                ).astype(jnp.int32)
            tcb = TCB.create(
                n_hosts, n_sockets, rcv_wnd=rcv_wnd, wnd_words=ww,
                snd_cap=snd_buf_bytes,
            )
        return HostNet(
            nic_tx=NIC.create(up),
            nic_rx=NIC.create(down, buf_bytes=rx_buf_bytes),
            codel=CoDel.create(n_hosts),
            sockets=SocketTable.create(n_hosts, n_sockets),
            tcb=tcb,
        )


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class SimHost:
    """Default host-state shape: network stack + app pytree."""

    net: HostNet
    app: Any


# App receive callback: (host_state, slot, Pkt, now, key) -> (host_state',
# Emit). It sees the full host state, so replies can go straight through
# Stack.send_udp / tcp ops.
OnRecvHost = Callable[[Any, jax.Array, "Pkt", jax.Array, jax.Array], tuple[Any, Emit]]


class Stack:
    """Builds the packet-pipeline handlers and the send-path helpers.

    Host state seen by handlers must be a pytree with `.net: HostNet` and
    `.app` attributes (use `SimHost` or any compatible dataclass).
    """

    def __init__(self, *, bootstrap_end: int = 0, tcp=None,
                 rx_queue: str = "codel", fuse_rx: bool = False):
        """rx_queue selects the upstream router's queue manager
        (router.c:50-55 QUEUE_MANAGER_{CODEL,STATIC,SINGLE}): 'codel'
        (AQM, the reference host default, host.c:205), 'static' (pure
        drop-tail against the NIC buffer bound), or 'single' (one packet
        queued at a time, router_queue_single.c).

        fuse_rx=True folds the KIND_PKT_RX delivery into the
        KIND_PKT_ARRIVE handler: one event per packet hop instead of
        two. Every OUTPUT time is exact — the delivery's emits are
        shifted by the rx-NIC serialization delay (finish - arrival), so
        replies and relays leave at the same instants as the two-event
        pipeline — but the socket/app STATE is read at arrival time
        rather than at NIC-finish time, so another event executing
        inside that (typically tens-of-microseconds) gap observes the
        post-delivery state early. The reference always pays the
        two-step path (network_interface.c:192-226 receive queue, then
        socket demux); fusion is the TPU-era tradeoff that halves the
        sequential depth of the engine's chained drain, where each step
        costs a full handler-table pass."""
        if rx_queue not in ("codel", "static", "single"):
            raise ValueError(f"unknown rx_queue {rx_queue!r}")
        self.bootstrap_end = bootstrap_end  # unlimited-bandwidth phase end
        self.tcp = tcp  # TCP protocol hook (transport.tcp.TCP instance)
        self.rx_queue = rx_queue
        self.fuse_rx = fuse_rx

    # ---------------------------------------------------------------- send
    def send_udp(self, hs, now, slot, dst_host, dst_port, nbytes,
                 aux=0, mask=True):
        """One UDP datagram through the tx NIC; returns (hs', Emit).

        Serialization delay = wire bytes / up-bandwidth from the virtual
        clock (fluid token bucket, network_interface.c:519-579 semantics);
        the engine then adds path latency and rolls reliability.
        """
        net: HostNet = hs.net
        unlimited = now < self.bootstrap_end
        wire = jnp.asarray(nbytes, jnp.int32) + HEADER_UDP
        nic_tx, _start, finish = net.nic_tx.admit(now, wire, unlimited)
        # only advance the NIC clock if this send actually happens
        nic_tx = jax.tree.map(
            lambda n, o: jnp.where(mask, n, o), nic_tx, net.nic_tx
        )
        from shadow_tpu.transport.tcp import _sel

        sport = _sel(net.sockets.local_port, slot)
        # socket counters track app payload; wire overhead is charged to
        # the NIC only (the reference's tracker splits payload vs header
        # bytes the same way, tracker.c:433-479)
        sockets = net.sockets.add_tx(jnp.where(mask, slot, -1), nbytes)
        cap = net.cap
        if cap is not None:
            # tx-side lifecycle record on the SENDER's ring (the
            # reference captures both directions at the NIC,
            # network_interface.c:337-373)
            from shadow_tpu.utils.pcap import STG_SENT

            cap2 = cap.append(
                now, jnp.asarray(-1, jnp.int32), dst_host, sport, dst_port,
                jnp.asarray(PROTO_UDP, jnp.int32),
                jnp.asarray(nbytes, jnp.int32), 0, 0, STG_SENT,
            )
            cap = jax.tree.map(
                lambda n, o: jnp.where(mask, n, o), cap2, cap
            )
        hs = dataclasses.replace(
            hs,
            net=dataclasses.replace(
                net, nic_tx=nic_tx, sockets=sockets, cap=cap
            ),
        )
        args = Pkt.encode_args(PROTO_UDP, sport, dst_port, length=nbytes, aux=aux)
        em = Emit.single(
            dst=dst_host,
            dt=finish - now,
            kind=KIND_PKT_ARRIVE,
            args=args,
            mask=mask,
            n_args=N_PKT_ARGS,
        )
        return hs, em

    # ------------------------------------------------------------ handlers
    def make_handlers(self, on_recv: OnRecvHost):
        """[KIND_PKT_ARRIVE, KIND_PKT_RX] handler pair.

        `on_recv(hs, slot, pkt, now, key) -> (hs', Emit)` is invoked for
        demuxed UDP payload deliveries (and TCP app-data deliveries when a
        tcp hook is installed).
        """

        def on_arrive(hs, ev: Events, key):
            # Router enqueue + rx-NIC dequeue scheduling + CoDel verdict.
            # The packet reached the host edge at ev.time; its rx start is
            # the NIC virtual clock; sojourn (start - arrival) is the
            # standing queue delay CoDel controls on
            # (router_queue_codel.c:198-267).
            net: HostNet = hs.net
            now = ev.time
            # rate-limit on wire bytes (payload + header), matching the tx
            # side — the reference's token buckets charge total packet size
            # in both directions (network_interface.c:192-226)
            proto = ev.args[A_META] & 0x3
            header = jnp.where(proto == PROTO_TCP, HEADER_TCP, HEADER_UDP)
            # a burst-folded arrival stands for nseg wire packets: its
            # payload is the run's total and each segment pays a header.
            # A zero-payload packet with a count (a dup ACK answering a
            # fold) is ONE wire packet — the count is ack bookkeeping.
            nseg = jnp.maximum(ev.args[A_LEN] >> BURST_NSEG_SHIFT, 1)
            paylen = ev.args[A_LEN] & BURST_LEN_MASK
            wire = paylen + jnp.where(paylen > 0, nseg, 1) * header
            unlimited = now < self.bootstrap_end
            # drop-tail against the NIC receive buffer (interfacebuffer,
            # options.c:132; 0 = unbounded). 'single' bounds the implicit
            # queue at one in-service packet (router_queue_single.c)
            backlog = net.nic_rx.backlog_bytes(now)
            if self.rx_queue == "single":
                tail_drop = backlog > MTU
            else:
                tail_drop = (net.nic_rx.buf_bytes > 0) & (
                    backlog + wire > net.nic_rx.buf_bytes
                )
            tail_drop = tail_drop & ~unlimited
            nic_rx, start, finish = net.nic_rx.admit(now, wire, unlimited)
            sojourn = start - now
            if self.rx_queue == "codel":
                codel, aqm_drop = net.codel.on_dequeue(start, sojourn)
                codel = jax.tree.map(
                    lambda n, o: jnp.where(unlimited | tail_drop, o, n),
                    codel, net.codel,
                )
            else:
                codel, aqm_drop = net.codel, jnp.asarray(False)
            drop = (aqm_drop & ~unlimited) | tail_drop
            # a dropped packet never occupies the link
            nic_rx = jax.tree.map(
                lambda n, o: jnp.where(drop, o, n), nic_rx, net.nic_rx
            )
            nic_rx = dataclasses.replace(
                nic_rx, drops=nic_rx.drops + tail_drop.astype(jnp.int64)
            )
            cap = net.cap
            if cap is not None:
                # packet-lifecycle capture: a STAGE bitmask per record
                # reconstructs the packet's path (the reference appends
                # PDS_* stage flags hop by hop, packet.h:20-40; its pcap
                # capture runs before the receive queue and cannot see
                # drops, network_interface.c:337-373)
                from shadow_tpu.utils.pcap import (
                    STG_AQM_DROP, STG_ARRIVED, STG_DELIVERED, STG_QUEUED,
                    STG_RETX, STG_TAIL_DROP,
                )

                stages = (
                    STG_ARRIVED
                    | jnp.where(sojourn > 0, STG_QUEUED, 0)
                    | jnp.where(tail_drop, STG_TAIL_DROP, 0)
                    | jnp.where(drop & ~tail_drop, STG_AQM_DROP, 0)
                    | jnp.where(drop, 0, STG_DELIVERED)
                    | jnp.where(
                        (ev.args[A_META] & F_RETX) != 0, STG_RETX, 0
                    )
                )
                cap = cap.append(
                    now, ev.src, ev.dst, ev.args[A_SPORT], ev.args[A_DPORT],
                    ev.args[A_META], ev.args[A_LEN] & BURST_LEN_MASK,
                    ev.args[A_SEQ], ev.args[A_ACK], stages,
                )
            hs = dataclasses.replace(
                hs,
                net=dataclasses.replace(
                    net, nic_rx=nic_rx, codel=codel, cap=cap
                ),
            )
            args = ev.args.at[A_SRC].set(ev.src)  # stash true source
            if not self.fuse_rx:
                em = Emit.single(
                    dst=ev.dst,
                    dt=finish - now,
                    kind=KIND_PKT_RX,
                    args=args,
                    mask=~drop,
                    local=True,
                    n_args=N_PKT_ARGS,
                )
                return hs, em
            # fused delivery: run the rx path inline AT the NIC-finish
            # instant (emits shift by finish - now, so all output timing
            # matches the two-event pipeline); a dropped packet delivers
            # nothing and leaves delivery state untouched. The delivery
            # consumes an independent key stream so fused and unfused
            # modes draw from separated domains.
            rx_ev = dataclasses.replace(
                ev, time=finish, args=args, kind=jnp.int32(KIND_PKT_RX)
            )
            hs2, em = deliver(hs, rx_ev, srng.fold_in(key, 0x52580001))
            hs = jax.tree.map(
                lambda dropped_v, ok_v: jnp.where(drop, dropped_v, ok_v),
                hs, hs2,
            )
            em = dataclasses.replace(
                em,
                dt=em.dt + (finish - now),
                mask=em.mask & ~drop,
            )
            return hs, em

        def deliver(hs, ev: Events, key):
            # Socket demux + protocol dispatch (network_interface.c:375-455
            # -> udp_processPacket / tcp_processPacket).
            net: HostNet = hs.net
            pkt = Pkt.decode(ev)
            slot = net.sockets.demux(
                pkt.proto, pkt.dst_port, pkt.src_host, pkt.src_port
            )
            if self.tcp is not None:
                # the TCP hook owns byte accounting (it counts delivered
                # bytes, not raw arrivals) and routes UDP through on_recv
                return self.tcp.process_segment(
                    self, hs, slot, pkt, ev, key, on_recv
                )
            sockets = net.sockets.add_rx(slot, pkt.length)
            hs = dataclasses.replace(
                hs, net=dataclasses.replace(net, sockets=sockets)
            )
            return on_recv(hs, slot, pkt, ev.time, key)

        def on_rx(hs, ev: Events, key):
            if self.fuse_rx:
                # deliveries ride inside on_arrive when fused; nothing
                # emits KIND_PKT_RX events, but the branch still sits in
                # the vmapped switch (every branch's ops execute masked),
                # so it must be a stub, not a second copy of the delivery
                # path
                return hs, Emit.none(1, N_PKT_ARGS)
            return deliver(hs, ev, key)

        handlers = [on_arrive, on_rx]
        if self.tcp is not None:
            handlers += self.tcp.make_handlers(self)
        return handlers

    def frontier_kinds(self) -> tuple:
        """Stack-level kinds eligible for multi-position runs under the
        engine's frontier drain (engine._drain_window_frontier).

        The run rule is only exact when every LOCAL emit a kind can
        produce lands at dt >= 1. Fused arrivals qualify: the delivery
        runs inline and every follow-up (tcp tx kick, retransmit timer,
        delayed ack, app reply) is scheduled through helpers that floor
        the delay at 1 ns (tcp._kick_row / _arm_row / da_row, the fused
        re-emit's `finish - now` NIC serialization). KIND_PKT_RX is
        deliberately absent — when fused it is a stub that never runs,
        and unfused mode is refused by sim.build_simulation because the
        bootstrap-phase ARRIVE->RX re-emit can land at dt=0.
        """
        fk = (KIND_PKT_ARRIVE,)
        if self.tcp is not None:
            fk += (N_STACK_KINDS, N_STACK_KINDS + 1)  # tcp_timer, tcp_tx
        return fk

from shadow_tpu.transport.stack import (
    HostNet,
    Pkt,
    Stack,
    KIND_PKT_ARRIVE,
    KIND_PKT_RX,
    N_STACK_KINDS,
)

__all__ = [
    "HostNet",
    "Pkt",
    "Stack",
    "KIND_PKT_ARRIVE",
    "KIND_PKT_RX",
    "N_STACK_KINDS",
]

import sys

from shadow_tpu.cli import main

sys.exit(main())

"""The conservative-window simulation engine.

Reference semantics being reproduced (see SURVEY.md §3.1-3.3):

- Master computes conservative execution windows from the minimum
  cross-host latency and drives rounds (reference:
  src/main/core/master.c:133-159,450-480).
- Workers pop events below the window barrier per host and execute them
  (reference: src/main/core/worker.c:149-216,
  scheduler_policy_host_single.c:210-271).
- Cross-host sends roll reliability, add path latency, and are clamped up
  to the window barrier to preserve causality (reference:
  src/main/core/worker.c:243-304, scheduler_policy_host_single.c:180-184).

TPU-native re-expression: all hosts pop/execute/emit in lockstep as one
vmapped kernel over [H]-leading state arrays; the inner drain loop is a
`lax.while_loop`; the window barrier is a global min over per-host
next-event times (`lax.pmin` across the device mesh when sharded). One
"round" of the reference's pthread barrier dance is one iteration of the
outer while loop here — no locks, no threads, no barrier waits.

Drain algorithm (v3, chained): each outer iteration (sweep) moves every
host's frontier — its `drain_batch` earliest below-barrier events, a
prefix of the key-sorted queue rows — into a per-host STAGING buffer,
then an inner while_loop executes, per iteration, each host's minimum-key
staged event (vmapped) and appends the handler's routed emits back into
the staging buffer with one-hot masked writes (no sort, no scatter).
Because cross-host sends are clamped to the window barrier, an emitted
event is below the barrier iff it is LOCAL — so chains of local
follow-ups (packet arrival -> rx delivery -> tx kick) execute inside ONE
sweep in exact (time, src, seq) order, instead of costing one full
queue-push + re-sort sweep per cascade level (the v2 bottleneck: TCP
workloads measured ~2 events/sweep, ~48 sweeps/window). The sweep ends
when no staged event is below the barrier; leftovers (clamped remote
sends, far-future timers, high-water overflow) are flushed to the queues
in one push + cross-shard exchange per sweep. The reference's per-host
drain semantics (pop everything below the barrier,
scheduler_policy_host_single.c:210-271) are preserved exactly — the
per-host execution order is identical to v2's, which makes v3
bit-compatible with v2 — and the inner loop still needs no collectives,
so each shard drains with its own trip count and only the outer loop
synchronizes.
"""

from __future__ import annotations

import copy
import dataclasses
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp

from shadow_tpu.core import rng as srng
from shadow_tpu.core.events import (
    N_ARGS,
    EventQueue,
    Events,
    group_run_starts,
    pack_srcseq,
    queue_push,
)
from shadow_tpu.core.timebase import TIME_INVALID

# Burst-fold length-word layout: low bits payload total, high bits the
# folded-run segment count. Every packer/unpacker (the fold below, the
# stack's Pkt decode and wire accounting, tcp's dup-ACK carrier) derives
# from these; the stage-width guard in EngineConfig enforces NSEG_MAX.
BURST_NSEG_SHIFT = 24
BURST_LEN_MASK = (1 << BURST_NSEG_SHIFT) - 1
BURST_NSEG_MAX = 127  # bits 24..30; bit 31 is the i32 sign


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class Emit:
    """Up to K events emitted by one handler invocation (per host).

    dst is a *global* host id; dt is a non-negative delay relative to the
    executing event's time. local=True means a same-host scheduled task
    (worker_scheduleTask semantics: dst is forced to self, no routing);
    local=False means a network send that the engine routes — + path
    latency, reliability drop roll, barrier clamp (worker_sendPacket
    semantics) — including sends addressed to the sending host itself,
    which traverse the topology's self-loop exactly like the reference.
    """

    dst: jax.Array  # i32[K]
    dt: jax.Array  # i64[K]
    kind: jax.Array  # i32[K]
    args: jax.Array  # i32[K, N_ARGS]
    mask: jax.Array  # bool[K]
    local: jax.Array  # bool[K]

    @staticmethod
    def none(k: int, n_args: int = N_ARGS) -> "Emit":
        return Emit(
            dst=jnp.zeros((k,), jnp.int32),
            dt=jnp.zeros((k,), jnp.int64),
            kind=jnp.zeros((k,), jnp.int32),
            args=jnp.zeros((k, n_args), jnp.int32),
            mask=jnp.zeros((k,), bool),
            local=jnp.zeros((k,), bool),
        )

    @staticmethod
    def single(
        dst, dt, kind, args=None, mask=True, local=False, n_args: int = N_ARGS
    ) -> "Emit":
        a = jnp.zeros((1, n_args), jnp.int32)
        if args is not None:
            args = jnp.asarray(args, jnp.int32).reshape(1, -1)
            a = a.at[:, : args.shape[1]].set(args)
        return Emit(
            dst=jnp.asarray(dst, jnp.int32).reshape(1),
            dt=jnp.asarray(dt, jnp.int64).reshape(1),
            kind=jnp.asarray(kind, jnp.int32).reshape(1),
            args=a,
            mask=jnp.asarray(mask, bool).reshape(1),
            local=jnp.asarray(local, bool).reshape(1),
        )

    def pad_to(self, k: int) -> "Emit":
        cur = self.dst.shape[0]
        if cur == k:
            return self
        assert cur < k, f"handler emitted {cur} > max_emit {k}"
        return jax.tree.map(
            lambda a: jnp.concatenate(
                [a, jnp.zeros((k - cur,) + a.shape[1:], a.dtype)]
            ),
            self,
        )


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class Stats:
    """Per-host accounting (the reference's ObjectCounter/Tracker spirit:
    object_counter.c tracks new/free per object type; here every event
    kind gets an executed count, the struct-of-arrays analog)."""

    n_executed: jax.Array  # i64[H]
    n_emitted: jax.Array  # i64[H]
    n_net_dropped: jax.Array  # i64[H] packets lost to reliability rolls
    n_windows: jax.Array  # i64[] (replicated across shards)
    n_by_kind: jax.Array  # i64[H, NK] executed events per handler kind
    # scheduler self-profiling (the reference logs per-thread barrier
    # waits and push/pop idle time every run, scheduler.c:266-271;
    # the lockstep analogs are sweep and collective-round counts):
    n_sweeps: jax.Array  # i64[] outer drain iterations (queue merges)
    n_inner_steps: jax.Array  # i64[] sequential frontier positions run
    n_xchg_rounds: jax.Array  # i64[] cross-shard all_to_all rounds
    n_cross_shard: jax.Array  # i64[] packets delivered across shards
    # fault-injection attribution (every drop the chaos causes is
    # accounted somewhere: either the packet died on the wire or the
    # event died with its crashed host)
    n_fault_dropped: jax.Array  # i64[H] packets lost to fault overlays
    n_quarantined: jax.Array  # i64[H] events voided by host crashes

    @staticmethod
    def create(n_hosts: int, n_kinds: int = 1) -> "Stats":
        z = jnp.zeros((n_hosts,), jnp.int64)
        s = jnp.zeros((), jnp.int64)
        return Stats(
            z, z, z, s,
            jnp.zeros((n_hosts, n_kinds), jnp.int64),
            s, s, s, s, z, z,
        )


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class ExchangeBuf:
    """In-flight cross-shard events: the exchange double buffer.

    `bucket` holds the [S, R] result of the LAST all_to_all round of the
    previous flush — events destined for this shard that have been
    exchanged but not yet merged into its queue. Delivery is deferred to
    the next point the queue is actually read (the top of the next sweep
    body, or the next window's open), so the shard-local drain of window
    k overlaps the wire time of window k-1's exchange, and the window
    barrier pmin never waits on an all_to_all completing.

    `sent_min` is the min time of the events this shard SENT in that
    deferred round (i64 max when none). The global pmin over per-shard
    sent_min equals the global pmin over per-shard received mins — the
    all_to_all only permutes the same [S, R] blocks — so `_next_time`
    can fold the in-flight events into the barrier without a data
    dependence on the collective's result.

    Deferral is exact, not approximate: every delivery point sits in a
    gap where no other queue operation runs (cond/flag evaluations only
    read, and cross-window events are clamped >= the sending window's
    end so they can never change a drain flag), and `queue_push` is
    push-order-insensitive including its capacity drops — so the queue
    trajectory, drops included, is bit-identical to immediate delivery
    and therefore to the single-device run.
    """

    bucket: Events  # [S, R] received, undelivered cross-shard events
    # i64[1], not a scalar: per-shard private state must shard on the
    # mesh axis across the shard_map boundary (a scalar would be forced
    # into a replicated P() out_spec, which this value is not)
    sent_min: jax.Array  # i64[1] min time sent in the deferred round

    @staticmethod
    def create(n_shards: int, r: int, n_args: int = N_ARGS) -> "ExchangeBuf":
        return ExchangeBuf(
            bucket=Events.empty((n_shards, r), n_args=n_args),
            sent_min=jnp.full((1,), TIME_INVALID, jnp.int64),
        )


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class EngineState:
    """Complete simulation state for one shard: a pure pytree.

    Because state is a pytree of arrays, checkpoint/resume is trivial
    (serialize the pytree) — a capability the reference lacks entirely
    (SURVEY.md §5 "Checkpoint / resume: Absent").
    """

    now: jax.Array  # i64[] current window start (replicated)
    queues: EventQueue
    hosts: Any  # user pytree, every leaf [H, ...]
    src_seq: jax.Array  # i32[H] per-source sequence counters
    exec_cnt: jax.Array  # i32[H] per-host executed-event counters (RNG)
    stats: Stats
    cpu_free: jax.Array  # i64[H] virtual-CPU available-from time
    # last fault-schedule epoch whose transitions (crash wipes, restart
    # re-templating, bandwidth rescales) have been applied; always 0
    # when no fault schedule is configured
    fault_epoch: jax.Array  # i32[] (replicated)
    # device-side event-trace ring (shadow_tpu.obs.trace.TraceRing) or
    # None when EngineConfig.trace == 0 — None contributes zero pytree
    # leaves, keeping the compiled program and checkpoint layout
    # identical to a trace-free build
    trace: Any = None
    # in-flight cross-shard exchange buffer (ExchangeBuf) or None when
    # unsharded — None contributes zero pytree leaves, so single-device
    # programs and checkpoints are untouched by the sharded overlap
    xchg: Any = None
    # sim-time analytics histograms (shadow_tpu.obs.stats.StatPlane)
    # or None when EngineConfig.stats == 0 — None contributes zero
    # pytree leaves, same zero-cost discipline as `trace`
    splane: Any = None


def state_summary(state: EngineState) -> dict:
    """Cheap host-side progress snapshot of an EngineState.

    One batched device_get of a handful of scalars — safe to call at
    every window boundary. This is what the supervised-run layer
    (shadow_tpu/runtime/) pets its watchdog with and what the stall
    diagnostic bundle records as "last known progress": the frontier
    (clock) time, the window count, and the executed-event total.
    """
    now, windows, executed, sweeps, drops = jax.device_get((  # shadowlint: no-deadline=diagnostic summary helper; not on the supervised loop
        state.now, state.stats.n_windows, state.stats.n_executed.sum(),
        state.stats.n_sweeps, state.queues.drops.sum(),
    ))
    out = {
        "now_ns": int(now),
        "windows": int(windows),
        "executed": int(executed),
        "sweeps": int(sweeps),
        "queue_drops": int(drops),
    }
    ring = state.queues.spill
    if ring is not None:
        spilled, lost, hwm = jax.device_get((  # shadowlint: no-deadline=diagnostic summary helper; not on the supervised loop
            ring.n_spilled.sum(), ring.n_lost.sum(), ring.fill_hwm.max(),
        ))
        out["spilled"] = int(spilled)
        out["spill_lost"] = int(lost)
        out["fill_hwm"] = int(hwm)
    return out


# Handler signature: (host_state_slice, ev: Events scalar, key) ->
#                    (host_state_slice', Emit)
Handler = Callable[[Any, Events, jax.Array], tuple[Any, Emit]]


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    n_hosts: int  # hosts on this shard
    capacity: int  # event queue slots per host
    lookahead: int  # conservative window width, ns (min cross-host latency)
    max_emit: int = 2  # K: max events emitted per handler invocation
    n_args: int = N_ARGS
    seed: int = 0
    axis_name: str | None = None  # mesh axis hosts are sharded over
    n_shards: int = 1  # static mesh axis size (1 when unsharded)
    drain_batch: int = 32  # B: frontier events extracted per host per sweep
    route_bucket: int = 0  # per-peer all_to_all bucket slots (0 = auto)
    stage_width: int = 0  # staging slots per host (0 = auto: B + 4K)
    # Device-side event tracing (shadow_tpu.obs.trace): records per host
    # the ring holds between drains. 0 (the default) compiles the trace
    # path away entirely — EngineState.trace is None (a leaf-free pytree
    # subtree), so the jitted program and the checkpoint leaf list are
    # identical to a trace-free build.
    trace: int = 0
    # args column holding the payload-length word for trace records
    # (A_LEN for the packet stack; harmless 0 for bare-engine models)
    trace_len_arg: int = 0
    # Overflow-spill ring slots per host (shadow_tpu.runtime.pressure):
    # queue evictions land in a per-host device ring that a host-side
    # reservoir harvests at window boundaries instead of being dropped.
    # 0 (the default) compiles the spill path away entirely —
    # EventQueue.spill is None (a leaf-free pytree subtree), so the
    # jitted program and the checkpoint leaf list are identical to a
    # spill-free build, the same zero-cost discipline as `trace`.
    spill: int = 0
    # Burst delivery: fold contiguous same-flow packet arrivals staged in
    # one sweep into a single multi-segment event — the chained drain's
    # sequential depth is the busiest host's event count, and TCP data
    # bursts are most of it. None disables. The tuple is a static
    # descriptor supplied by the stack layer:
    #   (kind, seq_arg, len_arg, sport_arg, dport_arg, meta_arg,
    #    proto, flags_excl_mask, mss, ctl_cols)
    # ctl_cols: arg indices whose folded value comes from the run's
    # LAST (highest-seq) member as one consistent snapshot — cumulative
    # ack, window advertisement, ts echo, and the SACK words, whose
    # bits are relative to their own segment's ack and must never be
    # paired with another segment's ack value.
    # Eligible events (matching kind/proto, none of the excluded flags,
    # 0 < len <= mss) that form a strictly seq-contiguous run of one
    # (src, sport, dport) flow collapse into the run head: its length
    # word becomes total_bytes | (n_segments << 24), its time the run's
    # earliest. PATH loss is exact (reliability was rolled per packet
    # at send time, before folding); receiver-side drop-tail and CoDel
    # verdicts coarsen to one per burst, and absorbed segments' timing
    # coarsens by at most the window width — the same tradeoff class as
    # Stack(fuse_rx=True). Dup-ACK counting is burst-exact: an ACK
    # answering a fold carries its segment count, so the peer's fast
    # retransmit fires at the same byte position as unfolded.
    burst: tuple | None = None
    # Queue-merge kernel for queue_push (core.events): "xla" (default)
    # lowers the densify + rotate + merge as plain XLA ops; "pallas"
    # fuses them into one Pallas kernel call (core.merge_pallas,
    # interpret-mode off-TPU). The two are bit-identical by construction
    # and pinned so by tests/test_kernel_equivalence.py.
    kernel: str = "xla"
    # Frontier run batching: the THIRD drain contract, between the fully
    # chained path and the commutative batch_handler path. When > 0 (and
    # no batch_handler is installed) the window drain runs
    # `_drain_window_frontier`: per round each host's staged events are
    # key-sorted once and a RUN — the maximal prefix of equal-time,
    # same-kind events, capped at this many positions — executes through
    # a sequential position fold whose per-step cost is only the handler
    # pass + routing; the per-event staging bookkeeping the chained path
    # pays every step (min-key selection, rank-matched append, trace
    # append) amortizes to once per round. Results are BIT-IDENTICAL to
    # the chained drain (tests/test_model_batching.py pins state, emit
    # order, and trace records); only the sweep's sequential decomposition
    # changes, so stats.n_inner_steps counts fold positions as before but
    # reaches the same total along fewer synchronization points.
    # Soundness needs every LOCAL emit scheduled at dt >= 1 (the
    # transport/model tier declares this; sim.build_simulation refuses
    # configs that cannot) so in-round emits can never precede a run
    # member. 0 (the default) compiles the frontier path away entirely:
    # the lowered program is byte-identical to a knob-free build.
    frontier: int = 0
    # Sim-time analytics plane (shadow_tpu.obs.stats): when > 0 the
    # window loop streams log2 histograms of event wait time, network
    # latency, per-window host occupancy, queue fill at pop, and
    # frontier run length into device-array StatPlane leaves, across
    # all three drain contracts. 0 (the default) compiles the plane
    # away entirely — EngineState.splane is None (a leaf-free pytree
    # subtree), the same zero-cost discipline as `trace`/`spill`.
    stats: int = 0

    def __post_init__(self):
        if self.kernel not in ("xla", "pallas"):
            raise ValueError(
                f"kernel must be 'xla' or 'pallas', got {self.kernel!r}"
            )
        # a window of width 0 can never drain an event: the compiled outer
        # loop would spin forever on-device with no Python escape. The
        # reference bounds runahead below by 1ms for the same reason
        # (master.c:133-159 minTimeJump floor).
        if self.lookahead < 1:
            raise ValueError(f"lookahead must be >= 1 ns, got {self.lookahead}")
        # a non-positive bucket can never send an event: the exchange loop
        # would spin forever on-device with no Python escape
        if self.route_bucket < 0:
            raise ValueError(
                f"route_bucket must be >= 0, got {self.route_bucket}"
            )
        if self.trace < 0:
            raise ValueError(f"trace must be >= 0, got {self.trace}")
        if self.spill < 0:
            raise ValueError(f"spill must be >= 0, got {self.spill}")
        if not 0 <= self.trace_len_arg < self.n_args:
            raise ValueError(
                f"trace_len_arg {self.trace_len_arg} outside "
                f"[0, {self.n_args})"
            )
        if self.burst is not None and self.eff_stage_width > BURST_NSEG_MAX:
            # the fold packs its run count into bits 24..30 of the
            # length word; a wider staging buffer could form runs that
            # silently overflow into the sign bit — refuse loudly
            raise ValueError(
                f"burst folding requires stage_width <= {BURST_NSEG_MAX} "
                f"(got {self.eff_stage_width}); shrink drain_batch/"
                "stage_width or disable burst"
            )
        if self.frontier < 0:
            raise ValueError(f"frontier must be >= 0, got {self.frontier}")
        if self.stats < 0:
            raise ValueError(f"stats must be >= 0, got {self.stats}")
        if self.stage_width and self.stage_width < self.eff_drain_batch + self.max_emit:
            # staging must hold a full frontier dump plus one handler's
            # emits, or the chained drain could stall with zero headroom
            raise ValueError(
                f"stage_width {self.stage_width} < drain_batch "
                f"{self.eff_drain_batch} + max_emit {self.max_emit}"
            )

    @property
    def eff_drain_batch(self) -> int:
        return max(1, min(self.drain_batch, self.capacity))

    @property
    def eff_stage_width(self) -> int:
        return self.stage_width or (self.eff_drain_batch + 4 * self.max_emit)


def _kind_cost(cpu_cost: jax.Array, kind: jax.Array) -> jax.Array:
    """Per-event cost from a [..., NK] cost table by event kind, as a
    one-hot select (computed-index gathers like take_along_axis are far
    slower than elementwise work on TPU at engine batch sizes)."""
    nk = cpu_cost.shape[-1]
    kidx = jnp.clip(kind, 0, nk - 1)
    onehot = kidx[..., None] == jnp.arange(nk, dtype=kind.dtype)
    # cpu_cost [H, NK] broadcasts against kidx [H, ...]: align trailing NK
    extra = kidx.ndim - (cpu_cost.ndim - 1)
    table = cpu_cost.reshape(
        cpu_cost.shape[:1] + (1,) * extra + cpu_cost.shape[1:]
    )
    return jnp.sum(jnp.where(onehot, table, 0), axis=-1)


def _select_rows(mask: jax.Array, new: Any, old: Any) -> Any:
    """Per-host select across two equal-structure pytrees ([H, ...] leaves)."""

    def sel(n, o):
        m = mask.reshape(mask.shape + (1,) * (n.ndim - 1))
        return jnp.where(m, n, o)

    return jax.tree.map(sel, new, old)


class Engine:
    """Builds jittable window-step / run functions over a handler table.

    `network.route(src_gid, dst_gid) -> (latency_ns i64, reliability f32,
    jitter_ns i64)` supplies the topology model (element-wise over
    arrays); a truthy `network.has_jitter` enables the per-packet jitter
    roll.
    """

    def __init__(self, cfg: EngineConfig, handlers: Sequence[Handler], network,
                 cpu_cost=None, batch_handler=None, faults=None,
                 fault_reset=None, frontier_kinds=None):
        """`cpu_cost`: optional per-event virtual-CPU nanoseconds, indexed
        by GLOBAL host id (the reference's per-host CPU model delays
        event execution while the virtual CPU is busy — cpu.c:56-107,
        event.c:75-84). Two shapes:
          i64[H_global]          — uniform cost per event, or
          i64[H_global, n_kinds] — per-KIND cost (the analog of the
        reference charging each task its measured execution time rather
        than a flat constant). Global indexing lets one engine closure
        serve every shard: each window gathers its own hosts' costs by
        gid. None or zeros disables the model with no overhead in
        results.

        `batch_handler`: optional commutative fast path. When set, the
        window drain executes each host's whole below-barrier frontier in
        ONE vmapped call instead of one sequential step per event:
        `batch_handler(host_state_slice, evs: Events with [B]-leading
        fields, keys[B]) -> (host_state_slice', Emit with [B, K] fields)`.
        Only valid when (a) the state transition commutes across the
        events of one window (order-insensitive folds like counters), and
        (b) handlers never emit local events below the window barrier —
        both hold for PHOLD-style models. Per-position RNG keys derive
        from (gid, exec_cnt + position), so results remain deterministic
        and sharding-independent.

        The CPU model composes with the batched drain at whole-frontier
        granularity: a host whose virtual CPU is busy past the barrier
        runs nothing this window, and each executed frontier advances
        cpu_free by the SUM of its events' costs — the batched analog of
        the reference's delay rounding (cpu.c:85-95 rounds accumulated
        delay to a precision grid rather than modeling each instant).

        `faults`: optional CompiledFaults (shadow_tpu.faults). Baked
        into the compiled step like `network` is — the schedule is
        constants, only the `fault_epoch` watermark is state. Crashed
        hosts stop executing (events quarantined), packets to/through
        faulted links or dead destinations drop with attribution, and
        epoch transitions wipe crashed hosts' queues and re-template
        their state rows from `fault_reset` (a global-shaped hosts
        pytree: the same initial SimHost the simulation was built with,
        so a restarted host comes back with fresh listening sockets).

        `frontier_kinds`: static tuple of event kinds allowed to form
        multi-position runs under the frontier drain (cfg.frontier > 0).
        Kinds outside the set still execute — one position per round, in
        exact chained order (the explicit in-host ordering fold) — they
        just never amortize. None (the default) allows every kind.
        Ignored when cfg.frontier == 0."""
        self.cfg = cfg
        self.handlers = tuple(handlers)
        self.network = network
        self.batch_handler = batch_handler
        self._frontier_kinds = (
            tuple(sorted({int(x) for x in frontier_kinds}))
            if frontier_kinds is not None else None
        )
        self._base_key = srng.root_key(cfg.seed)
        hg = cfg.n_hosts * cfg.n_shards
        nk = len(self.handlers)
        if cpu_cost is None:
            cpu_cost = jnp.zeros((hg, nk), jnp.int64)
        cpu_cost = jnp.asarray(cpu_cost, jnp.int64)
        if cpu_cost.shape not in ((hg,), (hg, nk)):
            raise ValueError(
                f"cpu_cost must be [H_global]={hg} or [H_global, "
                f"n_kinds]=({hg}, {nk}), got shape {cpu_cost.shape}"
            )
        if cpu_cost.ndim == 1:
            cpu_cost = jnp.broadcast_to(cpu_cost[:, None], (hg, nk))
        self.cpu_cost = cpu_cost
        # static fast path: with no CPU model (the default), skip every
        # cpu_free compare/update in the compiled step — profiled at ~20%
        # of the PHOLD sweep as a [H*B]-lane gather of an all-zeros table
        self._cpu_enabled = bool(jax.device_get((cpu_cost != 0).any()))  # shadowlint: no-deadline=build-time constant fetch; no collectives in flight
        # jitter rolls cost an extra uniform per emit row; skip them
        # entirely for jitter-free networks
        self._use_jitter = bool(getattr(network, "has_jitter", False))
        # device-side event tracing: a static flag like the CPU/jitter
        # paths — trace=0 builds carry no ring and compile no appends
        self._trace = cfg.trace > 0
        # sim-time analytics histograms: same static-flag discipline;
        # stats=0 builds carry no StatPlane and compile no observes
        self._stats = cfg.stats > 0
        # fault schedule: static sub-flags keep the no-fault (and
        # partial-fault) compiled programs free of dead overlay work
        self.faults = faults
        self.fault_reset = fault_reset
        self._f_crash = bool(faults is not None and faults.has_crash)
        self._f_link = bool(faults is not None and faults.has_link)
        self._f_bw = bool(faults is not None and faults.has_bw)
        if (self._f_crash or self._f_bw) and fault_reset is None:
            raise ValueError(
                "faults with crashes or bandwidth changes need a "
                "fault_reset template (the initial hosts pytree)"
            )
        # static all_to_all bucket width: ONE width for every exchange in
        # the program, because the deferred recv bucket is carried state
        # (ExchangeBuf) whose shape must agree across sweeps and across
        # the narrow/wide flush branches. Sized off the widest flat batch
        # either drain path pushes, with the same quarter-of-uniform
        # default the per-call sizing used.
        if cfg.axis_name is not None:
            if batch_handler is not None:
                m_ref = cfg.n_hosts * cfg.eff_drain_batch * cfg.max_emit
            else:
                m_ref = cfg.n_hosts * max(
                    cfg.eff_stage_width, cfg.eff_drain_batch + cfg.max_emit
                )
            self._xchg_r = cfg.route_bucket or max(
                16, -(-m_ref // cfg.n_shards) // 4
            )
        else:
            self._xchg_r = 0

    # -- collectives (identity when unsharded) ------------------------------
    def _gmin(self, x):
        if self.cfg.axis_name is not None:
            return jax.lax.pmin(x, self.cfg.axis_name)
        return x

    def _gany(self, x: jax.Array) -> jax.Array:
        if self.cfg.axis_name is not None:
            return jax.lax.psum(x.astype(jnp.int32), self.cfg.axis_name) > 0
        return x

    def _gsum(self, x: jax.Array) -> jax.Array:
        if self.cfg.axis_name is not None:
            return jax.lax.psum(x, self.cfg.axis_name)
        return x

    def _drain_flag(self, q: EventQueue, cpu_free, window_end) -> jax.Array:
        """True while any host (globally) still has an executable event
        below the window barrier. Computed in loop BODIES and threaded
        through the carry — never evaluated inside a while_loop cond —
        so the lowered predicate contains no collective (the 0.4.37
        experimental-shard_map miscompile leaks device 0's carry when a
        collective sits inside a cond; see docs/12-Sharding.md)."""
        nxt = q.min_time()
        if self._cpu_enabled:
            nxt = jnp.maximum(nxt, cpu_free)
        return self._gany(jnp.any(nxt < window_end))

    def _xchg_deliver(self, q: EventQueue, xchg, host0):
        """Merge the in-flight exchange buffer into the local queue and
        return it emptied. The guard predicate is shard-local and both
        branches are collective-free, so per-shard divergence is safe
        under shard_map; the common no-cross-traffic case skips the
        queue merge entirely."""
        if xchg is None:
            return q, xchg
        flat = xchg.bucket.flatten()
        valid = flat.time != TIME_INVALID
        q = jax.lax.cond(
            jnp.any(valid),
            lambda q: queue_push(q, flat, valid, host0, self.cfg.kernel),
            lambda q: q,
            q,
        )
        return q, ExchangeBuf.create(
            self.cfg.n_shards, self._xchg_r, self.cfg.n_args
        )

    def _exchange_push(self, q: EventQueue, xchg, ev: Events,
                       mask: jax.Array, host0):
        """Push a flat routed batch, delivering cross-shard events by
        bucketed all_to_all.

        Hosts are block-partitioned over the mesh axis (gid // n_hosts is
        the owning shard), so same-shard events push directly. Cross-shard
        events are grouped by destination shard into a [S, R] bucket and
        exchanged with `lax.all_to_all`; if any destination's load exceeds
        the R bucket slots, the loop runs another round with the remainder
        — lossless, and traffic scales with the cross-shard packet count
        rather than total packets (the TPU-native replacement for the
        reference's shared-memory scheduler_push across threads,
        scheduler.c:342-360; SURVEY.md §2.4).

        Each round's received bucket is NOT pushed in that round: it
        lands in `xchg` and is merged at the top of the NEXT round's
        body — and the final round's recv rides out in the returned
        ExchangeBuf to the next sweep or window (double buffering). The
        loop predicate reads a carried flag; the psum deciding another
        round runs in the body (see `_drain_flag`).
        """
        z = jnp.zeros((), jnp.int64)
        if self.cfg.axis_name is None:
            return queue_push(q, ev, mask, host0, self.cfg.kernel), xchg, z, z
        cfg = self.cfg
        ax = cfg.axis_name
        h, s = cfg.n_hosts, cfg.n_shards
        my = jax.lax.axis_index(ax).astype(jnp.int32)
        m = ev.time.shape[0]
        # engine-level static bucket width (see __init__): a quarter of
        # the widest uniform-traffic case — small enough that lightly-
        # coupled shards don't pay Θ(batch) ICI traffic every iteration,
        # large enough that uniform workloads rarely need a second round
        # (overflow just loops, lossless)
        r = self._xchg_r

        dshard = ev.dst // jnp.int32(h)
        in_range = (dshard >= 0) & (dshard < s)
        is_local = mask & (dshard == my)
        q = queue_push(q, ev, is_local, host0, cfg.kernel)
        remaining = mask & in_range & ~is_local

        pos = jnp.arange(m, dtype=jnp.int32)

        def cond(carry):
            return carry[0]

        def body(carry):
            _, q, xchg, rem, rounds = carry
            q, xchg = self._xchg_deliver(q, xchg, host0)
            dkey = jnp.where(rem, dshard, s)
            order = jnp.argsort(dkey, stable=True)
            sd = dkey[order]
            rank = pos - group_run_starts(sd)
            sel = (sd < s) & (rank < r)

            brow = jnp.where(sel, sd, s)
            bcol = jnp.minimum(rank, r - 1)
            evo = ev.at(order)
            bucket = Events(
                time=jnp.full((s, r), TIME_INVALID, jnp.int64)
                .at[brow, bcol].set(evo.time, mode="drop"),
                dst=jnp.zeros((s, r), jnp.int32).at[brow, bcol].set(evo.dst, mode="drop"),
                src=jnp.zeros((s, r), jnp.int32).at[brow, bcol].set(evo.src, mode="drop"),
                seq=jnp.zeros((s, r), jnp.int32).at[brow, bcol].set(evo.seq, mode="drop"),
                kind=jnp.zeros((s, r), jnp.int32).at[brow, bcol].set(evo.kind, mode="drop"),
                args=jnp.zeros((s, r, cfg.n_args), jnp.int32)
                .at[brow, bcol].set(evo.args, mode="drop"),
            )
            recv = jax.tree.map(
                lambda x: jax.lax.all_to_all(x, ax, split_axis=0, concat_axis=0),
                bucket,
            )
            # min over what this shard SENT (pre-exchange) — globally
            # pmin-equivalent to the receiver-side min, with no data
            # dependence on the collective's result
            xchg = ExchangeBuf(
                bucket=recv, sent_min=jnp.min(bucket.time).reshape((1,))
            )
            sent = jnp.zeros((m,), bool).at[order].set(sel)
            rem = rem & ~sent
            return self._gany(jnp.any(rem)), q, xchg, rem, rounds + 1

        # global count (each shard only sees its own outbound packets;
        # the replicated stats scalar needs the psum'd total)
        n_cross = jax.lax.psum(
            jnp.sum(remaining, dtype=jnp.int64), ax
        )
        _, q, xchg, _, rounds = jax.lax.while_loop(
            cond, body,
            (self._gany(jnp.any(remaining)), q, xchg, remaining,
             jnp.zeros((), jnp.int64)),
        )
        return q, xchg, rounds, n_cross

    # -- state construction -------------------------------------------------
    def _trace_slack(self) -> int:
        """Scratch columns past the trace ring's capacity: the widest
        single append either drain path performs, so full-ring overflow
        writes always land in the never-read zone (obs.trace docstring).
        """
        k = self.cfg.max_emit
        if self.batch_handler is not None:
            b = max(1, min(self.cfg.drain_batch, self.cfg.capacity))
            return b * (1 + k)
        if self.cfg.frontier > 0:
            # the frontier drain defers tracing to one append per round:
            # up to `u` positions of (1 exec + K emit) records each
            u = max(1, min(self.cfg.frontier, self.cfg.eff_stage_width))
            return u * (1 + k)
        return 1 + k

    def init_state(self, hosts: Any, initial: Events, host0: int | jax.Array = 0):
        cfg = self.cfg
        q = EventQueue.create(
            cfg.n_hosts, cfg.capacity, cfg.n_args, spill=cfg.spill
        )
        flat = initial.flatten()
        valid = flat.time != TIME_INVALID
        q = queue_push(q, flat, valid, host0, cfg.kernel)
        # start each source's sequence counter past any seq the initial
        # events consumed, so engine-emitted events never reuse a (src, seq)
        # pair — uniqueness is what makes the (time, src, seq) total order
        # deterministic (event.c:110-153)
        local_src = flat.src - jnp.asarray(host0, jnp.int32)
        seq0 = jnp.zeros((cfg.n_hosts,), jnp.int32).at[
            jnp.where(valid & (local_src >= 0) & (local_src < cfg.n_hosts),
                      local_src, cfg.n_hosts)
        ].max(flat.seq + 1, mode="drop")
        trace = None
        if self._trace:
            from shadow_tpu.obs.trace import TraceRing

            trace = TraceRing.create(
                cfg.n_hosts, cfg.trace, self._trace_slack()
            )
        xchg = None
        if cfg.axis_name is not None:
            xchg = ExchangeBuf.create(cfg.n_shards, self._xchg_r, cfg.n_args)
        splane = None
        if self._stats:
            from shadow_tpu.obs.stats import StatPlane

            splane = StatPlane.create(cfg.n_hosts)
        return EngineState(
            now=jnp.zeros((), jnp.int64),
            queues=q,
            hosts=hosts,
            src_seq=seq0,
            exec_cnt=jnp.zeros((cfg.n_hosts,), jnp.int32),
            stats=Stats.create(cfg.n_hosts, len(self.handlers)),
            cpu_free=jnp.zeros((cfg.n_hosts,), jnp.int64),
            fault_epoch=jnp.zeros((), jnp.int32),
            trace=trace,
            xchg=xchg,
            splane=splane,
        )

    # -- per-lane rebinding (scenario fleets) --------------------------------
    def bind_lane(self, *, base_key=None, faults=None, fault_reset=None,
                  network=None):
        """Shallow-copy this engine with per-lane scenario bindings.

        The fleet tier (runtime/fleet.py) calls this INSIDE a vmapped
        function, so every value may be a tracer: the RNG root key, the
        CompiledFaults arrays, and the network wrapper's scale become
        per-lane traced inputs instead of baked closure constants —
        the values the engine computes from them are identical either
        way (rng.root_key(seed) traced vs static yields the same key),
        which is what makes a fleet lane bit-identical to its solo run.
        The base engine object is never mutated, so its default (non-
        fleet) lowering stays byte-identical — the zero-cost pin.
        """
        eng = copy.copy(self)
        if base_key is not None:
            eng._base_key = base_key
        if fault_reset is not None:
            eng.fault_reset = fault_reset
        if faults is not None:
            eng.faults = faults
            eng._f_crash = bool(faults.has_crash)
            eng._f_link = bool(faults.has_link)
            eng._f_bw = bool(faults.has_bw)
            if (eng._f_crash or eng._f_bw) and eng.fault_reset is None:
                raise ValueError(
                    "faults with crashes or bandwidth changes need a "
                    "fault_reset template (the initial hosts pytree)"
                )
        if network is not None:
            eng.network = network
            eng._use_jitter = bool(getattr(network, "has_jitter", False))
        return eng

    # -- fault-schedule helpers ---------------------------------------------
    def _alive_slice(self, host0):
        """[H, T] per-shard liveness table (bool), sliced from the global
        [T, Hg] schedule. Constant per drain call; the per-event check is
        then a one-hot select over the tiny epoch axis."""
        f = self.faults
        return jax.lax.dynamic_slice_in_dim(
            f.alive, host0, self.cfg.n_hosts, axis=1
        ).T

    def _alive_at(self, al_sh, t):
        """bool liveness per host at time(s) t. `al_sh` is _alive_slice's
        [H, T]; t is [H] or [H, B] (leading host axis)."""
        f = self.faults
        e = f.epoch_of(t)  # [H] or [H, B]
        tt = f.times.shape[0]
        onehot = e[..., None] == jnp.arange(tt, dtype=jnp.int32)
        if t.ndim == 1:
            return jnp.any(onehot & al_sh, axis=-1)
        return jnp.any(onehot & al_sh[:, None, :], axis=-1)

    # -- shared emit routing -------------------------------------------------
    def _route(self, emit: Emit, base_time, gids, window_end, rkeys, emask,
               seq):
        """Route an [N, K] emit batch: local tasks keep their time;
        network sends add path latency (+jitter), roll reliability, and
        clamp to the window barrier (worker_sendPacket semantics,
        worker.c:243-304; self-addressed sends traverse the topology
        self-loop like any other packet).

        The fault schedule (when compiled in) overlays the lookup: path
        latency scales by the active epoch's [G, G] factor BEFORE the
        barrier clamp (so even scaled-to-zero latency stays causal),
        and an extra pass-probability roll — lane offset 2K, disjoint
        from the reliability (0) and jitter (K) lanes — plus a
        destination-liveness check at the ARRIVAL epoch drop packets
        with their own attribution counter.

        Returns (Events[N, K], final_mask, dropped, fdropped, t,
        is_local)."""
        n, k = emit.dst.shape
        self_gid = gids[:, None]
        is_local = emit.local
        dst = jnp.where(is_local, self_gid, emit.dst)
        dt = jnp.maximum(emit.dt, 0)
        lat, rel, jit = self.network.route(
            jnp.broadcast_to(self_gid, (n, k)), dst
        )

        def rolls(offset):
            # one fused elementwise threefry pass over all [N, K] lanes
            return srng.uniform_lanes(rkeys, k, offset)

        t = base_time[:, None] + dt
        if self._f_link:
            f = self.faults
            hg = f.fgrp.shape[0]
            dstc = jnp.clip(dst, 0, hg - 1)
            gs = f.fgrp[jnp.broadcast_to(jnp.clip(self_gid, 0, hg - 1),
                                         (n, k))]
            gd = f.fgrp[dstc]
            e_s = f.epoch_of(t)  # link state is read at SEND time
            lat = lat * f.lat_milli[e_s, gs, gd] // 1000
        if self._use_jitter:
            # seeded symmetric latency noise, per packet (the reference
            # carries per-edge jitter attrs, topology.c:101-105; paths
            # accumulate them like latency)
            uj = rolls(k)
            lat = jnp.maximum(
                lat + ((uj * 2.0 - 1.0) * jit.astype(jnp.float32)).astype(
                    jnp.int64
                ),
                0,
            )
        t_remote = jnp.maximum(t + lat, window_end)

        u = rolls(0)
        dropped = (~is_local) & (u >= rel) & emask
        fdropped = jnp.zeros_like(dropped)
        if self._f_link:
            u2 = rolls(2 * k)
            fdropped = u2 >= f.passp[e_s, gs, gd]
        if self._f_crash:
            f = self.faults
            hg = f.fgrp.shape[0]
            dstc = jnp.clip(dst, 0, hg - 1)
            # a packet addressed to a host that is dead when it ARRIVES
            # is lost — the NIC it would land on does not exist
            e_a = f.epoch_of(t_remote)
            fdropped = fdropped | ~f.alive[e_a, dstc]
        if self._f_link or self._f_crash:
            fdropped = fdropped & (~is_local) & emask & ~dropped
        t = jnp.where(is_local, t, t_remote)
        final_mask = emask & ~dropped & ~fdropped

        out = Events(
            time=jnp.where(final_mask, t, TIME_INVALID),
            dst=dst,
            src=jnp.broadcast_to(self_gid, (n, k)).astype(jnp.int32),
            seq=seq,
            kind=emit.kind,
            args=emit.args,
        )
        return out, final_mask, dropped, fdropped, t, is_local

    # -- execute one frontier position across all hosts ---------------------
    def _execute_step(self, hosts, src_seq, exec_cnt, stats, ev: Events,
                      active: jax.Array, window_end: jax.Array,
                      gids: jax.Array, trace=None, splane=None):
        """Run handlers for one event per host (masked), route the emits.

        Returns (hosts', src_seq', exec_cnt', stats', routed Events[H, K],
        final_mask[H, K], trace', splane'). `trace` passes through
        untouched (None) unless tracing is compiled in, in which case
        one append records the executed event plus every non-local
        emit; `splane` likewise accumulates the wait/net histograms
        only when the stats plane is compiled in.
        """
        cfg = self.cfg
        h, k = cfg.n_hosts, cfg.max_emit

        hkeys, rkeys = srng.event_keys(self._base_key, gids, exec_cnt)

        def per_host(hs, e, key):
            branches = tuple(
                (lambda fn: lambda: _pad(fn(hs, e, key), k))(fn) for fn in self.handlers
            )

            def _pad(res, kk):
                hs2, em = res
                return hs2, em.pad_to(kk)

            idx = jnp.clip(e.kind, 0, len(branches) - 1)
            return jax.lax.switch(idx, branches)

        hosts2, emit = jax.vmap(per_host)(hosts, ev, hkeys)
        hosts = _select_rows(active, hosts2, hosts)
        emask = emit.mask & active[:, None]

        # per-source sequence numbers, dense over the masked emits so the
        # numbering is independent of K padding (event.c:110-153 tie-break)
        inc = emask.astype(jnp.int32)
        within = jnp.cumsum(inc, axis=1) - inc
        seq = src_seq[:, None] + within
        src_seq = src_seq + jnp.sum(inc, axis=1, dtype=jnp.int32)

        out, final_mask, dropped, fdropped, _t, _is_local = self._route(
            emit, ev.time, gids, window_end, rkeys, emask, seq
        )

        if self._stats and splane is not None:
            # every delivered emit executes at its routed time _t, so
            # _t - now IS exec-minus-enqueue sim time; the non-local
            # subset is the send->exec network latency
            delta = _t - ev.time[:, None]
            splane = splane.observe("wait", delta, final_mask)
            splane = splane.observe("net", delta, final_mask & ~_is_local)

        if self._trace and trace is not None:
            from shadow_tpu.obs.trace import (
                OP_DROP, OP_EXEC, OP_FDROP, OP_SEND, trace_append,
            )

            la = cfg.trace_len_arg
            # one width-(1+K) append: the executed event (op EXEC, on the
            # executing host's row) + its non-local emits (op SEND, or
            # DROP/FDROP with the loss attribution, on the source row at
            # emission time — the matching EXEC on the destination row is
            # the arrival, and (src, seq) ties the pair into a flow)
            op_send = jnp.where(
                dropped, OP_DROP,
                jnp.where(fdropped, OP_FDROP, OP_SEND),
            ).astype(jnp.int32)
            col = lambda a: a[:, None]
            trace = trace_append(
                trace, cfg.trace,
                time=jnp.concatenate(
                    [col(ev.time), jnp.broadcast_to(col(ev.time), (h, k))], 1
                ),
                src=jnp.concatenate([col(ev.src), out.src], 1),
                dst=jnp.concatenate([col(ev.dst), out.dst], 1),
                kind=jnp.concatenate([col(ev.kind), out.kind], 1),
                plen=jnp.concatenate(
                    [ev.args[:, la:la + 1], out.args[:, :, la]], 1
                ),
                seq=jnp.concatenate([col(ev.seq), out.seq], 1),
                op=jnp.concatenate(
                    [jnp.full((h, 1), OP_EXEC, jnp.int32), op_send], 1
                ),
                mask=jnp.concatenate(
                    [col(active), emask & ~_is_local], 1
                ),
            )

        exec_cnt = exec_cnt + active.astype(jnp.int32)
        stats = dataclasses.replace(
            stats,
            n_executed=stats.n_executed + active,
            n_emitted=stats.n_emitted + jnp.sum(inc, axis=1, dtype=jnp.int64),
            n_net_dropped=stats.n_net_dropped + jnp.sum(dropped, axis=1, dtype=jnp.int64),
            n_fault_dropped=stats.n_fault_dropped
            + jnp.sum(fdropped, axis=1, dtype=jnp.int64),
            n_by_kind=stats.n_by_kind + (
                jax.nn.one_hot(
                    jnp.clip(ev.kind, 0, len(self.handlers) - 1),
                    len(self.handlers), dtype=jnp.int64,
                )
                * active[:, None]
            ),
        )
        return (hosts, src_seq, exec_cnt, stats, out, final_mask, trace,
                splane)

    # -- commutative fast path: whole frontiers in one vmapped call ---------
    def _drain_window_batched(self, st: EngineState, window_end, host0):
        """Window drain for batch_handler engines: every below-barrier
        frontier event executes in a single [H, B]-shaped handler call
        per sweep — no sequential inner loop. Valid only under the
        batch_handler contract (commutative state folds, no local
        below-barrier emits); per-position keys keep determinism."""
        cfg = self.cfg
        h, k, c = cfg.n_hosts, cfg.max_emit, cfg.capacity
        b = max(1, min(cfg.drain_batch, c))
        gids = host0 + jnp.arange(h, dtype=jnp.int32)
        cpu_cost = self.cpu_cost[gids]  # [H, NK]
        al_sh = self._alive_slice(host0) if self._f_crash else None

        def outer_cond(carry):
            # carried flag: the psum/any deciding another sweep runs at
            # the END of the body (`_drain_flag`), never in this cond —
            # collective-free predicates are what keep the sharded
            # lowering correct on jax 0.4.37 (see docs/12-Sharding.md)
            return carry[0]

        def outer_body(carry):
            (_, q, xchg, hosts, src_seq, exec_cnt, stats, cpu_free, trace,
             splane) = carry
            # merge window k-1's in-flight exchange before reading the
            # frontier: the gap since the sending sweep's push contains
            # no queue operation, so deferred delivery is bit-identical
            q, xchg = self._xchg_deliver(q, xchg, host0)
            bt = q.time[:, :b]
            # a host whose virtual CPU is busy past the barrier runs
            # nothing this window (whole-frontier granularity)
            bvalid = bt < window_end  # a prefix: rows are key-sorted
            if self._cpu_enabled:
                bvalid = bvalid & (cpu_free[:, None] < window_end)
            if self._stats and splane is not None:
                # queue fill at pop, pre-clear (chained-drain semantics:
                # hosts popping at least one event this sweep)
                splane = splane.observe(
                    "qfill",
                    jnp.sum(q.time != TIME_INVALID, axis=1,
                            dtype=jnp.int64),
                    jnp.any(bvalid, axis=1),
                )
            # crashed hosts consume (quarantine) their frontier without
            # executing it: rows still clear below, handlers see
            # TIME_INVALID
            if self._f_crash:
                run = bvalid & self._alive_at(
                    al_sh, jnp.where(bvalid, bt, 0)
                )
            else:
                run = bvalid
            evs = Events(
                time=jnp.where(run, bt, TIME_INVALID),
                dst=jnp.broadcast_to(gids[:, None], (h, b)),
                src=q.src[:, :b],
                seq=q.seq[:, :b],
                kind=q.kind[:, :b],
                args=q.args[:, :b],
            )
            cnts = exec_cnt[:, None] + jnp.arange(b, dtype=jnp.int32)[None, :]
            hk, rk = srng.event_keys(
                self._base_key,
                jnp.broadcast_to(gids[:, None], (h, b)).reshape(-1),
                cnts.reshape(-1),
            )
            hk = hk.reshape((h, b, 2))

            hosts2, emit = jax.vmap(self.batch_handler)(hosts, evs, hk)
            # n_exec counts the CLEARED prefix (and RNG positions) —
            # quarantined events consume both; n_run counts executions
            n_exec = jnp.sum(bvalid, axis=1, dtype=jnp.int32)
            n_run = jnp.sum(run, axis=1, dtype=jnp.int32)
            hosts = _select_rows(n_run > 0, hosts2, hosts)
            emask = emit.mask & run[:, :, None]

            # dense per-source sequence numbers across the [B, K] lanes
            inc = emask.astype(jnp.int32).reshape(h, b * k)
            within = jnp.cumsum(inc, axis=1) - inc
            seq = (src_seq[:, None] + within).reshape(h, b, k)
            src_seq = src_seq + jnp.sum(inc, axis=1, dtype=jnp.int32)

            flat = lambda a: a.reshape((h * b,) + a.shape[2:])
            em_flat = jax.tree.map(flat, emit)
            out, final_mask, dropped, fdropped, _t, _loc = self._route(
                em_flat,
                evs.time.reshape(-1),
                jnp.broadcast_to(gids[:, None], (h, b)).reshape(-1),
                window_end,
                rk,
                flat(emask),
                flat(seq),
            )

            if self._stats and splane is not None:
                delta = (_t - evs.time.reshape(-1)[:, None]).reshape(
                    h, b * k
                )
                fm = final_mask.reshape(h, b * k)
                splane = splane.observe("wait", delta, fm)
                splane = splane.observe(
                    "net", delta, fm & ~_loc.reshape(h, b * k)
                )

            if self._trace and trace is not None:
                from shadow_tpu.obs.trace import (
                    OP_DROP, OP_EXEC, OP_FDROP, OP_SEND, trace_append,
                )

                la = cfg.trace_len_arg
                # one width-(B + B*K) append per sweep: the executed
                # frontier (EXEC rows) + every non-local emit
                # (SEND/DROP/FDROP rows) — same semantics as the chained
                # path's per-step append in _execute_step
                wide = lambda a: a.reshape(h, b * k)  # [H*B, K] -> [H, BK]
                op_send = jnp.where(
                    dropped, OP_DROP,
                    jnp.where(fdropped, OP_FDROP, OP_SEND),
                ).astype(jnp.int32)
                send_t = jnp.broadcast_to(
                    evs.time[:, :, None], (h, b, k)
                ).reshape(h, b * k)
                trace = trace_append(
                    trace, cfg.trace,
                    time=jnp.concatenate([evs.time, send_t], 1),
                    src=jnp.concatenate([evs.src, wide(out.src)], 1),
                    dst=jnp.concatenate([evs.dst, wide(out.dst)], 1),
                    kind=jnp.concatenate([evs.kind, wide(out.kind)], 1),
                    plen=jnp.concatenate(
                        [evs.args[:, :, la],
                         out.args[:, :, la].reshape(h, b * k)], 1
                    ),
                    seq=jnp.concatenate([evs.seq, wide(out.seq)], 1),
                    op=jnp.concatenate(
                        [jnp.full((h, b), OP_EXEC, jnp.int32),
                         wide(op_send)], 1
                    ),
                    mask=jnp.concatenate(
                        [run, wide(flat(emask) & ~_loc)], 1
                    ),
                )

            exec_cnt = exec_cnt + n_exec
            stats2 = dataclasses.replace(
                stats,
                n_executed=stats.n_executed + n_run,
                n_emitted=stats.n_emitted
                + jnp.sum(inc, axis=1, dtype=jnp.int64),
                n_net_dropped=stats.n_net_dropped
                + jnp.sum(
                    dropped.reshape(h, b * k), axis=1, dtype=jnp.int64
                ),
                n_fault_dropped=stats.n_fault_dropped
                + jnp.sum(
                    fdropped.reshape(h, b * k), axis=1, dtype=jnp.int64
                ),
                n_quarantined=stats.n_quarantined
                + jnp.sum(bvalid & ~run, axis=1, dtype=jnp.int64),
                n_by_kind=stats.n_by_kind + jnp.sum(
                    jax.nn.one_hot(
                        jnp.clip(evs.kind, 0, len(self.handlers) - 1),
                        len(self.handlers), dtype=jnp.int64,
                    )
                    * run[:, :, None],
                    axis=1,
                ),
            )
            if self._cpu_enabled:
                # virtual-CPU charge: the frontier's summed per-kind
                # costs advance cpu_free past its last executed event.
                # One-hot select, not take_along_axis: a computed-index
                # gather here measured ~20% of the whole sweep on TPU
                ev_cost = _kind_cost(cpu_cost, evs.kind)
                total_cost = jnp.sum(
                    jnp.where(run, ev_cost, 0), axis=1
                )
                t_last = jnp.max(jnp.where(run, bt, 0), axis=1)
                cpu_free = jnp.where(
                    total_cost > 0,
                    jnp.maximum(cpu_free, t_last) + total_cost,
                    cpu_free,
                )

            cleared = jnp.arange(c, dtype=jnp.int32)[None, :] < n_exec[:, None]
            q = dataclasses.replace(
                q, time=jnp.where(cleared, TIME_INVALID, q.time)
            )
            q, xchg, xr, nc = self._exchange_push(
                q, xchg, out.flatten(), final_mask.reshape(-1), host0
            )
            stats2 = dataclasses.replace(
                stats2,
                n_sweeps=stats2.n_sweeps + 1,
                n_xchg_rounds=stats2.n_xchg_rounds + xr,
                n_cross_shard=stats2.n_cross_shard + nc,
            )
            more = self._drain_flag(q, cpu_free, window_end)
            return (more, q, xchg, hosts, src_seq, exec_cnt, stats2,
                    cpu_free, trace, splane)

        carry = (self._drain_flag(st.queues, st.cpu_free, window_end),
                 st.queues, st.xchg, st.hosts, st.src_seq, st.exec_cnt,
                 st.stats, st.cpu_free, st.trace, st.splane)
        (_, q, xchg, hosts, src_seq, exec_cnt, stats, cpu_free,
         trace, splane) = jax.lax.while_loop(outer_cond, outer_body, carry)
        if self._cpu_enabled:
            # the barrier's sent_min shortcut cannot see a destination
            # host's busy CPU; flush in-flight events before `_next_time`
            # runs so the max(min_time, cpu_free) defer stays exact
            q, xchg = self._xchg_deliver(q, xchg, host0)
        if self._stats and splane is not None:
            occ = stats.n_executed - st.stats.n_executed
            splane = splane.observe("occ", occ, occ > 0)
        return dataclasses.replace(
            st,
            queues=q,
            hosts=hosts,
            src_seq=src_seq,
            exec_cnt=exec_cnt,
            stats=dataclasses.replace(stats, n_windows=stats.n_windows + 1),
            cpu_free=cpu_free,
            trace=trace,
            xchg=xchg,
            splane=splane,
        )

    # -- staging-buffer helpers (chained drain) ------------------------------
    def _burst_fold(self, stage: Events) -> Events:
        """Collapse contiguous same-flow arrival runs in [H, SW] staging.

        Sort each host's staged events by (flow key, tcp seq); a run of
        eligible events whose seqs chain by +1 (every segment before the
        last full-MSS) folds into its head: length word = total |
        (count << 24), time = run min. Absorbed slots clear. All work is
        one lax.sort plus [H, SW, SW] masked reductions — no scatter.
        Slot order afterwards is arbitrary, which staging permits
        (_stage_min selects by content, _stage_append by free rank).
        """
        (kind, seq_a, len_a, sport_a, dport_a, meta_a, proto, flags_x,
         mss, ctl_cols) = self.cfg.burst
        t = stage.time
        h, sw = t.shape
        meta = stage.args[:, :, meta_a]
        ln = stage.args[:, :, len_a]
        elig = (
            (t != TIME_INVALID)
            & (stage.kind == kind)
            & ((meta & 0x3) == proto)
            & ((meta & flags_x) == 0)
            & (ln > 0) & (ln <= mss)
        )
        i64max = jnp.iinfo(jnp.int64).max
        slot = jnp.arange(sw, dtype=jnp.int64)[None, :]
        flow = (
            (stage.src.astype(jnp.int64) << 32)
            | (stage.args[:, :, sport_a].astype(jnp.int64) << 16)
            | stage.args[:, :, dport_a].astype(jnp.int64)
        )
        k1 = jnp.where(elig, flow, i64max - sw + slot)  # inelig: stable tail
        k2 = jnp.where(elig, stage.args[:, :, seq_a].astype(jnp.int64), 0)
        cols = jax.lax.sort(
            (k1, k2, t, stage.dst, stage.src, stage.seq, stage.kind,
             *[stage.args[:, :, i] for i in range(stage.args.shape[2])]),
            dimension=1, num_keys=2,
        )
        k1, k2, t2, dst2, src2, seq2, kind2, *acols = cols
        args2 = jnp.stack(acols, axis=-1)
        ln2 = args2[:, :, len_a]
        elig2 = k1 < (i64max - sw)  # eligibility survives the sort via k1
        prev = lambda a, fill: jnp.concatenate(
            [jnp.full_like(a[:, :1], fill), a[:, :-1]], axis=1
        )
        contig = (
            elig2
            & prev(elig2, False)
            & (k1 == prev(k1, -1))
            & (k2 == prev(k2, i64max) + 1)
            & (prev(ln2, 0) == mss)  # only a run's LAST segment may be short
        )
        start = elig2 & ~contig
        run = jnp.cumsum(start.astype(jnp.int32), axis=1)  # run id per slot
        same = (
            (run[:, :, None] == run[:, None, :])
            & elig2[:, :, None] & elig2[:, None, :]
        )  # [H, SW, SW]
        count = jnp.sum(same, axis=2, dtype=jnp.int32)
        total = jnp.sum(
            jnp.where(same, ln2[:, None, :], 0), axis=2, dtype=ln2.dtype
        )
        tmin = jnp.min(
            jnp.where(same, t2[:, None, :], i64max), axis=2
        )
        # count is uniform across a run's members, so membership in a
        # folded (>1 segment) run is a direct test
        folded_head = start & (count > 1)
        absorbed = elig2 & contig & (count > 1)
        args2 = args2.at[:, :, len_a].set(
            jnp.where(
                folded_head, total | (count << BURST_NSEG_SHIFT), ln2
            )
        )
        # the head takes the run's LAST member's piggybacked control
        # words as ONE consistent snapshot: the freshest cumulative
        # ack/window/ts, and the SACK words that are only meaningful
        # relative to that same segment's ack
        idx2 = jnp.arange(sw, dtype=jnp.int32)[None, None, :]
        endpos = jnp.max(
            jnp.where(same, idx2, -1), axis=2
        )  # [H, SW] index of each run's last member
        at_end = idx2 == endpos[:, :, None]  # [H, SW, SW] one-hot
        for col in ctl_cols:
            v = args2[:, :, col]
            vend = jnp.sum(
                jnp.where(at_end & same, v[:, None, :], 0),
                axis=2, dtype=v.dtype,
            )
            args2 = args2.at[:, :, col].set(
                jnp.where(folded_head, vend, v)
            )
        return Events(
            time=jnp.where(
                absorbed, TIME_INVALID, jnp.where(folded_head, tmin, t2)
            ),
            dst=dst2, src=src2, seq=seq2, kind=kind2, args=args2,
        )

    @staticmethod
    def _stage_min(stage: Events):
        """Per host, the minimum-(time, src, seq) staged event.

        Returns (ev: Events with [H] fields, mss i64[H] the packed
        (src, seq) key of that event — the total-order guard consumes
        it, onehot bool[H, S] selecting its slot, valid_cnt i32[H]).
        Empty rows yield time=TIME_INVALID. All elementwise/reduction
        work — computed-index gathers and scatters serialize on TPU,
        one-hot select is VPU-cheap.
        """
        t = stage.time
        s = t.shape[1]
        i64max = jnp.iinfo(jnp.int64).max
        mt = jnp.min(t, axis=1)  # [H]
        cand = t == mt[:, None]
        ss = pack_srcseq(stage.src, stage.seq)
        ssm = jnp.where(cand, ss, i64max)
        mss = jnp.min(ssm, axis=1)
        sel = cand & (ssm == mss[:, None])
        first = jnp.argmax(sel, axis=1)  # (time, src, seq) is unique
        onehot = jnp.arange(s, dtype=jnp.int32)[None, :] == first[:, None]
        # dtype pinned: a bare int32 jnp.sum promotes to int64 under x64,
        # which would leak wider event fields into every handler trace
        pick32 = lambda a: jnp.sum(
            jnp.where(onehot, a, 0), axis=1, dtype=a.dtype
        )
        ev = Events(
            time=mt,
            dst=pick32(stage.dst),
            src=pick32(stage.src),
            seq=pick32(stage.seq),
            kind=pick32(stage.kind),
            args=jnp.sum(
                jnp.where(onehot[:, :, None], stage.args, 0), axis=1,
                dtype=stage.args.dtype,
            ),
        )
        valid_cnt = jnp.sum(t != TIME_INVALID, axis=1, dtype=jnp.int32)
        return ev, mss, onehot, valid_cnt

    @staticmethod
    def _stage_append(stage: Events, out: Events):
        """Append a routed [H, K] emit batch into each host's free staging
        slots by RANK MATCHING: the j-th valid emit lands in the j-th
        free slot (two cumsum rank scans + one [H, S, K] compare), all
        elementwise — no sort, no scatter. The earlier implementation
        sorted [H, S+K] x 16 operands per inner step, which profiled as
        the drain's dominant per-iteration traffic at 1k hosts. The
        caller's high-water gate guarantees at least K free slots, so
        every valid emit matches exactly one slot. Slot arrangement is
        irrelevant: _stage_min selects by content key.
        """
        free = stage.time == TIME_INVALID  # [H, S]
        fr = jnp.cumsum(free.astype(jnp.int32), axis=1) - free
        valid = out.time != TIME_INVALID  # [H, K]
        er = jnp.cumsum(valid.astype(jnp.int32), axis=1) - valid
        match = (
            (fr[:, :, None] == er[:, None, :])
            & free[:, :, None]
            & valid[:, None, :]
        )  # [H, S, K]; at most one True per (row, slot) and per emit
        hit = jnp.any(match, axis=2)

        def put(cur, new):  # [H, S](, A) <- [H, K](, A)
            zero = jnp.zeros((), new.dtype)
            if cur.ndim == 2:
                sel = jnp.sum(
                    jnp.where(match, new[:, None, :], zero), axis=2,
                    dtype=new.dtype,
                )
                return jnp.where(hit, sel, cur)
            sel = jnp.sum(
                jnp.where(match[..., None], new[:, None, :, :], zero),
                axis=2, dtype=new.dtype,
            )
            return jnp.where(hit[..., None], sel, cur)

        return Events(
            time=put(stage.time, out.time),
            dst=put(stage.dst, out.dst),
            src=put(stage.src, out.src),
            seq=put(stage.seq, out.seq),
            kind=put(stage.kind, out.kind),
            args=put(stage.args, out.args),
        )

    # -- window = drain all events below the barrier ------------------------
    def _drain_window(self, st: EngineState, window_end, host0):
        if self.batch_handler is not None:
            return self._drain_window_batched(st, window_end, host0)
        if self.cfg.frontier > 0:
            return self._drain_window_frontier(st, window_end, host0)
        cfg = self.cfg
        h, k, c = cfg.n_hosts, cfg.max_emit, cfg.capacity
        b = cfg.eff_drain_batch
        sw = max(cfg.eff_stage_width, b + k)
        gids = host0 + jnp.arange(h, dtype=jnp.int32)
        cpu_cost = self.cpu_cost[gids]  # [H, NK] this shard's costs
        al_sh = self._alive_slice(host0) if self._f_crash else None

        def outer_cond(carry):
            # carried flag (computed by `_drain_flag` in the body): a
            # host's next executable instant is its earliest event or,
            # if later, when its virtual CPU frees up (cpu.c semantics).
            # The psum lives in the body, never in this predicate — the
            # structural rule that keeps 0.4.37 shard_map correct
            return carry[0]

        def outer_body(carry):
            (_, q, xchg, hosts, src_seq, exec_cnt, stats, cpu_free, trace,
             splane) = carry
            # merge the previous sweep's in-flight exchange before the
            # frontier read: no queue op ran since its sending push, so
            # the deferred merge is bit-identical to an immediate one
            q, xchg = self._xchg_deliver(q, xchg, host0)

            # 1. move the frontier into staging: queue rows are sorted by
            # (time, src, seq) with empties last (events.py invariant), so
            # each host's b earliest below-barrier events are its first b
            # columns, and clearing them is a prefix compare — no scatter.
            bvalid = q.time[:, :b] < window_end  # a prefix of each row
            ndump = jnp.sum(bvalid, axis=1, dtype=jnp.int32)
            if self._stats and splane is not None:
                # queue fill at pop: how full each popping host's queue
                # is the moment its frontier dumps (pre-clear)
                splane = splane.observe(
                    "qfill",
                    jnp.sum(q.time != TIME_INVALID, axis=1,
                            dtype=jnp.int64),
                    ndump > 0,
                )
            pad = ((0, 0), (0, sw - b))
            stage = Events(
                time=jnp.pad(
                    jnp.where(bvalid, q.time[:, :b], TIME_INVALID),
                    pad, constant_values=TIME_INVALID,
                ),
                dst=jnp.pad(jnp.broadcast_to(gids[:, None], (h, b)), pad),
                src=jnp.pad(q.src[:, :b], pad),
                seq=jnp.pad(q.seq[:, :b], pad),
                kind=jnp.pad(q.kind[:, :b], pad),
                args=jnp.pad(q.args[:, :b], (*pad, (0, 0))),
            )
            cleared = jnp.arange(c, dtype=jnp.int32)[None, :] < ndump[:, None]
            q = dataclasses.replace(
                q, time=jnp.where(cleared, TIME_INVALID, q.time)
            )
            if cfg.burst is not None:
                # the dump is each host's earliest-b prefix, so every
                # staged event precedes the queue head: folding inside
                # it can never violate the head guard below
                stage = self._burst_fold(stage)

            # queue-head guard: the first UN-dumped event's key, per host
            # (rows keep a sorted tail after the prefix clear, so it sits
            # at column ndump; i64max when the row is exhausted). A staged
            # event may only execute while its key precedes this — an
            # event beyond the b-column dump could still be due first, and
            # executing around it would break the (time, src, seq) total
            # order. The queue is untouched mid-sweep, so this is constant
            # per sweep.
            i64max = jnp.iinfo(jnp.int64).max
            headsel = (
                jnp.arange(c, dtype=jnp.int32)[None, :] == ndump[:, None]
            )
            qh_t = jnp.min(jnp.where(headsel, q.time, i64max), axis=1)
            qh_ss = jnp.min(
                jnp.where(
                    headsel & (q.time != TIME_INVALID),
                    pack_srcseq(q.src, q.seq), i64max,
                ),
                axis=1,
            )

            def precede_q(ev_t, ev_ss):
                return (ev_t < qh_t) | ((ev_t == qh_t) & (ev_ss < qh_ss))

            def can_run(sm, cpu_free):
                """Any host with a below-barrier staged event that precedes
                the un-dumped queue head, CPU permitting, with append
                headroom for one more handler invocation. `sm` is a
                precomputed _stage_min result — it is carried through the
                loop so each iteration pays the [H, S] min-key selection
                exactly once."""
                ev, mss, _oh, cnt = sm
                mt = ev.time
                eff = jnp.maximum(mt, cpu_free) if self._cpu_enabled else mt
                return jnp.any(
                    (eff < window_end) & precede_q(mt, mss) & (cnt + k <= sw)
                )

            # 2. chained execution: per iteration every host runs its
            # minimum staged event; emits append back into staging, so
            # same-window local follow-up chains run without another
            # sweep. Remote sends are barrier-clamped, hence never
            # below-barrier — they park in staging until the flush.
            def inner_cond(ic):
                return ic[0]

            def inner_body(ic):
                (_, sm, stage, hosts, src_seq, exec_cnt, stats, cpu_free,
                 trace, splane) = ic
                ev, mss, onehot, cnt = sm
                ev_t = ev.time
                eff_t = (
                    jnp.maximum(ev_t, cpu_free) if self._cpu_enabled else ev_t
                )
                active = (
                    (ev_t != TIME_INVALID)
                    & (eff_t < window_end)
                    & precede_q(ev_t, mss)
                    & (cnt + k <= sw)  # high-water: leftovers flush
                )
                # a crashed host consumes its due events without running
                # them (quarantine): the slot still clears below — via
                # `active` — so the drain makes progress, but the handler
                # never fires and no emits escape the dead host
                if self._f_crash:
                    alv = self._alive_at(al_sh, eff_t)
                    runm = active & alv
                    stats = dataclasses.replace(
                        stats,
                        n_quarantined=stats.n_quarantined
                        + (active & ~alv),
                    )
                else:
                    runm = active
                stage = dataclasses.replace(
                    stage,
                    time=jnp.where(
                        onehot & active[:, None], TIME_INVALID, stage.time
                    ),
                )
                ev = dataclasses.replace(
                    ev,
                    time=jnp.where(runm, eff_t, TIME_INVALID),
                    dst=gids,
                )
                (hosts, src_seq, exec_cnt, stats, out, _fmask, trace,
                 splane) = self._execute_step(
                    hosts, src_seq, exec_cnt, stats, ev, runm,
                    window_end, gids, trace, splane,
                )
                if self._cpu_enabled:
                    ev_cost = _kind_cost(cpu_cost, ev.kind)
                    if self.cfg.burst is not None:
                        # a folded arrival stands for nseg segments: the
                        # virtual CPU pays per segment, not per event.
                        # Zero-payload count carriers (dup ACKs) are one
                        # packet; their count is protocol bookkeeping.
                        bkind, _sq, blen = self.cfg.burst[:3]
                        lw = ev.args[:, blen]
                        nseg = jnp.where(
                            (lw & BURST_LEN_MASK) > 0,
                            jnp.maximum(lw >> BURST_NSEG_SHIFT, 1), 1,
                        )
                        ev_cost = ev_cost * jnp.where(
                            ev.kind == bkind, nseg.astype(ev_cost.dtype), 1
                        )
                    cpu_free = jnp.where(
                        runm & (ev_cost > 0), eff_t + ev_cost,
                        cpu_free,
                    )
                stage = self._stage_append(stage, out)
                stats = dataclasses.replace(
                    stats, n_inner_steps=stats.n_inner_steps + 1
                )
                sm2 = self._stage_min(stage)
                return (can_run(sm2, cpu_free), sm2, stage, hosts, src_seq,
                        exec_cnt, stats, cpu_free, trace, splane)

            sm0 = self._stage_min(stage)
            (_, _, stage, hosts, src_seq, exec_cnt, stats, cpu_free,
             trace, splane) = jax.lax.while_loop(
                inner_cond,
                inner_body,
                (can_run(sm0, cpu_free), sm0, stage, hosts, src_seq,
                 exec_cnt, stats, cpu_free, trace, splane),
            )

            # 3. flush staging leftovers (clamped remote sends, far-future
            # locals, high-water overflow) in one push + exchange. A
            # row-wise key sort compacts valid entries to a prefix; the
            # common case pushes only a narrow column slice (staged
            # leftovers are few), with a full-width fallback when any
            # host's count exceeds it — exact either way.
            skey = pack_srcseq(stage.src, stage.seq)
            t2, _ss2, dst2, src2, seq2, kind2, *acols = jax.lax.sort(
                (stage.time, skey, stage.dst, stage.src, stage.seq,
                 stage.kind,
                 *[stage.args[:, :, i] for i in range(cfg.n_args)]),
                dimension=1, num_keys=2,
            )
            stage = Events(
                time=t2, dst=dst2, src=src2, seq=seq2, kind=kind2,
                args=jnp.stack(acols, axis=-1),
            )
            w1 = min(sw, 16)
            maxcnt = jnp.max(
                jnp.sum(stage.time != TIME_INVALID, axis=1, dtype=jnp.int32)
            )

            def push_narrow(args):
                q, xchg, stage = args
                sl = jax.tree.map(lambda a: a[:, :w1], stage)
                flat = sl.flatten()
                return self._exchange_push(
                    q, xchg, flat, flat.time != TIME_INVALID, host0
                )

            def push_full(args):
                q, xchg, stage = args
                flat = stage.flatten()
                return self._exchange_push(
                    q, xchg, flat, flat.time != TIME_INVALID, host0
                )

            if w1 == sw:
                q, xchg, xr, nc = push_full((q, xchg, stage))
            elif cfg.axis_name is not None:
                # sharded: the exchange's collectives must run under a
                # shard-uniform program, and maxcnt differs per shard —
                # make the branch choice global. The ExchangeBuf's one
                # static engine-level width is what lets both branches
                # return the same carried-buffer shape.
                go_wide = self._gany(maxcnt > w1)
                q, xchg, xr, nc = jax.lax.cond(
                    go_wide, push_full, push_narrow, (q, xchg, stage)
                )
            else:
                q, xchg, xr, nc = jax.lax.cond(
                    maxcnt > w1, push_full, push_narrow, (q, xchg, stage)
                )
            stats = dataclasses.replace(
                stats,
                n_sweeps=stats.n_sweeps + 1,
                n_xchg_rounds=stats.n_xchg_rounds + xr,
                n_cross_shard=stats.n_cross_shard + nc,
            )
            more = self._drain_flag(q, cpu_free, window_end)
            return (more, q, xchg, hosts, src_seq, exec_cnt, stats,
                    cpu_free, trace, splane)

        carry = (self._drain_flag(st.queues, st.cpu_free, window_end),
                 st.queues, st.xchg, st.hosts, st.src_seq, st.exec_cnt,
                 st.stats, st.cpu_free, st.trace, st.splane)
        (_, q, xchg, hosts, src_seq, exec_cnt, stats, cpu_free,
         trace, splane) = jax.lax.while_loop(outer_cond, outer_body, carry)
        if self._cpu_enabled:
            # sent_min cannot see a destination's busy CPU: flush the
            # in-flight buffer before `_next_time`'s cpu_free defer runs
            q, xchg = self._xchg_deliver(q, xchg, host0)
        # each shard's inner loop trips independently; fold this window's
        # delta across shards so the counter stays replicated-consistent
        inner = st.stats.n_inner_steps + self._gsum(
            stats.n_inner_steps - st.stats.n_inner_steps
        )
        if self._stats and splane is not None:
            # per-window occupancy: events each host executed this
            # window (hosts that ran nothing contribute no sample)
            occ = stats.n_executed - st.stats.n_executed
            splane = splane.observe("occ", occ, occ > 0)
        return dataclasses.replace(
            st,
            queues=q,
            hosts=hosts,
            src_seq=src_seq,
            exec_cnt=exec_cnt,
            stats=dataclasses.replace(
                stats, n_windows=stats.n_windows + 1, n_inner_steps=inner
            ),
            cpu_free=cpu_free,
            trace=trace,
            xchg=xchg,
            splane=splane,
        )

    # -- frontier drain: kind-partitioned runs, per-round bookkeeping --------
    def _drain_window_frontier(self, st: EngineState, window_end, host0):
        """The third drain contract (cfg.frontier > 0): bit-identical to
        the chained drain, amortized bookkeeping.

        Per round, each host's staging is key-sorted ONCE so the
        executable events form a column prefix; a sequential position
        fold (a while_loop capped at `u` positions with global early
        exit) then executes, per host, the maximal prefix RUN of
        equal-time same-kind events — per position it pays only the
        vmapped handler pass + routing. The per-event staging work the
        chained path repeats every step — the [H, S] min-key selection,
        the [H, S, K] rank-matched append, the trace-ring append —
        happens once per ROUND: executed slots clear as a prefix compare
        on the sorted buffer, every position's routed emits land in one
        deferred `_stage_append`, and tracing is one wide append whose
        per-host record order (position-major, exec then emits) matches
        the chained per-step appends record for record.

        Why the run rule is exact: run members share one time t, and
        every in-round LOCAL emit is scheduled at >= t+1 (the dt >= 1
        invariant the transport/model tier declares; remote emits are
        barrier-clamped >= window_end), so no emit can precede a
        remaining run member in (time, src, seq) order — the sorted
        column j IS the host's minimum staged event when position j
        executes, exactly what the chained drain would have selected.
        Per-host stall conditions (CPU busy past the barrier, queue-head
        guard, append headroom) are evaluated per position with the same
        accounting the chained path uses, and they are monotone within a
        sweep, so both paths stop each host at the same event. The
        same-kind rule partitions each round by handler kind ("every
        kind runs once per round"); kinds outside `frontier_kinds`
        execute one position per round — the explicit in-host ordering
        fold for kinds that want visible sequential granularity.
        """
        cfg = self.cfg
        h, k, c = cfg.n_hosts, cfg.max_emit, cfg.capacity
        b = cfg.eff_drain_batch
        sw = max(cfg.eff_stage_width, b + k)
        u = max(1, min(cfg.frontier, sw))
        gids = host0 + jnp.arange(h, dtype=jnp.int32)
        cpu_cost = self.cpu_cost[gids]  # [H, NK] this shard's costs
        al_sh = self._alive_slice(host0) if self._f_crash else None
        fk = self._frontier_kinds
        use_tr = self._trace and st.trace is not None
        if use_tr:
            from shadow_tpu.obs.trace import (
                OP_DROP, OP_EXEC, OP_FDROP, OP_SEND, trace_append,
            )
        la = cfg.trace_len_arg
        i64max = jnp.iinfo(jnp.int64).max

        def per_host(hs, e, key):
            branches = tuple(
                (lambda fn: lambda: _pad(fn(hs, e, key), k))(fn)
                for fn in self.handlers
            )

            def _pad(res, kk):
                hs2, em = res
                return hs2, em.pad_to(kk)

            idx = jnp.clip(e.kind, 0, len(branches) - 1)
            return jax.lax.switch(idx, branches)

        def outer_cond(carry):
            # carried flag (see the chained drain): the psum/any runs in
            # the body, never in this predicate
            return carry[0]

        def outer_body(carry):
            (_, q, xchg, hosts, src_seq, exec_cnt, stats, cpu_free, trace,
             splane) = carry
            q, xchg = self._xchg_deliver(q, xchg, host0)

            # 1. frontier dump into staging — identical to the chained
            # drain (same prefix clear, same optional burst fold)
            bvalid = q.time[:, :b] < window_end
            ndump = jnp.sum(bvalid, axis=1, dtype=jnp.int32)
            if self._stats and splane is not None:
                # same pre-clear observation point as the chained drain,
                # so qfill histograms are bit-identical across contracts
                splane = splane.observe(
                    "qfill",
                    jnp.sum(q.time != TIME_INVALID, axis=1,
                            dtype=jnp.int64),
                    ndump > 0,
                )
            pad = ((0, 0), (0, sw - b))
            stage = Events(
                time=jnp.pad(
                    jnp.where(bvalid, q.time[:, :b], TIME_INVALID),
                    pad, constant_values=TIME_INVALID,
                ),
                dst=jnp.pad(jnp.broadcast_to(gids[:, None], (h, b)), pad),
                src=jnp.pad(q.src[:, :b], pad),
                seq=jnp.pad(q.seq[:, :b], pad),
                kind=jnp.pad(q.kind[:, :b], pad),
                args=jnp.pad(q.args[:, :b], (*pad, (0, 0))),
            )
            cleared = jnp.arange(c, dtype=jnp.int32)[None, :] < ndump[:, None]
            q = dataclasses.replace(
                q, time=jnp.where(cleared, TIME_INVALID, q.time)
            )
            if cfg.burst is not None:
                stage = self._burst_fold(stage)

            # queue-head guard — identical to the chained drain
            headsel = (
                jnp.arange(c, dtype=jnp.int32)[None, :] == ndump[:, None]
            )
            qh_t = jnp.min(jnp.where(headsel, q.time, i64max), axis=1)
            qh_ss = jnp.min(
                jnp.where(
                    headsel & (q.time != TIME_INVALID),
                    pack_srcseq(q.src, q.seq), i64max,
                ),
                axis=1,
            )

            def precede_q(ev_t, ev_ss):
                return (ev_t < qh_t) | ((ev_t == qh_t) & (ev_ss < qh_ss))

            def can_run(sm, cpu_free):
                ev, mss, _oh, cnt = sm
                mt = ev.time
                eff = jnp.maximum(mt, cpu_free) if self._cpu_enabled else mt
                return jnp.any(
                    (eff < window_end) & precede_q(mt, mss) & (cnt + k <= sw)
                )

            # 2. rounds: sort once, execute a run, bookkeep once
            def round_cond(rc):
                return rc[0]

            def round_body(rc):
                (_, stage, hosts, src_seq, exec_cnt, stats, cpu_free,
                 trace, splane) = rc
                skey = pack_srcseq(stage.src, stage.seq)
                t2, ss2, dst2, src2, seq2, kind2, *acols = jax.lax.sort(
                    (stage.time, skey, stage.dst, stage.src, stage.seq,
                     stage.kind,
                     *[stage.args[:, :, i] for i in range(cfg.n_args)]),
                    dimension=1, num_keys=2,
                )
                args2 = jnp.stack(acols, axis=-1)
                cnt0 = jnp.sum(
                    t2 != TIME_INVALID, axis=1, dtype=jnp.int32
                )
                t0 = t2[:, 0]
                kind0 = kind2[:, 0]
                if fk is not None:
                    allowed0 = jnp.zeros((h,), bool)
                    for kk in fk:
                        allowed0 = allowed0 | (kind0 == kk)
                uidx = jnp.arange(u, dtype=jnp.int32)

                def pos_cond(pc):
                    return pc[0]

                def pos_body(pc):
                    (_, j, still, hosts, src_seq, exec_cnt, stats,
                     cpu_free, cnt, nact, outbuf, trbuf, splane) = pc
                    col = lambda a: jax.lax.dynamic_index_in_dim(
                        a, j, axis=1, keepdims=False
                    )
                    ev_t = col(t2)
                    ev_ss = col(ss2)
                    e_src = col(src2)
                    e_seq = col(seq2)
                    e_kind = col(kind2)
                    e_args = col(args2)
                    eff_t = (
                        jnp.maximum(ev_t, cpu_free)
                        if self._cpu_enabled else ev_t
                    )
                    member = (ev_t == t0) & (e_kind == kind0)
                    if fk is not None:
                        member = member & (allowed0 | (j == 0))
                    active = (
                        still & member
                        & (ev_t != TIME_INVALID)
                        & (eff_t < window_end)
                        & precede_q(ev_t, ev_ss)
                        & (cnt + k <= sw)
                    )
                    if self._f_crash:
                        alv = self._alive_at(al_sh, eff_t)
                        runm = active & alv
                        stats = dataclasses.replace(
                            stats,
                            n_quarantined=stats.n_quarantined
                            + (active & ~alv),
                        )
                    else:
                        runm = active
                    ev = Events(
                        time=jnp.where(runm, eff_t, TIME_INVALID),
                        dst=gids, src=e_src, seq=e_seq, kind=e_kind,
                        args=e_args,
                    )
                    hkeys, rkeys = srng.event_keys(
                        self._base_key, gids, exec_cnt
                    )
                    hosts2, emit = jax.vmap(per_host)(hosts, ev, hkeys)
                    hosts = _select_rows(runm, hosts2, hosts)
                    emask = emit.mask & runm[:, None]
                    inc = emask.astype(jnp.int32)
                    within = jnp.cumsum(inc, axis=1) - inc
                    seq = src_seq[:, None] + within
                    src_seq = src_seq + jnp.sum(inc, axis=1, dtype=jnp.int32)
                    out, final_mask, dropped, fdropped, _t, _is_local = (
                        self._route(
                            emit, ev.time, gids, window_end, rkeys, emask,
                            seq,
                        )
                    )
                    if self._stats and splane is not None:
                        # same observation as _execute_step's, so the
                        # wait/net histograms are bit-identical to the
                        # chained drain's
                        delta = _t - ev.time[:, None]
                        splane = splane.observe("wait", delta, final_mask)
                        splane = splane.observe(
                            "net", delta, final_mask & ~_is_local
                        )
                    if self._cpu_enabled:
                        ev_cost = _kind_cost(cpu_cost, ev.kind)
                        if cfg.burst is not None:
                            bkind, _sq, blen = cfg.burst[:3]
                            lw = ev.args[:, blen]
                            nseg = jnp.where(
                                (lw & BURST_LEN_MASK) > 0,
                                jnp.maximum(lw >> BURST_NSEG_SHIFT, 1), 1,
                            )
                            ev_cost = ev_cost * jnp.where(
                                ev.kind == bkind,
                                nseg.astype(ev_cost.dtype), 1,
                            )
                        cpu_free = jnp.where(
                            runm & (ev_cost > 0), eff_t + ev_cost, cpu_free
                        )
                    exec_cnt = exec_cnt + runm.astype(jnp.int32)
                    stats = dataclasses.replace(
                        stats,
                        n_executed=stats.n_executed + runm,
                        n_emitted=stats.n_emitted
                        + jnp.sum(inc, axis=1, dtype=jnp.int64),
                        n_net_dropped=stats.n_net_dropped
                        + jnp.sum(dropped, axis=1, dtype=jnp.int64),
                        n_fault_dropped=stats.n_fault_dropped
                        + jnp.sum(fdropped, axis=1, dtype=jnp.int64),
                        n_by_kind=stats.n_by_kind + (
                            jax.nn.one_hot(
                                jnp.clip(
                                    ev.kind, 0, len(self.handlers) - 1
                                ),
                                len(self.handlers), dtype=jnp.int64,
                            )
                            * runm[:, None]
                        ),
                    )
                    cnt = (
                        cnt - active.astype(jnp.int32)
                        + jnp.sum(final_mask, axis=1, dtype=jnp.int32)
                    )
                    nact = nact + active.astype(jnp.int32)

                    def buf_put(buf, v):
                        m = (uidx == j).reshape(
                            (1, u) + (1,) * (buf.ndim - 2)
                        )
                        return jnp.where(m, v[:, None], buf)

                    outbuf = Events(
                        time=buf_put(outbuf.time, out.time),
                        dst=buf_put(outbuf.dst, out.dst),
                        src=buf_put(outbuf.src, out.src),
                        seq=buf_put(outbuf.seq, out.seq),
                        kind=buf_put(outbuf.kind, out.kind),
                        args=buf_put(outbuf.args, out.args),
                    )
                    if use_tr:
                        ecol = lambda a: a[:, None]
                        op_send = jnp.where(
                            dropped, OP_DROP,
                            jnp.where(fdropped, OP_FDROP, OP_SEND),
                        ).astype(jnp.int32)
                        row = (
                            jnp.concatenate(
                                [ecol(ev.time),
                                 jnp.broadcast_to(ecol(ev.time), (h, k))],
                                1,
                            ),
                            jnp.concatenate([ecol(ev.src), out.src], 1),
                            jnp.concatenate([ecol(ev.dst), out.dst], 1),
                            jnp.concatenate([ecol(ev.kind), out.kind], 1),
                            jnp.concatenate(
                                [ev.args[:, la:la + 1],
                                 out.args[:, :, la]], 1
                            ),
                            jnp.concatenate([ecol(ev.seq), out.seq], 1),
                            jnp.concatenate(
                                [jnp.full((h, 1), OP_EXEC, jnp.int32),
                                 op_send], 1
                            ),
                            jnp.concatenate(
                                [ecol(runm), emask & ~_is_local], 1
                            ),
                        )
                        trbuf = tuple(
                            buf_put(bb, vv) for bb, vv in zip(trbuf, row)
                        )
                    go = jnp.any(active) & (j + 1 < u)
                    return (go, j + 1, active, hosts, src_seq, exec_cnt,
                            stats, cpu_free, cnt, nact, outbuf, trbuf,
                            splane)

                outbuf0 = Events(
                    time=jnp.full((h, u, k), TIME_INVALID, jnp.int64),
                    dst=jnp.zeros((h, u, k), jnp.int32),
                    src=jnp.zeros((h, u, k), jnp.int32),
                    seq=jnp.zeros((h, u, k), jnp.int32),
                    kind=jnp.zeros((h, u, k), jnp.int32),
                    args=jnp.zeros((h, u, k, cfg.n_args), jnp.int32),
                )
                trbuf0 = None
                if use_tr:
                    z32 = jnp.zeros((h, u, 1 + k), jnp.int32)
                    trbuf0 = (
                        jnp.zeros((h, u, 1 + k), jnp.int64),
                        z32, z32, z32, z32, z32, z32,
                        jnp.zeros((h, u, 1 + k), bool),
                    )
                (_, jn, _still, hosts, src_seq, exec_cnt, stats, cpu_free,
                 _cnt, nact, outbuf, trbuf, splane) = jax.lax.while_loop(
                    pos_cond, pos_body,
                    (jnp.asarray(True), jnp.zeros((), jnp.int32),
                     jnp.ones((h,), bool), hosts, src_seq, exec_cnt,
                     stats, cpu_free, cnt0, jnp.zeros((h,), jnp.int32),
                     outbuf0, trbuf0, splane),
                )
                if self._stats and splane is not None:
                    # frontier run length: how many positions each host
                    # actually executed this round — the quantity that
                    # decides whether the per-round sort amortizes
                    splane = splane.observe("runlen", nact, nact > 0)

                # 3. per-round bookkeeping: prefix-clear the executed
                # columns, one deferred append of every position's routed
                # emits (headroom is guaranteed — the per-position gate
                # kept cnt + K <= SW with the exact chained accounting),
                # one wide trace append in chained record order
                colmask = (
                    jnp.arange(sw, dtype=jnp.int32)[None, :] < nact[:, None]
                )
                stage = Events(
                    time=jnp.where(colmask, TIME_INVALID, t2),
                    dst=dst2, src=src2, seq=seq2, kind=kind2, args=args2,
                )
                stage = self._stage_append(
                    stage,
                    Events(
                        time=outbuf.time.reshape(h, u * k),
                        dst=outbuf.dst.reshape(h, u * k),
                        src=outbuf.src.reshape(h, u * k),
                        seq=outbuf.seq.reshape(h, u * k),
                        kind=outbuf.kind.reshape(h, u * k),
                        args=outbuf.args.reshape(h, u * k, cfg.n_args),
                    ),
                )
                if use_tr:
                    w = u * (1 + k)
                    rs = lambda a: a.reshape(h, w)
                    trace = trace_append(
                        trace, cfg.trace,
                        time=rs(trbuf[0]), src=rs(trbuf[1]),
                        dst=rs(trbuf[2]), kind=rs(trbuf[3]),
                        plen=rs(trbuf[4]), seq=rs(trbuf[5]),
                        op=rs(trbuf[6]), mask=rs(trbuf[7]),
                    )
                stats = dataclasses.replace(
                    stats,
                    n_inner_steps=stats.n_inner_steps
                    + jn.astype(jnp.int64),
                )
                sm2 = self._stage_min(stage)
                return (can_run(sm2, cpu_free), stage, hosts, src_seq,
                        exec_cnt, stats, cpu_free, trace, splane)

            sm0 = self._stage_min(stage)
            (_, stage, hosts, src_seq, exec_cnt, stats, cpu_free,
             trace, splane) = jax.lax.while_loop(
                round_cond, round_body,
                (can_run(sm0, cpu_free), stage, hosts, src_seq, exec_cnt,
                 stats, cpu_free, trace, splane),
            )

            # 4. flush staging leftovers — identical to the chained drain
            skey = pack_srcseq(stage.src, stage.seq)
            t2, _ss2, dst2, src2, seq2, kind2, *acols = jax.lax.sort(
                (stage.time, skey, stage.dst, stage.src, stage.seq,
                 stage.kind,
                 *[stage.args[:, :, i] for i in range(cfg.n_args)]),
                dimension=1, num_keys=2,
            )
            stage = Events(
                time=t2, dst=dst2, src=src2, seq=seq2, kind=kind2,
                args=jnp.stack(acols, axis=-1),
            )
            w1 = min(sw, 16)
            maxcnt = jnp.max(
                jnp.sum(stage.time != TIME_INVALID, axis=1, dtype=jnp.int32)
            )

            def push_narrow(args):
                q, xchg, stage = args
                sl = jax.tree.map(lambda a: a[:, :w1], stage)
                flat = sl.flatten()
                return self._exchange_push(
                    q, xchg, flat, flat.time != TIME_INVALID, host0
                )

            def push_full(args):
                q, xchg, stage = args
                flat = stage.flatten()
                return self._exchange_push(
                    q, xchg, flat, flat.time != TIME_INVALID, host0
                )

            if w1 == sw:
                q, xchg, xr, nc = push_full((q, xchg, stage))
            elif cfg.axis_name is not None:
                go_wide = self._gany(maxcnt > w1)
                q, xchg, xr, nc = jax.lax.cond(
                    go_wide, push_full, push_narrow, (q, xchg, stage)
                )
            else:
                q, xchg, xr, nc = jax.lax.cond(
                    maxcnt > w1, push_full, push_narrow, (q, xchg, stage)
                )
            stats = dataclasses.replace(
                stats,
                n_sweeps=stats.n_sweeps + 1,
                n_xchg_rounds=stats.n_xchg_rounds + xr,
                n_cross_shard=stats.n_cross_shard + nc,
            )
            more = self._drain_flag(q, cpu_free, window_end)
            return (more, q, xchg, hosts, src_seq, exec_cnt, stats,
                    cpu_free, trace, splane)

        carry = (self._drain_flag(st.queues, st.cpu_free, window_end),
                 st.queues, st.xchg, st.hosts, st.src_seq, st.exec_cnt,
                 st.stats, st.cpu_free, st.trace, st.splane)
        (_, q, xchg, hosts, src_seq, exec_cnt, stats, cpu_free,
         trace, splane) = jax.lax.while_loop(outer_cond, outer_body, carry)
        if self._cpu_enabled:
            q, xchg = self._xchg_deliver(q, xchg, host0)
        inner = st.stats.n_inner_steps + self._gsum(
            stats.n_inner_steps - st.stats.n_inner_steps
        )
        if self._stats and splane is not None:
            occ = stats.n_executed - st.stats.n_executed
            splane = splane.observe("occ", occ, occ > 0)
        return dataclasses.replace(
            st,
            queues=q,
            hosts=hosts,
            src_seq=src_seq,
            exec_cnt=exec_cnt,
            stats=dataclasses.replace(
                stats, n_windows=stats.n_windows + 1, n_inner_steps=inner
            ),
            cpu_free=cpu_free,
            trace=trace,
            xchg=xchg,
            splane=splane,
        )

    def _next_time(self, st: EngineState) -> jax.Array:
        """Global earliest executable time (one reduction + one pmin):
        per host the earliest pending event, deferred to when its virtual
        CPU frees up (empty queues stay at TIME_INVALID = i64 max).

        Sharded, the barrier also folds in `xchg.sent_min` — the min
        time of events still in flight in the exchange double buffer —
        through the SENDER-side copy, so the pmin never carries a data
        dependence on an all_to_all completing (ExchangeBuf docstring).
        """
        nxt = st.queues.min_time()
        if self._cpu_enabled:
            nxt = jnp.maximum(nxt, st.cpu_free)
        m = jnp.min(nxt)
        if st.xchg is not None:
            m = jnp.minimum(m, st.xchg.sent_min[0])
        return self._gmin(m)

    def _apply_fault_epoch(self, st: EngineState, nxt, host0) -> EngineState:
        """Apply fault-schedule transitions entered since the last window.

        Window starts are globally synchronized (pmin barrier), so every
        shard applies the same transitions at the same sim time — the
        epoch watermark keeps this exact across checkpoint/restore too.
        For hosts dead at any newly-entered epoch: wipe their queues
        (counted as quarantined — a crash voids pending work) and
        re-template their state rows from `fault_reset`, which is what a
        restart is — fresh listening sockets, zeroed app state, while
        `src_seq`/`exec_cnt` stay monotone so (src, seq) uniqueness and
        RNG streams survive the reboot. Bandwidth epochs rescale NIC
        rates from the template's configured values. Runs under lax.cond:
        a window with no epoch change pays one scalar compare."""
        f = self.faults
        h = self.cfg.n_hosts
        tt = f.times.shape[0]
        e = f.epoch_of(nxt)

        def apply(st):
            idx = jnp.arange(tt, dtype=jnp.int32)
            gap = (idx > st.fault_epoch) & (idx <= e)
            if self._f_crash or self._f_bw:
                tmpl = jax.tree.map(
                    lambda a: jax.lax.dynamic_slice_in_dim(
                        a, host0, h, axis=0
                    ),
                    self.fault_reset,
                )
            hosts, q, stats = st.hosts, st.queues, st.stats
            if self._f_crash:
                al_sh = jax.lax.dynamic_slice_in_dim(
                    f.alive, host0, h, axis=1
                )
                reset = jnp.any(gap[:, None] & ~al_sh, axis=0)  # [H]
                wiped = jnp.sum(
                    reset[:, None] & (q.time != TIME_INVALID),
                    axis=1, dtype=jnp.int64,
                )
                q = dataclasses.replace(
                    q, time=jnp.where(reset[:, None], TIME_INVALID, q.time)
                )
                hosts = _select_rows(reset, tmpl, hosts)
                stats = dataclasses.replace(
                    stats, n_quarantined=stats.n_quarantined + wiped
                )
            if self._f_bw:
                bw_t = jax.lax.dynamic_slice_in_dim(
                    f.bw_scale, host0, h, axis=1
                )  # [T, H]
                bw_e = jnp.sum(
                    jnp.where((idx == e)[:, None], bw_t, 0.0), axis=0
                )
                net = hosts.net
                hosts = dataclasses.replace(
                    hosts,
                    net=dataclasses.replace(
                        net,
                        nic_tx=dataclasses.replace(
                            net.nic_tx, rate=tmpl.net.nic_tx.rate * bw_e
                        ),
                        nic_rx=dataclasses.replace(
                            net.nic_rx, rate=tmpl.net.nic_rx.rate * bw_e
                        ),
                    ),
                )
            return dataclasses.replace(
                st, queues=q, hosts=hosts, stats=stats,
                fault_epoch=e.astype(jnp.int32),
            )

        return jax.lax.cond(e != st.fault_epoch, apply, lambda s: s, st)

    def _advance(self, st: EngineState, nxt, stop, host0,
                 window=None) -> EngineState:
        """Open the window [nxt, min(nxt+window, stop)) and drain it.

        `window` defaults to the static conservative bound
        (cfg.lookahead). A *wider* traced bound stays causally safe but
        is NOT bit-identical to the default: `_route` clamps cross-host
        arrivals up to the window barrier (t_remote = max(t + lat,
        window_end)), so a barrier farther out defers those arrivals
        with it — cross-host packet timing coarsens by up to the extra
        width. That is exactly the documented `--runahead` tradeoff,
        except the bound here is a traced scalar: adaptive window
        sizing retunes it between windows with zero recompiles, where
        --runahead bakes a constant into the program. Same-host events
        inside the window keep their exact (time, src, seq) order
        regardless of width. A narrower bound than lookahead is legal
        too (it just wastes barriers). Runs that must be bit-identical
        use the default fixed bound (`--window` absent).
        """
        if window is None:
            window = self.cfg.lookahead
        window_end = jnp.minimum(nxt + window, stop)
        if st.xchg is not None:
            # open of window k: merge window k-1's in-flight exchange.
            # Must precede the fault-epoch wipe (an immediate push would
            # have) and the drain's initial flag, whose barrier these
            # events may now be below.
            q, xchg = self._xchg_deliver(st.queues, st.xchg, host0)
            st = dataclasses.replace(st, queues=q, xchg=xchg)
        if self._f_crash or self._f_link or self._f_bw:
            # link-only schedules advance just the epoch watermark (one
            # scalar compare per window): keeping the watermark current
            # for EVERY fault kind is what lets a fleet lane's state
            # match its solo run leaf-for-leaf whatever mix of fault
            # kinds its sibling lanes compiled in
            st = self._apply_fault_epoch(st, nxt, host0)
        st = self._drain_window(st, window_end, host0)
        return dataclasses.replace(st, now=window_end)

    def step_window(self, st: EngineState, stop, host0=0,
                    window=None) -> EngineState:
        """Advance one conservative window (jittable; no-op when finished).

        `window` optionally widens the window bound past cfg.lookahead
        as a traced i64 scalar (see `_advance`); None keeps the static
        default and the default lowering byte-identical."""
        host0 = jnp.asarray(host0, jnp.int32)
        stop = jnp.asarray(stop, jnp.int64)
        nxt = self._next_time(st)

        def done(st):
            # no event below stop remains: land on stop so callers looping
            # "while now < stop: step_window" terminate. Flush any
            # in-flight exchange so the final queues match a run whose
            # deliveries were immediate (i.e. the single-device run).
            q, xchg = self._xchg_deliver(st.queues, st.xchg, host0)
            return dataclasses.replace(st, queues=q, xchg=xchg, now=stop)

        return jax.lax.cond(
            nxt < stop,
            lambda s: self._advance(s, nxt, stop, host0, window),
            done,
            st,
        )

    def run(self, st: EngineState, stop, host0=0) -> EngineState:
        """Run until no pending event is earlier than `stop` (jittable).

        This is the whole of master_run/slave_run/worker_run collapsed into
        one compiled loop: window barrier = global pmin, round = outer
        iteration, event execution = vmapped sweeps. The next-event time is
        threaded through the carry so each window costs exactly one global
        reduction + pmin collective.
        """
        host0 = jnp.asarray(host0, jnp.int32)
        stop = jnp.asarray(stop, jnp.int64)

        def cond(carry):
            _, nxt = carry
            return nxt < stop

        def body(carry):
            st, nxt = carry
            st = self._advance(st, nxt, stop, host0)
            return st, self._next_time(st)

        st, _ = jax.lax.while_loop(cond, body, (st, self._next_time(st)))
        if st.xchg is not None:
            # flush the last window's in-flight exchange: every remaining
            # event is >= stop, but it must sit in the queues (not the
            # double buffer) for the final state to match single-device
            q, xchg = self._xchg_deliver(st.queues, st.xchg, host0)
            st = dataclasses.replace(st, queues=q, xchg=xchg)
        return dataclasses.replace(st, now=stop)


class ConstantNetwork:
    """Uniform complete-graph network: fixed latency, fixed reliability.

    Mirrors the single-PoI topologies the reference's tests embed (e.g.
    src/test/phold/phold.test.shadow.config.xml: one vertex, 50ms self-loop).
    """

    def __init__(self, latency_ns: int, reliability: float = 1.0,
                 jitter_ns: int = 0):
        self.latency_ns = latency_ns
        self.reliability = reliability
        self.jitter_ns = jitter_ns
        self.has_jitter = jitter_ns > 0

    def route(self, src, dst):
        shape = jnp.broadcast_shapes(src.shape, dst.shape)
        return (
            jnp.full(shape, self.latency_ns, jnp.int64),
            jnp.full(shape, self.reliability, jnp.float32),
            jnp.full(shape, self.jitter_ns, jnp.int64),
        )

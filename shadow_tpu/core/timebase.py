"""Simulation time base.

SimTime is int64 nanoseconds since simulation start, mirroring the
reference's `SimulationTime` u64-ns convention
(reference: src/main/core/support/definitions.h:18-78). Emulated wall time
presented to applications is offset to the Y2K epoch exactly like the
reference's EMULATED_TIME_OFFSET.
"""

import jax.numpy as jnp

TIME_DTYPE = jnp.int64

NANOSECOND = 1
MICROSECOND = 1_000
MILLISECOND = 1_000_000
SECOND = 1_000_000_000
MINUTE = 60 * SECOND
HOUR = 60 * MINUTE

# Jan 1 2000 00:00 UTC in unix ns — the epoch applications observe
# (reference: definitions.h:78 EMULATED_TIME_OFFSET).
EMULATED_TIME_OFFSET = 946_684_800 * SECOND

# Sentinel meaning "no event" / "empty slot"; sorts after every real time.
TIME_INVALID = jnp.iinfo(jnp.int64).max

# Maximum simulateable instant (one century, same spirit as the reference's
# SIMTIME_MAX bound).
TIME_MAX = 100 * 365 * 24 * HOUR


def seconds(x: float) -> int:
    """Convert float seconds to integer SimTime nanoseconds."""
    return int(round(x * SECOND))

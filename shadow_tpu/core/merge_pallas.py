"""Fused queue-merge Pallas kernel: densify + merge in one pass.

`queue_push` (core.events) splits a push into a flat grouping sort, a
gather densify, and a stable merge of each row's sorted incoming block
into its sorted resident prefix. With ``kernel="xla"`` those last two
stages lower as separate XLA ops — gathers and broadcast compares that
each round-trip the hot columns through memory. This module fuses them
into ONE Pallas kernel invocation per merge round: the kernel reads the
flat grouped key arrays and the queue's hot columns once, densifies the
per-destination runs by value-level gather, rotates each row's
cleared-empty prefix out, computes stable merge-path positions, and
writes the merged rows — a single pass over the hot columns.

The arithmetic is element-for-element the same as the XLA path, so the
two kernels are bit-identical on every input (pinned by
tests/test_kernel_equivalence.py, including spill-ring eviction order).

Off-TPU the kernel runs under ``interpret=True``, which executes the
same jnp ops eagerly inside the jitted program — the CPU tier-1 suite
and ``JAX_PLATFORMS=cpu`` benches exercise the identical code path with
no TPU present. (vmap over `pl.load` is unsupported on this jax
pin, so all gathers are value-level fancy indexing after full-ref
loads — which is also what a TPU lowering wants: one VMEM load per
operand, vector gathers after.)
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from shadow_tpu.core.timebase import TIME_INVALID

_I64MAX = jnp.iinfo(jnp.int64).max


def merge_body(qt, qss, qpay, st, sss, bpay, starts, cnt):
    """The densify + rotate + merge arithmetic, shared verbatim by the
    Pallas kernel body and the plain-XLA path (`queue_push` calls this
    directly when kernel="xla"). Shapes: qt/qss [H, hc], qpay
    [H, hc, nw], st/sss [m] flat grouped keys, bpay [H, w, nw],
    starts/cnt [H]."""
    h, hc = qt.shape
    w = bpay.shape[1]
    m = st.shape[0]

    # densify: group g's admitted events sit at flat positions
    # starts[g] .. starts[g]+cnt[g]-1 in key order; masked lanes become
    # fillers with the same key an empty-padded sort would produce
    lane = jnp.arange(w, dtype=jnp.int32)
    gidx = starts[:, None] + lane[None, :]
    okl = lane[None, :] < cnt[:, None]
    gsafe = jnp.minimum(gidx, m - 1)
    bt = jnp.where(okl, st[gsafe], _I64MAX)
    bss = jnp.where(okl, sss[gsafe], _I64MAX)

    # rotate the cleared-empty prefix to the tail: rows arrive as
    # [empties x k | valid ascending | empties] (the engine's frontier
    # prefix-clear), and every empty is canonical (t=INV, ss=0, pay=0)
    inv = qt == TIME_INVALID
    k = jnp.sum(jnp.cumprod(inv.astype(jnp.int32), axis=1), axis=1)
    ridx = jnp.arange(hc, dtype=jnp.int32)[None, :] + k[:, None]
    rin = ridx < hc
    rsafe = jnp.minimum(ridx, hc - 1)
    gat = lambda x, fill: jnp.where(
        rin, jnp.take_along_axis(x, rsafe, axis=1), fill
    )
    at = gat(qt, _I64MAX)
    ass = gat(qss, 0)
    apay = jnp.where(
        rin[:, :, None],
        jnp.take_along_axis(qpay, rsafe[:, :, None], axis=1),
        0,
    )

    # stable merge-path: A ([H, hc] sorted) + B ([H, w] sorted); ties
    # place A first, matching lax.sort's stability over [A | B]
    le = (at[:, :, None] < bt[:, None, :]) | (
        (at[:, :, None] == bt[:, None, :])
        & (ass[:, :, None] <= bss[:, None, :])
    )
    pos_b = lane[None, :] + jnp.sum(le, axis=1, dtype=jnp.int32)  # [H, w]
    ncol = hc + w
    p = jnp.arange(ncol, dtype=jnp.int32)[None, :]
    jb = jnp.sum(
        pos_b[:, None, :] <= p[:, :, None], axis=2, dtype=jnp.int32
    )  # [H, ncol]: incoming events placed at or before each output slot
    ib = jnp.clip(jb - 1, 0, w - 1)
    isb = (jb > 0) & (jnp.take_along_axis(pos_b, ib, axis=1) == p)
    ia = jnp.clip(p - jb, 0, hc - 1)
    mrg = lambda xa, xb: jnp.where(
        isb,
        jnp.take_along_axis(xb, ib, axis=1),
        jnp.take_along_axis(xa, ia, axis=1),
    )
    mt = mrg(at, bt)
    mss = mrg(ass, bss)
    mpay = jnp.where(
        isb[:, :, None],
        jnp.take_along_axis(bpay, ib[:, :, None], axis=1),
        jnp.take_along_axis(apay, ia[:, :, None], axis=1),
    )
    return mt, mss, mpay


def _kernel(qt_ref, qss_ref, qpay_ref, st_ref, sss_ref, bpay_ref,
            starts_ref, cnt_ref, ot_ref, oss_ref, opay_ref):
    mt, mss, mpay = merge_body(
        qt_ref[...], qss_ref[...], qpay_ref[...], st_ref[...], sss_ref[...],
        bpay_ref[...], starts_ref[...], cnt_ref[...],
    )
    ot_ref[...] = mt
    oss_ref[...] = mss
    opay_ref[...] = mpay


@functools.lru_cache(maxsize=None)
def _build_call(h, hc, w, m, nw, interpret):
    from jax.experimental import pallas as pl

    ncol = hc + w
    i64 = jnp.int64
    return pl.pallas_call(
        _kernel,
        out_shape=(
            jax.ShapeDtypeStruct((h, ncol), i64),
            jax.ShapeDtypeStruct((h, ncol), i64),
            jax.ShapeDtypeStruct((h, ncol, nw), i64),
        ),
        interpret=interpret,
    )


def fused_merge(qt, qss, qpay, st, sss, bpay, starts, cnt):
    """One fused densify + rotate + merge pass over the hot columns.

    Returns (mt, mss, mpay) merged rows of width hc + w, exactly what
    `lax.sort` over [resident | block] with key (time, srcseq) yields.
    Interpret mode is selected automatically off-TPU.
    """
    h, hc = qt.shape
    w = bpay.shape[1]
    interpret = jax.default_backend() != "tpu"
    call = _build_call(h, hc, w, st.shape[0], qpay.shape[-1], interpret)
    return call(qt, qss, qpay, st, sss, bpay, starts, cnt)

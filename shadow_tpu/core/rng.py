"""Deterministic per-host random streams, vectorized threefry2x32.

The reference derives determinism from a seed hierarchy master→slave→host of
`rand_r` streams (reference: src/main/utility/random.c:15-50,
src/main/core/master.c:95, src/main/host/host.c:176). Here every executed
event gets a counter-based key derived from (root seed, global host id,
per-host execution counter) — bit-reproducible regardless of how hosts are
sharded across chips.

Why not `jax.random`: its typed-key API lowers vmapped `fold_in`/`split`
chains into per-lane key plumbing that measures ~100× slower than bulk
elementwise work on TPU (5.7 ms vs 0.06 ms for 131k lanes on v5e — the
engine's dominant per-sweep cost when profiled). The generator below is
the same threefry2x32 construction (20 rounds, Salmon et al. SC'11), but
keys are plain `uint32[..., 2]` arrays and every derivation/sample is a
single fused elementwise pass over the batch, so deriving 131k event keys
costs microseconds. Handlers receive such a key per event and consume it
with the helpers here (`split`, `uniform`, `randint`, `exponential`).

Stream-separation discipline: every derivation folds a distinct DOMAIN
tag into the counter word, so handler keys, route keys, split children,
and lane rolls can never collide however many draws a handler makes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_KS_PARITY = 0x1BD11BDA  # threefry key-schedule parity constant
# domain tags (counter-word c1) for the derivation kinds
_DOM_EVENT = 0x45564E54  # "EVNT": (gid, cnt) -> event key
_DOM_HANDLER = 0x484E444C  # "HNDL": event key -> handler key
_DOM_ROUTE = 0x524F5554  # "ROUT": event key -> route key
_DOM_SPLIT = 0x53504C54  # "SPLT": split children
_DOM_LANE = 0x4C414E45  # "LANE": per-lane rolls
_DOM_FOLD = 0x464F4C44  # "FOLD": fold_in derivations
_DOM_UNIF = 0x554E4946  # "UNIF": uniform/bernoulli draws
_DOM_RINT = 0x52494E54  # "RINT": randint draws
_DOM_FAULT = 0x464C5453  # "FLTS": named fault-schedule streams


def _rotl(x: jax.Array, r: int) -> jax.Array:
    return (x << r) | (x >> (32 - r))


def threefry2x32(k0, k1, c0, c1) -> tuple[jax.Array, jax.Array]:
    """The standard 20-round threefry2x32 block cipher, elementwise over
    arbitrary (broadcastable) uint32 array operands."""
    k0 = jnp.asarray(k0, jnp.uint32)
    k1 = jnp.asarray(k1, jnp.uint32)
    c0 = jnp.asarray(c0, jnp.uint32)
    c1 = jnp.asarray(c1, jnp.uint32)
    ks2 = k0 ^ k1 ^ jnp.uint32(_KS_PARITY)
    rot = ((13, 15, 26, 6), (17, 29, 16, 24))
    x0 = c0 + k0
    x1 = c1 + k1
    ks = (k1, ks2, k0)
    for i in range(5):
        for r in rot[i % 2]:
            x0 = x0 + x1
            x1 = _rotl(x1, r)
            x1 = x0 ^ x1
        x0 = x0 + ks[i % 3]
        x1 = x1 + ks[(i + 1) % 3] + jnp.uint32(i + 1)
    return x0, x1


def _key(k0: jax.Array, k1: jax.Array) -> jax.Array:
    return jnp.stack([k0, k1], axis=-1)


def root_key(seed: int) -> jax.Array:
    """uint32[2] root key from a Python seed (both halves mixed)."""
    s = jnp.uint32(seed & 0xFFFFFFFF)
    hi = jnp.uint32((int(seed) >> 32) & 0xFFFFFFFF)
    return _key(*threefry2x32(s, hi, jnp.uint32(0), jnp.uint32(0)))


def event_keys(base: jax.Array, host_gids: jax.Array, exec_cnt: jax.Array):
    """Per-event (handler_key, route_key), each uint32[..., 2].

    handler_key is consumed by the application/protocol handler; route_key
    by the engine for reliability/jitter rolls — separated by domain tag so
    the two can never collide however many draws a handler performs.
    """
    g = host_gids.astype(jnp.uint32)
    c = exec_cnt.astype(jnp.uint32)
    a, b = threefry2x32(base[..., 0], base[..., 1], g, c ^ jnp.uint32(_DOM_EVENT))
    hk = _key(*threefry2x32(a, b, jnp.uint32(0), jnp.uint32(_DOM_HANDLER)))
    rk = _key(*threefry2x32(a, b, jnp.uint32(0), jnp.uint32(_DOM_ROUTE)))
    return hk, rk


def fold_in(key: jax.Array, data) -> jax.Array:
    """New key folding integer `data` (array or scalar) into `key`."""
    d = jnp.asarray(data).astype(jnp.uint32)
    return _key(*threefry2x32(key[..., 0], key[..., 1], d,
                              jnp.uint32(_DOM_FOLD)))


def split(key: jax.Array, n: int):
    """n statically-indexed child keys (tuple). Elementwise over any
    leading batch shape — under vmap this is still one fused pass."""
    return tuple(
        _key(*threefry2x32(key[..., 0], key[..., 1], jnp.uint32(i),
                           jnp.uint32(_DOM_SPLIT)))
        for i in range(n)
    )


def _bits(key: jax.Array, c0=0, c1=0) -> jax.Array:
    x0, _ = threefry2x32(key[..., 0], key[..., 1], c0, c1)
    return x0


def _to_unit(bits: jax.Array) -> jax.Array:
    # 24-bit mantissa path: exact on f32, uniform in [0, 1)
    return (bits >> 8).astype(jnp.float32) * jnp.float32(1.0 / (1 << 24))


def uniform(key: jax.Array) -> jax.Array:
    """f32 uniform in [0, 1), shaped like the key's batch shape."""
    return _to_unit(_bits(key, c1=jnp.uint32(_DOM_UNIF)))


def uniform_lanes(key: jax.Array, n_lanes: int, offset: int = 0) -> jax.Array:
    """[..., n_lanes] uniforms from one key: lane i uses counter offset+i.
    The bulk replacement for per-lane fold_in+uniform chains."""
    lanes = jnp.arange(n_lanes, dtype=jnp.uint32) + jnp.uint32(offset)
    x0, _ = threefry2x32(
        key[..., 0:1], key[..., 1:2], lanes, jnp.uint32(_DOM_LANE)
    )
    return _to_unit(x0)


def fault_stream_uniform(seed: int, stream: int, n: int) -> jax.Array:
    """f32[n] uniforms from the named fault-schedule stream.

    Derived from (root seed, stream index, element index) only — never
    from host sharding or execution counters — so a fault timeline built
    from these draws is identical across shard counts and across
    checkpoint/restore (the schedule is recompiled from the same config;
    faults/schedule.py consumes this at build time, host-side).
    """
    base = root_key(seed)
    k = _key(*threefry2x32(base[..., 0], base[..., 1],
                           jnp.uint32(stream & 0xFFFFFFFF),
                           jnp.uint32(_DOM_FAULT)))
    idx = jnp.arange(n, dtype=jnp.uint32)
    x0, _ = threefry2x32(k[..., 0:1], k[..., 1:2], idx,
                         jnp.uint32(_DOM_FAULT))
    return _to_unit(x0)


def randint(key: jax.Array, minval: int, maxval: int,
            dtype=jnp.int32) -> jax.Array:
    """Integer in [minval, maxval); modulo draw (bias < 2^-20 for any
    simulation-scale range, irrelevant for DES workloads). An empty
    range returns minval (u32 x % 0 is backend-undefined in XLA, which
    would break bit-reproducibility)."""
    span = jnp.maximum(jnp.uint32(maxval - minval), jnp.uint32(1))
    return (jnp.asarray(minval, dtype)
            + (_bits(key, c1=jnp.uint32(_DOM_RINT)) % span).astype(dtype))


def exponential(key: jax.Array) -> jax.Array:
    """f32 unit-rate exponential."""
    u = uniform(key)
    return -jnp.log1p(-u)


def bernoulli(key: jax.Array, p) -> jax.Array:
    """Shares uniform's draw: bernoulli(key, p) and uniform(key) are the
    same sample viewed two ways — derive child keys to get both."""
    return uniform(key) < p

"""Deterministic per-host random streams.

The reference derives determinism from a seed hierarchy master→slave→host of
`rand_r` streams (reference: src/main/utility/random.c:15-50,
src/main/core/master.c:95, src/main/host/host.c:176). Here we use JAX's
counter-based threefry generator: every executed event gets a key derived
from (root seed, global host id, per-host execution counter), which is
bit-reproducible regardless of how hosts are sharded across chips.
"""

import jax
import jax.numpy as jnp


def root_key(seed: int) -> jax.Array:
    return jax.random.key(seed)


def event_keys(base: jax.Array, host_gids: jax.Array, exec_cnt: jax.Array):
    """Per-host (handler_key, route_key) for the current event execution.

    handler_key is consumed by the application/protocol handler; route_key is
    consumed by the engine for reliability drop rolls — split so the two can
    never collide however many fold_ins a handler performs.
    """

    def one(gid, cnt):
        k = jax.random.fold_in(jax.random.fold_in(base, gid), cnt)
        hk, rk = jax.random.split(k)
        return hk, rk

    return jax.vmap(one)(host_gids.astype(jnp.uint32), exec_cnt.astype(jnp.uint32))

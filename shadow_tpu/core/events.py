"""Event records and bounded per-host event queues, struct-of-arrays.

The reference keeps one locked binary-heap priority queue per virtual host
(reference: src/main/core/scheduler/scheduler_policy_host_single.c:20-25,
src/main/utility/priority_queue.c) and defines a deterministic total order
over events as the tuple (time, dstHostID, srcHostID, per-src sequence)
(reference: src/main/core/work/event.c:110-153).

Here every host's queue is a fixed-capacity slot array; all hosts' queues
form [H, C] device arrays. Rows maintain a **sorted invariant**: slots are
ordered by the event key (time, src, seq) with empty slots
(time == TIME_INVALID) at the end. That choice is TPU-motivated: XLA
scatters with computed indices serialize on TPU (~ms for tens of
thousands of updates), while flat `lax.sort` + gathers + row-wise merge
networks are fast VPU work — so push is implemented as "group incoming
events by destination via one flat sort, gather each host's contiguous
run into a dense block, merge the block into the row with a stable
merge-path network" with no scatter anywhere (see `queue_push` and
core.merge_pallas), and pop-min / frontier extraction are free prefix
reads of the sorted rows. Bounded capacity drops the
*largest*-key events on overflow and accounts them in `drops` — or, when
the queue carries a `SpillRing` (shadow_tpu.runtime.pressure), lands them
in the per-host overflow ring instead so a host-side reservoir can
harvest and re-insert them at window boundaries (lossless pressure
handling; see docs/9-Queue-Pressure.md).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

from shadow_tpu.core import merge_pallas
from shadow_tpu.core.timebase import TIME_INVALID

# Number of i32 payload words carried by every event. The reference carries a
# Task closure pointer + argument pointers (src/main/core/work/task.c:13-41);
# we carry a fixed tuple of words whose meaning depends on `kind`.
N_ARGS = 6

# Common-round block width for queue_push (step 3 of its docstring): the
# [H, MERGE_W] incoming block bounds the merge network's per-row compare
# count, so it is sized to cover every per-destination per-sweep count a
# steady-state workload produces (Poisson tails at typical loads put
# P(count > 24) below 1e-8 per host); rarer bursts take the exact
# full-width fallback round.
MERGE_W = 24

# Hot-region width for the row-wise merge (step 4): when every row's
# resident population plus the incoming block fits inside the first
# HOT_C columns, the merge touches only [H, HOT_C + W] and leaves the
# (all-empty) tail untouched — exact, because the sorted-rows invariant
# makes "population <= HOT_C" mean "all valid slots live in the first
# HOT_C columns". Large-capacity TCP simulations size C for worst-case
# bursts (a full receive window in flight) but hold far fewer resident
# events in steady state, so this bounds the dominant per-sweep merge
# cost by HOT_C, not C, per row. Rows past the bound fall back to the
# full-width merge (a lax.cond; no collectives inside).
HOT_C = 128


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class Events:
    """A batch of event records (any leading shape).

    time: i64[...]  absolute sim time (TIME_INVALID = empty)
    dst:  i32[...]  destination (global) host id
    src:  i32[...]  source (global) host id
    seq:  i32[...]  per-source sequence number (tie-break)
    kind: i32[...]  handler index
    args: i32[..., N_ARGS] payload words
    """

    time: jax.Array
    dst: jax.Array
    src: jax.Array
    seq: jax.Array
    kind: jax.Array
    args: jax.Array

    @staticmethod
    def empty(shape, n_args: int = N_ARGS) -> "Events":
        shape = tuple(shape) if not isinstance(shape, int) else (shape,)
        i32 = jnp.int32
        return Events(
            time=jnp.full(shape, TIME_INVALID, jnp.int64),
            dst=jnp.zeros(shape, i32),
            src=jnp.zeros(shape, i32),
            seq=jnp.zeros(shape, i32),
            kind=jnp.zeros(shape, i32),
            args=jnp.zeros(shape + (n_args,), i32),
        )

    @property
    def shape(self):
        return self.time.shape

    def flatten(self) -> "Events":
        """Collapse the batch dims shared by all fields into one.

        args keeps its trailing N_ARGS dim; every other field is fully flat.
        """
        nb = self.time.ndim
        return jax.tree.map(
            lambda a: a.reshape((-1,) + a.shape[nb:]), self
        )

    def at(self, idx) -> "Events":
        return jax.tree.map(lambda a: a[idx], self)


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class SpillRing:
    """Per-host overflow ring: events evicted by `queue_push` land here
    instead of vanishing, in eviction order, for a host-side reservoir
    (shadow_tpu.runtime.pressure) to harvest at window boundaries.

    Same stop-at-full SoA discipline as obs.trace.TraceRing: `wr` counts
    events *offered* since the last reset; records land at min(wr, cap)
    so a full ring's writes fall into `slack` scratch columns (sized to
    the widest single eviction, the queue capacity) that the harvester
    never reads. Ring-overflow events are the only ones truly lost under
    spill, accounted in both `n_lost` and the queue's `drops`.

    Payload rides bit-packed exactly as inside `queue_push` (kind + args
    as i64 word pairs), so spilling adds no pack/unpack work to the merge.
    """

    time: jax.Array  # i64[H, cap + slack]
    srcseq: jax.Array  # i64[H, cap + slack] pack_srcseq(src, seq)
    pay: jax.Array  # i64[H, cap + slack, NW] packed kind+args words
    wr: jax.Array  # i32[H] events offered since last reset
    n_spilled: jax.Array  # i64[H] cumulative events evicted into the ring
    n_lost: jax.Array  # i64[H] cumulative events lost to ring overflow
    fill_hwm: jax.Array  # i32[H] high-water mark of queue fill

    @staticmethod
    def create(n_hosts: int, cap: int, slack: int, n_args: int = N_ARGS
               ) -> "SpillRing":
        nw = (1 + n_args + 1) // 2  # payload words, packed two per i64
        width = cap + slack
        return SpillRing(
            time=jnp.full((n_hosts, width), TIME_INVALID, jnp.int64),
            srcseq=jnp.zeros((n_hosts, width), jnp.int64),
            pay=jnp.zeros((n_hosts, width, nw), jnp.int64),
            wr=jnp.zeros((n_hosts,), jnp.int32),
            n_spilled=jnp.zeros((n_hosts,), jnp.int64),
            n_lost=jnp.zeros((n_hosts,), jnp.int64),
            fill_hwm=jnp.zeros((n_hosts,), jnp.int32),
        )


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class EventQueue:
    """All hosts' bounded event queues on one shard: [H, C] slot arrays.

    A slot is empty iff time == TIME_INVALID. `drops` counts events lost to
    queue overflow per host (the reference's queues are unbounded; we bound
    and account, in the spirit of its ObjectCounter leak accounting —
    reference: src/main/core/support/object_counter.c). i64: multi-hour
    campaigns overflow an i32 long before they finish.

    `spill` is None (zero pytree leaves — compiled program and checkpoint
    leaf layout identical to a spill-free build) unless the engine was
    configured with an overflow ring, in which case evictions land there
    instead of being counted as drops.
    """

    time: jax.Array  # i64[H, C]
    src: jax.Array  # i32[H, C]
    seq: jax.Array  # i32[H, C]
    kind: jax.Array  # i32[H, C]
    args: jax.Array  # i32[H, C, N_ARGS]
    drops: jax.Array  # i64[H]
    spill: Any = None  # SpillRing, or None when spill is off

    @staticmethod
    def create(n_hosts: int, capacity: int, n_args: int = N_ARGS,
               spill: int = 0) -> "EventQueue":
        i32 = jnp.int32
        return EventQueue(
            time=jnp.full((n_hosts, capacity), TIME_INVALID, jnp.int64),
            src=jnp.zeros((n_hosts, capacity), i32),
            seq=jnp.zeros((n_hosts, capacity), i32),
            kind=jnp.zeros((n_hosts, capacity), i32),
            args=jnp.zeros((n_hosts, capacity, n_args), i32),
            drops=jnp.zeros((n_hosts,), jnp.int64),
            # slack = capacity: every merge round evicts at most
            # w <= min(C, M) <= C events per host in one append
            spill=(
                SpillRing.create(n_hosts, spill, capacity, n_args)
                if spill > 0 else None
            ),
        )

    @property
    def n_hosts(self) -> int:
        return self.time.shape[0]

    @property
    def capacity(self) -> int:
        return self.time.shape[1]

    def valid(self) -> jax.Array:
        return self.time != TIME_INVALID

    def size(self) -> jax.Array:
        return jnp.sum(self.valid(), axis=1, dtype=jnp.int32)

    def min_time(self) -> jax.Array:
        """Earliest pending event time per host (TIME_INVALID if empty)."""
        return jnp.min(self.time, axis=1)


def group_run_starts(sorted_group_ids: jax.Array) -> jax.Array:
    """Index where each position's group run begins, for a group-sorted
    1-D array (associative max-scan over run boundaries). Subtracting it
    from the position index yields each element's rank within its group.
    """
    n = sorted_group_ids.shape[0]
    pos = jnp.arange(n, dtype=jnp.int32)
    boundary = jnp.concatenate(
        [jnp.ones((1,), bool), sorted_group_ids[1:] != sorted_group_ids[:-1]]
    )
    return jax.lax.associative_scan(jnp.maximum, jnp.where(boundary, pos, 0))


def pack_srcseq(src: jax.Array, seq: jax.Array) -> jax.Array:
    """Pack (src, seq) into one i64 preserving lexicographic order.

    Within one host's queue, dst is constant, so the reference's total order
    (time, dst, src, seq) (event.c:110-153) reduces to (time, src, seq);
    this packing lets a single compare/sort operand resolve the tie. seq is
    masked through u32 so a (never expected) negative value cannot
    sign-extend into the src bits.
    """
    return (src.astype(jnp.int64) << 32) | seq.astype(jnp.uint32).astype(jnp.int64)


def unpack_srcseq(p: jax.Array) -> tuple[jax.Array, jax.Array]:
    return (
        (p >> 32).astype(jnp.int32),
        (p & 0xFFFFFFFF).astype(jnp.uint32).astype(jnp.int32),
    )




def queue_pop(
    q: EventQueue, before: jax.Array, host_ids: jax.Array
) -> tuple[EventQueue, Events, jax.Array]:
    """Pop, per host, the minimum-(time,src,seq) event with time < `before`.

    Rows carry the sorted-by-key invariant (module docstring), so the
    minimum is column 0 and popping is a left shift of the popped rows —
    which *preserves* the invariant, keeping this safe to mix with the
    engine's prefix reads. (The engine itself drains frontiers in batch
    via `_drain_window`; this single-pop form serves tests and simple
    drivers.)

    Returns (queue', events[H], active[H]) where active[h] says host h
    popped a real event. Inactive rows contain garbage fields
    (time=TIME_INVALID).
    """
    active = (q.time[:, 0] < before) & (q.time[:, 0] != TIME_INVALID)

    ev = Events(
        time=jnp.where(active, q.time[:, 0], TIME_INVALID),
        dst=host_ids.astype(jnp.int32),
        src=q.src[:, 0],
        seq=q.seq[:, 0],
        kind=q.kind[:, 0],
        args=q.args[:, 0],
    )

    def shift(a, fill):
        pad = jnp.full_like(a[:, :1], fill)
        shifted = jnp.concatenate([a[:, 1:], pad], axis=1)
        m = active.reshape((-1,) + (1,) * (a.ndim - 1))
        return jnp.where(m, shifted, a)

    q2 = dataclasses.replace(
        q,
        time=shift(q.time, TIME_INVALID),
        src=shift(q.src, 0),
        seq=shift(q.seq, 0),
        kind=shift(q.kind, 0),
        args=shift(q.args, 0),
    )
    return q2, ev, active


def queue_push(
    q: EventQueue, ev: Events, mask: jax.Array, host0,
    kernel: str = "xla",
) -> EventQueue:
    """Insert a flat batch of events [M] into their destination queues.

    `host0` is the global id of this shard's first host; events whose dst
    falls outside [host0, host0 + H) are silently ignored (the caller routes
    cross-shard events via collectives before pushing). When a destination
    queue overflows its capacity, the *largest*-key events are dropped and
    counted in `drops` (the reference's heaps are unbounded; we bound and
    account — src/main/core/support/object_counter.c spirit) — unless the
    queue carries a SpillRing, in which case every evicted event lands in
    the ring (the merge leaves the evicted tail contiguous, so the
    capture is one vmapped dynamic_update_slice per field) and only
    ring-overflow events count as drops. With a ring attached the final
    round's admission width is not capped either: extra full-width rounds
    run under a while_loop until every rank is admitted, so no event can
    bypass the ring as an unmaterialized rank-overflow.

    Scatter-free algorithm (XLA scatters with computed indices serialize
    on TPU; everything here is one flat sort plus gathers, searchsorted,
    and a merge network — all budgeted by analysis/hlo_audit.py):

    1. One flat multi-key sort groups incoming events by destination in
       (time, src, seq) order. Grouping in key order means the per-row
       admission cap W admits each destination's *smallest*-key events —
       which events survive overflow then depends only on keys, never on
       batch composition (single-vs-sharded runs stay identical under
       overflow: "keep the C smallest" commutes with batch splits).
       Only the keys and an i32 position index ride the sort; payload
       words are gathered afterward through the sorted index, so wide
       payloads (network-stack models) never inflate the sort operand
       set. (Earlier revisions packed kind+args into extra i64 sort
       operands and derived counts from injected boundary markers plus
       a second recovery sort — profiled against this lowering, the
       marker machinery and payload operands together roughly double
       the flat-sort cost, and the jnp.searchsorted below lowers as a
       scatter-free fori/gather binary search that costs a rounding
       error next to the sort.)
    2. Per-destination run starts and counts come from ONE
       `jnp.searchsorted(sdst, arange(H + 1))` over the grouped
       destination keys: start[g] = bounds[g], count[g] =
       bounds[g + 1] - bounds[g]. Rejected events carry key H and fall
       past bounds[H], so no separate compaction pass is needed.
    3. Each merge round DENSIFIES its [H, W] incoming block by value
       gather — lane j of row g reads flat position start[g] + lo + j,
       masked to a canonical filler (time = i64max, srcseq = i64max,
       payload = 0) past the row's count. (Earlier revisions densified
       with a second flat sort over [incoming | H*W fillers]; the
       gather replaces the dominant sort of the whole push at ~1/300
       of its cost on the current bench target.)
    4. The block merges into the resident rows WITHOUT a row sort:
       rows already hold the sorted invariant (module docstring) apart
       from a cleared-empty prefix, so a rotation compacts each row's
       prefix out in one gather, and a stable MERGE-PATH network
       (broadcast compares + take_along_axis, core.merge_pallas) merges
       the two sorted sequences exactly as `lax.sort` over their
       concatenation would — ties resolve resident-first, matching the
       stable sort it replaces. `kernel="pallas"` runs this densify +
       rotate + merge fused as one Pallas kernel invocation
       (interpret-mode off-TPU); `kernel="xla"` (default) runs the
       identical arithmetic as plain XLA ops. The two are bit-identical
       by construction and pinned so by test.
    5. Truncating the merged row to capacity keeps the smallest keys;
       the cut tail plus the final round's rank overflow are counted as
       drops (or spill to the ring). Empty slots in the kept region are
       re-canonicalized (src = seq = kind = args = 0), which both keeps
       rotation exact on the next push and restores the empties-last
       invariant behind the engine's prefix-clear of executed events.

    Round structure is TWO-LEVEL: the common round runs at a narrow W1
    (MERGE_W covers every per-destination count seen in steady state);
    iff some destination's count exceeds W1, a `lax.cond` fallback round
    admits the rank >= W1 remainder at full width. The split is exact,
    not approximate: the merge keeps the C smallest keys whatever round
    events arrive in, so one round vs two produces identical queues (an
    element dropped at the intermediate truncation has C smaller
    elements that persist to the end, so it would have been dropped
    regardless).

    Payload words (kind + args) ride bit-packed into i64 word pairs.
    """
    if kernel not in ("xla", "pallas"):
        raise ValueError(f"kernel must be 'xla' or 'pallas', got {kernel!r}")
    h, c = q.n_hosts, q.capacity
    m = ev.time.shape[0]
    a = q.args.shape[-1]
    i64max = jnp.iinfo(jnp.int64).max

    local = ev.dst - jnp.asarray(host0, jnp.int32)
    # sim times are non-negative ns by construction (the engine clamps
    # dt and latency); a negative-time event is invalid input and is
    # excluded here like an out-of-shard destination.
    ok = (
        mask & (local >= 0) & (local < h)
        & (ev.time >= 0) & (ev.time != TIME_INVALID)
    )

    pk, unpk = pack_srcseq, unpack_srcseq
    nw = 1 + a  # payload words per event

    def pack_words(words):  # list of i32[...] -> list of i64[...]
        out = []
        for i in range(0, len(words), 2):
            hi = words[i].astype(jnp.int64) << 32
            lo = (
                words[i + 1].astype(jnp.int64) & 0xFFFFFFFF
                if i + 1 < len(words)
                else 0
            )
            out.append(hi | lo)
        return out

    def unpack_words(packed, n):  # list of i64[...] -> n i32[...] words
        words = []
        for i, p in enumerate(packed):
            words.append((p >> 32).astype(jnp.int32))
            if 2 * i + 1 < n:
                words.append((p & 0xFFFFFFFF).astype(jnp.uint32).astype(jnp.int32))
        return words[:n]

    # -- 1. group incoming by destination in (time, src, seq) order;
    # rejected events key to H and group past every real destination
    dkey = jnp.where(ok, local, h)
    flat_idx = jnp.arange(m, dtype=jnp.int32)
    sdst, st, sss, sidx = jax.lax.sort(
        (dkey, ev.time, pk(ev.src, ev.seq), flat_idx), num_keys=3
    )

    # -- 2. per-destination run bounds in one searchsorted
    bounds = jnp.searchsorted(
        sdst, jnp.arange(h + 1, dtype=sdst.dtype), side="left"
    ).astype(jnp.int32)
    mpos = bounds[:h]
    count = bounds[1:] - mpos

    def merge_round(q, lo, w, count_tail):
        """Admit rank in [lo, lo + w) into a [H, w] block, merge into the
        queue rows, truncate to capacity. `count_tail`: this is the last
        round — account rank >= lo + w as drops."""
        cnt_r = jnp.clip(count - lo, 0, w)
        starts = mpos + lo
        # -- 3. densify the block payload by gather through the sorted
        # position index (keys densify inside the merge body, which
        # recomputes the same lane mask)
        lane = jnp.arange(w, dtype=jnp.int32)
        gidx = starts[:, None] + lane[None, :]
        okl = lane[None, :] < cnt_r[:, None]
        oidx = sidx[jnp.minimum(gidx, m - 1)]
        bw = [jnp.where(okl, ev.kind[oidx], 0)] + [
            jnp.where(okl, ev.args[:, i][oidx], 0) for i in range(a)
        ]
        bpay = jnp.stack(pack_words(bw), axis=-1)  # [H, w, NW]

        def row_merge(q, hc):
            """Merge the incoming [H, w] block into the first `hc` queue
            columns and truncate back to hc; columns >= hc ride along
            untouched. Exact when every valid slot lives below hc (the
            hot-branch predicate guarantees it; hc == c is the general
            case, where the tail is empty by construction)."""
            qt = q.time[:, :hc]
            qss = pk(q.src[:, :hc], q.seq[:, :hc])
            qpay = jnp.stack(
                pack_words(
                    [q.kind[:, :hc]] + [q.args[:, :hc, i] for i in range(a)]
                ),
                axis=-1,
            )  # [H, hc, NW]
            # -- 4. fused densify + rotate + merge (see step 4 above)
            body = (
                merge_pallas.fused_merge
                if kernel == "pallas"
                else merge_pallas.merge_body
            )
            mt, mss, mpay = body(qt, qss, qpay, st, sss, bpay, starts, cnt_r)

            over = jnp.sum(
                mt[:, hc:] != TIME_INVALID, axis=1, dtype=jnp.int32
            )
            spill = q.spill
            if spill is None:
                if count_tail:
                    over = over + jnp.maximum(count - lo - w, 0)
                drops_add = over.astype(jnp.int64)
            else:
                # the merged row keeps empties last, so the evicted
                # events sit contiguously at the FRONT of the [H, w]
                # tail: append the whole tail at min(wr, cap) and
                # advance the cursor by the valid count only — garbage
                # beyond it is overwritten by the next append or never
                # read (slack columns absorb full-ring writes)
                scap = spill.time.shape[1] - c  # slack == queue capacity
                sstarts = jnp.minimum(spill.wr, scap)
                put = jax.vmap(
                    lambda row, rec, s: jax.lax.dynamic_update_slice(
                        row, rec, (s,)
                    )
                )
                put2 = jax.vmap(
                    lambda row, rec, s: jax.lax.dynamic_update_slice(
                        row, rec, (s, jnp.int32(0))
                    )
                )
                wr2 = spill.wr + over
                lost = (
                    jnp.maximum(wr2 - scap, 0)
                    - jnp.maximum(spill.wr - scap, 0)
                ).astype(jnp.int64)
                spill = SpillRing(
                    time=put(spill.time, mt[:, hc:], sstarts),
                    srcseq=put(spill.srcseq, mss[:, hc:], sstarts),
                    pay=put2(spill.pay, mpay[:, hc:, :], sstarts),
                    wr=wr2,
                    n_spilled=spill.n_spilled + over.astype(jnp.int64),
                    n_lost=spill.n_lost + lost,
                    fill_hwm=spill.fill_hwm,
                )
                drops_add = lost
            # -- 5. truncate + re-canonicalize kept empties
            keep_t = mt[:, :hc]
            emp = keep_t == TIME_INVALID
            new_src, new_seq = unpk(jnp.where(emp, 0, mss[:, :hc]))
            pay_k = jnp.where(emp[:, :, None], 0, mpay[:, :hc, :])
            words = unpack_words(
                [pay_k[:, :, i] for i in range(pay_k.shape[-1])], nw
            )
            glue = lambda head, tail: jnp.concatenate([head, tail], axis=1)
            new_time = glue(keep_t, q.time[:, hc:])
            if spill is not None:
                fill = jnp.sum(
                    new_time != TIME_INVALID, axis=1, dtype=jnp.int32
                )
                spill = dataclasses.replace(
                    spill, fill_hwm=jnp.maximum(spill.fill_hwm, fill)
                )
            return EventQueue(
                time=new_time,
                src=glue(new_src, q.src[:, hc:]),
                seq=glue(new_seq, q.seq[:, hc:]),
                kind=glue(words[0], q.kind[:, hc:]),
                args=jnp.concatenate(
                    [jnp.stack(words[1:], axis=-1), q.args[:, hc:]], axis=1
                ),
                drops=q.drops + drops_add,
                spill=spill,
            )

        if c < 2 * HOT_C:
            return row_merge(q, c)
        # hot-region fast path: all resident events in the first HOT_C
        # columns AND guaranteed to still fit after admitting w more.
        # (The engine's frontier prefix-clear leaves an INVALID prefix, so
        # residency is checked structurally — any valid slot at or past
        # HOT_C forces the full-width merge.)
        n_res = jnp.max(
            jnp.sum(q.time != TIME_INVALID, axis=1, dtype=jnp.int32)
        )
        tail_clear = ~jnp.any(q.time[:, HOT_C:] != TIME_INVALID)
        # the cleared prefix can push residents past HOT_C even when the
        # count fits; bound it by the worst-case prefix offset (<= the
        # count of leading INVALIDs is unknown here, so rely on the
        # structural tail check plus the post-merge fit guarantee)
        hot_ok = tail_clear & (n_res + w <= HOT_C)
        return jax.lax.cond(
            hot_ok,
            lambda q: row_merge(q, HOT_C),
            lambda q: row_merge(q, c),
            q,
        )

    w_full = min(c, m)
    w1 = min(w_full, MERGE_W)
    if q.spill is None:
        if w1 == w_full:
            return merge_round(q, 0, w_full, True)
        q = merge_round(q, 0, w1, False)
        return jax.lax.cond(
            jnp.any(count > w1),
            lambda q: merge_round(q, w1, w_full, True),
            lambda q: q,
            q,
        )
    # spill: a rank past lo + w in the last round would be dropped
    # without ever materializing in the ring, so instead of capping,
    # keep admitting at full width until every rank is covered (the
    # ring slack equals the queue capacity >= w_full, so each round's
    # eviction tail always fits one append)
    q = merge_round(q, 0, w1, False)
    q, _ = jax.lax.while_loop(
        lambda carry: jnp.any(count > carry[1]),
        lambda carry: (
            merge_round(carry[0], carry[1], w_full, False),
            carry[1] + w_full,
        ),
        (q, jnp.asarray(w1, jnp.int32)),
    )
    return q

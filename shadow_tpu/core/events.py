"""Event records and bounded per-host event queues, struct-of-arrays.

The reference keeps one locked binary-heap priority queue per virtual host
(reference: src/main/core/scheduler/scheduler_policy_host_single.c:20-25,
src/main/utility/priority_queue.c) and defines a deterministic total order
over events as the tuple (time, dstHostID, srcHostID, per-src sequence)
(reference: src/main/core/work/event.c:110-153).

Here every host's queue is a fixed-capacity slot array; all hosts' queues
form [H, C] device arrays. Rows maintain a **sorted invariant**: slots are
ordered by the event key (time, src, seq) with empty slots
(time == TIME_INVALID) at the end. That choice is TPU-motivated: XLA
scatters with computed indices serialize on TPU (~ms for tens of
thousands of updates), while row-wise `lax.sort` is fast VPU work — so
push is implemented as "group incoming events by destination via one flat
sort, slice each host's contiguous run, concatenate to the row, re-sort
the row" with no scatter anywhere, and pop-min / frontier extraction are
free prefix reads of the sorted rows. Bounded capacity drops the
*largest*-key events on overflow and accounts them in `drops`.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from shadow_tpu.core.timebase import TIME_INVALID

# Number of i32 payload words carried by every event. The reference carries a
# Task closure pointer + argument pointers (src/main/core/work/task.c:13-41);
# we carry a fixed tuple of words whose meaning depends on `kind`.
N_ARGS = 6


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class Events:
    """A batch of event records (any leading shape).

    time: i64[...]  absolute sim time (TIME_INVALID = empty)
    dst:  i32[...]  destination (global) host id
    src:  i32[...]  source (global) host id
    seq:  i32[...]  per-source sequence number (tie-break)
    kind: i32[...]  handler index
    args: i32[..., N_ARGS] payload words
    """

    time: jax.Array
    dst: jax.Array
    src: jax.Array
    seq: jax.Array
    kind: jax.Array
    args: jax.Array

    @staticmethod
    def empty(shape, n_args: int = N_ARGS) -> "Events":
        shape = tuple(shape) if not isinstance(shape, int) else (shape,)
        i32 = jnp.int32
        return Events(
            time=jnp.full(shape, TIME_INVALID, jnp.int64),
            dst=jnp.zeros(shape, i32),
            src=jnp.zeros(shape, i32),
            seq=jnp.zeros(shape, i32),
            kind=jnp.zeros(shape, i32),
            args=jnp.zeros(shape + (n_args,), i32),
        )

    @property
    def shape(self):
        return self.time.shape

    def flatten(self) -> "Events":
        """Collapse the batch dims shared by all fields into one.

        args keeps its trailing N_ARGS dim; every other field is fully flat.
        """
        nb = self.time.ndim
        return jax.tree.map(
            lambda a: a.reshape((-1,) + a.shape[nb:]), self
        )

    def at(self, idx) -> "Events":
        return jax.tree.map(lambda a: a[idx], self)


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class EventQueue:
    """All hosts' bounded event queues on one shard: [H, C] slot arrays.

    A slot is empty iff time == TIME_INVALID. `drops` counts events lost to
    queue overflow per host (the reference's queues are unbounded; we bound
    and account, in the spirit of its ObjectCounter leak accounting —
    reference: src/main/core/support/object_counter.c).
    """

    time: jax.Array  # i64[H, C]
    src: jax.Array  # i32[H, C]
    seq: jax.Array  # i32[H, C]
    kind: jax.Array  # i32[H, C]
    args: jax.Array  # i32[H, C, N_ARGS]
    drops: jax.Array  # i32[H]

    @staticmethod
    def create(n_hosts: int, capacity: int, n_args: int = N_ARGS) -> "EventQueue":
        i32 = jnp.int32
        return EventQueue(
            time=jnp.full((n_hosts, capacity), TIME_INVALID, jnp.int64),
            src=jnp.zeros((n_hosts, capacity), i32),
            seq=jnp.zeros((n_hosts, capacity), i32),
            kind=jnp.zeros((n_hosts, capacity), i32),
            args=jnp.zeros((n_hosts, capacity, n_args), i32),
            drops=jnp.zeros((n_hosts,), i32),
        )

    @property
    def n_hosts(self) -> int:
        return self.time.shape[0]

    @property
    def capacity(self) -> int:
        return self.time.shape[1]

    def valid(self) -> jax.Array:
        return self.time != TIME_INVALID

    def size(self) -> jax.Array:
        return jnp.sum(self.valid(), axis=1, dtype=jnp.int32)

    def min_time(self) -> jax.Array:
        """Earliest pending event time per host (TIME_INVALID if empty)."""
        return jnp.min(self.time, axis=1)


def group_run_starts(sorted_group_ids: jax.Array) -> jax.Array:
    """Index where each position's group run begins, for a group-sorted
    1-D array (associative max-scan over run boundaries). Subtracting it
    from the position index yields each element's rank within its group.
    """
    n = sorted_group_ids.shape[0]
    pos = jnp.arange(n, dtype=jnp.int32)
    boundary = jnp.concatenate(
        [jnp.ones((1,), bool), sorted_group_ids[1:] != sorted_group_ids[:-1]]
    )
    return jax.lax.associative_scan(jnp.maximum, jnp.where(boundary, pos, 0))


def pack_srcseq(src: jax.Array, seq: jax.Array) -> jax.Array:
    """Pack (src, seq) into one i64 preserving lexicographic order.

    Within one host's queue, dst is constant, so the reference's total order
    (time, dst, src, seq) (event.c:110-153) reduces to (time, src, seq);
    this packing lets a single compare/sort operand resolve the tie. seq is
    masked through u32 so a (never expected) negative value cannot
    sign-extend into the src bits.
    """
    return (src.astype(jnp.int64) << 32) | seq.astype(jnp.uint32).astype(jnp.int64)


def unpack_srcseq(p: jax.Array) -> tuple[jax.Array, jax.Array]:
    return (
        (p >> 32).astype(jnp.int32),
        (p & 0xFFFFFFFF).astype(jnp.uint32).astype(jnp.int32),
    )




def queue_pop(
    q: EventQueue, before: jax.Array, host_ids: jax.Array
) -> tuple[EventQueue, Events, jax.Array]:
    """Pop, per host, the minimum-(time,src,seq) event with time < `before`.

    Rows carry the sorted-by-key invariant (module docstring), so the
    minimum is column 0 and popping is a left shift of the popped rows —
    which *preserves* the invariant, keeping this safe to mix with the
    engine's prefix reads. (The engine itself drains frontiers in batch
    via `_drain_window`; this single-pop form serves tests and simple
    drivers.)

    Returns (queue', events[H], active[H]) where active[h] says host h
    popped a real event. Inactive rows contain garbage fields
    (time=TIME_INVALID).
    """
    active = (q.time[:, 0] < before) & (q.time[:, 0] != TIME_INVALID)

    ev = Events(
        time=jnp.where(active, q.time[:, 0], TIME_INVALID),
        dst=host_ids.astype(jnp.int32),
        src=q.src[:, 0],
        seq=q.seq[:, 0],
        kind=q.kind[:, 0],
        args=q.args[:, 0],
    )

    def shift(a, fill):
        pad = jnp.full_like(a[:, :1], fill)
        shifted = jnp.concatenate([a[:, 1:], pad], axis=1)
        m = active.reshape((-1,) + (1,) * (a.ndim - 1))
        return jnp.where(m, shifted, a)

    q2 = dataclasses.replace(
        q,
        time=shift(q.time, TIME_INVALID),
        src=shift(q.src, 0),
        seq=shift(q.seq, 0),
        kind=shift(q.kind, 0),
        args=shift(q.args, 0),
    )
    return q2, ev, active


def queue_push(
    q: EventQueue, ev: Events, mask: jax.Array, host0
) -> EventQueue:
    """Insert a flat batch of events [M] into their destination queues.

    `host0` is the global id of this shard's first host; events whose dst
    falls outside [host0, host0 + H) are silently ignored (the caller routes
    cross-shard events via collectives before pushing). When a destination
    queue overflows its capacity, the *largest*-key events are dropped and
    counted in `drops` (the reference's heaps are unbounded; we bound and
    account — src/main/core/support/object_counter.c spirit).

    Scatter-AND-gather-free algorithm (TPU: both computed-index scatters
    and large gathers run orders of magnitude slower than `lax.sort`, so
    everything is expressed as two sorts + elementwise ops):

    1. One flat multi-key sort groups incoming events by destination in
       (time, src, seq) order. Per-destination ranks come from an
       associative max-scan over run boundaries; per-destination counts
       from two searchsorteds.
    2. One global multi-key sort over the concatenation of
       [all existing slots | grouped incoming | fillers] with key
       (row, time, src, seq). Each host row contributes its C existing
       slots; incoming events ranked below the cap W route to their row
       (rank >= W overflows — those could never fit and are counted as
       drops); exactly W - count fillers per row pad every row segment to
       a fixed C + W length, so after the sort a plain reshape yields the
       merged, key-sorted rows. Truncating to C drops the largest keys.

    Narrow payloads (kind + up to 4 args words, e.g. PHOLD) ride the
    sorts directly, bit-packed into i64 operand pairs; wider payloads
    (the 9-word packet args) instead carry a position into a virtual
    [q.args ; ev.args ; zero] table and are materialized with a single
    final gather. The row re-sort also repairs rows whose invariant was
    broken by the engine's prefix-clear of executed events.
    """
    h, c = q.n_hosts, q.capacity
    m = ev.time.shape[0]
    a = q.args.shape[-1]
    i64max = jnp.iinfo(jnp.int64).max

    local = ev.dst - jnp.asarray(host0, jnp.int32)
    ok = mask & (local >= 0) & (local < h) & (ev.time != TIME_INVALID)

    pk, unpk = pack_srcseq, unpack_srcseq

    # payload (kind + args words) rides the sorts directly, bit-packed in
    # i64 pairs, when narrow; wide payloads instead carry a position into
    # a virtual [q rows ; ev rows ; zero row] table gathered once at the
    # end (one gather of [H, C] rows — still no computed-index scatter)
    ride = (1 + a) <= 5

    def pack_words(words):  # list of i32[N] -> list of i64[N]
        out = []
        for i in range(0, len(words), 2):
            hi = words[i].astype(jnp.int64) << 32
            lo = (
                words[i + 1].astype(jnp.int64) & 0xFFFFFFFF
                if i + 1 < len(words)
                else 0
            )
            out.append(hi | lo)
        return out

    def unpack_words(packed, n):  # list of i64[...] -> n i32[...] words
        words = []
        for i, p in enumerate(packed):
            words.append((p >> 32).astype(jnp.int32))
            if 2 * i + 1 < n:
                words.append((p & 0xFFFFFFFF).astype(jnp.uint32).astype(jnp.int32))
        return words[:n]

    # -- 1. group incoming by destination in (time, src, seq) order, so
    # the rank cap below admits each destination's *smallest*-key events —
    # which events survive overflow then depends only on keys, never on
    # batch composition (keeps single-vs-sharded runs identical even when
    # queues overflow: "keep the C smallest" commutes with batch splits)
    dkey = jnp.where(ok, local, h)
    in_ss = pk(ev.src, ev.seq)
    pos32 = jnp.arange(m, dtype=jnp.int32)
    if ride:
        in_pay = pack_words([ev.kind] + [ev.args[:, i] for i in range(a)])
        sdst, st, sss, *gpay = jax.lax.sort(
            (dkey, ev.time, in_ss, *in_pay), num_keys=3
        )
    else:
        sdst, st, sss, spos = jax.lax.sort(
            (dkey, ev.time, in_ss, pos32), num_keys=3
        )
        gpay = [spos + h * c]  # table position of the args row

    rank = pos32 - group_run_starts(sdst)

    hosts = jnp.arange(h, dtype=jnp.int32)
    count = (
        jnp.searchsorted(sdst, hosts, side="right")
        - jnp.searchsorted(sdst, hosts, side="left")
    ).astype(jnp.int32)

    # -- 2. global merge sort of existing + incoming + fillers, key =
    # (row, time, srcseq). Each row contributes its C existing slots,
    # its rank<W incoming (rank >= W could never fit: counted as drops),
    # and exactly W-count fillers, so every row segment is C + W long and
    # a reshape recovers the merged rows.
    w = min(c, m)
    row_ex = jnp.broadcast_to(hosts[:, None], (h, c)).reshape(-1)
    row_in = jnp.where((sdst < h) & (rank < w), sdst, h)
    need = jnp.maximum(w - count, 0)
    jidx = jnp.arange(w, dtype=jnp.int32)[None, :]
    row_f = jnp.where(jidx < need[:, None], hosts[:, None], h).reshape(-1)

    nf = h * w
    cat = lambda ex, inc, fill_val, dtype: jnp.concatenate(
        [ex.reshape(-1), inc, jnp.full((nf,), fill_val, dtype)]
    )
    rkey = jnp.concatenate([row_ex, row_in, row_f])
    times = cat(q.time, st, i64max, jnp.int64)
    srcseqs = cat(pk(q.src, q.seq), sss, i64max, jnp.int64)
    if ride:
        ex_pay = pack_words(
            [q.kind.reshape(-1)] + [q.args[:, :, i].reshape(-1) for i in range(a)]
        )
        pays = [
            cat(e, g, 0, jnp.int64) for e, g in zip(ex_pay, gpay)
        ]
    else:
        pays = [
            cat(
                jnp.arange(h * c, dtype=jnp.int32).reshape(h, c),
                gpay[0].astype(jnp.int32),
                h * c + m,
                jnp.int32,
            )
        ]
    rkey, times, srcseqs, *pays = jax.lax.sort(
        (rkey, times, srcseqs, *pays), num_keys=3
    )

    # every row segment has exactly C + W entries; reshape and truncate
    seg = lambda x: x[: h * (c + w)].reshape(h, c + w)[:, :c]
    mt = seg(times)
    tail = times[: h * (c + w)].reshape(h, c + w)[:, c:]
    over = jnp.sum(tail != TIME_INVALID, axis=1, dtype=jnp.int32) + jnp.maximum(
        count - w, 0
    )
    new_src, new_seq = unpk(seg(srcseqs))

    if ride:
        words = unpack_words([seg(p) for p in pays], 1 + a)
        new_kind = words[0]
        new_args = jnp.stack(words[1:], axis=-1)
    else:
        table = jnp.concatenate(
            [
                jnp.concatenate(
                    [q.kind.reshape(h * c, 1), q.args.reshape(h * c, a)], axis=1
                ),
                jnp.concatenate([ev.kind[:, None], ev.args], axis=1),
                jnp.zeros((1, 1 + a), jnp.int32),
            ]
        )
        ka = jnp.take(table, seg(pays[0]), axis=0)
        new_kind = ka[:, :, 0]
        new_args = ka[:, :, 1:]
    return EventQueue(
        time=mt,
        src=new_src,
        seq=new_seq,
        kind=new_kind,
        args=new_args,
        drops=q.drops + over,
    )

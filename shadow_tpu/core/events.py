"""Event records and bounded per-host event queues, struct-of-arrays.

The reference keeps one locked binary-heap priority queue per virtual host
(reference: src/main/core/scheduler/scheduler_policy_host_single.c:20-25,
src/main/utility/priority_queue.c) and defines a deterministic total order
over events as the tuple (time, dstHostID, srcHostID, per-src sequence)
(reference: src/main/core/work/event.c:110-153).

Here every host's queue is a fixed-capacity slot array; all hosts' queues
form [H, C] device arrays. Pop-min is a masked reduction per row (so it
vectorizes over all hosts at once on the VPU); push is a sort-based batch
scatter that assigns each incoming event a distinct free slot, so the
scatter is collision-free and therefore deterministic. Slot order carries
no meaning — ordering lives entirely in the (time, src, seq) key — so the
queue needs no heap maintenance at all.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from shadow_tpu.core.timebase import TIME_INVALID

# Number of i32 payload words carried by every event. The reference carries a
# Task closure pointer + argument pointers (src/main/core/work/task.c:13-41);
# we carry a fixed tuple of words whose meaning depends on `kind`.
N_ARGS = 6


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class Events:
    """A batch of event records (any leading shape).

    time: i64[...]  absolute sim time (TIME_INVALID = empty)
    dst:  i32[...]  destination (global) host id
    src:  i32[...]  source (global) host id
    seq:  i32[...]  per-source sequence number (tie-break)
    kind: i32[...]  handler index
    args: i32[..., N_ARGS] payload words
    """

    time: jax.Array
    dst: jax.Array
    src: jax.Array
    seq: jax.Array
    kind: jax.Array
    args: jax.Array

    @staticmethod
    def empty(shape, n_args: int = N_ARGS) -> "Events":
        shape = tuple(shape) if not isinstance(shape, int) else (shape,)
        i32 = jnp.int32
        return Events(
            time=jnp.full(shape, TIME_INVALID, jnp.int64),
            dst=jnp.zeros(shape, i32),
            src=jnp.zeros(shape, i32),
            seq=jnp.zeros(shape, i32),
            kind=jnp.zeros(shape, i32),
            args=jnp.zeros(shape + (n_args,), i32),
        )

    @property
    def shape(self):
        return self.time.shape

    def flatten(self) -> "Events":
        """Collapse the batch dims shared by all fields into one.

        args keeps its trailing N_ARGS dim; every other field is fully flat.
        """
        nb = self.time.ndim
        return jax.tree.map(
            lambda a: a.reshape((-1,) + a.shape[nb:]), self
        )

    def at(self, idx) -> "Events":
        return jax.tree.map(lambda a: a[idx], self)


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class EventQueue:
    """All hosts' bounded event queues on one shard: [H, C] slot arrays.

    A slot is empty iff time == TIME_INVALID. `drops` counts events lost to
    queue overflow per host (the reference's queues are unbounded; we bound
    and account, in the spirit of its ObjectCounter leak accounting —
    reference: src/main/core/support/object_counter.c).
    """

    time: jax.Array  # i64[H, C]
    src: jax.Array  # i32[H, C]
    seq: jax.Array  # i32[H, C]
    kind: jax.Array  # i32[H, C]
    args: jax.Array  # i32[H, C, N_ARGS]
    drops: jax.Array  # i32[H]

    @staticmethod
    def create(n_hosts: int, capacity: int, n_args: int = N_ARGS) -> "EventQueue":
        i32 = jnp.int32
        return EventQueue(
            time=jnp.full((n_hosts, capacity), TIME_INVALID, jnp.int64),
            src=jnp.zeros((n_hosts, capacity), i32),
            seq=jnp.zeros((n_hosts, capacity), i32),
            kind=jnp.zeros((n_hosts, capacity), i32),
            args=jnp.zeros((n_hosts, capacity, n_args), i32),
            drops=jnp.zeros((n_hosts,), i32),
        )

    @property
    def n_hosts(self) -> int:
        return self.time.shape[0]

    @property
    def capacity(self) -> int:
        return self.time.shape[1]

    def valid(self) -> jax.Array:
        return self.time != TIME_INVALID

    def size(self) -> jax.Array:
        return jnp.sum(self.valid(), axis=1, dtype=jnp.int32)

    def min_time(self) -> jax.Array:
        """Earliest pending event time per host (TIME_INVALID if empty)."""
        return jnp.min(self.time, axis=1)


def _tiebreak_key(src: jax.Array, seq: jax.Array) -> jax.Array:
    """Pack (src, seq) into one i64 so a single argmin resolves ties.

    Within one host's queue, dst is constant, so the reference's total order
    (time, dst, src, seq) (event.c:110-153) reduces to (time, src, seq).
    """
    return (src.astype(jnp.int64) << 32) | seq.astype(jnp.uint32).astype(jnp.int64)


def queue_pop(
    q: EventQueue, before: jax.Array, host_ids: jax.Array
) -> tuple[EventQueue, Events, jax.Array]:
    """Pop, per host, the minimum-(time,src,seq) event with time < `before`.

    Vectorized over all hosts: two masked row reductions (min time, then min
    tie-break key among slots at that time) and one collision-free scatter to
    clear the popped slots.

    Returns (queue', events[H], active[H]) where active[h] says host h popped
    a real event. Inactive rows contain garbage fields (time=TIME_INVALID).
    """
    h = q.n_hosts
    t = q.time
    min_t = jnp.min(t, axis=1)  # i64[H]
    is_min = t == min_t[:, None]
    key2 = jnp.where(is_min, _tiebreak_key(q.src, q.seq), jnp.iinfo(jnp.int64).max)
    slot = jnp.argmin(key2, axis=1)  # i32[H]
    active = min_t < before

    rows = jnp.arange(h)
    take = lambda a: a[rows, slot]
    ev = Events(
        time=jnp.where(active, take(q.time), TIME_INVALID),
        dst=host_ids.astype(jnp.int32),
        src=take(q.src),
        seq=take(q.seq),
        kind=take(q.kind),
        args=q.args[rows, slot],
    )
    new_time = q.time.at[rows, slot].set(
        jnp.where(active, TIME_INVALID, take(q.time))
    )
    return dataclasses.replace(q, time=new_time), ev, active


def queue_push(
    q: EventQueue, ev: Events, mask: jax.Array, host0
) -> EventQueue:
    """Insert a flat batch of events [M] into their destination queues.

    `host0` is the global id of this shard's first host; events whose dst
    falls outside [host0, host0 + H) are silently ignored (the caller routes
    cross-shard events via collectives before pushing). Overflowing events
    (destination queue full) are dropped and counted in `drops`, mirroring
    where the reference would grow its unbounded heap.

    Algorithm: sort events by local dst (stable), rank each event within its
    dst run, list each queue's free slots in slot order (argsort of the
    occupancy mask — False sorts first), and give the rank-th event the
    rank-th free slot. Every surviving event gets a distinct (row, slot), so
    the scatter has no collisions and the result is order-deterministic.
    """
    h, c = q.n_hosts, q.capacity
    m = ev.time.shape[0]

    local = ev.dst - jnp.asarray(host0, jnp.int32)
    ok = mask & (local >= 0) & (local < h)
    dkey = jnp.where(ok, local, h)  # out-of-shard / masked events sort last
    order = jnp.argsort(dkey, stable=True)
    sd = dkey[order]  # i32[M] sorted local dst

    pos = jnp.arange(m, dtype=jnp.int32)
    run_start = jnp.where(
        jnp.concatenate([jnp.ones((1,), bool), sd[1:] != sd[:-1]]), pos, 0
    )
    run_start = jax.lax.associative_scan(jnp.maximum, run_start)
    rank = pos - run_start  # position within the same-dst run

    occupied = q.valid()
    free_order = jnp.argsort(occupied, axis=1, stable=True)  # free slots first
    free_cnt = c - jnp.sum(occupied, axis=1, dtype=jnp.int32)

    row = jnp.minimum(sd, h - 1)
    slot = free_order[row, jnp.minimum(rank, c - 1)]
    live = (sd < h) & (rank < free_cnt[row])
    over = (sd < h) & ~live

    # mode="drop" discards writes for dead rows instead of writing garbage
    # (a dead row sharing a clamped (row, slot) with a live one would race).
    drow = jnp.where(live, row, h)
    evo = ev.at(order)
    new = dataclasses.replace(
        q,
        time=q.time.at[drow, slot].set(evo.time, mode="drop"),
        src=q.src.at[drow, slot].set(evo.src, mode="drop"),
        seq=q.seq.at[drow, slot].set(evo.seq, mode="drop"),
        kind=q.kind.at[drow, slot].set(evo.kind, mode="drop"),
        args=q.args.at[drow, slot].set(evo.args, mode="drop"),
        drops=q.drops.at[jnp.where(over, row, h)].add(1, mode="drop"),
    )
    return new

"""Built-in example configuration (the reference's --test content,
src/main/core/support/examples.c:1-86: a minimal embedded config so
`shadow --test` runs without any files on disk)."""

EXAMPLE_TOPOLOGY = """<graphml xmlns="http://graphml.graphdrawing.org/xmlns">
  <key attr.name="packetloss" attr.type="double" for="edge" id="d6" />
  <key attr.name="latency" attr.type="double" for="edge" id="d5" />
  <key attr.name="packetloss" attr.type="double" for="node" id="d4" />
  <key attr.name="countrycode" attr.type="string" for="node" id="d3" />
  <key attr.name="bandwidthdown" attr.type="int" for="node" id="d2" />
  <key attr.name="bandwidthup" attr.type="int" for="node" id="d1" />
  <key attr.name="ip" attr.type="string" for="node" id="d0" />
  <graph edgedefault="undirected">
    <node id="poi-1">
      <data key="d0">0.0.0.0</data>
      <data key="d1">10240</data>
      <data key="d2">10240</data>
      <data key="d3">US</data>
      <data key="d4">0.0</data>
    </node>
    <edge source="poi-1" target="poi-1">
      <data key="d5">50.0</data>
      <data key="d6">0.0</data>
    </edge>
  </graph>
</graphml>"""


def example_config() -> str:
    """A 2-host TGen echo over a 50ms single-PoI topology — the same shape
    as the shipped example (resource/examples/shadow.config.xml)."""
    return f"""<shadow stoptime="120">
  <topology><![CDATA[{EXAMPLE_TOPOLOGY}]]></topology>
  <plugin id="tgen" path="tgen"/>
  <host id="server">
    <process plugin="tgen" starttime="1" arguments="server port=8888"/>
  </host>
  <host id="client">
    <process plugin="tgen" starttime="2"
      arguments="peers=server:8888 sendsize=64KiB recvsize=1MiB count=3 pause=1,2,3"/>
  </host>
</shadow>"""

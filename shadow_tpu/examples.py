"""Built-in example configuration (the reference's --test content,
src/main/core/support/examples.c:1-86: a minimal embedded config so
`shadow --test` runs without any files on disk)."""

EXAMPLE_TOPOLOGY = """<graphml xmlns="http://graphml.graphdrawing.org/xmlns">
  <key attr.name="packetloss" attr.type="double" for="edge" id="d6" />
  <key attr.name="latency" attr.type="double" for="edge" id="d5" />
  <key attr.name="packetloss" attr.type="double" for="node" id="d4" />
  <key attr.name="countrycode" attr.type="string" for="node" id="d3" />
  <key attr.name="bandwidthdown" attr.type="int" for="node" id="d2" />
  <key attr.name="bandwidthup" attr.type="int" for="node" id="d1" />
  <key attr.name="ip" attr.type="string" for="node" id="d0" />
  <graph edgedefault="undirected">
    <node id="poi-1">
      <data key="d0">0.0.0.0</data>
      <data key="d1">10240</data>
      <data key="d2">10240</data>
      <data key="d3">US</data>
      <data key="d4">0.0</data>
    </node>
    <edge source="poi-1" target="poi-1">
      <data key="d5">50.0</data>
      <data key="d6">0.0</data>
    </edge>
  </graph>
</graphml>"""


def example_config() -> str:
    """A 2-host TGen echo over a 50ms single-PoI topology — the same shape
    as the shipped example (resource/examples/shadow.config.xml)."""
    return f"""<shadow stoptime="120">
  <topology><![CDATA[{EXAMPLE_TOPOLOGY}]]></topology>
  <plugin id="tgen" path="tgen"/>
  <host id="server">
    <process plugin="tgen" starttime="1" arguments="server port=8888"/>
  </host>
  <host id="client">
    <process plugin="tgen" starttime="2"
      arguments="peers=server:8888 sendsize=64KiB recvsize=1MiB count=3 pause=1,2,3"/>
  </host>
</shadow>"""


def tgen_example(
    n_pairs: int = 64,
    sendsize: str = "16KiB",
    recvsize: str = "64KiB",
    count: int = 4,
    stoptime: int = 60,
) -> str:
    """A scalable TGen transfer workload (BASELINE.md configs 1-2 shape
    scaled out): n_pairs client/server pairs, each client runs `count`
    request/response streams against its own server with a 1-3 s
    cycling pause. Client starts stagger over a 5 s period like
    tor_example so a 10-sim-s window measures steady state.

    The pause choices are all >= 1 s, so the parsed model declares
    frontier_safe and the config can run under the engine's frontier
    drain (docs/11-Performance.md "Model-tier batching")."""
    hosts = []
    for i in range(n_pairs):
        hosts.append(
            f'<host id="srv{i}" bandwidthup="102400" '
            'bandwidthdown="102400">'
            '<process plugin="tgen" starttime="1" '
            'arguments="server port=8888"/>'
            "</host>"
        )
    for i in range(n_pairs):
        hosts.append(
            f'<host id="cli{i}" bandwidthup="102400" '
            'bandwidthdown="102400">'
            f'<process plugin="tgen" starttime="{3 + (i % 5)}" '
            f'arguments="peers=srv{i}:8888 sendsize={sendsize} '
            f'recvsize={recvsize} count={count} pause=1,2,3"/>'
            "</host>"
        )
    return (
        f'<shadow stoptime="{stoptime}">'
        f"<topology><![CDATA[{EXAMPLE_TOPOLOGY}]]></topology>"
        '<plugin id="tgen" path="tgen"/>'
        + "".join(hosts)
        + "</shadow>"
    )


def tor_example(
    n_relays_per_class: int = 10,
    n_clients: int = 950,
    n_servers: int = 10,
    filesize: str = "320KiB",
    count: int = 5,
    stoptime: int = 60,
    relay_cpu_ghz: float = 0.0,
) -> str:
    """A Tor-like network config (BASELINE.md config 3 shape: minimal Tor
    with guard/middle/exit classes plus torperf-style clients).

    relay_cpu_ghz > 0 gives every relay a cpufrequency attribute, which
    switches on the virtual-CPU model for relay byte handling (the
    reference charges plugin execution time against the host CPU,
    cpu.c:56-107; TorModel charges per-segment onion-crypto cycles)."""
    cpu_attr = (
        f' cpufrequency="{int(relay_cpu_ghz * 1_000_000)}"'
        if relay_cpu_ghz > 0 else ""
    )
    hosts = []
    for klass in ("guard", "middle", "exit"):
        for i in range(n_relays_per_class):
            hosts.append(
                f'<host id="{klass}{i}" bandwidthup="102400" '
                f'bandwidthdown="102400"{cpu_attr}>'
                '<process plugin="tor" starttime="1" arguments="relay"/>'
                "</host>"
            )
    for i in range(n_servers):
        hosts.append(
            f'<host id="web{i}" bandwidthup="102400" '
            'bandwidthdown="102400">'
            '<process plugin="tor" starttime="1" arguments="server port=80"/>'
            "</host>"
        )
    for i in range(n_clients):
        # stagger period 5 s: every client is live by t=8, so a
        # 10-sim-s measurement window reflects the steady state the
        # reference's torperf benchmarks report (long-horizon runs),
        # not the rampup idle of a 20-s spread
        hosts.append(
            f'<host id="torclient{i}">'
            f'<process plugin="tor" starttime="{3 + (i % 5)}" '
            f'arguments="client server=web{i % n_servers}:80 '
            f'filesize={filesize} count={count} pause=1,2,3"/>'
            "</host>"
        )
    return (
        f'<shadow stoptime="{stoptime}">'
        f"<topology><![CDATA[{EXAMPLE_TOPOLOGY}]]></topology>"
        '<plugin id="tor" path="shadow-plugin-tor"/>'
        + "".join(hosts)
        + "</shadow>"
    )


def tor_churn_example(
    n_relays_per_class: int = 10,
    n_clients: int = 950,
    n_servers: int = 10,
    filesize: str = "320KiB",
    count: int = 5,
    stoptime: int = 60,
    relay_cpu_ghz: float = 0.0,
    churn_frac: float = 0.2,
    churn_period: float = 20.0,
    churn_downtime: float = 5.0,
    churn_start: float = 10.0,
    churn_end: float | None = None,
) -> str:
    """The Tor example under relay churn: a deterministic fraction of the
    relays crash and restart on a cycle mid-run (the defining dynamic of
    live overlay networks the reference cannot model — its packetloss is
    frozen at topology load, topology.c:86-105). Surviving circuits keep
    their streams; streams through a crashed relay hit the real
    RST/retransmit teardown paths and their drops land in the tracker's
    [fault] section."""
    base = tor_example(
        n_relays_per_class=n_relays_per_class, n_clients=n_clients,
        n_servers=n_servers, filesize=filesize, count=count,
        stoptime=stoptime, relay_cpu_ghz=relay_cpu_ghz,
    )
    end = stoptime if churn_end is None else churn_end
    fault = (
        f'<fault type="churn" hosts="guard* middle* exit*" '
        f'start="{churn_start}" end="{end}" period="{churn_period}" '
        f'downtime="{churn_downtime}" frac="{churn_frac}"/>'
    )
    return base.replace("</shadow>", fault + "</shadow>")


def bitcoin_example(
    n_nodes: int = 5000,
    blocks: int = 3,
    blocksize: str = "512KiB",
    interval: int = 60,
    stoptime: int | None = None,
) -> str:
    """A Bitcoin gossip config (BASELINE.md config 5 shape: N-node P2P
    block propagation)."""
    stop = stoptime if stoptime is not None else interval * (blocks + 2)
    hosts = [
        '<host id="miner0">'
        f'<process plugin="bitcoin" starttime="1" arguments="node miner '
        f'peers=4 blocksize={blocksize} interval={interval} '
        f'blocks={blocks}"/></host>'
    ]
    for i in range(1, n_nodes):
        hosts.append(
            f'<host id="btc{i}">'
            f'<process plugin="bitcoin" starttime="1" arguments="node '
            f'peers=4 blocksize={blocksize} interval={interval} '
            f'blocks={blocks}"/></host>'
        )
    return (
        f'<shadow stoptime="{stop}">'
        f"<topology><![CDATA[{EXAMPLE_TOPOLOGY}]]></topology>"
        '<plugin id="bitcoin" path="shadow-plugin-bitcoin"/>'
        + "".join(hosts)
        + "</shadow>"
    )


def phold_example(n_hosts: int = 64, msgs_per_host: int = 4,
                  stoptime: int = 60) -> str:
    """A PHOLD config (the reference's perf harness as a config-driven
    sim: src/test/phold/phold.test.shadow.config.xml, quantity=N over a
    single 50ms PoI)."""
    return (
        f'<shadow stoptime="{stoptime}">'
        f"<topology><![CDATA[{EXAMPLE_TOPOLOGY}]]></topology>"
        '<plugin id="phold" path="shadow-plugin-test-phold"/>'
        f'<host id="peer" quantity="{n_hosts}">'
        f'<process plugin="phold" starttime="1" '
        f'arguments="load={msgs_per_host}"/>'
        "</host></shadow>"
    )

"""Tor-like onion-circuit model: multi-hop relayed TCP streams.

The reference's flagship workload is a Tor network (BASELINE.md configs
3/4: guards/middles/exits + torperf clients, run as real tor binaries via
shadow-plugin-tor). This jitted model reproduces the *traffic shape* that
those benchmarks measure — telescoped client→guard→middle→exit→server TCP
circuits, hop-by-hop relaying with per-hop queueing/CoDel/congestion, and
torperf-style fixed-size fetches — without Tor's cryptography:

- Circuits are chosen at build time (client i's circuit id is i), and each
  relay learns a connection's circuit from the *source port*
  (CIRC_PORT_BASE + circuit id), standing in for the onion-layer EXTEND
  handshake; hop positions come from a static circuit table instead of
  decrypted cells. Deviation documented here for the parity check.
- A client opens one circuit connection, sends a REQ_BYTES request cell,
  and the server answers with `filesize` bytes that flow back through all
  three hops (torperf's fixed-size downloads). `count` fetches per client
  with cycling `pause` gaps, tgen-style.
- Relays are pure byte movers: data arriving on one side of a circuit is
  re-sent on the other side; EOF propagates as close. This is where the
  4× traffic amplification (and the realistic relay load) comes from.

Arguments per <process>:
  relay    [port=9001]                     — onion relay (any position)
  server   [port=80]                       — destination web server
  client   server=<name>[:port] filesize=5MiB count=10 pause=1,2
           [guards=g1,g2 middles=... exits=...]  — explicit relay pools;
           default pools come from hosts named guard*/middle*/exit*/relay*
"""

from __future__ import annotations

import dataclasses
import random as pyrandom

import jax
import jax.numpy as jnp
import numpy as np

from shadow_tpu.config import parse_kv_arguments, parse_size
from shadow_tpu.core.engine import Emit
from shadow_tpu.core.events import Events
from shadow_tpu.core.timebase import SECOND
from shadow_tpu.host.sockets import PROTO_NONE, PROTO_TCP
from shadow_tpu.transport.stack import F_FIN, N_PKT_ARGS
from shadow_tpu.transport.tcp import _put, _sel, emit_concat

_I32 = jnp.int32
_I64 = jnp.int64

OR_PORT = 9001          # default relay listen port
WEB_PORT = 80           # default server listen port
CIRC_PORT_BASE = 20_000  # sport CIRC_PORT_BASE+cid identifies the circuit
REQ_BYTES = 512         # one request "cell" (Tor's cell size)

# Relay crypto cost: cycles a relay core spends per forwarded byte
# (AES-CTR + digest over ~2 onion layers; public single-core relay
# throughput of 100-300 MB/s at ~3 GHz puts this at 10-30 cycles/byte).
# Charged per delivered segment at KIND_PKT_RX via the engine's per-kind
# CPU table when the host has a cpufrequency (cpu.c:56-107 semantics).
RELAY_CYCLES_PER_BYTE = 20

ROLE_NONE, ROLE_RELAY, ROLE_CLIENT, ROLE_SERVER = 0, 1, 2, 3


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class TorApp:
    """Per-host state ([H] / [H, S] at rest)."""

    gid: jax.Array  # i32
    role: jax.Array  # i32
    fwd: jax.Array  # i32[S] circuit peer slot (-1 = none)
    req_rx: jax.Array  # i64[S] server: request bytes seen per conn
    streams_started: jax.Array  # i32 client
    streams_done: jax.Array  # i32 client
    conn_rx: jax.Array  # i64 client: reply bytes on the circuit conn
    t_last_done: jax.Array  # i64
    relayed_bytes: jax.Array  # i64 relay observability


class TorModel:
    name = "tor"
    needs_tcp = True
    n_kinds = 1  # KIND_FETCH: open circuit / issue the next fetch

    def __init__(self):
        self._stack = None
        self._kind_fetch = None

    def app_rows(self) -> int:
        # relay new-circuit: connect(2) + fwd send(1) + close fwd(1);
        # client: next-fetch event; server: reply send — union is 4
        return 4

    def handler_rows(self) -> int:
        return 4  # client fetch: connect(2) + request send(1) + spare

    # ------------------------------------------------------------- build
    def build(self, b):
        n = b.n_hosts
        role = np.zeros((n,), np.int32)
        pools: dict[str, list[int]] = {
            "guard": [], "middle": [], "exit": [], "relay": []
        }
        clients: list[tuple[int, dict]] = []

        for h in b.hosts:
            for proc in h.spec.processes:
                kv = parse_kv_arguments(proc.arguments)
                # role keyword order matters: a client line carries
                # `server=<name>` as a key, so "client" is checked first
                if "client" in kv:
                    role[h.gid] = ROLE_CLIENT
                    clients.append((h.gid, kv))
                    b.add_start_event(h.gid, proc.starttime, 0)
                elif "relay" in kv:
                    role[h.gid] = ROLE_RELAY
                    name = h.name.lower()
                    for p in ("guard", "middle", "exit"):
                        if name.startswith(p):
                            pools[p].append(h.gid)
                            break
                    else:
                        pools["relay"].append(h.gid)
                    port = int(kv.get("port", OR_PORT))
                    b.sockets = b.sockets.bind(h.gid, 0, PROTO_TCP, port)
                    b.tcb = b.tcb.listen(h.gid, 0)
                elif "server" in kv:
                    role[h.gid] = ROLE_SERVER
                    port = int(kv.get("port", WEB_PORT))
                    b.sockets = b.sockets.bind(h.gid, 0, PROTO_TCP, port)
                    b.tcb = b.tcb.listen(h.gid, 0)
                else:
                    raise ValueError(
                        f"tor process on {h.name!r} needs a role "
                        "(relay/server/client)"
                    )

        # circuit table: client i = circuit i; deterministic selection
        # (the role the directory consensus plays in real Tor)
        nc = max(len(clients), 1)
        hops = np.zeros((nc, 3), np.int32)
        srv_gid = np.zeros((nc,), np.int32)
        srv_port = np.full((nc,), WEB_PORT, np.int32)
        filesize = np.full((nc,), 1 << 20, np.int64)
        count = np.zeros((nc,), np.int32)
        pause_ns = np.full((nc, 4), SECOND, np.int64)
        n_pause = np.ones((nc,), np.int32)
        client_circ = np.full((n,), -1, np.int32)

        rng = pyrandom.Random(0xC1BC)
        guards = pools["guard"] or pools["relay"]
        middles = pools["middle"] or pools["relay"]
        exits = pools["exit"] or pools["relay"]
        if clients and not (guards and middles and exits):
            raise ValueError("tor config has clients but no relays")

        for ci, (gid, kv) in enumerate(clients):
            client_circ[gid] = ci
            # distinct relays per circuit (a relay appears in one position)
            path = None
            for _ in range(64):
                cand = (rng.choice(guards), rng.choice(middles),
                        rng.choice(exits))
                if len(set(cand)) == 3 or (
                    len(guards) * len(middles) * len(exits) < 8
                ):
                    path = cand
                    break
            hops[ci] = path
            srv = kv.get("server", "")
            sname, _, sport = srv.partition(":")
            addr = b.dns.resolve_name(sname) if sname else None
            if addr is None:
                raise ValueError(
                    f"tor client on gid {gid} has unknown server {srv!r}"
                )
            srv_gid[ci] = addr.host_id
            srv_port[ci] = int(sport) if sport else WEB_PORT
            filesize[ci] = parse_size(kv.get("filesize", "1MiB"))
            count[ci] = int(kv.get("count", 1))
            pauses = [
                float(t) for t in str(kv.get("pause", "1")).split(",") if t
            ]
            for j, t in enumerate(pauses[:4]):
                pause_ns[ci, j] = int(t * SECOND)
            n_pause[ci] = max(min(len(pauses), 4), 1)

        self._g = dict(
            hops=jnp.asarray(hops),
            srv_gid=jnp.asarray(srv_gid),
            srv_port=jnp.asarray(srv_port),
            filesize=jnp.asarray(filesize),
            count=jnp.asarray(count),
            pause_ns=jnp.asarray(pause_ns),
            n_pause=jnp.asarray(n_pause),
            client_circ=jnp.asarray(client_circ),
            or_port=jnp.int32(OR_PORT),
        )

        self._role = role  # for the per-kind CPU table

        s = b.n_sockets
        state = TorApp(
            gid=jnp.arange(n, dtype=_I32),
            role=jnp.asarray(role),
            fwd=jnp.full((n, s), -1, _I32),
            req_rx=jnp.zeros((n, s), _I64),
            streams_started=jnp.zeros((n,), _I32),
            streams_done=jnp.zeros((n,), _I32),
            conn_rx=jnp.zeros((n,), _I64),
            t_last_done=jnp.zeros((n,), _I64),
            relayed_bytes=jnp.zeros((n,), _I64),
        )
        return state, self._make_handlers, self._on_recv

    def _make_handlers(self, stack, kind_base):
        self._stack = stack
        self._kind_fetch = kind_base
        return [self._on_fetch]

    def cpu_kind_cycles(self, n_kinds: int) -> np.ndarray:
        """Per-(host, kind) cycle charges: relays pay onion-crypto work
        for every delivered segment (KIND_PKT_RX). Takes effect only on
        hosts whose config sets cpufrequency — build_simulation converts
        cycles to virtual-CPU nanoseconds there."""
        from shadow_tpu.transport.stack import KIND_PKT_RX
        from shadow_tpu.transport.tcp import MSS

        cy = np.zeros((self._role.shape[0], n_kinds), np.int64)
        cy[self._role == ROLE_RELAY, KIND_PKT_RX] = (
            RELAY_CYCLES_PER_BYTE * MSS
        )
        return cy

    # ------------------------------------------------- client fetch kind
    def _on_fetch(self, hs, ev: Events, key):
        """Open the circuit connection (first fetch) / issue a request."""
        stack, tcp, g = self._stack, self._stack.tcp, self._g
        app: TorApp = hs.app
        me = app.gid
        cid = g["client_circ"][me]
        is_client = (app.role == ROLE_CLIENT) & (cid >= 0)
        ok = is_client & (app.streams_started < g["count"][jnp.maximum(cid, 0)])
        cidc = jnp.maximum(cid, 0)
        first = ok & (app.streams_started == 0)

        cs = hs.net.tcb.state.shape[0] - 1  # dedicated circuit slot (top)
        sk = hs.net.sockets
        w = lambda a, v: _put(a, cs, v, first)
        sk = dataclasses.replace(
            sk,
            proto=w(sk.proto, PROTO_TCP),
            local_port=w(sk.local_port, CIRC_PORT_BASE + cidc),
            peer_host=w(sk.peer_host, g["hops"][cidc, 0]),
            peer_port=w(sk.peer_port, g["or_port"]),
        )
        app = dataclasses.replace(
            app, streams_started=app.streams_started + ok.astype(_I32)
        )
        hs = dataclasses.replace(
            hs, app=app, net=dataclasses.replace(hs.net, sockets=sk)
        )
        hs, em_conn = tcp.connect(stack, hs, cs, ev.time, mask=first)
        hs, em_req = tcp.send(hs, cs, REQ_BYTES, ev.time, mask=ok)
        return hs, emit_concat(em_conn, em_req)

    # -------------------------------------------------------- deliveries
    def _on_recv(self, hs, slot, pkt, now, key):
        """Role dispatch on every delivered chunk/EOF."""
        stack, tcp, g = self._stack, self._stack.tcp, self._g
        app: TorApp = hs.app
        me = app.gid
        got = slot >= 0
        s = jnp.maximum(slot, 0)
        eof = got & ((pkt.flags & F_FIN) != 0)
        dlen = jnp.where(got, pkt.length.astype(_I64), 0)

        # ---------------- relay: forward bytes along the circuit
        is_relay = got & (app.role == ROLE_RELAY)
        have_fwd = _sel(app.fwd, s) >= 0
        # new inbound circuit conn: source port encodes the circuit
        cid = pkt.src_port - CIRC_PORT_BASE
        new_circ = is_relay & ~have_fwd & (cid >= 0) & (
            cid < g["hops"].shape[0]
        )
        cidc = jnp.clip(cid, 0, g["hops"].shape[0] - 1)
        hop_row = g["hops"][cidc]
        my_pos = jnp.argmax(hop_row == me).astype(_I32)  # guard/middle/exit
        at_exit = my_pos == 2
        nxt_gid = jnp.where(
            at_exit, g["srv_gid"][cidc], hop_row[jnp.minimum(my_pos + 1, 2)]
        )
        nxt_port = jnp.where(at_exit, g["srv_port"][cidc], g["or_port"])

        # allocate the outbound slot: last free (children fill from 0 up)
        free = hs.net.sockets.proto == PROTO_NONE
        ns = free.shape[0]
        out_slot = (ns - 1 - jnp.argmax(free[::-1])).astype(_I32)
        can_open = new_circ & jnp.any(free)

        sk = hs.net.sockets
        w = lambda a, v: _put(a, out_slot, v, can_open)
        sk = dataclasses.replace(
            sk,
            proto=w(sk.proto, PROTO_TCP),
            local_port=w(sk.local_port, CIRC_PORT_BASE + cidc),
            peer_host=w(sk.peer_host, nxt_gid),
            peer_port=w(sk.peer_port, nxt_port),
        )
        fwd = app.fwd
        fwd = _put(fwd, s, out_slot, can_open)
        fwd = _put(fwd, out_slot, s, can_open)
        app = dataclasses.replace(
            app,
            fwd=fwd,
            relayed_bytes=app.relayed_bytes
            + jnp.where(is_relay, dlen, 0),
        )
        hs = dataclasses.replace(
            hs, app=app, net=dataclasses.replace(hs.net, sockets=sk)
        )
        hs, em_open = tcp.connect(stack, hs, out_slot, now, mask=can_open)

        fwd_to = _sel(hs.app.fwd, s)
        do_fwd = is_relay & (fwd_to >= 0) & (dlen > 0)
        hs, em_fwd = tcp.send(hs, fwd_to, dlen, now, mask=do_fwd)
        do_close = is_relay & (fwd_to >= 0) & eof
        hs, em_fc = tcp.close(hs, fwd_to, now, mask=do_close)

        # ---------------- server: answer each request cell with filesize
        app = hs.app
        is_server = got & (app.role == ROLE_SERVER)
        scid = jnp.clip(pkt.src_port - CIRC_PORT_BASE, 0,
                        g["hops"].shape[0] - 1)
        prev = _sel(app.req_rx, s)
        newr = prev + jnp.where(is_server, dlen, 0)
        n_req = (newr // REQ_BYTES - prev // REQ_BYTES).astype(_I64)
        app = dataclasses.replace(
            app, req_rx=_put(app.req_rx, s, newr, got)
        )
        hs = dataclasses.replace(hs, app=app)
        reply = n_req * g["filesize"][scid]
        hs, em_srv = tcp.send(
            hs, s, reply, now, mask=is_server & (reply > 0)
        )

        # ---------------- client: count reply bytes, schedule next fetch
        app = hs.app
        ccid = g["client_circ"][me]
        is_client = got & (app.role == ROLE_CLIENT) & (ccid >= 0)
        ccidc = jnp.maximum(ccid, 0)
        rx2 = app.conn_rx + jnp.where(is_client, dlen, 0)
        done_now = jnp.minimum(
            (rx2 // jnp.maximum(g["filesize"][ccidc], 1)).astype(_I32),
            app.streams_started,
        )
        newly = is_client & (done_now > app.streams_done)
        app = dataclasses.replace(
            app,
            conn_rx=rx2,
            streams_done=jnp.where(newly, done_now, app.streams_done),
            t_last_done=jnp.where(newly, now, app.t_last_done),
        )
        hs = dataclasses.replace(hs, app=app)
        more = newly & (app.streams_done < g["count"][ccidc])
        pause = g["pause_ns"][
            ccidc, app.streams_done % jnp.maximum(g["n_pause"][ccidc], 1)
        ]
        em_next = Emit.single(
            dst=0, dt=pause, kind=self._kind_fetch, mask=more, local=True,
            n_args=N_PKT_ARGS,
        )

        # rows: open(2 rows) | fwd send + fwd close | server reply | next
        em_a = emit_concat(em_fwd, em_fc)
        em_b = emit_concat(em_srv, em_next)
        # merge mutually-exclusive row groups to stay within 4 rows:
        # relay rows never coexist with server/client rows on one host
        merged = jax.tree.map(
            lambda x, y: jnp.where(
                jnp.broadcast_to(
                    is_relay.reshape((1,) + (1,) * (x.ndim - 1)), x.shape
                ),
                x, y,
            ),
            em_a, em_b,
        )
        return hs, emit_concat(em_open, merged)

"""Tor-like onion-circuit model: multi-hop relayed TCP streams.

The reference's flagship workload is a Tor network (BASELINE.md configs
3/4: guards/middles/exits + torperf clients, run as real tor binaries via
shadow-plugin-tor). This jitted model reproduces the *traffic shape* that
those benchmarks measure — telescoped client→guard→middle→exit→server TCP
circuits, hop-by-hop relaying with per-hop queueing/CoDel/congestion, and
torperf-style fixed-size fetches — without Tor's cryptography:

- Circuits are chosen at build time (client i's circuit id is i), and each
  relay learns a connection's circuit from the *source port*
  (CIRC_PORT_BASE + circuit id), standing in for the onion-layer EXTEND
  handshake; hop positions come from a static circuit table instead of
  decrypted cells. Deviation documented here for the parity check.
- A client opens one circuit connection, sends a REQ_BYTES request cell,
  and the server answers with `filesize` bytes that flow back through all
  three hops (torperf's fixed-size downloads). `count` fetches per client
  with cycling `pause` gaps, tgen-style.
- Relays are pure byte movers: data arriving on one side of a circuit is
  re-sent on the other side; EOF propagates as close. This is where the
  4× traffic amplification (and the realistic relay load) comes from.

Arguments per <process>:
  relay    [port=9001]                     — onion relay (any position)
  server   [port=80]                       — destination web server
  client   server=<name>[:port] filesize=5MiB count=10 pause=1,2
           [guards=g1,g2 middles=... exits=...]  — explicit relay pools;
           default pools come from hosts named guard*/middle*/exit*/relay*
"""

from __future__ import annotations

import dataclasses
import random as pyrandom

import jax
import jax.numpy as jnp
import numpy as np

from shadow_tpu.config import parse_kv_arguments, parse_size
from shadow_tpu.core.engine import Emit
from shadow_tpu.core.events import Events
from shadow_tpu.core.timebase import SECOND
from shadow_tpu.host.sockets import PROTO_NONE, PROTO_TCP
from shadow_tpu.transport.stack import F_FIN, N_PKT_ARGS
from shadow_tpu.transport.tcp import _put, _sel, emit_concat

_I32 = jnp.int32
_I64 = jnp.int64

OR_PORT = 9001          # default relay listen port
WEB_PORT = 80           # default server listen port
CIRC_PORT_BASE = 20_000  # sport CIRC_PORT_BASE+cid identifies the circuit
REQ_BYTES = 512         # one request "cell" (Tor's cell size)

# Relay crypto cost: cycles a relay core spends per forwarded byte
# (AES-CTR + digest over ~2 onion layers; public single-core relay
# throughput of 100-300 MB/s at ~3 GHz puts this at 10-30 cycles/byte).
# Charged per delivered segment at KIND_PKT_RX via the engine's per-kind
# CPU table when the host has a cpufrequency (cpu.c:56-107 semantics).
RELAY_CYCLES_PER_BYTE = 20

ROLE_NONE, ROLE_RELAY, ROLE_CLIENT, ROLE_SERVER = 0, 1, 2, 3


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class TorApp:
    """Per-host state ([H] / [H, S] / [H, CM] at rest).

    All circuit configuration is PER-HOST: a relay/server row carries its
    own small [CM] list of (circuit id -> next hop / served filesize),
    and a client row carries its own fetch parameters. Handlers therefore
    never index a global [NC]-sized table — a per-host gather of such a
    table serializes on TPU (and its [NC, 3] form tiles the trailing dim
    to 128 lanes: a measured 35 GB intermediate at the 10k-host shape).
    Every lookup here is a one-hot match over <=CM lanes, elementwise at
    any host count.
    """

    gid: jax.Array  # i32
    role: jax.Array  # i32
    fwd: jax.Array  # i32[S] circuit peer slot (-1 = none)
    req_rx: jax.Array  # i64[S] server: request bytes seen per conn
    streams_started: jax.Array  # i32 client
    streams_done: jax.Array  # i32 client
    conn_rx: jax.Array  # i64 client: reply bytes on the circuit conn
    t_last_done: jax.Array  # i64
    relayed_bytes: jax.Array  # i64 relay observability
    # client parameters (own row; -1 / 0 on non-clients)
    circ_id: jax.Array  # i32 this client's circuit id
    cl_guard: jax.Array  # i32 entry relay gid
    cl_file: jax.Array  # i64 fetch size (bytes)
    cl_count: jax.Array  # i32 fetches to run
    cl_pause: jax.Array  # i64[4] think-time cycle
    cl_npause: jax.Array  # i32 live entries in cl_pause
    # relay/server circuit table (first-match wins, -1 = empty slot)
    tc_cid: jax.Array  # i32[CM]
    tc_nxt: jax.Array  # i32[CM] next-hop gid (relay rows)
    tc_port: jax.Array  # i32[CM] next-hop port
    tc_file: jax.Array  # i64[CM] served filesize (server rows)


def _tc_lookup(app: TorApp, cid):
    """(found, nxt_gid, nxt_port, filesize) for `cid` in this host's
    circuit table — one-hot over [CM], no gathers."""
    match = app.tc_cid == cid
    # first match wins (duplicate-relay circuits in tiny pools)
    first = jnp.cumsum(match.astype(_I32)) == 1
    m = match & first
    found = jnp.any(m)
    pick = lambda a: jnp.sum(
        jnp.where(m, a, jnp.zeros((), a.dtype)), dtype=a.dtype
    )
    return found, pick(app.tc_nxt), pick(app.tc_port), pick(app.tc_file)


class TorModel:
    name = "tor"
    needs_tcp = True
    n_kinds = 1  # KIND_FETCH: open circuit / issue the next fetch

    def __init__(self):
        self._stack = None
        self._kind_fetch = None

    def app_rows(self) -> int:
        # relay new-circuit: connect(2) + fwd send(1) + close fwd(1);
        # client: next-fetch event; server: reply send — union is 4
        return 4

    def handler_rows(self) -> int:
        return 4  # client fetch: connect(2) + request send(1) + spare

    # ------------------------------------------------------------- build
    def build(self, b):
        n = b.n_hosts
        role = np.zeros((n,), np.int32)
        pools: dict[str, list[int]] = {
            "guard": [], "middle": [], "exit": [], "relay": []
        }
        clients: list[tuple[int, dict]] = []

        for h in b.hosts:
            for proc in h.spec.processes:
                kv = parse_kv_arguments(proc.arguments)
                # role keyword order matters: a client line carries
                # `server=<name>` as a key, so "client" is checked first
                if "client" in kv:
                    role[h.gid] = ROLE_CLIENT
                    clients.append((h.gid, kv))
                    b.add_start_event(h.gid, proc.starttime, 0)
                elif "relay" in kv:
                    role[h.gid] = ROLE_RELAY
                    name = h.name.lower()
                    for p in ("guard", "middle", "exit"):
                        if name.startswith(p):
                            pools[p].append(h.gid)
                            break
                    else:
                        pools["relay"].append(h.gid)
                    port = int(kv.get("port", OR_PORT))
                    b.sockets = b.sockets.bind(h.gid, 0, PROTO_TCP, port)
                    b.tcb = b.tcb.listen(h.gid, 0)
                elif "server" in kv:
                    role[h.gid] = ROLE_SERVER
                    port = int(kv.get("port", WEB_PORT))
                    b.sockets = b.sockets.bind(h.gid, 0, PROTO_TCP, port)
                    b.tcb = b.tcb.listen(h.gid, 0)
                else:
                    raise ValueError(
                        f"tor process on {h.name!r} needs a role "
                        "(relay/server/client)"
                    )

        # circuit table: client i = circuit i; deterministic selection
        # (the role the directory consensus plays in real Tor)
        nc = max(len(clients), 1)
        hops = np.zeros((nc, 3), np.int32)
        srv_gid = np.zeros((nc,), np.int32)
        srv_port = np.full((nc,), WEB_PORT, np.int32)
        filesize = np.full((nc,), 1 << 20, np.int64)
        count = np.zeros((nc,), np.int32)
        pause_ns = np.full((nc, 4), SECOND, np.int64)
        n_pause = np.ones((nc,), np.int32)
        client_circ = np.full((n,), -1, np.int32)

        rng = pyrandom.Random(0xC1BC)
        guards = pools["guard"] or pools["relay"]
        middles = pools["middle"] or pools["relay"]
        exits = pools["exit"] or pools["relay"]
        if clients and not (guards and middles and exits):
            raise ValueError("tor config has clients but no relays")

        for ci, (gid, kv) in enumerate(clients):
            client_circ[gid] = ci
            # distinct relays per circuit (a relay appears in one position)
            path = None
            for _ in range(64):
                cand = (rng.choice(guards), rng.choice(middles),
                        rng.choice(exits))
                if len(set(cand)) == 3 or (
                    len(guards) * len(middles) * len(exits) < 8
                ):
                    path = cand
                    break
            hops[ci] = path
            srv = kv.get("server", "")
            sname, _, sport = srv.partition(":")
            addr = b.dns.resolve_name(sname) if sname else None
            if addr is None:
                raise ValueError(
                    f"tor client on gid {gid} has unknown server {srv!r}"
                )
            srv_gid[ci] = addr.host_id
            srv_port[ci] = int(sport) if sport else WEB_PORT
            filesize[ci] = parse_size(kv.get("filesize", "1MiB"))
            count[ci] = int(kv.get("count", 1))
            pauses = [
                float(t) for t in str(kv.get("pause", "1")).split(",") if t
            ]
            for j, t in enumerate(pauses[:4]):
                pause_ns[ci, j] = int(t * SECOND)
            n_pause[ci] = max(min(len(pauses), 4), 1)

        # flatten the circuit table into PER-HOST rows (TorApp docstring:
        # global [NC] tables gathered per event serialize on TPU; these
        # one-hot-matched [CM] rows stay elementwise at any scale).
        # Each circuit contributes one entry to each of its three relays
        # (next hop along the telescope) and one to its server (filesize
        # to serve); first entry per (host, cid) wins, matching the old
        # first-position-match semantics for duplicate-relay circuits.
        per_host: dict[int, list[tuple[int, int, int, int]]] = {}
        for ci in range(len(clients)):
            g0, g1, g2 = int(hops[ci, 0]), int(hops[ci, 1]), int(hops[ci, 2])
            chain = [
                (g0, g1, OR_PORT, 0),
                (g1, g2, OR_PORT, 0),
                (g2, int(srv_gid[ci]), int(srv_port[ci]), 0),
                (int(srv_gid[ci]), -1, 0, int(filesize[ci])),
            ]
            for gid_e, nxt, prt, fsz in chain:
                per_host.setdefault(gid_e, []).append((ci, nxt, prt, fsz))
        cm = 4
        longest = max((len(v) for v in per_host.values()), default=1)
        while cm < longest:
            cm *= 2
        if cm > 4096:
            raise ValueError(
                f"a relay/server participates in {longest} circuits; "
                "per-host circuit tables cap at 4096 — add relays/servers"
            )
        tc_cid = np.full((n, cm), -1, np.int32)
        tc_nxt = np.full((n, cm), -1, np.int32)
        tc_port = np.zeros((n, cm), np.int32)
        tc_file = np.zeros((n, cm), np.int64)
        for gid_e, rowlist in per_host.items():
            for j, (ci, nxt, prt, fsz) in enumerate(rowlist):
                tc_cid[gid_e, j] = ci
                tc_nxt[gid_e, j] = nxt
                tc_port[gid_e, j] = prt
                tc_file[gid_e, j] = fsz

        cl_guard = np.full((n,), -1, np.int32)
        cl_file = np.zeros((n,), np.int64)
        cl_count = np.zeros((n,), np.int32)
        cl_pause = np.full((n, 4), SECOND, np.int64)
        cl_npause = np.ones((n,), np.int32)
        for ci, (gid_c, _kv) in enumerate(clients):
            cl_guard[gid_c] = hops[ci, 0]
            cl_file[gid_c] = filesize[ci]
            cl_count[gid_c] = count[ci]
            cl_pause[gid_c] = pause_ns[ci]
            cl_npause[gid_c] = n_pause[ci]

        self._role = role  # for the per-kind CPU table
        # frontier-drain eligibility (sim.build_simulation): the client
        # think-time pause is this model's only local emit delay — TCP-
        # side delays are floored at 1 ns by the stack — so the run-rule
        # invariant holds iff every configured pause is >= 1 ns. Unused
        # rows keep the SECOND default, so the table-wide check is exact.
        self._frontier_safe = bool((cl_pause >= 1).all())

        s = b.n_sockets
        state = TorApp(
            gid=jnp.arange(n, dtype=_I32),
            role=jnp.asarray(role),
            fwd=jnp.full((n, s), -1, _I32),
            req_rx=jnp.zeros((n, s), _I64),
            streams_started=jnp.zeros((n,), _I32),
            streams_done=jnp.zeros((n,), _I32),
            conn_rx=jnp.zeros((n,), _I64),
            t_last_done=jnp.zeros((n,), _I64),
            relayed_bytes=jnp.zeros((n,), _I64),
            circ_id=jnp.asarray(client_circ),
            cl_guard=jnp.asarray(cl_guard),
            cl_file=jnp.asarray(cl_file),
            cl_count=jnp.asarray(cl_count),
            cl_pause=jnp.asarray(cl_pause),
            cl_npause=jnp.asarray(cl_npause),
            tc_cid=jnp.asarray(tc_cid),
            tc_nxt=jnp.asarray(tc_nxt),
            tc_port=jnp.asarray(tc_port),
            tc_file=jnp.asarray(tc_file),
        )
        return state, self._make_handlers, self._on_recv

    def _make_handlers(self, stack, kind_base):
        self._stack = stack
        self._kind_fetch = kind_base
        return [self._on_fetch]

    @property
    def frontier_safe(self) -> bool:
        """True when every local emit delay this build can schedule is
        provably >= 1 ns — the engine frontier drain's run-rule
        invariant (docs/11-Performance.md, "Model-tier batching")."""
        return getattr(self, "_frontier_safe", False)

    def frontier_kinds(self) -> tuple:
        """Model kinds eligible for multi-position frontier runs (all of
        them: KIND_FETCH's emits are pause-delayed or TCP-floored)."""
        return tuple(range(self.n_kinds))

    def cpu_kind_cycles(self, n_kinds: int) -> np.ndarray:
        """Per-(host, kind) cycle charges: relays pay onion-crypto work
        for every delivered segment (KIND_PKT_RX). Takes effect only on
        hosts whose config sets cpufrequency — build_simulation converts
        cycles to virtual-CPU nanoseconds there."""
        from shadow_tpu.transport.stack import KIND_PKT_RX
        from shadow_tpu.transport.tcp import MSS

        cy = np.zeros((self._role.shape[0], n_kinds), np.int64)
        cy[self._role == ROLE_RELAY, KIND_PKT_RX] = (
            RELAY_CYCLES_PER_BYTE * MSS
        )
        return cy

    # ------------------------------------------------- client fetch kind
    def _on_fetch(self, hs, ev: Events, key):
        """Open the circuit connection (first fetch) / issue a request."""
        stack, tcp = self._stack, self._stack.tcp
        app: TorApp = hs.app
        cid = app.circ_id
        is_client = (app.role == ROLE_CLIENT) & (cid >= 0)
        ok = is_client & (app.streams_started < app.cl_count)
        cidc = jnp.maximum(cid, 0)
        first = ok & (app.streams_started == 0)

        cs = hs.net.tcb.state.shape[0] - 1  # dedicated circuit slot (top)
        sk = hs.net.sockets
        w = lambda a, v: _put(a, cs, v, first)
        sk = dataclasses.replace(
            sk,
            proto=w(sk.proto, PROTO_TCP),
            local_port=w(sk.local_port, CIRC_PORT_BASE + cidc),
            peer_host=w(sk.peer_host, app.cl_guard),
            peer_port=w(sk.peer_port, jnp.int32(OR_PORT)),
        )
        app = dataclasses.replace(
            app, streams_started=app.streams_started + ok.astype(_I32)
        )
        hs = dataclasses.replace(
            hs, app=app, net=dataclasses.replace(hs.net, sockets=sk)
        )
        hs, em_conn = tcp.connect(stack, hs, cs, ev.time, mask=first)
        hs, em_req = tcp.send(hs, cs, REQ_BYTES, ev.time, mask=ok)
        return hs, emit_concat(em_conn, em_req)

    # -------------------------------------------------------- deliveries
    def _on_recv(self, hs, slot, pkt, now, key):
        """Role dispatch on every delivered chunk/EOF."""
        stack, tcp = self._stack, self._stack.tcp
        app: TorApp = hs.app
        got = slot >= 0
        s = jnp.maximum(slot, 0)
        eof = got & ((pkt.flags & F_FIN) != 0)
        dlen = jnp.where(got, pkt.length.astype(_I64), 0)

        # ---------------- relay: forward bytes along the circuit
        is_relay = got & (app.role == ROLE_RELAY)
        have_fwd = _sel(app.fwd, s) >= 0
        # new inbound circuit conn: source port encodes the circuit;
        # the next hop comes from this host's OWN [CM] circuit table
        cid = pkt.src_port - CIRC_PORT_BASE
        # one lookup serves both roles: relays read the next hop,
        # servers read the served filesize (a host is only ever one)
        tc_found, nxt_gid, nxt_port, tc_fsz = _tc_lookup(app, cid)
        new_circ = is_relay & ~have_fwd & (cid >= 0) & tc_found
        cidc = jnp.maximum(cid, 0)

        # allocate the outbound slot: last free (children fill from 0 up)
        free = hs.net.sockets.proto == PROTO_NONE
        ns = free.shape[0]
        out_slot = (ns - 1 - jnp.argmax(free[::-1])).astype(_I32)
        can_open = new_circ & jnp.any(free)

        sk = hs.net.sockets
        w = lambda a, v: _put(a, out_slot, v, can_open)
        sk = dataclasses.replace(
            sk,
            proto=w(sk.proto, PROTO_TCP),
            local_port=w(sk.local_port, CIRC_PORT_BASE + cidc),
            peer_host=w(sk.peer_host, nxt_gid),
            peer_port=w(sk.peer_port, nxt_port),
        )
        fwd = app.fwd
        fwd = _put(fwd, s, out_slot, can_open)
        fwd = _put(fwd, out_slot, s, can_open)
        app = dataclasses.replace(
            app,
            fwd=fwd,
            relayed_bytes=app.relayed_bytes
            + jnp.where(is_relay, dlen, 0),
        )
        hs = dataclasses.replace(
            hs, app=app, net=dataclasses.replace(hs.net, sockets=sk)
        )
        hs, em_open = tcp.connect(stack, hs, out_slot, now, mask=can_open)

        fwd_to = _sel(hs.app.fwd, s)
        do_fwd = is_relay & (fwd_to >= 0) & (dlen > 0)
        hs, em_fwd = tcp.send(hs, fwd_to, dlen, now, mask=do_fwd)
        do_close = is_relay & (fwd_to >= 0) & eof
        hs, em_fc = tcp.close(hs, fwd_to, now, mask=do_close)

        # ---------------- server: answer each request cell with filesize
        app = hs.app
        is_server = got & (app.role == ROLE_SERVER)
        prev = _sel(app.req_rx, s)
        newr = prev + jnp.where(is_server, dlen, 0)
        n_req = (newr // REQ_BYTES - prev // REQ_BYTES).astype(_I64)
        app = dataclasses.replace(
            app, req_rx=_put(app.req_rx, s, newr, got)
        )
        hs = dataclasses.replace(hs, app=app)
        reply = n_req * tc_fsz
        hs, em_srv = tcp.send(
            hs, s, reply, now, mask=is_server & tc_found & (reply > 0)
        )

        # ---------------- client: count reply bytes, schedule next fetch
        app = hs.app
        is_client = got & (app.role == ROLE_CLIENT) & (app.circ_id >= 0)
        rx2 = app.conn_rx + jnp.where(is_client, dlen, 0)
        done_now = jnp.minimum(
            (rx2 // jnp.maximum(app.cl_file, 1)).astype(_I32),
            app.streams_started,
        )
        newly = is_client & (done_now > app.streams_done)
        app = dataclasses.replace(
            app,
            conn_rx=rx2,
            streams_done=jnp.where(newly, done_now, app.streams_done),
            t_last_done=jnp.where(newly, now, app.t_last_done),
        )
        hs = dataclasses.replace(hs, app=app)
        more = newly & (app.streams_done < app.cl_count)
        pk_ = app.streams_done % jnp.maximum(app.cl_npause, 1)
        pause = jnp.sum(
            jnp.where(
                jnp.arange(4, dtype=_I32) == pk_, app.cl_pause,
                jnp.int64(0),
            ),
            dtype=_I64,
        )
        em_next = Emit.single(
            dst=0, dt=pause, kind=self._kind_fetch, mask=more, local=True,
            n_args=N_PKT_ARGS,
        )

        # rows: open(2 rows) | fwd send + fwd close | server reply | next
        em_a = emit_concat(em_fwd, em_fc)
        em_b = emit_concat(em_srv, em_next)
        # merge mutually-exclusive row groups to stay within 4 rows:
        # relay rows never coexist with server/client rows on one host
        merged = jax.tree.map(
            lambda x, y: jnp.where(
                jnp.broadcast_to(
                    is_relay.reshape((1,) + (1,) * (x.ndim - 1)), x.shape
                ),
                x, y,
            ),
            em_a, em_b,
        )
        return hs, emit_concat(em_open, merged)


def churn_scenario(
    n_relays_per_class: int = 10,
    n_clients: int = 100,
    churn_frac: float = 0.2,
    churn_period: float = 20.0,
    churn_downtime: float = 5.0,
    churn_start: float = 10.0,
    stoptime: int = 60,
    **kw,
):
    """Parsed relay-churn config: the Tor example with >= `churn_frac` of
    the guard/middle/exit relays crashing and restarting on a cycle (the
    live-overlay dynamic the reference cannot model — topology.c freezes
    packetloss at load time). Build with `build_simulation(cfg)`; relay
    selection and cycle phases draw from the named fault stream, so the
    same seed gives the same churn timeline on any mesh and across a
    checkpoint/restore (docs/6-Fault-Injection.md).
    """
    from shadow_tpu.config import parse_config
    from shadow_tpu.examples import tor_churn_example

    return parse_config(tor_churn_example(
        n_relays_per_class=n_relays_per_class, n_clients=n_clients,
        churn_frac=churn_frac, churn_period=churn_period,
        churn_downtime=churn_downtime, churn_start=churn_start,
        stoptime=stoptime, **kw,
    ))

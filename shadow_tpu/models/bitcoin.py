"""Bitcoin-like block gossip: INV/GETDATA/BLOCK over a random peer graph.

BASELINE.md config 5 is a 5k-node Bitcoin P2P gossip network measuring
block propagation (the reference runs real bitcoind via
shadow-plugin-bitcoin). This jitted model reproduces that workload's
traffic pattern: a static random peer graph of persistent TCP links,
miners announcing sequentially-numbered blocks at an interval, and the
classic three-step relay — INV announce → GETDATA request → block body —
with duplicate suppression by each node's best-known block.

Deviations (documented for the parity check): INV/GETDATA control
messages ride small UDP datagrams whose aux word carries
(type << 24 | block id) — the device TCP moves byte counts, not app
payloads, so control goes out-of-band while the ~1MiB block *bodies* flow
through the persistent TCP connections (where congestion/queueing
matters). Each peer pair shares exactly one TCP link (dialed by the
lower gid), and a node downloads one block at a time.

Arguments per <process>:
  node [miner] [peers=4] [blocksize=1MiB] [interval=600] [blocks=10]
"""

from __future__ import annotations

import dataclasses
import random as pyrandom

import jax
import jax.numpy as jnp
import numpy as np

from shadow_tpu.config import parse_kv_arguments, parse_size
from shadow_tpu.core.engine import Emit
from shadow_tpu.core.events import Events
from shadow_tpu.core.timebase import SECOND
from shadow_tpu.host.sockets import PROTO_TCP, PROTO_UDP
from shadow_tpu.transport.stack import N_PKT_ARGS
from shadow_tpu.transport.tcp import ESTABLISHED, _put, _sel, emit_concat

_I32 = jnp.int32
_I64 = jnp.int64

GOSSIP_PORT = 8333   # UDP control plane
LINK_PORT = 8334     # TCP block-body links
INV_BYTES = 61       # wire sizes of the real messages (approx)
GETDATA_BYTES = 61

T_INV = 1
T_GETDATA = 2


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class BtcApp:
    gid: jax.Array  # i32
    is_node: jax.Array  # bool
    best: jax.Array  # i32 highest fully-received block (0 = genesis)
    curr_dl: jax.Array  # i32 block id being downloaded (-1)
    pending: jax.Array  # i32[S] block id expected on this TCP slot (-1)
    target: jax.Array  # i64[S] dl_rx threshold that completes it
    dl_rx: jax.Array  # i64[S] cumulative TCP bytes delivered per slot
    t_best: jax.Array  # i64 sim time `best` was reached (propagation metric)


class BitcoinModel:
    name = "bitcoin"
    needs_tcp = True
    n_kinds = 2  # KIND_DIAL (link setup), KIND_MINE (miner tick)

    MAX_PEERS = 6

    def __init__(self):
        self._stack = None
        self._kind_dial = None
        self._kind_mine = None

    def app_rows(self) -> int:
        # completion announce: INV to every peer; or GETDATA reply; union
        return self.MAX_PEERS

    def handler_rows(self) -> int:
        # dial: connect(2) x outbound links is sequenced one per event;
        # mine: INV fanout + next tick
        return self.MAX_PEERS + 1

    # ------------------------------------------------------------- build
    def build(self, b):
        n = b.n_hosts
        is_node = np.zeros((n,), bool)
        miner = np.zeros((n,), bool)
        kpeers = np.full((n,), 4, np.int32)
        blocksize = 1 << 20
        interval_s = 600.0
        n_blocks = 10

        for h in b.hosts:
            for proc in h.spec.processes:
                kv = parse_kv_arguments(proc.arguments)
                if "node" not in kv:
                    raise ValueError(
                        f"bitcoin process on {h.name!r}: arguments must "
                        "include 'node'"
                    )
                is_node[h.gid] = True
                miner[h.gid] = "miner" in kv
                kpeers[h.gid] = min(int(kv.get("peers", 4)), self.MAX_PEERS)
                if "blocksize" in kv:
                    blocksize = parse_size(kv["blocksize"])
                if "interval" in kv:
                    interval_s = float(kv["interval"])
                if "blocks" in kv:
                    n_blocks = int(kv["blocks"])
                # UDP control socket + TCP link listener
                b.sockets = b.sockets.bind(h.gid, 0, PROTO_UDP, GOSSIP_PORT)
                b.sockets = b.sockets.bind(h.gid, 1, PROTO_TCP, LINK_PORT)
                b.tcb = b.tcb.listen(h.gid, 1)
                b.add_start_event(h.gid, proc.starttime, 0)  # dial links
                if miner[h.gid]:
                    b.add_start_event(
                        h.gid, proc.starttime + interval_s, 1
                    )

        # deterministic random peer graph; each undirected edge is dialed
        # by its lower-gid endpoint so every pair shares exactly one link
        nodes = np.nonzero(is_node)[0]
        rng = pyrandom.Random(0xB17C)
        edges: set[tuple[int, int]] = set()
        for g in nodes:
            want = int(kpeers[g])
            tries = 0
            while (
                sum(1 for e in edges if g in e) < want
                and tries < 10 * want
                and len(nodes) > 1
            ):
                p = int(rng.choice(nodes))
                tries += 1
                if p != g:
                    edges.add((min(g, p), max(g, p)))

        peers = np.full((n, self.MAX_PEERS), -1, np.int32)
        dials = np.full((n, self.MAX_PEERS), -1, np.int32)
        deg = np.zeros((n,), np.int32)
        ndial = np.zeros((n,), np.int32)
        for a, c in sorted(edges):
            # keep an edge only if both endpoints have capacity, so the
            # peer lists and the dialed links describe the same graph
            if deg[a] >= self.MAX_PEERS or deg[c] >= self.MAX_PEERS:
                continue
            peers[a, deg[a]] = c
            peers[c, deg[c]] = a
            deg[a] += 1
            deg[c] += 1
            dials[a, ndial[a]] = c
            ndial[a] += 1

        self._g = dict(
            peers=jnp.asarray(peers),
            n_peers=jnp.asarray(deg),
            dials=jnp.asarray(dials),
            n_dials=jnp.asarray(ndial),
            blocksize=jnp.int64(blocksize),
            interval_ns=jnp.int64(int(interval_s * SECOND)),
            n_blocks=jnp.int32(n_blocks),
            miner=jnp.asarray(miner),
        )

        s = b.n_sockets
        state = BtcApp(
            gid=jnp.arange(n, dtype=_I32),
            is_node=jnp.asarray(is_node),
            best=jnp.zeros((n,), _I32),
            curr_dl=jnp.full((n,), -1, _I32),
            pending=jnp.full((n, s), -1, _I32),
            target=jnp.zeros((n, s), _I64),
            dl_rx=jnp.zeros((n, s), _I64),
            t_best=jnp.zeros((n,), _I64),
        )
        # frontier-drain eligibility (sim.build_simulation): the dial
        # chain re-arms at a 10 ms constant and the miner tick at
        # interval_ns; both must be >= 1 ns for the run-rule invariant
        self._frontier_safe = int(interval_s * SECOND) >= 1
        return state, self._make_handlers, self._on_recv

    @property
    def frontier_safe(self) -> bool:
        """True when every local emit delay this build can schedule is
        provably >= 1 ns — the engine frontier drain's run-rule
        invariant (docs/11-Performance.md, "Model-tier batching")."""
        return getattr(self, "_frontier_safe", False)

    def frontier_kinds(self) -> tuple:
        """Model kinds eligible for multi-position frontier runs (all of
        them: dial/mine re-arms are interval-delayed, announces are
        TCP-floored)."""
        return tuple(range(self.n_kinds))

    def _make_handlers(self, stack, kind_base):
        self._stack = stack
        self._kind_dial = kind_base
        self._kind_mine = kind_base + 1
        return [self._on_dial, self._on_mine]

    # ---------------------------------------------------------- link setup
    def _on_dial(self, hs, ev: Events, key):
        """Dial one outbound link per event, chaining until all are up.

        args[0] = dial index. Out slot for dial i = S-1-i (children fill
        from low slots; slot 0/1 are the UDP socket and the listener).
        """
        stack, tcp, g = self._stack, self._stack.tcp, self._g
        app: BtcApp = hs.app
        me = app.gid
        i = ev.args[0]
        nd = g["n_dials"][me]
        ok = app.is_node & (i < nd)
        peer = g["dials"][me, jnp.clip(i, 0, self.MAX_PEERS - 1)]

        s = hs.app.pending.shape[0]
        out_slot = s - 1 - jnp.clip(i, 0, self.MAX_PEERS - 1)
        sk = hs.net.sockets
        w = lambda a, v: _put(a, out_slot, v, ok)
        sk = dataclasses.replace(
            sk,
            proto=w(sk.proto, PROTO_TCP),
            local_port=w(sk.local_port, LINK_PORT + 1 + i),
            peer_host=w(sk.peer_host, jnp.maximum(peer, 0)),
            peer_port=w(sk.peer_port, LINK_PORT),
        )
        hs = dataclasses.replace(hs, net=dataclasses.replace(hs.net, sockets=sk))
        hs, em_conn = tcp.connect(stack, hs, out_slot, ev.time, mask=ok)
        em_next = Emit.single(
            dst=0, dt=10_000_000, kind=self._kind_dial,
            args=[i + 1], mask=ok & (i + 1 < nd), local=True,
            n_args=N_PKT_ARGS,
        )
        return hs, emit_concat(em_conn, em_next)

    # ------------------------------------------------------------- mining
    def _on_mine(self, hs, ev: Events, key):
        """Miner tick: adopt a new block, announce INV to all peers."""
        g = self._g
        app: BtcApp = hs.app
        me = app.gid
        mine = g["miner"][me] & (app.best < g["n_blocks"])
        new_best = app.best + mine.astype(_I32)
        app = dataclasses.replace(
            app,
            best=new_best,
            t_best=jnp.where(mine, ev.time, app.t_best),
        )
        hs = dataclasses.replace(hs, app=app)
        hs, em_inv = self._announce(hs, new_best, ev.time, mine)
        em_next = Emit.single(
            dst=0, dt=g["interval_ns"], kind=self._kind_mine,
            mask=mine & (new_best < g["n_blocks"]), local=True,
            n_args=N_PKT_ARGS,
        )
        return hs, emit_concat(em_inv, em_next)

    def _announce(self, hs, block_id, now, mask):
        """INV(block_id) to every peer (UDP fanout)."""
        g = self._g
        me = hs.app.gid
        ems = []
        for j in range(self.MAX_PEERS):
            peer = g["peers"][me, j]
            m = mask & (peer >= 0)
            hs, em = self._stack.send_udp(
                hs, now, 0, jnp.maximum(peer, 0), GOSSIP_PORT, INV_BYTES,
                aux=(T_INV << 24) | block_id, mask=m,
            )
            ems.append(em)
        return hs, emit_concat(*ems)

    # ---------------------------------------------------------- deliveries
    def _on_recv(self, hs, slot, pkt, now, key):
        stack, tcp, g = self._stack, self._stack.tcp, self._g
        app: BtcApp = hs.app
        me = app.gid
        got = (slot >= 0) & app.is_node
        s = jnp.maximum(slot, 0)
        is_udp = got & (pkt.proto == PROTO_UDP)
        mtype = pkt.aux >> 24
        mblock = pkt.aux & 0xFFFFFF

        # find the single TCP link shared with a given peer
        def link_slot(peer):
            sk = hs.net.sockets
            match = (
                (sk.proto == PROTO_TCP)
                & (sk.peer_host == peer)
                & (hs.net.tcb.state >= ESTABLISHED)
            )
            return jnp.where(
                jnp.any(match), jnp.argmax(match).astype(_I32), -1
            )

        # -- INV: request the block if it's news and we're idle
        want = (
            is_udp & (mtype == T_INV) & (mblock > app.best)
            & (app.curr_dl < 0)
        )
        lslot = link_slot(pkt.src_host)
        want &= lslot >= 0  # link not up yet: a later INV will retry
        hs2, em_get = stack.send_udp(
            hs, now, 0, pkt.src_host, GOSSIP_PORT, GETDATA_BYTES,
            aux=(T_GETDATA << 24) | mblock, mask=want,
        )
        app = hs2.app
        ls = jnp.maximum(lslot, 0)
        app = dataclasses.replace(
            app,
            curr_dl=jnp.where(want, mblock, app.curr_dl),
            pending=_put(app.pending, ls, mblock, want),
            target=_put(
                app.target, ls, _sel(app.dl_rx, ls) + g["blocksize"], want
            ),
        )
        hs = dataclasses.replace(hs2, app=app)

        # -- GETDATA: push the block body down the shared TCP link
        serve = is_udp & (mtype == T_GETDATA) & (mblock <= app.best)
        sslot = link_slot(pkt.src_host)
        serve &= sslot >= 0
        hs, em_body = tcp.send(
            hs, jnp.maximum(sslot, 0), g["blocksize"], now, mask=serve
        )

        # -- TCP bytes: accumulate; completion adopts + re-announces
        is_tcp_data = got & (pkt.proto == PROTO_TCP) & (pkt.length > 0)
        app = hs.app
        dl2 = app.dl_rx + jnp.where(
            (jnp.arange(app.dl_rx.shape[0], dtype=_I32) == s) & is_tcp_data,
            pkt.length.astype(_I64), 0,
        )
        complete = (
            is_tcp_data & (_sel(app.pending, s) >= 0)
            & (_sel(dl2, s) >= _sel(app.target, s))
        )
        new_best = jnp.where(
            complete, jnp.maximum(app.best, _sel(app.pending, s)), app.best
        )
        app = dataclasses.replace(
            app,
            dl_rx=dl2,
            best=new_best,
            t_best=jnp.where(complete, now, app.t_best),
            curr_dl=jnp.where(complete, -1, app.curr_dl),
            pending=_put(app.pending, s, -1, complete),
        )
        hs = dataclasses.replace(hs, app=app)
        hs, em_inv = self._announce(hs, new_best, now, complete)

        # merge mutually-exclusive row groups (a UDP control delivery and
        # a TCP data delivery never happen in the same event)
        em_ctl = emit_concat(em_get, em_body).pad_to(self.MAX_PEERS)
        merged = jax.tree.map(
            lambda x, y: jnp.where(
                jnp.broadcast_to(
                    is_udp.reshape((1,) + (1,) * (x.ndim - 1)), x.shape
                ),
                x, y,
            ),
            em_ctl, em_inv.pad_to(self.MAX_PEERS),
        )
        return hs, merged

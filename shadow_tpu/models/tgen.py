"""TGen-like traffic generator, compiled into the device step.

The reference drives its example/benchmark configs with TGen — a traffic
generator whose behavior is an action graph (GraphML) of
start -> stream -> end -> pause -> ... nodes (reference:
resource/examples/shadow.config.xml runs plugin tgen with
tgen.client.graphml.xml / tgen.server.graphml.xml; the client graph's
stream node carries sendsize/recvsize, the end node a stream count, the
pause node a comma list of wait seconds; the server graph is a start node
with a serverport).

Model semantics (the jitted app tier of SURVEY.md §7 step 6a):

- A *server* host binds a TCP listener on `serverport` at process start.
- A *client* host runs `count` sequential streams against its peer list
  (round-robin): each stream opens a fresh connection (fresh ephemeral
  port), sends `sendsize` bytes, then half-closes. The server replies to
  the stream EOF with `recvsize` bytes (looked up from the client's own
  static config table — the real tgen transmits the size inside its
  command header; metadata-only packets can't carry app bytes, so the
  server reads the global config table by the client's gid instead) and
  closes. The client counts reply bytes; on completion it waits `pause`
  (cycling the choices) and starts the next stream.

Deliberate deviations (documented for the parity check):
- a zero sendsize is sent as 1 byte (the command-header stand-in);
- one concurrent outbound stream per host (tgen graphs can fan out);
- the pause choice cycles round-robin instead of uniformly at random.

Arguments accepted per <process>: a path to a tgen GraphML file (like the
reference's configs) or an inline 'k=v' string: `server port=8888` /
`peers=server:8888,b:80 sendsize=1MiB recvsize=1MiB count=10 pause=1,2,3
time=0`.
"""

from __future__ import annotations

import dataclasses
import os

import jax
import jax.numpy as jnp
import numpy as np

from shadow_tpu.config import parse_kv_arguments, parse_size, resolve_path
from shadow_tpu.core.engine import Emit
from shadow_tpu.core.events import Events
from shadow_tpu.core.timebase import SECOND
from shadow_tpu.host.sockets import EPHEMERAL_BASE, PROTO_TCP
from shadow_tpu.transport.stack import F_FIN, N_PKT_ARGS
from shadow_tpu.transport.tcp import emit_concat

try:
    import networkx as nx
except ImportError:  # pragma: no cover
    nx = None

_I32 = jnp.int32
_I64 = jnp.int64


@dataclasses.dataclass
class TGenProfile:
    """One host's parsed tgen behavior."""

    server_port: int = -1  # >=0: listen
    peers: list[tuple[str, int]] = dataclasses.field(default_factory=list)
    sendsize: int = 0
    recvsize: int = 0
    count: int = 1
    pause_s: list[float] = dataclasses.field(default_factory=lambda: [1.0])
    start_delay_s: float = 0.0


def parse_tgen_graphml(text: str) -> TGenProfile:
    """Subset of the tgen action-graph format (see module docstring)."""
    if nx is None:  # pragma: no cover
        raise RuntimeError("networkx unavailable")
    g = nx.parse_graphml(text)
    prof = TGenProfile()
    for nid, a in g.nodes(data=True):
        nid_l = str(nid).lower()
        if nid_l.startswith("start"):
            if "serverport" in a:
                prof.server_port = int(a["serverport"])
            if "peers" in a:
                prof.peers = [
                    (p.rsplit(":", 1)[0], int(p.rsplit(":", 1)[1]))
                    for p in str(a["peers"]).split(",") if p.strip()
                ]
            if "time" in a:
                prof.start_delay_s = float(str(a["time"]).split(",")[0])
        elif nid_l.startswith("stream") or nid_l.startswith("transfer"):
            if "sendsize" in a:
                prof.sendsize = parse_size(a["sendsize"])
            if "recvsize" in a:
                prof.recvsize = parse_size(a["recvsize"])
            # legacy <transfer> node: type get/put + filesize
            if "filesize" in a:
                size = parse_size(a["filesize"])
                if str(a.get("type", "get")).lower() == "get":
                    prof.recvsize = size
                else:
                    prof.sendsize = size
        elif nid_l.startswith("pause"):
            if "time" in a:
                prof.pause_s = [
                    float(t) for t in str(a["time"]).split(",") if t.strip()
                ]
        elif nid_l.startswith("end"):
            if "count" in a:
                prof.count = int(a["count"])
    return prof


def parse_arguments(args: str, base_dir: str) -> TGenProfile:
    args = args.strip()
    if args and " " not in args and (
        args.endswith(".xml") or args.endswith(".graphml")
    ):
        path = resolve_path(args, base_dir)
        if os.path.exists(path):
            with open(path) as f:
                return parse_tgen_graphml(f.read())
        raise FileNotFoundError(f"tgen graph file not found: {args!r}")
    kv = parse_kv_arguments(args)
    prof = TGenProfile()
    if "server" in kv or "serverport" in kv:
        prof.server_port = int(kv.get("serverport") or kv.get("port", 8888))
    if "peers" in kv:
        prof.peers = [
            (p.rsplit(":", 1)[0], int(p.rsplit(":", 1)[1]))
            for p in kv["peers"].split(",") if p.strip()
        ]
    if "sendsize" in kv:
        prof.sendsize = parse_size(kv["sendsize"])
    if "recvsize" in kv:
        prof.recvsize = parse_size(kv["recvsize"])
    if "count" in kv:
        prof.count = int(kv["count"])
    if "pause" in kv:
        prof.pause_s = [float(t) for t in kv["pause"].split(",") if t.strip()]
    if "time" in kv:
        prof.start_delay_s = float(kv["time"])
    return prof


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class TGenState:
    """Dynamic per-host app state ([H] at rest; scalar lanes under vmap).

    `gid` is the host's own global id — static, but carried in the state
    pytree so vmapped handlers can index global config tables for their
    own lane (the engine batches host state; closures aren't sliced).

    The same every-parameter-in-the-state discipline is what lets a
    whole tgen scenario join a fleet (`sim.build_fleet`, docs/16):
    under the fleet vmap this state gains a leading lane axis
    ([L, H, ...]) and the `tgen_fleet` hlo_audit contract pins that the
    lowered op counts stay lane-count-independent.
    """

    gid: jax.Array  # i32 (static iota)
    streams_started: jax.Array  # i32
    streams_done: jax.Array  # i32
    conn_rx: jax.Array  # i64 bytes received on the current outbound stream
    t_last_done: jax.Array  # i64 sim time the last stream completed


class TGenModel:
    """AppModel implementation (see shadow_tpu.sim.AppModel)."""

    name = "tgen"
    needs_tcp = True
    n_kinds = 1  # KIND_STREAM: start/continue the client stream loop

    def __init__(self):
        self._stack = None
        self._kind_stream = None

    def app_rows(self) -> int:
        return 3  # server: reply send + close; client: next-stream event

    def handler_rows(self) -> int:
        return 4  # connect(2) + send(1) + close(1)

    def build(self, b):
        n = b.n_hosts
        server_port = np.full((n,), -1, np.int32)
        sendsize = np.zeros((n,), np.int64)
        recvsize = np.zeros((n,), np.int64)
        count = np.zeros((n,), np.int32)
        profiles: list[TGenProfile | None] = [None] * n

        for h in b.hosts:
            if len(h.spec.processes) > 1:
                # one tgen process per host for now: profiles are per-host
                # arrays and clients own a single stream slot, so a second
                # process would silently clobber the first mid-flight
                raise ValueError(
                    f"host {h.name!r} declares {len(h.spec.processes)} tgen "
                    "processes; the jitted tgen model supports one per host"
                )
            for proc in h.spec.processes:
                prof = parse_arguments(proc.arguments, b.cfg.base_dir)
                profiles[h.gid] = prof
                server_port[h.gid] = prof.server_port
                sendsize[h.gid] = max(prof.sendsize, 1)
                recvsize[h.gid] = prof.recvsize
                count[h.gid] = prof.count if prof.peers else 0
                b.add_start_event(
                    h.gid, proc.starttime + prof.start_delay_s, 0
                )

        max_peers = max((len(p.peers) for p in profiles if p), default=0) or 1
        peer_gid = np.zeros((n, max_peers), np.int32)
        peer_port = np.zeros((n, max_peers), np.int32)
        n_peers = np.zeros((n,), np.int32)
        max_pause = max((len(p.pause_s) for p in profiles if p), default=0) or 1
        pause_ns = np.full((n, max_pause), SECOND, np.int64)
        n_pause = np.ones((n,), np.int32)
        for h in b.hosts:
            prof = profiles[h.gid]
            if prof is None:
                continue
            for j, (pname, pport) in enumerate(prof.peers):
                peer_gid[h.gid, j] = b.resolve_gid(pname)
                peer_port[h.gid, j] = pport
            n_peers[h.gid] = len(prof.peers)
            for j, t in enumerate(prof.pause_s):
                pause_ns[h.gid, j] = int(t * SECOND)
            n_pause[h.gid] = max(len(prof.pause_s), 1)

        # static listener binds (slot 0) — the reference binds listeners
        # during process start (host.c:773-900)
        for gid in range(n):
            if server_port[gid] >= 0:
                b.sockets = b.sockets.bind(
                    gid, 0, PROTO_TCP, int(server_port[gid])
                )
                b.tcb = b.tcb.listen(gid, 0)

        cs = b.n_sockets - 1  # dedicated client-stream slot (children
        # allocate first-free from 0, so the ends never collide)
        self._g = dict(
            peer_gid=jnp.asarray(peer_gid),
            peer_port=jnp.asarray(peer_port),
            n_peers=jnp.asarray(n_peers),
            sendsize=jnp.asarray(sendsize),
            recvsize=jnp.asarray(recvsize),
            count=jnp.asarray(count),
            pause_ns=jnp.asarray(pause_ns),
            n_pause=jnp.asarray(n_pause),
        )
        self._cs = cs
        # frontier-drain eligibility (sim.build_simulation): inter-stream
        # pauses are this model's only local emit delays; unused table
        # rows keep the SECOND default, so the check covers exactly the
        # configured clients
        self._frontier_safe = bool((pause_ns >= 1).all())

        z32 = jnp.zeros((n,), _I32)
        state = TGenState(
            gid=jnp.arange(n, dtype=_I32),
            streams_started=z32,
            streams_done=z32,
            conn_rx=jnp.zeros((n,), _I64),
            t_last_done=jnp.zeros((n,), _I64),
        )
        return state, self._make_handlers, self._on_recv

    @property
    def frontier_safe(self) -> bool:
        """True when every local emit delay this build can schedule is
        provably >= 1 ns — the engine frontier drain's run-rule
        invariant (docs/11-Performance.md, "Model-tier batching")."""
        return getattr(self, "_frontier_safe", False)

    def frontier_kinds(self) -> tuple:
        """Model kinds eligible for multi-position frontier runs (all of
        them: KIND_STREAM's emits are pause-delayed or TCP-floored)."""
        return tuple(range(self.n_kinds))

    # ---------------------------------------------------------- handlers
    def _make_handlers(self, stack, kind_base):
        self._stack = stack
        self._kind_stream = kind_base
        return [self._on_stream]

    def _on_stream(self, hs, ev: Events, key):
        """KIND_STREAM: open the next outbound stream (clients only)."""
        stack, tcp, g, cs = self._stack, self._stack.tcp, self._g, self._cs
        app: TGenState = hs.app
        me = app.gid
        ok = (g["n_peers"][me] > 0) & (app.streams_started < g["count"][me])
        idx = app.streams_started
        pidx = idx % jnp.maximum(g["n_peers"][me], 1)
        peer = g["peer_gid"][me, pidx]
        pport = g["peer_port"][me, pidx]
        sport = EPHEMERAL_BASE + idx

        # rebind the client slot for a fresh connection (fresh ephemeral
        # port per stream = TIME_WAIT safety; host.c:1058-1110 random-port
        # allocation becomes a deterministic per-stream port)
        sk = hs.net.sockets
        w = lambda a, v: a.at[cs].set(jnp.where(ok, v, a[cs]))
        sk = dataclasses.replace(
            sk,
            proto=w(sk.proto, PROTO_TCP),
            local_port=w(sk.local_port, sport),
            peer_host=w(sk.peer_host, peer),
            peer_port=w(sk.peer_port, pport),
        )
        app = dataclasses.replace(
            app,
            streams_started=app.streams_started + ok.astype(_I32),
            conn_rx=jnp.where(ok, 0, app.conn_rx),
        )
        hs = dataclasses.replace(
            hs, app=app, net=dataclasses.replace(hs.net, sockets=sk)
        )
        hs, em1 = tcp.connect(stack, hs, cs, ev.time, mask=ok)
        hs, em2 = tcp.send(hs, cs, g["sendsize"][me], ev.time, mask=ok)
        hs, em3 = tcp.close(hs, cs, ev.time, mask=ok)
        return hs, emit_concat(em1, em2, em3)

    def _on_recv(self, hs, slot, pkt, now, key):
        """Demuxed delivery: client reply accounting + server EOF reply."""
        tcp, g, cs = self._stack.tcp, self._g, self._cs
        app: TGenState = hs.app
        me = app.gid
        got = slot >= 0
        eof = got & ((pkt.flags & F_FIN) != 0)
        is_client_sock = got & (slot == cs)

        # ---- client: count reply bytes, detect stream completion
        before = app.conn_rx
        after = before + jnp.where(is_client_sock, pkt.length.astype(_I64), 0)
        need = g["recvsize"][me]
        bytes_done = is_client_sock & (before < need) & (after >= need)
        eof_done = is_client_sock & eof & (after >= need)
        newly = (bytes_done | eof_done) & (
            app.streams_done < app.streams_started
        )
        done_idx = app.streams_done
        app = dataclasses.replace(
            app,
            conn_rx=after,
            streams_done=app.streams_done + newly.astype(_I32),
            t_last_done=jnp.where(newly, now, app.t_last_done),
        )
        hs = dataclasses.replace(hs, app=app)

        # next stream after the cycling pause choice
        more = newly & (app.streams_done < g["count"][me])
        pause = g["pause_ns"][me, done_idx % jnp.maximum(g["n_pause"][me], 1)]
        em_next = Emit.single(
            dst=0, dt=pause, kind=self._kind_stream, mask=more, local=True,
            n_args=N_PKT_ARGS,
        )

        # ---- server: reply to stream EOF (size from the client's static
        # config), then close
        do_reply = eof & ~is_client_sock
        # cross-host lookup is the point: the server replies with the
        # CLIENT's configured recvsize, known only from its static row
        reply_sz = g["recvsize"][pkt.src_host]  # shadowlint: disable=SL112
        hs, em_s = tcp.send(hs, slot, reply_sz, now,
                            mask=do_reply & (reply_sz > 0))
        hs, em_c = tcp.close(hs, slot, now, mask=do_reply)
        return hs, emit_concat(em_s, em_c, em_next)

"""PHOLD over the real UDP stack (the config-driven variant).

The reference's PHOLD plugin sends 1-byte UDP datagrams to weighted-random
peers on port 8998: at startup each peer generates `load` messages, and
every received message triggers one new message to a weighted-random
target (reference: src/test/phold/test_phold.c:36-52 `_phold_chooseTarget`
weights, PHOLD_LISTEN_PORT 8998, config
src/test/phold/phold.test.shadow.config.xml arguments
"basename=peer quantity=10 load=25 weightsfilepath=weights.txt").

Unlike models/phold.py (the raw-engine microbenchmark), this model runs
each message through the full pipeline: socket -> tx NIC -> topology
latency/reliability -> CoDel -> rx NIC -> socket demux, so it doubles as a
stack stress test at config-selected scale.

Arguments: `basename=peer quantity=N load=K [weightsfilepath=weights.txt]`
(weights file: one float per line, weight of peer i; uniform if absent —
matching the plugin's behavior when weights are equal).
"""

from __future__ import annotations

import dataclasses
import os

import jax
import jax.numpy as jnp
import numpy as np

from shadow_tpu.config import parse_kv_arguments, resolve_path
from shadow_tpu.core import rng as srng
from shadow_tpu.core.engine import Emit
from shadow_tpu.core.events import Events
from shadow_tpu.host.sockets import PROTO_UDP
from shadow_tpu.transport.stack import N_PKT_ARGS

PHOLD_PORT = 8998  # test_phold.c PHOLD_LISTEN_PORT
_I32 = jnp.int32
_I64 = jnp.int64


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class PholdNetState:
    gid: jax.Array  # i32 own global id (vmap lane recovery)
    n_sent: jax.Array  # i64
    n_recv: jax.Array  # i64


class PholdNetModel:
    name = "phold"
    needs_tcp = False
    n_kinds = 1  # KIND_LOAD: emit one startup message, chain the next

    def __init__(self):
        self._stack = None
        self._kind_load = None

    def app_rows(self) -> int:
        return 1  # one relayed message per receive

    def handler_rows(self) -> int:
        return 2  # startup message + chain event

    def build(self, b):
        n = b.n_hosts
        load = np.zeros((n,), np.int32)
        member = np.zeros((n,), bool)
        weights = None
        for h in b.hosts:
            for proc in h.spec.processes:
                kv = parse_kv_arguments(proc.arguments)
                member[h.gid] = True
                load[h.gid] = int(kv.get("load", 1))
                wf = kv.get("weightsfilepath", "")
                if wf and weights is None:
                    path = resolve_path(wf, b.cfg.base_dir)
                    if os.path.exists(path):
                        with open(path) as f:
                            weights = np.asarray(
                                [float(x) for x in f.read().split() if x],
                                np.float64,
                            )
                b.add_start_event(h.gid, proc.starttime, 0, [load[h.gid]])
                b.sockets = b.sockets.bind(h.gid, 0, PROTO_UDP, PHOLD_PORT)

        targets = np.nonzero(member)[0].astype(np.int32)
        if weights is None or len(weights) != len(targets):
            weights = np.ones((len(targets),), np.float64)
        cdf = np.cumsum(weights / weights.sum())

        self._targets = jnp.asarray(targets)
        self._cdf = jnp.asarray(cdf, jnp.float32)

        state = PholdNetState(
            gid=jnp.arange(n, dtype=_I32),
            n_sent=jnp.zeros((n,), _I64),
            n_recv=jnp.zeros((n,), _I64),
        )
        return state, self._make_handlers, self._on_recv

    def _pick_target(self, key):
        """Weighted choice by inverse-CDF (the plugin walks its weight
        array the same way, test_phold.c _phold_chooseTarget)."""
        u = srng.uniform(key)
        idx = jnp.searchsorted(self._cdf, u)
        return self._targets[jnp.minimum(idx, len(self._targets) - 1)]

    def _send_one(self, hs, now, key, mask):
        stack = self._stack
        target = self._pick_target(key)
        hs, em = stack.send_udp(
            hs, now, 0, target, PHOLD_PORT, 1, mask=mask
        )
        app = hs.app
        app = dataclasses.replace(
            app, n_sent=app.n_sent + jnp.where(mask, 1, 0)
        )
        return dataclasses.replace(hs, app=app), em

    def _make_handlers(self, stack, kind_base):
        self._stack = stack
        self._kind_load = kind_base

        def on_load(hs, ev: Events, key):
            # emit one of the `load` startup messages, then chain the next
            # (keeps max_emit at 2 instead of `load`)
            remaining = ev.args[0]
            ok = remaining > 0
            hs, em_msg = self._send_one(hs, ev.time, key, ok)
            args = jnp.zeros((N_PKT_ARGS,), _I32).at[0].set(remaining - 1)
            em_next = Emit(
                dst=jnp.zeros((1,), _I32),
                dt=jnp.ones((1,), _I64),
                kind=jnp.full((1,), self._kind_load, _I32),
                args=args[None, :],
                mask=jnp.asarray(remaining > 1).reshape(1),
                local=jnp.ones((1,), bool),
            )
            return hs, jax.tree.map(
                lambda a, b_: jnp.concatenate([a, b_]), em_msg, em_next
            )

        return [on_load]

    def _on_recv(self, hs, slot, pkt, now, key):
        got = slot >= 0
        app = hs.app
        app = dataclasses.replace(
            app, n_recv=app.n_recv + jnp.where(got, 1, 0)
        )
        hs = dataclasses.replace(hs, app=app)
        return self._send_one(hs, now, key, got)

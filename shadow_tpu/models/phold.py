"""PHOLD: the classic parallel-DES benchmark, as a jitted host behavior.

The reference ships PHOLD as a plugin — N peers bounce UDP messages to
weighted-random targets (reference: src/test/phold/test_phold.c:36-52, config
src/test/phold/phold.test.shadow.config.xml). It is the natural first
benchmark for the engine (SURVEY.md §4, §6): every executed event emits one
new event to a random peer, so steady-state event population is constant and
events/sec is measured directly.

Here each host's behavior is a handler compiled into the device step: on
receiving a message, pick a uniform random peer and send a new message with
an exponential service delay.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from shadow_tpu.core import rng as srng
from shadow_tpu.core.engine import Emit, Engine, EngineConfig, ConstantNetwork
from shadow_tpu.core.events import Events
from shadow_tpu.core.timebase import MILLISECOND, TIME_INVALID

KIND_MSG = 0

# PHOLD events carry no payload; one arg word keeps the queue sorts narrow.
N_PHOLD_ARGS = 1


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class PholdHost:
    n_received: jax.Array  # i64[] per host

    @staticmethod
    def create(n_hosts: int) -> "PholdHost":
        return PholdHost(n_received=jnp.zeros((n_hosts,), jnp.int64))


def make_handler(
    n_hosts_global: int,
    mean_delay_ns: int,
    hot_hosts: int = 0,
    hot_weight: float = 0.0,
):
    """PHOLD message handler; optional skewed target weights.

    The reference's PHOLD supports non-uniform target selection via a
    weights file (reference: src/test/phold/test_phold.c:36-52 weights /
    totalWeight). Here the skew is parametric: with probability
    `hot_weight` the target is drawn from the first `hot_hosts` hosts —
    the classic hot-spot variant that collapses one-event-per-sweep
    schedulers.
    """

    draw = _make_draw(n_hosts_global, mean_delay_ns, hot_hosts, hot_weight)

    def on_msg(hs: PholdHost, ev: Events, key: jax.Array):
        peer, delay = draw(key)
        hs = PholdHost(n_received=hs.n_received + 1)
        return hs, Emit.single(dst=peer, dt=delay, kind=KIND_MSG, n_args=N_PHOLD_ARGS)

    return on_msg


def _make_draw(n_hosts_global, mean_delay_ns, hot_hosts, hot_weight):
    """The per-event (peer, delay) draw — one definition shared by the
    sequential and batched handlers, so the engine's bit-identity
    guarantee cannot be broken by the two drifting apart."""

    def draw(key):
        kp, kd, kh = srng.split(key, 3)
        peer = srng.randint(kp, 0, n_hosts_global)
        if hot_hosts > 0 and hot_weight > 0.0:
            hot = srng.uniform(kh) < hot_weight
            # folded sub-key: reusing kp here would correlate the hot
            # draw with the uniform one (peer_hot == peer % hot_hosts
            # whenever bounds divide); non-hot draws keep their keys so
            # plain-PHOLD trajectories are unchanged
            peer_hot = srng.randint(srng.fold_in(kp, 1), 0, hot_hosts)
            peer = jnp.where(hot, peer_hot, peer)
        delay = (
            srng.exponential(kd) * mean_delay_ns
        ).astype(jnp.int64)
        return peer, delay

    return draw


def make_batch_handler(
    n_hosts_global: int,
    mean_delay_ns: int,
    hot_hosts: int = 0,
    hot_weight: float = 0.0,
):
    """Whole-frontier PHOLD handler for the engine's commutative fast
    path: executes a host's [B] below-barrier events in one call. PHOLD
    qualifies — the state fold is a counter (order-insensitive) and every
    emit is a remote send (never local below the barrier). Per-position
    keys and the same split/draw sequence keep results bit-identical to
    the sequential path."""

    draw = _make_draw(n_hosts_global, mean_delay_ns, hot_hosts, hot_weight)

    def on_msgs(hs: PholdHost, evs: Events, keys: jax.Array):
        valid = evs.time != TIME_INVALID  # [B]
        peers, delays = jax.vmap(draw)(keys)
        hs = PholdHost(
            n_received=hs.n_received + jnp.sum(valid, dtype=jnp.int64)
        )
        b = valid.shape[0]
        em = Emit(
            dst=peers[:, None],
            dt=delays[:, None],
            kind=jnp.full((b, 1), KIND_MSG, jnp.int32),
            args=jnp.zeros((b, 1, N_PHOLD_ARGS), jnp.int32),
            mask=valid[:, None],
            local=jnp.zeros((b, 1), bool),
        )
        return hs, em

    return on_msgs


def build(
    n_hosts: int,
    *,
    hot_hosts: int = 0,
    hot_weight: float = 0.0,
    capacity: int = 64,
    latency_ns: int = 50 * MILLISECOND,
    mean_delay_ns: int = 10 * MILLISECOND,
    msgs_per_host: int = 1,
    seed: int = 0,
    axis_name: str | None = None,
    n_shards: int = 1,
    # 24 covers the steady-state frontier (Poisson tail ~1e-8 per host at
    # the stock load) while keeping the push's flat sorts -- which scale
    # with H*drain_batch -- 25% smaller than the engine's general default
    drain_batch: int = 24,
    batched: bool = False,
    trace: int = 0,
    stats: int = 0,
    spill: int = 0,
    kernel: str = "xla",
):
    """Build (engine, initial_state) for an n_hosts PHOLD network.

    The 50ms single-PoI topology matches the reference's stock config.
    With axis_name set, n_hosts is the *per-shard* host count.
    `batched` uses the engine's commutative fast path (whole frontiers
    per handler call); results are bit-identical either way.
    """
    cfg = EngineConfig(
        n_hosts=n_hosts,
        capacity=capacity,
        lookahead=latency_ns,
        max_emit=1,
        n_args=N_PHOLD_ARGS,
        seed=seed,
        axis_name=axis_name,
        n_shards=n_shards,
        drain_batch=drain_batch,
        trace=trace,
        stats=stats,
        spill=spill,
        kernel=kernel,
    )
    net = ConstantNetwork(latency_ns)
    eng = Engine(
        cfg,
        [make_handler(n_hosts * n_shards, mean_delay_ns, hot_hosts, hot_weight)],
        net,
        batch_handler=(
            make_batch_handler(
                n_hosts * n_shards, mean_delay_ns, hot_hosts, hot_weight
            )
            if batched
            else None
        ),
    )

    def init(host0=0):
        init_ev = Events.empty((n_hosts, msgs_per_host), n_args=N_PHOLD_ARGS)
        gids = host0 + jnp.arange(n_hosts, dtype=jnp.int32)
        init_ev = dataclasses.replace(
            init_ev,
            # stagger start times so the first window isn't one giant burst
            time=jnp.broadcast_to(
                (gids[:, None].astype(jnp.int64) % 16 + 1) * MILLISECOND,
                (n_hosts, msgs_per_host),
            ),
            dst=jnp.broadcast_to(gids[:, None], (n_hosts, msgs_per_host)),
            src=jnp.broadcast_to(gids[:, None], (n_hosts, msgs_per_host)),
            seq=jnp.broadcast_to(
                jnp.arange(msgs_per_host, dtype=jnp.int32)[None, :],
                (n_hosts, msgs_per_host),
            ),
            kind=jnp.full((n_hosts, msgs_per_host), KIND_MSG, jnp.int32),
        )
        return eng.init_state(PholdHost.create(n_hosts), init_ev, host0)

    return eng, init


def build_fleet(n_hosts: int, lanes: int, *, seeds=None, stop_ns: int = 0,
                **build_kw):
    """Seed-sweep fleet over one PHOLD shape: `lanes` copies of the
    `build(n_hosts, **build_kw)` scenario vmapped into one program
    (docs/16-Scenario-Fleets.md). `seeds` defaults to `seed .. seed+L-1`
    off the base build's seed; every other knob is uniform across lanes
    by construction, which is exactly the fleet tier's static-knob rule.
    """
    from shadow_tpu.runtime.fleet import build_fleet_from_engine

    eng, init = build(n_hosts, **build_kw)
    if seeds is None:
        base = build_kw.get("seed", 0)
        seeds = tuple(base + i for i in range(lanes))
    return build_fleet_from_engine(
        eng, init(), lanes, seeds=tuple(seeds), stop_ns=stop_ns
    )

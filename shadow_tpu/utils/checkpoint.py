"""Checkpoint/resume of simulation state.

The reference has no checkpointing at all (SURVEY.md §5 "Checkpoint /
resume: Absent" — its state is a heap-object web spread across pthread
queues and green-thread stacks). Here the *entire* simulation — per-host
event queues, TCP connection tables, NIC clocks, CoDel controllers, app
state, RNG counters — is one pytree of device arrays (EngineState), so a
checkpoint is just that pytree written to disk, and resume is bit-exact:
the restored run produces the same event order and final state as the
uninterrupted one (verified by tests/test_checkpoint.py).

Format: a single .npz holding the flattened leaves by index, plus a JSON
metadata blob recording leaf paths/shapes/dtypes for validation and a
free-form user dict (config digest, sim time, version). Restoring requires
a template state with identical tree structure (rebuild the simulation
from the same config, then load into its state0).

Integrity & rotation (the supervised-runs layer, docs/7-Supervised-Runs.md):
every leaf carries a CRC32 in the header, verified on load — the zip
container's own CRCs only cover the compressed members, not a write that
flipped bits before compression or a tool that rewrote a member. `keep=N`
rotates generations (`path` newest, `path.1` … `path.N-1` older), and
`find_resume_checkpoint` implements `--resume auto`: newest generation
that verifies wins, corrupt ones are skipped with a reason.
"""

from __future__ import annotations

import errno
import json
import os
import re
import time
import zipfile
import zlib
from typing import Any

import jax
import numpy as np

# v2: event-queue rows carry a sorted-by-(time,src,seq) invariant (empties
# last) that the engine's frontier reads rely on; v1 checkpoints (arbitrary
# slot order) would silently execute events out of order if loaded.
# v3: EngineState.fault_epoch + fault Stats counters.
# v4: per-leaf CRC32s in the header. Loading still accepts v3 (same tree
# semantics, just no integrity data to verify against).
# v5: optional named "extra" arrays outside the state tree (the pressure
# reservoir rides here so --resume is bit-exact mid-pressure), and
# EventQueue.drops widened i32 -> i64. Loading still accepts v3/v4: an
# integer leaf whose checkpoint dtype is narrower than the template's is
# widened in place (lossless), so pre-widening checkpoints keep resuming.
# v6: mesh-portable metadata for elastic reshard-on-resume
# (docs/13-Elastic-Recovery.md): the header records the writer's mesh
# shape (`mesh`: n_shards / dcn_slices / host_order) and whether the
# cross-shard exchange buffer was empty (`xchg_empty` — always true at a
# window boundary because the engine flushes in-flight events before
# returning), plus optional `shard` [i, n] identity for per-worker
# shard-set members. Leaves are unchanged — they were already host-major
# global arrays — so v3/v4/v5 files still load; they just carry no mesh
# info and are treated as mesh-unconstrained on resume.
# v7: optional `serve` header section — the serving plane's beat-
# boundary lane snapshot manifest (docs/17-Serving.md "Failure
# semantics"): the packed batch's request ids/docs, the class string,
# and the beat progress, enough for a restarted `shadow_tpu serve` to
# rebuild the batch's binds deterministically and resume the [L, ...]
# fleet state tree mid-launch. Leaves are unchanged; v3-v6 files load
# as before and simply carry no serve section.
FORMAT_VERSION = 7
_LOADABLE_VERSIONS = (3, 4, 5, 6, 7)

# Bounded retry for transient IO failure during the atomic write:
# EINTR (a signal landing mid-fsync — the supervisor's SIGUSR1
# checkpoint-now path makes that likely), ENOSPC (rotation or an
# external cleaner may free space between attempts), EAGAIN. Anything
# else propagates immediately. `_io_sleep` is module-level so tests can
# stub the backoff.
_IO_RETRY_ERRNOS = (errno.EINTR, errno.ENOSPC, errno.EAGAIN)
_IO_ATTEMPTS = 5
_IO_BACKOFF_S = 0.05
_io_sleep = time.sleep
_savez = np.savez_compressed


def _leaf_paths(tree: Any) -> list[str]:
    paths = []
    for path, _leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        paths.append(jax.tree_util.keystr(path))
    return paths


def _crc(arr: np.ndarray) -> int:
    return zlib.crc32(np.ascontiguousarray(arr).tobytes()) & 0xFFFFFFFF


def _rotate(path: str, keep: int) -> None:
    """Shift existing generations one slot older: path -> path.1 -> …
    -> path.{keep-1}; anything at or beyond the keep horizon is removed
    (so lowering --checkpoint-keep actually reclaims the disk)."""
    n = 1
    while os.path.exists(f"{path}.{n}"):
        n += 1
    for i in range(n, keep - 1, -1):  # prune the tail beyond the horizon
        stale = f"{path}.{i}"
        if os.path.exists(stale):
            os.remove(stale)
    for i in range(min(n, keep - 1), 0, -1):
        src = path if i == 1 else f"{path}.{i - 1}"
        if os.path.exists(src):
            os.replace(src, f"{path}.{i}")


def checkpoint_generations(path: str) -> list[str]:
    """Existing generation files, newest first (path, path.1, …)."""
    out = [path] if os.path.exists(path) else []
    suffixed = []
    base = os.path.basename(path)
    d = os.path.dirname(os.path.abspath(path))
    if os.path.isdir(d):
        pat = re.compile(re.escape(base) + r"\.(\d+)$")
        for name in os.listdir(d):
            m = pat.match(name)
            if m:
                suffixed.append((int(m.group(1)), os.path.join(
                    os.path.dirname(path) or ".", name)))
    out += [p for _, p in sorted(suffixed)]
    return out


def _is_xchg(path: str) -> bool:
    return path.startswith(".xchg")


def _xchg_empty(paths: list[str], leaves: list[np.ndarray]) -> bool:
    """True when the cross-shard exchange buffer holds no in-flight
    events: every occupancy-bearing xchg leaf (`.time` slots and the
    `sent_min` barrier) is all TIME_INVALID (int max). Non-xchg trees
    are trivially empty."""
    empty = True
    for pth, arr in zip(paths, leaves):
        if not _is_xchg(pth):
            continue
        if pth.endswith(".time") or pth.endswith("sent_min"):
            if arr.dtype.kind == "i":
                empty &= bool(np.all(arr == np.iinfo(arr.dtype).max))
    return empty


def _is_spill(path: str) -> bool:
    return path.startswith(".queues.spill")


def _spill_empty(paths: list[str], leaves: list[np.ndarray]) -> bool:
    """True when the overflow spill ring parked nothing: occupancy is a
    prefix below the per-host write cursor, so empty means every `.wr`
    is zero. Trees without a spill subtree are trivially empty."""
    empty = True
    for pth, arr in zip(paths, leaves):
        if _is_spill(pth) and pth.endswith(".wr"):
            empty &= bool(np.all(arr == 0))
    return empty


def shard_member_path(path: str, index: int, count: int) -> str:
    """File name of one member of a sharded checkpoint set."""
    return f"{path}.shard{index}of{count}"


def _write_atomic(path: str, arrs: dict[str, np.ndarray],
                  keep: int = 1) -> None:
    """write-tmp / fsync / atomic-rename / fsync-dir, with bounded
    backoff on transient errno — a crash mid-write (the very event
    checkpoints guard against) cannot destroy the previous good
    checkpoint, and a power loss cannot persist the rename without the
    data."""
    tmp = path + ".tmp"
    for attempt in range(_IO_ATTEMPTS):
        try:
            with open(tmp, "wb") as f:
                _savez(f, **arrs)
                f.flush()
                os.fsync(f.fileno())
            break
        except OSError as e:
            # reclaim the partial file first — on ENOSPC it IS the
            # space we need back
            try:
                os.remove(tmp)
            except OSError:
                pass
            if (e.errno not in _IO_RETRY_ERRNOS
                    or attempt == _IO_ATTEMPTS - 1):
                raise
            _io_sleep(_IO_BACKOFF_S * (2 ** attempt))
    if keep > 1:
        _rotate(path, keep)
    os.replace(tmp, path)
    dfd = os.open(os.path.dirname(os.path.abspath(path)), os.O_RDONLY)
    try:
        os.fsync(dfd)
    finally:
        os.close(dfd)


def save_checkpoint(path: str, state: Any, meta: dict | None = None,
                    keep: int = 1,
                    extra: dict[str, np.ndarray] | None = None,
                    mesh_info: dict | None = None,
                    shard: tuple[int, int] | None = None,
                    serve_manifest: dict | None = None) -> None:
    """Write `state` (any pytree of arrays) to `path` as .npz.

    `keep > 1` rotates: the previous `path` becomes `path.1` (and so on
    up to `path.{keep-1}`) before the new file lands, so a corrupted
    newest generation never strands the run without a fallback.

    `extra` carries named host-side arrays that are not part of the
    device state tree (the pressure reservoir, PressureController
    .serialize()); they are CRC'd like leaves but excluded from the
    template structure match on load, so the same checkpoint loads with
    or without a controller attached.

    `mesh_info` (v6) records the writer's mesh so `--resume auto` can
    restore onto a different shard count: {"n_shards", "dcn_slices",
    "host_order" (the applied locality permutation, or None for config
    order)}. `shard=(i, n)` writes one member of a sharded set to
    `shard_member_path(path, i, n)` instead of `path` (no rotation —
    set atomicity is all-or-none at resume, not per member).

    `serve_manifest` (v7) records a serving-plane batch manifest (rids,
    request docs, class string, beat progress) so a restarted serve
    process can rebuild the packed batch and resume the snapshotted
    fleet state mid-launch (docs/17-Serving.md "Failure semantics").
    """
    leaves, _ = jax.tree_util.tree_flatten(state)
    leaves = [np.asarray(x) for x in jax.device_get(leaves)]  # shadowlint: no-deadline=checkpoint save; the CLI pets its watchdog at this site
    paths = _leaf_paths(state)
    extra = {k: np.asarray(v) for k, v in (extra or {}).items()}
    header = {
        "format_version": FORMAT_VERSION,
        "n_leaves": len(leaves),
        "paths": paths,
        "shapes": [list(np.shape(x)) for x in leaves],
        "dtypes": [str(x.dtype) for x in leaves],
        "crc32": [_crc(x) for x in leaves],
        "extra": {k: _crc(v) for k, v in sorted(extra.items())},
        "meta": meta or {},
        "xchg_empty": _xchg_empty(paths, leaves),
    }
    if mesh_info is not None:
        header["mesh"] = dict(mesh_info)
    if serve_manifest is not None:
        header["serve"] = dict(serve_manifest)
    if shard is not None:
        i, n = shard
        if not (0 <= i < n):
            raise ValueError(f"shard index {i} out of range for set of {n}")
        header["shard"] = [i, n]
        path = shard_member_path(path, i, n)
        keep = 1
    arrs = {f"leaf_{i}": x for i, x in enumerate(leaves)}
    arrs.update({f"extra_{k}": v for k, v in extra.items()})
    arrs["__header__"] = np.frombuffer(
        json.dumps(header).encode("utf-8"), dtype=np.uint8
    )
    _write_atomic(path, arrs, keep=keep)


def _read_raw(path: str) -> tuple[dict, list[np.ndarray]]:
    """Read header + every leaf, mapping container-level damage
    (truncation, zip corruption, missing members) to a ValueError that
    names the file instead of leaking a zipfile traceback."""
    try:
        with np.load(path) as data:
            header = json.loads(bytes(data["__header__"]).decode("utf-8"))
            leaves = [data[f"leaf_{i}"] for i in range(header["n_leaves"])]
    # ValueError covers np.load mistaking a non-archive for a pickle
    except (zipfile.BadZipFile, KeyError, EOFError, OSError, ValueError,
            json.JSONDecodeError) as e:
        raise ValueError(
            f"checkpoint {path!r} is truncated or corrupt "
            f"({type(e).__name__}: {e})"
        ) from e
    ver = header.get("format_version")
    if ver not in _LOADABLE_VERSIONS:
        raise ValueError(
            f"checkpoint {path!r}: format {ver} not in loadable set "
            f"{_LOADABLE_VERSIONS} (current writer: {FORMAT_VERSION})"
        )
    return header, leaves


def verify_checkpoint(path: str) -> dict:
    """Fully read `path` and verify every leaf against its header CRC32.

    Returns the user meta dict on success; raises ValueError naming the
    file and the first mismatching leaf otherwise. v3 files (no CRCs)
    pass the container checks only.
    """
    header, leaves = _read_raw(path)
    crcs = header.get("crc32")
    if crcs is not None:
        for i, (arr, want) in enumerate(zip(leaves, crcs)):
            got = _crc(arr)
            if got != want:
                pth = header["paths"][i] if i < len(header["paths"]) else "?"
                raise ValueError(
                    f"checkpoint {path!r}: CRC mismatch on leaf {i} ({pth}): "
                    f"stored {want:#010x}, computed {got:#010x} — the file "
                    "was damaged after it was written"
                )
    if header.get("extra"):
        for name, arr in read_extra(path).items():
            want = header["extra"][name]
            got = _crc(arr)
            if got != want:
                raise ValueError(
                    f"checkpoint {path!r}: CRC mismatch on extra {name!r}: "
                    f"stored {want:#010x}, computed {got:#010x} — the file "
                    "was damaged after it was written"
                )
    return header.get("meta", {})


def read_extra(path: str) -> dict[str, np.ndarray]:
    """The checkpoint's named extra arrays (empty for v3/v4 files)."""
    try:
        with np.load(path) as data:
            header = json.loads(bytes(data["__header__"]).decode("utf-8"))
            return {
                k: data[f"extra_{k}"] for k in header.get("extra", {})
            }
    except (zipfile.BadZipFile, KeyError, EOFError, OSError, ValueError,
            json.JSONDecodeError) as e:
        raise ValueError(
            f"checkpoint {path!r} is truncated or corrupt "
            f"({type(e).__name__}: {e})"
        ) from e


def read_header_info(path: str) -> dict:
    """Light header read (no leaf data): {"format_version", "meta",
    "mesh" (None for pre-v6), "xchg_empty", "shard", "serve" (None for
    pre-v7 / non-serve files)}. Raises the same ValueError as
    `_read_raw` on container damage."""
    try:
        with np.load(path) as data:
            header = json.loads(bytes(data["__header__"]).decode("utf-8"))
    except (zipfile.BadZipFile, KeyError, EOFError, OSError, ValueError,
            json.JSONDecodeError) as e:
        raise ValueError(
            f"checkpoint {path!r} is truncated or corrupt "
            f"({type(e).__name__}: {e})"
        ) from e
    return {
        "format_version": header.get("format_version"),
        "meta": header.get("meta", {}),
        "mesh": header.get("mesh"),
        "xchg_empty": header.get("xchg_empty", True),
        "shard": header.get("shard"),
        "serve": header.get("serve"),
    }


def load_checkpoint_raw(path: str) -> tuple[dict, dict[str, np.ndarray]]:
    """Read a checkpoint WITHOUT a template: (header, {leaf_path:
    array}) with every leaf verified against its stored CRC32.

    This is the file-level half of serving-plane lane migration
    (docs/17-Serving.md "Elasticity"): the migrator slices the raw
    `[L, ...]` leaves along the lane axis and writes the parts back
    through `save_checkpoint_raw` under the SAME leaf-path keys, so a
    part file loads against a smaller-shape template via the ordinary
    tree-path matching of `load_checkpoint` — no template needed at
    migration time, when the old shape's fleet no longer exists.
    """
    header, leaves = _read_raw(path)
    crcs = header.get("crc32")
    if crcs is not None:
        for i, (arr, want) in enumerate(zip(leaves, crcs)):
            got = _crc(arr)
            if got != want:
                pth = header["paths"][i] if i < len(header["paths"]) else "?"
                raise ValueError(
                    f"checkpoint {path!r}: CRC mismatch on leaf {i} "
                    f"({pth}): stored {want:#010x}, computed {got:#010x} "
                    "— the file was damaged after it was written"
                )
    return header, dict(zip(header["paths"], leaves))


def save_checkpoint_raw(path: str, leaves_by_path: dict[str, np.ndarray],
                        *, meta: dict | None = None,
                        mesh_info: dict | None = None,
                        serve_manifest: dict | None = None) -> None:
    """Write pre-flattened `{leaf_path: array}` leaves as a checkpoint,
    preserving the given path strings verbatim (insertion order is the
    leaf order). Shapes, dtypes, and per-leaf CRCs are recomputed from
    the arrays, so a lane-sliced copy of a loaded file carries honest
    integrity data of its own. Same atomic tmp+fsync+rename write as
    `save_checkpoint`."""
    paths = list(leaves_by_path)
    leaves = [np.asarray(leaves_by_path[p]) for p in paths]
    header = {
        "format_version": FORMAT_VERSION,
        "n_leaves": len(leaves),
        "paths": paths,
        "shapes": [list(np.shape(x)) for x in leaves],
        "dtypes": [str(x.dtype) for x in leaves],
        "crc32": [_crc(x) for x in leaves],
        "extra": {},
        "meta": meta or {},
        "xchg_empty": _xchg_empty(paths, leaves),
    }
    if mesh_info is not None:
        header["mesh"] = dict(mesh_info)
    if serve_manifest is not None:
        header["serve"] = dict(serve_manifest)
    arrs = {f"leaf_{i}": x for i, x in enumerate(leaves)}
    arrs["__header__"] = np.frombuffer(
        json.dumps(header).encode("utf-8"), dtype=np.uint8
    )
    _write_atomic(path, arrs, keep=1)


def _shard_sets(path: str) -> dict[int, dict[int, str]]:
    """{set_size: {member_index: member_path}} for files named
    `<path>.shard<i>of<n>` next to `path`."""
    base = os.path.basename(path)
    d = os.path.dirname(os.path.abspath(path))
    sets: dict[int, dict[int, str]] = {}
    if os.path.isdir(d):
        pat = re.compile(re.escape(base) + r"\.shard(\d+)of(\d+)$")
        for name in os.listdir(d):
            m = pat.match(name)
            if m:
                i, n = int(m.group(1)), int(m.group(2))
                sets.setdefault(n, {})[i] = os.path.join(
                    os.path.dirname(path) or ".", name)
    return sets


def find_resume_checkpoint(path: str):
    """`--resume auto`: newest checkpoint of `path` that verifies.

    Candidates, newest-mtime first: rotation generations (`path`,
    `path.1`, …), the crash-path `path.emergency` file, and complete
    sharded sets (`path.shard<i>of<n>` — every member present and
    verifying, all-or-none; a torn set is never resumed, it is reported
    in `skipped` instead).

    Returns (chosen, meta, skipped) where `chosen` is a single path, or
    a list of member paths (shard order) for a set — load the latter
    with `load_shard_set`. `skipped` lists (path, reason) for newer
    candidates that failed. Returns None when nothing checkpoint-like
    exists; raises ValueError when candidates exist but none verifies.
    """
    skipped: list[tuple[str, str]] = []
    # (mtime, tiebreak, chosen, member_paths) — tiebreak keeps the
    # historical generation order when mtimes collide
    cands: list[tuple[float, int, Any, list[str]]] = []
    for i, p in enumerate(checkpoint_generations(path)):
        cands.append((os.path.getmtime(p), i, p, [p]))
    emerg = path + ".emergency"
    if os.path.exists(emerg):
        # written at crash time, so usually the newest and the furthest
        # along; ties with the bare path prefer the emergency file
        cands.append((os.path.getmtime(emerg), -1, emerg, [emerg]))
    for n, members in sorted(_shard_sets(path).items()):
        if sorted(members) != list(range(n)):
            got = ", ".join(
                os.path.basename(members[i]) for i in sorted(members))
            skipped.append((
                shard_member_path(path, 0, n).replace("0of", "*of", 1),
                f"incomplete shard set: {len(members)} of {n} members "
                f"present ({got}) — refusing to resume a torn state",
            ))
            continue
        paths_n = [members[i] for i in range(n)]
        cands.append((
            max(os.path.getmtime(p) for p in paths_n), 0,
            paths_n if n > 1 else paths_n[0], paths_n,
        ))
    if not cands:
        if skipped:
            raise ValueError(
                "no verifiable checkpoint:\n  "
                + "\n  ".join(f"{p}: {r}" for p, r in skipped)
            )
        return None
    cands.sort(key=lambda c: (-c[0], c[1]))
    for _, _, chosen, member_paths in cands:
        try:
            meta = {}
            for p in member_paths:
                meta = verify_checkpoint(p)
        except ValueError as e:
            skipped.append((
                member_paths[0] if len(member_paths) == 1
                else str(member_paths), str(e)))
            continue
        # a serving-plane lane snapshot (v7 "serve" manifest) is a
        # lane-STACKED batch state, not a batch-run state — loading it
        # into a solo template would fail with a baffling shape
        # mismatch, so refuse it by name and point at the right door
        serve_member = next(
            (p for p in member_paths
             if read_header_info(p).get("serve") is not None), None)
        if serve_member is not None:
            skipped.append((
                serve_member,
                "serving-plane lane snapshot (v7 'serve' manifest) — "
                "batch-run --resume auto cannot load a lane-stacked "
                "batch state; restart `shadow_tpu serve` with the same "
                "--snapshot-path and let resume_pending_batch pick up "
                "the in-flight batch instead",
            ))
            continue
        return chosen, meta, skipped
    raise ValueError(
        "no verifiable checkpoint generation:\n  "
        + "\n  ".join(f"{p}: {r}" for p, r in skipped)
    )


def _check_leaf(arr: np.ndarray, tmpl: Any, pth: str, want_crc,
                path: str, i) -> np.ndarray:
    """Shape/dtype/CRC validation of one checkpoint leaf against its
    template leaf, with the lossless int-widening migration (v4 -> v5
    widened EventQueue.drops to i64): CRC is verified against the
    stored bytes FIRST, then the widening brings the leaf to the
    template dtype."""
    want_shape = tuple(np.shape(tmpl))
    want_dtype = (
        np.asarray(tmpl).dtype if not hasattr(tmpl, "dtype")
        else tmpl.dtype
    )
    widen = (
        arr.shape == want_shape
        and str(arr.dtype) != str(want_dtype)
        and arr.dtype.kind == np.dtype(want_dtype).kind == "i"
        and arr.dtype.itemsize < np.dtype(want_dtype).itemsize
    )
    if (arr.shape != want_shape
            or str(arr.dtype) != str(want_dtype)) and not widen:
        raise ValueError(
            f"leaf {i} ({pth}): checkpoint {arr.shape}/{arr.dtype} vs "
            f"template {want_shape}/{want_dtype}"
        )
    if want_crc is not None and _crc(arr) != want_crc:
        raise ValueError(
            f"checkpoint {path!r}: CRC mismatch on leaf {i} ({pth}) — "
            "the file was damaged after it was written"
        )
    if widen:
        arr = arr.astype(want_dtype)
    return arr


def load_checkpoint(path: str, template: Any, *,
                    reshard: bool = False) -> tuple[Any, dict]:
    """Load a checkpoint into the structure of `template`.

    Returns (state, meta). Raises ValueError on container corruption,
    per-leaf CRC mismatch, or structural mismatch.

    With `reshard=False` (default) checkpoint files are only portable
    across identical builds (same config, host count, socket/queue
    capacities, mesh shape). With `reshard=True`, leaves are matched by
    tree path and the `.xchg` subtree — the only mesh-shaped part of
    the state — may differ: a checkpoint taken at S shards restores
    onto a template built for S' shards (including S or S' == 1, where
    the xchg subtree is absent entirely) by taking the template's
    freshly-initialized exchange buffer. That is only sound when the
    checkpoint's exchange buffer held no in-flight events; the engine
    flushes it before every window boundary, so any checkpoint written
    by the driver qualifies — but a file claiming otherwise is refused
    loudly rather than dropping events. The `.queues.spill` overflow
    ring gets the same treatment when exactly one side has it (sharded
    builds refuse spill modes, so a reshard legitimately crosses spill
    presence): template-fresh when the stored ring parked nothing,
    refused when it did.
    """
    header, leaves = _read_raw(path)
    t_leaves, treedef = jax.tree_util.tree_flatten(template)
    paths = _leaf_paths(template)
    crcs = header.get("crc32") or [None] * len(leaves)
    exact = header["paths"] == paths and header["n_leaves"] == len(t_leaves)
    if exact and reshard:
        # same tree, but possibly a different mesh: S and S' shards both
        # HAVE an exchange buffer, just differently shaped — route those
        # through the portable branch below instead of failing the
        # strict per-leaf shape check
        exact = all(
            arr.shape == tuple(np.shape(tmpl))
            for pth, arr, tmpl in zip(paths, leaves, t_leaves)
            if _is_xchg(pth)
        )
    if exact:
        new_leaves = [
            jax.numpy.asarray(_check_leaf(arr, tmpl, pth, want_crc, path, i))
            for i, (tmpl, pth, arr, want_crc) in enumerate(
                zip(t_leaves, paths, leaves, crcs))
        ]
        return (jax.tree_util.tree_unflatten(treedef, new_leaves),
                header.get("meta", {}))
    if not reshard:
        if header["n_leaves"] != len(t_leaves):
            raise ValueError(
                f"checkpoint has {header['n_leaves']} leaves, template has "
                f"{len(t_leaves)} — was it built from the same config?"
            )
        diff = [
            f"  {a} (checkpoint) vs {b} (template)"
            for a, b in zip(header["paths"], paths)
            if a != b
        ]
        raise ValueError(
            "checkpoint tree structure differs from template:\n"
            + "\n".join(diff[:10])
        )
    # --- mesh-portable path: match leaves by tree path ------------------
    c_non_xchg = [p for p in header["paths"] if not _is_xchg(p)]
    t_non_xchg = [p for p in paths if not _is_xchg(p)]
    # The spill ring exists only under --overflow spill/grow, which
    # sharded builds refuse — so a reshard legitimately crosses spill
    # presence (the unsharded CLI default is spill, the sharded default
    # drop). Treat the subtree like the exchange buffer: take it fresh
    # from the template, provided the checkpoint's ring parked nothing.
    spill_mismatch = (
        c_non_xchg != t_non_xchg
        and [p for p in c_non_xchg if not _is_spill(p)]
        == [p for p in t_non_xchg if not _is_spill(p)]
    )
    if spill_mismatch:
        if not _spill_empty(header["paths"], leaves):
            raise ValueError(
                f"checkpoint {path!r} holds spilled events in its "
                "overflow ring — resume once with --overflow spill on "
                "the original mesh to re-seat them, then reshard."
            )
        c_non_xchg = [p for p in c_non_xchg if not _is_spill(p)]
        t_non_xchg = [p for p in t_non_xchg if not _is_spill(p)]
    if c_non_xchg != t_non_xchg:
        diff = [f"  {a} (checkpoint) vs {b} (template)"
                for a, b in zip(c_non_xchg, t_non_xchg) if a != b]
        if len(c_non_xchg) != len(t_non_xchg):
            diff.append(
                f"  {len(c_non_xchg)} non-exchange leaves (checkpoint) vs "
                f"{len(t_non_xchg)} (template)")
        raise ValueError(
            "checkpoint differs from template beyond the mesh-shaped "
            "exchange buffer — reshard needs the same config/host count:\n"
            + "\n".join(diff[:10])
        )
    by_path = {
        p: (arr, crc)
        for p, arr, crc in zip(header["paths"], leaves, crcs)
    }
    if not _xchg_empty(header["paths"], leaves):
        raise ValueError(
            f"checkpoint {path!r} holds in-flight cross-shard events "
            "(non-empty exchange buffer) — it cannot restore onto a "
            "different mesh. Resume once on the original shard count to "
            "reach a window boundary, then reshard."
        )
    new_leaves = []
    for i, (tmpl, pth) in enumerate(zip(t_leaves, paths)):
        mesh_shaped = _is_xchg(pth) or (spill_mismatch and _is_spill(pth))
        if pth in by_path and (
                not mesh_shaped
                or by_path[pth][0].shape == tuple(np.shape(tmpl))):
            arr, want_crc = by_path[pth]
            arr = _check_leaf(arr, tmpl, pth, want_crc, path, i)
            new_leaves.append(jax.numpy.asarray(arr))
        elif mesh_shaped:
            # the target mesh's own (empty) exchange buffer or spill
            # ring — the checkpoint's was verified empty above, so no
            # event is lost
            new_leaves.append(tmpl)
        else:
            raise ValueError(
                f"leaf {pth} missing from checkpoint {path!r}"
            )
    state = jax.tree_util.tree_unflatten(treedef, new_leaves)
    return state, header.get("meta", {})


def load_shard_set(member_paths: list[str], template: Any,
                   ) -> tuple[Any, dict]:
    """Load a complete sharded checkpoint set (one file per worker,
    from `save_checkpoint(..., shard=(i, n))`) into a global template.

    Per-host leaves (leading dim == global hosts / n members in every
    member) are concatenated in shard order; replicated leaves (same
    shape as the template) must agree bit-for-bit across members and
    are taken from member 0; exchange-buffer leaves must be empty in
    every member and come fresh from the template. Returns
    (state, meta-of-member-0). Extras are refused (the pressure
    reservoir never coexists with a sharded mesh).
    """
    n = len(member_paths)
    read = [_read_raw(p) for p in member_paths]
    for p, (hdr, lvs) in zip(member_paths, read):
        shard = hdr.get("shard")
        if shard is not None and shard[1] != n:
            raise ValueError(
                f"{p!r} belongs to a set of {shard[1]}, got {n} members")
        if hdr.get("extra"):
            raise ValueError(
                f"{p!r} carries extra arrays; sharded sets cannot hold "
                "a pressure reservoir")
        if hdr["paths"] != read[0][0]["paths"]:
            raise ValueError(
                f"{p!r}: leaf paths differ from {member_paths[0]!r}")
        if not _xchg_empty(hdr["paths"], lvs):
            raise ValueError(
                f"{p!r} holds in-flight cross-shard events — the set "
                "cannot restore onto a different mesh")
    c_paths = read[0][0]["paths"]
    t_leaves, treedef = jax.tree_util.tree_flatten(template)
    paths = _leaf_paths(template)
    c_non_xchg = [p for p in c_paths if not _is_xchg(p)]
    t_non_xchg = [p for p in paths if not _is_xchg(p)]
    # members were written by sharded builds, which refuse spill modes —
    # an unsharded target template may still carry the (default) spill
    # ring, which starts fresh exactly like the exchange buffer
    spill_mismatch = (
        c_non_xchg != t_non_xchg
        and c_non_xchg == [p for p in t_non_xchg if not _is_spill(p)]
    )
    if spill_mismatch:
        t_non_xchg = [p for p in t_non_xchg if not _is_spill(p)]
    if c_non_xchg != t_non_xchg:
        raise ValueError(
            "shard set differs from template beyond the exchange buffer "
            "— was it written from the same config?"
        )
    idx = {p: i for i, p in enumerate(c_paths)}
    new_leaves = []
    for tmpl, pth in zip(t_leaves, paths):
        if _is_xchg(pth) or (spill_mismatch and _is_spill(pth)):
            new_leaves.append(tmpl)
            continue
        i = idx[pth]
        want_shape = tuple(np.shape(tmpl))
        members = []
        for p, (hdr, lvs) in zip(member_paths, read):
            crc = (hdr.get("crc32") or [None] * len(lvs))[i]
            arr = lvs[i]
            if crc is not None and _crc(arr) != crc:
                raise ValueError(
                    f"checkpoint {p!r}: CRC mismatch on leaf {i} ({pth}) "
                    "— the file was damaged after it was written")
            members.append(arr)
        shapes = {m.shape for m in members}
        if len(shapes) == 1 and members[0].shape == want_shape:
            for p, m in zip(member_paths[1:], members[1:]):
                if not np.array_equal(members[0], m):
                    raise ValueError(
                        f"replicated leaf {pth} differs between "
                        f"{member_paths[0]!r} and {p!r}")
            arr = members[0]
        elif (len(shapes) == 1 and want_shape
                and members[0].shape[1:] == want_shape[1:]
                and members[0].shape[0] * n == want_shape[0]):
            arr = np.concatenate(members, axis=0)
        else:
            raise ValueError(
                f"leaf {pth}: member shape {members[0].shape} does not "
                f"tile template {want_shape} across {n} shards")
        arr = _check_leaf(arr, tmpl, pth, None, member_paths[0], pth)
        new_leaves.append(jax.numpy.asarray(arr))
    state = jax.tree_util.tree_unflatten(treedef, new_leaves)
    return state, read[0][0].get("meta", {})


def transfer_state(state: Any, template: Any) -> Any:
    """Carry `state` into the (larger) shapes of `template` — the
    `--overflow grow` re-templating path: the engine is rebuilt with
    doubled queue capacity and the live state moves across mid-run.

    Leaves are matched by tree path (both trees must have identical
    structure). Where a template leaf is longer along some axes, the
    state leaf is padded at the END of each grown axis — correct for
    every capacity-sized array here because the queue invariant keeps
    occupied slots in a contiguous sorted prefix (empties last), and the
    spill ring's occupancy is a prefix below its write cursor (the
    driver harvests the ring before growing, so the cursor is zero
    anyway). Pad value: TIME_INVALID for leaves whose path ends in
    `.time` (empty-slot sentinel), zero otherwise. Integer leaves are
    widened to the template dtype when needed; shrinking any axis or
    narrowing any dtype is refused loudly.
    """
    s_flat = jax.tree_util.tree_flatten_with_path(state)[0]
    t_leaves, treedef = jax.tree_util.tree_flatten(template)
    t_paths = _leaf_paths(template)
    s_paths = [jax.tree_util.keystr(p) for p, _ in s_flat]
    if s_paths != t_paths:
        diff = [f"  {a} (state) vs {b} (template)"
                for a, b in zip(s_paths, t_paths) if a != b]
        raise ValueError(
            "transfer_state: tree structure differs:\n" + "\n".join(diff[:10])
        )
    time_invalid = np.iinfo(np.int64).max
    out = []
    for pth, (src, tmpl) in zip(t_paths, zip(
            (leaf for _, leaf in s_flat), t_leaves)):
        arr = np.asarray(jax.device_get(src))  # shadowlint: no-deadline=offline state transfer during re-template
        want_shape = tuple(np.shape(tmpl))
        want_dtype = np.dtype(
            tmpl.dtype if hasattr(tmpl, "dtype") else np.asarray(tmpl).dtype
        )
        if arr.dtype != want_dtype:
            if not (arr.dtype.kind == want_dtype.kind == "i"
                    and arr.dtype.itemsize < want_dtype.itemsize):
                raise ValueError(
                    f"transfer_state: leaf {pth}: cannot convert "
                    f"{arr.dtype} -> {want_dtype}"
                )
            arr = arr.astype(want_dtype)
        if arr.shape != want_shape:
            if arr.ndim != len(want_shape) or any(
                a > w for a, w in zip(arr.shape, want_shape)
            ):
                raise ValueError(
                    f"transfer_state: leaf {pth}: cannot shrink "
                    f"{arr.shape} -> {want_shape}"
                )
            fill = (
                time_invalid if pth.endswith(".time")
                and want_dtype == np.int64 else 0
            )
            grown = np.full(want_shape, fill, want_dtype)
            grown[tuple(slice(0, a) for a in arr.shape)] = arr
            arr = grown
        out.append(jax.numpy.asarray(arr))
    return jax.tree_util.tree_unflatten(treedef, out)

"""Checkpoint/resume of simulation state.

The reference has no checkpointing at all (SURVEY.md §5 "Checkpoint /
resume: Absent" — its state is a heap-object web spread across pthread
queues and green-thread stacks). Here the *entire* simulation — per-host
event queues, TCP connection tables, NIC clocks, CoDel controllers, app
state, RNG counters — is one pytree of device arrays (EngineState), so a
checkpoint is just that pytree written to disk, and resume is bit-exact:
the restored run produces the same event order and final state as the
uninterrupted one (verified by tests/test_checkpoint.py).

Format: a single .npz holding the flattened leaves by index, plus a JSON
metadata blob recording leaf paths/shapes/dtypes for validation and a
free-form user dict (config digest, sim time, version). Restoring requires
a template state with identical tree structure (rebuild the simulation
from the same config, then load into its state0).
"""

from __future__ import annotations

import json
import os
from typing import Any

import jax
import numpy as np

# v2: event-queue rows carry a sorted-by-(time,src,seq) invariant (empties
# last) that the engine's frontier reads rely on; v1 checkpoints (arbitrary
# slot order) would silently execute events out of order if loaded.
FORMAT_VERSION = 3  # v3: EngineState.fault_epoch + fault Stats counters


def _leaf_paths(tree: Any) -> list[str]:
    paths = []
    for path, _leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        paths.append(jax.tree_util.keystr(path))
    return paths


def save_checkpoint(path: str, state: Any, meta: dict | None = None) -> None:
    """Write `state` (any pytree of arrays) to `path` as .npz."""
    leaves, _ = jax.tree_util.tree_flatten(state)
    leaves = jax.device_get(leaves)
    header = {
        "format_version": FORMAT_VERSION,
        "n_leaves": len(leaves),
        "paths": _leaf_paths(state),
        "shapes": [list(np.shape(x)) for x in leaves],
        "dtypes": [str(np.asarray(x).dtype) for x in leaves],
        "meta": meta or {},
    }
    arrs = {f"leaf_{i}": np.asarray(x) for i, x in enumerate(leaves)}
    arrs["__header__"] = np.frombuffer(
        json.dumps(header).encode("utf-8"), dtype=np.uint8
    )
    # write-fsync-rename so a crash mid-write (the very event checkpoints
    # guard against) cannot destroy the previous good checkpoint, and a
    # power loss cannot persist the rename without the data
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez_compressed(f, **arrs)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    dfd = os.open(os.path.dirname(os.path.abspath(path)), os.O_RDONLY)
    try:
        os.fsync(dfd)
    finally:
        os.close(dfd)


def load_checkpoint(path: str, template: Any) -> tuple[Any, dict]:
    """Load a checkpoint into the structure of `template`.

    Returns (state, meta). Raises ValueError on structural mismatch —
    checkpoint files are only portable across identical builds (same
    config, host count, socket/queue capacities).
    """
    with np.load(path) as data:
        header = json.loads(bytes(data["__header__"]).decode("utf-8"))
        if header.get("format_version") != FORMAT_VERSION:
            raise ValueError(
                f"checkpoint format {header.get('format_version')} != "
                f"{FORMAT_VERSION}"
            )
        t_leaves, treedef = jax.tree_util.tree_flatten(template)
        if header["n_leaves"] != len(t_leaves):
            raise ValueError(
                f"checkpoint has {header['n_leaves']} leaves, template has "
                f"{len(t_leaves)} — was it built from the same config?"
            )
        paths = _leaf_paths(template)
        if header["paths"] != paths:
            diff = [
                f"  {a} (checkpoint) vs {b} (template)"
                for a, b in zip(header["paths"], paths)
                if a != b
            ]
            raise ValueError(
                "checkpoint tree structure differs from template:\n"
                + "\n".join(diff[:10])
            )
        new_leaves = []
        for i, (tmpl, pth) in enumerate(zip(t_leaves, paths)):
            arr = data[f"leaf_{i}"]
            want_shape = tuple(np.shape(tmpl))
            want_dtype = np.asarray(tmpl).dtype if not hasattr(tmpl, "dtype") else tmpl.dtype
            if arr.shape != want_shape or str(arr.dtype) != str(want_dtype):
                raise ValueError(
                    f"leaf {i} ({pth}): checkpoint {arr.shape}/{arr.dtype} vs "
                    f"template {want_shape}/{want_dtype}"
                )
            new_leaves.append(jax.numpy.asarray(arr))
        state = jax.tree_util.tree_unflatten(treedef, new_leaves)
        return state, header.get("meta", {})

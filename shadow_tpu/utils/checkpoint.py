"""Checkpoint/resume of simulation state.

The reference has no checkpointing at all (SURVEY.md §5 "Checkpoint /
resume: Absent" — its state is a heap-object web spread across pthread
queues and green-thread stacks). Here the *entire* simulation — per-host
event queues, TCP connection tables, NIC clocks, CoDel controllers, app
state, RNG counters — is one pytree of device arrays (EngineState), so a
checkpoint is just that pytree written to disk, and resume is bit-exact:
the restored run produces the same event order and final state as the
uninterrupted one (verified by tests/test_checkpoint.py).

Format: a single .npz holding the flattened leaves by index, plus a JSON
metadata blob recording leaf paths/shapes/dtypes for validation and a
free-form user dict (config digest, sim time, version). Restoring requires
a template state with identical tree structure (rebuild the simulation
from the same config, then load into its state0).

Integrity & rotation (the supervised-runs layer, docs/7-Supervised-Runs.md):
every leaf carries a CRC32 in the header, verified on load — the zip
container's own CRCs only cover the compressed members, not a write that
flipped bits before compression or a tool that rewrote a member. `keep=N`
rotates generations (`path` newest, `path.1` … `path.N-1` older), and
`find_resume_checkpoint` implements `--resume auto`: newest generation
that verifies wins, corrupt ones are skipped with a reason.
"""

from __future__ import annotations

import json
import os
import re
import zipfile
import zlib
from typing import Any

import jax
import numpy as np

# v2: event-queue rows carry a sorted-by-(time,src,seq) invariant (empties
# last) that the engine's frontier reads rely on; v1 checkpoints (arbitrary
# slot order) would silently execute events out of order if loaded.
# v3: EngineState.fault_epoch + fault Stats counters.
# v4: per-leaf CRC32s in the header. Loading still accepts v3 (same tree
# semantics, just no integrity data to verify against).
# v5: optional named "extra" arrays outside the state tree (the pressure
# reservoir rides here so --resume is bit-exact mid-pressure), and
# EventQueue.drops widened i32 -> i64. Loading still accepts v3/v4: an
# integer leaf whose checkpoint dtype is narrower than the template's is
# widened in place (lossless), so pre-widening checkpoints keep resuming.
FORMAT_VERSION = 5
_LOADABLE_VERSIONS = (3, 4, 5)


def _leaf_paths(tree: Any) -> list[str]:
    paths = []
    for path, _leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        paths.append(jax.tree_util.keystr(path))
    return paths


def _crc(arr: np.ndarray) -> int:
    return zlib.crc32(np.ascontiguousarray(arr).tobytes()) & 0xFFFFFFFF


def _rotate(path: str, keep: int) -> None:
    """Shift existing generations one slot older: path -> path.1 -> …
    -> path.{keep-1}; anything at or beyond the keep horizon is removed
    (so lowering --checkpoint-keep actually reclaims the disk)."""
    n = 1
    while os.path.exists(f"{path}.{n}"):
        n += 1
    for i in range(n, keep - 1, -1):  # prune the tail beyond the horizon
        stale = f"{path}.{i}"
        if os.path.exists(stale):
            os.remove(stale)
    for i in range(min(n, keep - 1), 0, -1):
        src = path if i == 1 else f"{path}.{i - 1}"
        if os.path.exists(src):
            os.replace(src, f"{path}.{i}")


def checkpoint_generations(path: str) -> list[str]:
    """Existing generation files, newest first (path, path.1, …)."""
    out = [path] if os.path.exists(path) else []
    suffixed = []
    base = os.path.basename(path)
    d = os.path.dirname(os.path.abspath(path))
    if os.path.isdir(d):
        pat = re.compile(re.escape(base) + r"\.(\d+)$")
        for name in os.listdir(d):
            m = pat.match(name)
            if m:
                suffixed.append((int(m.group(1)), os.path.join(
                    os.path.dirname(path) or ".", name)))
    out += [p for _, p in sorted(suffixed)]
    return out


def save_checkpoint(path: str, state: Any, meta: dict | None = None,
                    keep: int = 1,
                    extra: dict[str, np.ndarray] | None = None) -> None:
    """Write `state` (any pytree of arrays) to `path` as .npz.

    `keep > 1` rotates: the previous `path` becomes `path.1` (and so on
    up to `path.{keep-1}`) before the new file lands, so a corrupted
    newest generation never strands the run without a fallback.

    `extra` carries named host-side arrays that are not part of the
    device state tree (the pressure reservoir, PressureController
    .serialize()); they are CRC'd like leaves but excluded from the
    template structure match on load, so the same checkpoint loads with
    or without a controller attached.
    """
    leaves, _ = jax.tree_util.tree_flatten(state)
    leaves = [np.asarray(x) for x in jax.device_get(leaves)]
    extra = {k: np.asarray(v) for k, v in (extra or {}).items()}
    header = {
        "format_version": FORMAT_VERSION,
        "n_leaves": len(leaves),
        "paths": _leaf_paths(state),
        "shapes": [list(np.shape(x)) for x in leaves],
        "dtypes": [str(x.dtype) for x in leaves],
        "crc32": [_crc(x) for x in leaves],
        "extra": {k: _crc(v) for k, v in sorted(extra.items())},
        "meta": meta or {},
    }
    arrs = {f"leaf_{i}": x for i, x in enumerate(leaves)}
    arrs.update({f"extra_{k}": v for k, v in extra.items()})
    arrs["__header__"] = np.frombuffer(
        json.dumps(header).encode("utf-8"), dtype=np.uint8
    )
    # write-fsync-rename so a crash mid-write (the very event checkpoints
    # guard against) cannot destroy the previous good checkpoint, and a
    # power loss cannot persist the rename without the data
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez_compressed(f, **arrs)
        f.flush()
        os.fsync(f.fileno())
    if keep > 1:
        _rotate(path, keep)
    os.replace(tmp, path)
    dfd = os.open(os.path.dirname(os.path.abspath(path)), os.O_RDONLY)
    try:
        os.fsync(dfd)
    finally:
        os.close(dfd)


def _read_raw(path: str) -> tuple[dict, list[np.ndarray]]:
    """Read header + every leaf, mapping container-level damage
    (truncation, zip corruption, missing members) to a ValueError that
    names the file instead of leaking a zipfile traceback."""
    try:
        with np.load(path) as data:
            header = json.loads(bytes(data["__header__"]).decode("utf-8"))
            leaves = [data[f"leaf_{i}"] for i in range(header["n_leaves"])]
    # ValueError covers np.load mistaking a non-archive for a pickle
    except (zipfile.BadZipFile, KeyError, EOFError, OSError, ValueError,
            json.JSONDecodeError) as e:
        raise ValueError(
            f"checkpoint {path!r} is truncated or corrupt "
            f"({type(e).__name__}: {e})"
        ) from e
    ver = header.get("format_version")
    if ver not in _LOADABLE_VERSIONS:
        raise ValueError(
            f"checkpoint {path!r}: format {ver} not in loadable set "
            f"{_LOADABLE_VERSIONS} (current writer: {FORMAT_VERSION})"
        )
    return header, leaves


def verify_checkpoint(path: str) -> dict:
    """Fully read `path` and verify every leaf against its header CRC32.

    Returns the user meta dict on success; raises ValueError naming the
    file and the first mismatching leaf otherwise. v3 files (no CRCs)
    pass the container checks only.
    """
    header, leaves = _read_raw(path)
    crcs = header.get("crc32")
    if crcs is not None:
        for i, (arr, want) in enumerate(zip(leaves, crcs)):
            got = _crc(arr)
            if got != want:
                pth = header["paths"][i] if i < len(header["paths"]) else "?"
                raise ValueError(
                    f"checkpoint {path!r}: CRC mismatch on leaf {i} ({pth}): "
                    f"stored {want:#010x}, computed {got:#010x} — the file "
                    "was damaged after it was written"
                )
    if header.get("extra"):
        for name, arr in read_extra(path).items():
            want = header["extra"][name]
            got = _crc(arr)
            if got != want:
                raise ValueError(
                    f"checkpoint {path!r}: CRC mismatch on extra {name!r}: "
                    f"stored {want:#010x}, computed {got:#010x} — the file "
                    "was damaged after it was written"
                )
    return header.get("meta", {})


def read_extra(path: str) -> dict[str, np.ndarray]:
    """The checkpoint's named extra arrays (empty for v3/v4 files)."""
    try:
        with np.load(path) as data:
            header = json.loads(bytes(data["__header__"]).decode("utf-8"))
            return {
                k: data[f"extra_{k}"] for k in header.get("extra", {})
            }
    except (zipfile.BadZipFile, KeyError, EOFError, OSError, ValueError,
            json.JSONDecodeError) as e:
        raise ValueError(
            f"checkpoint {path!r} is truncated or corrupt "
            f"({type(e).__name__}: {e})"
        ) from e


def find_resume_checkpoint(path: str):
    """`--resume auto`: newest generation of `path` that verifies.

    Returns (chosen_path, meta, skipped) where skipped is a list of
    (path, reason) for newer generations that failed verification;
    returns None when no generation files exist at all. Raises
    ValueError when generations exist but none verifies.
    """
    gens = checkpoint_generations(path)
    if not gens:
        return None
    skipped: list[tuple[str, str]] = []
    for p in gens:
        try:
            meta = verify_checkpoint(p)
        except ValueError as e:
            skipped.append((p, str(e)))
            continue
        return p, meta, skipped
    raise ValueError(
        "no verifiable checkpoint generation:\n  "
        + "\n  ".join(f"{p}: {r}" for p, r in skipped)
    )


def load_checkpoint(path: str, template: Any) -> tuple[Any, dict]:
    """Load a checkpoint into the structure of `template`.

    Returns (state, meta). Raises ValueError on container corruption,
    per-leaf CRC mismatch, or structural mismatch — checkpoint files are
    only portable across identical builds (same config, host count,
    socket/queue capacities).
    """
    header, leaves = _read_raw(path)
    t_leaves, treedef = jax.tree_util.tree_flatten(template)
    if header["n_leaves"] != len(t_leaves):
        raise ValueError(
            f"checkpoint has {header['n_leaves']} leaves, template has "
            f"{len(t_leaves)} — was it built from the same config?"
        )
    paths = _leaf_paths(template)
    if header["paths"] != paths:
        diff = [
            f"  {a} (checkpoint) vs {b} (template)"
            for a, b in zip(header["paths"], paths)
            if a != b
        ]
        raise ValueError(
            "checkpoint tree structure differs from template:\n"
            + "\n".join(diff[:10])
        )
    crcs = header.get("crc32") or [None] * len(leaves)
    new_leaves = []
    for i, (tmpl, pth, arr, want_crc) in enumerate(
        zip(t_leaves, paths, leaves, crcs)
    ):
        want_shape = tuple(np.shape(tmpl))
        want_dtype = (
            np.asarray(tmpl).dtype if not hasattr(tmpl, "dtype")
            else tmpl.dtype
        )
        widen = (
            arr.shape == want_shape
            and str(arr.dtype) != str(want_dtype)
            and arr.dtype.kind == np.dtype(want_dtype).kind == "i"
            and arr.dtype.itemsize < np.dtype(want_dtype).itemsize
        )
        if (arr.shape != want_shape
                or str(arr.dtype) != str(want_dtype)) and not widen:
            raise ValueError(
                f"leaf {i} ({pth}): checkpoint {arr.shape}/{arr.dtype} vs "
                f"template {want_shape}/{want_dtype}"
            )
        if want_crc is not None and _crc(arr) != want_crc:
            raise ValueError(
                f"checkpoint {path!r}: CRC mismatch on leaf {i} ({pth}) — "
                "the file was damaged after it was written"
            )
        if widen:
            # dtype migration (v4 -> v5 widened EventQueue.drops to i64):
            # CRC is verified against the stored bytes above, THEN the
            # lossless int widening brings the leaf to the template dtype
            arr = arr.astype(want_dtype)
        new_leaves.append(jax.numpy.asarray(arr))
    state = jax.tree_util.tree_unflatten(treedef, new_leaves)
    return state, header.get("meta", {})


def transfer_state(state: Any, template: Any) -> Any:
    """Carry `state` into the (larger) shapes of `template` — the
    `--overflow grow` re-templating path: the engine is rebuilt with
    doubled queue capacity and the live state moves across mid-run.

    Leaves are matched by tree path (both trees must have identical
    structure). Where a template leaf is longer along some axes, the
    state leaf is padded at the END of each grown axis — correct for
    every capacity-sized array here because the queue invariant keeps
    occupied slots in a contiguous sorted prefix (empties last), and the
    spill ring's occupancy is a prefix below its write cursor (the
    driver harvests the ring before growing, so the cursor is zero
    anyway). Pad value: TIME_INVALID for leaves whose path ends in
    `.time` (empty-slot sentinel), zero otherwise. Integer leaves are
    widened to the template dtype when needed; shrinking any axis or
    narrowing any dtype is refused loudly.
    """
    s_flat = jax.tree_util.tree_flatten_with_path(state)[0]
    t_leaves, treedef = jax.tree_util.tree_flatten(template)
    t_paths = _leaf_paths(template)
    s_paths = [jax.tree_util.keystr(p) for p, _ in s_flat]
    if s_paths != t_paths:
        diff = [f"  {a} (state) vs {b} (template)"
                for a, b in zip(s_paths, t_paths) if a != b]
        raise ValueError(
            "transfer_state: tree structure differs:\n" + "\n".join(diff[:10])
        )
    time_invalid = np.iinfo(np.int64).max
    out = []
    for pth, (src, tmpl) in zip(t_paths, zip(
            (leaf for _, leaf in s_flat), t_leaves)):
        arr = np.asarray(jax.device_get(src))
        want_shape = tuple(np.shape(tmpl))
        want_dtype = np.dtype(
            tmpl.dtype if hasattr(tmpl, "dtype") else np.asarray(tmpl).dtype
        )
        if arr.dtype != want_dtype:
            if not (arr.dtype.kind == want_dtype.kind == "i"
                    and arr.dtype.itemsize < want_dtype.itemsize):
                raise ValueError(
                    f"transfer_state: leaf {pth}: cannot convert "
                    f"{arr.dtype} -> {want_dtype}"
                )
            arr = arr.astype(want_dtype)
        if arr.shape != want_shape:
            if arr.ndim != len(want_shape) or any(
                a > w for a, w in zip(arr.shape, want_shape)
            ):
                raise ValueError(
                    f"transfer_state: leaf {pth}: cannot shrink "
                    f"{arr.shape} -> {want_shape}"
                )
            fill = (
                time_invalid if pth.endswith(".time")
                and want_dtype == np.int64 else 0
            )
            grown = np.full(want_shape, fill, want_dtype)
            grown[tuple(slice(0, a) for a in arr.shape)] = arr
            arr = grown
        out.append(jax.numpy.asarray(arr))
    return jax.tree_util.tree_unflatten(treedef, out)

"""PCAP capture: device-side packet ring -> libpcap files.

The reference captures per-interface packets into .pcap files when a host
sets logpcap/pcapdir (reference: src/main/host/network_interface.c:337-373
_networkinterface_capturePacket; src/main/utility/pcap_writer.c writes the
global header + per-packet records with synthesized Ethernet/IP/TCP
headers and no payload bytes).

TPU-native redesign: packets never exist host-side, so capture is a
fixed-size **ring buffer in device state** ([H, R] struct-of-arrays).
Every KIND_PKT_ARRIVE handler appends one record — timestamp, src/dst
host, ports, proto/flags, length, seq/ack, and the queue verdict
(delivered / CoDel drop / tail drop; richer than the reference, which
cannot see drops in its capture). The CLI drains rings at heartbeat
boundaries and the writer synthesizes wire-format headers exactly like
pcap_writer.c — payload bytes are zero-filled metadata-only frames
(`incl_len` truncated at the headers, the standard snaplen convention).

Sequence numbers are in MSS-sized segments on device (transport/tcp.py);
the writer rescales them to byte offsets (seq * MSS) so wireshark-style
flow analysis lines up.
"""

from __future__ import annotations

import dataclasses
import struct
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

# record meta word layout ([H, R, 8] i32)
M_SRC = 0
M_DST = 1
M_SPORT = 2
M_DPORT = 3
M_META = 4  # proto | tcp flag bits | verdict << 16
M_LEN = 5
M_SEQ = 6
M_ACK = 7
N_META = 8

# Packet-lifecycle STAGE bitmask (the reference appends 21 PDS_* stage
# flags to every packet as it moves, packet.h:20-40,
# packet_addDeliveryStatus; this is the observable-stage subset of that
# lifecycle for the two-hop device pipeline). Rides the capture record's
# verdict byte, so a packet's path is reconstructible from its capture
# row — and from any standard pcap tool via the IP TOS field.
STG_ARRIVED = 1 << 0     # reached the destination host edge (PDS_RCV_INTERFACE_*)
STG_QUEUED = 1 << 1      # waited in the rx queue (standing sojourn > 0)
STG_DELIVERED = 1 << 2   # handed to the socket demux (PDS_RCV_SOCKET_*)
STG_AQM_DROP = 1 << 3    # CoDel control-law drop (PDS_RCV_INTERFACE_DROPPED)
STG_TAIL_DROP = 1 << 4   # rx-buffer tail drop
STG_RETX = 1 << 5        # sender stamped this a retransmission
STG_SENT = 1 << 6        # tx-side record (source host's own ring)

STAGE_NAMES = {
    STG_ARRIVED: "arrived", STG_QUEUED: "queued",
    STG_DELIVERED: "delivered", STG_AQM_DROP: "dropped_aqm",
    STG_TAIL_DROP: "dropped_tail", STG_RETX: "retransmitted",
    STG_SENT: "sent",
}

# legacy single-verdict aliases (round-2 records; still what the drop
# analysis keys on)
V_DELIVERED = STG_DELIVERED
V_AQM_DROP = STG_AQM_DROP
V_TAIL_DROP = STG_TAIL_DROP


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class CaptureRing:
    """Per-host packet capture ring ([H]-leading; elementwise append)."""

    t: jax.Array  # i64[H, R] arrival sim time
    meta: jax.Array  # i32[H, R, N_META]
    wr: jax.Array  # i32[H] monotone write counter
    enabled: jax.Array  # bool[H]

    @staticmethod
    def create(enabled, ring: int = 1024) -> "CaptureRing":
        enabled = jnp.asarray(enabled, bool)
        h = enabled.shape[0]
        return CaptureRing(
            t=jnp.zeros((h, ring), jnp.int64),
            meta=jnp.zeros((h, ring, N_META), jnp.int32),
            wr=jnp.zeros((h,), jnp.int32),
            enabled=enabled,
        )

    def append(self, now, src, dst, sport, dport, meta_word, length, seq,
               ack, verdict):
        """Append one record (scalar row context under vmap)."""
        r = self.t.shape[0]
        slot = self.wr % r
        on = self.enabled
        rec = jnp.stack([
            jnp.asarray(src, jnp.int32),
            jnp.asarray(dst, jnp.int32),
            jnp.asarray(sport, jnp.int32),
            jnp.asarray(dport, jnp.int32),
            jnp.asarray(meta_word, jnp.int32)
            | (jnp.asarray(verdict, jnp.int32) << 16),
            jnp.asarray(length, jnp.int32),
            jnp.asarray(seq, jnp.int32),
            jnp.asarray(ack, jnp.int32),
        ])
        return CaptureRing(
            t=self.t.at[slot].set(
                jnp.where(on, jnp.asarray(now, jnp.int64), self.t[slot])
            ),
            meta=self.meta.at[slot].set(
                jnp.where(on, rec, self.meta[slot])
            ),
            wr=self.wr + on.astype(jnp.int32),
            enabled=self.enabled,
        )


def _ip_of(host_id: int) -> bytes:
    """Deterministic fallback 10.x.y.z from the host id."""
    return bytes([10, (host_id >> 16) & 0xFF, (host_id >> 8) & 0xFF,
                  host_id & 0xFF])


class PcapWriter:
    """One host's capture file (pcap_writer.c format, LINKTYPE_ETHERNET)."""

    # our flag bits (transport/stack.py) -> wire TCP flag bits
    _FLAGMAP = ((1 << 2, 0x02), (1 << 3, 0x10), (1 << 4, 0x01),
                (1 << 5, 0x04))  # SYN, ACK, FIN, RST

    def __init__(self, path: str, ip_lookup=None, mss: int = 1434):
        self.f = open(path, "wb")
        self.ip_lookup = ip_lookup or _ip_of
        self.mss = mss
        # magic, version 2.4, tz 0, sigfigs 0, snaplen, LINKTYPE_ETHERNET
        self.f.write(struct.pack("<IHHiIII", 0xA1B2C3D4, 2, 4, 0, 0,
                                 65535, 1))

    def record(self, t_ns: int, src: int, dst: int, sport: int, dport: int,
               meta: int, length: int, seq: int, ack: int,
               verdict: int = 0) -> None:
        proto = meta & 0x3
        is_tcp = proto == 2  # sockets.PROTO_TCP
        wire_flags = 0
        for ours, theirs in self._FLAGMAP:
            if meta & ours:
                wire_flags |= theirs
        l4 = (
            struct.pack(
                ">HHIIBBHHH", sport & 0xFFFF, dport & 0xFFFF,
                (seq * self.mss) & 0xFFFFFFFF, (ack * self.mss) & 0xFFFFFFFF,
                5 << 4, wire_flags, 65535, 0, 0,
            )
            if is_tcp
            else struct.pack(">HHHH", sport & 0xFFFF, dport & 0xFFFF,
                             8 + length, 0)
        )
        ip_len = 20 + len(l4) + length
        # the lifecycle STAGE BITMASK (STG_* bits above) rides the IP
        # TOS/DSCP byte, so stage analysis works in any standard pcap
        # tool via ip.dsfield bit filters (e.g. delivered = bit 2)
        ip = struct.pack(
            ">BBHHHBBH4s4s", 0x45, verdict & 0xFF, ip_len & 0xFFFF, 0, 0,
            64, 6 if is_tcp else 17, 0, self.ip_lookup(src),
            self.ip_lookup(dst),
        )
        eth = (
            dst.to_bytes(6, "big", signed=False)
            + src.to_bytes(6, "big", signed=False)
        ) + b"\x08\x00"
        frame = eth + ip + l4  # headers only; payload is metadata
        orig = len(eth) + ip_len
        self.f.write(struct.pack("<IIII", t_ns // 10**9,
                                 (t_ns % 10**9) // 1000, len(frame), orig))
        self.f.write(frame)

    def close(self) -> None:
        self.f.close()


class CaptureDrain:
    """Incrementally drains a CaptureRing into per-host pcap files.

    Tracks each host's last-seen write counter; overrun records (ring
    wrapped between drains) are counted in `lost`."""

    def __init__(self, names, host_ids, pcap_dir: str, dns=None):
        import os

        os.makedirs(pcap_dir, exist_ok=True)
        self.lost = 0

        def lookup(gid: int) -> bytes:
            if dns is not None:
                addr = dns.address_of(gid)
                if addr is not None:
                    return addr.ip.to_bytes(4, "big")
            return _ip_of(gid)

        self.writers = {
            gid: PcapWriter(
                os.path.join(pcap_dir, f"{name}.pcap"), ip_lookup=lookup
            )
            for gid, name in zip(host_ids, names)
        }
        self.last_wr = {gid: 0 for gid in host_ids}
        # per-lifecycle-stage record counts across all drained rings
        # (surfaced by the CLI summary; the parse/plot tools read the
        # same classes from the capture files' TOS byte)
        self.stage_counts = {name: 0 for name in STAGE_NAMES.values()}

    @staticmethod
    def gather(cap: CaptureRing) -> dict:
        """Device-array refs for one drain (the heartbeat-harvest bundle
        embeds this so the pcap drain shares the heartbeat's one batched
        `jax.device_get`; hand the fetched copy to `ingest`)."""
        return {"t": cap.t, "meta": cap.meta, "wr": cap.wr}

    def drain(self, cap: CaptureRing) -> None:
        self.ingest(jax.device_get(self.gather(cap)))  # shadowlint: no-deadline=pcap drain; off the supervised loop

    def ingest(self, fetched: dict) -> None:
        """Host-side half of `drain`: decode a fetched (numpy) `gather`
        dict into the per-host pcap files. The ring is cursor-tracked
        (never reset on device), so ingesting the same snapshot twice is
        a no-op."""
        t = np.asarray(fetched["t"])
        meta = np.asarray(fetched["meta"])
        wr = np.asarray(fetched["wr"])
        r = t.shape[1]  # derive from the ring itself
        for gid, w in self.writers.items():
            new = int(wr[gid])
            start = self.last_wr[gid]
            if new - start > r:
                self.lost += new - start - r
                start = new - r
            idx = [(i % r) for i in range(start, new)]
            order = sorted(idx, key=lambda i: int(t[gid, i]))
            for i in order:
                m = meta[gid, i]
                stages = (int(m[M_META]) >> 16) & 0xFF
                for bit, name in STAGE_NAMES.items():
                    if stages & bit:
                        self.stage_counts[name] += 1
                src = int(m[M_SRC])
                if src < 0:
                    src = gid  # tx-side record: the ring's own host
                w.record(
                    int(t[gid, i]), src, int(m[M_DST]),
                    int(m[M_SPORT]), int(m[M_DPORT]),
                    int(m[M_META]) & 0xFFFF, int(m[M_LEN]),
                    int(m[M_SEQ]), int(m[M_ACK]),
                    verdict=stages,
                )
            self.last_wr[gid] = new

    def close(self) -> None:
        for w in self.writers.values():
            w.close()

"""Simtime-ordered buffered logger with per-host log levels.

The reference's ShadowLogger batches records per worker thread and ships
them to a helper pthread that sorts by simulated time before writing
(reference: src/main/core/logger/shadow_logger.c:23-58), with per-host
level overrides (:102-121). Here record producers are the host-side run
loop, the tracker, and native-process log calls — device code never
formats strings — so the logger is a plain buffered sorter: records
accumulate with a (sim_ns, seq) key and flush in simulated order, which
keeps interleaved multi-host output deterministic no matter what order
the host code produced it in.
"""

from __future__ import annotations

import atexit
import dataclasses
import sys
import weakref
from typing import IO

LEVELS = ("error", "critical", "warning", "message", "info", "debug")
_RANK = {name: i for i, name in enumerate(LEVELS)}


@dataclasses.dataclass(frozen=True)
class LogRecord:
    sim_ns: int
    seq: int
    host: str
    level: str
    message: str

    def format(self) -> str:
        s, ns = divmod(self.sim_ns, 1_000_000_000)
        h, rem = divmod(s, 3600)
        m, sec = divmod(rem, 60)
        return (
            f"{h:02d}:{m:02d}:{sec:02d}.{ns // 1000:06d} "
            f"[{self.level}] [{self.host}] {self.message}"
        )


class ShadowLogger:
    """Buffered, simtime-sorted log sink.

    Buffered records are flushed at interpreter exit (atexit, via a
    weakref so the hook never pins the logger alive) and on context
    exit — an uncaught exception between heartbeats must not eat the
    log lines already produced. Usable as a context manager::

        with ShadowLogger() as logger:
            logger.log(...)
        # flushed here, even on exception
    """

    def __init__(self, default_level: str = "message",
                 stream: IO | None = None):
        self._default = _RANK[default_level]
        self._host_levels: dict[str, int] = {}
        self._buf: list[LogRecord] = []
        self._seq = 0
        self._stream = stream if stream is not None else sys.stdout
        ref = weakref.ref(self)
        self._atexit = lambda: (lambda lg: lg and lg.flush())(ref())
        atexit.register(self._atexit)

    def __enter__(self) -> "ShadowLogger":
        return self

    def __exit__(self, *exc) -> None:
        self.flush()

    def __del__(self):  # pragma: no cover - GC timing dependent
        try:
            atexit.unregister(self._atexit)
        except Exception:
            pass

    def set_default_level(self, level: str) -> None:
        self._default = _RANK[level]

    def set_host_level(self, host: str, level: str) -> None:
        """Per-host override (shadow_logger.c:102-121; host loglevel attr)."""
        if level:
            self._host_levels[host] = _RANK[level]

    def enabled(self, host: str, level: str) -> bool:
        return _RANK[level] <= self._host_levels.get(host, self._default)

    def log(self, sim_ns: int, host: str, level: str, message: str) -> None:
        if not self.enabled(host, level):
            return
        self._buf.append(
            LogRecord(int(sim_ns), self._seq, host, level, message)
        )
        self._seq += 1

    def flush(self) -> int:
        """Write buffered records in (simtime, arrival) order. Safe to
        call at interpreter exit: a closed/broken stream drops the
        batch instead of raising into the atexit machinery."""
        self._buf.sort(key=lambda r: (r.sim_ns, r.seq))
        n = len(self._buf)
        try:
            for r in self._buf:
                print(r.format(), file=self._stream)
        except ValueError:  # stream already closed (interpreter teardown)
            pass
        self._buf.clear()
        return n

from shadow_tpu.utils.checkpoint import (  # noqa: F401
    checkpoint_generations,
    find_resume_checkpoint,
    load_checkpoint,
    load_shard_set,
    read_header_info,
    save_checkpoint,
    shard_member_path,
    verify_checkpoint,
)

from shadow_tpu.utils.checkpoint import (  # noqa: F401
    checkpoint_generations,
    find_resume_checkpoint,
    load_checkpoint,
    save_checkpoint,
    verify_checkpoint,
)

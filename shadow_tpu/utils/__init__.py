from shadow_tpu.utils.checkpoint import (  # noqa: F401
    load_checkpoint,
    save_checkpoint,
)

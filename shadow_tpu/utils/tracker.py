"""Per-host heartbeat tracker: node and socket CSV lines per interval.

The reference's Tracker emits `[shadow-heartbeat] [node|socket|ram]` CSV
at a configurable interval, splitting bytes into payload/header classes
with retransmission counts (reference: src/main/host/tracker.c:433-561).
Here the equivalents are interval deltas of device-side accumulators:
socket tables carry payload bytes, the NICs carry wire packet/byte
counters (header bytes = wire - payload), the TCBs carry retransmitted
segment counts, and the engine's stats carry executed-event counts. The
lines feed shadow_tpu.tools.parse_shadow the way the reference's feed
parse-shadow.py.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import numpy as np

NODE_HEADER = (
    "[shadow-heartbeat] [node-header] time-seconds,name,"
    "recv-bytes,send-bytes,recv-wire-bytes,send-wire-bytes,"
    "recv-packets,send-packets,recv-header-bytes,send-header-bytes,"
    "retrans-segments,events-executed,queue-drops,tail-drops"
)
SOCKET_HEADER = (
    "[shadow-heartbeat] [socket-header] time-seconds,name,slot,"
    "protocol,local-port,peer-host,peer-port,recv-bytes,send-bytes,"
    "retrans-segments"
)
# the reference's [ram] line tracks per-host allocation; the device-array
# analog is occupancy of the host's fixed-capacity state rows
RAM_HEADER = (
    "[shadow-heartbeat] [ram-header] time-seconds,name,"
    "queue-slots-used,queue-capacity,sockets-used,sockets-capacity,"
    "state-bytes"
)
# fault attribution (only emitted when the run has a fault schedule):
# packets lost to fault overlays, events voided by crashes, and seconds
# of scheduled downtime — so runs report what the chaos did
FAULT_HEADER = (
    "[shadow-heartbeat] [fault-header] time-seconds,name,"
    "fault-drops,quarantined-events,downtime-seconds"
)
# supervised-run progress (one line per heartbeat, whole-run not
# per-host): wall-clock window/event rates, how close the run came to
# the watchdog deadline since the last beat, and checkpoints written —
# the operator-facing "is this campaign healthy" row
SUPERVISOR_HEADER = (
    "[shadow-heartbeat] [supervisor-header] time-seconds,"
    "windows,windows-per-sec,events-per-sec,"
    "stall-margin-seconds,checkpoints-written"
)
# exact per-host record counts from the device trace drain (only with
# --trace): unlike the [node] section's interval-sampled counter deltas,
# these come straight from the per-event trace records, so drop and
# retransmit attribution is exact down to the event
TRACE_HEADER = (
    "[shadow-heartbeat] [trace-header] time-seconds,name,"
    "exec-records,send-records,net-drop-records,fault-drop-records,"
    "lost-records"
)
# queue-pressure telemetry (only with --overflow spill/grow): one
# aggregate row per interval — how many hosts hit the spill path, the
# peak queue fill, interval spill/refill counts, events lost to ring
# overflow (0 unless the ring is undersized), events resident in the
# host reservoir, and harvest wall time (stripped from determinism
# diffs by tools/strip_log.py like every wall-clock column)
PRESSURE_HEADER = (
    "[shadow-heartbeat] [pressure-header] time-seconds,"
    "hosts-pressured,fill-hwm,spilled,refilled,spill-lost,"
    "reservoir-resident,overdue,harvest-seconds"
)
# scenario-fleet progress (only with --fleet): one row per LANE per
# heartbeat, from the harvest bundle's [L]-valued summary reductions —
# per-lane sim clock, window/event totals, the interval's event delta,
# queue drops, and queue fill. Lanes that finished early keep emitting
# rows with a frozen clock (their windows are masked no-ops), which is
# exactly the signal a sweep operator reads lane skew from
FLEET_HEADER = (
    "[shadow-heartbeat] [fleet-header] time-seconds,lane,seed,"
    "now-seconds,windows,events,events-delta,queue-drops,fill"
)


@dataclasses.dataclass
class Snapshot:
    """Host-side copy of the cumulative counters a heartbeat diffs."""

    rx: np.ndarray  # [H] payload bytes
    tx: np.ndarray
    rx_wire: np.ndarray  # [H] wire bytes through the rx NIC
    tx_wire: np.ndarray
    rx_pkts: np.ndarray
    tx_pkts: np.ndarray
    retx: np.ndarray  # [H] retransmitted segments
    events: np.ndarray  # [H]
    drops: np.ndarray  # [H]
    tail_drops: np.ndarray  # [H] NIC receive-buffer drop-tail losses
    fault_drops: np.ndarray  # [H] packets lost to fault overlays
    quarantined: np.ndarray  # [H] events voided by host crashes

    @staticmethod
    def zero(n: int) -> "Snapshot":
        z = lambda: np.zeros((n,), np.int64)
        return Snapshot(z(), z(), z(), z(), z(), z(), z(), z(), z(), z(),
                        z(), z())


def snapshot_refs(st) -> dict:
    """Device-array refs (reductions applied, nothing transferred) for
    one Snapshot — the gather half of `snapshot`. The heartbeat-harvest
    bundle embeds this dict so the whole heartbeat costs ONE batched
    `jax.device_get` instead of one transfer per counter."""
    import jax.numpy as jnp

    net = st.hosts.net
    socks = net.sockets
    retx = (
        net.tcb.n_retx.sum(axis=1)
        if net.tcb is not None
        else jnp.zeros((socks.rx_bytes.shape[0],), jnp.int64)
    )
    return {
        "rx": socks.rx_bytes.sum(axis=1),
        "tx": socks.tx_bytes.sum(axis=1),
        "rx_wire": net.nic_rx.wire,
        "tx_wire": net.nic_tx.wire,
        "rx_pkts": net.nic_rx.pkts,
        "tx_pkts": net.nic_tx.pkts,
        "retx": retx,
        "events": st.stats.n_executed,
        "drops": st.queues.drops,
        "tail_drops": net.nic_rx.drops,
        "fault_drops": st.stats.n_fault_dropped,
        "quarantined": st.stats.n_quarantined,
    }


def snapshot_from(fetched: dict) -> Snapshot:
    """Build a Snapshot from a fetched (numpy) `snapshot_refs` dict."""
    a = {k: np.asarray(v) for k, v in fetched.items()}
    a["drops"] = a["drops"].astype(np.int64)
    return Snapshot(**a)


def snapshot(st) -> Snapshot:
    """Pull the cumulative counters from an EngineState (one batched
    transfer)."""
    return snapshot_from(jax.device_get(snapshot_refs(st)))  # shadowlint: no-deadline=tracker snapshot; the caller overlaps it behind dispatch


class SupervisorHeartbeat:
    """Whole-run supervision heartbeat: windows/sec, events/sec, the
    minimum watchdog stall margin observed since the last beat, and the
    checkpoints-written count.

    The per-host sections above answer "what did the simulated network
    do"; this row answers "is the *driver* healthy" — the quantity a
    long campaign's operator watches. `observe_margin` is called every
    window boundary (cheap: two float compares); `beat` once per
    heartbeat interval emits the CSV line through the same simtime-
    sorted logger as the other sections.
    """

    def __init__(self, logger: Any, watchdog: Any = None):
        import time

        self.logger = logger
        self.watchdog = watchdog  # runtime.Watchdog or None
        self.checkpoints_written = 0
        self._clock = time.monotonic
        self._last_wall = self._clock()
        self._last_windows = 0
        self._last_events = 0
        self._min_margin: float | None = None
        self._emitted_header = False

    def checkpoint_written(self) -> None:
        self.checkpoints_written += 1

    def observe_margin(self) -> None:
        """Record the watchdog's remaining deadline at a window
        boundary; the beat reports the interval's minimum (the closest
        the run came to being declared stalled)."""
        if self.watchdog is None:
            return
        m = self.watchdog.margin_s()
        if self._min_margin is None or m < self._min_margin:
            self._min_margin = m

    def beat(self, sim_ns: int, summary: dict) -> None:
        """Emit one supervisor line. `summary` is engine.state_summary
        output (windows/executed are cumulative; rates are interval
        deltas over wall time)."""
        if not self._emitted_header:
            self.logger.log(sim_ns, "tracker", "message", SUPERVISOR_HEADER)
            self._emitted_header = True
        wall = self._clock()
        dt = max(wall - self._last_wall, 1e-9)
        windows = int(summary.get("windows", 0))
        events = int(summary.get("executed", 0))
        w_rate = (windows - self._last_windows) / dt
        e_rate = (events - self._last_events) / dt
        margin = (
            "" if self._min_margin is None else f"{self._min_margin:.1f}"
        )
        self.logger.log(
            sim_ns, "supervisor", "message",
            "[shadow-heartbeat] [supervisor] "
            f"{sim_ns // 1_000_000_000},{windows},{w_rate:.1f},"
            f"{e_rate:.1f},{margin},{self.checkpoints_written}",
        )
        self._last_wall = wall
        self._last_windows = windows
        self._last_events = events
        self._min_margin = None


class Tracker:
    """Stateful heartbeat emitter: call heartbeat() once per interval.

    `info_of`/`level_of` hold per-host overrides of which sections a host
    logs (node/socket — the heartbeatloginfo attr) and at which level
    (the heartbeatloglevel attr; default "message") — per host like the
    reference, not globally (tracker.c:433-561).
    """

    def __init__(self, names: list[str], logger: Any,
                 log_info: tuple[str, ...] = ("node",),
                 info_of: dict[str, tuple[str, ...]] | None = None,
                 level_of: dict[str, str] | None = None,
                 faults: Any = None, trace: Any = None,
                 pressure: Any = None, metrics: Any = None):
        self.names = names
        self.logger = logger
        self.log_info = log_info
        self.info_of = info_of or {}
        self.level_of = level_of or {}
        self.faults = faults  # CompiledFaults -> emit the [fault] section
        self.trace = trace  # obs.TraceDrain -> emit the [trace] section
        # runtime.pressure.PressureController -> emit the [pressure]
        # section (cumulative snapshots diffed per interval, like prev)
        self.pressure = pressure
        # obs.metrics.MetricsRegistry -> emit the [metrics] section: the
        # exporter's *cumulative* totals (not interval deltas), so a
        # live /metrics scrape, this row, and the end-of-run summary are
        # directly comparable. The CLI loop ingests the fetched bundle
        # into the registry before consume() runs this heartbeat, so
        # the row and the [node] section describe the same extraction.
        self.metrics = metrics
        self._prev_pressure: dict | None = None
        # --stats: the harvest hands the fetched histogram bundle to
        # stats_from separately (it lives at the bundle top level, next
        # to the [metrics] reductions, not inside the tracker gather)
        self._emitted_stats_header = False
        self._stats_prev_ns: int | None = None
        self.prev = Snapshot.zero(len(names))
        # None until the first heartbeat lands; afterwards the guard in
        # heartbeat() drops zero-length (or backwards) intervals so a
        # driver that fires two beats at the same sim time can't emit
        # all-zero delta rows or divide the interval math by nothing
        self._prev_ns: int | None = None
        self._emitted_headers = False
        # (queue capacity, socket capacity, per-host state bytes) — pure
        # shape math captured by gather() so heartbeat_from is state-free
        self._ram_static: tuple[int, int, int] | None = None

    def _info(self, name: str) -> tuple[str, ...]:
        return self.info_of.get(name, self.log_info)

    def _level(self, name: str) -> str:
        return self.level_of.get(name, "message")

    def gather(self, st) -> dict:
        """Device-array refs for everything one heartbeat consumes —
        node counters, and the socket/ram/pressure sections when any
        host enables them. Per-host reductions happen on device; the
        caller fetches the whole dict in ONE `jax.device_get` (the
        heartbeat-harvest bundle) and hands it to `heartbeat_from`."""
        import math

        import jax.numpy as jnp

        from shadow_tpu.core.timebase import TIME_INVALID

        refs: dict[str, Any] = {"snap": snapshot_refs(st)}
        if any("socket" in self._info(n) for n in self.names):
            net = st.hosts.net
            socks = net.sockets
            refs["socket"] = {
                "proto": socks.proto, "lport": socks.local_port,
                "phost": socks.peer_host, "pport": socks.peer_port,
                "rx": socks.rx_bytes, "tx": socks.tx_bytes,
                "retx": (net.tcb.n_retx if net.tcb is not None
                         else jnp.zeros_like(socks.proto)),
            }
        if any("ram" in self._info(n) for n in self.names):
            refs["ram"] = {
                "q_used": jnp.sum(
                    st.queues.time != TIME_INVALID, axis=1,
                    dtype=jnp.int32,
                ),
                "s_used": jnp.sum(
                    st.hosts.net.sockets.proto != 0, axis=1,
                    dtype=jnp.int32,
                ),
            }
            # static shape math, not a transfer: ride it in the bundle
            # so heartbeat_from never needs the state
            self._ram_static = (
                int(st.queues.time.shape[1]),
                int(st.hosts.net.sockets.proto.shape[1]),
                sum(
                    math.prod(l.shape) * l.dtype.itemsize
                    for l in jax.tree.leaves(st)
                ) // max(len(self.names), 1),
            )
        if self.pressure is not None and (
            getattr(st.queues, "spill", None) is not None
        ):
            refs["pressure"] = self.pressure.gather(st)
        return refs

    def heartbeat(self, st, sim_ns: int) -> None:
        """Gather + fetch + emit in one call (one batched transfer).
        The overlapped CLI loop instead calls `gather` inside its
        harvest bundle and `heartbeat_from` on the fetched copy."""
        if self._prev_ns is not None and sim_ns <= self._prev_ns:
            return  # zero-length interval: nothing can have accumulated
        self.heartbeat_from(jax.device_get(self.gather(st)), sim_ns)  # shadowlint: no-deadline=tracker heartbeat; the caller overlaps it behind dispatch

    def heartbeat_from(self, fetched: dict, sim_ns: int) -> None:
        """Emit one heartbeat from a fetched (numpy) `gather` dict —
        pure host-side work, safe to run while the device computes the
        next window segment."""
        if self._prev_ns is not None and sim_ns <= self._prev_ns:
            return  # zero-length interval: nothing can have accumulated
        cur = snapshot_from(fetched["snap"])
        any_socket = any("socket" in self._info(n) for n in self.names)
        if not self._emitted_headers:
            self.logger.log(sim_ns, "tracker", "message", NODE_HEADER)
            if any_socket:
                self.logger.log(sim_ns, "tracker", "message", SOCKET_HEADER)
            if any("ram" in self._info(n) for n in self.names):
                self.logger.log(sim_ns, "tracker", "message", RAM_HEADER)
            if self.faults is not None:
                self.logger.log(sim_ns, "tracker", "message", FAULT_HEADER)
            if self.trace is not None:
                self.logger.log(sim_ns, "tracker", "message", TRACE_HEADER)
            if self.pressure is not None:
                self.logger.log(sim_ns, "tracker", "message",
                                PRESSURE_HEADER)
            if self.metrics is not None:
                from shadow_tpu.obs.metrics import METRICS_HEADER

                self.logger.log(sim_ns, "tracker", "message",
                                METRICS_HEADER)
            self._emitted_headers = True
        t_s = sim_ns // 1_000_000_000
        p = self.prev
        # a crash-restart re-templates the host's state, rewinding its
        # socket/NIC accumulators — a negative interval delta just means
        # "rebooted", so clamp to 0 (the lost remainder is attributed in
        # the [fault] section instead)
        d = lambda a, b: max(int(a) - int(b), 0)
        for i, name in enumerate(self.names):
            if "node" not in self._info(name):
                continue
            rx, tx = d(cur.rx[i], p.rx[i]), d(cur.tx[i], p.tx[i])
            rxw, txw = (
                d(cur.rx_wire[i], p.rx_wire[i]),
                d(cur.tx_wire[i], p.tx_wire[i]),
            )
            self.logger.log(
                sim_ns, name, self._level(name),
                "[shadow-heartbeat] [node] "
                f"{t_s},{name},{rx},{tx},{rxw},{txw},"
                f"{d(cur.rx_pkts[i], p.rx_pkts[i])},"
                f"{d(cur.tx_pkts[i], p.tx_pkts[i])},"
                f"{max(rxw - rx, 0)},{max(txw - tx, 0)},"
                f"{d(cur.retx[i], p.retx[i])},"
                f"{cur.events[i] - p.events[i]},"
                f"{d(cur.drops[i], p.drops[i])},"
                f"{d(cur.tail_drops[i], p.tail_drops[i])}",
            )
        if any_socket and "socket" in fetched:
            self._socket_lines(fetched["socket"], sim_ns, t_s)
        if "ram" in fetched:
            self._ram_lines(fetched["ram"], sim_ns, t_s)
        if self.faults is not None:
            self._fault_lines(cur, sim_ns, t_s)
        if self.trace is not None:
            self._trace_lines(sim_ns, t_s)
        if self.pressure is not None and "pressure" in fetched:
            self._pressure_line(fetched["pressure"], sim_ns, t_s)
        if self.metrics is not None:
            self.logger.log(
                sim_ns, "tracker", "message",
                "[shadow-heartbeat] [metrics] "
                + self.metrics.metrics_row(t_s),
            )
        self.prev = cur
        self._prev_ns = sim_ns

    def stats_from(self, stats_fetched: dict, sim_ns: int) -> None:
        """Emit one `[stats]` row from a fetched --stats histogram
        bundle (obs.stats.stats_device_refs after device_get): per
        family the cumulative count, value sum, p50/p95, and the sparse
        bucket spec — enough for parse_shadow/plot_shadow to rebuild
        the full distributions from the log alone. Cumulative like the
        [metrics] row, so the last row reconciles with the end-of-run
        summary."""
        if self._stats_prev_ns is not None and \
                sim_ns <= self._stats_prev_ns:
            return
        from shadow_tpu.obs.stats import (
            STATS_HEADER, stats_row, summarize,
        )

        if not self._emitted_stats_header:
            self.logger.log(
                sim_ns, "tracker", "message",
                "[shadow-heartbeat] [stats-header] " + STATS_HEADER)
            self._emitted_stats_header = True
        t_s = sim_ns // 1_000_000_000
        self.logger.log(
            sim_ns, "tracker", "message",
            "[shadow-heartbeat] [stats] "
            + stats_row(t_s, summarize(stats_fetched)),
        )
        self._stats_prev_ns = sim_ns

    def _pressure_line(self, fetched: dict, sim_ns: int, t_s: int) -> None:
        """One aggregate queue-pressure row per interval (like the
        [supervisor] section: whole-run, not per-host — pressure is a
        capacity-sizing signal, and the per-host detail lives in the
        trace ops and the validator). Counters are cumulative on the
        controller/ring; this diffs them against the previous beat."""
        cur = self.pressure.snapshot_from(fetched)
        n_spilled = np.asarray(fetched["n_spilled"])
        prev = self._prev_pressure or {}
        prev_sp = prev.get("per_host_spilled")
        d_sp = n_spilled - (prev_sp if prev_sp is not None else 0)
        hosts_pressured = int((d_sp > 0).sum())
        dd = lambda k: int(cur.get(k, 0)) - int(prev.get(k, 0))
        self.logger.log(
            sim_ns, "tracker", "message",
            "[shadow-heartbeat] [pressure] "
            f"{t_s},{hosts_pressured},{cur['fill_hwm']},"
            f"{dd('spilled')},{dd('refilled')},{dd('spill_lost')},"
            f"{cur['resident']},{dd('overdue')},"
            f"{cur['harvest_seconds'] - prev.get('harvest_seconds', 0.0):.3f}",
        )
        cur["per_host_spilled"] = n_spilled
        self._prev_pressure = cur

    def _trace_lines(self, sim_ns: int, t_s: int) -> None:
        """Exact per-host record counts from the device trace drain.
        Skips all-zero rows like the [fault] section; the drain must be
        harvested (TraceDrain.drain_state) before the heartbeat or the
        interval is empty and nothing is emitted."""
        iv = self.trace.take_interval()
        if iv is None:
            return
        g = lambda a, i: int(a[i]) if i < len(a) else 0
        for i, name in enumerate(self.names):
            if "node" not in self._info(name):
                continue
            ex = g(iv["exec"], i)
            snd = g(iv["send"], i)
            drp = g(iv["drop"], i)
            fdrp = g(iv["fault_drop"], i)
            lost = g(iv["lost"], i)
            if ex == 0 and snd == 0 and drp == 0 and fdrp == 0 and lost == 0:
                continue
            self.logger.log(
                sim_ns, name, self._level(name),
                "[shadow-heartbeat] [trace] "
                f"{t_s},{name},{ex},{snd},{drp},{fdrp},{lost}",
            )

    def _fault_lines(self, cur: Snapshot, sim_ns: int, t_s: int) -> None:
        p = self.prev
        downtime = self.faults.downtime_in(self._prev_ns or 0, sim_ns)
        for i, name in enumerate(self.names):
            if "node" not in self._info(name):
                continue
            fd = cur.fault_drops[i] - p.fault_drops[i]
            qr = cur.quarantined[i] - p.quarantined[i]
            dt = downtime[i] if i < len(downtime) else 0.0
            if fd == 0 and qr == 0 and dt == 0.0:
                continue
            self.logger.log(
                sim_ns, name, self._level(name),
                "[shadow-heartbeat] [fault] "
                f"{t_s},{name},{fd},{qr},{dt:.3f}",
            )

    def _ram_lines(self, fetched: dict, sim_ns: int, t_s: int) -> None:
        """Per-host state occupancy (the reference's [ram] allocation
        heartbeat, tracker.c ram section, reinterpreted for fixed-width
        device arrays: used slots vs capacity plus the per-host share of
        the resident state bytes). Occupancy reduces on device in
        `gather`; the static capacities/bytes ride `_ram_static`."""
        used = np.asarray(fetched["q_used"])
        s_used = np.asarray(fetched["s_used"])
        cap, s_cap, state_bytes = self._ram_static
        for i, name in enumerate(self.names):
            if "ram" not in self._info(name):
                continue
            self.logger.log(
                sim_ns, name, self._level(name),
                "[shadow-heartbeat] [ram] "
                f"{t_s},{name},{used[i]},{cap},{s_used[i]},{s_cap},"
                f"{state_bytes}",
            )

    def _socket_lines(self, fetched: dict, sim_ns: int, t_s: int) -> None:
        proto = np.asarray(fetched["proto"])
        lport = np.asarray(fetched["lport"])
        phost = np.asarray(fetched["phost"])
        pport = np.asarray(fetched["pport"])
        rx = np.asarray(fetched["rx"])
        tx = np.asarray(fetched["tx"])
        retx = np.asarray(fetched["retx"])
        pname = {0: "NONE", 1: "UDP", 2: "TCP"}
        for i, name in enumerate(self.names):
            if "socket" not in self._info(name):
                continue
            for s in range(proto.shape[1]):
                if proto[i, s] == 0:
                    continue
                self.logger.log(
                    sim_ns, name, self._level(name),
                    "[shadow-heartbeat] [socket] "
                    f"{t_s},{name},{s},{pname.get(int(proto[i, s]), '?')},"
                    f"{lport[i, s]},{phost[i, s]},{pport[i, s]},"
                    f"{rx[i, s]},{tx[i, s]},{retx[i, s]}",
                )

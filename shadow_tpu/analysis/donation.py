"""Donation/aliasing verifier + host-transfer census (post-compile).

PR 6 threaded `donate_argnums` through every window-loop jit so the
[H, C] queue arrays alias through instead of copying once per window.
But donation is a *request*: XLA silently drops it when a leaf's
layout, dtype, or sharding prevents aliasing — the program still
answers correctly, it just pays a 2x memory tax nobody sees. This
module compiles each production jit and reads the answer back from
the compiled module's `input_output_alias` table:

- `alias_params(text)` parses the aliased parameter numbers from the
  compiled HLO header. XLA numbers parameters in the flattened-leaf
  order of the jit's arguments *minus* the leaves jax's dead-argument
  elimination dropped (`keep_unused=False` default; e.g. `.now` is
  write-only in `step_window`, so it never becomes a parameter) — the
  kept-leaf set comes from the lowering's `kept_var_idx`, so each
  donated leaf maps to exactly one parameter number.
- `audit_jit(jitted, args, label)` verifies every donated leaf
  actually aliases; a dropped donation becomes a named violation
  carrying the offending leaf path (e.g. ``args[0].queues.time``).
  Donated-but-unused leaves (elided before XLA, so no copy can exist)
  are reported separately, not failed.
- `audit_all()` runs the production targets: the engine window loop
  (`Engine.run`), the pressure path's `step_window` jit (what
  `runtime.pressure.run_with_spill` builds), the harvest extraction
  jits (full + light), and the sharded `Simulation._wrap` step over
  an 8-device mesh (skipped, not failed, when fewer devices exist).
- `transfer_census(text)` counts transfer-crossing ops
  (infeed/outfeed/send/recv) in a compiled program; `census_all()`
  applies it to the harvest extraction programs, pinning the "exactly
  one host fetch per heartbeat segment" claim: the compiled segment
  program crosses to host zero times, so the single `jax.device_get`
  in `HeartbeatHarvest.fetch` is the segment's only transfer (the
  runtime side is pinned in tests/test_dataflow.py).

CLI: ``python -m shadow_tpu.tools.lint --donation-audit``.
"""

from __future__ import annotations

import re
from typing import Any, Callable, Iterable

# `{output_index}: (param_number, {}, may-alias)` entries in the
# `input_output_alias={ ... }` header of compiled HLO text.
_ALIAS_ENTRY_RE = re.compile(r"\{[\d,\s]*\}:\s*\((\d+),")
# Transfer-crossing op invocations in compiled HLO (op name directly
# followed by its operand list — metadata strings never match).
_TRANSFER_RE = re.compile(
    r"\b(infeed|outfeed|send|recv|send-done|recv-done)\(")


def alias_params(compiled_text: str) -> set[int]:
    """Parameter numbers that alias an output in compiled HLO text."""
    i = compiled_text.find("input_output_alias={")
    if i < 0:
        return set()
    start = compiled_text.index("{", i)
    depth, j = 0, start
    while j < len(compiled_text):
        if compiled_text[j] == "{":
            depth += 1
        elif compiled_text[j] == "}":
            depth -= 1
            if depth == 0:
                break
        j += 1
    table = compiled_text[start:j + 1]
    return {int(p) for p in _ALIAS_ENTRY_RE.findall(table)}


def _leaf_paths(args: tuple) -> list[str]:
    """Flat-order leaf path strings over the call arguments."""
    import jax

    out: list[str] = []
    for i, arg in enumerate(args):
        for path, _leaf in jax.tree_util.tree_flatten_with_path(arg)[0]:
            out.append(f"args[{i}]{jax.tree_util.keystr(path)}")
    return out


def transfer_census(compiled_text: str) -> dict[str, int]:
    """Count transfer-crossing ops in compiled HLO text."""
    counts: dict[str, int] = {}
    for op in _TRANSFER_RE.findall(compiled_text):
        counts[op] = counts.get(op, 0) + 1
    return counts


def audit_jit(jitted: Callable, args: tuple, label: str) -> dict:
    """Compile `jitted(*args)` and verify every donated leaf aliases.

    `jitted` must already carry its donate_argnums (the production
    object is audited, not a reconstruction). Donation flags come from
    the lowering's own per-leaf `args_info`; parameter numbers account
    for jax's dead-argument elimination via `kept_var_idx` (a donated
    leaf the jit dropped as unused never reaches XLA — no copy can
    exist, so it is reported as `unused_leaves`, not failed). Returns
    a report dict; `violations` names each donated-but-unaliased leaf
    path.
    """
    import jax

    lowered = jitted.lower(*args)
    compiled = lowered.compile()
    text = compiled.as_text()
    aliased = alias_params(text)
    infos = jax.tree_util.tree_leaves(
        lowered.args_info, is_leaf=lambda x: hasattr(x, "donated"))
    paths = _leaf_paths(args)
    kept = getattr(getattr(lowered, "_lowering", None), "compile_args",
                   {}).get("kept_var_idx")
    if kept is None:  # private API moved: assume nothing was elided
        kept = range(len(infos))
    param_of = {flat: p for p, flat in enumerate(sorted(kept))}
    violations: list[str] = []
    unused: list[str] = []
    n_donated = n_aliased = 0
    for flat, info in enumerate(infos):
        if not getattr(info, "donated", False):
            continue
        n_donated += 1
        p = param_of.get(flat)
        if p is None:
            unused.append(paths[flat])
            continue
        if p in aliased:
            n_aliased += 1
        else:
            violations.append(
                f"{label}: donated leaf {paths[flat]} (parameter {p}) "
                f"is NOT aliased in the compiled module — XLA dropped "
                f"the donation; the buffer is copied every call")
    report = {
        "label": label,
        "donated_leaves": n_donated,
        "aliased_leaves": n_aliased,
        "unused_leaves": unused,
        "violations": violations,
        "transfers": transfer_census(text),
        "ok": not violations,
    }
    try:
        ma = compiled.memory_analysis()
        report["memory"] = {
            "argument_bytes": int(ma.argument_size_in_bytes),
            "output_bytes": int(ma.output_size_in_bytes),
            "alias_bytes": int(ma.alias_size_in_bytes),
            "temp_bytes": int(ma.temp_size_in_bytes),
        }
    except Exception:
        pass  # memory_analysis is backend-dependent; the alias table is not
    return report


def audit_fn(fn: Callable, args: tuple, donate_argnums, label: str) -> dict:
    """Convenience: jit `fn` with the given donation and audit it."""
    import jax

    donated = ((donate_argnums,) if isinstance(donate_argnums, int)
               else tuple(donate_argnums))
    jitted = jax.jit(fn, donate_argnums=donated)
    return audit_jit(jitted, args, label)


# ------------------------------------------------------------- targets


def _phold_tiny():
    import jax.numpy as jnp

    from shadow_tpu.models import phold

    eng, init = phold.build(8, seed=3, capacity=32, msgs_per_host=2)
    return eng, init(), jnp.int64(5_000_000_000)


def _sim_tiny(**kw):
    from shadow_tpu import examples
    from shadow_tpu.config import parse_config
    from shadow_tpu.sim import build_simulation

    text = examples.phold_example(8, msgs_per_host=2, stoptime=5)
    return build_simulation(parse_config(text), seed=3, **kw)


def audit_all(names: Iterable[str] | None = None) -> dict[str, dict]:
    """Audit the production window-loop jits. Each target compiles the
    object the runtime actually calls:

    - engine_run: jit(Engine.run, donate_argnums=0) — the unsharded
      window loop (what Simulation._wrap builds for mesh=None).
    - pressure_step: jit(Engine.step_window, donate_argnums=0) on a
      spill-enabled build — runtime.pressure.run_with_spill's step.
    - harvest_full / harvest_light: HeartbeatHarvest._build(full) —
      the donating extraction jits the CLI heartbeat loop calls.
    - sharded_step: Simulation._wrap(engine.run) over an 8-device
      mesh (shard_map path) — skipped when fewer devices exist.
    - frontier_run: jit(Engine.run, donate_argnums=0) on a
      frontier-drain TCP build (docs/11-Performance.md "Model-tier
      batching") — the per-round outbuf staging must not break the
      state carry's aliasing.
    - fleet_run: the 4-lane PHOLD Fleet's production `_jit_run` (the
      vmapped window loop, donate_argnums=0 on the stacked `[L, ...]`
      state) — proves the whole stacked carry aliases through every
      segment; the lane binds (arg 1) are reused and must NOT donate.
    """
    import jax.numpy as jnp

    targets: dict[str, Callable[[], dict]] = {}

    def engine_run() -> dict:
        eng, st, stop = _phold_tiny()
        return audit_fn(eng.run, (st, stop), 0, "engine_run")

    def frontier_run() -> dict:
        from shadow_tpu import examples
        from shadow_tpu.config import parse_config
        from shadow_tpu.sim import build_simulation

        text = examples.tgen_example(n_pairs=2, stoptime=5)
        sim = build_simulation(parse_config(text), seed=3, n_sockets=4,
                               frontier=4)
        return audit_fn(sim.engine.run,
                        (sim.state0, jnp.int64(sim.stop_ns)),
                        0, "frontier_run")

    def pressure_step() -> dict:
        sim = _sim_tiny(overflow="spill", spill_len=64)
        # the exact jit runtime.pressure.run_with_spill constructs
        return audit_fn(sim.engine.step_window,
                        (sim.state0, jnp.int64(sim.stop_ns)),
                        0, "pressure_step")

    def _harvest(full: bool) -> dict:
        from shadow_tpu.runtime.harvest import HeartbeatHarvest

        sim = _sim_tiny()
        h = HeartbeatHarvest(sim)
        label = "harvest_full" if full else "harvest_light"
        return audit_jit(h._build(full), (sim.state0,), label)

    def sharded_step() -> dict:
        from shadow_tpu.parallel import mesh as pmesh

        m = pmesh.make_mesh(8)  # RuntimeError when devices < 8 -> skip
        sim = _sim_tiny(mesh=m)
        jitted = sim._wrap(sim.engine.run)
        return audit_jit(jitted, (sim.state0, jnp.int64(sim.stop_ns)),
                         "sharded_step")

    def fleet_run() -> dict:
        from shadow_tpu.runtime.fleet import build_fleet_from_engine

        eng, st, stop = _phold_tiny()
        fleet = build_fleet_from_engine(eng, st, 4, seeds=(0, 1, 2, 3))
        # the production jit itself (donate_argnums=0), not a remake
        return audit_jit(fleet._jit_run,
                         (fleet.state0, fleet.binds, stop), "fleet_run")

    targets["engine_run"] = engine_run
    targets["fleet_run"] = fleet_run
    targets["frontier_run"] = frontier_run
    targets["pressure_step"] = pressure_step
    targets["harvest_full"] = lambda: _harvest(True)
    targets["harvest_light"] = lambda: _harvest(False)
    targets["sharded_step"] = sharded_step

    out: dict[str, dict] = {}
    for name in (names or sorted(targets)):
        try:
            out[name] = targets[name]()
        except RuntimeError as e:
            out[name] = {"label": name, "ok": True, "skipped": str(e),
                         "violations": []}
    return out


def census_all() -> dict[str, Any]:
    """Transfer census over the compiled harvest segment programs.

    The heartbeat contract is "exactly one host fetch per segment":
    the compiled extraction program must cross to host zero times
    (every transfer op counted here is a violation), leaving the
    single `jax.device_get` in HeartbeatHarvest.fetch as the
    segment's only device->host transfer. The runtime single-fetch
    pin lives in tests/test_dataflow.py.
    """
    from shadow_tpu.runtime.harvest import HeartbeatHarvest

    sim = _sim_tiny()
    h = HeartbeatHarvest(sim)
    out: dict[str, Any] = {"fetches_per_segment": 1, "ok": True,
                           "violations": []}
    for full in (True, False):
        name = "harvest_full" if full else "harvest_light"
        text = h._build(full).lower(sim.state0).compile().as_text()
        counts = transfer_census(text)
        out[name] = {"transfer_ops": counts}
        if counts:
            out["ok"] = False
            out["violations"].append(
                f"{name}: compiled extraction program crosses to host "
                f"({counts}) — the segment must fetch exactly once, "
                f"through HeartbeatHarvest.fetch")
    return out

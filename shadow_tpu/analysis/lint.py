"""shadowlint — AST linter for JAX footguns in the shadow_tpu package.

The simulator's correctness story leans on a small set of disciplines
(ROADMAP.md invariants; docs/10-Static-Analysis.md rule catalog):
everything in the window loop traces once and lowers to one XLA
program, simulated time is always the `core.timebase` dtype (i64 ns),
and pytrees have a deterministic leaf order. Each lint rule guards one
way those disciplines have been (or nearly were) broken:

- SL101 host materialization in jit scope — ``float()``/``int()``/
  ``bool()`` on traced values, ``.item()``, ``np.*`` compute,
  ``jax.device_get``: silently forces a device sync per call, or a
  tracer error at the worst possible time.
- SL102 Python branch on a traced value in jit scope — ``if``/``while``
  on a tracer raises ConcretizationTypeError only for the config that
  first reaches the branch.
- SL103 i32 arithmetic/casts on simulated-time expressions — i32
  nanoseconds wrap after ~2.1 s of simulated time; the PR 4 ``drops``
  widening was exactly this bug one field over.
- SL104 PRNG key reuse without ``split`` — two draws from one key are
  perfectly correlated; invisible in smoke tests, fatal to statistics.
- SL105 mutable default (function defaults and class-body defaults) —
  shared-instance aliasing, and a stale-pytree hazard for dataclass
  state.
- SL106 iteration over a ``set`` when building pytrees/collections —
  set order is hash order; pytree leaf order must be deterministic
  across processes (checkpoint layout, multi-host bit-identity).
- SL107 window-loop entry point jitted without buffer donation — a
  ``jax.jit`` over a state-threading callable (``run``/``step_window``,
  or any function whose first parameters include a ``state``/``st``
  carrier) with no ``donate_argnums``: every window then COPIES the
  [H, C] queue arrays and rings instead of aliasing them through. The
  drain hot path's donation (Simulation._wrap) exists precisely to
  kill those copies; new entry points must donate or declare why they
  can't with ``# shadowlint: no-donate=<reason>`` (the bare
  ``disable=SL107`` works too, but the reasoned marker is the
  documented mechanism — it forces the "why" into the source).
- SL109 bare blocking device sync outside watchdog-scoped sites —
  ``jax.device_get``/``.block_until_ready()`` OUTSIDE jit scope (SL101
  owns the inside-jit case) blocks the driver until the device answers,
  with no deadline: a lost mesh peer turns the call into an infinite
  hang the stall watchdog can only attribute to "no progress". The
  sanctioned blocking sites are ``runtime.harvest.HeartbeatHarvest``
  (petted by the CLI's collective watchdog) and ``runtime/supervisor.py``
  (the watchdog layer itself); every other site must carry
  ``# shadowlint: no-deadline=<reason>`` — the reason is mandatory, so
  each undeadlined sync documents why a hang there is acceptable
  (docs/13-Elastic-Recovery.md).
- SL110 wall-clock read inside jit scope — ``time.time()``/
  ``time.perf_counter()``/``time.monotonic()`` (and their ``_ns``
  variants) return Python floats/ints, so inside a traced function the
  "timestamp" freezes into a compile-time constant: every later call
  of the compiled program sees the clock of its first trace. Wall
  timing belongs on host around the jit (``obs.WindowProfiler``); a
  timestamp a kernel needs must be threaded in as an argument.
- SL111 donation misuse at the call site — the two ways
  ``donate_argnums`` silently goes wrong in *caller* code: passing the
  same array object to two donated parameters of one jit call (XLA
  aliases two outputs onto one buffer — results corrupt), and reading
  a Python reference again after it was passed to a donated position
  (the donated buffer is deleted by the call; jax either errors or
  silently re-copies, losing the donation). The fix is the engine's
  own convention: immediately rebind the carry
  (``state = step(state, stop)``) — rebinding clears the tracking.
- SL108 collective call inside a ``while_loop``/``cond`` predicate —
  jax 0.4.x's experimental shard_map under ``check_rep=False``
  miscompiles collectives lowered into loop/branch predicates: device
  0's carried state leaks to every shard (the PR-1 pmap-fallback bug;
  docs/12-Sharding.md post-mortem). The engine computes every such
  flag in the loop BODY and threads it through the carry
  (``core.engine._drain_flag``); this rule pins that structurally.
- SL112 computed-index gather of a global ``[NC]``-sized table inside
  vmapped handler scope — model handlers receive the global config
  dict ``g`` and by convention index its per-host tables with their
  own gid (``g["count"][me]``): under vmap that lowers to a cheap
  aligned row select. Indexing with any *other* traced value
  (``g["recvsize"][pkt.src_host]``) lowers to a full gather across the
  whole table per host per sweep — O(H·NC) traffic that scales
  quadratically with host count and silently dominates city-scale
  builds. Cross-host lookups are sometimes the point; sanctioned sites
  carry ``# shadowlint: disable=SL112`` with a reason.
- SL113 blocking socket/HTTP call on the jit or window-dispatch path —
  ``sock.recv()``/``sock.accept()``/``httpd.serve_forever()``/
  ``conn.getresponse()`` park the calling thread in the kernel with no
  deadline. Inside jit scope, or inside a window-loop drive scope
  (``run``/``step_window``/``dispatch``), that stalls the entire
  device loop behind one slow peer. The serving plane's discipline
  (obs/server.py, serve/http.py): blocking socket work lives ONLY on
  ThreadingHTTPServer handler threads; the drive path never touches a
  socket.
- SL114 shared-attribute mutation in a thread-entry scope without the
  instance lock — `do_<VERB>` HTTP handler methods run one per request
  thread, and any method passed as ``threading.Thread(target=...)``
  runs concurrently with the submitting thread. Writing state other
  threads read (`self.attr` in a lock-owning worker class; anything
  reached through ``self.<obj>.<attr>`` from a per-request handler)
  outside a ``with self._lock:`` block is a data race the serving
  plane's discipline (serve/service.py, obs/servetrace.py,
  obs/server.py) already forbids. Code lexically under a ``with`` on a
  lock-ish attribute (``*lock*``/``*cond*``/``*mutex*``), methods
  named ``*_locked`` (caller holds it), and the lock attributes
  themselves are exempt.

Findings carry a stable key (rule | relpath | enclosing function |
stripped source line) so the baseline survives unrelated line drift.
Inline suppression: ``# shadowlint: disable=SL101,SL104`` (or a bare
``# shadowlint: disable``) on the flagged line.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
from typing import Iterable

RULES = {
    "SL101": "host materialization inside jit scope",
    "SL102": "Python branch on a traced value inside jit scope",
    "SL103": "i32 cast/construction of a simulated-time expression",
    "SL104": "PRNG key reuse without split",
    "SL105": "mutable default argument or class-body default",
    "SL106": "iteration over a set (nondeterministic order)",
    "SL107": "window-loop entry point jitted without donate_argnums",
    "SL108": "collective call inside a while_loop/cond predicate",
    "SL109": "blocking device sync outside watchdog-scoped sites",
    "SL110": "wall-clock read inside jit scope",
    "SL111": "donated buffer double-donated or reused after donation",
    "SL112": "computed-index gather of a global host table in handler scope",
    "SL113": "blocking socket/HTTP call on the jit or window-dispatch path",
    "SL114": "shared-attribute mutation in thread-entry scope without "
             "the instance lock",
}

# SL112: names under which model handlers receive the global config
# dict (models/*.py convention: `def build(...)` packs per-host tables
# into `g`, handlers close over it or take it as a parameter).
_GLOBAL_TABLE_NAMES = {"g", "_g", "gtab", "gtables"}
# Index heads that select the handler's OWN row (aligned under vmap):
# the gid convention plus static full-range constructions.
_OWN_GID_NAMES = {"me", "gid", "gids"}
_STATIC_INDEX_CALLS = {"arange", "iota", "broadcasted_iota"}

# SL110: time-module entry points that read the wall clock. Bare-name
# calls (``from time import perf_counter``) match everything except
# plain ``time`` — a bare ``time()`` is far more often a shadowed
# variable than the stdlib call, and the module-qualified form covers
# the real uses.
_WALLCLOCK_ATTRS = {
    "time", "perf_counter", "monotonic",
    "time_ns", "perf_counter_ns", "monotonic_ns",
}

# SL113: blocking socket / http.server entry points — each parks the
# calling thread in the kernel with NO deadline. Reachable from jit
# scope or from a window-loop drive scope (`run`/`step_window`/
# `dispatch`) they stall the whole device loop behind one slow client.
# The serving discipline (obs/server.py, serve/http.py) keeps them on
# ThreadingHTTPServer handler threads, never on the drive path.
_BLOCKING_SOCKET_ATTRS = {
    "recv", "recvfrom", "recv_into", "recvmsg", "accept",
    "serve_forever", "handle_request", "getresponse",
}
# window-loop drive scopes: the engine/fleet state-threading entry
# points plus the segment-dispatch site of the run loop
_DISPATCH_SCOPES = {"run", "step_window", "dispatch"}

# SL114: thread-entry scopes and the lock discipline they must follow.
# `do_<VERB>` methods run one per ThreadingHTTPServer request thread;
# methods named as a `threading.Thread(target=...)` (pass 1) run
# concurrently with the thread that spawned them.
_HTTP_VERB_RE = re.compile(r"^do_[A-Z]+$")
# attributes that ARE the synchronization (with self._lock: /
# self._cond: / self._scrape_lock:) — both the exemption context and
# excluded as mutation targets
_LOCKISH_RE = re.compile(r"lock|cond|mutex", re.IGNORECASE)
# constructors whose result makes a class "lock-owning" when assigned
# to a self attribute anywhere in the class body
_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore",
               "BoundedSemaphore"}
# container mutators that write through an attribute chain. "set" is
# deliberately absent — `self.metrics.set(...)`-style gauge APIs are
# value setters on objects that do their own locking, and the single
# word collides with far too many benign APIs.
_SL114_MUTATORS = {
    "append", "extend", "insert", "remove", "clear", "update",
    "setdefault", "add", "discard", "popleft", "appendleft",
}

# SL107: callables by these names are window-loop entry points (the
# engine's state-threading convention), and parameters by these names
# carry the donated EngineState.
_ENTRY_NAMES = {"run", "step_window"}
_STATE_PARAMS = {"state", "st"}

# Functions whose callee-arguments are traced (their bodies are jit
# scope): jax.jit itself plus the structured control-flow / mapping
# combinators the engine uses.
_JIT_WRAPPERS = {
    "jit",
    "while_loop",
    "fori_loop",
    "cond",
    "scan",
    "switch",
    "vmap",
    "pmap",
    "shard_map",
    "checkpoint",
    "remat",
    "custom_jvp",
    "custom_vjp",
}

# np.<attr> uses that are dtype/constant plumbing, not host compute.
_NP_ALLOWED = {
    "int8", "int16", "int32", "int64",
    "uint8", "uint16", "uint32", "uint64",
    "float16", "float32", "float64",
    "bool_", "dtype", "ndarray", "generic",
    "pi", "inf", "nan", "newaxis",
    "iinfo", "finfo", "issubdtype", "integer", "floating",
}

# Time-like identifier fragments (core/timebase.py semantics: these
# carry simulated nanoseconds and must stay TIME_DTYPE = i64)...
_TIMEY = re.compile(
    r"(?:^|_|\b)(time|now|deadline|delay|due|latency|clock|window_end|"
    r"stoptime|cpu_free|t0|t1|ns|when|expiry|timeout)(?:_|\b|$)",
    re.IGNORECASE,
)
# ...unless the name is really a count/index that happens to mention
# time (event counts, sequence numbers, shard ranks, ...).
_NOT_TIMEY = re.compile(
    r"(count|idx|index|seq|rank|slot|drops|num_|n_|_id\b|mask|kind|bins)",
    re.IGNORECASE,
)

_PRNG_CONSUMERS_SKIP = {
    "split", "fold_in", "PRNGKey", "key", "key_data", "wrap_key_data",
    "clone",
}
_PRNG_NAMESPACES = {"srng", "random", "jr", "rng"}

# SL108: collective primitives whose lowering into a while_loop cond or
# a lax.cond predicate triggers the 0.4.x experimental-shard_map
# check_rep=False miscompile (predicate re-evaluated per shard off
# device 0's carry), plus the engine's in-package reduction wrappers
# built directly on them — a `self._gany(...)` in a predicate is the
# same bug one call away.
_COLLECTIVES = {
    "psum", "pmin", "pmax", "pmean", "psum_scatter",
    "all_to_all", "ppermute", "all_gather", "pshuffle", "pbroadcast",
}
_COLLECTIVE_WRAPPERS = {"_gany", "_gmin", "_gsum"}

_SUPPRESS_RE = re.compile(r"#\s*shadowlint:\s*disable(?:=([A-Z0-9,\s]+))?")
# SL107's reasoned exemption: the reason is mandatory (an empty one
# does not suppress), so every undonated entry point documents itself.
_NO_DONATE_RE = re.compile(r"#\s*shadowlint:\s*no-donate=(\S.*)")
# SL109's reasoned exemption, same contract: a bare `no-deadline=` does
# not suppress — the reason documents why an unbounded block is safe.
_NO_DEADLINE_RE = re.compile(r"#\s*shadowlint:\s*no-deadline=(\S.*)")

# SL109 sanctioned blocking scopes: the harvest class whose fetch the
# CLI pets its collective watchdog around, and the watchdog layer
# itself (its whole job is bounding everyone else's blocking).
_SL109_CLASS_ALLOWED = {"HeartbeatHarvest"}
_SL109_FILE_ALLOWED = ("runtime/supervisor.py",)


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str  # repo-relative where possible
    line: int
    col: int
    func: str  # dotted enclosing-scope name ("<module>" at top level)
    message: str
    snippet: str  # stripped source line (stable-key component)

    @property
    def key(self) -> str:
        return f"{self.rule}|{self.path}|{self.func}|{self.snippet}"

    def to_json(self) -> dict:
        d = dataclasses.asdict(self)
        d["key"] = self.key
        return d

    def __str__(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: {self.rule} "
                f"[{self.func}] {self.message}")


def _unparse(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:
        return ast.dump(node)


def _call_basename(func: ast.AST) -> str:
    """Rightmost name of a call target: jax.lax.while_loop -> while_loop."""
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""


def _attr_root(node: ast.AST) -> str:
    """Leftmost name of an attribute chain: self.cfg.trace -> self."""
    while isinstance(node, ast.Attribute):
        node = node.value
    return node.id if isinstance(node, ast.Name) else ""


def _is_timey(text: str) -> bool:
    return bool(_TIMEY.search(text)) and not _NOT_TIMEY.search(text)


def _is_int32_expr(node: ast.AST) -> bool:
    """jnp.int32 / np.int32 / 'int32' / "i4"-style dtype expressions."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value in ("int32", "i32", "<i4", "i4")
    if isinstance(node, ast.Attribute) and node.attr == "int32":
        return _attr_root(node) in ("jnp", "np", "numpy", "jax")
    return False


class _Scope:
    """Per-function lint context threaded through the visitor."""

    def __init__(self, name: str, jitted: bool, params: set[str],
                 predicate: bool = False):
        self.name = name
        self.jitted = jitted
        self.params = params  # traced-candidate parameter names
        self.predicate = predicate  # body lowers as a while_loop cond


class _Linter(ast.NodeVisitor):
    def __init__(self, path: str, src: str):
        self.path = path
        self.lines = src.splitlines()
        self.findings: list[Finding] = []
        self.scopes: list[_Scope] = [_Scope("<module>", False, set())]
        # names referenced as callee arguments of jit wrappers anywhere
        # in the file (pass 1) — their defs are jit scope
        self.jit_marked: set[str] = set()
        # names passed as while_loop's cond_fun (pass 1) — their defs
        # lower as loop predicates (SL108 scope)
        self.pred_marked: set[str] = set()
        # SL108 nodes already reported (a lax.cond inside a predicate
        # function would otherwise double-fire)
        self._sl108_seen: set[int] = set()
        # def name -> parameter names, for SL107's in-file resolution
        self.func_params: dict[str, tuple[str, ...]] = {}
        # per-function PRNG use tracking: {keyname: [linenos]}
        self._prng_uses: list[dict[str, list[ast.Call]]] = [{}]
        # SL111 per-function tracking: names bound to a donating
        # jax.jit (name -> donated positions), and names whose buffer
        # was consumed by a donated call (name -> consuming call)
        self._donating: list[dict[str, set[int]]] = [{}]
        self._donate_consumed: list[dict[str, ast.Call]] = [{}]
        # SL114: method names passed as Thread(target=...) (pass 1),
        # the lock-attr sets of enclosing classes, and the lexical
        # `with <lock>:` nesting depth
        self.thread_marked: set[str] = set()
        self._class_locks: list[set[str]] = []
        self._lock_depth = 0

    # ------------------------------------------------------------ utils

    def _suppressed(self, line: int, rule: str) -> bool:
        if 1 <= line <= len(self.lines):
            m = _SUPPRESS_RE.search(self.lines[line - 1])
            if m:
                if not m.group(1):
                    return True
                rules = {r.strip() for r in m.group(1).split(",")}
                return rule in rules
        return False

    def _emit(self, rule: str, node: ast.AST, message: str) -> None:
        line = getattr(node, "lineno", 1)
        if self._suppressed(line, rule):
            return
        snippet = ""
        if 1 <= line <= len(self.lines):
            snippet = self.lines[line - 1].strip()
        func = ".".join(s.name for s in self.scopes[1:]) or "<module>"
        self.findings.append(
            Finding(rule, self.path, line, getattr(node, "col_offset", 0),
                    func, message, snippet))

    @property
    def _scope(self) -> _Scope:
        return self.scopes[-1]

    def _in_jit(self) -> bool:
        return any(s.jitted for s in self.scopes)

    def _traced_names(self) -> set[str]:
        names: set[str] = set()
        for s in self.scopes:
            if s.jitted:
                names |= s.params
        return names

    # --------------------------------------------------------- functions

    def _func_is_jitted(self, node: ast.FunctionDef | ast.AsyncFunctionDef) -> bool:
        for dec in node.decorator_list:
            base = dec
            if isinstance(base, ast.Call):  # @partial(jax.jit, ...)
                if any(_call_basename(a) in _JIT_WRAPPERS
                       for a in base.args
                       if isinstance(a, (ast.Name, ast.Attribute))):
                    return True
                base = base.func
            if _call_basename(base) in _JIT_WRAPPERS:
                return True
        if node.name in self.jit_marked:
            return True
        return self._in_jit()  # nested defs inherit jit scope

    def _visit_funcdef(self, node) -> None:
        jitted = self._func_is_jitted(node)
        params = set()
        if jitted:
            a = node.args
            names = [p.arg for p in
                     (a.posonlyargs + a.args + a.kwonlyargs)]
            # drop self/cls and obviously-static plumbing names; params
            # with defaults are usually static feature flags
            n_def = len(a.defaults)
            defaulted = {p.arg for p in a.args[len(a.args) - n_def:]} if n_def else set()
            defaulted |= {p.arg for p, d in zip(a.kwonlyargs, a.kw_defaults) if d}
            for n in names:
                if n in ("self", "cls", "cfg", "config", "axis_name",
                         "dtype", "shape", "name"):
                    continue
                if n in defaulted:
                    continue
                params.add(n)
        # SL105: mutable defaults
        for d in list(node.args.defaults) + [d for d in node.args.kw_defaults if d]:
            if self._mutable_literal(d):
                self._emit("SL105", d,
                           f"mutable default `{_unparse(d)}` in "
                           f"{node.name}() is shared across calls; use "
                           f"None + in-body construction (or a tuple)")
        scope = _Scope(node.name, jitted, params,
                       predicate=node.name in self.pred_marked)
        # SL114: a do_<VERB> method or a Thread-target method is a
        # thread-entry scope; nested defs inherit it (closures run on
        # the same thread). `*_locked` methods document that the
        # caller already holds the lock.
        scope.sl114 = next(
            (getattr(s, "sl114", None) for s in reversed(self.scopes)
             if getattr(s, "sl114", None)), None)
        if scope.sl114 is None \
                and getattr(self._scope, "is_class", False):
            locks = self._class_locks[-1] if self._class_locks else set()
            if _HTTP_VERB_RE.match(node.name):
                scope.sl114 = ("handler", locks)
            elif node.name in self.thread_marked:
                scope.sl114 = ("worker", locks)
        if node.name.endswith("_locked"):
            scope.sl114 = None
        self.scopes.append(scope)
        self._prng_uses.append({})
        self._donating.append({})
        self._donate_consumed.append({})
        self.generic_visit(node)
        self._flush_prng()
        self._prng_uses.pop()
        self._donating.pop()
        self._donate_consumed.pop()
        self.scopes.pop()

    visit_FunctionDef = _visit_funcdef
    visit_AsyncFunctionDef = _visit_funcdef

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        # SL105 for class-body defaults (dataclass fields included):
        # a mutable class attribute is shared by every instance/pytree.
        for stmt in node.body:
            val = None
            if isinstance(stmt, ast.AnnAssign):
                val = stmt.value
                tgts = [stmt.target]
            elif isinstance(stmt, ast.Assign):
                val = stmt.value
                tgts = stmt.targets
            if val is not None and any(
                    isinstance(t, ast.Name) and t.id in
                    ("_fields_", "_anonymous_", "__slots__",
                     "__match_args__")
                    for t in tgts):
                # ctypes/structure protocol attributes: consumed by the
                # metaclass at class creation, never mutated
                val = None
            if val is not None and self._mutable_literal(val):
                self._emit("SL105", val,
                           f"mutable class-body default `{_unparse(val)}` "
                           f"in {node.name} is shared by every instance; "
                           f"use dataclasses.field(default_factory=...)")
        scope = _Scope(node.name, False, set())
        scope.is_class = True
        # SL114: lock attributes the class owns (self.X = Lock() /
        # Condition() / ... anywhere in its body)
        locks: set[str] = set()
        for sub in ast.walk(node):
            if isinstance(sub, ast.Assign) \
                    and isinstance(sub.value, ast.Call) \
                    and _call_basename(sub.value.func) in _LOCK_CTORS:
                for t in sub.targets:
                    if isinstance(t, ast.Attribute) \
                            and _attr_root(t) == "self":
                        locks.add(t.attr)
        self.scopes.append(scope)
        self._class_locks.append(locks)
        self._prng_uses.append({})
        self._donating.append({})
        self._donate_consumed.append({})
        self.generic_visit(node)
        self._prng_uses.pop()
        self._donating.pop()
        self._donate_consumed.pop()
        self._class_locks.pop()
        self.scopes.pop()

    @staticmethod
    def _mutable_literal(node: ast.AST) -> bool:
        if isinstance(node, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                             ast.DictComp, ast.SetComp)):
            return True
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
            return node.func.id in ("list", "dict", "set") and not node.args \
                and not node.keywords
        return False

    # ------------------------------------------------------------- calls

    def visit_Call(self, node: ast.Call) -> None:
        base = _call_basename(node.func)

        # pass-1 marking is done before visiting; nothing to do here for
        # wrapper detection.

        in_jit = self._in_jit()
        traced = self._traced_names() if in_jit else set()

        # SL101: float()/int()/bool() on traced-looking args in jit scope
        if in_jit and isinstance(node.func, ast.Name) \
                and node.func.id in ("float", "int", "bool") and node.args:
            if self._mentions(node.args[0], traced):
                self._emit("SL101", node,
                           f"`{node.func.id}()` on a traced value forces "
                           f"host materialization inside jit scope")

        # SL101: .item() / jax.device_get / np.* compute in jit scope
        if in_jit and isinstance(node.func, ast.Attribute):
            if node.func.attr in ("item", "tolist", "block_until_ready"):
                self._emit("SL101", node,
                           f"`.{node.func.attr}()` materializes on host "
                           f"inside jit scope")
            elif node.func.attr == "device_get" \
                    and _attr_root(node.func) == "jax":
                self._emit("SL101", node,
                           "`jax.device_get` inside jit scope")
            elif _attr_root(node.func) in ("np", "numpy") \
                    and node.func.attr not in _NP_ALLOWED:
                self._emit("SL101", node,
                           f"`np.{node.func.attr}(...)` runs on host "
                           f"inside jit scope; use jnp")

        # SL110: wall-clock reads in jit scope — the call traces to a
        # host float, so the "timestamp" is a compile-time constant
        if in_jit and self._is_wallclock_call(node):
            self._emit(
                "SL110", node,
                f"`{_unparse(node.func)}()` inside jit scope freezes "
                f"the wall clock into a compile-time constant; time on "
                f"host around the jit (obs.WindowProfiler) or thread "
                f"the timestamp in as an argument")

        # SL109: bare blocking sync OUTSIDE jit scope (SL101 owns the
        # inside — the two are mutually exclusive by construction)
        if not in_jit and isinstance(node.func, ast.Attribute):
            blocking = (
                node.func.attr == "block_until_ready"
                or (node.func.attr == "device_get"
                    and _attr_root(node.func) == "jax"))
            if blocking and not self._sl109_allowed(node):
                self._emit(
                    "SL109", node,
                    f"`{_unparse(node.func)}` blocks with no deadline — "
                    f"a lost peer hangs here forever; fetch through "
                    f"HeartbeatHarvest / a watchdog-petted site, or mark "
                    f"the line `# shadowlint: no-deadline=<reason>`")

        # SL113: blocking socket/HTTP-server call reachable from jit
        # scope or a window-loop drive scope — the thread parks in the
        # kernel with no deadline while the device loop waits behind it
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr in _BLOCKING_SOCKET_ATTRS:
            drive = [s.name for s in self.scopes
                     if s.name in _DISPATCH_SCOPES]
            if in_jit or drive:
                where = ("jit scope" if in_jit
                         else f"window-dispatch scope `{drive[-1]}`")
                self._emit(
                    "SL113", node,
                    f"`{_unparse(node.func)}()` blocks in the kernel "
                    f"with no deadline inside {where}; socket/HTTP work "
                    f"belongs on a handler thread "
                    f"(obs.server/serve.http discipline)")

        # SL108: collectives lowered into a loop/branch predicate
        self._check_pred_collective(node, base)

        # SL107: jit over a window-loop entry point without donation
        self._check_jit_donation(node)

        # SL103: i32 construction of a time-like expression
        self._check_i32_time(node)

        # SL104: collect PRNG consumer uses
        self._track_prng(node)

        # SL114: container mutation through a shared chain in a
        # thread-entry scope
        self._check_sl114_call(node)

        # SL111: donation hazards at the call site. Consumption is
        # registered only AFTER the call's own arguments are visited,
        # so the consuming call never flags itself.
        consumed = self._check_donate_call(node)

        self.generic_visit(node)
        for name in consumed:
            self._donate_consumed[-1].setdefault(name, node)

    @staticmethod
    def _is_wallclock_call(node: ast.Call) -> bool:
        if isinstance(node.func, ast.Attribute):
            return (node.func.attr in _WALLCLOCK_ATTRS
                    and _attr_root(node.func) in ("time", "_time"))
        if isinstance(node.func, ast.Name):
            return node.func.id in _WALLCLOCK_ATTRS - {"time"}
        return False

    def _sl109_allowed(self, node: ast.Call) -> bool:
        if self.path.replace(os.sep, "/").endswith(_SL109_FILE_ALLOWED):
            return True
        if any(s.name in _SL109_CLASS_ALLOWED for s in self.scopes):
            return True
        line = getattr(node, "lineno", 1)
        return bool(1 <= line <= len(self.lines)
                    and _NO_DEADLINE_RE.search(self.lines[line - 1]))

    def _mentions(self, node: ast.AST, names: set[str]) -> bool:
        if not names:
            return False
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name) and sub.id in names:
                return True
        return False

    # ---------------------------------------------------- SL107 donation

    def _check_jit_donation(self, node: ast.Call) -> None:
        """jax.jit over a state-threading entry point must donate its
        carry (or carry a reasoned `# shadowlint: no-donate=` marker)."""
        if _call_basename(node.func) != "jit" or not node.args:
            return
        if isinstance(node.func, ast.Attribute) \
                and _attr_root(node.func) != "jax":
            return
        if any(kw.arg in ("donate_argnums", "donate_argnames")
               for kw in node.keywords):
            return
        target = node.args[0]
        why = None
        if isinstance(target, ast.Lambda):
            params = tuple(p.arg for p in target.args.args)
            if params and any(p in _STATE_PARAMS for p in params):
                why = (f"lambda with state carry "
                       f"`{', '.join(params)}`")
        elif isinstance(target, (ast.Name, ast.Attribute)):
            name = _call_basename(target)
            if name in _ENTRY_NAMES:
                why = f"window-loop entry point `{_unparse(target)}`"
            elif isinstance(target, ast.Name):
                params = self.func_params.get(name, ())
                if any(p in _STATE_PARAMS for p in params):
                    why = (f"`{name}({', '.join(params)})` threads a "
                           f"state carry")
        if why is None:
            return
        line = getattr(node, "lineno", 1)
        if 1 <= line <= len(self.lines) \
                and _NO_DONATE_RE.search(self.lines[line - 1]):
            return  # reasoned exemption
        self._emit(
            "SL107", node,
            f"jax.jit over {why} without donate_argnums — the window "
            f"carry is copied every call; donate it (see "
            f"Simulation._wrap) or mark the line "
            f"`# shadowlint: no-donate=<reason>`")

    # ------------------------------------------------ SL111 donation use

    @staticmethod
    def _jit_donate_positions(call: ast.Call) -> set[int] | None:
        """Donated positions of a `jax.jit(...)` call expression, or
        None when it isn't one (or they aren't literal ints)."""
        if _call_basename(call.func) != "jit":
            return None
        if isinstance(call.func, ast.Attribute) \
                and _attr_root(call.func) != "jax":
            return None
        for kw in call.keywords:
            if kw.arg != "donate_argnums":
                continue
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return {v.value}
            if isinstance(v, (ast.Tuple, ast.List)):
                out: set[int] = set()
                for el in v.elts:
                    if not (isinstance(el, ast.Constant)
                            and isinstance(el.value, int)):
                        return None
                    out.add(el.value)
                return out or None
            return None
        return None

    def _check_donate_call(self, node: ast.Call) -> list[str]:
        """SL111 at a call site. Returns Name args consumed by
        donation (the caller registers them after generic_visit)."""
        pos: set[int] | None = None
        if isinstance(node.func, ast.Name):
            for frame in reversed(self._donating):
                if node.func.id in frame:
                    pos = frame[node.func.id]
                    break
        elif isinstance(node.func, ast.Call):
            # direct form: jax.jit(f, donate_argnums=0)(state, ...)
            pos = self._jit_donate_positions(node.func)
        if not pos:
            return []
        callee = _unparse(node.func)
        by_name: dict[str, list[int]] = {}
        for p in sorted(pos):
            if p < len(node.args) and isinstance(node.args[p], ast.Name):
                by_name.setdefault(node.args[p].id, []).append(p)
        for name, ps in by_name.items():
            if len(ps) >= 2:
                self._emit(
                    "SL111", node,
                    f"`{name}` fills donated parameters "
                    f"{' and '.join(map(str, ps))} of `{callee}` in one "
                    f"call — XLA aliases two outputs onto one buffer "
                    f"and the results silently corrupt; pass distinct "
                    f"arrays")
        return list(by_name)

    def visit_Name(self, node: ast.Name) -> None:
        if isinstance(node.ctx, ast.Load):
            first = self._donate_consumed[-1].get(node.id)
            if first is not None:
                self._emit(
                    "SL111", node,
                    f"`{node.id}` was donated to `{_unparse(first.func)}` "
                    f"at line {first.lineno} and is read again — the "
                    f"donated buffer is deleted by that call (jax errors "
                    f"or silently re-copies); rebind the result "
                    f"(`{node.id} = ...`) or pass a copy")
        else:
            # Store/Del rebinds the reference to a fresh buffer (for
            # targets, with-as, del) — clear the tracking
            self._donate_consumed[-1].pop(node.id, None)
            self._donating[-1].pop(node.id, None)
        self.generic_visit(node)

    # --------------------------------------------- SL108 pred collective

    @staticmethod
    def _is_collective_call(node: ast.Call) -> bool:
        base = _call_basename(node.func)
        if base in _COLLECTIVE_WRAPPERS:
            return True  # self._gany / eng._gmin — psum/pmin one call away
        if base not in _COLLECTIVES:
            return False
        if isinstance(node.func, ast.Attribute):
            return _attr_root(node.func) in ("lax", "jax")
        return True  # `from jax.lax import psum` style

    def _sl108_emit(self, node: ast.Call) -> None:
        if id(node) in self._sl108_seen:
            return
        self._sl108_seen.add(id(node))
        self._emit(
            "SL108", node,
            f"collective `{_unparse(node.func)}` lowers into a "
            f"while/cond predicate — 0.4.x experimental shard_map "
            f"(check_rep=False) leaks device 0's carry to every shard "
            f"there; compute the flag in the loop body and carry it "
            f"(core.engine._drain_flag)")

    def _check_pred_collective(self, node: ast.Call, base: str) -> None:
        # (a) any collective lexically inside a cond-function body
        if self._is_collective_call(node) \
                and any(s.predicate for s in self.scopes):
            self._sl108_emit(node)
        # (b) inline-lambda cond: while_loop(lambda c: ..., body, init)
        # (named/attribute conds are resolved by pass-1 pred_marked)
        pred = None
        if base == "while_loop":
            tgt = node.args[0] if node.args else None
            for kw in node.keywords:
                if kw.arg == "cond_fun":
                    tgt = kw.value
            if isinstance(tgt, ast.Lambda):
                pred = tgt.body
        # (c) lax.cond's predicate EXPRESSION (first positional arg)
        elif base == "cond" and isinstance(node.func, ast.Attribute) \
                and _attr_root(node.func) in ("lax", "jax"):
            pred = node.args[0] if node.args else None
        if pred is not None:
            for sub in ast.walk(pred):
                if isinstance(sub, ast.Call) \
                        and self._is_collective_call(sub):
                    self._sl108_emit(sub)

    # ------------------------------------------------------ SL102 branch

    def _check_branch(self, node, kind: str) -> None:
        if not self._in_jit():
            self.generic_visit(node)
            return
        test = node.test
        if self._test_whitelisted(test):
            self.generic_visit(node)
            return
        traced = self._traced_names()
        if self._mentions(test, traced):
            self._emit("SL102", node,
                       f"Python `{kind}` on `{_unparse(test)}` — traced "
                       f"values cannot drive Python control flow; use "
                       f"lax.cond/jnp.where")
        self.generic_visit(node)

    def visit_If(self, node: ast.If) -> None:
        self._check_branch(node, "if")

    def visit_While(self, node: ast.While) -> None:
        self._check_branch(node, "while")

    def visit_IfExp(self, node: ast.IfExp) -> None:
        self._check_branch(node, "ternary")

    @staticmethod
    def _test_whitelisted(test: ast.AST) -> bool:
        """Static-dispatch shapes: isinstance/hasattr/len checks, `is
        (not) None`, and attribute chains rooted at self/cfg (static
        engine configuration, not traced state)."""
        def ok(node: ast.AST) -> bool:
            if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.Not):
                return ok(node.operand)
            if isinstance(node, ast.BoolOp):
                return all(ok(v) for v in node.values)
            if isinstance(node, ast.Call):
                return _call_basename(node.func) in (
                    "isinstance", "hasattr", "len", "callable", "getattr")
            if isinstance(node, ast.Compare):
                if all(isinstance(op, (ast.Is, ast.IsNot))
                       for op in node.ops):
                    return True
                return ok(node.left) and all(ok(c) for c in node.comparators)
            if isinstance(node, ast.Attribute):
                return _attr_root(node) in ("self", "cfg", "config")
            if isinstance(node, ast.Constant):
                return True
            return False
        return ok(test)

    # -------------------------------------------------------- SL103 time

    def _check_i32_time(self, node: ast.Call) -> None:
        base = _call_basename(node.func)
        # <timey>.astype(int32-ish)
        if base == "astype" and node.args and _is_int32_expr(node.args[0]) \
                and isinstance(node.func, ast.Attribute):
            target = _unparse(node.func.value)
            if _is_timey(target):
                self._emit("SL103", node,
                           f"`{target}.astype(int32)` truncates simulated "
                           f"time (wraps after ~2.1 s); keep "
                           f"timebase.TIME_DTYPE")
            return
        # jnp.int32(<timey>) / np.int32(<timey>)
        if base == "int32" and node.args \
                and _attr_root(node.func) in ("jnp", "np", "numpy"):
            arg = _unparse(node.args[0])
            if _is_timey(arg):
                self._emit("SL103", node,
                           f"`int32({arg})` truncates simulated time; "
                           f"keep timebase.TIME_DTYPE")
            return
        # dtype=int32 kwarg where a positional arg is time-like.
        # Comparisons are exempt: `sum(t != TIME_INVALID, dtype=int32)`
        # counts booleans derived FROM time — count arithmetic, not
        # time arithmetic.
        for kw in node.keywords:
            if kw.arg == "dtype" and _is_int32_expr(kw.value):
                args = [a for a in node.args
                        if not isinstance(a, ast.Compare)]
                texts = [_unparse(a) for a in args]
                if any(_is_timey(t) for t in texts):
                    self._emit("SL103", node,
                               f"`dtype=int32` on time-like value "
                               f"`{', '.join(texts)}`; keep "
                               f"timebase.TIME_DTYPE")

    def visit_Assign(self, node: ast.Assign) -> None:
        # SL114: shared-attribute store in a thread-entry scope
        for tgt in node.targets:
            self._check_sl114_store(tgt, node)
        # SL103: timey_name = jnp.zeros(..., dtype=int32)-style constructions
        if isinstance(node.value, ast.Call):
            for kw in node.value.keywords:
                if kw.arg == "dtype" and _is_int32_expr(kw.value):
                    for tgt in node.targets:
                        t = _unparse(tgt)
                        if _is_timey(t) and not self._suppressed(
                                node.lineno, "SL103"):
                            self._emit("SL103", node,
                                       f"time-like `{t}` constructed with "
                                       f"dtype=int32; keep "
                                       f"timebase.TIME_DTYPE")
                        break
        # SL104: reassignment of a key name resets its use count
        for tgt in node.targets:
            for sub in ast.walk(tgt):
                if isinstance(sub, ast.Name):
                    self._prng_uses[-1].pop(sub.id, None)
        self.generic_visit(node)
        # SL111: a rebound name is a fresh buffer — clear AFTER the
        # value was visited, so `st = step(st, stop)` first registers
        # st as consumed (by the call) and then immediately clears it;
        # a binding to a donating jax.jit becomes a tracked callee
        tgt_names = [sub.id for tgt in node.targets
                     for sub in ast.walk(tgt)
                     if isinstance(sub, ast.Name)]
        for n in tgt_names:
            self._donate_consumed[-1].pop(n, None)
            self._donating[-1].pop(n, None)
        if isinstance(node.value, ast.Call) and len(tgt_names) == 1:
            pos = self._jit_donate_positions(node.value)
            if pos:
                self._donating[-1][tgt_names[0]] = pos

    # -------------------------------------------------------- SL104 PRNG

    def _track_prng(self, node: ast.Call) -> None:
        if not isinstance(node.func, ast.Attribute):
            return
        root = _attr_root(node.func)
        chain_is_jax_random = (
            isinstance(node.func.value, ast.Attribute)
            and node.func.value.attr == "random"
            and _attr_root(node.func.value) == "jax")
        if root not in _PRNG_NAMESPACES and not chain_is_jax_random:
            return
        if "stream" in node.func.attr:
            # counter-based stream APIs (core/rng.py fault_stream_*,
            # uniform_lanes-style) take (seed, stream_id): the first
            # arg is deliberately reused across distinct stream ids
            return
        if node.func.attr in _PRNG_CONSUMERS_SKIP:
            # split/fold_in consume-and-derive; also reset the budget
            # for their source key (splitting IS the fix for reuse)
            if node.args and isinstance(node.args[0], ast.Name):
                self._prng_uses[-1].pop(node.args[0].id, None)
            return
        if node.args and isinstance(node.args[0], ast.Name):
            self._prng_uses[-1].setdefault(node.args[0].id, []).append(node)

    def _flush_prng(self) -> None:
        for name, calls in self._prng_uses[-1].items():
            if len(calls) >= 2:
                for call in calls[1:]:
                    self._emit(
                        "SL104", call,
                        f"PRNG key `{name}` already consumed at line "
                        f"{calls[0].lineno}; reuse correlates draws — "
                        f"split first")

    # --------------------------------------------------------- SL106 set

    def _check_set_iter(self, iter_node: ast.AST, where: ast.AST) -> None:
        is_set = isinstance(iter_node, (ast.Set, ast.SetComp)) or (
            isinstance(iter_node, ast.Call)
            and isinstance(iter_node.func, ast.Name)
            and iter_node.func.id in ("set", "frozenset"))
        if is_set:
            self._emit("SL106", where,
                       f"iterating `{_unparse(iter_node)}` — set order is "
                       f"hash order; sort first (pytree leaf order must "
                       f"be deterministic)")

    def visit_For(self, node: ast.For) -> None:
        self._check_set_iter(node.iter, node)
        self.generic_visit(node)

    def _visit_comp(self, node) -> None:
        for gen in node.generators:
            self._check_set_iter(gen.iter, node)
        self.generic_visit(node)

    visit_ListComp = _visit_comp
    visit_SetComp = _visit_comp
    visit_DictComp = _visit_comp
    visit_GeneratorExp = _visit_comp

    # ----------------------------------------------------- SL112 gather

    def _in_handler_scope(self) -> bool:
        # Model handlers lower under the engine's vmap even though no
        # jit wrapper appears in the model file itself: they are either
        # closures inside a *make_handlers factory or `_on_*` methods
        # registered by one (models/*.py convention). Jit scope proper
        # also counts.
        return self._in_jit() or any(
            "handlers" in s.name or s.name.startswith("_on_")
            for s in self.scopes[1:])

    @staticmethod
    def _is_own_row_index(idx: ast.AST) -> bool:
        # Only the FIRST index element picks the host row; trailing
        # elements (`g["peers"][me, j]`) index within the own row.
        if isinstance(idx, ast.Tuple) and idx.elts:
            idx = idx.elts[0]
        if isinstance(idx, (ast.Constant, ast.Slice)):
            return True
        if isinstance(idx, ast.Name):
            return idx.id in _OWN_GID_NAMES
        if isinstance(idx, ast.Attribute):
            return idx.attr in _OWN_GID_NAMES
        if isinstance(idx, ast.Call):
            return _call_basename(idx.func) in _STATIC_INDEX_CALLS
        return False

    def visit_Subscript(self, node: ast.Subscript) -> None:
        inner = node.value
        if (isinstance(inner, ast.Subscript)
                and isinstance(inner.slice, ast.Constant)
                and isinstance(inner.slice.value, str)
                and _attr_root(inner.value) in _GLOBAL_TABLE_NAMES
                and self._in_handler_scope()
                and not self._is_own_row_index(node.slice)):
            table = _unparse(inner)
            head = node.slice
            if isinstance(head, ast.Tuple) and head.elts:
                head = head.elts[0]
            self._emit(
                "SL112", node,
                f"`{table}[{_unparse(head)}]` gathers a global table by "
                f"a computed index inside vmapped handler scope — under "
                f"vmap this reads the whole [NC] table per host per "
                f"sweep; index by own gid (`me`) or, if the cross-host "
                f"lookup is intended, suppress with a reason")
        self.generic_visit(node)

    # ---------------------------------------------------- SL114 threads

    def _sl114_ctx(self):
        """(kind, class_locks) when the current scope is a thread-entry
        scope and the write is not under a lock; None otherwise."""
        if self._lock_depth:
            return None
        for s in reversed(self.scopes):
            ctx = getattr(s, "sl114", None)
            if ctx:
                return ctx
        return None

    @staticmethod
    def _is_lockish(expr: ast.AST) -> bool:
        """`with self._lock:` / `with self._cond:` / `with lock:` —
        also through chains (`self.service._lock`)."""
        if isinstance(expr, ast.Call):  # acquire_timeout()-style helpers
            expr = expr.func
        if isinstance(expr, ast.Attribute):
            return bool(_LOCKISH_RE.search(expr.attr))
        if isinstance(expr, ast.Name):
            return bool(_LOCKISH_RE.search(expr.id))
        return False

    @staticmethod
    def _self_chain(node: ast.AST) -> list[str] | None:
        """Attribute names of a chain rooted at `self`, outermost last;
        None for non-self targets. Subscripts are transparent: storing
        to `self.a.b[k]` mutates the shared `self.a.b`."""
        attrs: list[str] = []
        while True:
            if isinstance(node, ast.Subscript):
                node = node.value
            elif isinstance(node, ast.Attribute):
                attrs.append(node.attr)
                node = node.value
            else:
                break
        if isinstance(node, ast.Name) and node.id == "self" and attrs:
            return list(reversed(attrs))
        return None

    def _check_sl114_store(self, target: ast.AST, node: ast.AST) -> None:
        ctx = self._sl114_ctx()
        if ctx is None:
            return
        kind, locks = ctx
        chain = self._self_chain(target)
        if not chain or any(_LOCKISH_RE.search(a) for a in chain):
            return
        dotted = "self." + ".".join(chain)
        if len(chain) >= 2:
            # a handler/worker writing through self.<obj>.<attr>
            # mutates an object every other request thread shares
            self._emit(
                "SL114", node,
                f"`{dotted}` written in thread-entry scope "
                f"`{self._scope.name}` mutates a shared object without "
                f"the instance lock; wrap in `with ...lock:` (or move "
                f"the write behind a `*_locked` method)")
        elif kind == "worker" and locks:
            # a Thread-target method of a lock-owning class: every
            # bare self write races the submitting thread
            self._emit(
                "SL114", node,
                f"`{dotted}` written in worker-thread scope "
                f"`{self._scope.name}` outside "
                f"`with self.{sorted(locks)[0]}:` — the class owns a "
                f"lock precisely so worker-visible state is only "
                f"touched under it")

    def _check_sl114_call(self, node: ast.Call) -> None:
        if not isinstance(node.func, ast.Attribute) \
                or node.func.attr not in _SL114_MUTATORS:
            return
        ctx = self._sl114_ctx()
        if ctx is None:
            return
        kind, locks = ctx
        chain = self._self_chain(node.func.value)
        if not chain or any(_LOCKISH_RE.search(a) for a in chain):
            return
        if len(chain) >= 2 or (kind == "worker" and locks):
            dotted = "self." + ".".join(chain)
            self._emit(
                "SL114", node,
                f"`{dotted}.{node.func.attr}(...)` mutates shared "
                f"state in thread-entry scope `{self._scope.name}` "
                f"without the instance lock; wrap in `with ...lock:`")

    def visit_With(self, node: ast.With) -> None:
        lockish = any(self._is_lockish(item.context_expr)
                      for item in node.items)
        if lockish:
            self._lock_depth += 1
        self.generic_visit(node)
        if lockish:
            self._lock_depth -= 1

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._check_sl114_store(node.target, node)
        self.generic_visit(node)


class _JitMarker(ast.NodeVisitor):
    """Pass 1: collect names referenced as callee arguments of jit
    wrappers (lax.while_loop(cond, body, ...) marks cond/body)."""

    def __init__(self) -> None:
        self.marked: set[str] = set()
        # def name -> parameter names (SL107 resolves in-file callables)
        self.func_params: dict[str, tuple[str, ...]] = {}
        # names passed as while_loop's cond_fun — predicate scope (SL108)
        self.pred_marked: set[str] = set()
        # names passed as Thread(target=...) — thread-entry scope (SL114)
        self.thread_targets: set[str] = set()

    def _visit_funcdef(self, node) -> None:
        a = node.args
        self.func_params[node.name] = tuple(
            p.arg for p in (a.posonlyargs + a.args))
        self.generic_visit(node)

    visit_FunctionDef = _visit_funcdef
    visit_AsyncFunctionDef = _visit_funcdef

    def visit_Call(self, node: ast.Call) -> None:
        if _call_basename(node.func) == "Thread":
            for kw in node.keywords:
                if kw.arg == "target":
                    if isinstance(kw.value, ast.Attribute):
                        self.thread_targets.add(kw.value.attr)
                    elif isinstance(kw.value, ast.Name):
                        self.thread_targets.add(kw.value.id)
        if _call_basename(node.func) == "while_loop":
            tgt = node.args[0] if node.args else None
            for kw in node.keywords:
                if kw.arg == "cond_fun":
                    tgt = kw.value
            if isinstance(tgt, ast.Name):
                self.pred_marked.add(tgt.id)
            elif isinstance(tgt, ast.Attribute):
                self.pred_marked.add(tgt.attr)
        if _call_basename(node.func) in _JIT_WRAPPERS:
            for a in list(node.args) + [k.value for k in node.keywords]:
                if isinstance(a, ast.Name):
                    self.marked.add(a.id)
                elif isinstance(a, (ast.List, ast.Tuple)):
                    for el in a.elts:
                        if isinstance(el, ast.Name):
                            self.marked.add(el.id)
                elif isinstance(a, ast.Attribute):
                    # lax.while_loop(cond, self._body, ...) marks _body
                    self.marked.add(a.attr)
        self.generic_visit(node)


# ------------------------------------------------------------- frontend


def _rel(path: str) -> str:
    root = _repo_root()
    try:
        return os.path.relpath(os.path.abspath(path), root)
    except ValueError:
        return path


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def lint_source(src: str, path: str = "<string>") -> list[Finding]:
    """Lint one source text. `path` labels findings (and baseline keys)."""
    tree = ast.parse(src, filename=path)
    marker = _JitMarker()
    marker.visit(tree)
    linter = _Linter(path, src)
    linter.jit_marked = marker.marked
    linter.func_params = marker.func_params
    linter.pred_marked = marker.pred_marked
    linter.thread_marked = marker.thread_targets
    linter.visit(tree)
    return sorted(linter.findings, key=lambda f: (f.path, f.line, f.rule))


def lint_paths(paths: Iterable[str]) -> list[Finding]:
    findings: list[Finding] = []
    for p in paths:
        with open(p, "r", encoding="utf-8") as fh:
            src = fh.read()
        findings.extend(lint_source(src, _rel(p)))
    return sorted(findings, key=lambda f: (f.path, f.line, f.rule))


def package_files(root: str | None = None) -> list[str]:
    """All .py files of the shadow_tpu package (analysis included —
    the linter lints itself)."""
    if root is None:
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                out.append(os.path.join(dirpath, fn))
    return out


def lint_package(root: str | None = None) -> list[Finding]:
    return lint_paths(package_files(root))


# ------------------------------------------------------------- baseline

BASELINE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "lint_baseline.json")


def load_baseline(path: str = BASELINE_PATH) -> dict[str, int]:
    if not os.path.exists(path):
        return {}
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    return {str(k): int(v) for k, v in data.get("entries", {}).items()}


def save_baseline(findings: Iterable[Finding],
                  path: str = BASELINE_PATH) -> dict[str, int]:
    entries: dict[str, int] = {}
    for f in findings:
        entries[f.key] = entries.get(f.key, 0) + 1
    data = {
        "version": 1,
        "comment": "shadowlint accepted findings; regenerate with "
                   "`python -m shadow_tpu.tools.lint --update-baseline`",
        "entries": dict(sorted(entries.items())),
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(data, fh, indent=1, sort_keys=False)
        fh.write("\n")
    return entries


def split_new(findings: Iterable[Finding],
              baseline: dict[str, int]) -> tuple[list[Finding], list[Finding], list[str]]:
    """Partition findings into (new, baselined) and report stale
    baseline keys that matched nothing (candidates for pruning)."""
    budget = dict(baseline)
    new: list[Finding] = []
    old: list[Finding] = []
    for f in findings:
        if budget.get(f.key, 0) > 0:
            budget[f.key] -= 1
            old.append(f)
        else:
            new.append(f)
    stale = sorted(k for k, v in budget.items() if v > 0)
    return new, old, stale

"""Roofline cost model over the lowered window loop (pre-silicon).

ROADMAP item 1 asks "know which drain wins before you burn chip
time". The measured half is `--stats` (run lengths, critical depth)
and BENCH_r07's CPU wall times; this module is the static half: price
ONE round of the window loop's innermost `while` body straight from
the op graph's byte/flop math against a chip row (`analysis.chips`),
then convert rounds/s into events/s with the measured events-per-
inner-step ratio from the bench metadata.

The model (documented with its error bars in docs/10, "TPU
readiness"):

- HBM time: every op's operand + result bytes, tile-padded for the
  chip, once over the bus (`bytes / hbm_gbps`). Fusion makes this an
  upper bound on traffic; treating it as fully overlapped with
  compute (roofline max) pulls the other way.
- VPU time: elementwise/compare/reduce flops at `vpu_gflops`;
  `dot_general` prices on the MXU.
- Sort time: `lax.sort` is priced separately as compare-exchanges
  (`rows * n * ceil(log2 n)` per operand column) against the chip's
  `sort_gcps` — the chained-vs-frontier question IS a sort-throughput
  question (frontier's per-round sort was ~2x slower on one CPU core,
  BENCH_r07; the VPU bet is that a vectorized bitonic network makes
  it cheap).
- round time = overhead + max(HBM, VPU + sort + MXU); counts scale
  linearly from the tiny audit build to the bench topology via the
  host-count ratio.

Predicted events/s = events_per_inner_step / round_time. The winner
per model compares the chained and frontier lowerings each under its
own round time and its own measured events-per-inner-step. Under the
CPU row the prediction is cross-checked for directional agreement
with BENCH_r07's measured wall times (pinned in
tests/test_tpu_readiness.py) — a cost model that cannot postdict the
CPU measurement has no business predicting silicon.
"""

from __future__ import annotations

import json
import math
import os
import re

from shadow_tpu.analysis import hlo_graph
from shadow_tpu.analysis.chips import CHIP_NAMES, Chip, chip as chip_row

# Model -> (chained config, frontier config) drain pairs the economics
# cover; both lower from the identical topology so the host-count
# scale factor cancels in the comparison.
DRAIN_PAIRS = {
    "tor": ("tor", "tor_frontier"),
    "tgen": ("tgen", "tgen_frontier"),
}

# Bench report carrying the measured drain economics (events,
# inner_steps, run_s per drain). Pinned fallbacks keep the model
# usable if the file ever moves; the numbers are BENCH_r07's.
BENCH_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "BENCH_r07.json")

_FALLBACK_BENCH = {
    "tor": {"hosts": 1020,
            "chained": {"events": 14293, "inner_steps": 824,
                        "run_s": 245.11},
            "frontier": {"events": 14293, "inner_steps": 1640,
                         "run_s": 495.68}},
    "tgen": {"hosts": 512,
             "chained": {"events": 25462, "inner_steps": 400,
                         "run_s": 43.28},
             "frontier": {"events": 25462, "inner_steps": 778,
                          "run_s": 86.51}},
}

_TENSOR_RE = re.compile(r"^tensor<")

# Ops that move bytes but burn no arithmetic worth pricing: layout and
# data-movement plumbing (their cost is the HBM term).
_MOVE_OPS = {
    "reshape", "transpose", "bitcast_convert", "broadcast_in_dim",
    "gather", "scatter", "dynamic_slice", "dynamic_update_slice",
    "slice", "concatenate", "iota", "constant", "convert", "reverse",
    "pad", "tuple", "get_tuple_element", "optimization_barrier",
    "copy", "all_to_all", "all_gather", "collective_permute",
}


def parse_tensor(t: str) -> tuple[list, str] | None:
    """(dims, dtype) of one `tensor<...>` type; None for non-tensors
    or dynamic dims. Encoding attrs after the dims are dropped the
    same way `hlo_graph.bytes_of_type` drops them."""
    t = t.strip()
    if not _TENSOR_RE.match(t):
        return None
    end = hlo_graph._balanced(t, len("tensor"), "<", ">")
    payload = hlo_graph._split_commas(t[len("tensor<"):end - 1])[0]
    parts = payload.strip().split("x")
    dims = []
    for d in parts[:-1]:
        if not d.isdigit():
            return None
        dims.append(int(d))
    return dims, parts[-1]


def _elems(dims: list) -> int:
    n = 1
    for d in dims:
        n *= int(d)
    return n


def _padded_bytes(t: str, chip: Chip) -> int:
    parsed = parse_tensor(t)
    if parsed is None:
        return 0
    dims, dtype = parsed
    eb = hlo_graph.dtype_bytes(dtype)
    return chip.padded_bytes(dims, eb) if eb else 0


# ------------------------------------------------- innermost while body


def innermost_while(module: hlo_graph.Module):
    """The deepest `while` op of the reachable graph (ties broken by
    body size) and the func that owns it — the drain round the model
    prices. Returns (op, func) or (None, None)."""
    best, best_func, best_key = None, None, (-1, -1)

    def _scan(region, depth, func):
        nonlocal best, best_func, best_key
        for op in region.ops:
            if op.short == "while":
                body = next((r for r in op.regions if r.label == "do"),
                            None)
                n_ops = sum(1 for _ in body.walk()) if body else 0
                if (depth, n_ops) > best_key:
                    best, best_func, best_key = op, func, (depth, n_ops)
            for r in op.regions:
                _scan(r, depth + (1 if op.short == "while" else 0), func)

    for f in module.reachable_funcs():
        _scan(f.body, 0, f)
    return best, best_func


def _type_env(func: hlo_graph.Func) -> dict[str, str]:
    """SSA name -> type over one func (single-result ops and block
    args; multi-result groups stay unresolved — estimates degrade to
    'saw less', never crash)."""
    env: dict[str, str] = {}
    for name, t, _a in func.args:
        env[name] = t
    for op in func.walk():
        if op.result is not None and op.n_results == 1 \
                and op.result_types:
            env[op.result] = op.result_types[0]
        for r in op.regions:
            for n, t in r.block_args:
                env.setdefault(n, t)
    return env


def price_region(region: hlo_graph.Region, env: dict[str, str],
                 chip: Chip) -> dict:
    """Byte/flop/compare counts of one execution of `region`.

    Nested non-while regions (sort comparators, reducers) are priced
    through their owning op's formula, not op-by-op; a nested while is
    priced as one round of its own body (the model prices rounds, not
    trip counts)."""
    out = {"bytes": 0, "vpu_flops": 0, "sort_compares": 0,
           "mxu_flops": 0}

    def _add(d):
        for k in out:
            out[k] += d[k]

    for op in region.ops:
        if op.dialect not in ("stablehlo", "mhlo", "chlo"):
            continue
        rbytes = sum(_padded_bytes(t, chip) for t in op.result_types)
        obytes = sum(_padded_bytes(env.get(o, ""), chip)
                     for o in op.operands)
        out["bytes"] += rbytes + obytes
        short = op.short
        if short == "while":
            body = next((r for r in op.regions if r.label == "do"),
                        None)
            if body is not None:
                _add(price_region(body, env, chip))
            continue
        if short in ("case", "if"):
            for r in op.regions:
                _add(price_region(r, env, chip))
            continue
        first = parse_tensor(op.result_types[0]) \
            if op.result_types else None
        if first is None:
            continue
        dims, _dtype = first
        elems = _elems(dims)
        if short == "sort":
            n = dims[-1] if dims else 1
            rows = _elems(dims[:-1])
            per_col = rows * n * max(1, math.ceil(math.log2(max(n, 2))))
            out["sort_compares"] += per_col * max(op.n_results, 1)
        elif short == "dot_general":
            k = 1
            if op.operands:
                lhs = parse_tensor(env.get(op.operands[0], ""))
                if lhs is not None and lhs[0]:
                    k = lhs[0][-1]
            out["mxu_flops"] += 2 * elems * k
        elif short in ("reduce", "reduce_window"):
            ops_in = sum(
                _elems(p[0]) for p in
                (parse_tensor(env.get(o, "")) for o in op.operands)
                if p is not None)
            out["vpu_flops"] += max(ops_in, elems)
        elif short not in _MOVE_OPS:
            # elementwise / compare / select / rng default: one lane
            # op per result element
            out["vpu_flops"] += elems
    return out


def round_time_s(counts: dict, chip: Chip, scale: float = 1.0) -> dict:
    """Roofline time of one round: overhead + max(memory, compute)."""
    b = counts["bytes"] * scale
    hbm_s = b / (chip.hbm_gbps * 1e9)
    vpu_s = counts["vpu_flops"] * scale / (chip.vpu_gflops * 1e9)
    sort_s = counts["sort_compares"] * scale / (chip.sort_gcps * 1e9)
    mxu_s = (counts["mxu_flops"] * scale / (chip.mxu_tflops * 1e12)
             if chip.mxu_tflops else 0.0)
    compute_s = vpu_s + sort_s + mxu_s
    total = chip.round_overhead_us * 1e-6 + max(hbm_s, compute_s)
    return {
        "round_us": total * 1e6,
        "bound": ("hbm" if hbm_s > compute_s else
                  "sort" if sort_s >= vpu_s + mxu_s else "vpu"),
    }


def price_module(module: hlo_graph.Module, chip_name: str,
                 scale: float = 1.0) -> dict | None:
    """Round counts + roofline time of a lowered program's drain round
    under one chip row; None when no while loop exists."""
    op, func = innermost_while(module)
    if op is None:
        return None
    body = next((r for r in op.regions if r.label == "do"), None)
    if body is None:
        return None
    c = chip_row(chip_name)
    counts = price_region(body, _type_env(func), c)
    timing = round_time_s(counts, c, scale)
    return {**counts, **timing, "scale": round(scale, 3)}


# --------------------------------------------------- bench ground truth


def bench_drain_metadata(path: str | None = None) -> dict:
    """Measured drain economics per model from the bench report:
    {"tor": {"hosts", "chained": {events, inner_steps, run_s},
    "frontier": {...}}, ...}. Falls back to BENCH_r07's pinned numbers
    when the report is absent."""
    path = BENCH_PATH if path is None else path
    if not os.path.exists(path):
        return _FALLBACK_BENCH
    with open(path, "r", encoding="utf-8") as fh:
        parsed = json.load(fh).get("parsed", {})
    out = {}
    for model in DRAIN_PAIRS:
        entry = {}
        for drain in ("chained", "frontier"):
            rec = parsed.get(f"{model}_{drain}")
            if rec is None:
                break
            entry[drain] = {
                "events": rec[f"{model}_events"],
                "inner_steps": rec[f"{model}_inner_steps"],
                "run_s": rec[f"{model}_profile"]["run_s"],
            }
            entry["hosts"] = rec[f"{model}_hosts"]
        if len(entry) == 3:
            out[model] = entry
    return out or _FALLBACK_BENCH


def drain_report(modules: dict, hosts: dict,
                 bench: dict | None = None,
                 chips: tuple = CHIP_NAMES) -> dict:
    """Chained-vs-frontier economics per model per chip.

    `modules` maps config name -> parsed Module for every config in
    DRAIN_PAIRS; `hosts` maps config name -> host count of the tiny
    audit build (the linear scale-up target is the bench topology's
    host count). Returns per-model predictions, winners, the measured
    CPU winner, and whether the CPU-row prediction agrees with it.
    """
    bench = bench_drain_metadata() if bench is None else bench
    out: dict = {}
    for model, (cfg_c, cfg_f) in DRAIN_PAIRS.items():
        meta = bench.get(model)
        if meta is None or cfg_c not in modules or cfg_f not in modules:
            continue
        epr = {
            "chained": meta["chained"]["events"]
            / max(meta["chained"]["inner_steps"], 1),
            "frontier": meta["frontier"]["events"]
            / max(meta["frontier"]["inner_steps"], 1),
        }
        measured = ("chained"
                    if meta["chained"]["run_s"]
                    <= meta["frontier"]["run_s"] else "frontier")
        rec: dict = {
            "events_per_round": {k: round(v, 2) for k, v in epr.items()},
            "measured_cpu_winner": measured,
            "per_chip": {}, "winner": {},
        }
        for cname in chips:
            per = {}
            for drain, cfg in (("chained", cfg_c), ("frontier", cfg_f)):
                scale = meta["hosts"] / max(hosts.get(cfg, 1), 1)
                priced = price_module(modules[cfg], cname, scale)
                if priced is None:
                    per = {}
                    break
                per[drain] = {
                    "round_us": round(priced["round_us"], 3),
                    "bound": priced["bound"],
                    "events_per_s": round(
                        epr[drain] / (priced["round_us"] * 1e-6), 1),
                }
            if not per:
                continue
            rec["per_chip"][cname] = per
            rec["winner"][cname] = (
                "chained" if per["chained"]["events_per_s"]
                >= per["frontier"]["events_per_s"] else "frontier")
        if "cpu" in rec["winner"]:
            rec["cpu_agrees_with_bench"] = \
                rec["winner"]["cpu"] == measured
        out[model] = rec
    return out

"""TPU-readiness auditor over the lowered production programs.

hlo_audit pins op *counts* and memory.py pins HBM *liveness*; neither
says whether the programs will perform on real silicon. This module
walks every production lowering (all hlo_audit contract configs —
chained, `_frontier`, `_fleet`, `_sharded`, the serve warm-path fleet
step — plus the harvest extraction jits) and computes the three
static signals ROADMAP item 1's campaign needs before chip time:

- **tile report**: per-op (sublane, 128)-tile padding waste by dtype
  (`analysis.chips` geometry: f32/i64-as-2xi32 (8,128), bf16
  (16,128), i8 (32,128)). A shape like [H, 3] wastes 125/128 of
  every vector register; the report names the worst offenders with
  their line and region path.
- **layout-churn census**: transpose / reshape / bitcast_convert
  instances and bytes, split hot (inside a `while` body) vs total —
  each hot churn op is a relayout between every round.
- **placement report**: gather / scatter / dynamic_slice /
  dynamic_update_slice relative to the window `while` body, hot ones
  flagged with their region path (`Module.ops_with_path`) — the ops
  whose TPU lowering quality decides the drain's round time.
- **VMEM fit**: the fused merge kernel's working set (its actual
  traced block shapes, recorded off the lowering, x dtype bytes x
  double buffering) checked against each generation's VMEM capacity,
  with the max merge rows that fit per chip.

Findings land in the checked-in `analysis/TPU_READINESS.json`
baseline: waste %, churn counts, hot-op counts, VMEM bytes, and the
cost model's predicted events/s floors (`analysis.costmodel`). The
audit fails on regressions against the baseline (more waste, new hot
ops, bigger VMEM set, a floor dropping below tolerance) and on a CPU
cost-model prediction that disagrees with BENCH_r07's measured
chained-vs-frontier direction; improvements land silently and show up
in `--diff`. Refresh deliberately with
``python -m shadow_tpu.tools.lint --tpu-audit all --update-baseline``.
"""

from __future__ import annotations

import json
import os
from typing import Iterable

from shadow_tpu.analysis import costmodel, hlo_graph
from shadow_tpu.analysis.chips import CHIP_NAMES, CHIPS, chip as chip_row
from shadow_tpu.analysis.costmodel import parse_tensor

BASELINE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "TPU_READINESS.json")

# Inputs and outputs of the (gridless) merge pallas_call stream
# through VMEM double-buffered; intermediates are single-buffered.
DOUBLE_BUFFER = 2

# Tolerances: waste may drift this many percentage points before the
# audit fires (tiny-build shapes make the % jumpy at the margin);
# predicted floors may drop to this fraction of the baseline (the
# cost model's own error bars are wider than its round-to-round
# jitter — see docs/10-Static-Analysis.md).
WASTE_TOL_PCT = 0.5
FLOOR_TOL = 0.8

# The data-movement ops whose hot-loop placement the report pins.
PLACEMENT_OPS = ("gather", "scatter", "dynamic_slice",
                 "dynamic_update_slice")
CHURN_OPS = ("transpose", "reshape", "bitcast_convert")

# The harvest extraction jits ride along with the contract configs:
# same parser, same tile math, no roofline (no window loop inside).
EXTRA_CONFIGS = ("harvest_full", "harvest_light")


def ready_configs() -> list[str]:
    from shadow_tpu.analysis import hlo_audit

    return sorted(hlo_audit.CONTRACTS) + list(EXTRA_CONFIGS)


# ------------------------------------------------------------ lowering


def lower_config(name: str) -> tuple[str, list[dict], int]:
    """(lowered text, merge-kernel shape records, host rows) for one
    config. Merge shapes are recorded by wrapping
    `merge_pallas.merge_body` during the trace — the wrapper sees the
    exact block shapes the kernel is built with (per lane, under the
    fleet vmaps). Host rows feed the cost model's linear scale-up."""
    if name in EXTRA_CONFIGS:
        from shadow_tpu.analysis.donation import _sim_tiny
        from shadow_tpu.runtime.harvest import HeartbeatHarvest

        sim = _sim_tiny()
        h = HeartbeatHarvest(sim)
        text = h._build(name == "harvest_full").lower(
            sim.state0).as_text()
        return text, [], _host_rows(sim.state0)

    from shadow_tpu.analysis import hlo_audit

    run, state, stop = hlo_audit._build(name)
    shapes: list[dict] = []
    from shadow_tpu.core import merge_pallas

    orig = merge_pallas.merge_body

    def _recording(qt, qss, qpay, st, sss, bpay, starts, cnt):
        shapes.append({
            "h": int(qt.shape[0]), "hc": int(qt.shape[1]),
            "w": int(bpay.shape[1]), "m": int(st.shape[0]),
            "nw": int(qpay.shape[2]),
        })
        return orig(qt, qss, qpay, st, sss, bpay, starts, cnt)

    merge_pallas.merge_body = _recording
    try:
        text = hlo_audit.lower_text(run, state, stop)
    finally:
        merge_pallas.merge_body = orig
    return text, shapes, _host_rows(state)


def _host_rows(state) -> int:
    """Host-row count of a build's queue arrays ([H, C] solo,
    [L, H, C] fleet) — the axis the cost model scales linearly."""
    try:
        return int(state.queues.time.shape[-2])
    except AttributeError:
        return 1


# ------------------------------------------------------------- reports


def tile_report(module: hlo_graph.Module, *, chip_name: str = "v5e",
                worst: int = 5) -> dict:
    """Logical vs tile-padded bytes over every reachable op result.
    Geometry is identical across the TPU rows (the sublane map depends
    only on element width), so one report serves all three."""
    c = chip_row(chip_name)
    logical = padded = 0
    by_dtype: dict[str, dict] = {}
    offenders: list[tuple[int, dict]] = []
    for op, path in module.ops_with_path():
        if op.dialect not in ("stablehlo", "mhlo", "chlo"):
            continue
        for t in op.result_types:
            parsed = parse_tensor(t)
            if parsed is None:
                continue
            dims, dtype = parsed
            eb = hlo_graph.dtype_bytes(dtype)
            if not eb:
                continue
            lb = costmodel._elems(dims) * eb
            pb = c.padded_bytes(dims, eb)
            logical += lb
            padded += pb
            d = by_dtype.setdefault(
                dtype, {"logical_bytes": 0, "padded_bytes": 0})
            d["logical_bytes"] += lb
            d["padded_bytes"] += pb
            if pb > lb:
                offenders.append((pb - lb, {
                    "op": op.short, "line": op.line, "type": t.strip(),
                    "waste_bytes": pb - lb, "path": path,
                }))
    offenders.sort(key=lambda x: (-x[0], x[1]["line"]))
    for d in by_dtype.values():
        d["waste_pct"] = _waste_pct(d["logical_bytes"],
                                    d["padded_bytes"])
    return {
        "logical_bytes": logical,
        "padded_bytes": padded,
        "waste_pct": _waste_pct(logical, padded),
        "by_dtype": {k: by_dtype[k] for k in sorted(by_dtype)},
        "worst": [o for _, o in offenders[:worst]],
    }


def _waste_pct(logical: int, padded: int) -> float:
    return round(100.0 * (padded - logical) / padded, 2) if padded else 0.0


def churn_report(module: hlo_graph.Module) -> dict:
    """Layout-churn census: relayout ops, hot (inside any while body)
    vs total, with the bytes they move."""
    out = {k: {"count": 0, "hot": 0, "bytes": 0} for k in CHURN_OPS}
    for op, path in module.ops_with_path():
        if op.short not in out:
            continue
        rec = out[op.short]
        rec["count"] += 1
        rec["bytes"] += op.result_bytes()
        if _is_hot(path):
            rec["hot"] += 1
    return out


def placement_report(module: hlo_graph.Module, *, flag: int = 8) -> dict:
    """Gather/scatter/dynamic-slice placement relative to the window
    while body; hot instances carry their region path."""
    out = {k: {"count": 0, "hot": 0, "flagged": []}
           for k in PLACEMENT_OPS}
    for op, path in module.ops_with_path():
        if op.short not in out:
            continue
        rec = out[op.short]
        rec["count"] += 1
        if _is_hot(path):
            rec["hot"] += 1
            if len(rec["flagged"]) < flag:
                rec["flagged"].append(
                    {"line": op.line, "path": path,
                     "type": (op.result_types[0].strip()
                              if op.result_types else "")})
    return out


def _is_hot(path: str) -> bool:
    return "while@" in path and ".do" in path


# ---------------------------------------------------------- VMEM check


def merge_vmem_report(h: int, hc: int, w: int, m: int, nw: int,
                      chips: Iterable[str] = CHIP_NAMES) -> dict:
    """VMEM working set of one fused-merge invocation (the gridless
    pallas_call holds every ref whole): tile-padded input + output
    blocks double-buffered, plus the merge-path intermediates ([h, hc,
    w] and [h, hc+w, w] compare/count planes, charged at i32 width —
    TPU masks occupy full lanes). `fits`/`max_rows` per chip row."""
    ncol = hc + w
    i64, i32 = 8, 4

    def _pb(chip, dims, eb):
        return chip.padded_bytes(list(dims), eb)

    per_chip: dict[str, dict] = {}
    for cname in chips:
        c = chip_row(cname)
        io_bytes = (
            _pb(c, (h, hc), i64) * 2          # qt, qss
            + _pb(c, (h, hc, nw), i64)        # qpay
            + _pb(c, (m,), i64) * 2           # st, sss
            + _pb(c, (h, w, nw), i64)         # bpay
            + _pb(c, (h,), i32) * 2           # starts, cnt
            + _pb(c, (h, ncol), i64) * 2      # ot, oss
            + _pb(c, (h, ncol, nw), i64)      # opay
        )
        mid_bytes = (
            _pb(c, (h, hc, w), i32)           # le compare plane
            + _pb(c, (h, ncol, w), i32)       # jb count plane
            + _pb(c, (h, ncol, nw), i64)      # apay staging
        )
        ws = io_bytes * DOUBLE_BUFFER + mid_bytes
        rec = {"working_set_bytes": ws}
        if c.vmem_bytes is not None:
            rec["fits"] = ws <= c.vmem_bytes
            rec["max_rows"] = max(int(h * c.vmem_bytes / ws), 0) \
                if ws else 0
        per_chip[cname] = rec
    return {"h": h, "hc": hc, "w": w, "m": m, "nw": nw,
            "working_set_bytes":
                per_chip[next(iter(per_chip))]["working_set_bytes"]
                if per_chip else 0,
            "per_chip": per_chip}


def merge_report(shapes: list[dict]) -> dict | None:
    """The VMEM report of a config's LARGEST recorded merge call (the
    binding constraint); None when the config never merges."""
    if not shapes:
        return None
    biggest = max(shapes, key=lambda s: (s["h"] * (s["hc"] + s["w"])
                                         * (s["nw"] + 2), s["m"]))
    rep = merge_vmem_report(**biggest)
    rep["calls"] = len(shapes)
    return rep


# ------------------------------------------------------------ auditing


def audit_config(name: str) -> dict:
    """Full readiness report for one config."""
    text, shapes, rows = lower_config(name)
    module = hlo_graph.parse_module(text)
    return {
        "hosts": rows,
        "tile": tile_report(module),
        "churn": churn_report(module),
        "placement": placement_report(module),
        "vmem": merge_report(shapes),
        "_module": module,  # stripped before serialization
    }


def audit_all(names: Iterable[str] | None = None) -> dict:
    """Audit every config + the drain economics, checked against the
    checked-in baseline. Structure mirrors hlo_audit.audit_all: each
    entry carries ok/violations; `drain_economics` carries the cost
    model's predictions and the BENCH_r07 direction check."""
    names = list(names) if names else ready_configs()
    baseline = load_baseline()
    out: dict = {}
    modules: dict[str, hlo_graph.Module] = {}
    hosts: dict[str, int] = {}
    for name in names:
        try:
            rep = audit_config(name)
        except RuntimeError as e:
            # the sharded config needs 8 devices; skipped, not failed
            out[name] = {"ok": True, "skipped": str(e),
                         "violations": []}
            continue
        modules[name] = rep.pop("_module")
        hosts[name] = rep["hosts"]
        bl = baseline.get("configs", {}).get(name)
        violations = check_config(name, rep, bl)
        out[name] = {"ok": not violations, "violations": violations,
                     **rep}

    econ = costmodel.drain_report(modules, hosts)
    evio: list[str] = []
    for model, rec in econ.items():
        if rec.get("cpu_agrees_with_bench") is False:
            evio.append(
                f"drain_economics: {model} cost model predicts "
                f"`{rec['winner']['cpu']}` wins under CPU parameters "
                f"but BENCH_r07 measured "
                f"`{rec['measured_cpu_winner']}` — recalibrate "
                f"analysis/chips.py before trusting the TPU ranking")
    # predicted floors ride on the drain-pair configs
    for model, (cfg_c, cfg_f) in costmodel.DRAIN_PAIRS.items():
        rec = econ.get(model)
        if rec is None:
            continue
        for drain, cfg in (("chained", cfg_c), ("frontier", cfg_f)):
            if cfg not in out or "skipped" in out[cfg]:
                continue
            floors = {cn: rec["per_chip"][cn][drain]["events_per_s"]
                      for cn in rec["per_chip"]}
            out[cfg]["floors"] = floors
            bl = baseline.get("configs", {}).get(cfg, {})
            for cn, v in (bl.get("floors") or {}).items():
                got = floors.get(cn)
                if got is not None and got < v * FLOOR_TOL:
                    out[cfg]["violations"].append(
                        f"{cfg}: predicted {cn} floor {got:.1f} "
                        f"events/s fell below {FLOOR_TOL:.0%} of the "
                        f"baseline {v:.1f} — the drain round got "
                        f"statically slower; investigate or re-pin "
                        f"with --update-baseline")
                    out[cfg]["ok"] = False
    out["drain_economics"] = {"ok": not evio, "violations": evio,
                              **econ}
    return out


def check_config(name: str, rep: dict, bl: dict | None) -> list[str]:
    """Baseline regressions for one config's report; [] means clean."""
    if bl is None:
        return [f"{name}: no entry in TPU_READINESS.json — pin it with "
                f"--tpu-audit all --update-baseline"]
    v: list[str] = []
    waste, bwaste = rep["tile"]["waste_pct"], bl["tile"]["waste_pct"]
    if waste > bwaste + WASTE_TOL_PCT:
        v.append(f"{name}: tile padding waste {waste}% exceeds "
                 f"baseline {bwaste}% — a padded-to-waste shape "
                 f"entered the lowering (see tile.worst)")
    for op_name, rec in rep["churn"].items():
        brec = bl["churn"].get(op_name, {"count": 0, "hot": 0})
        for k in ("count", "hot"):
            if rec[k] > brec[k]:
                v.append(f"{name}: {rec[k]}x {op_name} "
                         f"({k}) exceeds baseline {brec[k]} — layout "
                         f"churn crept into the lowering")
    for op_name, rec in rep["placement"].items():
        bhot = bl["hot_ops"].get(op_name, 0)
        if rec["hot"] > bhot:
            v.append(f"{name}: {rec['hot']}x hot-loop {op_name} "
                     f"exceeds baseline {bhot} — a new {op_name} "
                     f"entered the window while body "
                     f"(placement.{op_name}.flagged has the paths)")
    bvm = bl.get("vmem")
    vm = rep.get("vmem")
    if vm is not None and bvm is not None:
        if vm["working_set_bytes"] > bvm["working_set_bytes"]:
            v.append(f"{name}: merge-kernel VMEM working set "
                     f"{vm['working_set_bytes']} bytes exceeds "
                     f"baseline {bvm['working_set_bytes']} — the "
                     f"fused merge block grew")
        for cn, rec in vm["per_chip"].items():
            if "fits" in rec and not rec["fits"] \
                    and bvm.get("per_chip", {}).get(cn, {}).get(
                        "fits", True):
                v.append(f"{name}: merge kernel no longer fits {cn} "
                         f"VMEM ({rec['working_set_bytes']} bytes > "
                         f"{CHIPS[cn].vmem_bytes})")
    return v


# ------------------------------------------------------------- baseline


def load_baseline(path: str = BASELINE_PATH) -> dict:
    if not os.path.exists(path):
        return {}
    with open(path, "r", encoding="utf-8") as fh:
        return json.load(fh)


def save_baseline(results: dict, path: str = BASELINE_PATH) -> dict:
    """Distill an audit_all result into the checked-in baseline (the
    enforced numbers only — worst-offender lists and per-chip detail
    stay in the full report)."""
    configs: dict[str, dict] = {}
    for name, rep in results.items():
        if name == "drain_economics" or "skipped" in rep \
                or "tile" not in rep:
            continue
        entry = {
            "tile": {"logical_bytes": rep["tile"]["logical_bytes"],
                     "padded_bytes": rep["tile"]["padded_bytes"],
                     "waste_pct": rep["tile"]["waste_pct"]},
            "churn": {k: {"count": r["count"], "hot": r["hot"]}
                      for k, r in rep["churn"].items()},
            "hot_ops": {k: r["hot"]
                        for k, r in rep["placement"].items()},
        }
        vm = rep.get("vmem")
        if vm is not None:
            entry["vmem"] = {
                "working_set_bytes": vm["working_set_bytes"],
                "per_chip": {cn: {k: r[k] for k in ("fits",)
                                  if k in r}
                             for cn, r in vm["per_chip"].items()},
            }
        if "floors" in rep:
            entry["floors"] = rep["floors"]
        configs[name] = entry
    econ = results.get("drain_economics", {})
    winners = {m: rec.get("winner", {})
               for m, rec in econ.items()
               if isinstance(rec, dict) and "winner" in rec}
    data = {
        "version": 1,
        "comment": "TPU-readiness baseline (tile waste / layout churn "
                   "/ hot-loop placement / merge-kernel VMEM / "
                   "predicted events-per-s floors) over the lowered "
                   "production programs; regenerate with `python -m "
                   "shadow_tpu.tools.lint --tpu-audit all "
                   "--update-baseline`",
        "configs": {k: configs[k] for k in sorted(configs)},
        "winners": winners,
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(data, fh, indent=1)
        fh.write("\n")
    return data


def report_json(results: dict) -> dict:
    """The audit result with only JSON-safe content (drop nothing
    today — modules are already stripped in audit_all)."""
    return results

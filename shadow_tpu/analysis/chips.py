"""Chip parameter table for the TPU-readiness audits (docs/10, "TPU
readiness").

One frozen row per accelerator generation the silicon campaign
(ROADMAP item 1) targets, plus a CPU row describing the single-core
container every BENCH_r* number was measured on. The rows feed three
consumers:

- the tile auditor (`tpu_readiness`): native tile geometry per dtype
  width — the (sublane, 128) minimum tile of the Pallas guide's table
  (f32 (8,128), bf16 (16,128), int8/fp8 (32,128); i64 is emulated as
  two i32 words so it pads like a 4-byte type);
- the VMEM fit check: per-core VMEM capacity the fused merge kernel's
  working set is checked against;
- the roofline cost model (`costmodel`): HBM bandwidth and VPU/MXU
  peaks that price one window round.

Provenance: tile geometry, the ~16 MB/core VMEM figure, and the
8x128 VPU / 128x128 MXU shapes come from the Pallas TPU guide; HBM
capacity/bandwidth and peak bf16 FLOPs are the public v5e/v5p/v6e
spec-sheet numbers. `sort_gcps` (sustainable sort compares/s) is the
one deliberately soft number: on the TPU rows it assumes the
per-round `lax.sort` lowers to a vectorized bitonic network filling
the 8x128 VPU (the frontier drain's whole bet, BENCH_r07); the CPU
row is calibrated against this repo's measured single-core container
(BENCH_r07: scalar, branchy compare-exchange ~0.1 G compares/s).
Error bars are a factor of ~2 either way — the model ranks drains and
flags order-of-magnitude VMEM misses, it does not predict wall
seconds to a percent (docs/10-Static-Analysis.md spells this out).
"""

from __future__ import annotations

import dataclasses

MIB = 1 << 20
GIB = 1 << 30


@dataclasses.dataclass(frozen=True)
class Chip:
    """One accelerator generation's audit-relevant parameters."""

    name: str
    lane: int                 # last-dim tile width (128 on TPU)
    sublanes: dict            # element bytes -> second-to-last tile dim
    vmem_bytes: int | None    # per-core VMEM; None = no VMEM tier (CPU)
    hbm_bytes: int            # device memory capacity
    hbm_gbps: float           # memory bandwidth, GB/s
    vpu_gflops: float         # elementwise/vector peak, GFLOP/s
    mxu_tflops: float         # matmul peak (bf16), TFLOP/s; 0 = no MXU
    sort_gcps: float          # sustainable sort compare-exchanges/s, G/s
    round_overhead_us: float  # fixed per-round dispatch/latency charge

    def tile(self, elem_bytes: int) -> tuple[int, int]:
        """Minimum (sublane, lane) tile for an element width. 8-byte
        types (the engine's i64 timestamps) are emulated as two 4-byte
        words, so they tile like f32/i32."""
        b = 4 if elem_bytes >= 8 else max(int(elem_bytes), 1)
        sub = self.sublanes.get(b, self.sublanes.get(4, 1))
        return (sub, self.lane)

    def padded_dims(self, dims: list, elem_bytes: int) -> list:
        """Tile-padded physical dims for a logical shape: the last two
        dims round up to the native tile; leading dims are unpadded.
        Rank-0/rank-1 arrays occupy one tile's worth of lanes."""
        sub, lane = self.tile(elem_bytes)
        if not dims:
            return [sub, lane] if self.lane > 1 else []
        out = list(dims)
        out[-1] = _round_up(out[-1], lane)
        if len(out) >= 2:
            out[-2] = _round_up(out[-2], sub)
        elif self.lane > 1:
            out = [sub, out[-1]]
        return out

    def padded_bytes(self, dims: list, elem_bytes: int) -> int:
        n = 1
        for d in self.padded_dims(dims, elem_bytes):
            n *= int(d)
        return n * int(elem_bytes)


def _round_up(n: int, to: int) -> int:
    return -(-int(n) // int(to)) * int(to) if to > 1 else int(n)


# TPU native sublane counts by element width (Pallas guide tiling
# table): 4-byte (8,128), 2-byte (16,128), 1-byte (32,128). 8-byte
# i64 is handled in Chip.tile (two 4-byte words).
_TPU_SUBLANES = {1: 32, 2: 16, 4: 8}

CHIPS: dict[str, Chip] = {
    # v5e: 16 GiB HBM @ 819 GB/s, 197 bf16 TFLOP/s MXU per chip.
    "v5e": Chip(
        name="v5e", lane=128, sublanes=_TPU_SUBLANES,
        vmem_bytes=16 * MIB, hbm_bytes=16 * GIB, hbm_gbps=819.0,
        vpu_gflops=3900.0, mxu_tflops=197.0, sort_gcps=450.0,
        round_overhead_us=2.0,
    ),
    # v5p: 95 GiB HBM @ 2765 GB/s, 459 bf16 TFLOP/s per chip (2 cores).
    "v5p": Chip(
        name="v5p", lane=128, sublanes=_TPU_SUBLANES,
        vmem_bytes=16 * MIB, hbm_bytes=95 * GIB, hbm_gbps=2765.0,
        vpu_gflops=7800.0, mxu_tflops=459.0, sort_gcps=900.0,
        round_overhead_us=2.0,
    ),
    # v6e (Trillium): 32 GiB HBM @ 1640 GB/s, 918 bf16 TFLOP/s.
    "v6e": Chip(
        name="v6e", lane=128, sublanes=_TPU_SUBLANES,
        vmem_bytes=32 * MIB, hbm_bytes=32 * GIB, hbm_gbps=1640.0,
        vpu_gflops=7800.0, mxu_tflops=918.0, sort_gcps=900.0,
        round_overhead_us=2.0,
    ),
    # The measured baseline: one CPU core of the CI container (every
    # BENCH_r* CPU number). No tiling (lane 1), no VMEM tier, no MXU;
    # sort_gcps is the scalar compare-exchange rate calibrated against
    # BENCH_r07's chained-vs-frontier gap on this box.
    "cpu": Chip(
        name="cpu", lane=1, sublanes={1: 1, 2: 1, 4: 1},
        vmem_bytes=None, hbm_bytes=16 * GIB, hbm_gbps=12.0,
        vpu_gflops=12.0, mxu_tflops=0.0, sort_gcps=0.1,
        round_overhead_us=0.5,
    ),
}

# Order reports/baselines list the rows in.
CHIP_NAMES = ("v5e", "v5p", "v6e", "cpu")


def chip(name: str) -> Chip:
    try:
        return CHIPS[name]
    except KeyError:
        raise KeyError(f"unknown chip `{name}` (have {CHIP_NAMES})")

"""StableHLO pretty-text -> structural op graph (defs/uses/regions/bytes).

The contract audits (hlo_audit), the donation verifier (donation) and
the peak-memory estimator (memory) all interrogate the *lowered
program*, not the Python source. Until PR 12 that interrogation was a
flat regex over the text — which cannot tell an op inside the window
loop's while body from one in a dead private helper, counts the
`applies stablehlo.minimum` clause of a reduce as an op, and misses the
quoted `custom_call @"..."` target form. This module parses the MLIR
pretty form jax emits (`jit(f).lower(...).as_text()`) into a real
graph:

- `Module` / `Func` / `Region` / `Op`: ops with result names, operand
  names (SSA base names, `%123#15` -> `%123`), result types, and
  nested regions (while cond/do, sort comparators, reduce reducers,
  case branches) attached where they occur.
- Reachability from the public funcs over `func.call` edges, so dead
  private helpers never count against a budget.
- `bytes_of_type("tensor<8x32xi64>")` for the liveness estimator.

The grammar is the subset jax 0.4.x actually prints (verified against
full engine lowerings of every model config); unrecognized lines are
skipped, never fatal — an auditor must degrade to "saw less", not
crash the lint gate. Loose op fragments outside any `func.func` (used
by contract tests) land in an implicit public `<toplevel>` func.
"""

from __future__ import annotations

import dataclasses
import re
from collections import Counter
from typing import Iterator

# --------------------------------------------------------------- bytes

_DTYPE_BYTES = {
    "i1": 1, "i2": 1, "i4": 1, "i8": 1, "i16": 2, "i32": 4, "i64": 8,
    "ui2": 1, "ui4": 1, "ui8": 1, "ui16": 2, "ui32": 4, "ui64": 8,
    "f8E4M3FN": 1, "f8E4M3": 1, "f8E5M2": 1, "f8E4M3B11FNUZ": 1,
    "f16": 2, "bf16": 2, "f32": 4, "f64": 8,
    "c64": 8, "c128": 16,
    "pred": 1, "index": 8,
}

def dtype_bytes(dtype: str) -> int:
    """Bytes per element of an MLIR element type; 0 when unknown."""
    if dtype in _DTYPE_BYTES:
        return _DTYPE_BYTES[dtype]
    m = re.fullmatch(r"[a-z]+?(\d+)(?:E\w+)?", dtype)
    return (int(m.group(1)) + 7) // 8 if m else 0


def bytes_of_type(t: str) -> int:
    """Total bytes of one MLIR type string; 0 for non-tensor types
    (tokens, tuples sum their tensor elements). Encoding attributes
    after the dims (``tensor<8xi64, #stablehlo...<...>>``) nest angle
    brackets, so the payload is cut with a balanced scan, not a regex.
    """
    total = 0
    i = 0
    while True:
        j = t.find("tensor<", i)
        if j < 0:
            break
        end = _balanced(t, j + len("tensor"), "<", ">")
        payload = t[j + len("tensor<"):end - 1]
        i = end
        payload = _split_commas(payload)[0].strip()  # drop encoding attr
        parts = payload.split("x")
        n = 1
        for dim in parts[:-1]:
            n *= int(dim) if dim.isdigit() else 0
        total += n * dtype_bytes(parts[-1])
    return total


def _split_commas(s: str) -> list[str]:
    """Split on top-level commas, respecting <> () {} [] and quotes."""
    out, depth, start, i, q = [], 0, 0, 0, False
    while i < len(s):
        c = s[i]
        if q:
            if c == '"' and s[i - 1] != "\\":
                q = False
        elif c == '"':
            q = True
        elif c in "<({[":
            depth += 1
        elif c in ">)}]":
            depth -= 1
        elif c == "," and depth == 0:
            out.append(s[start:i].strip())
            start = i + 1
        i += 1
    tail = s[start:].strip()
    if tail:
        out.append(tail)
    return out


# ---------------------------------------------------------------- model


@dataclasses.dataclass
class Op:
    """One op instance. `result` is the SSA base name (`%2` for a
    `%2:29 = ...` group of 29 results); `operands` are base names of
    every value the op (or any op in its regions) reads."""

    name: str
    result: str | None = None
    n_results: int = 0
    result_types: list[str] = dataclasses.field(default_factory=list)
    operands: list[str] = dataclasses.field(default_factory=list)
    regions: list["Region"] = dataclasses.field(default_factory=list)
    line: int = 0
    callee: str | None = None
    custom_target: str | None = None

    @property
    def short(self) -> str:
        return self.name.rsplit(".", 1)[-1]

    @property
    def dialect(self) -> str:
        return self.name.rsplit(".", 1)[0] if "." in self.name else ""

    def result_bytes(self) -> int:
        return sum(bytes_of_type(t) for t in self.result_types)

    def walk(self) -> Iterator["Op"]:
        yield self
        for r in self.regions:
            yield from r.walk()


@dataclasses.dataclass
class Region:
    label: str = ""  # "cond" / "do" / "reducer" / "" (generic branch)
    block_args: list[tuple[str, str]] = dataclasses.field(
        default_factory=list)  # (name, type)
    ops: list[Op] = dataclasses.field(default_factory=list)

    def walk(self) -> Iterator[Op]:
        for op in self.ops:
            yield from op.walk()


@dataclasses.dataclass
class Func:
    name: str
    visibility: str  # "public" | "private"
    args: list[tuple[str, str, str]]  # (name, type, attr text)
    result_types: list[str]
    result_infos: list[str]  # jax.result_info per result ("" if absent)
    body: Region

    def arg_bytes(self) -> int:
        return sum(bytes_of_type(t) for _, t, _a in self.args)

    def walk(self) -> Iterator[Op]:
        yield from self.body.walk()


class Module:
    def __init__(self) -> None:
        self.funcs: dict[str, Func] = {}
        self.order: list[str] = []

    def add(self, f: Func) -> None:
        self.funcs[f.name] = f
        self.order.append(f.name)

    @property
    def entry(self) -> Func | None:
        for name in self.order:
            if self.funcs[name].visibility == "public":
                return self.funcs[name]
        return self.funcs[self.order[0]] if self.order else None

    def reachable_funcs(self) -> list[Func]:
        """Funcs reachable from the public funcs over call edges —
        structural dead-code elimination for the audits."""
        roots = [n for n in self.order
                 if self.funcs[n].visibility == "public"]
        if not roots and self.order:
            roots = [self.order[0]]
        seen: list[str] = []
        stack = list(roots)
        while stack:
            name = stack.pop()
            if name in seen or name not in self.funcs:
                continue
            seen.append(name)
            for op in self.funcs[name].walk():
                if op.callee and op.callee not in seen:
                    stack.append(op.callee)
        return [self.funcs[n] for n in self.order if n in seen]

    def ops(self, *, reachable_only: bool = True) -> Iterator[Op]:
        funcs = (self.reachable_funcs() if reachable_only
                 else [self.funcs[n] for n in self.order])
        for f in funcs:
            yield from f.walk()

    def histogram(self, *, reachable_only: bool = True) -> Counter:
        """Per-op-instance counts of dialect ops (short names), over
        reachable funcs only by default — the regex predecessor counted
        dead private helpers and `applies` clauses identically."""
        hist: Counter = Counter()
        for op in self.ops(reachable_only=reachable_only):
            if op.dialect in ("stablehlo", "mhlo", "chlo"):
                hist[op.short] += 1
        return hist

    def find_ops(self, short: str, *,
                 reachable_only: bool = True) -> list[Op]:
        return [op for op in self.ops(reachable_only=reachable_only)
                if op.short == short]

    def custom_call_targets(self, *,
                            reachable_only: bool = True) -> list[str]:
        """Unique custom_call targets, sorted (126 GSPMD `Sharding`
        markers are one fact about the module, not 126)."""
        return sorted({op.custom_target
                       for op in self.find_ops(
                           "custom_call", reachable_only=reachable_only)
                       if op.custom_target})

    def ops_with_path(self) -> Iterator[tuple[Op, str]]:
        """(op, region path) over reachable funcs. The path names every
        enclosing op region, e.g. ``main/while@12.do/while@40.do`` —
        a path containing ``while@N.do`` places the op inside the
        window loop's hot path, and the tail says exactly where (the
        tile/placement auditor's provenance string)."""
        def _walk(region: Region, prefix: str) -> Iterator[tuple[Op, str]]:
            for op in region.ops:
                yield op, prefix
                for i, r in enumerate(op.regions):
                    label = r.label or str(i)
                    yield from _walk(
                        r, f"{prefix}/{op.short}@{op.line}.{label}")

        for f in self.reachable_funcs():
            yield from _walk(f.body, f.name)

    def while_body_ops(self) -> Iterator[Op]:
        """Ops inside any while body ("do" region) — the structural
        form of "in the window loop's hot path"."""
        for op in self.ops():
            if op.short == "while":
                for r in op.regions:
                    if r.label == "do":
                        yield from r.walk()


# --------------------------------------------------------------- parser

_RESULT_RE = re.compile(r"^(%[A-Za-z0-9_]+)(?::(\d+))?\s*=\s*")
_OPNAME_QUOTED_RE = re.compile(r'^"([A-Za-z_][\w.$-]*)"')
_OPNAME_BARE_RE = re.compile(r"^([A-Za-z_][\w$]*\.[A-Za-z_][\w$]*)\b")
_ITER_RE = re.compile(r"(%iterArg\w*)\s*=\s*(%\w+)")
_VALUE_RE = re.compile(r"%([A-Za-z0-9_]+)")
_BLOCK_ARG_RE = re.compile(r"(%[A-Za-z0-9_]+):\s*([^,()]+)")
# quoted names may carry escaped characters (`@"a\"b"`): a string
# atom is any run of non-quote/non-backslash chars or escape pairs
_QSTR = r'(?:[^"\\]|\\.)'
_CALLEE_RE = re.compile(r'@(?:"(' + _QSTR + r'+)"|([\w.$-]+))')
_TARGET_NAME_RE = re.compile(
    r'call_target_name\s*=\s*"(' + _QSTR + r'+)"')
_RESULT_INFO_RE = re.compile(
    r'jax\.result_info\s*=\s*"(' + _QSTR + r'*)"')
_FUNC_RE = re.compile(r"^func\.func\s+(?:(public|private)\s+)?@"
                      r'(?:"(' + _QSTR + r'+)"|([\w.$-]+))\s*\(')


def _balanced(s: str, start: int, open_c: str, close_c: str) -> int:
    """Index just past the matching close for the open at `start`."""
    depth, i, q = 0, start, False
    while i < len(s):
        c = s[i]
        if q:
            if c == '"' and s[i - 1] != "\\":
                q = False
        elif c == '"':
            q = True
        elif c == open_c:
            depth += 1
        elif c == close_c:
            depth -= 1
            if depth == 0:
                return i + 1
        i += 1
    return len(s)


class _Parser:
    def __init__(self) -> None:
        self.module = Module()
        # frames: {"kind": "module"|"func"|"region", "region": Region|None,
        #          "owner": Op|None, "pending_types": bool}
        self.stack: list[dict] = []

    # ------------------------------------------------------------ frames

    def _current_region(self) -> Region | None:
        for fr in reversed(self.stack):
            if fr["region"] is not None:
                return fr["region"]
        return None

    def _ensure_region(self) -> Region:
        r = self._current_region()
        if r is None:
            f = Func("<toplevel>", "public", [], [], [], Region())
            self.module.add(f)
            self.stack.append({"kind": "func", "region": f.body,
                               "owner": None, "pending_types": False})
            r = f.body
        return r

    def _last_op(self) -> Op | None:
        r = self._current_region()
        return r.ops[-1] if r is not None and r.ops else None

    def _push_region(self, owner: Op, region: Region,
                     pending: bool = False) -> None:
        owner.regions.append(region)
        self.stack.append({"kind": "region", "region": region,
                           "owner": owner, "pending_types": pending})

    def _pop_region(self) -> dict | None:
        if self.stack and self.stack[-1]["kind"] == "region":
            return self.stack.pop()
        return None

    # -------------------------------------------------------------- feed

    def feed(self, line: str, lineno: int) -> None:
        s = line.strip()
        if not s or s.startswith("//"):
            return
        if s.startswith("module"):
            self.stack.append({"kind": "module", "region": None,
                               "owner": None, "pending_types": False})
            return
        if s.startswith("func.func"):
            f = self._parse_func(s)
            if f is not None:
                self.module.add(f)
                self.stack.append({"kind": "func", "region": f.body,
                                   "owner": None, "pending_types": False})
            return
        if s.startswith("^"):  # ^bb0(%a: t, ...):
            r = self._current_region()
            if r is not None and not r.block_args:
                r.block_args = _BLOCK_ARG_RE.findall(s)
            return
        if s.startswith("cond {"):
            self._open_while_region("cond")
            return
        if s.startswith("} do {"):
            fr = self._pop_region()
            if fr is not None:
                self._open_while_region("do", owner=fr["owner"])
            return
        if s.startswith("}, {"):  # sibling generic region (case branch)
            fr = self._pop_region()
            if fr is not None:
                self._push_region(fr["owner"], Region(),
                                  pending=fr["pending_types"])
                # siblings were appended by _push_region; undo the extra
                # stack entry duplication is fine — same owner, new region
            return
        if s.startswith("reducer(") and s.endswith("{"):
            op = self._last_op()
            if op is not None:
                self._push_region(op, Region(
                    "reducer", block_args=_BLOCK_ARG_RE.findall(s)))
            return
        if s.startswith("})"):
            fr = self._pop_region()
            if fr is not None and fr["pending_types"] and " : " in s:
                self._apply_types(fr["owner"], s.rsplit(" : ", 1)[1])
            return
        if s == "}":
            if self.stack:
                self.stack.pop()
            return
        self._parse_op(s, lineno)

    def _open_while_region(self, label: str, owner: Op | None = None) -> None:
        op = owner if owner is not None else self._last_op()
        if op is None:
            return
        region = Region(label)
        # the while declares its carry on the op line; both regions see
        # the same %iterArg block args
        region.block_args = list(getattr(op, "_carry", []))
        self._push_region(op, region)

    # ----------------------------------------------------------- pieces

    def _parse_func(self, s: str) -> Func | None:
        m = _FUNC_RE.match(s)
        if not m:
            return None
        vis = m.group(1) or "private"
        name = m.group(2) or m.group(3)
        paren_open = s.index("(", m.end() - 1)
        paren_close = _balanced(s, paren_open, "(", ")")
        args = []
        for item in _split_commas(s[paren_open + 1:paren_close - 1]):
            am = re.match(r"(%[A-Za-z0-9_]+):\s*(.*)", item)
            if not am:
                continue
            rest = am.group(2).strip()
            attr = ""
            brace = rest.find("{")
            if brace >= 0:
                attr = rest[brace:]
                rest = rest[:brace].strip()
            args.append((am.group(1), rest, attr))
        result_types: list[str] = []
        result_infos: list[str] = []
        tail = s[paren_close:]
        arrow = tail.find("->")
        if arrow >= 0:
            res = tail[arrow + 2:].strip()
            if res.endswith("{"):
                res = res[:-1].strip()
            if res.startswith("("):
                res = res[1:_balanced(res, 0, "(", ")") - 1]
            for item in _split_commas(res):
                im = _RESULT_INFO_RE.search(item)
                result_infos.append(im.group(1) if im else "")
                brace = item.find("{")
                result_types.append(
                    (item[:brace] if brace >= 0 else item).strip())
        return Func(name, vis, args, result_types, result_infos, Region())

    def _parse_op(self, s: str, lineno: int) -> None:
        m = _RESULT_RE.match(s)
        result, n_results, rest = None, 0, s
        if m:
            result = m.group(1)
            n_results = int(m.group(2) or 1)
            rest = s[m.end():]
        mq = _OPNAME_QUOTED_RE.match(rest)
        if mq:
            name, tail = mq.group(1), rest[mq.end():]
        else:
            mb = _OPNAME_BARE_RE.match(rest)
            if mb:
                name, tail = mb.group(1), rest[mb.end():]
            elif rest.startswith("return"):
                name, tail = "func.return", rest[len("return"):]
            elif rest.startswith("call ") or rest.startswith("call@"):
                # bare `call @callee(...)` — GSPMD-partitioned modules
                # wrap the real computation this way; losing it would
                # silently empty the reachable graph
                name, tail = "func.call", rest[len("call"):]
            else:
                return  # unrecognized line — lenient by design
        op = Op(name=name, result=result, n_results=n_results, line=lineno)

        if name == "stablehlo.while":
            pairs = _ITER_RE.findall(rest)
            op.operands = [rhs for _lhs, rhs in pairs]
            if " : " in rest:
                types = _split_commas(rest.rsplit(" : ", 1)[1])
                op.result_types = types
                op._carry = list(zip([lhs for lhs, _ in pairs], types))
            self._ensure_region().ops.append(op)
            return

        opens_region = tail.rstrip().endswith("({")
        scan = tail
        if " : " in tail and not opens_region:
            scan, types = tail.rsplit(" : ", 1)
            if op.result is not None:
                self._apply_types(op, types)
        seen: set[str] = set()
        for v in _VALUE_RE.findall(scan):
            if v not in seen:
                seen.add(v)
                op.operands.append("%" + v)

        if name in ("func.call", "call"):
            cm = _CALLEE_RE.search(tail)
            if cm:
                op.callee = cm.group(1) or cm.group(2)
        if op.short == "custom_call":
            tm = _TARGET_NAME_RE.search(s)
            if tm:
                op.custom_target = tm.group(1)
            else:
                am = _CALLEE_RE.search(tail)
                if am:
                    op.custom_target = am.group(1) or am.group(2)

        self._ensure_region().ops.append(op)
        if opens_region:
            self._push_region(op, Region(), pending=True)

    def _apply_types(self, op: Op | None, types: str) -> None:
        if op is None:
            return
        types = types.strip()
        if "->" in types:
            types = types.rsplit("->", 1)[1].strip()
        if types.startswith("("):
            types = types[1:_balanced(types, 0, "(", ")") - 1]
            op.result_types = _split_commas(types)
        else:
            parts = _split_commas(types)
            # pretty form lists operand types with the result last
            # (select/or/add print one shared type)
            op.result_types = parts[-1:] if parts else []


def parse_module(text: str) -> Module:
    """Parse lowered StableHLO pretty text into a Module graph."""
    p = _Parser()
    for lineno, line in enumerate(text.splitlines(), 1):
        p.feed(line, lineno)
    return p.module

"""Peak-live-buffer estimator over the lowered op graph, with budgets.

ROADMAP item 3 vmaps the whole engine over scenario fleets; before
that lands, peak device memory per config needs a regression net the
same way op counts have one. This module walks the parsed StableHLO
graph (`hlo_graph.parse_module`) and computes a deterministic
peak-live estimate per model config:

- values expire at their last use *before* an op's regions execute
  (XLA donates while-loop inputs through the carry, so the loop
  operands and the iterArg carry never coexist);
- an op's results materialize after its regions complete;
- a region's own peak (its carry plus its temporaries) is charged at
  the program point of the op that owns it; `func.call` charges the
  callee's peak (memoized) the same way;
- dead results (defined, never read) are charged at their definition
  point only.

This is an estimate of the *lowered* program, not a buffer-assignment
readback: XLA's scheduler can do better (rematerialization, fusion)
and the estimate deliberately ignores donation of the entry args (so
it upper-bounds). What matters is that it is deterministic and moves
when the carried state or the window loop's temporaries move — the
checked-in budgets in `MEM_BUDGETS.json` turn that movement into a
review-visible diff instead of a silent 2x on real silicon.

Budgets cover the five model configs, the frontier-drain twins of the
three TCP models (`*_frontier` — the per-round outbuf/trace staging is
the frontier executor's only extra live state, and these entries keep
its growth review-visible), plus the fleet twins (`phold_fleet`,
`tgen_fleet` — the real `runtime.fleet.Fleet` lowering over a 4-lane
seed sweep, so a per-scenario term that should batch shows up as ~4x
in review). Refresh with
``python -m shadow_tpu.tools.lint --mem-audit --update-baseline``.
"""

from __future__ import annotations

import json
import os
from typing import Iterable

from shadow_tpu.analysis import hlo_graph
from shadow_tpu.analysis.hlo_graph import Func, Module, Op, Region

BUDGETS_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "MEM_BUDGETS.json")

# The fleet axis the *_fleet entries vmap over (matches hlo_audit's
# fleet contracts): small enough to lower fast, big enough that a
# per-scenario term shows up as 4x.
FLEET = 4

MEM_CONFIGS = ("phold", "phold_net", "tgen", "tor", "bitcoin",
               "tgen_frontier", "tor_frontier", "bitcoin_frontier",
               "phold_fleet", "tgen_fleet", "phold_serve")


# ------------------------------------------------------------ liveness


def _op_uses(op: Op) -> set[str]:
    """Every value the op reads, including free uses inside its
    regions (charged at the op's program point)."""
    used = set(op.operands)
    for r in op.regions:
        for o in r.walk():
            used.update(o.operands)
    return used


def _region_peak(region: Region, module: Module,
                 memo: dict[str, int]) -> int:
    """Peak bytes live inside `region`, including its block-arg carry."""
    carry = sum(hlo_graph.bytes_of_type(t) for _, t in region.block_args)
    uses_at: list[set[str]] = []
    last: dict[str, int] = {}
    for i, op in enumerate(region.ops):
        u = _op_uses(op)
        uses_at.append(u)
        for v in u:
            last[v] = i
    running = carry
    peak = running
    live: dict[str, int] = {}
    for i, op in enumerate(region.ops):
        for v in uses_at[i]:
            if last[v] == i and v in live:
                running -= live.pop(v)
        inner = 0
        for r in op.regions:
            inner = max(inner, _region_peak(r, module, memo))
        if op.callee and op.callee in module.funcs:
            inner = max(inner, _func_peak(module.funcs[op.callee],
                                          module, memo))
        peak = max(peak, running + inner)
        rb = op.result_bytes()
        if op.result is not None and rb:
            running += rb
            peak = max(peak, running)
            if op.result in last:
                live[op.result] = rb
            else:
                running -= rb  # dead value: charged at its def only
    return peak


def _func_peak(func: Func, module: Module, memo: dict[str, int]) -> int:
    if func.name in memo:
        return memo[func.name]
    memo[func.name] = 0  # recursion guard (MLIR funcs don't recurse)
    peak = func.arg_bytes() + _region_peak(func.body, module, memo)
    memo[func.name] = peak
    return peak


def estimate_module(module: Module) -> dict:
    """Peak/carry/arg byte estimate for a parsed module's entry func."""
    entry = module.entry
    if entry is None:
        return {"args_bytes": 0, "carry_bytes": 0, "peak_bytes": 0}
    carry = 0
    for op in entry.walk():
        if op.short == "while":
            carry = sum(hlo_graph.bytes_of_type(t)
                        for t in op.result_types)
            break  # outermost while = the window loop's carried state
    return {
        "args_bytes": entry.arg_bytes(),
        "carry_bytes": carry,
        "peak_bytes": _func_peak(entry, module, {}),
    }


def estimate_text(text: str) -> dict:
    return estimate_module(hlo_graph.parse_module(text))


# ------------------------------------------------------------- configs


def estimate_config(name: str) -> dict:
    """Lower one config's window loop and estimate its peak.

    The `*_fleet` entries lower the real `runtime.fleet.Fleet` program
    (hlo_audit builds them at FLEET lanes): the lane binds are jit
    closure constants, so the entry args stay exactly the stacked
    `[FLEET, ...]` state plus the stop scalar — the args-bytes relation
    tests/test_dataflow.py pins."""
    from shadow_tpu.analysis import hlo_audit

    run, state, stop = hlo_audit._build(name)
    return estimate_text(hlo_audit.lower_text(run, state, stop))


# ------------------------------------------------------------- budgets


def load_budgets(path: str = BUDGETS_PATH) -> dict[str, dict]:
    if not os.path.exists(path):
        return {}
    with open(path, "r", encoding="utf-8") as fh:
        return json.load(fh).get("budgets", {})


def save_budgets(estimates: dict[str, dict],
                 path: str = BUDGETS_PATH) -> dict[str, dict]:
    data = {
        "version": 1,
        "comment": "peak-live estimates per config (hlo_graph liveness "
                   "over the lowered window loop); regenerate with "
                   "`python -m shadow_tpu.tools.lint --mem-audit "
                   "--update-baseline`",
        "budgets": {k: estimates[k] for k in sorted(estimates)},
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(data, fh, indent=1)
        fh.write("\n")
    return data["budgets"]


def audit_all(names: Iterable[str] | None = None,
              budgets: dict | None = None) -> dict[str, dict]:
    """Estimate each config and check it against the checked-in
    budgets. A config over budget, or missing from the budget file,
    fails; an estimate *under* budget passes (improvements land
    silently, `--diff` reports the drift)."""
    budgets = load_budgets() if budgets is None else budgets
    out: dict[str, dict] = {}
    for name in (names or MEM_CONFIGS):
        try:
            est = estimate_config(name)
        except RuntimeError as e:
            out[name] = {"ok": True, "skipped": str(e),
                         "violations": [], "estimate": {}}
            continue
        budget = budgets.get(name)
        violations: list[str] = []
        if budget is None:
            violations.append(
                f"{name}: no entry in MEM_BUDGETS.json — run "
                f"--mem-audit --update-baseline to pin it")
        elif est["peak_bytes"] > budget["peak_bytes"]:
            violations.append(
                f"{name}: peak-live estimate {est['peak_bytes']} bytes "
                f"exceeds budget {budget['peak_bytes']} — the window "
                f"loop grew; re-pin deliberately with --update-baseline")
        out[name] = {"ok": not violations, "violations": violations,
                     "estimate": est, "budget": budget}
    return out

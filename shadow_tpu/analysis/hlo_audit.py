"""HLO contract auditor: the lowering invariants, declared and checked.

The paper's performance story rests on what the window loop lowers to
(ROADMAP.md invariants): a single fused XLA program, sort-based queue
maintenance with no scatter in the unsharded hot path, no host
callbacks inside the loop, and byte-identical HLO when optional
subsystems (trace ring, spill ring, faults) are off. Until now those
were checked by ad-hoc string asserts copy-pasted across test files;
this module makes them declared contracts:

- `CONTRACTS` maps each model config to an `HloContract` (per-op
  budgets, custom-call allowlist, host-callback ban). The raw phold
  engine must be scatter-free; config-driven models get a small scatter
  budget for the TCP accept/bind row-slot updates in `host/sockets.py`
  (bounded, outside the per-event fast path). Budgets are checked
  against the structural op graph (`hlo_graph.parse_module`), so ops
  in dead private helper funcs never count and quoted custom_call
  targets (`@"..."`) resolve — the flat-regex predecessor had both
  blind spots.
- `audit_model(name)` builds a tiny instance of the config, lowers
  `Engine.run`, and returns violations against the contract.
- `phold_sharded` is the SPMD contract: the sharded PHOLD window loop
  lowered over an 8-device mesh (forced CPU devices in CI), with an
  explicit collective-op budget so exchange-op creep is regression-
  guarded the same way scatter creep is, and an allowlist holding
  exactly the GSPMD partitioning markers (`@Sharding`,
  `@SPMDFullToShardShape`, `@SPMDShardToFullShape`) — host callbacks
  stay banned in the sharded lowering too.
- `assert_no_recompile(fn, calls)` guards the one-program claim via
  jit cache inspection.
- `assert_zero_cost(base, off, on, stop)` is the single zero-cost
  checker (leaf count + pytree structure + checkpoint leaf paths +
  byte-identical lowered text) shared by the trace/pressure/faults
  test suites.

CLI: ``python -m shadow_tpu.tools.lint --hlo-audit all``.
"""

from __future__ import annotations

import dataclasses
from collections import Counter
from typing import Any, Callable, Iterable

from shadow_tpu.analysis import hlo_graph

# Ops that move control to the host (or to an opaque callback) — never
# acceptable inside the window loop under any budget.
HOST_CALLBACK_OPS = frozenset({
    "infeed", "outfeed", "send", "recv",
})
HOST_CALLBACK_TARGETS = (
    "xla_python_cpu_callback",
    "xla_python_gpu_callback",
    "xla_ffi_python_cpu_callback",
    "CallbackCustomCall",
)


@dataclasses.dataclass(frozen=True)
class HloContract:
    """Declared lowering budget for one model config.

    `budgets` caps specific op counts (0 forbids outright); any op not
    listed is unconstrained. `custom_call_allow` lists permitted
    custom_call targets; every other target is a violation. Host
    callbacks (infeed/outfeed/send/recv + python-callback custom
    calls) are always forbidden.
    """

    name: str
    budgets: dict  # op name -> max count
    custom_call_allow: tuple = ()

    def check(self, text: str) -> list[str]:
        return audit_text(text, self)


def ops_histogram(text: str) -> Counter:
    """Per-instance counts of dialect ops reachable from the entry
    func (dead private helpers excluded — structural, not textual)."""
    return hlo_graph.parse_module(text).histogram()


def custom_call_targets(text: str) -> list[str]:
    """Reachable custom_call targets. `call_target_name = "x"` is
    authoritative when present (the `@x` on such a line is just the
    op's pretty-printed symbol); otherwise the `@x` / quoted `@"x"`
    symbol of the stablehlo pretty form counts."""
    return hlo_graph.parse_module(text).custom_call_targets()


def audit_graph(module: hlo_graph.Module,
                contract: HloContract) -> list[str]:
    """Check a parsed op graph against a contract; [] means clean."""
    hist = module.histogram()
    violations: list[str] = []
    for op, cap in sorted(contract.budgets.items()):
        n = hist.get(op, 0)
        if n > cap:
            violations.append(
                f"{contract.name}: {n}x stablehlo.{op} exceeds budget "
                f"{cap}")
    for op in sorted(HOST_CALLBACK_OPS):
        if hist.get(op, 0):
            violations.append(
                f"{contract.name}: host-transfer op stablehlo.{op} in "
                f"lowered program")
    targets = module.custom_call_targets()
    for t in targets:
        if t in HOST_CALLBACK_TARGETS:
            violations.append(
                f"{contract.name}: host-callback custom_call `{t}`")
        elif t not in contract.custom_call_allow:
            violations.append(
                f"{contract.name}: custom_call target `{t}` not in "
                f"allowlist {sorted(contract.custom_call_allow)}")
    return violations


def audit_text(text: str, contract: HloContract) -> list[str]:
    """Check lowered IR text against a contract; [] means clean."""
    return audit_graph(hlo_graph.parse_module(text), contract)


# The raw engine (no socket stack) must stay scatter-free — the queue
# is maintained by sorts alone (ROADMAP invariant). Config-driven
# models lower one scatter per (host_row, slot) socket-table update
# site in host/sockets.py and the app models (accept/bind/stream
# bookkeeping): the count is structural — per traced update site, not
# per host or per event — so it is pinned exactly at today's value per
# config. A failing budget means a new scatter entered the window loop;
# either hoist it to sort/where form or consciously raise the budget
# here with a comment. (Budgets were halved when the audit moved from
# regex counting to the op graph: the regex counted every scatter
# twice — once for the op, once for its `#stablehlo.scatter<...>`
# dimension_numbers attribute.)
def _budget(scatter: int) -> dict:
    return {"scatter": scatter, "select_and_scatter": 0, "custom_call": 0}


# The number of forced-CPU devices the sharded contract lowers over
# (the tests' conftest and measure_all.sh both force this count).
SHARDED_DEVICES = 8

CONTRACTS: dict[str, HloContract] = {
    "phold": HloContract("phold", _budget(0)),
    "phold_net": HloContract("phold_net", _budget(4)),
    "tgen": HloContract("tgen", _budget(11)),
    "tor": HloContract("tor", _budget(7)),
    "bitcoin": HloContract("bitcoin", _budget(21)),
    # The same configs under the frontier drain (ISSUE 13 model-tier
    # batching). Budgets pinned equal to the chained contracts: the
    # frontier executor is built on sort / one-hot select / dynamic
    # slice only, so switching drains must add NO scatter — a frontier
    # budget above its chained twin means per-position bookkeeping
    # regressed into scattered writes.
    "tgen_frontier": HloContract("tgen_frontier", _budget(11)),
    "tor_frontier": HloContract("tor_frontier", _budget(7)),
    "bitcoin_frontier": HloContract("bitcoin_frontier", _budget(21)),
    # The vmapped fleet lowering (ISSUE 15 scenario fleets): the same
    # window loops batched over a 4-lane seed sweep. Budgets are pinned
    # EQUAL to the solo contracts — batching a program over scenario
    # lanes must add no scatter (vmap maps sort->sort, gather->gather,
    # scatter->scatter with a leading batch dim; the lane binds are
    # plain traced operands), and the op counts are lane-count-
    # independent (tests/test_fleet.py compares L=1 vs L=4 histograms).
    # A fleet budget above its solo twin means lane batching regressed
    # into per-lane bookkeeping writes.
    "phold_fleet": HloContract("phold_fleet", _budget(0)),
    "tgen_fleet": HloContract("tgen_fleet", _budget(11)),
    # The serve warm path (ISSUE 17 resident serving): the fleet's
    # fixed-window lane step under per-lane stops — the program
    # `Fleet.step_window` jits once and the service re-invokes per
    # request batch via `make_inputs`. Budget pinned equal to the
    # phold fleet contract: giving each lane its own traced stop adds
    # one vmap axis on a scalar, which must add NO scatter.
    "phold_serve": HloContract("phold_serve", _budget(0)),
    # The SPMD lowering of the raw PHOLD window loop over an 8-device
    # mesh. Every count is structural (per traced site x per Events
    # leaf), none scale with hosts or events:
    # - scatter 14: the exchange's [S, R] route-bucket build
    #   (`.at[row, col].set(mode="drop")` over the 6 Events leaves)
    #   plus the sent-mask update — per exchange ROUND, outside the
    #   per-event path. The drain itself stays sort-based.
    # - all_to_all 12: one per Events leaf per traced exchange site
    #   (the bucketed cross-shard delivery).
    # - all_reduce 12: the carried drain/exchange flags and the pmin
    #   window barrier — computed in loop BODIES; the companion
    #   test (test_spmd.py) asserts none sits in a while predicate.
    # A count above budget means a new collective or scatter entered
    # the sharded hot path; below budget, re-pin with a comment.
    "phold_sharded": HloContract(
        "phold_sharded",
        {"scatter": 14, "select_and_scatter": 0,
         "all_to_all": 12, "all_reduce": 12,
         "collective_permute": 0, "all_gather": 0},
        custom_call_allow=(
            "Sharding", "SPMDFullToShardShape", "SPMDShardToFullShape",
        ),
    ),
}


# ----------------------------------------------------------- lowering


def lower_text(run: Callable, state: Any, stop) -> str:
    """StableHLO text of jit(run) lowered at (state, stop)."""
    import jax

    return jax.jit(run).lower(state, stop).as_text()  # shadowlint: no-donate=lowering for inspection only; donation would add input_output_alias lines to every audited contract


def _build(name: str):
    """(run, state, stop) for a tiny instance of a model config.

    Sizes are the smallest that exercise the full drain/exchange path;
    the audit checks op structure, which is size-independent.
    """
    import jax.numpy as jnp

    if name == "phold":
        from shadow_tpu.models import phold

        eng, init = phold.build(8, seed=3, capacity=32, msgs_per_host=2)
        return eng.run, init(), jnp.int64(5_000_000_000)

    if name == "phold_fleet":
        from shadow_tpu.models import phold
        from shadow_tpu.runtime.fleet import build_fleet_from_engine

        eng, init = phold.build(8, seed=3, capacity=32, msgs_per_host=2)
        fleet = build_fleet_from_engine(
            eng, init(), 4, seeds=(0, 1, 2, 3)
        )
        return fleet.run_fn(), fleet.state0, jnp.int64(5_000_000_000)

    if name == "phold_serve":
        from shadow_tpu.models import phold
        from shadow_tpu.runtime.fleet import Fleet, FleetPlan

        eng, init = phold.build(8, seed=3, capacity=32, msgs_per_host=2)
        fleet = Fleet(eng, init(), FleetPlan(lanes=4, seeds=(0, 1, 2, 3)),
                      per_lane_stop=True)
        # the warm-path program: the fixed-window lane step the serving
        # plane re-invokes per packed batch (Fleet.step_window's
        # `_jit_step_fixed`), with per-lane [L] stops traced in
        import jax

        _, lane_step = fleet._make_lane_fns()
        stepped = jax.vmap(lambda s, bi, t: lane_step(s, bi, t, None),
                           in_axes=(0, 0, 0))
        binds = fleet.binds
        run = lambda st, stop: stepped(st, binds, stop)  # noqa: E731
        return run, fleet.state0, jnp.full((4,), jnp.int64(5_000_000_000))

    if name == "tgen_fleet":
        from shadow_tpu import examples
        from shadow_tpu.config import parse_config
        from shadow_tpu.sim import build_fleet, build_simulation

        sim = build_simulation(parse_config(examples.example_config()),
                               seed=3)
        fleet = build_fleet(sim, 4, seeds=(0, 1, 2, 3))
        return fleet.run_fn(), fleet.state0, jnp.int64(sim.stop_ns)

    if name == "phold_sharded":
        import jax

        from shadow_tpu.models import phold
        from shadow_tpu.parallel import mesh as pmesh

        n = SHARDED_DEVICES
        eng, init = phold.build(
            8, seed=3, capacity=32, msgs_per_host=2,
            axis_name=pmesh.HOSTS_AXIS, n_shards=n,
        )
        m = pmesh.make_mesh(n)  # raises RuntimeError when devices < n
        init_s, run, _ = pmesh.build_sharded(eng, init, m, 8)
        # abstract state: the audit inspects the lowering, never runs it
        return run, jax.eval_shape(init_s), jnp.int64(5_000_000_000)

    from shadow_tpu import examples
    from shadow_tpu.config import parse_config
    from shadow_tpu.sim import build_simulation

    # `<model>_frontier` lowers the identical config under the frontier
    # drain (docs/11-Performance.md "Model-tier batching") — a separate
    # contract because the window loop's body is a different program
    base, frontier = name, 0
    if name.endswith("_frontier"):
        base, frontier = name[: -len("_frontier")], 8

    if base == "phold_net":
        text = examples.phold_example(8, msgs_per_host=2, stoptime=5)
    elif base == "tgen":
        text = examples.example_config()
    elif base == "tor":
        text = examples.tor_example(n_relays_per_class=2, n_clients=4,
                                    n_servers=2, stoptime=5)
    elif base == "bitcoin":
        text = examples.bitcoin_example(n_nodes=8, blocks=1, stoptime=5)
    else:
        raise KeyError(f"unknown model config `{name}` "
                       f"(have {sorted(CONTRACTS)})")
    sim = build_simulation(parse_config(text), seed=3, frontier=frontier)
    return sim.engine.run, sim.state0, jnp.int64(sim.stop_ns)


def audit_model(name: str) -> tuple[str, list[str]]:
    """Lower one model config and audit it. Returns (text, violations)."""
    contract = CONTRACTS[name]
    run, state, stop = _build(name)
    text = lower_text(run, state, stop)
    return text, audit_text(text, contract)


def audit_all(names: Iterable[str] | None = None) -> dict[str, dict]:
    """Audit several configs; per-config dict has `violations` and the
    op histogram (for the JSON report)."""
    out: dict[str, dict] = {}
    for name in (names or sorted(CONTRACTS)):
        try:
            run, state, stop = _build(name)
            text = lower_text(run, state, stop)
        except RuntimeError as e:
            # the sharded contract needs SHARDED_DEVICES devices; on a
            # smaller host (no --xla_force_host_platform_device_count)
            # it is skipped, not failed
            out[name] = {"ok": True, "skipped": str(e),
                         "violations": [], "ops": {}}
            continue
        module = hlo_graph.parse_module(text)
        violations = audit_graph(module, CONTRACTS[name])
        hist = module.histogram()
        out[name] = {
            "ok": not violations,
            "violations": violations,
            "ops": {k: hist[k] for k in sorted(hist) if k in
                    ("scatter", "sort", "while", "gather", "custom_call",
                     "all_to_all", "all_reduce", "collective_permute",
                     "infeed", "outfeed", "send", "recv")},
        }
    return out


# ----------------------------------------------------- recompile guard


def assert_no_recompile(fn: Callable, calls: Iterable[tuple]) -> int:
    """Call jit(fn) across `calls` (same shapes/dtypes expected) and
    assert the jit cache holds exactly one entry — the one-program
    claim, checked rather than assumed."""
    import jax

    j = jax.jit(fn)
    for args in calls:
        jax.block_until_ready(j(*args))  # shadowlint: no-deadline=offline audit tool; no live mesh to lose
    size = j._cache_size()
    if size != 1:
        raise AssertionError(
            f"expected one compiled program, jit cache holds {size} — "
            f"an argument is changing shape/dtype/structure across calls")
    return size


# ----------------------------------------------------- zero-cost check


def _run_of(obj: Callable | Any) -> Callable:
    return obj.run if hasattr(obj, "run") else obj


def assert_zero_cost(base, off, on, stop, *, get_subtree=None) -> dict:
    """The centralized trace/spill/faults zero-cost check.

    `base`/`off`/`on` are (engine_or_run, state) pairs: `base` built
    with defaults, `off` with the subsystem explicitly disabled, `on`
    with it enabled. Asserts the off build is indistinguishable from
    the base build — same leaf count, same pytree structure, same
    checkpoint leaf paths, byte-identical lowered HLO — and that the
    on build actually lowers differently (so the check cannot pass
    vacuously). `get_subtree(state)` optionally points at the
    subsystem's state slot, asserted None when off / present when on.

    Returns {"base": text, "off": text, "on": text} for extra checks.
    """
    import jax

    from shadow_tpu.utils.checkpoint import _leaf_paths

    (eng_b, st_b), (eng_off, st_off), (eng_on, st_on) = base, off, on

    n_b = len(jax.tree.leaves(st_b))
    n_off = len(jax.tree.leaves(st_off))
    assert n_off == n_b, \
        f"off state has {n_off} leaves vs base {n_b} — the disabled " \
        f"subsystem still contributes pytree leaves"
    assert jax.tree.structure(st_off) == jax.tree.structure(st_b), \
        "off/base pytree structures differ"
    assert _leaf_paths(st_off) == _leaf_paths(st_b), \
        "off/base checkpoint leaf layouts differ"

    if get_subtree is not None:
        # state-carrying subsystems (trace ring, spill ring): the on
        # build must hold the subtree and grow the leaf set. Engine-
        # constant subsystems (faults) change only the program — pass
        # get_subtree=None for those.
        assert get_subtree(st_b) is None, \
            "base state carries the optional subsystem's subtree"
        assert get_subtree(st_off) is None, \
            "off state carries the optional subsystem's subtree"
        assert get_subtree(st_on) is not None, \
            "on state is missing the subsystem's subtree (check knobs)"
        assert len(jax.tree.leaves(st_on)) > n_b, \
            "on state added no leaves — the subsystem is not actually on"

    text_b = lower_text(_run_of(eng_b), st_b, stop)
    text_off = lower_text(_run_of(eng_off), st_off, stop)
    text_on = lower_text(_run_of(eng_on), st_on, stop)
    assert text_off == text_b, \
        "disabled subsystem changed the lowered program (zero-cost " \
        "violation — diff the returned texts)"
    assert text_on != text_b, \
        "enabled subsystem lowered identically to base — the zero-cost " \
        "check is vacuous"
    return {"base": text_b, "off": text_off, "on": text_on}

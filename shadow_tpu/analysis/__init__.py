"""Static analysis for the jitted hot path (docs/10-Static-Analysis.md).

Source-level and compiled-program layers; the lint layer is
importable without JAX side effects beyond what the package already
does at import:

- `shadow_tpu.analysis.lint`: an AST linter flagging the JAX footguns
  that have historically cost this codebase debugging time (tracer
  branches, host materialization inside jit, i32 sim-time truncation,
  PRNG key reuse, donation misuse at the call site, mutable default
  pytrees, unordered-iteration pytree hazards), with a checked-in
  baseline so accepted findings don't block the lint gate.
- `shadow_tpu.analysis.hlo_graph`: parses StableHLO pretty text into
  a structural op graph (funcs, regions, defs/uses, bytes-per-shape)
  — the substrate every audit below queries.
- `shadow_tpu.analysis.hlo_audit`: lowers the engine for each model
  config and checks the op graph against declared contracts (scatter
  budgets, custom-call allowlist, no host callbacks), plus the
  centralized zero-cost check shared by the trace/pressure/faults
  test suites.
- `shadow_tpu.analysis.donation`: compiles the production window-loop
  jits and verifies from `input_output_alias` that every donated
  carry leaf actually aliased, plus the harvest host-transfer census.
- `shadow_tpu.analysis.memory`: peak-live-buffer estimates per config
  from graph liveness, checked against `MEM_BUDGETS.json`.

CLI: ``python -m shadow_tpu.tools.lint`` (JSON findings, baseline
workflow, ``--hlo-audit`` / ``--donation-audit`` / ``--mem-audit`` /
``--diff``).
"""

from shadow_tpu.analysis.lint import (  # noqa: F401
    Finding,
    lint_package,
    lint_paths,
    lint_source,
    load_baseline,
    save_baseline,
    split_new,
)
from shadow_tpu.analysis.hlo_audit import (  # noqa: F401
    HloContract,
    CONTRACTS,
    assert_no_recompile,
    assert_zero_cost,
    audit_model,
    audit_text,
    ops_histogram,
)
from shadow_tpu.analysis.hlo_graph import (  # noqa: F401
    Module,
    bytes_of_type,
    dtype_bytes,
    parse_module,
)

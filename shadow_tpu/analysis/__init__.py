"""Static analysis for the jitted hot path (docs/10-Static-Analysis.md).

Two layers, both importable without JAX side effects beyond what the
package already does at import:

- `shadow_tpu.analysis.lint`: an AST linter flagging the JAX footguns
  that have historically cost this codebase debugging time (tracer
  branches, host materialization inside jit, i32 sim-time truncation,
  PRNG key reuse, mutable default pytrees, unordered-iteration pytree
  hazards), with a checked-in baseline so accepted findings don't
  block the lint gate.
- `shadow_tpu.analysis.hlo_audit`: lowers the engine for each model
  config and checks the StableHLO text against declared contracts
  (scatter budgets, custom-call allowlist, no host callbacks), plus
  the centralized zero-cost check shared by the trace/pressure/faults
  test suites.

CLI: ``python -m shadow_tpu.tools.lint`` (JSON findings, baseline
workflow, optional HLO audit).
"""

from shadow_tpu.analysis.lint import (  # noqa: F401
    Finding,
    lint_package,
    lint_paths,
    lint_source,
    load_baseline,
    save_baseline,
    split_new,
)
from shadow_tpu.analysis.hlo_audit import (  # noqa: F401
    HloContract,
    CONTRACTS,
    assert_no_recompile,
    assert_zero_cost,
    audit_model,
    audit_text,
    ops_histogram,
)

"""Simulation assembly: parsed config -> engine + device state + run loop.

This is the TPU-era Master/Slave bootstrap (reference:
src/main/core/master.c:271-448 `_master_registerPlugins/_master_registerHosts`
-> slave_addNewVirtualHost -> host_new/host_setup -> scheduler_addHost):
load the topology, expand and attach hosts, register DNS names, size the
NICs, let the app model bind its sockets and schedule its process start
events, then compile everything into one Engine whose handler table is
[stack pipeline | TCP machinery | app kinds].

Where the reference walks XML into heap objects and pthread queues, this
builder walks the same config into struct-of-arrays device state; where
the reference's hosts are partitioned across worker threads by random
shuffle (scheduler.c:440-534), hosts here are block-partitioned across the
device mesh axis by dense gid.
"""

from __future__ import annotations

import dataclasses
import weakref
from typing import Any, Callable, Protocol

import jax
import jax.numpy as jnp
import numpy as np

from shadow_tpu.config import (
    HostInstance,
    ShadowConfig,
    expand_hosts,
    resolve_path,
)
from shadow_tpu.core.engine import Engine, EngineConfig
from shadow_tpu.core.events import Events
from shadow_tpu.core.timebase import MILLISECOND, SECOND, TIME_INVALID
from shadow_tpu.net.dns import DNS
from shadow_tpu.net.topology import Topology
from shadow_tpu.transport.stack import N_PKT_ARGS, SimHost, Stack, HostNet
from shadow_tpu.transport.tcp import TCP

DEFAULT_BANDWIDTH_KIB = 10240  # when neither host attr nor vertex attr set

# Virtual-CPU model: every executed event costs this many cycles on the
# host's configured CPU (the reference scales measured wall time by
# rawFrequency/virtualFrequency, cpu.c:56-107; with jitted handlers there
# is no wall time to measure, so a fixed per-event cycle budget stands in).
CPU_CYCLES_PER_EVENT = 10_000


@dataclasses.dataclass
class SimBuild:
    """Mutable build context handed to the app model.

    The app reads per-host process specs/arguments, resolves peer names
    through `dns`, binds listen sockets into `sockets`/`tcb`, and appends
    process start events (starttime semantics of the <process> element).

    `hosts` is the subset of hosts the *current* model owns (all hosts in
    a single-model simulation); per-host arrays must still be sized
    `n_hosts` = the full host count, indexed by `HostInstance.gid`.
    """

    cfg: ShadowConfig
    hosts: list[HostInstance]
    dns: DNS
    topo: Topology
    n_sockets: int
    sockets: Any  # SocketTable [H, S]
    tcb: Any  # transport.tcp.TCB [H, S] or None
    start_events: list[tuple[int, int, int, list[int]]] = dataclasses.field(
        default_factory=list
    )  # (time_ns, gid, kind_rel, args words)
    n_total: int = 0  # full host count (len(hosts) when single-model)
    kind_offset: int = 0  # current model's kind base relative to the apps'

    @property
    def n_hosts(self) -> int:
        return self.n_total or len(self.hosts)

    def resolve_gid(self, name: str) -> int:
        addr = self.dns.resolve_name(name)
        if addr is None:
            raise ValueError(f"unknown hostname in config: {name!r}")
        return addr.host_id

    def add_start_event(self, gid: int, time_s: float, kind_rel: int,
                        args: list[int] | None = None) -> None:
        self.start_events.append(
            (int(time_s * SECOND), gid, self.kind_offset + kind_rel,
             list(args or []))
        )


class AppModel(Protocol):
    """A jitted application compiled into the device step (the fast tier
    of SURVEY.md §7 step 6: the analog of a plugin binary is a handler
    table + static per-host config arrays)."""

    name: str
    needs_tcp: bool
    n_kinds: int

    def app_rows(self) -> int:
        """Emit rows the on_recv callback returns (for max_emit sizing)."""
        ...

    def handler_rows(self) -> int:
        """Max Emit rows any of the app's own kind handlers returns."""
        ...

    def build(self, b: SimBuild) -> tuple[Any, Callable, Callable | None]:
        """-> (app_state [H,...], make_handlers(stack, kind_base) ->
        [handlers], on_recv or None)."""
        ...


@dataclasses.dataclass
class Simulation:
    """A built, runnable simulation.

    With `mesh` set, hosts are block-partitioned over the 1-D "hosts" mesh
    axis (gid // per_shard = owning shard — the TPU-era version of the
    reference's host→thread assignment, scheduler.c:440-534) and run/step
    execute under shard_map: the window barrier is lax.pmin across shards
    and cross-shard packet delivery rides the engine's all_to_all exchange.
    """

    engine: Engine
    state0: Any  # EngineState
    stop_ns: int
    dns: DNS
    topo: Topology
    names: list[str]
    app: Any  # the AppModel instance
    stack: Stack
    mesh: Any = None  # jax.sharding.Mesh when sharded
    # requested SPMD lowering for the sharded paths: "auto" resolves via
    # parallel.mesh.select_spmd (shard_map on every supported jax;
    # "constraint" = jit + explicit NamedShardings over a GLOBAL engine,
    # GSPMD inserts the collectives; "pmap" = the legacy 1-D fallback,
    # kept alive for soak comparison). See `spmd_path` for the resolved
    # value and docs/12-Sharding.md for the selection matrix.
    spmd: str = "auto"
    pcap_gids: tuple = ()  # hosts with logpcap set
    pcap_dir: str = "shadow.pcap.d"  # from the pcapdir host attr
    kind_names: tuple = ()  # handler-kind names (object-counter labels)
    faults: Any = None  # CompiledFaults when the config schedules any
    # WindowProfiler (shadow_tpu.obs) when built with profiling on: the
    # jitted step phase is timed here (the un-jitted skeleton around it —
    # drains, pump, checkpoints — is timed by the CLI / process tier),
    # and summary() grows a "profile" key
    profiler: Any = None

    # queue-overflow handling (docs/9-Queue-Pressure.md): "drop" keeps
    # the historical counted-drop behavior (with strict_overflow's loud
    # RuntimeError), "strict" raises QueuePressureError at the first
    # drop, "spill"/"grow" run losslessly via the attached
    # PressureController (runtime.pressure) — run() then steps window by
    # window so the controller can harvest/refill at every boundary
    overflow: str = "drop"
    pressure: Any = None  # PressureController for spill/grow modes

    # the host permutation applied at build time (position i holds the
    # config host formerly known as gid host_order[i]): the locality
    # layout when `locality=True`, a checkpoint's stored order on
    # reshard-resume, None for plain config order. Recorded in v6
    # checkpoints so a resume on a DIFFERENT shard count can force the
    # writer's layout instead of recomputing a shard-count-dependent
    # locality_order (docs/13-Elastic-Recovery.md).
    host_order: tuple | None = None

    _jit_run: Any = None
    _jit_step: Any = None
    _jit_step_w: Any = None  # traced-window variant (--window auto)
    _owned: Any = None  # weak id-map of donation-safe states we produced

    @property
    def spmd_path(self) -> str | None:
        """The EXECUTED sharding path: None (single device), "shard_map",
        "constraint", or "pmap". This is what tests assert on — no
        jax.pmap runs unless this says so."""
        if self.mesh is None:
            return None
        from shadow_tpu.parallel.mesh import select_spmd

        return select_spmd(self.spmd)

    def _wrap(self, fn):
        """Jit `fn(state, stop, host0)`, under the selected SPMD path
        when sharded.

        The state argument is DONATED: the [H, C] queue arrays, staging
        buffers, and trace/spill rings alias the outputs instead of
        being copied on every call — which is once per *window* on the
        window-stepped paths (pressure boundaries, the process tier, the
        CLI heartbeat loop). Callers own the consequence: a state passed
        into run()/step_window() is consumed (its buffers are deleted),
        so `state0` is defended by copy in run()/step_window() and
        external callers must re-chain the returned state, never reuse
        the input. Donation changes only input/output aliasing, not the
        computation: `assert_zero_cost` HLO identities compare donated
        builds against donated builds and hold unchanged."""
        if self.mesh is None:
            return jax.jit(lambda st, stop: fn(st, stop, 0), donate_argnums=0)
        from jax.sharding import PartitionSpec as P

        from shadow_tpu.parallel.mesh import (
            hosts_axes, shard_map, state_specs,
        )

        axes = hosts_axes(self.mesh)
        per = self.engine.cfg.n_hosts
        # state0 leaves are global-shaped; sharding splits the leading
        # host dim across the axis (or axis tuple for multi-slice)
        specs = state_specs(
            self.state0, per * self.engine.cfg.n_shards, axes
        )
        path = self.spmd_path

        if path == "pmap":
            from shadow_tpu.parallel.mesh import pmap_call

            # no donation on the pmap fallback: jax.pmap's donation is
            # per-device-buffer and interacts badly with the fallback's
            # reshape/stack plumbing on old jax pins; the fallback is a
            # compatibility path, not the perf path
            return pmap_call(fn, self.mesh, specs, per, axes)

        if path == "constraint":
            # GSPMD path: the engine is GLOBAL (axis_name=None — it runs
            # no manual collectives), the state is pinned to the mesh by
            # explicit NamedShardings, and the partitioner inserts the
            # cross-device movement. Bit-identity with single-device is
            # structural: this IS the single-device program.
            from jax.sharding import NamedSharding

            shardings = jax.tree.map(
                lambda sp: NamedSharding(self.mesh, sp), specs
            )

            def constrained(st, stop):
                st = jax.lax.with_sharding_constraint(st, shardings)
                return fn(st, stop, 0)

            return jax.jit(
                constrained,
                in_shardings=(shardings, None),
                out_shardings=shardings,
                donate_argnums=0,
            )

        def sharded(st, stop):
            host0 = jax.lax.axis_index(axes).astype(jnp.int32) * per
            return fn(st, stop, host0)

        return jax.jit(
            shard_map(
                sharded,
                mesh=self.mesh,
                in_specs=(specs, P()),
                out_specs=specs,
                check_vma=False,
            ),
            donate_argnums=0,
        )

    strict_overflow: bool = True

    def run(self, stop_ns: int | None = None, state=None):
        """Jit-run to the stop time; returns the final EngineState.

        The jitted callables are cached on the instance so repeated calls
        (the CLI's heartbeat loop, checkpoint-interval stepping) reuse one
        compiled executable instead of retracing.

        Queue overflow is loud by default: the reference's event heaps are
        unbounded (src/main/utility/priority_queue.c), so silently dropping
        events on a full fixed-capacity queue would corrupt simulation
        semantics mid-run. Set strict_overflow=False to accept counted
        drops instead (they remain visible in queues.drops).

        The jitted step DONATES its state input (see `_wrap`): a state
        passed via `state=` is consumed. `state0` itself is defended by
        a device-side copy so a Simulation stays re-runnable.
        """
        st = self._fresh_state(state)
        stop = jnp.int64(stop_ns if stop_ns is not None else self.stop_ns)
        if self.pressure is not None:
            # spill/grow: the controller must see every window boundary,
            # or an evicted event could miss the window it is due in —
            # so run window-stepped instead of one fused device loop.
            # The frontier probe and the controller's spill cursor fetch
            # share one batched device_get per window (the boundary's
            # idle probe would otherwise force a second round-trip).
            out = self._note_owned(st)
            stop_i = int(stop)
            now = int(jax.device_get(out.now))  # shadowlint: no-deadline=library run() path; the supervised CLI uses HeartbeatHarvest
            while now < stop_i:
                out = self.step_window(out, stop_i)
                now, wr = jax.device_get((out.now, out.queues.spill.wr))  # shadowlint: no-deadline=library run() path; the supervised CLI uses HeartbeatHarvest
                out = self._note_owned(
                    self.pressure.boundary(out, wr=np.asarray(wr))
                )
                now = int(now)
            return out
        if self._jit_run is None:
            object.__setattr__(self, "_jit_run", self._wrap(self.engine.run))
        if self.profiler is not None:
            with self.profiler.phase("step"):
                out = self._jit_run(st, stop)
                out.now.block_until_ready()  # shadowlint: no-deadline=library run() path; the supervised CLI uses HeartbeatHarvest
        else:
            out = self._jit_run(st, stop)
        out = self._note_owned(out)
        if self.overflow == "strict" or self.strict_overflow:
            drops = int(jax.device_get(out.queues.drops.sum()))  # shadowlint: no-deadline=library run() path; the supervised CLI uses HeartbeatHarvest
            if drops > 0:
                self.check_drops(drops, self.summary(out))
        return out

    def check_drops(self, drops: int, summary: dict | None = None):
        """Apply the loud-overflow contract to an already-fetched drop
        count. run() probes the count itself; the overlapped CLI loop
        reads it from its heartbeat-harvest bundle instead (the probe
        would be a second sync) and calls this with the fetched value."""
        if int(drops) <= 0:
            return
        if self.overflow == "strict":
            from shadow_tpu.runtime.pressure import QueuePressureError

            raise QueuePressureError(
                int(drops), self.engine.cfg.capacity, summary or {}
            )
        if self.strict_overflow:
            raise RuntimeError(
                f"event queue overflow: {int(drops)} events dropped "
                f"(per-host capacity {self.engine.cfg.capacity}); rerun "
                "with a larger --capacity, or set strict_overflow=False "
                "to accept counted drops"
            )

    def build_fleet(self, lanes: int, **overrides):
        """Batch `lanes` scenario variants of this simulation into one
        vmapped Fleet program — see the module-level `build_fleet`."""
        return build_fleet(self, lanes, **overrides)

    def dispatch(self, stop_ns: int, state, window_ns: int | None = None):
        """Asynchronously dispatch the next segment; returns the chained
        state WITHOUT any host<->device sync.

        The async half of the CLI's depth-1 dispatch-ahead: jax queues
        the computation on the backend and returns immediately, so the
        host can consume the previous heartbeat's fetched bundle while
        the device works. No profiler barrier (the CLI times the fetch
        wait instead), no overflow probe (`check_drops` runs on the
        harvest bundle's count). `window_ns` selects the traced-window
        step (one window per call — the adaptive controller decides
        between windows); None dispatches the fused run-to-stop loop.
        Pressure modes need run()'s window-boundary refills and are not
        dispatchable."""
        if self.pressure is not None:
            raise ValueError(
                "dispatch() cannot run spill/grow pressure modes; their "
                "reservoir refills are host-side window-boundary work — "
                "use run()"
            )
        st = self._fresh_state(state)
        stop = jnp.int64(stop_ns)
        if window_ns is None:
            if self._jit_run is None:
                object.__setattr__(
                    self, "_jit_run", self._wrap(self.engine.run)
                )
            return self._note_owned(self._jit_run(st, stop))
        self._ensure_step_w()
        return self._note_owned(
            self._jit_step_w(st, stop, jnp.int64(window_ns))
        )

    def _fresh_state(self, state):
        """Resolve the state argument for a donating jit call.

        Only states this Simulation itself produced (tracked weakly by
        identity) pass through to be donated in place — those are
        XLA-owned jit outputs, safe to alias. Everything else is copied
        first: `state0` so the Simulation stays re-runnable, and foreign
        states (checkpoint restores, test-built states) because
        `jnp.asarray` ZERO-COPIES aligned numpy arrays on CPU — donating
        such a leaf would let XLA write into (and alias outputs onto)
        memory numpy still owns, a use-after-free once the numpy side
        drops it. The copy is once per entry, never per window: chained
        step outputs are owned and flow through untouched."""
        if (
            state is not None
            and self._owned is not None
            and self._owned.get(id(state)) is state
        ):
            return state
        src = self.state0 if state is None else state
        return jax.tree.map(
            lambda x: jnp.copy(x) if isinstance(x, jax.Array) else x, src
        )

    def _note_owned(self, state):
        """Mark `state` as a donation-safe product of this Simulation's
        own jits (see `_fresh_state`); returns it for chaining."""
        if self._owned is None:
            object.__setattr__(self, "_owned", weakref.WeakValueDictionary())
        self._owned[id(state)] = state
        return state

    def step_window(self, state, stop_ns: int | None = None,
                    window_ns: int | None = None):
        """Advance one window; the input state is consumed (donated).

        `window_ns` widens the conservative window bound past
        cfg.lookahead as a TRACED scalar — causally safe but with the
        --runahead timing tradeoff (core.engine._advance); the
        adaptive-window controller retunes it between windows with
        zero recompiles. None keeps the fixed cfg.lookahead bound, the
        byte-identical default lowering, and bit-identical results.
        """
        state = self._fresh_state(state)
        stop = jnp.int64(stop_ns if stop_ns is not None else self.stop_ns)
        if window_ns is None:
            if self._jit_step is None:
                object.__setattr__(
                    self, "_jit_step", self._wrap(self.engine.step_window)
                )
            args = (state, stop)
            jit_step = self._jit_step
        else:
            self._ensure_step_w()
            args = (state, stop, jnp.int64(window_ns))
            jit_step = self._jit_step_w
        if self.profiler is not None:
            with self.profiler.phase("step"):
                out = jit_step(*args)
                out.now.block_until_ready()  # shadowlint: no-deadline=library run() path; the supervised CLI uses HeartbeatHarvest
            return self._note_owned(out)
        return self._note_owned(jit_step(*args))

    def _ensure_step_w(self):
        """Build the traced-window step jit once (--window N / auto)."""
        if self._jit_step_w is not None:
            return
        if self.spmd_path == "pmap":
            raise ValueError(
                "adaptive windows (--window auto) need the shard_map or "
                "constraint SPMD path; the pmap fallback runs fixed "
                "windows only (selected spmd='pmap')"
            )
        if self.mesh is None:
            jsw = jax.jit(
                lambda st, stop, w: self.engine.step_window(
                    st, stop, 0, window=w
                ),
                donate_argnums=0,
            )
        else:
            jsw = self._wrap_windowed()
        object.__setattr__(self, "_jit_step_w", jsw)

    def _wrap_windowed(self):
        """shard_map wrapper for the traced-window step (mesh path)."""
        from jax.sharding import PartitionSpec as P

        from shadow_tpu.parallel.mesh import (
            hosts_axes, shard_map, state_specs,
        )

        axes = hosts_axes(self.mesh)
        per = self.engine.cfg.n_hosts
        specs = state_specs(
            self.state0, per * self.engine.cfg.n_shards, axes
        )

        if self.spmd_path == "constraint":
            from jax.sharding import NamedSharding

            shardings = jax.tree.map(
                lambda sp: NamedSharding(self.mesh, sp), specs
            )

            def constrained(st, stop, w):
                st = jax.lax.with_sharding_constraint(st, shardings)
                return self.engine.step_window(st, stop, 0, window=w)

            return jax.jit(
                constrained,
                in_shardings=(shardings, None, None),
                out_shardings=shardings,
                donate_argnums=0,
            )

        def sharded(st, stop, w):
            host0 = jax.lax.axis_index(axes).astype(jnp.int32) * per
            return self.engine.step_window(st, stop, host0, window=w)

        return jax.jit(
            shard_map(
                sharded,
                mesh=self.mesh,
                in_specs=(specs, P(), P()),
                out_specs=specs,
                check_vma=False,
            ),
            donate_argnums=0,
        )

    def summary(self, state) -> dict:
        """Host-side progress snapshot (frontier time, window count,
        executed events) — what the supervised run loop pets its
        watchdog with and the stall bundle records; see
        core.engine.state_summary. With a profiler attached, grows a
        "profile" key (wall-clock phase aggregates + occupancy —
        stripped from determinism diffs by tools/strip_log.py)."""
        from shadow_tpu.core.engine import state_summary

        out = state_summary(state)
        if self.profiler is not None:
            out["profile"] = self.profiler.summary()
        if self.pressure is not None:
            snap = self.pressure.snapshot(state)
            out["refilled"] = snap.get("refilled", 0)
            out["reservoir"] = snap.get("resident", 0)
            out["overdue"] = snap.get("overdue", 0)
        return out

    def metrics_refs(self, state) -> dict:
        """Device-array refs for the live-telemetry extras (net drops,
        fault drops, cross-shard traffic, socket byte totals) — the
        reductions `HeartbeatHarvest` embeds in its bundle under
        `--metrics`. Exposed here for the one-off fetch the CLI's
        --overflow grow re-template path does after rebuilding (the
        rebuilt harvest hasn't extracted yet at that boundary)."""
        from shadow_tpu.obs.metrics import metrics_device_refs

        return metrics_device_refs(state)


def _plugin_tokens(cfg: ShadowConfig, plugin_id: str) -> set[str]:
    """Registry-matchable name tokens for a plugin: its id plus its path
    basename, split on separators (the reference identifies plugins purely
    by id but test configs name them after their .so, e.g.
    'shadow-plugin-test-phold'). Whole-token matching keeps registry names
    like 'tor' from matching inside unrelated words ('monitor')."""
    import re

    spec = cfg.plugin_by_id(plugin_id)
    names = [plugin_id] + ([spec.path.rsplit("/", 1)[-1]] if spec else [])
    toks: set[str] = set()
    for n in names:
        toks.update(t for t in re.split(r"[^a-z0-9]+", n.lower()) if t)
    return toks


def resolve_app_models(
    cfg: ShadowConfig, registry: dict[str, Callable], hosts: list[HostInstance]
):
    """Map every host's processes to registered app models.

    Returns [(name, model_instance, owned_host_list)] in first-appearance
    order. A host whose processes span two different models is rejected
    (each host's state rows belong to exactly one model).
    """
    owner: dict[int, str] = {}
    order: list[str] = []
    for h in hosts:
        for p in h.spec.processes:
            toks = _plugin_tokens(cfg, p.plugin)
            for regname in registry:
                if regname in toks:
                    break
            else:
                raise ValueError(
                    f"no app model registered for plugin {p.plugin!r} "
                    f"(known: {sorted(registry)})"
                )
            if owner.setdefault(h.gid, regname) != regname:
                raise ValueError(
                    f"host {h.name!r} mixes app models "
                    f"{owner[h.gid]!r} and {regname!r}"
                )
            if regname not in order:
                order.append(regname)
    return [
        (name, registry[name](),
         [h for h in hosts if owner.get(h.gid) == name])
        for name in order
    ]


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class MultiApp:
    """Fused app state: every sub-model's [H]-leading state side by side,
    plus the per-host owning-model index for receive dispatch."""

    model_id: jax.Array  # i32[H]
    subs: tuple


class FusedModel:
    """Handler-table fusion of several app models (lifts the round-1
    one-model-per-simulation limit).

    Kinds are laid out [stack | model0 kinds | model1 kinds | ...]; each
    sub-model's handlers run against its own state slice (the rest of the
    MultiApp rides along untouched), and packet deliveries dispatch to the
    receiving host's owning model via lax.switch on model_id.
    """

    def __init__(self, parts):  # [(name, model, owned_hosts)]
        self.parts = parts
        self.name = "+".join(name for name, _, _ in parts)
        self.needs_tcp = any(m.needs_tcp for _, m, _ in parts)
        self.n_kinds = sum(m.n_kinds for _, m, _ in parts)

    def app_rows(self) -> int:
        return max(m.app_rows() for _, m, _ in self.parts)

    def handler_rows(self) -> int:
        return max(m.handler_rows() for _, m, _ in self.parts)

    def cpu_kind_cycles(self, n_kinds: int):
        """Sum the parts' per-(host, kind) cycle tables: a fused model
        must not silently drop a part's declared CPU charges (e.g. Tor
        relay crypto) — the accepted-but-ignored failure mode this
        codebase elsewhere hard-errors on. Each part's table is already
        host-masked (rows it doesn't own are zero), so summation is the
        exact composition."""
        total = None
        for _, m, _ in self.parts:
            if not hasattr(m, "cpu_kind_cycles"):
                continue
            cy = m.cpu_kind_cycles(n_kinds)
            if cy is None:
                continue
            total = cy if total is None else total + cy
        return total

    def build(self, b: SimBuild):
        n = b.n_hosts
        model_id = np.zeros((n,), np.int32)
        subs, makers, recvs = [], [], []
        offset = 0
        for i, (name, model, owned) in enumerate(self.parts):
            for h in owned:
                model_id[h.gid] = i
            sub_b_hosts = b.hosts
            b.hosts = owned
            b.kind_offset = offset
            state_i, make_i, recv_i = model.build(b)
            b.hosts = sub_b_hosts
            subs.append(state_i)
            makers.append(make_i)
            recvs.append(recv_i)
            offset += model.n_kinds
        b.kind_offset = 0
        self._recvs = recvs
        self._makers = makers
        state = MultiApp(
            model_id=jnp.asarray(model_id), subs=tuple(subs)
        )
        return state, self._make_handlers, self._on_recv

    def _sub_call(self, hs, i, fn, *args):
        """Run a sub-model callable against its own app-state slice."""
        hs_sub = dataclasses.replace(hs, app=hs.app.subs[i])
        out = fn(hs_sub, *args)
        hs2, em = out
        new_subs = tuple(
            hs2.app if j == i else hs.app.subs[j]
            for j in range(len(hs.app.subs))
        )
        hs2 = dataclasses.replace(
            hs2, app=MultiApp(model_id=hs.app.model_id, subs=new_subs)
        )
        return hs2, em

    def _make_handlers(self, stack, kind_base):
        rows = self.handler_rows()
        handlers = []
        offset = kind_base
        for i, ((name, model, _), make) in enumerate(
            zip(self.parts, self._makers)
        ):
            for fn in make(stack, offset):
                def wrapped(hs, ev, key, _i=i, _fn=fn):
                    hs2, em = self._sub_call(hs, _i, _fn, ev, key)
                    return hs2, em.pad_to(rows)
                handlers.append(wrapped)
            offset += model.n_kinds
        return handlers

    def _on_recv(self, hs, slot, pkt, now, key):
        rows = self.app_rows()
        branches = []
        for i, recv in enumerate(self._recvs):
            def mk(_i=i, _recv=recv):
                if _recv is None:
                    from shadow_tpu.core.engine import Emit

                    return lambda: (
                        hs, Emit.none(rows, N_PKT_ARGS)
                    )

                def br():
                    hs2, em = self._sub_call(
                        hs, _i, _recv, slot, pkt, now, key
                    )
                    return hs2, em.pad_to(rows)

                return br
            branches.append(mk())
        idx = jnp.clip(hs.app.model_id, 0, len(branches) - 1)
        return jax.lax.switch(idx, branches)


def build_simulation(
    cfg: ShadowConfig,
    registry: dict[str, Callable] | None = None,
    *,
    seed: int = 0,
    n_sockets: int = 8,
    capacity: int | None = None,
    app_model: Any = None,
    mesh: Any = None,
    tcp_cc: str = "reno",
    tcp_in_order: bool = True,
    tcp_wnd_words: int | None = None,
    rx_queue: str = "codel",
    qdisc: str = "fifo",
    interface_buffer: int = 1_024_000,
    tcp_child_slot_limit: int | None = None,
    locality: bool = False,
    runahead_ns: int | None = None,
    frontier: int = 0,
    fuse_rx: bool = True,
    burst_rx: bool = True,
    shape_bucket: bool = True,
    trace: int = 0,
    stats: int = 0,
    profiler: Any = None,
    overflow: str = "drop",
    spill_len: int = 0,
    spmd: str = "auto",
    host_order: Any = None,
) -> Simulation:
    """Config -> Simulation; pass a `jax.sharding.Mesh` (1-D "hosts" or
    2-D "dcn" x "hosts") to shard hosts.

    `spmd` selects the sharded lowering: "auto" resolves to shard_map
    (public or experimental — the engine's collective-free loop
    predicates make both safe), "constraint" builds ONE global engine
    and lets GSPMD partition it from explicit NamedShardings, "pmap"
    keeps the legacy 1-D fallback. See docs/12-Sharding.md.

    `locality=True` (sharded runs only) reorders hosts at build time so
    config-visible traffic partners share a shard, cutting cross-shard
    packet traffic (the static replacement for the reference's random
    host->thread shuffle + work stealing, scheduler.c:440-534,
    scheduler_policy_host_steal.c). Host gids and the `names` order then
    follow the locality layout, so single-vs-sharded comparisons must
    match hosts by NAME, not position.

    `host_order` (elastic resume, docs/13-Elastic-Recovery.md) forces an
    explicit host permutation instead of computing one: pass the order a
    v6 checkpoint was written under and the rebuilt gids match the
    checkpoint's leaves regardless of the new mesh's shard count. It
    overrides `locality` (the stored order already IS the writer's
    locality layout) and is legal on any mesh, including unsharded.

    `stats` (docs/15-Sim-Analytics.md) compiles the sim-time analytics
    plane into the window loop: device-side log2 histograms of event
    wait time, network latency, per-window host occupancy, queue fill,
    and frontier run length (`EngineState.splane`, harvested through
    the heartbeat bundle's single fetch). 0 (the default) is zero-cost:
    the lowered program is byte-identical to a stats-free build.

    `frontier` (docs/11-Performance.md, "Model-tier batching") selects
    the engine's third drain contract: per round each host's staged
    events sort once and a RUN of up to `frontier` equal-time same-kind
    events executes through a position fold that amortizes the chained
    drain's per-event bookkeeping. Results are bit-identical to
    `frontier=0` (the chained default). Requires a TCP stack with
    fuse_rx=True and a model that declares `frontier_safe` (every local
    emit scheduled at dt >= 1) — refused loudly otherwise.
    """
    from shadow_tpu.runtime.pressure import OVERFLOW_MODES

    if overflow not in OVERFLOW_MODES:
        raise ValueError(
            f"overflow must be one of {OVERFLOW_MODES}, got {overflow!r}"
        )
    if overflow in ("spill", "grow") and mesh is not None and (
        int(mesh.devices.size) > 1
    ):
        # the reservoir's window-boundary harvest would need a cross-
        # shard barrier protocol the controller doesn't speak yet; fail
        # loudly instead of silently losing events (repo-wide principle)
        raise ValueError(
            f"--overflow {overflow} is not supported on sharded meshes "
            "yet; use strict or drop (or run unsharded)"
        )
    if registry is None:
        registry = default_registry()
    topo = Topology.from_graphml(cfg.topology_source())
    hosts = expand_hosts(cfg)
    n_hosts = len(hosts)
    applied_order: tuple | None = None
    if host_order is not None:
        from shadow_tpu.parallel.partition import apply_order

        perm = [int(g) for g in host_order]
        if sorted(perm) != list(range(n_hosts)):
            raise ValueError(
                f"host_order must be a permutation of range({n_hosts}) — "
                "was the checkpoint written from the same config?"
            )
        hosts = apply_order(hosts, perm)
        applied_order = tuple(perm)
    elif locality and (mesh is None or int(mesh.devices.size) <= 1):
        # semantics-bearing options act or fail loudly (the repo-wide
        # config principle): locality without a multi-shard mesh would
        # silently change nothing
        raise ValueError("locality=True requires a multi-device mesh")
    elif locality and mesh is not None and int(mesh.devices.size) > 1:
        from shadow_tpu.parallel.partition import (
            apply_order,
            locality_order,
            traffic_edges_from_config,
        )

        edges = traffic_edges_from_config(hosts)
        perm = locality_order(
            n_hosts, edges, int(mesh.devices.size),
            dcn_slices=(mesh.devices.shape[0]
                        if mesh.devices.ndim == 2 else 1),
        )
        hosts = apply_order(hosts, perm)
        applied_order = tuple(perm)

    # -- shape bucketing: pad the host dimension to a standard ladder so
    # configs of nearby sizes COMPILE TO THE SAME XLA PROGRAM. Every
    # distinct (n_hosts, n_sockets, capacity, ...) tuple is otherwise a
    # fresh 6-8 minute compile on a cold TPU tunnel; padded hosts are
    # inert (no processes, no events, default NICs), so they cost array
    # rows but no event traffic. The ladder doubles up to 1024 rows and
    # then steps by 1024 (bounded <=2x overhead below 1k hosts, <=10%
    # above), always honoring mesh divisibility.
    n_shards_req = int(mesh.devices.size) if mesh is not None else 1
    if shape_bucket:
        b_ = 16
        while b_ < n_hosts:
            b_ = b_ * 2 if b_ < 1024 else b_ + 1024
        if b_ % n_shards_req:
            b_ = ((b_ // n_shards_req) + 1) * n_shards_req
        n_hosts = max(b_, n_hosts)
    elif mesh is not None and n_hosts % n_shards_req:
        raise ValueError(
            f"{len(hosts)} hosts not divisible by mesh size "
            f"{n_shards_req} (enable shape_bucket to auto-pad)"
        )

    # -- attachment + DNS (master.c:307-345 registerHosts -> topology_attach,
    # dns_register)
    dns = DNS()
    host_vertex = []
    for h in hosts:
        s = h.spec
        v = topo.attach(
            ip_hint=s.iphint, citycode_hint=s.citycodehint,
            countrycode_hint=s.countrycodehint, geocode_hint=s.geocodehint,
            type_hint=s.typehint,
        )
        host_vertex.append(v)
        dns.register(h.gid, h.name, s.iphint or None)
    # bucket-padded rows attach to vertex 0; they originate no traffic
    host_vertex += [0] * (n_hosts - len(hosts))

    # -- NIC sizing: host attr overrides vertex attr (docs/3.1 host element)
    # defaults also give bucket-padded rows sane (never-exercised) NICs
    bw_up = np.full((n_hosts,), float(DEFAULT_BANDWIDTH_KIB), np.float64)
    bw_down = np.full((n_hosts,), float(DEFAULT_BANDWIDTH_KIB), np.float64)
    cpu_cost = np.zeros((n_hosts,), np.int64)
    cpu_khz = np.zeros((n_hosts,), np.int64)  # for per-kind model charges
    rcv_wnd_bytes = np.zeros((n_hosts,), np.int64)
    snd_buf_bytes = np.zeros((n_hosts,), np.int64)  # 0 = unlimited
    # NIC receive buffer bound (interfacebuffer host attr; reference
    # default 1024000 bytes, options.c:78 — CoDel acts long before a
    # megabyte of standing queue, so the default only bounds pathology)
    rx_buf = np.full((n_hosts,), interface_buffer, np.int64)
    pcap_mask = np.zeros((n_hosts,), bool)
    pcap_dirs: set[str] = set()
    proc_stop = np.full((n_hosts,), np.iinfo(np.int64).max, np.int64)
    for h, v in zip(hosts, host_vertex):
        vx = topo.vertices[v]
        s = h.spec
        bw_up[h.gid] = s.bandwidthup or vx.bandwidth_up_kib or DEFAULT_BANDWIDTH_KIB
        bw_down[h.gid] = (
            s.bandwidthdown or vx.bandwidth_down_kib or DEFAULT_BANDWIDTH_KIB
        )
        # semantics-bearing host attrs must act or fail loudly (round-1
        # accepted-and-ignored them, silently changing results)
        if s.cpufrequency:
            cpu_cost[h.gid] = CPU_CYCLES_PER_EVENT * 1_000_000 // s.cpufrequency
            cpu_khz[h.gid] = s.cpufrequency
        if s.socketrecvbuffer:
            rcv_wnd_bytes[h.gid] = s.socketrecvbuffer
        if s.socketsendbuffer:
            # bounded send buffer: bytes beyond the cap wait in the
            # TCB's app_pending and drain on ACK progress — the jitted
            # analog of the reference's blocking send against its
            # (autotuned) buffer, tcp.c:407-598
            snd_buf_bytes[h.gid] = s.socketsendbuffer
        if s.interfacebuffer:
            rx_buf[h.gid] = s.interfacebuffer
        if s.logpcap or s.pcapdir:
            pcap_mask[h.gid] = True
            if s.pcapdir:
                pcap_dirs.add(s.pcapdir)
        stops = {p.stoptime for p in s.processes if p.stoptime}
        if stops and not getattr(app_model, "owns_process_lifecycle", False):
            if len(s.processes) > 1 and (
                len(stops) > 1 or len(stops) < len(s.processes)
            ):
                # jitted app models collapse a host's processes into one
                # state row, so app-handler muting is per host; a partial
                # stop would silently kill the host's other processes
                # too. The process tier owns true per-process lifecycle
                # (each process is its own green thread) and opts out.
                raise ValueError(
                    f"host {h.name!r}: all processes on a host must share "
                    "one stoptime (per-process stop needs the real-binary "
                    "tier, whose processes are individual green threads)"
                )
            proc_stop[h.gid] = int(stops.pop() * SECOND)

    if app_model is not None:
        model = app_model
    else:
        parts = resolve_app_models(cfg, registry, hosts)
        model = parts[0][1] if len(parts) == 1 else FusedModel(parts)
    if snd_buf_bytes.any() and not model.needs_tcp:
        # semantics-bearing attrs act or fail loudly: without a TCP
        # stack there is no send buffer for the cap to bound
        raise ValueError(
            "socketsendbuffer is set but the app model "
            f"{model.name!r} runs no TCP stack; remove the attribute"
        )
    if capacity is None:
        # every in-flight packet occupies a destination queue slot, so a
        # TCP host must hold a full receive window (64*WND_WORDS segs)
        # plus timers/app events; non-TCP models need far less. The +64
        # headroom covers the fused rx path's earlier ACK clock (windows
        # open sooner, so bursts overlap slightly more in flight).
        from shadow_tpu.transport.tcp import WND_WORDS

        capacity = 64 * WND_WORDS * 2 + 64 if model.needs_tcp else 256
    net = HostNet.create(
        n_hosts, n_sockets, jnp.asarray(bw_up), jnp.asarray(bw_down),
        with_tcp=model.needs_tcp,
        rcv_wnd_bytes=rcv_wnd_bytes if rcv_wnd_bytes.any() else None,
        wnd_words=tcp_wnd_words,
        rx_buf_bytes=jnp.asarray(rx_buf),
        snd_buf_bytes=snd_buf_bytes if snd_buf_bytes.any() else None,
    )
    if pcap_mask.any():
        from shadow_tpu.utils.pcap import CaptureRing

        net = dataclasses.replace(
            net, cap=CaptureRing.create(jnp.asarray(pcap_mask))
        )

    b = SimBuild(
        cfg=cfg, hosts=hosts, dns=dns, topo=topo, n_sockets=n_sockets,
        sockets=net.sockets, tcb=net.tcb, n_total=n_hosts,
    )
    app_state, make_handlers, on_recv = model.build(b)
    net = dataclasses.replace(net, sockets=b.sockets, tcb=b.tcb)

    bootstrap_end = int(cfg.bootstraptime * SECOND)
    # config-driven sims get strict byte-stream delivery order (the
    # reference's apps read in-order streams); raw-engine users can still
    # build TCP(in_order=False) for on-arrival accounting.
    # qdisc 'rr' (options.c interface-qdisc): one segment per tx kick, so
    # contending connections strictly alternate through the shared NIC
    # virtual clock — round-robin at packet granularity. 'fifo' (default)
    # keeps burst transmission; admission follows the event total order,
    # which *is* packet-creation order (the reference's FIFO qdisc sorts
    # on a host-monotonic creation counter, packet.c:87-88; its single
    # exception — control packets stamped priority 0.0 to jump the
    # queue, tcp.c:844 — is immaterial here because pure ACKs ride
    # their own events through the same total order rather than a
    # shared tx backlog).
    if qdisc not in ("fifo", "rr"):
        raise ValueError(f"unknown qdisc {qdisc!r}")
    tcp_kw = dict(tx_burst=1, inline_budget=1) if qdisc == "rr" else {}
    # a restarted host has lost all connection state, so survivors'
    # segments to it must draw an RST (the kernel's answer to a segment
    # for no socket) rather than blackholing until RTO exhaustion
    have_crash_faults = any(
        f.type in ("crash", "churn") for f in cfg.faults
    )
    if have_crash_faults:
        tcp_kw["rst_on_unmatched"] = True
    tcp = (
        TCP(auto_close=False, cc=tcp_cc, in_order=tcp_in_order,
            child_slot_limit=tcp_child_slot_limit, **tcp_kw)
        if model.needs_tcp else None
    )
    # fuse_rx folds the per-packet ARRIVE->RX double event into one
    # (stack.py Stack docstring): output timing exact, state-read timing
    # early by the rx serialization delay, half the sequential depth in
    # the drain. On by default — the per-packet event pair is the
    # dominant chain in every TCP workload.
    stack = Stack(bootstrap_end=bootstrap_end, tcp=tcp, rx_queue=rx_queue,
                  fuse_rx=fuse_rx)

    if on_recv is None:
        def on_recv(hs, slot, pkt, now, key):  # noqa: F811
            from shadow_tpu.core.engine import Emit
            return hs, Emit.none(1, N_PKT_ARGS)

    # <process stoptime>: a stopped process's callbacks never run again
    # (the reference kills the plugin; its sockets keep the kernel-side
    # teardown going — here the stack/TCP handlers likewise continue)
    if (proc_stop < np.iinfo(np.int64).max).any():
        stop_arr = jnp.asarray(proc_stop)

        def _dead_select(hs, hs2, em, dead):
            hs_out = jax.tree.map(lambda a, b: jnp.where(dead, a, b), hs, hs2)
            return hs_out, dataclasses.replace(em, mask=em.mask & ~dead)

        def _mute_handler(fn):
            def wrapped(hs, ev, key):
                hs2, em = fn(hs, ev, key)
                return _dead_select(hs, hs2, em, ev.time >= stop_arr[ev.dst])

            return wrapped

        # recv-muting needs the lane's host id from the app state. A model
        # may declare it via a `lane_gid(app_state_slice)` method (the
        # AppModel-level contract); the fallback sniffs the conventional
        # `gid` field every bundled model carries. Fail at build time, not
        # trace time, when neither resolves.
        if hasattr(model, "lane_gid"):
            _lane_gid = model.lane_gid
        else:
            def _gid_resolvable(app):
                return hasattr(app, "gid") or any(
                    hasattr(sub, "gid") for sub in getattr(app, "subs", ())
                )

            if not _gid_resolvable(app_state):
                raise ValueError(
                    "process stoptime needs the app model to define "
                    "lane_gid(app_state) or carry a gid field "
                    f"(model {model.name!r} has neither)"
                )

            def _lane_gid(app):
                if hasattr(app, "gid"):
                    return app.gid
                for sub in app.subs:
                    if hasattr(sub, "gid"):
                        return sub.gid
                raise AssertionError  # unreachable: checked at build

        def _mute_recv(fn):
            def wrapped(hs, slot, pkt, now, key):
                hs2, em = fn(hs, slot, pkt, now, key)
                dead = now >= stop_arr[_lane_gid(hs.app)]
                return _dead_select(hs, hs2, em, dead)

            return wrapped

        make_inner = make_handlers

        def make_handlers(stack_, kind_base_):  # noqa: F811
            return [_mute_handler(fn) for fn in make_inner(stack_, kind_base_)]

        on_recv = _mute_recv(on_recv) if on_recv is not None else None

    base_handlers = stack.make_handlers(on_recv)
    kind_base = len(base_handlers)
    handlers = base_handlers + make_handlers(stack, kind_base)
    # handler-kind labels for the per-kind executed-event counters (the
    # reference's ObjectCounter type names, object_counter.h:13-27)
    kind_names = ["pkt_arrive", "pkt_rx"]
    if tcp is not None:
        kind_names += ["tcp_timer", "tcp_tx"]
    if isinstance(model, FusedModel):
        for name, sub, _ in model.parts:
            kind_names += [f"{name}.{i}" for i in range(sub.n_kinds)]
    else:
        kind_names += [f"{model.name}.{i}" for i in range(model.n_kinds)]
    if len(kind_names) != len(handlers):
        raise AssertionError(
            f"kind label table ({len(kind_names)}) out of sync with the "
            f"handler table ({len(handlers)}); update the names above "
            "alongside Stack.make_handlers/model kinds"
        )

    if tcp is not None:
        need = tcp.min_max_emit(model.app_rows())
    else:
        need = model.app_rows() + 1
    max_emit = max(need, model.handler_rows())

    # conservative window width: the topology's minimum path latency by
    # default, overridable by the user (the reference exposes the same
    # knob as --runahead / minTimeJump, options.c; master.c:133-159).
    # Wider than min latency is SAFE for causality — cross-host arrivals
    # are clamped up to the window barrier (engine._route), exactly the
    # reference's barrier clamp — it just coarsens packet timing by up
    # to the window width, the documented runahead tradeoff.
    if runahead_ns is not None:
        if runahead_ns < 1:
            raise ValueError(f"runahead must be >= 1 ns, got {runahead_ns}")
        lookahead = runahead_ns
    else:
        lookahead = max(int(topo.min_latency_ms * MILLISECOND), 1)
    spmd_path = None
    if mesh is not None:
        from shadow_tpu.parallel.mesh import hosts_axes, select_spmd

        spmd_path = select_spmd(spmd)
        n_shards = int(mesh.devices.size)
        if n_hosts % n_shards:
            raise ValueError(
                f"{n_hosts} hosts not divisible by mesh size {n_shards}"
            )
        per_shard = n_hosts // n_shards
        axis_name = hosts_axes(mesh)
        if spmd_path == "constraint":
            # GSPMD partitions ONE global program: the engine runs no
            # manual collectives (axis_name=None), sees every host, and
            # the mesh enters only through _wrap's NamedShardings
            n_shards, per_shard, axis_name = 1, n_hosts, None
    else:
        n_shards, per_shard, axis_name = 1, n_hosts, None
    # burst delivery (engine._burst_fold): contiguous same-flow TCP
    # arrivals staged in one sweep collapse into multi-segment events.
    # The chained drain's wall time is (busiest host's sequential event
    # count) x (full handler-pass cost), and steady-state TCP data
    # bursts dominate that count. Requires fuse_rx (the delivery runs
    # inside the arrival) and the TCP stack. Timing of absorbed
    # segments coarsens by at most one window; loss fidelity is exact
    # (reliability rolls happened at send time).
    burst = None
    if burst_rx and fuse_rx and tcp is not None and pcap_mask.any():
        # burst folding collapses contiguous same-flow arrivals into one
        # multi-segment event, so the capture ring would record one
        # merged frame where the reference writes N — silently coarser
        # pcaps. Capture fidelity wins over drain depth.
        import warnings

        warnings.warn(
            "burst_rx disabled: pcap capture is enabled and burst "
            "folding would merge captured segments (pass burst_rx=False "
            "to silence)",
            stacklevel=2,
        )
        burst_rx = False
    if burst_rx and fuse_rx and tcp is not None:
        from shadow_tpu.transport.stack import (
            A_ACK, A_AUX, A_DPORT, A_LEN, A_META, A_SACK0, A_SACK1,
            A_SEQ, A_SPORT, A_WND, F_FIN, F_RST, F_SYN, KIND_PKT_ARRIVE,
        )
        from shadow_tpu.host.sockets import PROTO_TCP
        from shadow_tpu.transport.tcp import MSS

        burst = (KIND_PKT_ARRIVE, A_SEQ, A_LEN, A_SPORT, A_DPORT, A_META,
                 int(PROTO_TCP), int(F_SYN | F_FIN | F_RST), int(MSS),
                 (A_ACK, A_WND, A_AUX, A_SACK0, A_SACK1))
    from shadow_tpu.transport.stack import A_LEN as _A_LEN

    # spill ring sizing: default 4x capacity of record slots absorbs the
    # worst bursts seen in the skew benchmarks with room to spare; the
    # ring reports (never hides) overflow via n_lost if undersized
    spill = 0
    if overflow in ("spill", "grow"):
        spill = int(spill_len) if spill_len > 0 else 4 * capacity
    # frontier drain eligibility: the run rule is only exact when every
    # LOCAL emit lands at dt >= 1 (engine._drain_window_frontier). The
    # unfused ARRIVE->RX re-emit violates it (dt can be 0 in bootstrap),
    # and a model with zero-valued pause/interval tables would too — so
    # the knob demands fuse_rx + an explicit model-side declaration.
    frontier_kinds = None
    if frontier:
        if tcp is None or not fuse_rx:
            raise ValueError(
                "frontier batching requires the TCP stack with "
                "fuse_rx=True (the unfused ARRIVE->RX re-emit can land "
                "at dt=0, breaking the run rule's dt >= 1 invariant)"
            )
        if not getattr(model, "frontier_safe", False):
            raise ValueError(
                f"model {model.name!r} does not declare frontier_safe "
                "(its local emit delays are not provably >= 1 ns for "
                "this config); run with frontier=0"
            )
        frontier_kinds = stack.frontier_kinds() + tuple(
            kind_base + int(i) for i in model.frontier_kinds()
        )
    ecfg = EngineConfig(
        n_hosts=per_shard, capacity=capacity, lookahead=lookahead,
        max_emit=max_emit, n_args=N_PKT_ARGS, seed=seed,
        axis_name=axis_name, n_shards=n_shards, burst=burst,
        trace=int(trace), trace_len_arg=int(_A_LEN),
        spill=spill, frontier=int(frontier), stats=int(stats),
    )
    network = topo.build_network(host_vertex)
    # per-KIND CPU charges: a model may declare cycle costs for specific
    # event kinds (e.g. Tor relay crypto per delivered segment); they
    # convert to virtual-CPU ns on hosts with a cpufrequency and stack on
    # the uniform per-event cost (the reference charges measured plugin
    # time per task, cpu.c:56-107 — per-kind tables are the jitted analog)
    cost_arg = cpu_cost
    if fuse_rx and cpu_cost.any():
        # the fused KIND_PKT_ARRIVE event executes the delivery too, so
        # it pays BOTH halves of the uniform per-event charge — keeping
        # CPU-model timing aligned with the unfused two-event pipeline.
        # (Remaining documented divergence: a packet dropped at the rx
        # queue still pays the delivery half here, where unfused mode
        # would never execute its KIND_PKT_RX event.)
        from shadow_tpu.transport.stack import KIND_PKT_ARRIVE

        cost_arg = np.broadcast_to(
            cpu_cost[:, None], (n_hosts, len(handlers))
        ).copy()
        cost_arg[:, KIND_PKT_ARRIVE] += cpu_cost
    if hasattr(model, "cpu_kind_cycles"):
        cycles = model.cpu_kind_cycles(len(handlers))
        if cycles is not None and cpu_khz.any():
            if fuse_rx:
                # deliveries execute inside KIND_PKT_ARRIVE when fused —
                # move any per-delivery charge (e.g. Tor relay crypto at
                # KIND_PKT_RX) onto the kind that actually runs, or the
                # CPU model would silently stop charging it
                from shadow_tpu.transport.stack import (
                    KIND_PKT_ARRIVE, KIND_PKT_RX,
                )

                cycles = np.array(cycles, copy=True)
                cycles[:, KIND_PKT_ARRIVE] += cycles[:, KIND_PKT_RX]
                cycles[:, KIND_PKT_RX] = 0
            extra_ns = np.where(
                cpu_khz[:, None] > 0,
                cycles * 1_000_000 // np.maximum(cpu_khz[:, None], 1),
                0,
            )
            base = (
                cost_arg if cost_arg.ndim == 2 else cpu_cost[:, None]
            )
            cost_arg = base + extra_ns
    hosts_state = SimHost(net=net, app=app_state)

    faults = None
    if cfg.faults:
        from shadow_tpu.faults import compile_faults

        name_by_gid = [""] * n_hosts
        for h in hosts:
            name_by_gid[h.gid] = h.name
        faults = compile_faults(cfg.faults, name_by_gid, n_hosts, seed)
    eng = Engine(
        ecfg, handlers, network,
        cpu_cost=jnp.asarray(cost_arg) if cost_arg.any() else None,
        faults=faults,
        # the initial hosts pytree doubles as the restart template: a
        # crashed-and-restarted host comes back with boot-fresh state
        # (listen sockets rebound, app state re-zeroed)
        fault_reset=hosts_state if faults is not None else None,
        frontier_kinds=frontier_kinds,
    )

    # -- initial events: process starts (slave.c:296-336 scheduling of
    # process start tasks at starttime)
    evs = b.start_events
    m = max(len(evs), 1)
    init = Events.empty((m,), n_args=N_PKT_ARGS)
    times = np.full((m,), TIME_INVALID, np.int64)
    dsts = np.zeros((m,), np.int32)
    seqs = np.zeros((m,), np.int32)
    kinds = np.zeros((m,), np.int32)
    argw = np.zeros((m, N_PKT_ARGS), np.int32)
    per_src_seq: dict[int, int] = {}
    for i, (t_ns, gid, kind_rel, args) in enumerate(evs):
        times[i] = t_ns
        dsts[i] = gid
        seqs[i] = per_src_seq.get(gid, 0)
        per_src_seq[gid] = seqs[i] + 1
        kinds[i] = kind_base + kind_rel
        for j, w in enumerate(args):
            argw[i, j] = w
    init = dataclasses.replace(
        init,
        time=jnp.asarray(times), dst=jnp.asarray(dsts),
        src=jnp.asarray(dsts), seq=jnp.asarray(seqs),
        kind=jnp.asarray(kinds), args=jnp.asarray(argw),
    )

    if mesh is None or spmd_path == "constraint":
        # constraint path: the global init IS the single-device init;
        # _wrap's in_shardings spread it over the mesh on first call
        st0 = eng.init_state(hosts_state, init)
    else:
        # build the initial state under shard_map: each shard slices its
        # host-state rows and keeps only its own initial events (the push
        # ignores out-of-shard destinations)
        from jax.sharding import PartitionSpec as P

        from shadow_tpu.parallel.mesh import (
            hosts_axes, shard_map, state_specs,
        )

        axes = hosts_axes(mesh)
        hspecs = jax.tree.map(lambda _: P(axes), hosts_state)

        def init_shard(hslice):
            host0 = jax.lax.axis_index(axes).astype(jnp.int32) * per_shard
            return eng.init_state(hslice, init, host0)

        slice_shapes = jax.tree.map(
            lambda l: jax.ShapeDtypeStruct(
                (per_shard,) + l.shape[1:], l.dtype
            ),
            hosts_state,
        )
        template = jax.eval_shape(
            lambda hs: eng.init_state(hs, init, 0), slice_shapes
        )
        ospecs = state_specs(template, per_shard, axes)
        st0 = jax.jit(
            shard_map(
                init_shard,
                mesh=mesh,
                in_specs=(hspecs,),
                out_specs=ospecs,
                check_vma=False,
            )
        )(hosts_state)
    if len(pcap_dirs) > 1:
        raise ValueError(
            f"hosts disagree on pcapdir ({sorted(pcap_dirs)}); captures "
            "share one directory per run"
        )
    pressure = None
    if overflow in ("spill", "grow"):
        from shadow_tpu.runtime.pressure import PressureController

        pressure = PressureController(
            n_hosts, capacity, lookahead, mode=overflow,
            n_args=N_PKT_ARGS,
        )
    return Simulation(
        engine=eng, state0=st0, stop_ns=int(cfg.stoptime * SECOND),
        dns=dns, topo=topo, names=[h.name for h in hosts], app=model,
        stack=stack, mesh=mesh, spmd=spmd,
        pcap_gids=tuple(int(g) for g in np.nonzero(pcap_mask)[0]),
        pcap_dir=(pcap_dirs.pop() if pcap_dirs else "shadow.pcap.d"),
        kind_names=tuple(kind_names),
        faults=faults,
        profiler=profiler,
        overflow=overflow,
        pressure=pressure,
        host_order=applied_order,
    )


def build_fleet(sim: Simulation, lanes: int, **overrides):
    """Batch `lanes` variants of a built scenario into one Fleet.

    Per-lane knobs (`seeds`, `faults`, `latency_scale`,
    `bandwidth_scale`, `state_override` — see runtime.fleet.FleetPlan)
    become traced inputs of ONE jitted vmapped window loop; static
    compile-time knobs (kernel/frontier/window/capacity/...) must stay
    uniform and are rejected with the reason. The fleet's stacked
    `[L, ...]` state donates through every segment exactly like the
    solo `Simulation` jits, and `HeartbeatHarvest` drives it through
    the same single-fetch path. docs/16-Scenario-Fleets.md has the
    lane-semantics table.
    """
    from shadow_tpu.runtime.fleet import build_fleet_from_engine

    if sim.mesh is not None:
        raise ValueError(
            "fleets vmap the single-device engine; a sharded base "
            "scenario is not supported — shard across fleet replicas "
            "instead (one fleet per device group)"
        )
    if sim.pressure is not None:
        raise ValueError(
            "fleets cannot run spill/grow pressure modes; their "
            "reservoir refills are host-side per-window work that "
            "cannot ride one fused vmapped program — use --overflow "
            "drop/strict for fleet runs"
        )
    fleet = build_fleet_from_engine(
        sim.engine, sim.state0, lanes, names=sim.names,
        stop_ns=sim.stop_ns, **overrides,
    )
    fleet.strict_overflow = sim.strict_overflow or sim.overflow == "strict"
    return fleet


def default_registry() -> dict[str, Callable]:
    from shadow_tpu.models.bitcoin import BitcoinModel
    from shadow_tpu.models.phold_net import PholdNetModel
    from shadow_tpu.models.tgen import TGenModel
    from shadow_tpu.models.tor import TorModel

    return {
        "tgen": TGenModel,
        "phold": PholdNetModel,
        "tor": TorModel,
        "bitcoin": BitcoinModel,
    }

"""Simulation assembly: parsed config -> engine + device state + run loop.

This is the TPU-era Master/Slave bootstrap (reference:
src/main/core/master.c:271-448 `_master_registerPlugins/_master_registerHosts`
-> slave_addNewVirtualHost -> host_new/host_setup -> scheduler_addHost):
load the topology, expand and attach hosts, register DNS names, size the
NICs, let the app model bind its sockets and schedule its process start
events, then compile everything into one Engine whose handler table is
[stack pipeline | TCP machinery | app kinds].

Where the reference walks XML into heap objects and pthread queues, this
builder walks the same config into struct-of-arrays device state; where
the reference's hosts are partitioned across worker threads by random
shuffle (scheduler.c:440-534), hosts here are block-partitioned across the
device mesh axis by dense gid.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Protocol

import jax
import jax.numpy as jnp
import numpy as np

from shadow_tpu.config import (
    HostInstance,
    ShadowConfig,
    expand_hosts,
    resolve_path,
)
from shadow_tpu.core.engine import Engine, EngineConfig
from shadow_tpu.core.events import Events
from shadow_tpu.core.timebase import MILLISECOND, SECOND, TIME_INVALID
from shadow_tpu.net.dns import DNS
from shadow_tpu.net.topology import Topology
from shadow_tpu.transport.stack import N_PKT_ARGS, SimHost, Stack, HostNet
from shadow_tpu.transport.tcp import TCP

DEFAULT_BANDWIDTH_KIB = 10240  # when neither host attr nor vertex attr set


@dataclasses.dataclass
class SimBuild:
    """Mutable build context handed to the app model.

    The app reads per-host process specs/arguments, resolves peer names
    through `dns`, binds listen sockets into `sockets`/`tcb`, and appends
    process start events (starttime semantics of the <process> element).
    """

    cfg: ShadowConfig
    hosts: list[HostInstance]
    dns: DNS
    topo: Topology
    n_sockets: int
    sockets: Any  # SocketTable [H, S]
    tcb: Any  # transport.tcp.TCB [H, S] or None
    start_events: list[tuple[int, int, int, list[int]]] = dataclasses.field(
        default_factory=list
    )  # (time_ns, gid, kind_rel, args words)

    @property
    def n_hosts(self) -> int:
        return len(self.hosts)

    def resolve_gid(self, name: str) -> int:
        addr = self.dns.resolve_name(name)
        if addr is None:
            raise ValueError(f"unknown hostname in config: {name!r}")
        return addr.host_id

    def add_start_event(self, gid: int, time_s: float, kind_rel: int,
                        args: list[int] | None = None) -> None:
        self.start_events.append(
            (int(time_s * SECOND), gid, kind_rel, list(args or []))
        )


class AppModel(Protocol):
    """A jitted application compiled into the device step (the fast tier
    of SURVEY.md §7 step 6: the analog of a plugin binary is a handler
    table + static per-host config arrays)."""

    name: str
    needs_tcp: bool
    n_kinds: int

    def app_rows(self) -> int:
        """Emit rows the on_recv callback returns (for max_emit sizing)."""
        ...

    def handler_rows(self) -> int:
        """Max Emit rows any of the app's own kind handlers returns."""
        ...

    def build(self, b: SimBuild) -> tuple[Any, Callable, Callable | None]:
        """-> (app_state [H,...], make_handlers(stack, kind_base) ->
        [handlers], on_recv or None)."""
        ...


@dataclasses.dataclass
class Simulation:
    """A built, runnable simulation.

    With `mesh` set, hosts are block-partitioned over the 1-D "hosts" mesh
    axis (gid // per_shard = owning shard — the TPU-era version of the
    reference's host→thread assignment, scheduler.c:440-534) and run/step
    execute under shard_map: the window barrier is lax.pmin across shards
    and cross-shard packet delivery rides the engine's all_to_all exchange.
    """

    engine: Engine
    state0: Any  # EngineState
    stop_ns: int
    dns: DNS
    topo: Topology
    names: list[str]
    app: Any  # the AppModel instance
    stack: Stack
    mesh: Any = None  # jax.sharding.Mesh when sharded

    _jit_run: Any = None
    _jit_step: Any = None

    def _wrap(self, fn):
        """Jit `fn(state, stop, host0)`, under shard_map when sharded."""
        if self.mesh is None:
            return jax.jit(lambda st, stop: fn(st, stop, 0))
        from jax.sharding import PartitionSpec as P

        from shadow_tpu.parallel.mesh import HOSTS_AXIS, state_specs

        per = self.engine.cfg.n_hosts
        # state0 leaves are global-shaped; sharding splits the leading
        # host dim across the axis
        specs = state_specs(
            self.state0, per * self.engine.cfg.n_shards, HOSTS_AXIS
        )

        def sharded(st, stop):
            host0 = jax.lax.axis_index(HOSTS_AXIS).astype(jnp.int32) * per
            return fn(st, stop, host0)

        return jax.jit(
            jax.shard_map(
                sharded,
                mesh=self.mesh,
                in_specs=(specs, P()),
                out_specs=specs,
                check_vma=False,
            )
        )

    def run(self, stop_ns: int | None = None, state=None):
        """Jit-run to the stop time; returns the final EngineState.

        The jitted callables are cached on the instance so repeated calls
        (the CLI's heartbeat loop, checkpoint-interval stepping) reuse one
        compiled executable instead of retracing."""
        if self._jit_run is None:
            object.__setattr__(self, "_jit_run", self._wrap(self.engine.run))
        st = state if state is not None else self.state0
        stop = jnp.int64(stop_ns if stop_ns is not None else self.stop_ns)
        return self._jit_run(st, stop)

    def step_window(self, state, stop_ns: int | None = None):
        if self._jit_step is None:
            object.__setattr__(
                self, "_jit_step", self._wrap(self.engine.step_window)
            )
        stop = jnp.int64(stop_ns if stop_ns is not None else self.stop_ns)
        return self._jit_step(state, stop)


def _plugin_key(cfg: ShadowConfig, plugin_id: str) -> str:
    """Registry key for a plugin: its id, falling back to path basename
    substring matching (the reference identifies plugins purely by id but
    test configs name them after their .so, e.g. 'shadow-plugin-test-phold')."""
    spec = cfg.plugin_by_id(plugin_id)
    names = [plugin_id] + ([spec.path.rsplit("/", 1)[-1]] if spec else [])
    return " ".join(names).lower()


def resolve_app_model(cfg: ShadowConfig, registry: dict[str, Callable]):
    """Pick the single app model implied by the config's plugins.

    v1 constraint: one model per simulation (multi-model handler-table
    fusion is future work); every process's plugin must map to it.
    """
    found: dict[str, Callable] = {}
    for h in cfg.hosts:
        for p in h.processes:
            key = _plugin_key(cfg, p.plugin)
            for regname, factory in registry.items():
                if regname in key:
                    found[regname] = factory
                    break
            else:
                raise ValueError(
                    f"no app model registered for plugin {p.plugin!r} "
                    f"(known: {sorted(registry)})"
                )
    if len(found) != 1:
        raise ValueError(
            f"config mixes app models {sorted(found)}; v1 supports one"
        )
    return next(iter(found.values()))()


def build_simulation(
    cfg: ShadowConfig,
    registry: dict[str, Callable] | None = None,
    *,
    seed: int = 0,
    n_sockets: int = 8,
    capacity: int = 256,
    app_model: Any = None,
    mesh: Any = None,
) -> Simulation:
    """Config -> Simulation; pass a 1-D `jax.sharding.Mesh` to shard hosts."""
    if registry is None:
        registry = default_registry()
    topo = Topology.from_graphml(cfg.topology_source())
    hosts = expand_hosts(cfg)
    n_hosts = len(hosts)

    # -- attachment + DNS (master.c:307-345 registerHosts -> topology_attach,
    # dns_register)
    dns = DNS()
    host_vertex = []
    for h in hosts:
        s = h.spec
        v = topo.attach(
            ip_hint=s.iphint, citycode_hint=s.citycodehint,
            countrycode_hint=s.countrycodehint, geocode_hint=s.geocodehint,
            type_hint=s.typehint,
        )
        host_vertex.append(v)
        dns.register(h.gid, h.name, s.iphint or None)

    # -- NIC sizing: host attr overrides vertex attr (docs/3.1 host element)
    bw_up = np.zeros((n_hosts,), np.float64)
    bw_down = np.zeros((n_hosts,), np.float64)
    for h, v in zip(hosts, host_vertex):
        vx = topo.vertices[v]
        bw_up[h.gid] = h.spec.bandwidthup or vx.bandwidth_up_kib or DEFAULT_BANDWIDTH_KIB
        bw_down[h.gid] = (
            h.spec.bandwidthdown or vx.bandwidth_down_kib or DEFAULT_BANDWIDTH_KIB
        )

    model = app_model if app_model is not None else resolve_app_model(cfg, registry)
    net = HostNet.create(
        n_hosts, n_sockets, jnp.asarray(bw_up), jnp.asarray(bw_down),
        with_tcp=model.needs_tcp,
    )

    b = SimBuild(
        cfg=cfg, hosts=hosts, dns=dns, topo=topo, n_sockets=n_sockets,
        sockets=net.sockets, tcb=net.tcb,
    )
    app_state, make_handlers, on_recv = model.build(b)
    net = dataclasses.replace(net, sockets=b.sockets, tcb=b.tcb)

    bootstrap_end = int(cfg.bootstraptime * SECOND)
    tcp = TCP(auto_close=False) if model.needs_tcp else None
    stack = Stack(bootstrap_end=bootstrap_end, tcp=tcp)

    if on_recv is None:
        def on_recv(hs, slot, pkt, now, key):  # noqa: F811
            from shadow_tpu.core.engine import Emit
            return hs, Emit.none(1, N_PKT_ARGS)

    base_handlers = stack.make_handlers(on_recv)
    kind_base = len(base_handlers)
    handlers = base_handlers + make_handlers(stack, kind_base)

    if tcp is not None:
        need = tcp.min_max_emit(model.app_rows())
    else:
        need = model.app_rows() + 1
    max_emit = max(need, model.handler_rows())

    lookahead = max(int(topo.min_latency_ms * MILLISECOND), 1)
    if mesh is not None:
        n_shards = int(mesh.devices.size)
        if n_hosts % n_shards:
            raise ValueError(
                f"{n_hosts} hosts not divisible by mesh size {n_shards}"
            )
        per_shard = n_hosts // n_shards
        axis_name = _hosts_axis()
    else:
        n_shards, per_shard, axis_name = 1, n_hosts, None
    ecfg = EngineConfig(
        n_hosts=per_shard, capacity=capacity, lookahead=lookahead,
        max_emit=max_emit, n_args=N_PKT_ARGS, seed=seed,
        axis_name=axis_name, n_shards=n_shards,
    )
    network = topo.build_network(host_vertex)
    eng = Engine(ecfg, handlers, network)

    # -- initial events: process starts (slave.c:296-336 scheduling of
    # process start tasks at starttime)
    evs = b.start_events
    m = max(len(evs), 1)
    init = Events.empty((m,), n_args=N_PKT_ARGS)
    times = np.full((m,), TIME_INVALID, np.int64)
    dsts = np.zeros((m,), np.int32)
    seqs = np.zeros((m,), np.int32)
    kinds = np.zeros((m,), np.int32)
    argw = np.zeros((m, N_PKT_ARGS), np.int32)
    per_src_seq: dict[int, int] = {}
    for i, (t_ns, gid, kind_rel, args) in enumerate(evs):
        times[i] = t_ns
        dsts[i] = gid
        seqs[i] = per_src_seq.get(gid, 0)
        per_src_seq[gid] = seqs[i] + 1
        kinds[i] = kind_base + kind_rel
        for j, w in enumerate(args):
            argw[i, j] = w
    init = dataclasses.replace(
        init,
        time=jnp.asarray(times), dst=jnp.asarray(dsts),
        src=jnp.asarray(dsts), seq=jnp.asarray(seqs),
        kind=jnp.asarray(kinds), args=jnp.asarray(argw),
    )

    hosts_state = SimHost(net=net, app=app_state)
    if mesh is None:
        st0 = eng.init_state(hosts_state, init)
    else:
        # build the initial state under shard_map: each shard slices its
        # host-state rows and keeps only its own initial events (the push
        # ignores out-of-shard destinations)
        from jax.sharding import PartitionSpec as P

        from shadow_tpu.parallel.mesh import HOSTS_AXIS, state_specs

        hspecs = jax.tree.map(lambda _: P(HOSTS_AXIS), hosts_state)

        def init_shard(hslice):
            host0 = jax.lax.axis_index(HOSTS_AXIS).astype(jnp.int32) * per_shard
            return eng.init_state(hslice, init, host0)

        slice_shapes = jax.tree.map(
            lambda l: jax.ShapeDtypeStruct(
                (per_shard,) + l.shape[1:], l.dtype
            ),
            hosts_state,
        )
        template = jax.eval_shape(
            lambda hs: eng.init_state(hs, init, 0), slice_shapes
        )
        ospecs = state_specs(template, per_shard, HOSTS_AXIS)
        st0 = jax.jit(
            jax.shard_map(
                init_shard,
                mesh=mesh,
                in_specs=(hspecs,),
                out_specs=ospecs,
                check_vma=False,
            )
        )(hosts_state)
    return Simulation(
        engine=eng, state0=st0, stop_ns=int(cfg.stoptime * SECOND),
        dns=dns, topo=topo, names=[h.name for h in hosts], app=model,
        stack=stack, mesh=mesh,
    )


def _hosts_axis() -> str:
    from shadow_tpu.parallel.mesh import HOSTS_AXIS

    return HOSTS_AXIS


def default_registry() -> dict[str, Callable]:
    from shadow_tpu.models.tgen import TGenModel
    from shadow_tpu.models.phold_net import PholdNetModel

    return {"tgen": TGenModel, "phold": PholdNetModel}

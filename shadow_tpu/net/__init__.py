from shadow_tpu.net.topology import GraphNetwork, Topology
from shadow_tpu.net.dns import DNS

__all__ = ["GraphNetwork", "Topology", "DNS"]

"""DNS: virtual IP and hostname registry (host side, plain Python).

Mirrors the reference's DNS object (reference: src/main/routing/dns.c):
sequential IP assignment skipping all reserved IPv4 ranges
(dns.c:74-96 `_dns_isRestricted`), uniqueness enforcement, and the
name<->IP<->host-id maps (dns.c:117-190). Addresses here are plain records
(the reference's refcounted Address, src/main/routing/address.c) keyed by
the dense global host id the device engine uses.
"""

from __future__ import annotations

import dataclasses
import ipaddress

_RESERVED = [
    ipaddress.ip_network(c)
    for c in (
        "0.0.0.0/8", "10.0.0.0/8", "100.64.0.0/10", "127.0.0.0/8",
        "169.254.0.0/16", "172.16.0.0/12", "192.0.0.0/29", "192.0.2.0/24",
        "192.88.99.0/24", "192.168.0.0/16", "198.18.0.0/15",
        "198.51.100.0/24", "203.0.113.0/24", "224.0.0.0/4", "240.0.0.0/4",
        "255.255.255.255/32",
    )
]


def _is_restricted(ip: int) -> bool:
    a = ipaddress.ip_address(ip)
    return any(a in n for n in _RESERVED)


@dataclasses.dataclass(frozen=True)
class Address:
    """{host id, ip, name} — the reference's Address record
    (src/main/routing/address.h) minus refcounting (value type)."""

    host_id: int
    ip: int  # host-order u32
    name: str

    @property
    def ip_str(self) -> str:
        return str(ipaddress.ip_address(self.ip))


class DNS:
    def __init__(self):
        self._counter = 0
        self._by_ip: dict[int, Address] = {}
        self._by_name: dict[str, Address] = {}
        self._by_id: dict[int, Address] = {}

    def _generate_ip(self) -> int:
        self._counter += 1
        while True:
            a = ipaddress.ip_address(self._counter)
            hit = next((n for n in _RESERVED if a in n), None)
            if hit is not None:
                # leap the whole reserved block instead of walking it
                self._counter = int(hit.broadcast_address) + 1
                continue
            if self._counter in self._by_ip:
                self._counter += 1
                continue
            return self._counter

    def register(self, host_id: int, name: str, requested_ip: str | None = None) -> Address:
        """Register a host; honors a requested IP if it is usable, else
        auto-assigns (dns.c:117-165)."""
        if name in self._by_name:
            raise ValueError(f"hostname already registered: {name}")
        ip = None
        if requested_ip:
            cand = int(ipaddress.ip_address(requested_ip))
            if not _is_restricted(cand) and cand not in self._by_ip:
                ip = cand
        if ip is None:
            ip = self._generate_ip()
        addr = Address(host_id=host_id, ip=ip, name=name)
        self._by_ip[ip] = addr
        self._by_name[name] = addr
        self._by_id[host_id] = addr
        return addr

    def resolve_name(self, name: str) -> Address | None:
        return self._by_name.get(name)

    def resolve_ip(self, ip) -> Address | None:
        if isinstance(ip, str):
            ip = int(ipaddress.ip_address(ip))
        return self._by_ip.get(ip)

    def address_of(self, host_id: int) -> Address | None:
        return self._by_id.get(host_id)

    def entries(self) -> list[Address]:
        """All registered addresses (registration order)."""
        return list(self._by_name.values())

    def __len__(self) -> int:
        return len(self._by_id)

"""Network topology: GraphML graph -> dense device routing tables.

The reference wraps igraph and computes paths lazily per (src, dst) with
`igraph_get_shortest_paths_dijkstra`, caching {latency, reliability} under a
rwlock (reference: src/main/routing/topology.c:1655-1875, cache
:1268-1380). On TPU, lazy per-pair CPU callbacks would serialize the whole
engine, so we invert the design: compute the **all-pairs** PoI×PoI latency
and reliability matrices once at load time (hosts attach to far fewer PoI
vertices than there are hosts), push them to device, and make `route()` a
pure gather — O(1) per packet inside the jitted step, no cache, no lock.

Semantics reproduced from the reference:
- vertex attrs: bandwidthup/down (KiB/s), ip, citycode, countrycode, type,
  packetloss (topology.c:86-105); edge attrs: latency (ms), packetloss,
  jitter (topology.c:101-105).
- complete graphs use the direct edge as the path
  (docs/3.2-Network-Config.md "Routing"; topology.c:450-530,1321).
- otherwise Dijkstra by edge latency; path reliability is the product of
  (1 - src vertex loss), (1 - edge loss) per hop, (1 - dst vertex loss)
  (topology.c:1415-1540).
- a path from a vertex to itself (no self-loop) uses the minimum-latency
  incident edge twice: latency = 2*min, reliability = (1-loss)^2
  (topology.c:1545-1652).
- hosts attach to a vertex chosen by hint matching with the preference
  order ip > city+type > city > country+type > country > type > any
  (topology.c:107-138 AttachHelper ordering, topology_attach :2371).
- the graph-wide minimum path latency drives the conservative window
  (topology.c:1374-1385 -> worker_updateMinTimeJump).
"""

from __future__ import annotations

import dataclasses
import lzma
import os
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from shadow_tpu.core.timebase import MILLISECOND

try:
    import networkx as nx
except ImportError:  # pragma: no cover
    nx = None

try:
    from scipy.sparse import csr_matrix
    from scipy.sparse.csgraph import dijkstra as _csgraph_dijkstra
except ImportError:  # pragma: no cover
    csr_matrix = None


@dataclasses.dataclass
class Vertex:
    vid: str
    index: int
    bandwidth_up_kib: int = 0  # KiB/s
    bandwidth_down_kib: int = 0
    ip: str = ""
    citycode: str = ""
    countrycode: str = ""
    geocode: str = ""
    vtype: str = ""
    packetloss: float = 0.0


class Topology:
    """Parsed GraphML topology + all-pairs path computation (host side)."""

    def __init__(self, vertices: Sequence[Vertex], edges, *, directed=False,
                 prefer_direct_paths=False):
        self.vertices = list(vertices)
        # edges: list of (u_index, v_index, latency_ms, packetloss, jitter_ms)
        self.edges = list(edges)
        self.directed = directed
        self.prefer_direct_paths = prefer_direct_paths
        self._index = {v.vid: v.index for v in self.vertices}
        self._attach_rr: dict[tuple, int] = {}  # round-robin cursor per hint class
        self._lat_ms: np.ndarray | None = None
        self._jit_ms: np.ndarray | None = None
        self._rel: np.ndarray | None = None

    # ---------------------------------------------------------------- load
    @staticmethod
    def from_graphml(text_or_path) -> "Topology":
        """Load from a GraphML string, file path, or .xz file path."""
        if nx is None:  # pragma: no cover
            raise RuntimeError("networkx unavailable")
        data = text_or_path
        if not isinstance(data, str):
            data = os.fspath(data)
        if "\n" not in data and "<" not in data:
            with open(data, "rb") as f:
                raw = f.read()
            if data.endswith(".xz"):
                raw = lzma.decompress(raw)
            data = raw.decode()
        g = nx.parse_graphml(data)
        directed = g.is_directed()

        verts = []
        for i, (nid, attrs) in enumerate(g.nodes(data=True)):
            verts.append(
                Vertex(
                    vid=str(nid),
                    index=i,
                    bandwidth_up_kib=int(attrs.get("bandwidthup", 0)),
                    bandwidth_down_kib=int(attrs.get("bandwidthdown", 0)),
                    ip=str(attrs.get("ip", "")),
                    citycode=str(attrs.get("citycode", "")),
                    countrycode=str(attrs.get("countrycode", "")),
                    geocode=str(attrs.get("geocode", "")),
                    vtype=str(attrs.get("type", "")),
                    packetloss=float(attrs.get("packetloss", 0.0)),
                )
            )
        idx = {v.vid: v.index for v in verts}
        edges = []
        for u, v, attrs in g.edges(data=True):
            edges.append(
                (
                    idx[str(u)],
                    idx[str(v)],
                    float(attrs["latency"]),
                    float(attrs.get("packetloss", 0.0)),
                    float(attrs.get("jitter", 0.0)),
                )
            )
        prefer = bool(g.graph.get("preferdirectpaths", False))
        return Topology(verts, edges, directed=directed, prefer_direct_paths=prefer)

    @property
    def n_vertices(self) -> int:
        return len(self.vertices)

    # ------------------------------------------------------------- attach
    def attach(self, *, ip_hint: str = "", citycode_hint: str = "",
               countrycode_hint: str = "", geocode_hint: str = "",
               type_hint: str = "") -> int:
        """Pick the vertex a host attaches to, by hint preference classes.

        Classes, most-specific first (mirrors AttachHelper's queue ordering,
        topology.c:107-138): exact-ip, city+type, city, country+type,
        country, geo+type, geo, type, all. Within the winning class,
        assignment is deterministic round-robin (the reference draws
        randomly from its seeded RNG; round-robin keeps the same balancing
        property bit-reproducibly).
        """
        vs = self.vertices
        if ip_hint:
            exact = [v for v in vs if v.ip == ip_hint]
            if exact:
                return self._rr(("ip", ip_hint), exact)

        def match(city=None, country=None, geo=None, typ=None):
            out = []
            for v in vs:
                if city is not None and v.citycode != city:
                    continue
                if country is not None and v.countrycode != country:
                    continue
                if geo is not None and v.geocode != geo:
                    continue
                if typ is not None and v.vtype != typ:
                    continue
                out.append(v)
            return out

        classes = []
        if citycode_hint and type_hint:
            classes.append((("ct", citycode_hint, type_hint),
                            match(city=citycode_hint, typ=type_hint)))
        if citycode_hint:
            classes.append((("c", citycode_hint), match(city=citycode_hint)))
        if countrycode_hint and type_hint:
            classes.append((("nt", countrycode_hint, type_hint),
                            match(country=countrycode_hint, typ=type_hint)))
        if countrycode_hint:
            classes.append((("n", countrycode_hint), match(country=countrycode_hint)))
        if geocode_hint and type_hint:
            classes.append((("gt", geocode_hint, type_hint),
                            match(geo=geocode_hint, typ=type_hint)))
        if geocode_hint:
            classes.append((("g", geocode_hint), match(geo=geocode_hint)))
        if type_hint:
            classes.append((("t", type_hint), match(typ=type_hint)))
        classes.append((("all",), vs))
        for key, cand in classes:
            if cand:
                return self._rr(key, cand)
        raise ValueError("topology has no vertices")

    def _rr(self, key, cand):
        i = self._attach_rr.get(key, 0)
        self._attach_rr[key] = i + 1
        return cand[i % len(cand)].index

    # ------------------------------------------------- all-pairs matrices
    def _edge_matrices(self):
        """Dense [V,V] direct-edge latency (ms; inf if absent), -log
        reliability, and jitter (ms) matrices. Parallel edges keep the
        lowest latency."""
        v = self.n_vertices
        lat = np.full((v, v), np.inf)
        neglog = np.zeros((v, v))
        jit = np.zeros((v, v))
        for u, w, l, loss, j in self.edges:
            pairs = [(u, w)] if self.directed else [(u, w), (w, u)]
            for a, b in pairs:
                if l < lat[a, b]:
                    lat[a, b] = l
                    neglog[a, b] = -np.log(max(1.0 - loss, 1e-30))
                    jit[a, b] = j
        return lat, neglog, jit

    def _is_complete(self, lat: np.ndarray) -> bool:
        # every vertex must have an edge to every vertex *including itself*
        # (reference: topology.c:450-530 "_topology_isComplete")
        return bool(np.all(np.isfinite(lat)))

    def compute_all_pairs(self):
        """(latency_ms f64[V,V], reliability f32[V,V], jitter_ms f64[V,V])
        over path semantics; jitter accumulates along paths like latency
        (edge attrs, topology.c:101-105)."""
        if self._lat_ms is not None:
            return self._lat_ms, self._rel, self._jit_ms
        v = self.n_vertices
        w_lat, w_neglog, w_jit = self._edge_matrices()
        vloss = np.array([vx.packetloss for vx in self.vertices])
        v_neglog = -np.log(np.maximum(1.0 - vloss, 1e-30))

        if self._is_complete(w_lat):
            lat = w_lat.copy()
            neglog = w_neglog.copy()
            jit = w_jit.copy()
        else:
            if csr_matrix is None:  # pragma: no cover
                raise RuntimeError("scipy unavailable for Dijkstra")
            finite = np.isfinite(w_lat)
            graph = csr_matrix((w_lat[finite], np.nonzero(finite)), shape=(v, v))
            dist, pred = _csgraph_dijkstra(
                graph, directed=True, return_predecessors=True
            )
            neglog = self._path_cost_along_tree(pred, w_neglog)
            jit = self._path_cost_along_tree(pred, w_jit)
            lat = dist
            # diagonal: dijkstra gives 0; apply the self-path rule
            np.fill_diagonal(lat, np.inf)
            np.fill_diagonal(neglog, 0.0)
            np.fill_diagonal(jit, 0.0)
            self._fill_self_paths(lat, neglog, jit, w_lat, w_neglog, w_jit)
            if self.prefer_direct_paths:
                # adjacent pairs use the direct edge even if a multi-hop
                # path is shorter (topology.c:1321-1336 shouldStorePath)
                use = np.isfinite(w_lat)
                lat[use] = w_lat[use]
                neglog[use] = w_neglog[use]
                jit[use] = w_jit[use]

        # endpoint vertex loss applies for src != dst paths
        # (topology.c:1441-1463; self paths use edge loss only :1641)
        off = ~np.eye(v, dtype=bool)
        neglog = neglog + off * (v_neglog[:, None] + v_neglog[None, :])
        rel = np.exp(-neglog).astype(np.float32)
        rel[~np.isfinite(lat)] = 0.0
        self._lat_ms, self._rel, self._jit_ms = lat, rel, jit
        return lat, rel, jit

    @staticmethod
    def _path_cost_along_tree(pred: np.ndarray, w: np.ndarray) -> np.ndarray:
        """Accumulate per-edge cost `w` along the shortest-path trees.

        `pred[s, d]` is d's predecessor on the s->d shortest path. Pointer
        jumping: each round, every entry adds its predecessor's accumulated
        cost and jumps its pointer, so costs converge in O(log diameter)
        fully-vectorized rounds (the TPU-era answer to walking igraph path
        vectors one pair at a time, topology.c:1476-1510).
        """
        v = pred.shape[0]
        no_pred = pred < 0
        p = np.where(no_pred, np.arange(v)[None, :], pred)
        cost = np.where(no_pred, 0.0, w[p, np.arange(v)[None, :]])
        src = np.arange(v)[:, None]
        for _ in range(max(1, int(np.ceil(np.log2(v + 1))) + 1)):
            done = p == src
            add = np.take_along_axis(cost, p, axis=1)
            cost = cost + np.where(done, 0.0, add)
            p = np.take_along_axis(p, p, axis=1)
            if np.all(p == src):
                break
        return cost

    @staticmethod
    def _fill_self_paths(lat, neglog, jit, w_lat, w_neglog, w_jit):
        """Self paths: min-latency incident edge used twice
        (topology.c:1545-1652). A direct self-loop edge, if present, is its
        own incident edge — giving 2x its latency like the reference."""
        v = lat.shape[0]
        inc = w_lat.copy()
        best = np.argmin(inc, axis=1)
        rows = np.arange(v)
        m = inc[rows, best]
        lat[rows, rows] = 2.0 * m
        neglog[rows, rows] = 2.0 * w_neglog[rows, best]
        jit[rows, rows] = 2.0 * w_jit[rows, best]

    @property
    def min_latency_ms(self) -> float:
        """Graph-wide minimum edge latency — the conservative lookahead
        (topology.c:1374-1385, master.c:133-159). Jitter can shrink an
        edge's effective latency, so it tightens the bound."""
        if not self.edges:
            return 1.0
        return max(min(e[2] - e[4] for e in self.edges), 0.001)

    # -------------------------------------------------------- device side
    def build_network(self, host_vertex: Sequence[int]) -> "GraphNetwork":
        lat_ms, rel, jit_ms = self.compute_all_pairs()
        lat_ns = np.where(
            np.isfinite(lat_ms), lat_ms * MILLISECOND, np.int64(2**62)
        ).astype(np.int64)
        jit_ns = np.where(
            np.isfinite(lat_ms), jit_ms * MILLISECOND, 0
        ).astype(np.int64)
        return GraphNetwork(
            host2v=jnp.asarray(np.asarray(host_vertex, np.int32)),
            lat=jnp.asarray(lat_ns),
            rel=jnp.asarray(rel),
            jit=jnp.asarray(jit_ns),
        )


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class GraphNetwork:
    """Device routing tables; `route` is a pure gather (jit/vmap friendly).

    Replaces the reference's igraph Dijkstra + rwlocked path cache
    (topology.c:1268-1380) with precomputed matrices.
    """

    host2v: jax.Array  # i32[H_global] host -> attached vertex
    lat: jax.Array  # i64[V, V] path latency ns
    rel: jax.Array  # f32[V, V] path reliability
    jit: jax.Array  # i64[V, V] path jitter amplitude ns

    def route(self, src_gid, dst_gid):
        sv = self.host2v[src_gid]
        dv = self.host2v[dst_gid]
        return self.lat[sv, dv], self.rel[sv, dv], self.jit[sv, dv]

    @property
    def has_jitter(self) -> bool:
        # host-side numpy on purpose: this property is consulted from
        # inside traced code (`Engine.replace` during the fleet's
        # per-lane latency bind), where a staged `jnp.any` would be a
        # tracer and `bool()` of it a TracerBoolConversionError. The
        # routing tables are trace-time constants, so numpy stays
        # concrete there.
        return bool(np.any(np.asarray(self.jit) > 0))

    @property
    def min_latency_ns(self) -> int:
        return int(jnp.min(self.lat))

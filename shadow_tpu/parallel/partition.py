"""Locality-aware host partitioning for sharded meshes.

The reference assigns hosts to worker threads by random shuffle
(reference: src/main/core/scheduler/scheduler.c:440-534) and corrects
imbalance at runtime with work stealing
(scheduler_policy_host_steal.c:28-58). On a device mesh neither applies:
assignment is static and the cost that matters is CROSS-SHARD packets,
each of which rides the bucketed all_to_all exchange instead of a local
queue push. This module reorders hosts at build time so that hosts that
talk to each other land on the same shard.

Traffic edges come from the config itself: any process argument token
that names another host (tgen's `server=web3`, the process tier's
`client srv0 ...`, tor's `server=web1:80`) is an edge. Models whose
traffic topology is internal (tor circuit selection, bitcoin peer
graphs) can widen this by naming peers in arguments; unnamed traffic
simply keeps the config order.

The partition is a greedy capacity-bounded cluster merge (heaviest edge
first, union while the merged cluster still fits one shard), packed
first-fit-decreasing into shards. Deterministic: ties break on (weight,
gid) order, never on hash order.
"""

from __future__ import annotations

import re
from collections import defaultdict


def traffic_edges_from_config(hosts) -> list[tuple[int, int, int]]:
    """[(gid_a, gid_b, weight)] from process-argument name references.

    A token matches a host if it equals the host's name exactly or up to
    a ':port' suffix. Weight counts references (a client naming its
    server twice talks to it more).
    """
    by_name = {h.name: h.gid for h in hosts}
    weights: dict[tuple[int, int], int] = defaultdict(int)
    for h in hosts:
        for proc in h.spec.processes:
            for tok in re.split(r"[\s,=]+", proc.arguments or ""):
                tok = tok.split(":", 1)[0]
                peer = by_name.get(tok)
                if peer is None or peer == h.gid:
                    continue
                a, b = sorted((h.gid, peer))
                weights[(a, b)] += 1
    return [(a, b, w) for (a, b), w in sorted(weights.items())]


def locality_order(
    n_hosts: int, edges: list[tuple[int, int, int]], n_shards: int,
    dcn_slices: int = 1,
) -> list[int]:
    """Permutation `perm` such that placing host perm[i] at position i
    block-partitions chatty clusters onto common shards.

    Every shard receives exactly n_hosts // n_shards hosts (the engine's
    block partition requires equal shards).

    `dcn_slices` (multi-slice meshes): shards group dcn-major into
    slices of n_shards // dcn_slices — the same layout the mesh's
    block partition uses — and a cluster too large for one shard
    splits across the shards of ONE slice when any slice has the room,
    so its internal traffic rides ICI instead of DCN.
    """
    if n_hosts % n_shards:
        raise ValueError(f"{n_hosts} hosts not divisible by {n_shards}")
    if dcn_slices > 1 and n_shards % dcn_slices:
        raise ValueError(
            f"{n_shards} shards not divisible by {dcn_slices} DCN slices"
        )
    cap = n_hosts // n_shards

    parent = list(range(n_hosts))
    size = [1] * n_hosts

    def find(x: int) -> int:
        while parent[x] != x:
            parent[x] = parent[parent[x]]
            x = parent[x]
        return x

    # heaviest edges first; merge while the union still fits one shard
    for a, b, _w in sorted(edges, key=lambda e: (-e[2], e[0], e[1])):
        ra, rb = find(a), find(b)
        if ra == rb or size[ra] + size[rb] > cap:
            continue
        if size[ra] < size[rb]:
            ra, rb = rb, ra
        parent[rb] = ra
        size[ra] += size[rb]

    clusters: dict[int, list[int]] = defaultdict(list)
    for g in range(n_hosts):
        clusters[find(g)].append(g)

    # first-fit-decreasing packing into shards of exactly `cap` hosts
    shards: list[list[int]] = [[] for _ in range(n_shards)]
    for members in sorted(
        clusters.values(), key=lambda m: (-len(m), m[0])
    ):
        placed = False
        for s in shards:
            if len(s) + len(members) <= cap:
                s.extend(members)
                placed = True
                break
        if not placed:
            # split the cluster across shards (only happens when the
            # remaining free space is fragmented). On a multi-slice
            # mesh the WHOLE cluster prefers the roomiest single slice
            # before spilling to the next, so its internal traffic
            # rides ICI rather than DCN; slice order is fixed per
            # cluster, not re-chosen per chunk.
            rest = list(members)
            if dcn_slices > 1:
                per_slice = n_shards // dcn_slices

                def _free(sl: int) -> int:
                    return sum(cap - len(s) for s in
                               shards[sl * per_slice:(sl + 1) * per_slice])

                order = [
                    sl * per_slice + k
                    for sl in sorted(range(dcn_slices),
                                     key=lambda i: (-_free(i), i))
                    for k in range(per_slice)
                ]
                for idx in order:
                    if not rest:
                        break
                    take = min(cap - len(shards[idx]), len(rest))
                    shards[idx].extend(rest[:take])
                    rest = rest[take:]
            while rest:
                s = min(shards, key=len)
                take = min(cap - len(s), len(rest))
                s.extend(rest[:take])
                rest = rest[take:]

    perm = [g for s in shards for g in s]
    assert sorted(perm) == list(range(n_hosts))
    return perm


def apply_order(hosts, perm: list[int]):
    """Reorder HostInstances by `perm` and renumber gids densely.

    Returns the new list; position i holds the host formerly known as
    gid perm[i], now with gid i. Must run before DNS registration,
    attachment, or model build — every downstream gid then reflects the
    locality layout automatically.
    """
    import dataclasses

    return [
        dataclasses.replace(hosts[g], gid=i) for i, g in enumerate(perm)
    ]

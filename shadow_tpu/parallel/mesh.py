"""Host sharding across a TPU device mesh.

The reference assigns hosts to worker pthreads by random shuffle
(reference: src/main/core/scheduler/scheduler.c:440-534) and synchronizes
rounds with 6 countdown-latch barriers (scheduler.c:124-129). Here hosts are
block-partitioned across a `jax.sharding.Mesh`; every engine state leaf is
sharded on its leading host dimension; the round barrier is `lax.pmin` and
cross-shard packet delivery rides XLA collectives over ICI (SURVEY.md §2.4
"Distributed communication backend").

Multi-slice: the mesh may be 2-D ("dcn", "hosts") — slices of chips joined
over the data-center network, the reference's never-finished multi-machine
master/slave design (master.c:414-416, work/message.c stub) done properly.
Hosts block-partition over both axes (dcn-major); every collective
(pmin barrier, bucketed all_to_all exchange) runs over the combined axis
tuple, so XLA routes intra-slice traffic over ICI and inter-slice traffic
over DCN.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

HOSTS_AXIS = "hosts"
DCN_AXIS = "dcn"


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=False):
    """`jax.shard_map` across jax versions: older releases only expose
    `jax.experimental.shard_map.shard_map`, whose replication-check knob
    is spelled `check_rep` rather than `check_vma`."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _sm
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=check_vma)


def probe_spmd() -> str:
    """Which shard_map this jax ships: "shard_map" (public `jax.shard_map`)
    or "shard_map_exp" (`jax.experimental.shard_map`, every release back
    to 0.4.x). Both are safe for this engine: the experimental one's
    check_rep=False miscompile only fires when a collective sits inside a
    while/cond predicate, and core.engine carries every such flag through
    the loop body instead (the SL108 rule pins this structurally). The
    probe exists so path selection and error messages can name what the
    running jax actually supports."""
    if hasattr(jax, "shard_map"):
        return "shard_map"
    try:
        from jax.experimental.shard_map import shard_map as _sm  # noqa: F401
        return "shard_map_exp"
    except ImportError:  # pragma: no cover - ancient jax
        return "pmap"


def select_spmd(spmd: str = "auto") -> str:
    """Resolve an --spmd request to the executed path: "shard_map",
    "constraint" (jit + explicit shardings, GSPMD partitioning), or
    "pmap" (the legacy 1-D fallback). "auto" takes shard_map whenever
    the probe finds one (public or experimental) and only falls back to
    pmap on a jax with neither."""
    if spmd not in ("auto", "shard_map", "constraint", "pmap"):
        raise ValueError(
            f"spmd must be auto|shard_map|constraint|pmap, got {spmd!r}"
        )
    if spmd == "auto":
        return "shard_map" if probe_spmd() != "pmap" else "pmap"
    return spmd


def make_mesh(n_devices: int | None = None, axis: str = HOSTS_AXIS,
              dcn_slices: int = 1) -> Mesh:
    devs = jax.devices()
    if n_devices is not None:
        if len(devs) < n_devices:
            raise RuntimeError(
                f"need {n_devices} devices, found {len(devs)} "
                f"(set --xla_force_host_platform_device_count for CPU testing)"
            )
        devs = devs[:n_devices]
    if dcn_slices > 1:
        n = len(devs)
        if n % dcn_slices:
            raise ValueError(
                f"{n} devices not divisible by {dcn_slices} DCN slices"
            )
        return Mesh(
            np.array(devs).reshape(dcn_slices, n // dcn_slices),
            (DCN_AXIS, axis),
        )
    return Mesh(np.array(devs), (axis,))


def hosts_axes(mesh: Mesh):
    """The axis name (1-D mesh) or axis-name tuple (multi-slice mesh)
    hosts are sharded over — valid anywhere an axis_name is accepted."""
    names = mesh.axis_names
    return names[0] if len(names) == 1 else tuple(names)


def state_specs(st, n_hosts_local: int, axis: str = HOSTS_AXIS):
    """PartitionSpec pytree for an EngineState: leaves with a leading
    per-shard host dim shard on `axis`; scalars (now, n_windows) replicate.
    The exchange double buffer (EngineState.xchg) is per-shard PRIVATE
    state — its leaves shard on `axis` unconditionally, never replicate,
    whatever their leading dim happens to equal."""

    def spec(leaf):
        if leaf.ndim >= 1 and leaf.shape[0] == n_hosts_local:
            return P(axis)
        return P()

    specs = jax.tree.map(spec, st)
    xchg = getattr(st, "xchg", None)
    if xchg is not None:
        import dataclasses as _dc

        specs = _dc.replace(
            specs,
            xchg=jax.tree.map(
                lambda leaf: P(axis) if leaf.ndim >= 1 else P(), xchg
            ),
        )
    return specs


def pmap_call(fn, mesh: Mesh, specs, per: int, axes):
    """Run `fn(state, stop, host0)` data-parallel via `jax.pmap`.

    Fallback for jax versions without `jax.shard_map`: their experimental
    shard_map miscompiles this engine under check_rep=False (collectives
    inside while/cond conds leak device 0's carried state to every shard
    — observed as hosts on shard > 0 recording wrong peer gids), while
    the mature pmap path compiles the identical program correctly.

    `specs` is the state's PartitionSpec pytree: leaves sharded on the
    mesh axis reshape [S*d0, ...] <-> [S, d0, ...] around the pmap
    (d0 = leading dim / S: host-dim leaves use `per`, the exchange
    buffer its own width); replicated leaves broadcast in and take
    device 0's copy out (the same contract shard_map's P() out_spec
    has).
    """
    if not isinstance(axes, str):
        raise NotImplementedError(
            "the pmap fallback is single-axis only: a multi-slice "
            "(dcn x hosts) mesh must run through the SPMD paths — this "
            f"jax's capability probe says {probe_spmd()!r}, so build "
            "with spmd='auto' (selects "
            f"{select_spmd('auto')!r}) or spmd='constraint' instead of "
            "spmd='pmap'"
        )
    n = int(np.prod(mesh.devices.shape))
    mask = jax.tree.map(lambda sp: len(sp) > 0, specs)
    in_axes = jax.tree.map(lambda m: 0 if m else None, mask)

    def split(st):
        return jax.tree.map(
            lambda x, m: x.reshape((n, x.shape[0] // n) + x.shape[1:])
            if m else x,
            st, mask,
        )

    def join(st):
        return jax.tree.map(
            lambda x, m: x.reshape((x.shape[0] * x.shape[1],) + x.shape[2:])
            if m else x,
            st, mask,
        )

    pf = jax.pmap(
        lambda st, stop: fn(
            st, stop, jax.lax.axis_index(axes).astype(jnp.int32) * per
        ),
        axis_name=axes,
        in_axes=(in_axes, None),
        out_axes=in_axes,
        devices=list(mesh.devices.flatten()),
    )

    def call(st, stop):
        return join(pf(split(st), stop))

    return call


def build_sharded(eng, init_fn, mesh: Mesh, n_hosts_local: int,
                  axis: str = HOSTS_AXIS, spmd: str = "auto"):
    """Wrap an axis-aware Engine into sharded init/run/step callables.

    `eng` must have been built with axis_name=axis and per-shard host count
    n_hosts_local. Returns (init, run, step_window), all jitted over `mesh`:
    init() -> sharded EngineState; run(st, stop) / step_window(st, stop).

    `spmd` picks the execution path (see `select_spmd`): "auto" resolves
    to shard_map — public or experimental, both safe now that the engine
    carries every loop flag through the body (no collective ever sits in
    a lowered predicate) — and "pmap" keeps the legacy 1-D fallback
    alive for soak comparison.
    """
    path = select_spmd(spmd)
    if path == "constraint":
        raise ValueError(
            "spmd='constraint' partitions a GLOBAL (axis_name=None) "
            "engine with GSPMD and cannot wrap this per-shard engine; "
            "build it via sim.build_simulation(..., spmd='constraint')"
        )

    def _host0():
        return jax.lax.axis_index(axis).astype(jnp.int32) * n_hosts_local

    template = jax.eval_shape(init_fn, jnp.zeros((), jnp.int32))
    specs = state_specs(template, n_hosts_local, axis)

    init = jax.jit(
        shard_map(
            lambda: init_fn(_host0()),
            mesh=mesh,
            in_specs=(),
            out_specs=specs,
            check_vma=False,
        )
    )

    def _wrap(fn):
        if path == "pmap":
            return pmap_call(fn, mesh, specs, n_hosts_local, axis)
        # no donate_argnums here: this is the raw API and callers (tests,
        # smoke entries) legitimately reread their input state after the
        # call. The managed path (sim.Simulation) donates — it tracks
        # state ownership and can prove the input buffer is dead.
        return jax.jit(
            shard_map(
                lambda s, t: fn(s, t, _host0()),
                mesh=mesh,
                in_specs=(specs, P()),
                out_specs=specs,
                check_vma=False,
            )
        )

    return init, _wrap(eng.run), _wrap(eng.step_window)

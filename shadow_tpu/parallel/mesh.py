"""Host sharding across a TPU device mesh.

The reference assigns hosts to worker pthreads by random shuffle
(reference: src/main/core/scheduler/scheduler.c:440-534) and synchronizes
rounds with 6 countdown-latch barriers (scheduler.c:124-129). Here hosts are
block-partitioned across a `jax.sharding.Mesh`; every engine state leaf is
sharded on its leading host dimension; the round barrier is `lax.pmin` and
cross-shard packet delivery rides XLA collectives over ICI (SURVEY.md §2.4
"Distributed communication backend").

Multi-slice: the mesh may be 2-D ("dcn", "hosts") — slices of chips joined
over the data-center network, the reference's never-finished multi-machine
master/slave design (master.c:414-416, work/message.c stub) done properly.
Hosts block-partition over both axes (dcn-major); every collective
(pmin barrier, bucketed all_to_all exchange) runs over the combined axis
tuple, so XLA routes intra-slice traffic over ICI and inter-slice traffic
over DCN.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

HOSTS_AXIS = "hosts"
DCN_AXIS = "dcn"


def make_mesh(n_devices: int | None = None, axis: str = HOSTS_AXIS,
              dcn_slices: int = 1) -> Mesh:
    devs = jax.devices()
    if n_devices is not None:
        if len(devs) < n_devices:
            raise RuntimeError(
                f"need {n_devices} devices, found {len(devs)} "
                f"(set --xla_force_host_platform_device_count for CPU testing)"
            )
        devs = devs[:n_devices]
    if dcn_slices > 1:
        n = len(devs)
        if n % dcn_slices:
            raise ValueError(
                f"{n} devices not divisible by {dcn_slices} DCN slices"
            )
        return Mesh(
            np.array(devs).reshape(dcn_slices, n // dcn_slices),
            (DCN_AXIS, axis),
        )
    return Mesh(np.array(devs), (axis,))


def hosts_axes(mesh: Mesh):
    """The axis name (1-D mesh) or axis-name tuple (multi-slice mesh)
    hosts are sharded over — valid anywhere an axis_name is accepted."""
    names = mesh.axis_names
    return names[0] if len(names) == 1 else tuple(names)


def state_specs(st, n_hosts_local: int, axis: str = HOSTS_AXIS):
    """PartitionSpec pytree for an EngineState: leaves with a leading
    per-shard host dim shard on `axis`; scalars (now, n_windows) replicate."""

    def spec(leaf):
        if leaf.ndim >= 1 and leaf.shape[0] == n_hosts_local:
            return P(axis)
        return P()

    return jax.tree.map(spec, st)


def build_sharded(eng, init_fn, mesh: Mesh, n_hosts_local: int, axis: str = HOSTS_AXIS):
    """Wrap an axis-aware Engine into sharded init/run/step callables.

    `eng` must have been built with axis_name=axis and per-shard host count
    n_hosts_local. Returns (init, run, step_window), all jitted over `mesh`:
    init() -> sharded EngineState; run(st, stop) / step_window(st, stop).
    """

    def _host0():
        return jax.lax.axis_index(axis).astype(jnp.int32) * n_hosts_local

    template = jax.eval_shape(init_fn, jnp.zeros((), jnp.int32))
    specs = state_specs(template, n_hosts_local, axis)

    init = jax.jit(
        jax.shard_map(
            lambda: init_fn(_host0()),
            mesh=mesh,
            in_specs=(),
            out_specs=specs,
            check_vma=False,
        )
    )

    def _wrap(fn):
        return jax.jit(
            jax.shard_map(
                lambda s, t: fn(s, t, _host0()),
                mesh=mesh,
                in_specs=(specs, P()),
                out_specs=specs,
                check_vma=False,
            )
        )

    return init, _wrap(eng.run), _wrap(eng.step_window)

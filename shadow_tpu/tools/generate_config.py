"""Generate ready-to-run example simulation configs.

The reference ships generate_example_config.py, which writes a
shadow.config.xml plus tgen client/server graphml files
(reference: src/tools/generate_example_config.py). This generator covers
the same ground from the bundled example builders: every BASELINE.md
config shape (tgen pairs, tor circuits, bitcoin gossip, phold) plus the
tgen traffic-graph files our tgen model parses.

    python -m shadow_tpu.tools.generate_config tgen -o example/
    python -m shadow_tpu.tools.generate_config tor --clients 60 -o ex/
"""

from __future__ import annotations

import argparse
import os
import sys

from shadow_tpu.examples import (
    bitcoin_example,
    example_config,
    phold_example,
    tor_example,
)

TGEN_SERVER_GRAPHML = """<?xml version="1.0" encoding="utf-8"?>
<graphml xmlns="http://graphml.graphdrawing.org/xmlns">
  <key attr.name="serverport" attr.type="string" for="node" id="d0"/>
  <graph edgedefault="directed">
    <node id="start"><data key="d0">8888</data></node>
  </graph>
</graphml>
"""

TGEN_CLIENT_GRAPHML = """<?xml version="1.0" encoding="utf-8"?>
<graphml xmlns="http://graphml.graphdrawing.org/xmlns">
  <key attr.name="peers" attr.type="string" for="node" id="d0"/>
  <key attr.name="sendsize" attr.type="string" for="node" id="d1"/>
  <key attr.name="recvsize" attr.type="string" for="node" id="d2"/>
  <key attr.name="count" attr.type="string" for="node" id="d3"/>
  <key attr.name="time" attr.type="string" for="node" id="d4"/>
  <graph edgedefault="directed">
    <node id="start"><data key="d0">server:8888</data></node>
    <node id="transfer">
      <data key="d1">64 KiB</data>
      <data key="d2">1 MiB</data>
      <data key="d3">3</data>
    </node>
    <node id="pause"><data key="d4">5</data></node>
    <edge source="start" target="transfer"/>
    <edge source="transfer" target="pause"/>
    <edge source="pause" target="transfer"/>
  </graph>
</graphml>
"""


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("kind", choices=["tgen", "tor", "bitcoin", "phold"])
    p.add_argument("-o", "--out", default=".",
                   help="output directory (created if missing)")
    p.add_argument("--hosts", type=int, default=None,
                   help="host/node count (model-dependent default)")
    p.add_argument("--clients", type=int, default=None,
                   help="tor: client count")
    p.add_argument("--stoptime", type=int, default=None)
    args = p.parse_args(argv)

    os.makedirs(args.out, exist_ok=True)
    extras: dict[str, str] = {}
    if args.kind == "tgen":
        text = example_config()
        extras = {
            "tgen.server.graphml.xml": TGEN_SERVER_GRAPHML,
            "tgen.client.graphml.xml": TGEN_CLIENT_GRAPHML,
        }
    elif args.kind == "tor":
        kw = {}
        if args.clients:
            kw["n_clients"] = args.clients
        if args.stoptime:
            kw["stoptime"] = args.stoptime
        text = tor_example(**kw)
    elif args.kind == "bitcoin":
        kw = {}
        if args.hosts:
            kw["n_nodes"] = args.hosts
        if args.stoptime:
            kw["stoptime"] = args.stoptime
        text = bitcoin_example(**kw)
    else:
        kw = {}
        if args.hosts:
            kw["n_hosts"] = args.hosts
        if args.stoptime:
            kw["stoptime"] = args.stoptime
        text = phold_example(**kw)

    cfg_path = os.path.join(args.out, "shadow.config.xml")
    with open(cfg_path, "w") as f:
        f.write(text)
    for name, body in extras.items():
        with open(os.path.join(args.out, name), "w") as f:
            f.write(body)
    print(f"wrote {cfg_path}"
          + (f" + {', '.join(extras)}" if extras else ""))
    print(f"run it: python -m shadow_tpu {cfg_path}")
    return 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())

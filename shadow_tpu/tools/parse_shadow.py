"""parse_shadow: heartbeat log -> stats.shadow.json.

The reference's parse-shadow.py greps `[shadow-heartbeat]` lines out of
(possibly xz-compressed) simulator logs and writes a per-node JSON time
series consumed by plot-shadow.py (reference: src/tools/parse-shadow.py:
9-40, stats.shadow.json). This tool does the same for shadow_tpu's
heartbeat format (utils/tracker.py): per node, per interval, the
payload/wire/header byte classes, packet counts, retransmissions, events
and drops — plus a run-level ticks series.

Usage:
    python -m shadow_tpu.tools.parse_shadow shadow.log [-o DIR]
    ... | python -m shadow_tpu.tools.parse_shadow -
"""

from __future__ import annotations

import argparse
import json
import lzma
import os
import sys

NODE_FIELDS = (
    "bytes_payload_recv", "bytes_payload_send",
    "bytes_wire_recv", "bytes_wire_send",
    "packets_recv", "packets_send",
    "bytes_header_recv", "bytes_header_send",
    "retrans_segments", "events_executed", "queue_drops", "tail_drops",
)


RAM_FIELDS = (
    "queue_slots_used", "queue_capacity", "sockets_used",
    "sockets_capacity", "state_bytes",
)


def parse_lines(lines) -> dict:
    nodes: dict[str, dict] = {}
    sockets: dict[str, list] = {}
    ram: dict[str, dict] = {}
    for line in lines:
        if "[shadow-heartbeat] [node] " in line:
            csv = line.rsplit("[shadow-heartbeat] [node] ", 1)[1].strip()
            parts = csv.split(",")
            if len(parts) != 2 + len(NODE_FIELDS):
                continue
            t_s, name = int(parts[0]), parts[1]
            node = nodes.setdefault(
                name,
                {"ticks": [], **{f: [] for f in NODE_FIELDS}},
            )
            node["ticks"].append(t_s)
            for f, v in zip(NODE_FIELDS, parts[2:]):
                node[f].append(int(v))
        elif "[shadow-heartbeat] [socket] " in line:
            csv = line.rsplit("[shadow-heartbeat] [socket] ", 1)[1].strip()
            parts = csv.split(",")
            if len(parts) != 10:
                continue
            sockets.setdefault(parts[1], []).append(
                {
                    "time": int(parts[0]),
                    "slot": int(parts[2]),
                    "protocol": parts[3],
                    "local_port": int(parts[4]),
                    "peer_host": int(parts[5]),
                    "peer_port": int(parts[6]),
                    "recv_bytes": int(parts[7]),
                    "send_bytes": int(parts[8]),
                    "retrans_segments": int(parts[9]),
                }
            )
        elif "[shadow-heartbeat] [ram] " in line:
            csv = line.rsplit("[shadow-heartbeat] [ram] ", 1)[1].strip()
            parts = csv.split(",")
            if len(parts) != 2 + len(RAM_FIELDS):
                continue
            node = ram.setdefault(
                parts[1], {"ticks": [], **{f: [] for f in RAM_FIELDS}}
            )
            node["ticks"].append(int(parts[0]))
            for f, v in zip(RAM_FIELDS, parts[2:]):
                node[f].append(int(v))
    return {"nodes": nodes, "sockets": sockets, "ram": ram}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("logpath", help="log file, .xz allowed, or - for stdin")
    ap.add_argument("-o", "--output-dir", default=".",
                    help="directory for stats.shadow_tpu.json")
    args = ap.parse_args(argv)

    if args.logpath == "-":
        stats = parse_lines(sys.stdin)
    elif args.logpath.endswith(".xz"):
        with lzma.open(args.logpath, "rt") as f:
            stats = parse_lines(f)
    else:
        with open(args.logpath) as f:
            stats = parse_lines(f)

    out = os.path.join(args.output_dir, "stats.shadow_tpu.json")
    with open(out, "w") as f:
        json.dump(stats, f)
    print(out)
    return 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())

"""parse_shadow: heartbeat log -> stats.shadow.json.

The reference's parse-shadow.py greps `[shadow-heartbeat]` lines out of
(possibly xz-compressed) simulator logs and writes a per-node JSON time
series consumed by plot-shadow.py (reference: src/tools/parse-shadow.py:
9-40, stats.shadow.json). This tool does the same for shadow_tpu's
heartbeat format (utils/tracker.py): per node, per interval, the
payload/wire/header byte classes, packet counts, retransmissions, events
and drops — plus a run-level ticks series.

Usage:
    python -m shadow_tpu.tools.parse_shadow shadow.log [-o DIR]
    ... | python -m shadow_tpu.tools.parse_shadow -
"""

from __future__ import annotations

import argparse
import json
import lzma
import os
import sys

NODE_FIELDS = (
    "bytes_payload_recv", "bytes_payload_send",
    "bytes_wire_recv", "bytes_wire_send",
    "packets_recv", "packets_send",
    "bytes_header_recv", "bytes_header_send",
    "retrans_segments", "events_executed", "queue_drops", "tail_drops",
)


RAM_FIELDS = (
    "queue_slots_used", "queue_capacity", "sockets_used",
    "sockets_capacity", "state_bytes",
)

# the [fault] section only appears when the run had a fault schedule;
# downtime is fractional seconds, everything else integer counts
FAULT_FIELDS = ("fault_drops", "quarantined_events", "downtime_seconds")

# whole-run [supervisor] rows: wall rates + watchdog margin (the margin
# column may be empty when no watchdog was armed)
SUPERVISOR_FIELDS = (
    "windows", "windows_per_sec", "events_per_sec",
    "stall_margin_seconds", "checkpoints_written",
)

# exact per-host record counts from the --trace drain
TRACE_FIELDS = (
    "exec_records", "send_records", "net_drop_records",
    "fault_drop_records", "lost_records",
)

# whole-run [pressure] rows (only with --overflow spill/grow): one
# aggregate interval row — harvest_seconds is wall clock, the rest are
# event counts / the high-water queue fill
PRESSURE_FIELDS = (
    "hosts_pressured", "fill_hwm", "spilled", "refilled",
    "spill_lost", "reservoir_resident", "overdue", "harvest_seconds",
)

# per-LANE [fleet] rows (only with --fleet): one row per lane per
# heartbeat, keyed by lane index. `seed` is constant per lane, `fill`
# is the lane's mean queue occupancy in [0, 1]; the rest are the lane's
# cumulative solo-equivalent summary counters plus the interval delta
FLEET_FIELDS = ("seed", "now_seconds", "windows", "events",
                "events_delta", "queue_drops", "fill")

# whole-run [metrics] rows (only with --metrics): the telemetry
# registry's CUMULATIVE totals — unlike the interval-delta sections
# above, these columns match a live /metrics scrape and the end-of-run
# summary directly (queue_fill is the 0..1 occupancy gauge)
METRICS_FIELDS = (
    "events", "queue_drops", "net_dropped", "fault_dropped",
    "cross_shard_packets", "rx_bytes", "tx_bytes", "queue_fill",
    "heartbeats",
)

# whole-run [stats] rows (only with --stats): per histogram family the
# CUMULATIVE sample count, value sum, p50/p95, and the sparse bucket
# spec ("idx:count|..."), decoded here into {bucket-index: count} so
# plot_shadow can rebuild the full log2 distributions from the log
# alone. Column names come from the [stats-header] row when present
# (forward-compatible with new families); this is the current default.
STATS_FAMILIES = ("wait", "net", "occ", "qfill", "runlen")
STATS_COLS = tuple(
    f"{k}_{c}" for k in STATS_FAMILIES
    for c in ("count", "sum", "p50", "p95", "hist")
)


def _sort_series(series: dict, key: str = "ticks") -> None:
    """Stable-sort one tick-keyed column store in place. Heartbeat
    sections are buffered independently (and a resumed or sharded run
    may flush them interleaved), so consumers must not assume block
    contiguity — normalize to tick order here, preserving emission
    order within a tick."""
    ticks = series.get(key)
    if not ticks or all(a <= b for a, b in zip(ticks, ticks[1:])):
        return
    order = sorted(range(len(ticks)), key=ticks.__getitem__)
    for k, col in series.items():
        if isinstance(col, list) and len(col) == len(ticks):
            series[k] = [col[i] for i in order]


def parse_lines(lines) -> dict:
    nodes: dict[str, dict] = {}
    sockets: dict[str, list] = {}
    ram: dict[str, dict] = {}
    faults: dict[str, dict] = {}
    trace: dict[str, dict] = {}
    fleet: dict[str, dict] = {}
    supervisor: dict[str, list] = {
        "ticks": [], **{f: [] for f in SUPERVISOR_FIELDS}
    }
    pressure: dict[str, list] = {
        "ticks": [], **{f: [] for f in PRESSURE_FIELDS}
    }
    metrics: dict[str, list] = {
        "ticks": [], **{f: [] for f in METRICS_FIELDS}
    }
    stats: dict[str, list] = {
        "ticks": [], **{f: [] for f in STATS_COLS}
    }
    stats_cols: tuple[str, ...] = STATS_COLS
    for line in lines:
        if "[shadow-heartbeat] [node] " in line:
            csv = line.rsplit("[shadow-heartbeat] [node] ", 1)[1].strip()
            parts = csv.split(",")
            if len(parts) != 2 + len(NODE_FIELDS):
                continue
            t_s, name = int(parts[0]), parts[1]
            node = nodes.setdefault(
                name,
                {"ticks": [], **{f: [] for f in NODE_FIELDS}},
            )
            node["ticks"].append(t_s)
            for f, v in zip(NODE_FIELDS, parts[2:]):
                node[f].append(int(v))
        elif "[shadow-heartbeat] [socket] " in line:
            csv = line.rsplit("[shadow-heartbeat] [socket] ", 1)[1].strip()
            parts = csv.split(",")
            if len(parts) != 10:
                continue
            sockets.setdefault(parts[1], []).append(
                {
                    "time": int(parts[0]),
                    "slot": int(parts[2]),
                    "protocol": parts[3],
                    "local_port": int(parts[4]),
                    "peer_host": int(parts[5]),
                    "peer_port": int(parts[6]),
                    "recv_bytes": int(parts[7]),
                    "send_bytes": int(parts[8]),
                    "retrans_segments": int(parts[9]),
                }
            )
        elif "[shadow-heartbeat] [ram] " in line:
            csv = line.rsplit("[shadow-heartbeat] [ram] ", 1)[1].strip()
            parts = csv.split(",")
            if len(parts) != 2 + len(RAM_FIELDS):
                continue
            node = ram.setdefault(
                parts[1], {"ticks": [], **{f: [] for f in RAM_FIELDS}}
            )
            node["ticks"].append(int(parts[0]))
            for f, v in zip(RAM_FIELDS, parts[2:]):
                node[f].append(int(v))
        elif "[shadow-heartbeat] [fault] " in line:
            csv = line.rsplit("[shadow-heartbeat] [fault] ", 1)[1].strip()
            parts = csv.split(",")
            if len(parts) != 2 + len(FAULT_FIELDS):
                continue
            node = faults.setdefault(
                parts[1], {"ticks": [], **{f: [] for f in FAULT_FIELDS}}
            )
            node["ticks"].append(int(parts[0]))
            node["fault_drops"].append(int(parts[2]))
            node["quarantined_events"].append(int(parts[3]))
            node["downtime_seconds"].append(float(parts[4]))
        elif "[shadow-heartbeat] [trace] " in line:
            csv = line.rsplit("[shadow-heartbeat] [trace] ", 1)[1].strip()
            parts = csv.split(",")
            if len(parts) != 2 + len(TRACE_FIELDS):
                continue
            node = trace.setdefault(
                parts[1], {"ticks": [], **{f: [] for f in TRACE_FIELDS}}
            )
            node["ticks"].append(int(parts[0]))
            for f, v in zip(TRACE_FIELDS, parts[2:]):
                node[f].append(int(v))
        elif "[shadow-heartbeat] [pressure] " in line:
            csv = line.rsplit("[shadow-heartbeat] [pressure] ", 1)[1].strip()
            parts = csv.split(",")
            if len(parts) != 1 + len(PRESSURE_FIELDS):
                continue
            pressure["ticks"].append(int(parts[0]))
            for f, v in zip(PRESSURE_FIELDS[:-1], parts[1:-1]):
                pressure[f].append(int(v))
            # harvest_seconds is wall clock; strip_log may blank it
            pressure["harvest_seconds"].append(
                float(parts[-1]) if parts[-1] else None
            )
        elif "[shadow-heartbeat] [fleet] " in line:
            csv = line.rsplit("[shadow-heartbeat] [fleet] ", 1)[1].strip()
            parts = csv.split(",")
            if len(parts) != 2 + len(FLEET_FIELDS):
                continue
            lane = fleet.setdefault(
                parts[1], {"ticks": [], **{f: [] for f in FLEET_FIELDS}}
            )
            lane["ticks"].append(int(parts[0]))
            for f, v in zip(FLEET_FIELDS, parts[2:]):
                lane[f].append(float(v) if f == "fill" else int(v))
        elif "[shadow-heartbeat] [supervisor] " in line:
            csv = line.rsplit(
                "[shadow-heartbeat] [supervisor] ", 1
            )[1].strip()
            parts = csv.split(",")
            if len(parts) != 1 + len(SUPERVISOR_FIELDS):
                continue
            supervisor["ticks"].append(int(parts[0]))
            supervisor["windows"].append(int(parts[1]))
            supervisor["windows_per_sec"].append(float(parts[2]))
            supervisor["events_per_sec"].append(float(parts[3]))
            supervisor["stall_margin_seconds"].append(
                float(parts[4]) if parts[4] else None
            )
            supervisor["checkpoints_written"].append(int(parts[5]))
        elif "[shadow-heartbeat] [metrics] " in line:
            csv = line.rsplit("[shadow-heartbeat] [metrics] ", 1)[1].strip()
            parts = csv.split(",")
            if len(parts) != 1 + len(METRICS_FIELDS):
                continue
            metrics["ticks"].append(int(parts[0]))
            for f, v in zip(METRICS_FIELDS, parts[1:]):
                metrics[f].append(
                    float(v) if f == "queue_fill" else int(v)
                )
        elif "[shadow-heartbeat] [stats-header] " in line:
            csv = line.rsplit(
                "[shadow-heartbeat] [stats-header] ", 1
            )[1].strip()
            cols = tuple(csv.split(",")[1:])  # drop the t_s column
            if cols and cols != stats_cols:
                stats_cols = cols
                for f in cols:
                    stats.setdefault(f, [])
        elif "[shadow-heartbeat] [stats] " in line:
            csv = line.rsplit("[shadow-heartbeat] [stats] ", 1)[1].strip()
            parts = csv.split(",")
            if len(parts) != 1 + len(stats_cols):
                continue
            stats["ticks"].append(float(parts[0]))
            for f, v in zip(stats_cols, parts[1:]):
                if f.endswith("_hist"):
                    stats[f].append({
                        p.split(":", 1)[0]: int(p.split(":", 1)[1])
                        for p in v.split("|") if p
                    })
                else:
                    stats[f].append(float(v) if "." in v else int(v))
    # tolerate interleaved optional sections: logs from resumed/sharded
    # runs (or concatenated shards) need not keep each section's rows
    # contiguous or tick-ordered
    for series in (supervisor, pressure, metrics, stats):
        _sort_series(series)
    for per_name in (nodes, ram, faults, trace, fleet):
        for series in per_name.values():
            _sort_series(series)
    for rows in sockets.values():
        rows.sort(key=lambda r: r["time"])
    return {"nodes": nodes, "sockets": sockets, "ram": ram,
            "faults": faults, "trace": trace, "fleet": fleet,
            "supervisor": supervisor, "pressure": pressure,
            "metrics": metrics, "stats": stats}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("logpath", help="log file, .xz allowed, or - for stdin")
    ap.add_argument("-o", "--output-dir", default=".",
                    help="directory for stats.shadow_tpu.json")
    args = ap.parse_args(argv)

    if args.logpath == "-":
        stats = parse_lines(sys.stdin)
    elif args.logpath.endswith(".xz"):
        with lzma.open(args.logpath, "rt") as f:
            stats = parse_lines(f)
    else:
        with open(args.logpath) as f:
            stats = parse_lines(f)

    out = os.path.join(args.output_dir, "stats.shadow_tpu.json")
    with open(out, "w") as f:
        json.dump(stats, f)
    print(out)
    return 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())

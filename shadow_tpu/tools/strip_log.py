"""Normalize simulation logs for determinism diffs.

The reference strips per-run noise (memory addresses, wall-clock run
timing) from log files so repeated experiments can be compared byte for
byte (reference: src/tools/strip_log_for_compare.py; the determinism
tests diff host stdout the same way,
src/test/determinism/determinism1_compare.cmake). shadow_tpu logs carry
different noise: wall-clock fields in summary JSON, build/compile
timings, host hex ids in tracebacks. This tool keeps the
simulation-determined content only.

    python -m shadow_tpu.tools.strip_log run.log stripped.log
    diff <(... run1) <(... run2)
"""

from __future__ import annotations

import json
import re
import sys

# wall-clock-derived summary fields (everything else in the summary is
# simulation-determined and must be identical across repeat runs);
# "profile" is the --profile phase/occupancy report — wall timing
_WALL_KEYS = {
    "wall_seconds", "build_seconds", "events_per_sec", "sim_s_per_wall_s",
    "profile",
}

_HEX_ADDR = re.compile(r"0x[0-9a-fA-F]{6,}")
# [supervisor] heartbeat rows mix sim-determined fields (time, windows,
# checkpoints) with wall-clock rates and the watchdog stall margin;
# blank out only the wall-derived columns so the rest still diffs
_SUPERVISOR = re.compile(
    r"(\[shadow-heartbeat\] \[supervisor\] \d+,\d+,)"
    r"[0-9.]*,[0-9.]*,[0-9.]*(,\d+)$"
)
# [pressure] rows are sim-determined except the trailing harvest
# wall-clock column
_PRESSURE = re.compile(
    r"(\[shadow-heartbeat\] \[pressure\] (?:\d+,){7}\d+,)[0-9.]*$"
)


def strip_line(line: str) -> str | None:
    """Normalized line, or None to drop it entirely."""
    s = line.rstrip("\n")
    if s.startswith("{") and s.endswith("}"):
        try:
            obj = json.loads(s)
        except json.JSONDecodeError:
            obj = None
        if isinstance(obj, dict):
            for k in _WALL_KEYS:
                obj.pop(k, None)
            if isinstance(obj.get("pressure"), dict):
                obj["pressure"].pop("harvest_seconds", None)
            return json.dumps(obj, sort_keys=True)
    # progress/timing diagnostics are wall-clock noise
    if "compile" in s and "second" in s:
        return None
    s = _SUPERVISOR.sub(r"\g<1>W,W,W\g<2>", s)
    s = _PRESSURE.sub(r"\g<1>W", s)
    return _HEX_ADDR.sub("0xADDR", s)


def main(argv=None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    if len(argv) < 2:
        print("usage: strip_log <logfile> <outputfile>", file=sys.stderr)
        return 2
    with open(argv[0]) as fin, open(argv[1], "w") as fout:
        for line in fin:
            out = strip_line(line)
            if out is not None:
                fout.write(out + "\n")
    return 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())

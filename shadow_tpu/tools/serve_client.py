"""Load generator / client for the resident scenario service.

Submits a mixed-compatible request stream to a running `shadow_tpu
serve` instance (stdlib urllib only), polls results, optionally writes
each completed record to a directory (one `<request_id>.json` per
request — the exact artifact `tools/diff_runs.py` diffs against a solo
summary for the bit-identity gate), and prints one machine-readable
JSON line with throughput and latency percentiles.

When the server runs with `--trace-requests` (docs/18-Serve-Tracing.md)
the client also fetches each request's span tree from `/trace/<id>`,
writes it beside the result artifact (`<request_id>.trace.json`), and
the report gains per-class p50/p95/p99 of the queue-wait / pack-wait /
run decomposition. A server without tracing answers 404 there; the
client just skips those fields.

The default mix alternates two static-knob equivalence classes over one
phold shape — a plain seed sweep and a crash-fault class with varied
stop times and latency scales — so a 16-request run exercises lane
packing, inert-lane padding, AND the warm program cache (>= 1 hit per
class after the first launch). `--mix plain` keeps one class.

    python -m shadow_tpu.tools.serve_client --url http://127.0.0.1:8421 \
        --requests 16 --out-dir served/
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.error
import urllib.request


def request_docs(n: int, *, mix: str = "mixed", hosts: int = 8,
                 stop_s: float = 1.0, seed0: int = 100) -> list[dict]:
    """The deterministic request stream: request i is a function of
    (i, seed0) only, so a replayed stream packs identically and the
    solo references are reproducible."""
    params = {"hosts": hosts, "capacity": 64, "msgs_per_host": 2}
    docs = []
    for i in range(n):
        doc = {"model": "phold", "params": dict(params),
               "seed": seed0 + i, "stop_s": stop_s}
        if mix == "mixed" and i % 2 == 1:
            # the second equivalence class: crash faults, varied stops
            # and a latency-scaled lane every fourth request
            doc["faults"] = [
                f"crash hosts=host{i % hosts} start=0.2 end=0.5"
            ]
            doc["stop_s"] = stop_s * (0.75 if i % 4 == 1 else 1.0)
            if i % 4 == 3:
                doc["latency_scale"] = 1.5
        docs.append(doc)
    return docs


# Bounded connection retry across a server restart window: an elastic
# server that hits device loss exits and is relaunched by its --retry
# wrapper, so every request in flight from the CLIENT side sees
# connection-refused/reset for a second or two. `_RETRY` is module
# state so the report can surface how often the window was crossed;
# retries=0 (the default) keeps the old fail-fast behavior.
_RETRY = {"retries": 0, "backoff_s": 0.25, "count": 0}


def _http(url: str, data: bytes | None = None, timeout: float = 10.0):
    req = urllib.request.Request(
        url, data=data,
        headers={"Content-Type": "application/json"} if data else {})
    attempt = 0
    while True:
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                return resp.status, json.loads(
                    resp.read().decode("utf-8"))
        except (urllib.error.URLError, ConnectionResetError) as e:
            # RemoteDisconnected subclasses ConnectionResetError and
            # sometimes escapes urllib unwrapped mid-restart
            reason = getattr(e, "reason", e)
            refused = isinstance(
                reason, (ConnectionRefusedError, ConnectionResetError,
                         ConnectionAbortedError))
            if not refused or attempt >= _RETRY["retries"]:
                raise
            attempt += 1
            _RETRY["count"] += 1
            time.sleep(_RETRY["backoff_s"] * (2 ** (attempt - 1)))


def fetch_traces(url: str, rids: list[str]) -> dict[str, dict]:
    """Span trees for completed requests, `{}` when tracing is off
    (the server 404s every /trace/<id> then)."""
    trees: dict[str, dict] = {}
    for rid in rids:
        try:
            status, tree = _http(f"{url}/trace/{rid}")
        except urllib.error.HTTPError as e:
            if e.code == 404:
                continue  # tracing off, or the rid was evicted
            raise
        if status == 200:
            trees[rid] = tree
    return trees


def submit_all(url: str, docs: list[dict]) -> list[str]:
    rids = []
    for doc in docs:
        body = json.dumps(doc).encode("utf-8")
        status, out = _http(url + "/submit", data=body)
        if status != 200:
            raise RuntimeError(f"submit failed ({status}): {out}")
        rids.append(out["request_id"])
    return rids


def poll_results(url: str, rids: list[str], *,
                 timeout_s: float = 600.0,
                 poll_s: float = 0.2) -> dict[str, dict]:
    """Poll every request to completion (done or error)."""
    pending = set(rids)
    recs: dict[str, dict] = {}
    deadline = time.monotonic() + timeout_s
    while pending:
        if time.monotonic() > deadline:
            raise TimeoutError(
                f"{len(pending)} request(s) still pending after "
                f"{timeout_s}s: {sorted(pending)[:4]}...")
        for rid in sorted(pending):
            status, rec = _http(f"{url}/result/{rid}")
            if status == 200:
                recs[rid] = rec
                pending.discard(rid)
        if pending:
            time.sleep(poll_s)
    return recs


def _pct(sorted_vals: list[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    i = min(int(q * len(sorted_vals)), len(sorted_vals) - 1)
    return sorted_vals[i]


def _class_decomposition(trees: dict[str, dict]) -> dict:
    """Per-class p50/p95/p99 of queue-wait / pack-wait / run, from the
    span trees (shares `obs.servetrace.decompose` with serve_report)."""
    from shadow_tpu.obs.servetrace import decompose

    by_class: dict[str, list[dict]] = {}
    for tree in trees.values():
        by_class.setdefault(tree.get("class") or "?", []).append(
            decompose(tree))
    out = {}
    for cls, ds in sorted(by_class.items()):
        ent = {}
        for key in ("queue_wait_ms", "pack_wait_ms", "run_ms"):
            vals = sorted(d[key] for d in ds)
            ent[key] = {"p50": _pct(vals, 0.50),
                        "p95": _pct(vals, 0.95),
                        "p99": _pct(vals, 0.99)}
        out[cls] = ent
    return out


def run_load(url: str, docs: list[dict], *, out_dir: str | None = None,
             timeout_s: float = 600.0) -> dict:
    t0 = time.monotonic()
    rids = submit_all(url, docs)
    recs = poll_results(url, rids, timeout_s=timeout_s)
    wall_s = time.monotonic() - t0
    trees = fetch_traces(url, rids)
    if out_dir is not None:
        import os

        os.makedirs(out_dir, exist_ok=True)
        for rid, rec in recs.items():
            with open(os.path.join(out_dir, f"{rid}.json"), "w") as f:
                json.dump(rec, f, sort_keys=True, indent=1)
                f.write("\n")
        for rid, tree in trees.items():
            path = os.path.join(out_dir, f"{rid}.trace.json")
            with open(path, "w") as f:
                json.dump(tree, f, sort_keys=True, indent=1)
                f.write("\n")
    done = [r for r in recs.values() if r["status"] == "done"]
    lat = sorted(r["wall_ms"] for r in done)
    report = {
        "requests": len(docs),
        "done": len(done),
        "errors": len(recs) - len(done),
        "wall_s": round(wall_s, 3),
        "requests_per_sec": round(len(done) / max(wall_s, 1e-9), 3),
        "p50_ms": _pct(lat, 0.50),
        "p95_ms": _pct(lat, 0.95),
        "max_lanes_packed": max((r["lanes_packed"] for r in done),
                                default=0),
        "launches": len({r["launch"] for r in done}),
        "cache_hits_seen": sum(1 for r in done if r.get("cache_hit")),
        "conn_retries": _RETRY["count"],
    }
    if trees:
        report["traced"] = len(trees)
        report["class_decomposition"] = _class_decomposition(trees)
    return report


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="serve_client",
        description="load generator for `shadow_tpu serve` "
                    "(docs/17-Serving.md)")
    p.add_argument("--url", default="http://127.0.0.1:8421",
                   help="server base URL (no trailing slash)")
    p.add_argument("--requests", type=int, default=16)
    p.add_argument("--mix", choices=("mixed", "plain"), default="mixed",
                   help="mixed = two equivalence classes (default); "
                        "plain = one seed-sweep class")
    p.add_argument("--hosts", type=int, default=8)
    p.add_argument("--stop-s", type=float, default=1.0)
    p.add_argument("--seed0", type=int, default=100,
                   help="base seed; request i uses seed0+i")
    p.add_argument("--out-dir", default=None,
                   help="write each result record to DIR/<rid>.json "
                        "(diff_runs-able against solo summaries)")
    p.add_argument("--timeout", type=float, default=600.0)
    p.add_argument("--connect-retries", type=int, default=0,
                   help="bounded connection-refused retries per HTTP "
                        "call — rides out an elastic server's restart "
                        "window (0 = fail fast)")
    p.add_argument("--connect-backoff", type=float, default=0.25,
                   help="base backoff seconds between connection "
                        "retries (doubles per attempt)")
    p.add_argument("--print-docs", action="store_true",
                   help="print the request docs (one JSON per line) "
                        "and exit without contacting the server — for "
                        "generating matching solo references")
    args = p.parse_args(argv)

    _RETRY["retries"] = max(int(args.connect_retries), 0)
    _RETRY["backoff_s"] = max(float(args.connect_backoff), 0.0)
    docs = request_docs(args.requests, mix=args.mix, hosts=args.hosts,
                        stop_s=args.stop_s, seed0=args.seed0)
    if args.print_docs:
        for d in docs:
            print(json.dumps(d, sort_keys=True))
        return 0
    try:
        report = run_load(args.url.rstrip("/"), docs,
                          out_dir=args.out_dir, timeout_s=args.timeout)
    except (urllib.error.URLError, TimeoutError, RuntimeError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    print(json.dumps(report, sort_keys=True))
    return 0 if report["errors"] == 0 else 1


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())

"""plot_shadow: stats.shadow_tpu.json -> summary plots.

The reference's plot-shadow.py (src/tools/plot-shadow.py, 1252 lines of
matplotlib) renders per-node time series and distributions from
parse-shadow output. This is its lean shadow_tpu counterpart: one PNG per
figure — aggregate throughput (wire bytes/s in and out), per-node
cumulative goodput, packet and retransmission rates, and event-execution
rates — from the JSON emitted by shadow_tpu.tools.parse_shadow.

Usage:
    python -m shadow_tpu.tools.plot_shadow stats.shadow_tpu.json [-o DIR]
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _series(node: dict, field: str) -> tuple[list, list]:
    """(ticks, per-interval values) — heartbeat fields are interval
    deltas already (utils/tracker.py emits per-interval counts)."""
    return node.get("ticks", []), node.get(field, [])


def make_figures(stats: dict, outdir: str, fmt: str = "png") -> list[str]:
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    nodes = stats.get("nodes", {})
    written: list[str] = []

    def save(fig, name):
        path = os.path.join(outdir, f"{name}.{fmt}")
        fig.tight_layout()
        fig.savefig(path, dpi=120)
        plt.close(fig)
        written.append(path)

    # 1. aggregate wire throughput
    fig, ax = plt.subplots(figsize=(8, 4.5))
    agg: dict[int, list[float]] = {}
    for direction, field in (("recv", "bytes_wire_recv"),
                             ("send", "bytes_wire_send")):
        totals: dict[int, int] = {}
        interval = None
        for node in nodes.values():
            ticks, deltas = _series(node, field)
            if len(ticks) > 1 and interval is None:
                interval = ticks[1] - ticks[0]
            for t, d in zip(ticks, deltas):
                totals[t] = totals.get(t, 0) + d
        if totals:
            xs = sorted(totals)
            iv = max(interval or 1, 1)
            ax.plot(xs, [totals[x] / iv / 1024 for x in xs],
                    label=f"wire {direction}")
    ax.set_xlabel("sim time (s)")
    ax.set_ylabel("KiB/s")
    ax.set_title("aggregate wire throughput")
    ax.legend()
    save(fig, "shadow_tpu.throughput")

    # 2. per-node cumulative payload received (top 20 by total)
    fig, ax = plt.subplots(figsize=(8, 4.5))
    ranked = sorted(
        nodes.items(),
        key=lambda kv: sum(kv[1].get("bytes_payload_recv") or [0]),
        reverse=True,
    )[:20]
    for name, node in ranked:
        ticks = node.get("ticks", [])
        vals = node.get("bytes_payload_recv", [])
        cum, run = [], 0
        for v in vals:
            run += v
            cum.append(run / 1024)
        if ticks:
            ax.plot(ticks, cum, label=name, alpha=0.7)
    ax.set_xlabel("sim time (s)")
    ax.set_ylabel("cumulative payload recv (KiB)")
    ax.set_title("per-node goodput (top 20)")
    if len(ranked) <= 10:
        ax.legend(fontsize=7)
    save(fig, "shadow_tpu.goodput")

    # 3. packet + retransmission rates
    fig, ax = plt.subplots(figsize=(8, 4.5))
    for field, label in (("packets_recv", "packets in"),
                         ("packets_send", "packets out"),
                         ("retrans_segments", "retransmits")):
        totals = {}
        for node in nodes.values():
            ticks, deltas = _series(node, field)
            for t, d in zip(ticks, deltas):
                totals[t] = totals.get(t, 0) + d
        if totals:
            xs = sorted(totals)
            ax.plot(xs, [totals[x] for x in xs], label=label)
    ax.set_xlabel("sim time (s)")
    ax.set_ylabel("count / interval")
    ax.set_title("packets and retransmissions")
    ax.set_yscale("symlog")
    ax.legend()
    save(fig, "shadow_tpu.packets")

    # 4. event execution rate
    fig, ax = plt.subplots(figsize=(8, 4.5))
    totals = {}
    for node in nodes.values():
        ticks, deltas = _series(node, "events_executed")
        for t, d in zip(ticks, deltas):
            totals[t] = totals.get(t, 0) + d
    if totals:
        xs = sorted(totals)
        ax.plot(xs, [totals[x] for x in xs])
    ax.set_xlabel("sim time (s)")
    ax.set_ylabel("events / interval")
    ax.set_title("simulation event rate")
    save(fig, "shadow_tpu.events")

    # 4b. fleet lanes — only for --fleet runs (the [fleet] section is
    # per-lane cumulative, so the event curves are plotted as interval
    # deltas to match the solo event-rate figure's shape)
    fleet = stats.get("fleet", {})
    if fleet:
        fig, (ax, ax2) = plt.subplots(2, 1, figsize=(8, 6), sharex=True)
        for lane in sorted(fleet, key=lambda k: int(k)):
            series = fleet[lane]
            seed = (series.get("seed") or [None])[0]
            label = f"lane {lane} (seed {seed})"
            # fleet counters are cumulative; events_delta is the
            # tracker-computed interval column
            ticks, deltas = _series(series, "events_delta")
            ax.plot(ticks, deltas, label=label)
            ax2.plot(series.get("ticks", []), series.get("fill", []),
                     label=label)
        ax.set_ylabel("events / interval")
        ax.set_title(f"fleet lanes ({len(fleet)})")
        if len(fleet) <= 16:
            ax.legend(fontsize="x-small", ncol=2)
        ax2.set_xlabel("sim time (s)")
        ax2.set_ylabel("queue fill")
        save(fig, "shadow_tpu.fleet")

    # 5. fault impact timeline — only when the run had a fault schedule
    # (the [fault] heartbeat section is conditional, so this figure is too)
    faults = stats.get("faults", {})
    if faults:
        fig, (ax, ax2) = plt.subplots(
            2, 1, figsize=(8, 6), sharex=True
        )
        for field, label, axis in (
            ("fault_drops", "fault drops", ax),
            ("quarantined_events", "quarantined events", ax),
            ("downtime_seconds", "downtime (s)", ax2),
        ):
            totals = {}
            for node in faults.values():
                for t, d in zip(node.get("ticks", []),
                                node.get(field, [])):
                    totals[t] = totals.get(t, 0) + d
            if totals:
                xs = sorted(totals)
                axis.plot(xs, [totals[x] for x in xs], label=label)
        ax.set_ylabel("count / interval")
        ax.set_title("fault impact")
        ax.legend()
        ax2.set_xlabel("sim time (s)")
        ax2.set_ylabel("downtime (s) / interval")
        save(fig, "shadow_tpu.faults")

    # 6. supervisor progress — wall-clock window/event rates plus the
    # watchdog stall margin (only for supervised runs that beat)
    sup = stats.get("supervisor", {})
    if sup.get("ticks"):
        fig, ax = plt.subplots(figsize=(8, 4.5))
        xs = sup["ticks"]
        ax.plot(xs, sup.get("events_per_sec", []), label="events/s (wall)")
        ax.plot(xs, sup.get("windows_per_sec", []), label="windows/s (wall)")
        margins = [
            (t, m) for t, m in zip(xs, sup.get("stall_margin_seconds", []))
            if m is not None
        ]
        if margins:
            ax2 = ax.twinx()
            ax2.plot(*zip(*margins), color="tab:red", linestyle="--",
                     label="stall margin (s)")
            ax2.set_ylabel("watchdog margin (s)")
        ax.set_xlabel("sim time (s)")
        ax.set_ylabel("rate (wall)")
        ax.set_yscale("symlog")
        ax.set_title("supervisor progress")
        ax.legend(loc="upper left")
        save(fig, "shadow_tpu.supervisor")

    # 7. queue pressure — spill/refill flow and the reservoir footprint
    # (the [pressure] section only appears under --overflow spill/grow,
    # so this figure is conditional like the fault timeline)
    pres = stats.get("pressure", {})
    if pres.get("ticks"):
        fig, (ax, ax2) = plt.subplots(2, 1, figsize=(8, 6), sharex=True)
        xs = pres["ticks"]
        for field, label in (("spilled", "spilled"),
                             ("refilled", "refilled"),
                             ("spill_lost", "ring lost"),
                             ("overdue", "overdue")):
            ys = pres.get(field, [])
            if any(ys):
                ax.plot(xs, ys, label=label)
        ax.set_ylabel("events / interval")
        ax.set_yscale("symlog")
        ax.set_title("queue pressure")
        ax.legend()
        ax2.plot(xs, pres.get("reservoir_resident", []),
                 label="reservoir resident")
        ax2.plot(xs, pres.get("fill_hwm", []), linestyle="--",
                 label="device fill high-water")
        ax2.set_xlabel("sim time (s)")
        ax2.set_ylabel("events")
        ax2.legend()
        save(fig, "shadow_tpu.pressure")

    # 8. exporter-vs-tracker reconciliation — only with --metrics runs.
    # The [metrics] rows are the telemetry registry's cumulative totals
    # (what a live /metrics scrape returns); the [node] rows are the
    # tracker's per-interval deltas. Summing the deltas must land on the
    # registry curve at every heartbeat — any gap means the exporter and
    # the heartbeat log disagree about the same run.
    met = stats.get("metrics", {})
    if met.get("ticks"):
        fig, (ax, ax2) = plt.subplots(2, 1, figsize=(8, 6), sharex=True)
        xs = met["ticks"]
        ax.plot(xs, met.get("events", []), label="registry events",
                color="tab:blue")
        totals = {}
        for node in nodes.values():
            for t, d in zip(node.get("ticks", []),
                            node.get("events_executed", [])):
                totals[t] = totals.get(t, 0) + d
        if totals:
            txs, run, cum = sorted(totals), 0, []
            for t in txs:
                run += totals[t]
                cum.append(run)
            ax.plot(txs, cum, label="tracker cumulative", color="tab:orange",
                    linestyle="--", marker="x")
        ax.set_ylabel("events (cumulative)")
        ax.set_title("exporter vs tracker reconciliation")
        ax.legend()
        gap = []
        if totals and len(xs) == len(met.get("events", [])):
            node_cum = {}
            run = 0
            for t in sorted(totals):
                run += totals[t]
                node_cum[t] = run
            gap = [e - node_cum[t] for t, e in zip(xs, met["events"])
                   if t in node_cum]
        if gap:
            ax2.plot(xs[: len(gap)], gap, color="tab:red")
        ax2.axhline(0.0, color="grey", linewidth=0.8)
        ax2.set_xlabel("sim time (s)")
        ax2.set_ylabel("registry - tracker")
        save(fig, "shadow_tpu.metrics")

    # 9-11. --stats analytics figures — only when the run logged [stats]
    # rows. The rows are cumulative, so the LAST row's sparse bucket
    # specs are the run's final distributions; buckets are log2 with
    # upper bound 2^i - 1 (obs/stats.py's scheme), drawn as bar charts
    # over bucket index with power-of-two tick labels.
    sts = stats.get("stats", {})

    def _last_hist(fam: str) -> dict:
        cells = sts.get(f"{fam}_hist") or []
        return cells[-1] if cells else {}

    def _bars(axis, fam: str, label: str, color=None):
        h = _last_hist(fam)
        if not h:
            return False
        idx = sorted(int(i) for i in h)
        axis.bar(idx, [h[str(i)] for i in idx], width=0.9,
                 label=label, alpha=0.7, color=color)
        return True

    def _log2_ticks(axis):
        lo, hi = axis.get_xlim()
        ticks = [i for i in range(0, 64, 8) if lo <= i <= hi]
        axis.set_xticks(ticks)
        axis.set_xticklabels(
            ["0" if i == 0 else f"2^{i - 1}" for i in ticks])

    if sts.get("ticks"):
        # latency distributions: event wait + network latency
        fig, ax = plt.subplots(figsize=(8, 4.5))
        any_lat = _bars(ax, "wait", "event wait")
        any_lat |= _bars(ax, "net", "net latency")
        if any_lat:
            _log2_ticks(ax)
            ax.set_xlabel("ns (log2 bucket lower bound)")
            ax.set_ylabel("events")
            ax.set_yscale("symlog")
            ax.set_title("sim-time latency distributions")
            ax.legend()
            save(fig, "shadow_tpu.stats_latency")
        else:
            plt.close(fig)

        # occupancy distributions: events/host/window + queue fill
        fig, ax = plt.subplots(figsize=(8, 4.5))
        any_occ = _bars(ax, "occ", "events per host per window")
        any_occ |= _bars(ax, "qfill", "queue fill at pop")
        if any_occ:
            _log2_ticks(ax)
            ax.set_xlabel("count (log2 bucket lower bound)")
            ax.set_ylabel("observations")
            ax.set_yscale("symlog")
            ax.set_title("occupancy distributions")
            ax.legend()
            save(fig, "shadow_tpu.stats_occupancy")
        else:
            plt.close(fig)

        # frontier run length — the PR 13 TPU-bet measurement; only
        # frontier-drain runs populate it
        fig, ax = plt.subplots(figsize=(8, 4.5))
        if _bars(ax, "runlen", "frontier run length",
                 color="tab:green"):
            _log2_ticks(ax)
            ax.set_xlabel("positions/round (log2 bucket lower bound)")
            ax.set_ylabel("rounds")
            ax.set_yscale("symlog")
            ax.set_title("frontier-drain run length")
            ax.legend()
            save(fig, "shadow_tpu.stats_runlen")
        else:
            plt.close(fig)

    return written


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("stats", help="stats.shadow_tpu.json from parse_shadow")
    ap.add_argument("-o", "--output-dir", default=".")
    ap.add_argument("--format", default="png", choices=["png", "pdf", "svg"])
    args = ap.parse_args(argv)
    with open(args.stats) as f:
        stats = json.load(f)
    os.makedirs(args.output_dir, exist_ok=True)
    for path in make_figures(stats, args.output_dir, args.format):
        print(path)
    return 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())

"""Operator report over a serve-plane flight ledger.

Reduces the append-only JSONL ledger a traced `shadow_tpu serve` run
writes (`--ledger-file`, docs/18-Serve-Tracing.md) into the questions
an operator actually asks after the fact: where did each class's
latency go (queue wait vs pack wait vs run, p50/p95/p99), how full were
the launches (pack efficiency = lanes used / max lanes), how warm was
the program cache, and what did failures cost (retry backoff seconds,
bisection rounds, timeouts, chaos injections).

Works on dead servers by construction — the ledger is flushed per
record and `load_ledger` tolerates a torn final line. Rebuilding the
per-request span trees needs no side table: every request-scoped span
carries `rid` (or `rids` for batch-scoped records) and the
launch-linking spans (`pack_wait`, `result`) carry both, so the
rid -> launch association the live tracer keeps is recoverable from the
records alone.

    python -m shadow_tpu.tools.serve_report ledger.jsonl

prints one sorted-keys JSON line (the same artifact discipline as
`serve_client` / `bench`), diffable run-to-run with `diff_runs --rtol`
since every wall-derived key ends in `_ms`/`_s`.
"""

from __future__ import annotations

import argparse
import json
import sys

from shadow_tpu.obs.servetrace import decompose, load_ledger


def trees_from_ledger(records: list[dict]) -> dict[str, dict]:
    """Rebuild {rid: span tree} in `ServeTracer.trace` shape from the
    flat ledger stream. A record files under every rid it names (`rid`
    or batch `rids`) and under its launch; a rid is associated with a
    launch the first time one record carries both."""
    req: dict[str, dict] = {}
    launches: dict[int, list] = {}
    for rec in records:
        rids = ([rec["rid"]] if "rid" in rec else
                list(rec.get("rids", ())))
        launch = rec.get("launch")
        if launch is not None:
            launches.setdefault(int(launch), []).append(rec)
        for r in rids:
            ent = req.setdefault(
                r, {"cls": None, "launches": [], "spans": []})
            if ent["cls"] is None and "cls" in rec:
                ent["cls"] = rec["cls"]
            ent["spans"].append(rec)
            if launch is not None and int(launch) not in ent["launches"]:
                ent["launches"].append(int(launch))
    return {
        rid: {
            "request_id": rid,
            "class": ent["cls"],
            "spans": ent["spans"],
            "launches": [{"launch": n, "spans": launches.get(n, [])}
                         for n in ent["launches"]],
        }
        for rid, ent in req.items()
    }


def _pct(sorted_vals: list[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    i = min(int(q * len(sorted_vals)), len(sorted_vals) - 1)
    return round(sorted_vals[i], 3)


def _percentiles(vals: list[float]) -> dict:
    s = sorted(vals)
    return {"p50": _pct(s, 0.50), "p95": _pct(s, 0.95),
            "p99": _pct(s, 0.99)}


def reduce_ledger(header: dict, records: list[dict]) -> dict:
    """The full operator report as one JSON-safe dict."""
    trees = trees_from_ledger(records)
    by_class: dict[str, list[dict]] = {}
    for tree in trees.values():
        d = decompose(tree)
        by_class.setdefault(tree["class"] or "?", []).append(d)

    classes = {}
    for cls, decomps in sorted(by_class.items()):
        totals = [d["total_ms"] for d in decomps
                  if d["total_ms"] is not None]
        classes[cls] = {
            "requests": len(decomps),
            "done": sum(1 for d in decomps if d["status"] == "done"),
            "timeouts": sum(1 for d in decomps
                            if d["status"] == "timeout"),
            "errors": sum(1 for d in decomps if d["status"] == "error"),
            "queue_wait_ms": _percentiles(
                [d["queue_wait_ms"] for d in decomps]),
            "pack_wait_ms": _percentiles(
                [d["pack_wait_ms"] for d in decomps]),
            "run_ms": _percentiles([d["run_ms"] for d in decomps]),
            "total_ms": _percentiles(totals),
        }

    packs = [r for r in records
             if r.get("kind") == "span" and r.get("name") == "pack"]
    lanes_used = sum(int(r.get("lanes_packed", 0)) for r in packs)
    lanes_avail = sum(int(r.get("max_lanes", 0)) for r in packs)
    caches = [r for r in records
              if r.get("kind") == "span" and r.get("name") == "cache"]
    hits = sum(1 for r in caches if r.get("hit"))
    retries = [r for r in records if r.get("name") == "retry"]
    bisects = [r for r in records if r.get("name") == "bisect"]

    return {
        "ledger_version": header.get("ledger_version"),
        "requests": len(trees),
        "classes": classes,
        "launches": len({int(r["launch"]) for r in records
                         if "launch" in r}),
        "pack_efficiency": round(lanes_used / lanes_avail, 4)
        if lanes_avail else None,
        "cache_lookups": len(caches),
        "cache_hit_ratio": round(hits / len(caches), 4)
        if caches else None,
        "retries": len(retries),
        "retry_backoff_s": round(
            sum(float(r.get("backoff_s", 0.0)) for r in retries), 3),
        "bisections": len(bisects),
        "deadline_exceeded": sum(
            1 for r in records if r.get("name") == "deadline_exceeded"),
        "chaos_injections": sum(
            1 for r in records if r.get("name") == "chaos"),
        "snapshots": sum(
            1 for r in records
            if r.get("kind") == "span" and r.get("name") == "snapshot"),
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="serve_report",
        description="reduce a serve flight ledger (--ledger-file) into "
                    "the per-class latency decomposition / pack "
                    "efficiency / cache / failure-cost report "
                    "(docs/18-Serve-Tracing.md)")
    p.add_argument("ledger", help="flight ledger JSONL path")
    args = p.parse_args(argv)

    try:
        header, records = load_ledger(args.ledger)
    except OSError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if not header and not records:
        print(f"error: {args.ledger}: empty ledger", file=sys.stderr)
        return 2
    print(json.dumps(reduce_ledger(header, records), sort_keys=True))
    return 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())

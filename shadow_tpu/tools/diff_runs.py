"""Run-to-run drift report over shadow_tpu runtime artifacts.

The lint CLI's `--diff` compares two *static-analysis* reports; this
tool extends the precedent to what a run actually produced: the
end-of-run summary JSON, an OpenMetrics `/metrics` scrape, a heartbeat
log's cumulative `[stats]`/`[metrics]` rows, and the BENCH_r*.json
harness artifacts. Point it at two files — or two directories, where
every like-named artifact present in both is compared — and it prints
one drift line per diverging key, with a numeric tolerance for the
wall-clock-contaminated fields.

Exit status is the contract: 0 when nothing drifted (a run diffed
against itself MUST report zero), 1 when any key diverged, 2 on usage
errors. Determinism regressions, histogram drift after a "harmless"
refactor, and cross-machine BENCH comparisons all reduce to this one
command:

    python -m shadow_tpu.tools.diff_runs a/summary.json b/summary.json
    python -m shadow_tpu.tools.diff_runs runA/ runB/ --rtol 0.05
    python -m shadow_tpu.tools.diff_runs a.metrics b.metrics --json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any

# artifact type tags
JSON_T, OPENMETRICS_T, HEARTBEAT_T = "json", "openmetrics", "heartbeat"
# a served-result record (serve plane, docs/17-Serving.md): JSON with a
# request_id key wrapping the sim summary — loaded as the EMBEDDED
# summary so it diffs directly against a solo-run summary with sim keys
# exact (the serving bit-identity gate)
SERVED_T = "served"
# a serve flight ledger (docs/18-Serve-Tracing.md): JSONL whose header
# line carries ledger_version — loaded as the record list, so two
# replayed request streams diff span-for-span with sim keys (now_ns)
# exact and wall keys (t_s/dur_s/wall_ms) under --rtol
LEDGER_T = "ledger"

# numeric keys that are wall-clock (not sim) quantities: always
# compared with the tolerance, never exactly, because two bit-identical
# runs still disagree on them
_WALL_HINTS = ("wall", "seconds", "_s", "per_sec", "rate", "margin")


def classify(path: str, text: str) -> str:
    """Sniff an artifact's type from its content (extension is a hint
    only: BENCH artifacts are .json, scrapes are often .txt)."""
    stripped = text.lstrip()
    # heartbeat first: a log can OPEN with a `[shadow-heartbeat]` row
    # (e.g. a fleet run's header line), which the JSON sniff's leading
    # "[" would otherwise claim
    if "[shadow-heartbeat]" in text:
        return HEARTBEAT_T
    first = stripped.split("\n", 1)[0]
    if first.startswith("{") and '"ledger_version"' in first:
        return LEDGER_T
    if stripped.startswith("{") or stripped.startswith("["):
        if stripped.startswith("{") and '"request_id"' in text:
            return SERVED_T
        return JSON_T
    if "# EOF" in text or stripped.startswith("# TYPE"):
        return OPENMETRICS_T
    raise ValueError(f"{path}: unrecognized artifact "
                     "(not JSON / OpenMetrics / heartbeat log)")


def load_openmetrics(text: str) -> dict:
    """Flatten an exposition into {sample-left-hand-side: value}."""
    out: dict[str, float] = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        left, _, value = line.rpartition(" ")
        try:
            out[left] = float(value)
        except ValueError:
            out[left] = value
    return out


def load_heartbeat(text: str) -> dict:
    """The LAST row of every `[section]` whose header was also logged:
    cumulative sections ([stats], [metrics]) diff meaningfully on their
    final row; header columns become the keys. The `[fleet]` section is
    per-LANE cumulative — its rows key on the lane column, so a fleet
    log diffs lane by lane (`fleet:3.events`) and a run that lost or
    gained lanes shows up as only-in-one keys, not a silent overwrite."""
    headers: dict[str, list[str]] = {}
    last: dict[str, list[str]] = {}
    for line in text.splitlines():
        if "[shadow-heartbeat]" not in line:
            continue
        payload = line.split("[shadow-heartbeat]", 1)[1].strip()
        if not payload.startswith("["):
            continue
        section, _, row = payload.partition("] ")
        section = section.lstrip("[")
        if section.endswith("-header"):
            headers[section[: -len("-header")]] = row.split(",")
        elif section == "fleet":
            cells = row.split(",")
            lane = cells[1] if len(cells) > 1 else "?"
            last[f"fleet:{lane}"] = cells
        else:
            last[section] = row.split(",")
    out: dict[str, Any] = {}
    for section, row in sorted(last.items()):
        cols = headers.get(section.partition(":")[0])
        for i, cell in enumerate(row):
            key = (f"{section}.{cols[i]}" if cols and i < len(cols)
                   else f"{section}[{i}]")
            try:
                out[key] = float(cell)
            except ValueError:
                out[key] = cell
    return out


def load_artifact(path: str) -> tuple[str, Any]:
    with open(path) as f:
        text = f.read()
    kind = classify(path, text)
    if kind == JSON_T:
        return kind, json.loads(text)
    if kind == SERVED_T:
        doc = json.loads(text)
        summary = doc.get("summary")
        if not isinstance(summary, dict):
            raise ValueError(
                f"{path}: served result {doc.get('request_id')!r} has "
                f"no summary (status {doc.get('status')!r}) — only "
                "completed requests diff against a solo run"
            )
        # normalize to a plain summary: sim keys diff exactly against
        # the solo-run artifact; request metadata (lane, launch,
        # wall_ms) is serving detail, not run output
        return JSON_T, summary
    if kind == LEDGER_T:
        from shadow_tpu.obs.servetrace import load_ledger

        _, records = load_ledger(path)
        # diff as a plain record list: `now_ns` attrs compare exactly
        # (replayed streams must agree on sim progress), the wall keys
        # (t_s, dur_s, fetch_s, wall_ms, backoff_s) hit _WALL_HINTS
        return kind, records
    if kind == OPENMETRICS_T:
        return kind, load_openmetrics(text)
    return kind, load_heartbeat(text)


def _is_wall(key: str) -> bool:
    low = key.lower()
    return any(h in low for h in _WALL_HINTS)


def diff_values(a, b, *, rtol: float, path: str,
                out: list[dict]) -> None:
    """Recursive structural diff; appends one entry per drifting key."""
    if isinstance(a, dict) and isinstance(b, dict):
        for k in sorted(set(a) | set(b)):
            sub = f"{path}.{k}" if path else str(k)
            if k not in a:
                out.append({"key": sub, "a": None, "b": b[k],
                            "what": "only-in-b"})
            elif k not in b:
                out.append({"key": sub, "a": a[k], "b": None,
                            "what": "only-in-a"})
            else:
                diff_values(a[k], b[k], rtol=rtol, path=sub, out=out)
        return
    if isinstance(a, list) and isinstance(b, list):
        if len(a) != len(b):
            out.append({"key": f"{path}#len", "a": len(a), "b": len(b),
                        "what": "length"})
        for i, (x, y) in enumerate(zip(a, b)):
            diff_values(x, y, rtol=rtol, path=f"{path}[{i}]", out=out)
        return
    num = (int, float)
    if isinstance(a, num) and isinstance(b, num) \
            and not isinstance(a, bool) and not isinstance(b, bool):
        fa, fb = float(a), float(b)
        tol = rtol if (rtol > 0 and _is_wall(path)) else 0.0
        if fa == fb:
            return
        denom = max(abs(fa), abs(fb), 1e-12)
        rel = abs(fa - fb) / denom
        if rel <= tol:
            return
        out.append({"key": path, "a": a, "b": b,
                    "what": f"rel={rel:.3g}"})
        return
    if a != b:
        out.append({"key": path, "a": a, "b": b, "what": "value"})


def diff_files(path_a: str, path_b: str, *, rtol: float) -> list[dict]:
    kind_a, a = load_artifact(path_a)
    kind_b, b = load_artifact(path_b)
    if kind_a != kind_b:
        return [{"key": "", "a": kind_a, "b": kind_b,
                 "what": "artifact-type"}]
    out: list[dict] = []
    diff_values(a, b, rtol=rtol, path="", out=out)
    return out


def diff_dirs(dir_a: str, dir_b: str, *, rtol: float) -> dict:
    """Compare every like-named regular file present in both
    directories (recognized artifact types only; unrecognized files
    are listed as skipped, names present on one side as unmatched)."""
    names_a = {n for n in os.listdir(dir_a)
               if os.path.isfile(os.path.join(dir_a, n))}
    names_b = {n for n in os.listdir(dir_b)
               if os.path.isfile(os.path.join(dir_b, n))}
    report: dict[str, Any] = {
        "unmatched_a": sorted(names_a - names_b),
        "unmatched_b": sorted(names_b - names_a),
        "skipped": [],
        "files": {},
    }
    for name in sorted(names_a & names_b):
        pa, pb = os.path.join(dir_a, name), os.path.join(dir_b, name)
        try:
            report["files"][name] = diff_files(pa, pb, rtol=rtol)
        except (ValueError, json.JSONDecodeError):
            report["skipped"].append(name)
    return report


def _render_entries(entries: list[dict], prefix: str = "") -> list[str]:
    return [
        f"  {prefix}{e['key'] or '<root>'}: {e['a']!r} != {e['b']!r} "
        f"({e['what']})"
        for e in entries
    ]


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="diff_runs",
        description="drift report between two runs' artifacts "
                    "(summary JSON, OpenMetrics scrape, heartbeat log, "
                    "BENCH json); exit 0 = no drift",
    )
    p.add_argument("a", help="artifact file or run directory")
    p.add_argument("b", help="artifact file or run directory")
    p.add_argument("--rtol", type=float, default=0.0,
                   help="relative tolerance for wall-clock-derived "
                        "numeric fields (sim-derived fields always "
                        "compare exactly; default 0 = everything exact)")
    p.add_argument("--json", action="store_true",
                   help="emit the machine-readable report on stdout")
    args = p.parse_args(argv)

    a_dir, b_dir = os.path.isdir(args.a), os.path.isdir(args.b)
    if a_dir != b_dir:
        print("error: arguments must be two files or two directories",
              file=sys.stderr)
        return 2
    try:
        if a_dir:
            report = diff_dirs(args.a, args.b, rtol=args.rtol)
            n = sum(len(v) for v in report["files"].values())
            n += len(report["unmatched_a"]) + len(report["unmatched_b"])
        else:
            entries = diff_files(args.a, args.b, rtol=args.rtol)
            report = {"files": {os.path.basename(args.a): entries}}
            n = len(entries)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    if args.json:
        print(json.dumps({"drift": n, **report}))
        return 0 if n == 0 else 1

    if n == 0:
        print("no drift")
        return 0
    for name, entries in report["files"].items():
        if entries:
            print(f"{name}: {len(entries)} drifting key(s)")
            print("\n".join(_render_entries(entries)))
    for side, key in (("a", "unmatched_a"), ("b", "unmatched_b")):
        for name in report.get(key, ()):
            print(f"only in {side}: {name}")
    if report.get("skipped"):
        print("skipped (unrecognized): "
              + ", ".join(report["skipped"]))
    print(f"total: {n} drifting key(s)")
    return 1


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())

"""Migrate legacy configs to canonical shadow_tpu XML.

The reference ships convert_multi_app.py for migrating older
(scallion-era) experiment files to its current schema
(reference: src/tools/convert_multi_app.py). shadow_tpu's parser already
ACCEPTS the legacy spellings (config.py); this tool goes one step
further and re-emits a normalized file — legacy attribute names mapped
to canonical ones, quantity expansion preserved, topology inlined — so
downstream tooling only ever sees one dialect.

    python -m shadow_tpu.tools.convert_config old.xml new.xml
"""

from __future__ import annotations

import sys
from xml.sax.saxutils import escape, quoteattr

from shadow_tpu.config import parse_config


def convert(text: str, base_dir: str = ".") -> str:
    cfg = parse_config(text, base_dir=base_dir)
    attrs = [f'stoptime="{cfg.stoptime:g}"']
    if cfg.bootstraptime:
        attrs.append(f'bootstraptime="{cfg.bootstraptime:g}"')
    if cfg.preload:
        attrs.append(f"preload={quoteattr(cfg.preload)}")
    if cfg.environment:
        attrs.append(f"environment={quoteattr(cfg.environment)}")
    out = [f"<shadow {' '.join(attrs)}>"]
    # inline the topology TEXT so the converted file is self-contained
    # (topology_source returns a path for path-based configs)
    topo = cfg.topology_source()
    if cfg.topology_path:
        with open(topo) as f:
            topo = f.read()
    out.append("  <topology><![CDATA[" + topo + "]]></topology>")
    for pl in cfg.plugins:
        out.append(
            f"  <plugin id={quoteattr(pl.id)} path={quoteattr(pl.path)}/>"
        )
    for h in cfg.hosts:
        attrs = [f"id={quoteattr(h.id)}"]
        if h.quantity > 1:
            attrs.append(f'quantity="{h.quantity}"')
        for name in ("bandwidthup", "bandwidthdown", "cpufrequency",
                     "socketrecvbuffer", "socketsendbuffer",
                     "interfacebuffer"):
            v = getattr(h, name, None)
            if v:
                attrs.append(f'{name}="{v:g}"')
        for name in ("iphint", "citycodehint", "countrycodehint",
                     "geocodehint", "typehint", "pcapdir", "loglevel",
                     "heartbeatloglevel", "heartbeatloginfo"):
            v = getattr(h, name, None)
            if v:
                attrs.append(f"{name}={quoteattr(str(v))}")
        if getattr(h, "heartbeatfrequency", None):
            attrs.append(f'heartbeatfrequency="{h.heartbeatfrequency}"')
        if getattr(h, "logpcap", False):
            attrs.append('logpcap="true"')
        out.append(f"  <host {' '.join(attrs)}>")
        for p in h.processes:
            pa = [f"plugin={quoteattr(p.plugin)}",
                  f'starttime="{p.starttime:g}"']
            if p.stoptime:
                pa.append(f'stoptime="{p.stoptime:g}"')
            if p.preload:
                pa.append(f"preload={quoteattr(p.preload)}")
            if p.arguments:
                pa.append(f"arguments={quoteattr(p.arguments)}")
            out.append(f"    <process {' '.join(pa)}/>")
        out.append("  </host>")
    out.append("</shadow>")
    return "\n".join(out) + "\n"


def main(argv=None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    if len(argv) < 2:
        print("usage: convert_config <old.xml> <new.xml>", file=sys.stderr)
        return 2
    import os

    with open(argv[0]) as f:
        text = f.read()
    converted = convert(text, base_dir=os.path.dirname(
        os.path.abspath(argv[0])))
    with open(argv[1], "w") as f:
        f.write(converted)
    print(f"wrote {argv[1]}")
    return 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())

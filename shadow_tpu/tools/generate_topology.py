"""Generate an internet-like multi-PoI topology GraphML.

The reference ships a measured internet topology with its release
(resource/topology.graphml.xml.xz; GraphML attribute schema in
docs/3.2-Network-Config.md) that its 100-host bulk-transfer baseline
runs over. This generator synthesizes an original topology with the
same structure and attribute schema — geographic PoI clusters with
low intra-cluster and high inter-cluster latency, per-vertex bandwidth
tiers and packet loss, full connectivity — deterministically from a
seed, so large BASELINE-shaped configs have a realistic network to run
on without shipping measured data.

    python -m shadow_tpu.tools.generate_topology --pois 60 -o topo.graphml.xml
"""

from __future__ import annotations

import argparse
import random
import sys

# (citycode, countrycode, continent-position) for cluster centers; the
# latency model is distance-ish: intra-cluster ~2-15ms, cross-cluster
# 20-180ms depending on center separation
_REGIONS = [
    ("NYC", "US", 0.0), ("LAX", "US", 0.6), ("YYZ", "CA", 0.1),
    ("LHR", "GB", 1.4), ("FRA", "DE", 1.5), ("CDG", "FR", 1.45),
    ("GRU", "BR", 0.9), ("NRT", "JP", 2.6), ("SYD", "AU", 3.1),
    ("SIN", "SG", 2.3), ("BOM", "IN", 2.0), ("JNB", "ZA", 1.8),
]

_BW_TIERS_KIB = [1024, 10240, 102400, 1048576]  # 1MiB/s .. 1GiB/s


def generate(n_pois: int = 60, seed: int = 0) -> str:
    rng = random.Random(seed)
    nodes = []
    for i in range(n_pois):
        city, country, pos = _REGIONS[i % len(_REGIONS)]
        bw = rng.choice(_BW_TIERS_KIB)
        loss = rng.choice([0.0, 0.0, 0.0, 0.001, 0.005])
        nodes.append((i, city, country, pos, bw, loss))

    out = [
        '<graphml xmlns="http://graphml.graphdrawing.org/xmlns">',
        '  <key attr.name="packetloss" attr.type="double" for="edge" id="e2" />',
        '  <key attr.name="jitter" attr.type="double" for="edge" id="e1" />',
        '  <key attr.name="latency" attr.type="double" for="edge" id="e0" />',
        '  <key attr.name="packetloss" attr.type="double" for="node" id="n5" />',
        '  <key attr.name="type" attr.type="string" for="node" id="n4" />',
        '  <key attr.name="citycode" attr.type="string" for="node" id="n3" />',
        '  <key attr.name="countrycode" attr.type="string" for="node" id="n2" />',
        '  <key attr.name="bandwidthdown" attr.type="int" for="node" id="n1" />',
        '  <key attr.name="bandwidthup" attr.type="int" for="node" id="n0" />',
        '  <graph edgedefault="undirected">',
    ]
    for i, city, country, _pos, bw, loss in nodes:
        out += [
            f'    <node id="poi-{i}">',
            f'      <data key="n0">{bw}</data>',
            f'      <data key="n1">{bw}</data>',
            f'      <data key="n2">{country}</data>',
            f'      <data key="n3">{city}</data>',
            '      <data key="n4">net</data>',
            f'      <data key="n5">{loss}</data>',
            "    </node>",
        ]
    # complete graph: the engine precomputes all-pairs tables either way,
    # and completeness keeps the reference's complete-graph fast path
    # available (topology.c complete-graph check)
    for i, _c, _cc, pos_i, _b, _l in nodes:
        for j, _c2, _cc2, pos_j, _b2, _l2 in nodes:
            if j < i:
                continue
            if i == j:
                lat = round(rng.uniform(0.5, 2.0), 2)
            elif abs(pos_i - pos_j) < 1e-9:  # same region cluster
                lat = round(rng.uniform(2.0, 15.0), 2)
            else:
                base = 18.0 + 52.0 * abs(pos_i - pos_j)
                lat = round(base * rng.uniform(0.85, 1.25), 2)
            jit = round(lat * rng.uniform(0.0, 0.08), 2)
            out += [
                f'    <edge source="poi-{i}" target="poi-{j}">',
                f'      <data key="e0">{lat}</data>',
                f'      <data key="e1">{jit}</data>',
                '      <data key="e2">0.0</data>',
                "    </edge>",
            ]
    out += ["  </graph>", "</graphml>"]
    return "\n".join(out) + "\n"


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--pois", type=int, default=60)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("-o", "--out", default="topology.graphml.xml")
    args = p.parse_args(argv)
    text = generate(args.pois, args.seed)
    with open(args.out, "w") as f:
        f.write(text)
    print(f"wrote {args.out} ({args.pois} PoIs, complete graph)")
    return 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())

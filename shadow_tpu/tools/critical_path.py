"""Critical-path attribution over an exported event trace.

Reads the .npz written by --trace (obs.trace.TraceDrain.save) and walks
the send->exec flow edges — the same (src, seq, dst) join
export_trace.py draws as Perfetto flow arrows — to answer the question
the events/s headline can't: *how much of the workload is a sequential
dependency chain*, and therefore how fast the simulation could ever go
no matter how wide the vmap is.

Model: every OP_EXEC record is a node. An exec depends on
(a) the previous exec on the same host (hosts execute their queue in
    sim-time order — the in-host sequential chain), and
(b) when the event is a delivered packet, the exec that *sent* it —
    joined through the matching OP_SEND record on the source host.
Depth(e) = 1 + max(depth of its dependencies); the critical path is
the longest such chain, reconstructed via parent pointers. A send is
attributed the depth its source host had reached at the send's sim
time (records are processed in (time, op) order with execs first, so
same-time sends see their emitting exec; a send whose delivery lands
at the *same* sim time falls back to the host chain — a documented
approximation, exact whenever network latency is non-zero).

The report gives the chain length (depth), the depth-vs-width
parallelism profile (how many execs are available at each dependency
depth — the simulator's theoretical lockstep occupancy), and the
top-K host/edge hotspots on the critical path itself: where the
sequential time actually lives.

    python -m shadow_tpu.tools.critical_path shadow_tpu.trace.npz
    python -m shadow_tpu.tools.critical_path run.npz --top 5 --json
"""

from __future__ import annotations

import argparse
import json
import sys

import numpy as np

from shadow_tpu.obs.trace import OP_EXEC, OP_SEND


def analyze(recs: dict, meta: dict, top: int = 10) -> dict:
    """Pure transform: (trace records, meta) -> critical-path report.

    Returns a dict with `execs`, `flows` (send->exec joins), `depth`
    (longest chain), `width_mean`/`width_max` (parallelism profile),
    `widths` (execs per depth level), `span_ns` (sim time covered by
    the chain), `path_hosts` / `path_edges` (top-K hotspots on the
    reconstructed path), and `path` (the chain, root first, as
    (host, time, kind) triples capped at 1000 entries).
    """
    names = meta.get("names") or []
    kind_names = meta.get("kind_names") or []
    host = lambda g: names[g] if 0 <= g < len(names) else f"host{g}"
    kind = lambda k: (
        kind_names[k] if 0 <= k < len(kind_names) else f"kind{k}"
    )

    n = int(recs["time"].shape[0])
    time = np.asarray(recs["time"][:n], np.int64)
    op = np.asarray(recs["op"][:n], np.int64)
    src = np.asarray(recs["src"][:n], np.int64)
    dst = np.asarray(recs["dst"][:n], np.int64)
    seq = np.asarray(recs["seq"][:n], np.int64)
    owner = np.asarray(recs["owner"][:n], np.int64)
    knd = np.asarray(recs["kind"][:n], np.int64)

    # (time, op) order: at equal sim time the emitting exec (OP_EXEC=0)
    # is processed before the send it produced (OP_SEND=1)
    order = np.lexsort((seq, owner, op, time))

    # per-exec chain state, keyed by record index
    depth = np.zeros(n, np.int64)
    parent = np.full(n, -1, np.int64)
    via_send = np.zeros(n, bool)
    hdepth: dict[int, int] = {}  # host -> depth of its latest exec
    hlast: dict[int, int] = {}  # host -> record index of that exec
    # in-flight sends: (src, seq, dst) -> (depth at send, sender exec)
    sends: dict[tuple[int, int, int], tuple[int, int]] = {}
    flows = 0
    n_exec = 0
    for i in order:
        o = int(op[i])
        if o == OP_SEND:
            h = int(owner[i])
            sends.setdefault(
                (int(src[i]), int(seq[i]), int(dst[i])),
                (hdepth.get(h, 0), hlast.get(h, -1)),
            )
            continue
        if o != OP_EXEC:
            continue
        n_exec += 1
        h = int(owner[i])
        d, p, vs = hdepth.get(h, 0), hlast.get(h, -1), False
        sd = sends.pop((int(src[i]), int(seq[i]), h), None)
        if sd is not None:
            flows += 1
            if sd[0] > d:
                d, p, vs = sd[0], sd[1], True
        depth[i] = d + 1
        parent[i] = p
        via_send[i] = vs
        hdepth[h] = d + 1
        hlast[h] = i

    if n_exec == 0:
        return {"execs": 0, "flows": 0, "depth": 0, "width_mean": 0.0,
                "width_max": 0, "widths": [], "span_ns": 0,
                "path_hosts": [], "path_edges": [], "path": []}

    exec_mask = op == OP_EXEC
    max_depth = int(depth[exec_mask].max())
    widths = np.bincount(depth[exec_mask], minlength=max_depth + 1)[1:]

    # reconstruct the longest chain (root first)
    tip = int(np.flatnonzero(exec_mask & (depth == max_depth))[0])
    chain: list[int] = []
    j = tip
    while j >= 0:
        chain.append(j)
        j = int(parent[j])
    chain.reverse()
    host_counts: dict[int, int] = {}
    edge_counts: dict[tuple[int, int], int] = {}
    for idx, j in enumerate(chain):
        host_counts[int(owner[j])] = host_counts.get(int(owner[j]), 0) + 1
        if via_send[j] and idx > 0:
            e = (int(owner[chain[idx - 1]]), int(owner[j]))
            edge_counts[e] = edge_counts.get(e, 0) + 1
    top_hosts = sorted(host_counts.items(), key=lambda kv: -kv[1])[:top]
    top_edges = sorted(edge_counts.items(), key=lambda kv: -kv[1])[:top]

    return {
        "execs": int(n_exec),
        "flows": int(flows),
        "depth": max_depth,
        "width_mean": round(n_exec / max(max_depth, 1), 3),
        "width_max": int(widths.max()),
        "widths": [int(w) for w in widths],
        "span_ns": int(time[chain[-1]] - time[chain[0]]),
        "path_hosts": [
            {"host": host(g), "events": c} for g, c in top_hosts
        ],
        "path_edges": [
            {"src": host(a), "dst": host(b), "hops": c}
            for (a, b), c in top_edges
        ],
        "path": [
            (host(int(owner[j])), int(time[j]), kind(int(knd[j])))
            for j in chain[:1000]
        ],
    }


def _decile_widths(widths: list[int], bins: int = 10) -> list[tuple]:
    """Compress the per-depth width profile into up-to-`bins` depth
    ranges with their mean width, for the text report."""
    d = len(widths)
    if d == 0:
        return []
    out = []
    step = max(d // bins, 1)
    for lo in range(0, d, step):
        hi = min(lo + step, d)
        seg = widths[lo:hi]
        out.append((lo + 1, hi, round(sum(seg) / len(seg), 1)))
    return out


def render(report: dict, *, decile_bins: int = 10) -> str:
    """Human-readable report text from an `analyze` result."""
    r = report
    lines = [
        f"execs: {r['execs']}  send->exec flows joined: {r['flows']}",
        f"critical-path depth: {r['depth']} events "
        f"({r['span_ns'] / 1e9:.3f} sim-s span)",
        f"parallelism: mean width {r['width_mean']} "
        f"(max {r['width_max']}) — a perfect lockstep machine needs "
        f">= depth ({r['depth']}) sweeps",
    ]
    dw = _decile_widths(r["widths"], decile_bins)
    if dw:
        lines.append("depth-vs-width profile (depth range: mean width):")
        for lo, hi, w in dw:
            lines.append(f"  {lo:>6}-{hi:<6} {w}")
    if r["path_hosts"]:
        lines.append("critical-path host hotspots:")
        for e in r["path_hosts"]:
            lines.append(f"  {e['host']:<24} {e['events']} events")
    if r["path_edges"]:
        lines.append("critical-path edge hotspots:")
        for e in r["path_edges"]:
            lines.append(f"  {e['src']} -> {e['dst']:<16} "
                         f"{e['hops']} hops")
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="critical_path",
        description="longest send->exec dependency chain and "
                    "parallelism profile of a shadow_tpu trace .npz",
    )
    p.add_argument("trace", help=".npz written by shadow_tpu --trace")
    p.add_argument("--top", type=int, default=10, metavar="K",
                   help="host/edge hotspots to report (default 10)")
    p.add_argument("--json", action="store_true",
                   help="emit the full report as JSON on stdout")
    args = p.parse_args(argv)

    from shadow_tpu.obs.trace import load_trace

    recs, meta = load_trace(args.trace)
    report = analyze(recs, meta, top=args.top)
    if args.json:
        print(json.dumps(report))
    else:
        print(render(report))
    return 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())

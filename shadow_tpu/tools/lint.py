"""shadowlint CLI — lint gate + HLO contract audit with JSON output.

    python -m shadow_tpu.tools.lint                 # lint the package
    python -m shadow_tpu.tools.lint path/to/file.py # lint specific files
    python -m shadow_tpu.tools.lint --update-baseline
    python -m shadow_tpu.tools.lint --hlo-audit all # + lowering audit
    python -m shadow_tpu.tools.lint --hlo-audit phold,tgen

Exit status: 0 when there are no findings outside the checked-in
baseline (and, with --hlo-audit, every audited config meets its
contract); 1 otherwise. Output is a single JSON document on stdout —
machine-readable for the measure_all.sh lint stage — with human
one-liners on stderr.

The baseline (shadow_tpu/analysis/lint_baseline.json) holds accepted
findings keyed by (rule | path | function | source line) so they
survive line drift; stale entries are reported (not fatal) so the
baseline shrinks as findings are fixed. See docs/10-Static-Analysis.md.
"""

from __future__ import annotations

import argparse
import json
import sys

from shadow_tpu.analysis import lint as L


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m shadow_tpu.tools.lint",
        description="AST lint + HLO contract audit for shadow_tpu")
    ap.add_argument("paths", nargs="*",
                    help="files to lint (default: the shadow_tpu package)")
    ap.add_argument("--baseline", default=L.BASELINE_PATH,
                    help="baseline JSON path (default: packaged baseline)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding as new")
    ap.add_argument("--update-baseline", action="store_true",
                    help="accept current findings into the baseline and "
                         "exit 0")
    ap.add_argument("--hlo-audit", metavar="CONFIGS", default=None,
                    help="also lower + audit model configs: 'all' or a "
                         "comma list of phold,phold_net,tgen,tor,bitcoin")
    ap.add_argument("--output", default=None,
                    help="write the JSON report here instead of stdout")
    args = ap.parse_args(argv)

    findings = L.lint_paths(args.paths) if args.paths else L.lint_package()

    if args.update_baseline:
        entries = L.save_baseline(findings, args.baseline)
        print(f"baseline: {len(entries)} keys "
              f"({len(findings)} findings) -> {args.baseline}",
              file=sys.stderr)
        return 0

    baseline = {} if args.no_baseline else L.load_baseline(args.baseline)
    new, old, stale = L.split_new(findings, baseline)

    report = {
        "findings": [f.to_json() for f in new],
        "baselined": len(old),
        "new": len(new),
        "stale_baseline_keys": stale,
        "rules": L.RULES,
    }
    failed = bool(new)

    if args.hlo_audit:
        # imported lazily: the pure lint path must not pull in jax
        from shadow_tpu.analysis import hlo_audit as H

        names = (sorted(H.CONTRACTS) if args.hlo_audit == "all"
                 else [n.strip() for n in args.hlo_audit.split(",") if
                       n.strip()])
        audit = H.audit_all(names)
        report["hlo_audit"] = audit
        for name, res in audit.items():
            if not res["ok"]:
                failed = True
                for v in res["violations"]:
                    print(f"hlo_audit: {v}", file=sys.stderr)

    for f in new:
        print(str(f), file=sys.stderr)
    if stale:
        print(f"note: {len(stale)} stale baseline keys (safe to "
              f"--update-baseline)", file=sys.stderr)
    print(f"shadowlint: {len(new)} new, {len(old)} baselined",
          file=sys.stderr)

    text = json.dumps(report, indent=1)
    if args.output:
        with open(args.output, "w", encoding="utf-8") as fh:
            fh.write(text + "\n")
    else:
        print(text)
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())

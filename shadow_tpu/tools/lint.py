"""shadowlint CLI — lint gate + compiled-program audits, JSON output.

    python -m shadow_tpu.tools.lint                 # lint the package
    python -m shadow_tpu.tools.lint path/to/file.py # lint specific files
    python -m shadow_tpu.tools.lint --update-baseline
    python -m shadow_tpu.tools.lint --hlo-audit all # + lowering audit
    python -m shadow_tpu.tools.lint --hlo-audit phold,tgen
    python -m shadow_tpu.tools.lint --donation-audit # alias verifier
    python -m shadow_tpu.tools.lint --mem-audit      # peak-live budgets
    python -m shadow_tpu.tools.lint --mem-audit --update-baseline
    python -m shadow_tpu.tools.lint --tpu-audit all  # readiness gate
    python -m shadow_tpu.tools.lint --tpu-audit all --update-baseline
    python -m shadow_tpu.tools.lint --diff old.json new.json

Exit status: 0 when there are no findings outside the checked-in
baseline (and, with --hlo-audit / --donation-audit / --mem-audit /
--tpu-audit, every audited config meets its contract); 1 otherwise.
Output is a
single JSON document on stdout — machine-readable for the
measure_all.sh lint and dataflow_audit stages — with human one-liners
on stderr.

The baseline (shadow_tpu/analysis/lint_baseline.json) holds accepted
findings keyed by (rule | path | function | source line) so they
survive line drift; stale entries are reported (not fatal) so the
baseline shrinks as findings are fixed. `--mem-audit
--update-baseline` refreshes the peak-live budgets
(shadow_tpu/analysis/MEM_BUDGETS.json) the same way, and `--tpu-audit
--update-baseline` the TPU-readiness baseline
(shadow_tpu/analysis/TPU_READINESS.json). `--diff` compares two saved
JSON reports and prints the per-config drift of op budgets, alias
counts, memory estimates, and TPU-readiness numbers (tile waste,
layout churn, merge-kernel VMEM, predicted events/s floors) — the
review artifact for an intentional budget bump. See
docs/10-Static-Analysis.md.
"""

from __future__ import annotations

import argparse
import json
import sys

from shadow_tpu.analysis import lint as L


def _diff_reports(old: dict, new: dict) -> list[str]:
    """Human-readable per-config drift between two saved reports."""
    lines: list[str] = []

    def _num(section: str, cfg: str, key: str, a, b) -> None:
        if a != b and isinstance(a, (int, float)) \
                and isinstance(b, (int, float)):
            d = b - a
            delta = f"{d:+d}" if isinstance(d, int) else f"{d:+.2f}"
            lines.append(f"{section} {cfg}: {key} {a} -> {b} ({delta})")

    oh, nh = old.get("hlo_audit", {}), new.get("hlo_audit", {})
    for cfg in sorted(set(oh) | set(nh)):
        oo = oh.get(cfg, {}).get("ops", {})
        no = nh.get(cfg, {}).get("ops", {})
        for op in sorted(set(oo) | set(no)):
            _num("ops", cfg, op, oo.get(op, 0), no.get(op, 0))

    od, nd = old.get("donation_audit", {}), new.get("donation_audit", {})
    for tgt in sorted(set(od) | set(nd)):
        for key in ("donated_leaves", "aliased_leaves"):
            _num("donation", tgt, key,
                 od.get(tgt, {}).get(key, 0), nd.get(tgt, {}).get(key, 0))

    om, nm = old.get("mem_audit", {}), new.get("mem_audit", {})
    for cfg in sorted(set(om) | set(nm)):
        oe = om.get(cfg, {}).get("estimate", {})
        ne = nm.get(cfg, {}).get("estimate", {})
        for key in ("args_bytes", "carry_bytes", "peak_bytes"):
            _num("memory", cfg, key, oe.get(key, 0), ne.get(key, 0))

    # tpu_readiness: waste / churn / VMEM / predicted-floor drift per
    # config, plus per-chip winner flips in the drain economics
    ot, nt = old.get("tpu_readiness", {}), new.get("tpu_readiness", {})
    for cfg in sorted((set(ot) | set(nt)) - {"drain_economics"}):
        oc, nc = ot.get(cfg, {}), nt.get(cfg, {})
        _num("tpu", cfg, "tile.waste_pct",
             oc.get("tile", {}).get("waste_pct", 0),
             nc.get("tile", {}).get("waste_pct", 0))
        _num("tpu", cfg, "tile.padded_bytes",
             oc.get("tile", {}).get("padded_bytes", 0),
             nc.get("tile", {}).get("padded_bytes", 0))
        och, nch = oc.get("churn", {}), nc.get("churn", {})
        for op in sorted(set(och) | set(nch)):
            for key in ("count", "hot"):
                _num("tpu", cfg, f"churn.{op}.{key}",
                     och.get(op, {}).get(key, 0),
                     nch.get(op, {}).get(key, 0))
        op_, np_ = oc.get("placement", {}), nc.get("placement", {})
        for op in sorted(set(op_) | set(np_)):
            _num("tpu", cfg, f"hot.{op}",
                 op_.get(op, {}).get("hot", 0),
                 np_.get(op, {}).get("hot", 0))
        ov = oc.get("vmem") or {}
        nv = nc.get("vmem") or {}
        _num("tpu", cfg, "vmem.working_set_bytes",
             ov.get("working_set_bytes", 0),
             nv.get("working_set_bytes", 0))
        of_, nf = oc.get("floors", {}), nc.get("floors", {})
        for cn in sorted(set(of_) | set(nf)):
            _num("tpu", cfg, f"floor.{cn}",
                 of_.get(cn, 0), nf.get(cn, 0))
    oe_, ne_ = (ot.get("drain_economics", {}),
                nt.get("drain_economics", {}))
    for model in sorted((set(oe_) | set(ne_))
                        - {"ok", "violations"}):
        ow = oe_.get(model, {}).get("winner", {})
        nw = ne_.get(model, {}).get("winner", {})
        for cn in sorted(set(ow) | set(nw)):
            a, b = ow.get(cn), nw.get(cn)
            if a != b:
                lines.append(
                    f"tpu drain_economics {model}: {cn} winner "
                    f"{a} -> {b}")
    return lines


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m shadow_tpu.tools.lint",
        description="AST lint + HLO contract audit for shadow_tpu")
    ap.add_argument("paths", nargs="*",
                    help="files to lint (default: the shadow_tpu package)")
    ap.add_argument("--baseline", default=L.BASELINE_PATH,
                    help="baseline JSON path (default: packaged baseline)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding as new")
    ap.add_argument("--update-baseline", action="store_true",
                    help="accept current findings into the baseline and "
                         "exit 0")
    ap.add_argument("--hlo-audit", metavar="CONFIGS", default=None,
                    help="also lower + audit model configs: 'all' or a "
                         "comma list of phold,phold_net,tgen,tor,bitcoin")
    ap.add_argument("--donation-audit", action="store_true",
                    help="compile the production window-loop jits and "
                         "verify every donated leaf aliases; also runs "
                         "the harvest host-transfer census")
    ap.add_argument("--mem-audit", action="store_true",
                    help="estimate peak-live bytes per config and check "
                         "against MEM_BUDGETS.json")
    ap.add_argument("--tpu-audit", metavar="CONFIGS", default=None,
                    help="TPU-readiness audit (tile waste, layout "
                         "churn, hot-loop placement, merge-kernel VMEM, "
                         "roofline drain economics) checked against "
                         "TPU_READINESS.json: 'all' or a comma list of "
                         "configs")
    ap.add_argument("--diff", nargs=2, metavar=("OLD", "NEW"),
                    default=None,
                    help="compare two saved JSON reports and print the "
                         "contract drift (report-only; exit 0)")
    ap.add_argument("--output", default=None,
                    help="write the JSON report here instead of stdout")
    args = ap.parse_args(argv)

    if args.diff:
        with open(args.diff[0], "r", encoding="utf-8") as fh:
            old = json.load(fh)
        with open(args.diff[1], "r", encoding="utf-8") as fh:
            new_rep = json.load(fh)
        lines = _diff_reports(old, new_rep)
        for ln in lines:
            print(ln)
        if not lines:
            print("no contract drift")
        return 0

    findings = L.lint_paths(args.paths) if args.paths else L.lint_package()

    if args.update_baseline:
        entries = L.save_baseline(findings, args.baseline)
        print(f"baseline: {len(entries)} keys "
              f"({len(findings)} findings) -> {args.baseline}",
              file=sys.stderr)
        if args.mem_audit:
            from shadow_tpu.analysis import memory as M

            ests = {}
            for name in M.MEM_CONFIGS:
                try:
                    ests[name] = M.estimate_config(name)
                except RuntimeError as e:
                    print(f"mem baseline: {name} skipped ({e})",
                          file=sys.stderr)
            M.save_budgets(ests)
            print(f"mem baseline: {len(ests)} budgets -> "
                  f"{M.BUDGETS_PATH}", file=sys.stderr)
        if args.tpu_audit:
            from shadow_tpu.analysis import tpu_readiness as T

            names = (None if args.tpu_audit == "all"
                     else [n.strip() for n in args.tpu_audit.split(",")
                           if n.strip()])
            results = T.audit_all(names)
            data = T.save_baseline(results)
            print(f"tpu baseline: {len(data['configs'])} configs -> "
                  f"{T.BASELINE_PATH}", file=sys.stderr)
        return 0

    baseline = {} if args.no_baseline else L.load_baseline(args.baseline)
    new, old, stale = L.split_new(findings, baseline)

    report = {
        "findings": [f.to_json() for f in new],
        "baselined": len(old),
        "new": len(new),
        "stale_baseline_keys": stale,
        "rules": L.RULES,
    }
    failed = bool(new)

    if args.hlo_audit:
        # imported lazily: the pure lint path must not pull in jax
        from shadow_tpu.analysis import hlo_audit as H

        names = (sorted(H.CONTRACTS) if args.hlo_audit == "all"
                 else [n.strip() for n in args.hlo_audit.split(",") if
                       n.strip()])
        audit = H.audit_all(names)
        report["hlo_audit"] = audit
        for name, res in audit.items():
            if not res["ok"]:
                failed = True
                for v in res["violations"]:
                    print(f"hlo_audit: {v}", file=sys.stderr)

    if args.donation_audit:
        from shadow_tpu.analysis import donation as D

        don = D.audit_all()
        census = D.census_all()
        report["donation_audit"] = don
        report["transfer_census"] = census
        for name, res in don.items():
            if not res["ok"]:
                failed = True
                for v in res["violations"]:
                    print(f"donation_audit: {v}", file=sys.stderr)
        if not census["ok"]:
            failed = True
            for v in census["violations"]:
                print(f"transfer_census: {v}", file=sys.stderr)

    if args.mem_audit:
        from shadow_tpu.analysis import memory as M

        mem = M.audit_all()
        report["mem_audit"] = mem
        for name, res in mem.items():
            if not res["ok"]:
                failed = True
                for v in res["violations"]:
                    print(f"mem_audit: {v}", file=sys.stderr)

    if args.tpu_audit:
        from shadow_tpu.analysis import tpu_readiness as T

        names = (None if args.tpu_audit == "all"
                 else [n.strip() for n in args.tpu_audit.split(",")
                       if n.strip()])
        tpu = T.audit_all(names)
        report["tpu_readiness"] = tpu
        for name, res in tpu.items():
            if not res["ok"]:
                failed = True
                for v in res["violations"]:
                    print(f"tpu_audit: {v}", file=sys.stderr)

    for f in new:
        print(str(f), file=sys.stderr)
    if stale:
        print(f"note: {len(stale)} stale baseline keys (safe to "
              f"--update-baseline)", file=sys.stderr)
    print(f"shadowlint: {len(new)} new, {len(old)} baselined",
          file=sys.stderr)

    text = json.dumps(report, indent=1)
    if args.output:
        with open(args.output, "w", encoding="utf-8") as fh:
            fh.write(text + "\n")
    else:
        print(text)
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())

"""check_openmetrics: lint an OpenMetrics exposition for syntax errors.

Thin CLI over `shadow_tpu.obs.metrics.validate_openmetrics` so shell
harnesses (measure_all.sh's metrics_smoke / stats_smoke stages) can
gate on exporter output without a prometheus toolchain in the
container. Histogram families (the --stats expositions) get the full
semantic check: monotone `le` bucket ordering, the mandatory `+Inf`
bucket, and `_count`/`_sum` reconciliation against the bucket totals —
applied per label-series, so the serve plane's per-class histograms
(`class="..."` with one bucket ladder per equivalence class,
docs/18-Serve-Tracing.md) are each checked independently. OpenMetrics
exemplars (`... # {trace_id="r000001"} <value> <ts>`) are validated
for syntax and for appearing only on `_bucket`/`_total` samples.
Reads a scrape from a file or stdin; prints one violation per line and
exits 1 on any.

Usage:
    curl -s localhost:PORT/metrics | python -m \
        shadow_tpu.tools.check_openmetrics -
    python -m shadow_tpu.tools.check_openmetrics scrape.txt
"""

from __future__ import annotations

import argparse
import sys

from shadow_tpu.obs.metrics import validate_openmetrics


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("path", help="scrape file, or - for stdin")
    args = ap.parse_args(argv)

    if args.path == "-":
        text = sys.stdin.read()
    else:
        with open(args.path) as f:
            text = f.read()

    problems = validate_openmetrics(text)
    for p in problems:
        print(p)
    if not problems:
        n = sum(
            1 for ln in text.splitlines()
            if ln and not ln.startswith("#")
        )
        n_hist = sum(
            1 for ln in text.splitlines()
            if ln.startswith("# TYPE ") and ln.endswith(" histogram")
        )
        print(f"ok: {n} samples"
              + (f", {n_hist} histogram families" if n_hist else ""),
              file=sys.stderr)
    return 1 if problems else 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())

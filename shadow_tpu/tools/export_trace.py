"""Export a shadow_tpu event trace to Chrome trace-event JSON.

Reads the .npz written by --trace (obs.trace.TraceDrain.save) and emits
the Trace Event Format consumed by Perfetto (ui.perfetto.dev) and
chrome://tracing:

- **pid 0 "sim time"** — one thread track per host (tid = gid, named by
  host name). Every trace record becomes an instant event at its
  simulated time; send->receive deliveries are joined with flow arrows
  ("s" on the OP_SEND record at the source, "f" on the matching OP_EXEC
  record at the destination, id = src<<32 | seq).
- **pid 1 "wall clock"** — one thread track per run-loop phase (build /
  step / drain / pump / checkpoint), "X" complete-spans from the
  --profile WindowProfiler, relative to profiler start.

`--serve-ledger LEDGER.jsonl` merges a serving-plane flight ledger
(docs/18-Serve-Tracing.md) into the same file — alone or alongside a
device .npz:

- **pid 2 "serve wall"** — one thread track per request (submit /
  queue_wait / pack_wait / result) and one per launch (cache / pack /
  beat / snapshot / confirm), "X" spans on the tracer's wall clock;
  retry/resume/bisect/chaos/deadline records render as instants.
- **pid 3 "serve lanes (sim time)"** — one thread track per fleet
  lane; each beat's harvested per-lane progress becomes an instant at
  its SIM time, joined to the beat span that harvested it with a flow
  arrow ("s" on the beat span's wall end, "f" on the lane record) —
  one Perfetto view of a packed batch, wall x sim.

Timestamps are microseconds (the format's unit): sim nanoseconds /1e3,
wall seconds *1e6. Output is deterministic for a deterministic trace —
records arrive pre-sorted by (time, src, seq, op, dst) and keys are
emitted in a fixed order — so repeat-run exports diff byte for byte.

    python -m shadow_tpu.tools.export_trace shadow_tpu.trace.npz
    python -m shadow_tpu.tools.export_trace run.npz -o run.trace.json
    python -m shadow_tpu.tools.export_trace --serve-ledger led.jsonl \
        -o serve.trace.json
"""

from __future__ import annotations

import argparse
import json
import sys

from shadow_tpu.obs.trace import OP_DROP, OP_EXEC, OP_FDROP, OP_SEND


def _meta_event(pid: int, tid: int, what: str, name: str) -> dict:
    return {"ph": "M", "pid": pid, "tid": tid, "name": what,
            "args": {"name": name}}


def build_events(recs: dict, meta: dict) -> list[dict]:
    """Pure transform: (records, meta) -> Chrome trace event list."""
    names = meta.get("names") or []
    kind_names = meta.get("kind_names") or []
    op_names = meta.get("op_names") or ["exec", "send", "drop", "fault_drop"]
    host = lambda g: names[g] if 0 <= g < len(names) else f"host{g}"
    kind = lambda k: (
        kind_names[k] if 0 <= k < len(kind_names) else f"kind{k}"
    )

    events: list[dict] = [
        _meta_event(0, 0, "process_name", "sim time"),
        _meta_event(1, 0, "process_name", "wall clock"),
    ]
    n = int(recs["time"].shape[0])
    owners = sorted({int(o) for o in recs["owner"][:n]})
    for g in owners:
        events.append(_meta_event(0, g, "thread_name", host(g)))

    # flow targets: the OP_EXEC record of a delivered send lives on the
    # destination host and keeps the sender's (src, seq) identity
    exec_at: dict[tuple[int, int, int], int] = {}
    for i in range(n):
        if int(recs["op"][i]) == OP_EXEC:
            key = (int(recs["src"][i]), int(recs["seq"][i]),
                   int(recs["owner"][i]))
            exec_at.setdefault(key, i)

    def row(i: int) -> dict:
        return {
            "time": int(recs["time"][i]), "src": int(recs["src"][i]),
            "dst": int(recs["dst"][i]), "kind": int(recs["kind"][i]),
            "plen": int(recs["plen"][i]), "seq": int(recs["seq"][i]),
            "op": int(recs["op"][i]), "owner": int(recs["owner"][i]),
        }

    flows = 0
    for i in range(n):
        r = row(i)
        ts = r["time"] / 1e3  # ns -> us
        op = r["op"]
        label = (
            kind(r["kind"]) if op == OP_EXEC
            else f"{op_names[op] if op < len(op_names) else op}:"
                 f"{kind(r['kind'])}"
        )
        ev = {
            "ph": "i", "pid": 0, "tid": r["owner"], "ts": ts,
            "name": label, "s": "t",
            "args": {"src": host(r["src"]), "dst": host(r["dst"]),
                     "seq": r["seq"], "plen": r["plen"],
                     "op": op_names[op] if op < len(op_names) else str(op)},
        }
        events.append(ev)
        if op == OP_SEND:
            j = exec_at.get((r["src"], r["seq"], r["dst"]))
            if j is None:
                continue  # in flight past stoptime, or exec record lost
            fid = (r["src"] << 32) | r["seq"]
            deliver = f"deliver:{kind(r['kind'])}"
            events.append({
                "ph": "s", "pid": 0, "tid": r["owner"], "ts": ts,
                "id": fid, "name": deliver, "cat": "net",
            })
            events.append({
                "ph": "f", "pid": 0, "tid": int(recs["owner"][j]),
                "ts": int(recs["time"][j]) / 1e3, "id": fid,
                "name": deliver, "cat": "net", "bp": "e",
            })
            flows += 1

    profile = meta.get("profile") or {}
    spans = profile.get("spans") or []
    phase_tid = {}
    for name, start, dur in spans:
        if name not in phase_tid:
            phase_tid[name] = len(phase_tid)
            events.append(
                _meta_event(1, phase_tid[name], "thread_name", name)
            )
        events.append({
            "ph": "X", "pid": 1, "tid": phase_tid[name],
            "ts": float(start) * 1e6, "dur": float(dur) * 1e6,
            "name": name, "cat": "phase",
        })
    return events


# serve-ledger span names that live on a LAUNCH track; everything else
# with a rid lands on that request's track
_LAUNCH_SPANS = ("cache", "pack", "beat", "snapshot", "confirm")
_SERVE_PID = 2  # wall-time serve spans
_LANE_PID = 3  # per-lane sim-time beat progress


def build_serve_events(records: list[dict]) -> list[dict]:
    """Pure transform: flight-ledger records -> Chrome trace events
    (pids 2 and 3; composes with `build_events`' pids 0 and 1).
    Deterministic: tracks are keyed by sorted rid / launch id, events
    follow ledger order, flow ids derive from (launch, beat, lane)."""
    spans = [r for r in records if r.get("kind") in ("span", "event")]
    if not spans:
        return []
    base = min(r.get("t_s", 0.0) for r in spans)
    rids = sorted({r["rid"] for r in spans if "rid" in r}
                  | {x for r in spans for x in r.get("rids", ())})
    launches = sorted({int(r["launch"]) for r in spans
                       if "launch" in r})
    rid_tid = {rid: i for i, rid in enumerate(rids)}
    # launch tracks sit above the request tracks; lane tracks are tiny
    launch_tid = {n: 1000 + n for n in launches}

    events: list[dict] = [
        _meta_event(_SERVE_PID, 0, "process_name", "serve wall"),
        _meta_event(_LANE_PID, 0, "process_name",
                    "serve lanes (sim time)"),
    ]
    for rid in rids:
        events.append(_meta_event(_SERVE_PID, rid_tid[rid],
                                  "thread_name", f"req {rid}"))
    for n in launches:
        events.append(_meta_event(_SERVE_PID, launch_tid[n],
                                  "thread_name", f"launch {n}"))
    lanes_seen: set[int] = set()

    def wall_us(t_s: float) -> float:
        return round((t_s - base) * 1e6, 3)

    for r in spans:
        name = r["name"]
        launch = r.get("launch")
        if name in _LAUNCH_SPANS and launch is not None:
            tid = launch_tid[int(launch)]
        elif r.get("rid") in rid_tid:
            tid = rid_tid[r["rid"]]
        elif launch is not None:
            tid = launch_tid[int(launch)]
        elif r.get("rids"):
            tid = rid_tid[r["rids"][0]]
        else:
            tid = 999  # service-scoped (e.g. chaos) — its own track
        args = {k: v for k, v in sorted(r.items())
                if k not in ("kind", "name", "t_s", "dur_s", "lanes")}
        if r["kind"] == "span" and r.get("dur_s", 0.0) > 0.0:
            events.append({
                "ph": "X", "pid": _SERVE_PID, "tid": tid,
                "ts": wall_us(r["t_s"]), "dur": round(r["dur_s"] * 1e6,
                                                      3),
                "name": name, "cat": "serve", "args": args,
            })
        else:
            events.append({
                "ph": "i", "pid": _SERVE_PID, "tid": tid,
                "ts": wall_us(r["t_s"]), "name": name, "s": "t",
                "cat": "serve", "args": args,
            })
        if name == "beat" and launch is not None:
            beat = int(r.get("beat", 0))
            t_end = wall_us(r["t_s"] + r.get("dur_s", 0.0))
            for entry in r.get("lanes", ()):
                lane = int(entry.get("lane", 0))
                if lane not in lanes_seen:
                    lanes_seen.add(lane)
                    events.append(_meta_event(
                        _LANE_PID, lane, "thread_name", f"lane {lane}"))
                # the harvested lane record at its SIM time, joined to
                # the harvesting beat span by a wall->sim flow arrow
                fid = ((int(launch) * 4096 + beat) * 256) + lane
                events.append({
                    "ph": "i", "pid": _LANE_PID, "tid": lane,
                    "ts": int(entry.get("now_ns", 0)) / 1e3,
                    "name": f"beat {beat}", "s": "t", "cat": "serve",
                    "args": {"rid": entry.get("rid"),
                             "launch": int(launch),
                             "now_ns": int(entry.get("now_ns", 0))},
                })
                events.append({
                    "ph": "s", "pid": _SERVE_PID,
                    "tid": launch_tid[int(launch)], "ts": t_end,
                    "id": fid, "name": "harvest", "cat": "serve-flow",
                })
                events.append({
                    "ph": "f", "pid": _LANE_PID, "tid": lane,
                    "ts": int(entry.get("now_ns", 0)) / 1e3, "id": fid,
                    "name": "harvest", "cat": "serve-flow", "bp": "e",
                })
    return events


def export(in_path: str | None, out_path: str,
           ledger_path: str | None = None) -> dict:
    """Convert one .npz trace file and/or one serve flight ledger;
    returns stats for the caller."""
    events: list[dict] = []
    meta: dict = {}
    if in_path is not None:
        from shadow_tpu.obs.trace import load_trace

        recs, meta = load_trace(in_path)
        events += build_events(recs, meta)
    n_serve = 0
    if ledger_path is not None:
        from shadow_tpu.obs.servetrace import load_ledger

        _, records = load_ledger(ledger_path)
        serve_events = build_serve_events(records)
        events += serve_events
        n_serve = len(records)
    doc = {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            k: meta.get(k)
            for k in ("n_records", "lost", "truncated", "seed", "tier",
                      "xprof_dir")
            if k in meta
        },
    }
    if ledger_path is not None:
        doc["otherData"]["serve_ledger"] = ledger_path
    with open(out_path, "w") as f:
        json.dump(doc, f, separators=(",", ":"), sort_keys=True)
        f.write("\n")
    n_flows = sum(1 for e in events if e.get("ph") == "s")
    return {"events": len(events), "flows": n_flows,
            "records": meta.get("n_records", 0),
            "serve_records": n_serve, "out": out_path,
            "xprof_dir": meta.get("xprof_dir")}


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="export_trace",
        description="shadow_tpu trace .npz -> Chrome trace-event JSON "
                    "(load in ui.perfetto.dev or chrome://tracing)",
    )
    p.add_argument("trace", nargs="?", default=None,
                   help=".npz written by shadow_tpu --trace (optional "
                        "when --serve-ledger is given)")
    p.add_argument("--serve-ledger", default=None, metavar="JSONL",
                   help="serve-plane flight ledger (--ledger-file) to "
                        "merge as wall-time span tracks + per-lane "
                        "sim-time records (docs/18-Serve-Tracing.md)")
    p.add_argument("-o", "--out", default=None,
                   help="output JSON path (default: <trace>.json)")
    args = p.parse_args(argv)
    if args.trace is None and args.serve_ledger is None:
        p.error("need a trace .npz, a --serve-ledger, or both")
    src = args.trace or args.serve_ledger
    out = args.out or (
        src[:-4] + ".json" if src.endswith(".npz") else src + ".json"
    )
    stats = export(args.trace, out, ledger_path=args.serve_ledger)
    print(f"wrote {stats['events']} trace events "
          f"({stats['records']} records, {stats['serve_records']} "
          f"serve records, {stats['flows']} flow pairs) "
          f"-> {out}", file=sys.stderr)
    if stats.get("xprof_dir"):
        print(f"companion XLA profiler capture: {stats['xprof_dir']} "
              "(open with xprof / tensorboard-plugin-profile)",
              file=sys.stderr)
    return 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())

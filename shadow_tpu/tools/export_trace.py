"""Export a shadow_tpu event trace to Chrome trace-event JSON.

Reads the .npz written by --trace (obs.trace.TraceDrain.save) and emits
the Trace Event Format consumed by Perfetto (ui.perfetto.dev) and
chrome://tracing:

- **pid 0 "sim time"** — one thread track per host (tid = gid, named by
  host name). Every trace record becomes an instant event at its
  simulated time; send->receive deliveries are joined with flow arrows
  ("s" on the OP_SEND record at the source, "f" on the matching OP_EXEC
  record at the destination, id = src<<32 | seq).
- **pid 1 "wall clock"** — one thread track per run-loop phase (build /
  step / drain / pump / checkpoint), "X" complete-spans from the
  --profile WindowProfiler, relative to profiler start.

Timestamps are microseconds (the format's unit): sim nanoseconds /1e3,
wall seconds *1e6. Output is deterministic for a deterministic trace —
records arrive pre-sorted by (time, src, seq, op, dst) and keys are
emitted in a fixed order — so repeat-run exports diff byte for byte.

    python -m shadow_tpu.tools.export_trace shadow_tpu.trace.npz
    python -m shadow_tpu.tools.export_trace run.npz -o run.trace.json
"""

from __future__ import annotations

import argparse
import json
import sys

from shadow_tpu.obs.trace import OP_DROP, OP_EXEC, OP_FDROP, OP_SEND


def _meta_event(pid: int, tid: int, what: str, name: str) -> dict:
    return {"ph": "M", "pid": pid, "tid": tid, "name": what,
            "args": {"name": name}}


def build_events(recs: dict, meta: dict) -> list[dict]:
    """Pure transform: (records, meta) -> Chrome trace event list."""
    names = meta.get("names") or []
    kind_names = meta.get("kind_names") or []
    op_names = meta.get("op_names") or ["exec", "send", "drop", "fault_drop"]
    host = lambda g: names[g] if 0 <= g < len(names) else f"host{g}"
    kind = lambda k: (
        kind_names[k] if 0 <= k < len(kind_names) else f"kind{k}"
    )

    events: list[dict] = [
        _meta_event(0, 0, "process_name", "sim time"),
        _meta_event(1, 0, "process_name", "wall clock"),
    ]
    n = int(recs["time"].shape[0])
    owners = sorted({int(o) for o in recs["owner"][:n]})
    for g in owners:
        events.append(_meta_event(0, g, "thread_name", host(g)))

    # flow targets: the OP_EXEC record of a delivered send lives on the
    # destination host and keeps the sender's (src, seq) identity
    exec_at: dict[tuple[int, int, int], int] = {}
    for i in range(n):
        if int(recs["op"][i]) == OP_EXEC:
            key = (int(recs["src"][i]), int(recs["seq"][i]),
                   int(recs["owner"][i]))
            exec_at.setdefault(key, i)

    def row(i: int) -> dict:
        return {
            "time": int(recs["time"][i]), "src": int(recs["src"][i]),
            "dst": int(recs["dst"][i]), "kind": int(recs["kind"][i]),
            "plen": int(recs["plen"][i]), "seq": int(recs["seq"][i]),
            "op": int(recs["op"][i]), "owner": int(recs["owner"][i]),
        }

    flows = 0
    for i in range(n):
        r = row(i)
        ts = r["time"] / 1e3  # ns -> us
        op = r["op"]
        label = (
            kind(r["kind"]) if op == OP_EXEC
            else f"{op_names[op] if op < len(op_names) else op}:"
                 f"{kind(r['kind'])}"
        )
        ev = {
            "ph": "i", "pid": 0, "tid": r["owner"], "ts": ts,
            "name": label, "s": "t",
            "args": {"src": host(r["src"]), "dst": host(r["dst"]),
                     "seq": r["seq"], "plen": r["plen"],
                     "op": op_names[op] if op < len(op_names) else str(op)},
        }
        events.append(ev)
        if op == OP_SEND:
            j = exec_at.get((r["src"], r["seq"], r["dst"]))
            if j is None:
                continue  # in flight past stoptime, or exec record lost
            fid = (r["src"] << 32) | r["seq"]
            deliver = f"deliver:{kind(r['kind'])}"
            events.append({
                "ph": "s", "pid": 0, "tid": r["owner"], "ts": ts,
                "id": fid, "name": deliver, "cat": "net",
            })
            events.append({
                "ph": "f", "pid": 0, "tid": int(recs["owner"][j]),
                "ts": int(recs["time"][j]) / 1e3, "id": fid,
                "name": deliver, "cat": "net", "bp": "e",
            })
            flows += 1

    profile = meta.get("profile") or {}
    spans = profile.get("spans") or []
    phase_tid = {}
    for name, start, dur in spans:
        if name not in phase_tid:
            phase_tid[name] = len(phase_tid)
            events.append(
                _meta_event(1, phase_tid[name], "thread_name", name)
            )
        events.append({
            "ph": "X", "pid": 1, "tid": phase_tid[name],
            "ts": float(start) * 1e6, "dur": float(dur) * 1e6,
            "name": name, "cat": "phase",
        })
    return events


def export(in_path: str, out_path: str) -> dict:
    """Convert one .npz trace file; returns stats for the caller."""
    from shadow_tpu.obs.trace import load_trace

    recs, meta = load_trace(in_path)
    events = build_events(recs, meta)
    doc = {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            k: meta.get(k)
            for k in ("n_records", "lost", "truncated", "seed", "tier",
                      "xprof_dir")
            if k in meta
        },
    }
    with open(out_path, "w") as f:
        json.dump(doc, f, separators=(",", ":"), sort_keys=True)
        f.write("\n")
    n_flows = sum(1 for e in events if e.get("ph") == "s")
    return {"events": len(events), "flows": n_flows,
            "records": meta.get("n_records", 0), "out": out_path,
            "xprof_dir": meta.get("xprof_dir")}


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="export_trace",
        description="shadow_tpu trace .npz -> Chrome trace-event JSON "
                    "(load in ui.perfetto.dev or chrome://tracing)",
    )
    p.add_argument("trace", help=".npz written by shadow_tpu --trace")
    p.add_argument("-o", "--out", default=None,
                   help="output JSON path (default: <trace>.json)")
    args = p.parse_args(argv)
    out = args.out or (
        args.trace[:-4] + ".json" if args.trace.endswith(".npz")
        else args.trace + ".json"
    )
    stats = export(args.trace, out)
    print(f"wrote {stats['events']} trace events "
          f"({stats['records']} records, {stats['flows']} flow pairs) "
          f"-> {out}", file=sys.stderr)
    if stats.get("xprof_dir"):
        print(f"companion XLA profiler capture: {stats['xprof_dir']} "
              "(open with xprof / tensorboard-plugin-profile)",
              file=sys.stderr)
    return 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())

"""Post-run analysis tooling (the reference ships parse-shadow.py /
plot-shadow.py under src/tools; these are their shadow_tpu-native
counterparts)."""

"""Deterministic fault-injection & churn subsystem.

See schedule.py for the compiler and docs/6-Fault-Injection.md for the
schedule format and determinism guarantees.
"""

from shadow_tpu.faults.schedule import (
    FAULT_TYPES,
    CompiledFaults,
    FaultSpec,
    compile_faults,
    parse_fault_attrs,
    parse_fault_dsl,
)

__all__ = [
    "FAULT_TYPES",
    "CompiledFaults",
    "FaultSpec",
    "compile_faults",
    "parse_fault_attrs",
    "parse_fault_dsl",
]

"""Deterministic fault-injection schedules compiled to dense arrays.

The reference can only model *static* per-edge packet loss: Shadow 1.14
folds `packetloss` into a constant reliability matrix at topology load
(topology.c:86-105) and nothing can change network or host state
mid-run. Here a declarative schedule of `FaultSpec`s (host crash and
restart, churn cycles, link loss spikes, latency inflation, partitions,
bandwidth throttling) compiles — entirely host-side, at build time —
into dense time-indexed arrays the engine applies *inside* the jitted
window loop: a per-host `alive[T, H]` mask gates event execution, and a
small `[T, G, G]` group overlay rides the routing lookup. Fault
transitions therefore cost zero Python callbacks and vectorize across
the mesh exactly like the virtual-clock NIC does.

Determinism guarantees (tests/test_faults.py):
- The timeline is a pure function of (config, seed): random host
  selection and churn phases draw from the named fault stream in
  core/rng.py (`fault_stream_uniform`), which folds only (seed, spec
  index, host gid) — never sharding or execution order.
- Per-packet fault drops roll lane offset 2K of the same per-event
  route key the reliability/jitter rolls use, so drop decisions are
  bit-identical across shard counts and across checkpoint/restore.
- Epoch boundaries are global sim times; every shard evaluates the same
  `epoch_of(t)` on the same barrier-synchronized window sequence.
"""

from __future__ import annotations

import dataclasses
import math
from fnmatch import fnmatchcase

import jax
import jax.numpy as jnp
import numpy as np

from shadow_tpu.core import rng as srng
from shadow_tpu.core.timebase import SECOND

HOST_FAULTS = ("crash", "churn", "bandwidth")
LINK_FAULTS = ("loss", "latency", "partition")
FAULT_TYPES = HOST_FAULTS + LINK_FAULTS

# milli-fixed-point unit for latency scaling (1000 = 1.0x)
LAT_UNIT = 1000


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """One declarative fault. Times are seconds of simulation time.

    type:
      crash      — hosts matching `hosts` are down in [start, end);
                   end=None means they never come back.
      churn      — each selected host cycles down for `downtime` seconds
                   every `period` seconds within [start, end), with a
                   per-host random phase from the named fault stream.
      bandwidth  — NIC rates of matching hosts scale by `factor`.
      loss       — matching (src, dst) pairs drop an extra `loss`
                   fraction of packets (on top of topology reliability).
      latency    — matching pairs' path latency scales by `factor`
                   (inflation or reduction; the engine's window-barrier
                   clamp keeps any value causality-safe).
      partition  — matching pairs drop everything (loss=1).

    `hosts`/`src`/`dst` are space-separated fnmatch globs over host
    names. Link faults apply symmetrically (src<->dst), matching the
    undirected reference topology. `frac` subsamples the matched host
    set deterministically (crash/churn).
    """

    type: str
    hosts: str = "*"
    src: str = "*"
    dst: str = "*"
    start: float = 0.0
    end: float | None = None
    loss: float = 0.0
    factor: float = 1.0
    frac: float = 1.0
    period: float = 0.0
    downtime: float = 0.0
    restart: bool = True

    def __post_init__(self):
        if self.type not in FAULT_TYPES:
            raise ValueError(
                f"unknown fault type {self.type!r}; one of {FAULT_TYPES}"
            )
        if self.end is not None and self.end <= self.start:
            raise ValueError(f"fault end {self.end} <= start {self.start}")
        if self.type == "churn":
            if self.end is None:
                raise ValueError("churn faults need an explicit end=")
            if self.period <= 0 or self.downtime <= 0:
                raise ValueError("churn needs period > 0 and downtime > 0")
            if self.downtime >= self.period:
                raise ValueError("churn downtime must be < period")
        if not 0.0 <= self.loss <= 1.0:
            raise ValueError(f"loss must be in [0, 1], got {self.loss}")
        if self.factor < 0:
            raise ValueError(f"factor must be >= 0, got {self.factor}")


_BOOL = {"true": True, "1": True, "yes": True,
         "false": False, "0": False, "no": False}


def parse_fault_attrs(attrs: dict) -> FaultSpec:
    """Build a FaultSpec from string attrs (XML element or CLI DSL)."""
    kw: dict = {}
    for key, val in attrs.items():
        k = key.replace("-", "_")
        if k in ("type", "hosts", "src", "dst"):
            kw[k] = val
        elif k in ("start", "end", "loss", "factor", "frac", "period",
                   "downtime"):
            kw[k] = float(val)
        elif k == "restart":
            kw[k] = _BOOL[val.strip().lower()]
        else:
            raise ValueError(f"unknown fault attribute {key!r}")
    if "type" not in kw:
        raise ValueError("fault needs a type= attribute")
    return FaultSpec(**kw)


def parse_fault_dsl(text: str) -> FaultSpec:
    """CLI form: 'TYPE key=value ...', e.g.
    'crash hosts=relay* start=30 end=45' or
    'churn hosts=guard* start=10 end=60 period=20 downtime=5 frac=0.2'."""
    parts = text.split()
    if not parts:
        raise ValueError("empty --fault")
    attrs = {"type": parts[0]}
    for tok in parts[1:]:
        if "=" not in tok:
            raise ValueError(f"--fault token {tok!r} is not key=value")
        k, v = tok.split("=", 1)
        attrs[k] = v
    return parse_fault_attrs(attrs)


def _match_mask(pattern: str, names: list[str], n_hosts: int) -> np.ndarray:
    """bool[n_hosts] of hosts whose NAME matches any glob in `pattern`.
    Padded rows (gid >= len(names)) never match — they stay inert."""
    pats = (pattern or "*").split()
    m = np.zeros((n_hosts,), bool)
    for i, nm in enumerate(names[:n_hosts]):
        m[i] = bool(nm) and any(fnmatchcase(nm, p) for p in pats)
    return m


# far-future sentinel for end=None intervals (never a real boundary)
_T_INF = np.iinfo(np.int64).max // 4


@dataclasses.dataclass(frozen=True)
class CompiledFaults:
    """The dense, jit-ready form of a fault schedule.

    Time is partitioned into T epochs at `times` (ns, sorted, times[0]=0;
    epoch e covers [times[e], times[e+1])). Hosts with identical link-
    fault membership share one of G fault groups, so the per-pair overlay
    is a tiny [T, G, G] table instead of [T, H, H].

    These arrays are closed over by the engine's compiled step as
    constants — they are schedule, not state; the only state is the
    engine's `fault_epoch` watermark (an i32 scalar in EngineState).
    """

    times: jax.Array  # i64[T] epoch start times, ns
    alive: jax.Array  # bool[T, Hg] host liveness per epoch
    fgrp: jax.Array  # i32[Hg] link-fault group of each host
    lat_milli: jax.Array  # i64[T, G, G] latency scale, LAT_UNIT = 1x
    passp: jax.Array  # f32[T, G, G] pass probability (0 = partition)
    bw_scale: jax.Array  # f32[T, Hg] NIC rate scale
    has_crash: bool
    has_link: bool
    has_bw: bool
    # host-side copies for the tracker's downtime accounting
    np_times: np.ndarray
    np_alive: np.ndarray

    @property
    def n_epochs(self) -> int:
        return int(self.np_times.shape[0])

    def epoch_of(self, t) -> jax.Array:
        """i32 epoch index for time(s) t (any shape; T is small, so the
        compare-and-sum lowers to one fused elementwise pass)."""
        return (
            jnp.sum(jnp.asarray(t)[..., None] >= self.times, axis=-1) - 1
        ).astype(jnp.int32)

    # ---- host-side helpers (tracker / proc tier) ----
    def alive_at_host(self, t_ns: int) -> np.ndarray:
        """bool[Hg] liveness at one instant, computed host-side."""
        e = int(np.searchsorted(self.np_times, t_ns, side="right") - 1)
        return self.np_alive[max(e, 0)]

    def downtime_in(self, a_ns: int, b_ns: int) -> np.ndarray:
        """f64[Hg] seconds each host spent dead within [a_ns, b_ns)."""
        t = self.np_times
        out = np.zeros((self.np_alive.shape[1],), np.float64)
        for e in range(len(t)):
            lo = max(int(t[e]), a_ns)
            hi = min(int(t[e + 1]) if e + 1 < len(t) else b_ns, b_ns)
            if hi <= lo:
                continue
            out += np.where(self.np_alive[e], 0.0, (hi - lo) / SECOND)
        return out

    def transitions_in(self, a_ns: int, b_ns: int):
        """Host-side (t_ns, gid, up: bool) liveness flips in (a_ns, b_ns]
        — the proc tier kills/restarts native processes from these."""
        t = self.np_times
        out = []
        for e in range(1, len(t)):
            te = int(t[e])
            if not a_ns < te <= b_ns:
                continue
            flip = self.np_alive[e] != self.np_alive[e - 1]
            for g in np.nonzero(flip)[0]:
                out.append((te, int(g), bool(self.np_alive[e][g])))
        return out


def compile_faults(specs, names, n_hosts: int, seed: int) -> CompiledFaults:
    """Compile FaultSpecs into a CompiledFaults over `n_hosts` rows
    (names may be shorter when shape-bucket padding widened the arrays;
    padded rows stay alive/unscaled forever)."""
    specs = tuple(specs)
    names = list(names)

    def s2ns(s: float | None) -> int:
        return _T_INF if s is None else max(int(round(s * SECOND)), 0)

    # ---- per-host down intervals + selection draws --------------------
    down: list[tuple[int, int, int]] = []  # (gid, a_ns, b_ns)
    bw_specs: list[tuple[np.ndarray, int, int, float]] = []
    link_specs: list[tuple[int, FaultSpec, np.ndarray, np.ndarray]] = []
    for si, sp in enumerate(specs):
        if sp.type in LINK_FAULTS:
            link_specs.append((
                si, sp,
                _match_mask(sp.src, names, n_hosts),
                _match_mask(sp.dst, names, n_hosts),
            ))
            continue
        m = _match_mask(sp.hosts, names, n_hosts)
        if sp.type == "bandwidth":
            bw_specs.append((m, s2ns(sp.start), s2ns(sp.end), sp.factor))
            continue
        if sp.frac < 1.0:
            u = np.asarray(jax.device_get(  # shadowlint: no-deadline=build-time fault-schedule sampling
                srng.fault_stream_uniform(seed, si << 8, n_hosts)
            ))
            m = m & (u < sp.frac)
        a, b = s2ns(sp.start), s2ns(sp.end)
        if sp.type == "crash":
            for g in np.nonzero(m)[0]:
                down.append((int(g), a, b if sp.restart else _T_INF))
        else:  # churn
            phase = np.asarray(jax.device_get(  # shadowlint: no-deadline=build-time fault-schedule sampling
                srng.fault_stream_uniform(seed, (si << 8) | 1, n_hosts)
            )) * sp.period
            p_ns = int(round(sp.period * SECOND))
            d_ns = int(round(sp.downtime * SECOND))
            for g in np.nonzero(m)[0]:
                t0 = a + int(round(float(phase[g]) * SECOND))
                while t0 < b:
                    down.append((int(g), t0, min(t0 + d_ns, b)))
                    t0 += p_ns

    # ---- epoch boundary set -------------------------------------------
    bounds = {0}
    for _g, a, b in down:
        bounds.add(a)
        if b < _T_INF:
            bounds.add(b)
    for _m, a, b, _f in bw_specs:
        bounds.add(a)
        if b < _T_INF:
            bounds.add(b)
    for _si, sp, _ms, _md in link_specs:
        bounds.add(s2ns(sp.start))
        e = s2ns(sp.end)
        if e < _T_INF:
            bounds.add(e)
    times = np.array(sorted(b for b in bounds if b < _T_INF), np.int64)
    T = len(times)

    alive = np.ones((T, n_hosts), bool)
    for g, a, b in down:
        alive[(times >= a) & (times < b), g] = False

    bw = np.ones((T, n_hosts), np.float32)
    for m, a, b, f in bw_specs:
        for e in np.nonzero((times >= a) & (times < b))[0]:
            bw[e, m] *= f

    # ---- link groups: hosts with identical fault membership share one
    # group, so the per-pair overlay stays [T, G, G]-small ---------------
    sigs = np.zeros((n_hosts,), np.int64)
    for j, (_si, _sp, ms, md) in enumerate(link_specs):
        sigs |= ms.astype(np.int64) << (2 * j)
        sigs |= md.astype(np.int64) << (2 * j + 1)
    uniq, fgrp = np.unique(sigs, return_inverse=True)
    G = len(uniq)
    lat = np.full((T, G, G), LAT_UNIT, np.int64)
    passp = np.ones((T, G, G), np.float32)
    for j, (_si, sp, _ms, _md) in enumerate(link_specs):
        in_s = (uniq >> (2 * j)) & 1
        in_d = (uniq >> (2 * j + 1)) & 1
        # symmetric: the pair is affected when either direction matches
        pair = (
            (in_s[:, None] & in_d[None, :])
            | (in_d[:, None] & in_s[None, :])
        ).astype(bool)
        active = (times >= s2ns(sp.start)) & (times < s2ns(sp.end))
        for e in np.nonzero(active)[0]:
            if sp.type == "latency":
                lat[e][pair] = np.maximum(
                    (lat[e][pair].astype(np.float64) * sp.factor), 0
                ).astype(np.int64)
            elif sp.type == "loss":
                passp[e][pair] *= np.float32(1.0 - sp.loss)
            else:  # partition
                passp[e][pair] = 0.0

    if not math.isfinite(float(passp.min())):  # pragma: no cover
        raise AssertionError("non-finite pass probability")

    return CompiledFaults(
        times=jnp.asarray(times),
        alive=jnp.asarray(alive),
        fgrp=jnp.asarray(fgrp.astype(np.int32)),
        lat_milli=jnp.asarray(lat),
        passp=jnp.asarray(passp),
        bw_scale=jnp.asarray(bw),
        has_crash=bool((~alive).any()),
        has_link=bool(
            (lat != LAT_UNIT).any() or (passp != 1.0).any()
        ),
        has_bw=bool((bw != 1.0).any()),
        np_times=times,
        np_alive=alive,
    )

"""Request schema, equivalence classes, and the fleet-lane packer.

A scenario request is everything the fleet tier can vary per lane —
seed, fault schedule, latency/bandwidth scaling, stop time — plus the
static scenario shape (model + params) that picks its compiled program.
`equivalence_class` maps a request to its `ClassKey`: requests with the
same key can share one lowered fleet program; requests with different
keys cannot (that is the `check_lane_knobs` static-knob rule, plus the
fault-bind SHAPES, which are compile-time constants of the program —
pow2-rounded so schedules of similar size land in one class).

`LanePacker` is the RackSched-flavored batcher: per-class FIFO queues,
dispatch when a class fills `max_lanes` or its oldest request ages past
the pack deadline. Ordering is deterministic (submit sequence numbers,
not wall-clock ties): full classes first, then deadline-expired ones,
oldest head wins — so a replayed request stream packs identically.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import OrderedDict, deque
from typing import Any

from shadow_tpu.core.timebase import SECOND
from shadow_tpu.faults import FaultSpec, parse_fault_dsl


@dataclasses.dataclass(frozen=True)
class ScenarioRequest:
    """One validated scenario request (see `parse_request`)."""

    rid: str
    seq: int  # submit sequence number — the packer's deterministic order
    model: str
    params: tuple  # sorted (name, value) static scenario knobs
    seed: int
    stop_ns: int
    fault_dsl: tuple  # the DSL strings as submitted (for persist/replay)
    fault_specs: tuple  # parsed FaultSpec tuple
    latency_scale: float = 1.0
    bandwidth_scale: float = 1.0
    deadline_ms: float = 0.0  # 0 = no deadline (wall, from submit time)

    def doc(self) -> dict:
        """The re-submittable JSON form (drain persistence / replay)."""
        out = {
            "model": self.model,
            "params": dict(self.params),
            "seed": self.seed,
            "stop_ns": self.stop_ns,
            "faults": list(self.fault_dsl),
            "latency_scale": self.latency_scale,
            "bandwidth_scale": self.bandwidth_scale,
        }
        if self.deadline_ms:
            out["deadline_ms"] = self.deadline_ms
        return out


def parse_request(doc: dict, *, rid: str, seq: int) -> ScenarioRequest:
    """Validate a submit body into a ScenarioRequest; ValueError (with
    the field named) becomes the HTTP 400 body."""
    if not isinstance(doc, dict):
        raise ValueError("request body must be a JSON object")
    known = {"model", "params", "seed", "stop_s", "stop_ns", "faults",
             "latency_scale", "bandwidth_scale", "deadline_ms"}
    for k in doc:
        if k not in known:
            raise ValueError(
                f"unknown request field {k!r}; known fields are "
                f"{sorted(known)}"
            )
    model = doc.get("model", "phold")
    if not isinstance(model, str):
        raise ValueError("model must be a string")
    params = doc.get("params", {})
    if not isinstance(params, dict):
        raise ValueError("params must be an object of static knobs")
    for k, v in params.items():
        if not isinstance(v, (int, float, bool, str)):
            raise ValueError(
                f"params[{k!r}] must be a scalar, got {type(v).__name__}"
            )
    if "stop_ns" in doc:
        stop_ns = int(doc["stop_ns"])
    elif "stop_s" in doc:
        stop_ns = int(round(float(doc["stop_s"]) * SECOND))
    else:
        raise ValueError("request needs stop_s (seconds) or stop_ns")
    if stop_ns <= 0:
        raise ValueError(f"stop must be > 0, got {stop_ns} ns")
    fault_dsl = doc.get("faults", [])
    if isinstance(fault_dsl, str):
        fault_dsl = [fault_dsl]
    specs = []
    for f in fault_dsl:
        if isinstance(f, FaultSpec):
            raise ValueError("faults must be DSL strings, not specs")
        specs.append(parse_fault_dsl(str(f)))
    lat = float(doc.get("latency_scale", 1.0))
    if lat < 0:
        raise ValueError(f"latency_scale {lat} < 0")
    bw = float(doc.get("bandwidth_scale", 1.0))
    if bw <= 0:
        raise ValueError(f"bandwidth_scale {bw} <= 0")
    ddl = float(doc.get("deadline_ms", 0.0))
    if ddl < 0:
        raise ValueError(f"deadline_ms {ddl} < 0 (0 disables)")
    return ScenarioRequest(
        rid=rid, seq=seq, model=model,
        params=tuple(sorted(params.items())),
        seed=int(doc.get("seed", 0)), stop_ns=stop_ns,
        fault_dsl=tuple(str(f) for f in fault_dsl),
        fault_specs=tuple(specs),
        latency_scale=lat, bandwidth_scale=bw, deadline_ms=ddl,
    )


@dataclasses.dataclass(frozen=True)
class ClassKey:
    """Static-knob equivalence class of a request — the program-cache
    key. `fault_sig` is None for fault-free requests, else
    (epoch_pad, group_pad, (has_crash, has_link, has_bw)): the
    pow2-rounded fault-bind shape plus the fault-kind flags, both
    compile-time constants of the lowered program."""

    model: str
    params: tuple
    fault_sig: tuple | None = None

    def __str__(self):
        ps = ",".join(f"{k}={v}" for k, v in self.params)
        fs = ("none" if self.fault_sig is None else
              f"t{self.fault_sig[0]}g{self.fault_sig[1]}"
              + "".join("clb"[i] for i, f in enumerate(self.fault_sig[2])
                        if f))
        return f"{self.model}({ps})/faults:{fs}"


def _pow2(n: int) -> int:
    return 1 << max(int(n) - 1, 0).bit_length()


def fault_signature(specs: tuple, names: list, hg: int) -> tuple | None:
    """Compile a host-side probe of the fault schedule to read the
    shapes/flags the lowered program would fix. Seed 0 on purpose: the
    signature must not depend on the per-lane seed (shapes never do —
    seeds only perturb churn phases, which are values)."""
    if not specs:
        return None
    from shadow_tpu.faults.schedule import compile_faults

    comp = compile_faults(tuple(specs), names, hg, 0)
    flags = (comp.has_crash, comp.has_link, comp.has_bw)
    if not any(flags):
        # values-neutral schedule (e.g. globs matching no host): the
        # program binds no fault arrays, same as a fault-free request
        return None
    return (_pow2(comp.np_times.shape[0]),
            _pow2(int(comp.lat_milli.shape[1])), flags)


def equivalence_class(req: ScenarioRequest, names: list,
                      hg: int) -> ClassKey:
    """The request's program-cache key. Seeds, stop times, fault VALUES,
    and latency scale are launch inputs — never part of the key. The
    latency scale binds on every lane (scale 1.0 is integer-exact
    identity, pinned by the fleet tier), so it does not split classes;
    bandwidth scale is state-side and splits nothing either."""
    return ClassKey(
        model=req.model, params=req.params,
        fault_sig=fault_signature(req.fault_specs, names, hg),
    )


class LanePacker:
    """Deadline-or-full batcher of requests into fleet lanes.

    Thread-safe; `push` happens on HTTP handler threads, `ready`/`pop`
    on the launch worker. The condition variable lives in the service —
    this class only answers "what should launch now" and "how long may
    the worker sleep".
    """

    def __init__(self, max_lanes: int, deadline_s: float, *,
                 clock=time.monotonic):
        if max_lanes < 1:
            raise ValueError(f"max_lanes must be >= 1, got {max_lanes}")
        self.max_lanes = int(max_lanes)
        self.deadline_s = float(deadline_s)
        self._clock = clock
        self._q: "OrderedDict[Any, deque]" = OrderedDict()
        self._lock = threading.Lock()

    def push(self, key, req: ScenarioRequest) -> None:
        with self._lock:
            self._q.setdefault(key, deque()).append((req, self._clock()))

    def depth(self) -> int:
        with self._lock:
            return sum(len(d) for d in self._q.values())

    def _head_seq(self, key) -> int:
        return self._q[key][0][0].seq

    def ready(self, now: float | None = None):
        """The ClassKey that should launch now, or None. Full classes
        beat deadline-expired ones; ties break to the oldest head
        request (lowest submit seq) — fully deterministic."""
        now = self._clock() if now is None else now
        with self._lock:
            full = [k for k, d in self._q.items()
                    if len(d) >= self.max_lanes]
            if full:
                return min(full, key=self._head_seq)
            due = [k for k, d in self._q.items()
                   if now - d[0][1] >= self.deadline_s]
            if due:
                return min(due, key=self._head_seq)
            return None

    def next_timeout(self, now: float | None = None) -> float | None:
        """Seconds until the earliest pending deadline (>= 0), or None
        when the queue is empty — the worker's cond-wait bound."""
        now = self._clock() if now is None else now
        with self._lock:
            if not self._q:
                return None
            head = min(d[0][1] for d in self._q.values())
            return max(head + self.deadline_s - now, 0.0)

    def pop(self, key) -> list:
        """Up to max_lanes oldest requests of the class, FIFO."""
        with self._lock:
            d = self._q.get(key)
            if not d:
                return []
            out = [d.popleft()[0] for _ in range(min(len(d),
                                                     self.max_lanes))]
            if not d:
                del self._q[key]
            return out

    def drain_all(self) -> list:
        """Every pending request in submit order; empties the queue
        (the SIGTERM persist path)."""
        with self._lock:
            out = [r for d in self._q.values() for r, _ in d]
            self._q.clear()
        return sorted(out, key=lambda r: r.seq)

    def snapshot(self) -> dict:
        """GET /queue's packer view: per-class depth AND oldest-waiting
        age (head enqueue timestamp vs now) — degraded-mode triage
        reads which class is starving without needing the trace
        ledger."""
        now = self._clock()
        with self._lock:
            return {
                "depth": sum(len(d) for d in self._q.values()),
                "classes": {
                    str(k): {
                        "depth": len(d),
                        "oldest_wait_s": round(max(now - d[0][1], 0.0),
                                               3),
                    }
                    for k, d in self._q.items()
                },
                "max_lanes": self.max_lanes,
                "deadline_s": self.deadline_s,
            }

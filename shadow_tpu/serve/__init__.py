"""Resident scenario serving: multi-tenant requests on one warm engine.

The batch tiers compile a fresh XLA program per process launch; the
fleet tier (docs/16-Scenario-Fleets.md) showed 64 scenarios sharing one
lowered program amortize that compile 8x. This package turns the
amortization into an architecture: a long-lived service that

- accepts scenario requests over a stdlib-only HTTP plane
  (`serve.http`: POST /submit, GET /result/<id>, /queue, /metrics),
- keys compiled fleet programs by their static-knob equivalence class
  and keeps them warm across requests (`serve.cache.ProgramCache` —
  the class key is exactly the knob set `check_lane_knobs` rejects
  per-lane, because those are the knobs one lowered program fixes),
- packs compatible queued requests into fleet lanes
  (`serve.packer.LanePacker`, deadline-or-full dispatch) and launches
  them through the cached program with inert-lane padding, per-lane
  stop times, and heartbeat progress off the single-fetch harvest
  (`serve.service.SimService`),

returning each request's summary JSON bit-identical to its solo
`Simulation.run` (tests/test_serve.py pins this end to end).

docs/17-Serving.md is the narrative: request schema, equivalence-class
table, packer policy, drain semantics, bench methodology.
"""

from shadow_tpu.serve.cache import ProgramCache
from shadow_tpu.serve.packer import (
    ClassKey,
    LanePacker,
    ScenarioRequest,
    equivalence_class,
    parse_request,
)
from shadow_tpu.serve.chaos import ServeChaos
from shadow_tpu.serve.service import (
    ServiceDegraded,
    ServiceDraining,
    ServiceUnavailable,
    SimService,
    solo_reference,
)

__all__ = [
    "ClassKey",
    "LanePacker",
    "ProgramCache",
    "ScenarioRequest",
    "ServeChaos",
    "ServiceDegraded",
    "ServiceDraining",
    "ServiceUnavailable",
    "SimService",
    "equivalence_class",
    "parse_request",
    "solo_reference",
]
